# Developer entry points (reference analogue: Makefile:191-359)

PYTHON ?= python

.PHONY: help install test test-fast lint speclint jaxlint rangelint reftests bytediff bench multichip recovery-smoke postmortem serve_docs coverage clean

help:
	@echo "install    - editable install with test extras"
	@echo "test       - FAST lane: suite minus @slow (CPU, 8 virtual devices)"
	@echo "test-full  - everything incl. @slow (the nightly lane)"
	@echo "test-slow  - only the @slow modules"
	@echo "lint       - ruff check (if installed) + speclint + jaxlint + rangelint + env-docs diff"
	@echo "speclint   - AST-level project-native static analysis (docs/analysis.md)"
	@echo "jaxlint    - trace-level kernel analysis: transfers, donation,"
	@echo "             recompile surfaces, mesh collectives (docs/analysis.md)"
	@echo "rangelint  - value-range kernel analysis: interval proof that no"
	@echo "             limb intermediate wraps a lane (docs/analysis.md)"
	@echo "reftests   - emit test vectors to ./test_vectors"
	@echo "bytediff   - conformance byte-diff vs the compiled reference spec"
	@echo "bench      - run the driver benchmark"
	@echo "seed-device- one-time device-kernel compile into .jax_cache"
	@echo "multichip  - 8-virtual-device sharding dry run"
	@echo "postmortem - pretty-print the most recent flight-recorder bundle"
	@echo "clean      - remove caches and generated vectors"

install:
	$(PYTHON) -m pip install -e .[test]

# The default lane mirrors the reference's split: `make test` is the
# developer loop (reference Makefile:227-249), the heavy device-compile /
# pure-python-crypto / mainnet differential modules run nightly
# (reference .github/workflows/nightly-tests.yml).
test:
	$(PYTHON) -m pytest tests/ -q -m "not slow" -p xdist -n auto

test-full:
	$(PYTHON) -m pytest tests/ -q -p xdist -n auto

test-slow:
	$(PYTHON) -m pytest tests/ -q -m slow -p xdist -n auto

test-serial:
	$(PYTHON) -m pytest tests/ -q -m "not slow"

parity:
	$(PYTHON) -m pytest tests/parity/ -q -m "not slow"

parity-full:
	$(PYTHON) -m pytest tests/parity/ -q

# mainnet-SHAPED smoke: full 16,384-validator genesis, 64-committee slots,
# mainnet preset — a driver-runnable subset (not nightly-only).  The
# attestation-dense suites stay in `make test` under SPEC_TEST_PRESET.
mainnet-smoke:
	SPEC_TEST_PRESET=mainnet $(PYTHON) -m pytest \
	  tests/phase0/test_sanity.py tests/phase0/test_process_attestation.py \
	  tests/phase0/test_block_operations.py \
	  -k "empty_block or slots_1 or invalid_state_root or one_basic or proposer_slashing_basic or deposit_top_up" \
	  -q

test-fast: test

# ruff (style, best-effort) then speclint (AST-level project invariants,
# GATING: fork-safety, lock-order, jit-purity, obs/env/fault registries)
# then jaxlint (trace-level kernel invariants, GATING: transfer-free,
# donation-audit, recompile-surface, collective-audit, constant-bloat,
# x64-drift — docs/analysis.md) then rangelint (value-range invariants,
# GATING: lane-overflow, mask-consistency, lazy-bound-audit);
# env-reference.md must match the registry
lint:
	-$(PYTHON) -m ruff check eth_consensus_specs_tpu/ tests/
	$(PYTHON) scripts/speclint.py
	$(PYTHON) scripts/jaxlint.py
	$(PYTHON) scripts/rangelint.py
	$(PYTHON) scripts/gen_env_docs.py --check

speclint:
	$(PYTHON) scripts/speclint.py

# trace-level analysis of every registered kernel (analysis/kernels.py);
# --chips 8 is the CLI default, so the three mesh-sharded variants are
# analyzed on 8 virtual CPU devices even on a 1-device dev box
jaxlint:
	$(PYTHON) scripts/jaxlint.py

# value-range analysis: interval abstract interpretation over every
# registered kernel's jaxpr, seeded from the registry's declared input
# domains — proves no intermediate can wrap a u64/u32 lane
rangelint:
	$(PYTHON) scripts/rangelint.py

reftests:
	$(PYTHON) -m eth_consensus_specs_tpu.gen -o test_vectors -v

# cross-generator conformance byte-diff (docs/conformance-bytediff.md):
# emit the agreed slice, replay every vector through the specc-compiled
# reference markdown, require byte-identical post-states.  The script's
# exit code IS the gate — no pipeline may mask it.
bytediff:
	$(PYTHON) scripts/cross_gen_bytediff.py > BYTEDIFF_RESULT.json; \
	s=$$?; cat BYTEDIFF_RESULT.json; exit $$s

bench:
	$(PYTHON) bench.py

# one-time device-kernel compile into .jax_cache (accelerator required);
# after this the bench's hybrid BLS section uses the device stages
seed-device:
	$(PYTHON) scripts/seed_device_cache.py

multichip:
	$(PYTHON) -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('ok')"

# durable-resident-state chaos gate: SIGKILL the resident replica at
# the checkpoint commit seam, restore-then-replay, bit-identical root
# vs an uninterrupted control run (docs/robustness.md)
recovery-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) scripts/recovery_smoke.py --out recovery_smoke.json

# most recent flight-recorder bundle ($ETH_SPECS_OBS_POSTMORTEM_DIR or
# ./postmortems); `scripts/postmortem.py --list` / `A B` to diff
postmortem:
	$(PYTHON) scripts/postmortem.py

serve_docs:
	$(PYTHON) -m mkdocs serve

coverage:
	$(PYTHON) scripts/spec_coverage.py

clean:
	rm -rf .pytest_cache .jax_cache test_vectors
	find . -name __pycache__ -type d -exec rm -rf {} +
