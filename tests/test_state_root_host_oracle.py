"""Host state-root oracle (ops/state_root_host.py) vs the device path and
the object path — the independent leg the bench's correctness-coupled
timing stands on (round-4 verdict weak #1)."""

import numpy as np

import __graft_entry__ as graft
from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.ops import state_root_host as srh
from eth_consensus_specs_tpu.ops.state_root import synthetic_static
from eth_consensus_specs_tpu.parallel import resident


def test_tree_root_np_matches_device_kernel():
    import jax.numpy as jnp

    from eth_consensus_specs_tpu.ops.merkle import tree_root_words

    rng = np.random.default_rng(5)
    for depth in (0, 1, 3, 6):
        leaves = rng.integers(0, 2**32, size=(1 << depth, 8), dtype=np.uint64).astype(
            np.uint32
        )
        dev = np.asarray(tree_root_words(jnp.asarray(leaves), depth))
        host = srh.tree_root_np(leaves, depth)
        assert np.array_equal(dev, host), f"depth {depth}"


def test_tree_root_np_matches_hashlib():
    import hashlib

    rng = np.random.default_rng(6)
    leaves = rng.integers(0, 2**32, size=(8, 8), dtype=np.uint64).astype(np.uint32)
    raw = [r.astype(">u4").tobytes() for r in leaves]
    lvl = raw
    while len(lvl) > 1:
        lvl = [
            hashlib.sha256(lvl[2 * i] + lvl[2 * i + 1]).digest()
            for i in range(len(lvl) // 2)
        ]
    host = srh.tree_root_np(leaves, 3).astype(">u4").tobytes()
    assert host == lvl[0]


def test_chained_tree_matches_device_chain():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from eth_consensus_specs_tpu.ops.merkle import _tree_root_fused

    depth, chain = 8, 4
    rng = np.random.default_rng(7)
    base = rng.integers(0, 2**32, size=(1 << depth, 8), dtype=np.uint64).astype(np.uint32)
    salt = np.full(8, 3, np.uint32)

    @jax.jit
    def run(lv, acc0):
        def body(_, carry):
            lv, acc = carry
            return lv, _tree_root_fused(lv ^ acc, depth)

        return lax.fori_loop(0, chain, body, (lv, acc0))[1]

    dev = np.asarray(run(jnp.asarray(base), jnp.asarray(salt)))
    host = srh.tree_root_chain_np(base, depth, chain, salt)
    assert np.array_equal(dev, host)


def test_resident_root_acc_host_matches_device():
    spec = get_spec("deneb", "mainnet")
    n, epochs = 1 << 10, 3
    cols, just = graft._example_altair_inputs(n)
    static = synthetic_static(spec, n)
    carry = resident.run_epochs(spec, cols, just, epochs, with_root="state", static=static)
    dev = np.asarray(carry.root_acc)
    host = srh.resident_root_acc_host(spec, cols, just, epochs, static)
    assert np.array_equal(dev, host)
