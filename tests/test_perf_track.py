"""perf_track ingestion contract for the aggregation bench section.

The tracker once burned this repo by comparing in the wrong frame; the
agg section adds a new hazard class — rate metrics whose names end in
``_per_s`` would match the lower-is-better ``_s`` suffix rule and gate
throughput IMPROVEMENTS as regressions. These tests pin the direction
table and the section ingestion so a rename can't silently flip it."""

from __future__ import annotations

import importlib.util
import json
import os

_SPEC = importlib.util.spec_from_file_location(
    "perf_track",
    os.path.join(os.path.dirname(__file__), "..", "scripts", "perf_track.py"),
)
perf_track = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(perf_track)


def test_per_s_rates_are_higher_is_better():
    assert not perf_track._lower_is_better("attestations_agg_per_s")
    assert not perf_track._lower_is_better("agg_signatures_agg_per_s")
    assert not perf_track._lower_is_better("r2x8_rps")
    assert not perf_track._lower_is_better("incremental_root_speedup")
    # walls/latency/bytes still compare lower-is-better
    assert perf_track._lower_is_better("agg_slot_wall_s")
    assert perf_track._lower_is_better("resident_epoch_plus_root_ms")
    assert perf_track._lower_is_better("peak_bytes")


def _write_round(tmp_path, n, parsed):
    path = tmp_path / f"BENCH_r{n:02d}.json"
    path.write_text(json.dumps({"rc": 0, "parsed": parsed}))
    return path


def test_load_rounds_ingests_agg_section(tmp_path):
    _write_round(
        tmp_path, 1,
        {
            "metric": "attestations_agg_per_s", "value": 900.0,
            "platform": "cpu",
            "agg": {
                "attestations_agg_per_s": 900.0,
                "signatures_agg_per_s": 210000.0,
                "slot_wall_s": 4.5,
            },
        },
    )
    rounds = perf_track.load_rounds(str(tmp_path))
    assert len(rounds) == 1 and rounds[0]["status"] == "ok"
    m = rounds[0]["metrics"]
    # the primary keeps its name; section values prefix agg_ unless
    # they already carry it (no agg_agg_ stutter)
    assert m["attestations_agg_per_s"] == 900.0
    assert m["agg_signatures_agg_per_s"] == 210000.0
    assert m["agg_slot_wall_s"] == 4.5


def test_agg_rate_drop_gates_and_rise_does_not(tmp_path):
    base = {
        "metric": "attestations_agg_per_s",
        "platform": "cpu",
    }
    _write_round(tmp_path, 1, {**base, "value": 1000.0,
                               "agg": {"attestations_agg_per_s": 1000.0}})
    _write_round(tmp_path, 2, {**base, "value": 500.0,
                               "agg": {"attestations_agg_per_s": 500.0}})
    _write_round(tmp_path, 3, {**base, "value": 2000.0,
                               "agg": {"attestations_agg_per_s": 2000.0}})
    rounds = perf_track.load_rounds(str(tmp_path))
    regressions, _ = perf_track.compare(rounds, threshold=0.30, strict=False)
    flagged = {(r["round"], r["metric"]) for r in regressions}
    # the 1000 -> 500 drop gates; the 500 -> 2000 RISE must not (the
    # direction a bare "_s" suffix rule would have inverted)
    assert (2, "attestations_agg_per_s") in flagged
    assert not any(r == 3 for r, _ in flagged)


def test_load_rounds_ingests_das_section(tmp_path):
    _write_round(
        tmp_path, 1,
        {
            "metric": "blobs_per_s", "value": 40.0, "platform": "cpu",
            "das": {
                "blobs_per_s": 40.0,
                "ffts_per_s": 40.0,
                "flush_wall_s": 0.2,
                "correctness_coupled": True,
            },
        },
    )
    rounds = perf_track.load_rounds(str(tmp_path))
    assert len(rounds) == 1 and rounds[0]["status"] == "ok"
    m = rounds[0]["metrics"]
    assert m["das_blobs_per_s"] == 40.0
    assert m["das_ffts_per_s"] == 40.0
    assert m["das_flush_wall_s"] == 0.2
    # the parity flag is a gate marker, not a metric (bool is an int
    # subclass — the ingest must not let it ride the timeline)
    assert "das_correctness_coupled" not in m
    # direction table: blob rates are higher-is-better, walls lower
    assert not perf_track._lower_is_better("das_blobs_per_s")
    assert not perf_track._lower_is_better("das_ffts_per_s")
    assert perf_track._lower_is_better("das_flush_wall_s")


def test_quarantined_das_lkg_can_only_be_replaced_by_parity_coupled_run():
    """The re-earn-never-grandfather rule: copying the quarantined das
    numbers back into the usable LKG sections WITHOUT the
    correctness_coupled flag fails the tracker; a parity-coupled
    re-earned section passes; quarantined-only stays fine."""
    quarantined = {"quarantined": ["das"], "sections": {}, "present": True}
    assert perf_track.reearn_violations(quarantined) == []
    grandfathered = {
        "present": True,
        "quarantined": ["das"],
        "sections": {"das": {"das_ffts_per_sec": 621.1}},
    }
    assert perf_track.reearn_violations(grandfathered) == ["das"]
    # das is re-earn-only even if the quarantine note itself is deleted
    scrubbed = {
        "present": True,
        "quarantined": [],
        "sections": {"das": {"blobs_per_s": 40.0}},
    }
    assert perf_track.reearn_violations(scrubbed) == ["das"]
    reearned = {
        "present": True,
        "quarantined": ["das"],
        "sections": {"das": {"blobs_per_s": 40.0, "correctness_coupled": True}},
    }
    assert perf_track.reearn_violations(reearned) == []
    # bench.py's _store_lkg form counts too: verified must be the
    # literal True, NOT the "same-backend" CPU-lane string it writes
    # when coupling did not actually run against a host recompute
    bench_form = {
        "present": True,
        "quarantined": ["tree"],
        "sections": {"tree": {"hashes_per_sec": 3e9, "verified": True}},
    }
    assert perf_track.reearn_violations(bench_form) == []
    cpu_lane = {
        "present": True,
        "quarantined": ["epoch"],
        "sections": {"epoch": {
            "fused_epoch_ms": 5.0,
            "verified": "same-backend (CPU lane; coupling applies to accelerator runs)",
        }},
    }
    assert perf_track.reearn_violations(cpu_lane) == ["epoch"]
    # a truthy-but-not-True flag is not a parity proof
    sloppy = {
        "present": True,
        "quarantined": [],
        "sections": {"das": {"correctness_coupled": 1.0}},
    }
    assert perf_track.reearn_violations(sloppy) == ["das"]


def test_current_repo_lkg_passes_reearn_rule():
    """The committed BENCH_LKG.json (das et al. quarantined, usable
    sections empty) must satisfy the rule perf_track now gates on."""
    repo = os.path.join(os.path.dirname(__file__), "..")
    lkg = perf_track.load_lkg(repo)
    assert lkg["present"]
    assert "das" in lkg["quarantined"]
    assert perf_track.reearn_violations(lkg) == []
