"""perf_track ingestion contract for the aggregation bench section.

The tracker once burned this repo by comparing in the wrong frame; the
agg section adds a new hazard class — rate metrics whose names end in
``_per_s`` would match the lower-is-better ``_s`` suffix rule and gate
throughput IMPROVEMENTS as regressions. These tests pin the direction
table and the section ingestion so a rename can't silently flip it."""

from __future__ import annotations

import importlib.util
import json
import os

_SPEC = importlib.util.spec_from_file_location(
    "perf_track",
    os.path.join(os.path.dirname(__file__), "..", "scripts", "perf_track.py"),
)
perf_track = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(perf_track)


def test_per_s_rates_are_higher_is_better():
    assert not perf_track._lower_is_better("attestations_agg_per_s")
    assert not perf_track._lower_is_better("agg_signatures_agg_per_s")
    assert not perf_track._lower_is_better("r2x8_rps")
    assert not perf_track._lower_is_better("incremental_root_speedup")
    # walls/latency/bytes still compare lower-is-better
    assert perf_track._lower_is_better("agg_slot_wall_s")
    assert perf_track._lower_is_better("resident_epoch_plus_root_ms")
    assert perf_track._lower_is_better("peak_bytes")


def _write_round(tmp_path, n, parsed):
    path = tmp_path / f"BENCH_r{n:02d}.json"
    path.write_text(json.dumps({"rc": 0, "parsed": parsed}))
    return path


def test_load_rounds_ingests_agg_section(tmp_path):
    _write_round(
        tmp_path, 1,
        {
            "metric": "attestations_agg_per_s", "value": 900.0,
            "platform": "cpu",
            "agg": {
                "attestations_agg_per_s": 900.0,
                "signatures_agg_per_s": 210000.0,
                "slot_wall_s": 4.5,
            },
        },
    )
    rounds = perf_track.load_rounds(str(tmp_path))
    assert len(rounds) == 1 and rounds[0]["status"] == "ok"
    m = rounds[0]["metrics"]
    # the primary keeps its name; section values prefix agg_ unless
    # they already carry it (no agg_agg_ stutter)
    assert m["attestations_agg_per_s"] == 900.0
    assert m["agg_signatures_agg_per_s"] == 210000.0
    assert m["agg_slot_wall_s"] == 4.5


def test_agg_rate_drop_gates_and_rise_does_not(tmp_path):
    base = {
        "metric": "attestations_agg_per_s",
        "platform": "cpu",
    }
    _write_round(tmp_path, 1, {**base, "value": 1000.0,
                               "agg": {"attestations_agg_per_s": 1000.0}})
    _write_round(tmp_path, 2, {**base, "value": 500.0,
                               "agg": {"attestations_agg_per_s": 500.0}})
    _write_round(tmp_path, 3, {**base, "value": 2000.0,
                               "agg": {"attestations_agg_per_s": 2000.0}})
    rounds = perf_track.load_rounds(str(tmp_path))
    regressions, _ = perf_track.compare(rounds, threshold=0.30, strict=False)
    flagged = {(r["round"], r["metric"]) for r in regressions}
    # the 1000 -> 500 drop gates; the 500 -> 2000 RISE must not (the
    # direction a bare "_s" suffix rule would have inverted)
    assert (2, "attestations_agg_per_s") in flagged
    assert not any(r == 3 for r, _ in flagged)
