"""XLA-derived attribution (obs/xprof.py) + serve compile timing.

The cost-model cross-check contract: the hand ``work_bytes`` feeding
the roofline verdicts is an algorithmic FLOOR, so XLA's bytes-accessed
must not sit below it beyond tolerance (positive rel-err = the hand
model claims traffic the compiler never emitted = the roofline verdicts
judge fictional bytes). On sha256 and merkle the check must come back
clean; on backends without the analyses everything degrades to counted
no-ops. And on the serving side: every ``serve.compiles`` bump leaves
its wall time in the ``serve.compile_ms`` histogram — count in
lockstep with the counter.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eth_consensus_specs_tpu import obs
from eth_consensus_specs_tpu.obs import xprof
from eth_consensus_specs_tpu.obs.registry import Registry


@pytest.fixture(autouse=True)
def _isolated(monkeypatch):
    """Fresh registry + capture dedup per test; ambient capture stays
    OFF unless the test enables it (the suite must not pay AOT compiles
    it didn't ask for)."""
    from eth_consensus_specs_tpu.obs import registry as registry_mod

    monkeypatch.setattr(registry_mod, "_REGISTRY", Registry())
    monkeypatch.delenv("ETH_SPECS_OBS_XPROF", raising=False)
    xprof.reset_for_tests()
    yield
    xprof.reset_for_tests()


def _counters():
    return obs.snapshot()["counters"]


def test_ambient_capture_is_opt_in(monkeypatch):
    assert not xprof.enabled()
    assert xprof.analyze("noop", None, ()) is None  # gate short-circuits
    monkeypatch.setenv("ETH_SPECS_OBS_XPROF", "1")
    assert xprof.enabled()


def test_sha256_cost_model_within_tolerance():
    from eth_consensus_specs_tpu.ops.sha256 import _kernel

    n = 2048
    cap = xprof.analyze(
        "sha256", _kernel,
        (jax.ShapeDtypeStruct((n, 16), jnp.uint32),),
        hand_bytes=96 * n, dims=(n,), force=True,
    )
    assert cap is not None
    assert cap["compile_ms"] > 0
    assert cap["bytes_accessed"] > 0
    # the hand model is a floor: XLA must move at least that much
    # (amplification >= ~1), and the one-sided rel-err must be inside
    # tolerance — this is the acceptance-criteria assertion
    assert cap["bytes_amplification"] >= 0.99
    assert cap["cost_model_ok"], cap
    assert _counters().get("xprof.cost_model_mismatch", 0) == 0
    snap = obs.snapshot()
    for g in ("flops", "bytes_accessed", "arg_bytes", "out_bytes", "peak_bytes",
              "cost_model_rel_err", "bytes_amplification"):
        assert f"xprof.sha256.{g}" in snap["gauges"], g
    assert snap["histograms"]["xprof.compile_ms.sha256"]["count"] == 1


def test_merkle_cost_model_within_tolerance():
    from eth_consensus_specs_tpu.ops.merkle import _tree_root_fused, tree_real_hashes

    depth = 6
    cap = xprof.analyze(
        "merkle", _tree_root_fused,
        (jax.ShapeDtypeStruct((1 << depth, 8), jnp.uint32), depth),
        hand_bytes=96 * tree_real_hashes(depth), dims=(depth,), force=True,
    )
    assert cap is not None and cap["cost_model_ok"], cap
    assert cap["bytes_amplification"] >= 0.99
    assert _counters().get("xprof.cost_model_mismatch", 0) == 0


def test_capture_is_once_per_shape():
    from eth_consensus_specs_tpu.ops.merkle import _tree_root_fused

    args = (jax.ShapeDtypeStruct((8, 8), jnp.uint32), 3)
    assert xprof.analyze("merkle", _tree_root_fused, args, dims=(3,), force=True)
    assert xprof.analyze("merkle", _tree_root_fused, args, dims=(3,), force=True) is None
    snap = obs.snapshot()
    assert snap["histograms"]["xprof.compile_ms.merkle"]["count"] == 1


def test_ambient_hook_fires_on_merkleize(monkeypatch):
    """The ops-layer hook: with ETH_SPECS_OBS_XPROF=1 a plain
    merkleize_subtree_device call leaves the attribution gauges behind."""
    monkeypatch.setenv("ETH_SPECS_OBS_XPROF", "1")
    from eth_consensus_specs_tpu.ops.merkle import merkleize_subtree_device

    chunks = np.arange(4 * 32, dtype=np.uint8).reshape(4, 32)
    merkleize_subtree_device(chunks, 2)
    snap = obs.snapshot()
    assert "xprof.merkle.bytes_accessed" in snap["gauges"]
    assert snap["counters"].get("xprof.cost_model_mismatch", 0) == 0


class _Unanalyzable:
    """Lowered/compiled double whose analyses raise — the old-jax /
    exotic-backend shape."""

    def lower(self, *a):
        return self

    def compile(self):
        return self

    def cost_analysis(self):
        raise NotImplementedError("backend does not expose cost analysis")

    def memory_analysis(self):
        raise NotImplementedError("backend does not expose memory analysis")


def test_unavailable_analyses_degrade_to_counted_noop():
    cap = xprof.analyze("weird", _Unanalyzable(), (), hand_bytes=123, dims=(1,),
                        force=True)
    assert cap is not None  # the compile timing itself still stands
    assert "bytes_accessed" not in cap and "cost_model_ok" not in cap
    c = _counters()
    assert c.get("xprof.analysis_unavailable") == 1
    assert c.get("xprof.cost_model_mismatch", 0) == 0  # no-op-safe: no false alarm


class _FailsToLower:
    def lower(self, *a):
        raise RuntimeError("no backend")


def test_lowering_failure_never_raises():
    assert xprof.analyze("dead", _FailsToLower(), (), dims=(1,), force=True) is None
    assert _counters().get("xprof.analysis_unavailable") == 1


class _FixedBytes:
    def __init__(self, nbytes: float):
        self._n = nbytes

    def lower(self, *a):
        return self

    def compile(self):
        return self

    def cost_analysis(self):
        return [{"flops": 1.0, "bytes accessed": self._n}]

    def memory_analysis(self):
        return None


def test_overstated_hand_model_is_an_advisory():
    """hand_bytes far ABOVE what XLA compiled = roofline verdicts judged
    against fictional traffic → the advisory counter + event fire."""
    cap = xprof.analyze("liar", _FixedBytes(100.0), (), hand_bytes=1000.0,
                        dims=(1,), force=True)
    assert cap is not None and not cap["cost_model_ok"]
    c = _counters()
    assert c.get("xprof.cost_model_mismatch") == 1
    assert c.get("xprof.cost_model_mismatch.liar") == 1
    snap = obs.snapshot()
    assert snap["gauges"]["xprof.liar.cost_model_rel_err"]["last"] == pytest.approx(9.0)


def test_tolerance_env_override(monkeypatch):
    monkeypatch.setenv("ETH_SPECS_OBS_XPROF_TOL", "20")
    cap = xprof.analyze("lenient", _FixedBytes(100.0), (), hand_bytes=1000.0,
                        dims=(1,), force=True)
    assert cap["cost_model_ok"]  # rel_err 9 < tol 20
    assert _counters().get("xprof.cost_model_mismatch", 0) == 0


# ------------------------------------------------- serve compile timing --


def test_serve_compile_ms_tracks_serve_compiles():
    """Acceptance: every serve bucket's first compile lands in the
    serve.compile_ms histogram — count == serve.compiles."""
    from eth_consensus_specs_tpu import serve
    from eth_consensus_specs_tpu.serve import buckets
    from eth_consensus_specs_tpu.serve.config import ServeConfig

    buckets.reset_for_tests()
    svc = serve.VerifyService(ServeConfig.from_env(max_batch=4), name="xprof-test")
    rng = np.random.default_rng(7)
    futs = [
        svc.submit_hash_tree_root(
            rng.integers(0, 256, size=(n, 32)).astype(np.uint8)
        )
        for n in (48, 48, 13, 48, 13, 9)
    ]
    for f in futs:
        assert len(f.result()) == 32
    stats = svc.stats()
    svc.close()
    snap = obs.snapshot()
    compiles = snap["counters"].get("serve.compiles", 0)
    hist = snap["histograms"].get("serve.compile_ms", {})
    assert compiles >= 2  # two depths → at least two bucket shapes
    assert hist.get("count") == compiles
    assert hist.get("p50", 0) > 0
    # stats() surfaces the same numbers
    assert stats["compile_ms"]["count"] == compiles
    buckets.reset_for_tests()
