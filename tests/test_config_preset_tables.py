"""Config/preset two-tier system tables (reference analogue:
test/*/unittests/test_config_invariants.py — the reference asserts
cross-constant coherence per fork x preset; spec: presets/README.md,
configs/*.yaml)."""

import pytest

from eth_consensus_specs_tpu.config import load_config, load_preset
from eth_consensus_specs_tpu.forks import available_forks, get_spec

FORKS = available_forks()
PRESETS = ["minimal", "mainnet"]


@pytest.mark.parametrize("preset", PRESETS)
@pytest.mark.parametrize("fork", FORKS)
def test_spec_loads_every_fork_preset(fork, preset):
    spec = get_spec(fork, preset)
    assert int(spec.SLOTS_PER_EPOCH) > 0


@pytest.mark.parametrize("preset", PRESETS)
def test_epoch_containment_invariants(preset):
    spec = get_spec("phase0", preset)
    assert int(spec.EPOCHS_PER_ETH1_VOTING_PERIOD) >= 1
    assert int(spec.SLOTS_PER_HISTORICAL_ROOT) % int(spec.SLOTS_PER_EPOCH) == 0
    assert int(spec.EPOCHS_PER_HISTORICAL_VECTOR) > int(
        spec.MIN_SEED_LOOKAHEAD
    )
    assert int(spec.EPOCHS_PER_SLASHINGS_VECTOR) >= 2


@pytest.mark.parametrize("preset", PRESETS)
def test_committee_sizing_invariants(preset):
    spec = get_spec("phase0", preset)
    assert 1 <= int(spec.TARGET_COMMITTEE_SIZE) <= int(spec.MAX_VALIDATORS_PER_COMMITTEE)
    assert int(spec.MAX_COMMITTEES_PER_SLOT) >= 1
    assert int(spec.SHUFFLE_ROUND_COUNT) >= 1


@pytest.mark.parametrize("preset", PRESETS)
def test_balance_invariants(preset):
    spec = get_spec("electra", preset)
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    assert int(spec.MAX_EFFECTIVE_BALANCE) % inc == 0
    assert int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA) % inc == 0
    assert int(spec.MIN_ACTIVATION_BALANCE) <= int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA)
    assert int(spec.config.EJECTION_BALANCE) < int(spec.MIN_ACTIVATION_BALANCE)


def test_fork_epochs_monotone_mainnet():
    cfg = load_config("mainnet")
    order = [
        "ALTAIR_FORK_EPOCH",
        "BELLATRIX_FORK_EPOCH",
        "CAPELLA_FORK_EPOCH",
        "DENEB_FORK_EPOCH",
        "ELECTRA_FORK_EPOCH",
    ]
    epochs = [int(cfg[name]) for name in order if name in cfg]
    assert epochs == sorted(epochs)


def test_fork_versions_distinct_mainnet():
    cfg = load_config("mainnet")
    versions = [
        bytes(cfg[k]) for k in cfg.keys() if k.endswith("_FORK_VERSION")
    ]
    assert len(versions) == len(set(versions))


@pytest.mark.parametrize("preset", PRESETS)
def test_blob_constants_consistent(preset):
    spec = get_spec("deneb", preset)
    assert int(spec.FIELD_ELEMENTS_PER_BLOB) == 4096
    assert int(spec.config.MAX_BLOBS_PER_BLOCK) <= int(
        spec.MAX_BLOB_COMMITMENTS_PER_BLOCK
    )


@pytest.mark.parametrize("preset", PRESETS)
def test_fulu_das_constants_consistent(preset):
    spec = get_spec("fulu", preset)
    cols = int(spec.NUMBER_OF_COLUMNS)
    groups = int(spec.config.NUMBER_OF_CUSTODY_GROUPS)
    assert cols % groups == 0
    assert int(spec.CELLS_PER_EXT_BLOB) == cols
    assert int(spec.config.SAMPLES_PER_SLOT) <= cols
    assert int(spec.config.CUSTODY_REQUIREMENT) <= groups


@pytest.mark.parametrize("preset", PRESETS)
def test_preset_loader_covers_every_fork(preset):
    for fork in FORKS:
        p = load_preset(preset, fork)
        assert "SLOTS_PER_EPOCH" in p


def test_minimal_and_mainnet_differ_where_expected():
    mi = load_preset("minimal", "phase0")
    ma = load_preset("mainnet", "phase0")
    assert int(mi["SLOTS_PER_EPOCH"]) < int(ma["SLOTS_PER_EPOCH"])
    assert int(mi["MAX_COMMITTEES_PER_SLOT"]) <= int(ma["MAX_COMMITTEES_PER_SLOT"])


@pytest.mark.parametrize("fork", FORKS)
def test_domain_constants_distinct(fork):
    spec = get_spec(fork, "minimal")
    names = [n for n in dir(spec) if n.startswith("DOMAIN_")]
    values = []
    for n in names:
        v = getattr(spec, n)
        if isinstance(v, (bytes, bytearray)) or hasattr(v, "__bytes__"):
            values.append(bytes(v))
    assert len(values) == len(set(values)), "duplicate domain separators"


def test_gloas_builder_constants_sane():
    spec = get_spec("gloas", "minimal")
    assert int(spec.BUILDER_PAYMENT_THRESHOLD_NUMERATOR) <= int(
        spec.BUILDER_PAYMENT_THRESHOLD_DENOMINATOR
    )
    assert int(spec.PTC_SIZE) >= 1


@pytest.mark.parametrize("preset", PRESETS)
def test_churn_limit_invariants(preset):
    spec = get_spec("phase0", preset)
    assert int(spec.config.MIN_PER_EPOCH_CHURN_LIMIT) >= 1
    assert int(spec.config.CHURN_LIMIT_QUOTIENT) >= 1


@pytest.mark.parametrize("preset", PRESETS)
def test_electra_churn_limits_are_increment_multiples(preset):
    spec = get_spec("electra", preset)
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    assert int(spec.config.MIN_PER_EPOCH_CHURN_LIMIT_ELECTRA) % inc == 0
    assert int(spec.config.MAX_PER_EPOCH_ACTIVATION_EXIT_CHURN_LIMIT) % inc == 0


@pytest.mark.parametrize("preset", PRESETS)
def test_sync_committee_constants(preset):
    spec = get_spec("altair", preset)
    assert int(spec.SYNC_COMMITTEE_SIZE) >= 1
    assert int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD) >= 1


@pytest.mark.parametrize("preset", PRESETS)
def test_inactivity_and_hysteresis_quotients(preset):
    spec = get_spec("altair", preset)
    assert int(spec.config.INACTIVITY_SCORE_BIAS) >= 1
    assert int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE) >= 1
    assert int(spec.HYSTERESIS_DOWNWARD_MULTIPLIER) < int(
        spec.HYSTERESIS_UPWARD_MULTIPLIER
    )


def test_intervals_and_due_bps_sane():
    fc = None
    from eth_consensus_specs_tpu.specc import compile_fork

    fc = compile_fork("phase0", "minimal", None, True)
    assert int(fc.ATTESTATION_DUE_BPS) < 10_000


@pytest.mark.parametrize("preset", PRESETS)
def test_whistleblower_quotients_positive(preset):
    spec = get_spec("phase0", preset)
    assert int(spec.WHISTLEBLOWER_REWARD_QUOTIENT) >= 1
    assert int(spec.PROPOSER_REWARD_QUOTIENT) >= 1


@pytest.mark.parametrize("preset", PRESETS)
def test_max_operations_per_block_positive(preset):
    spec = get_spec("phase0", preset)
    for name in (
        "MAX_ATTESTATIONS",
        "MAX_DEPOSITS",
        "MAX_PROPOSER_SLASHINGS",
        "MAX_ATTESTER_SLASHINGS",
        "MAX_VOLUNTARY_EXITS",
    ):
        assert int(getattr(spec, name)) >= 1
