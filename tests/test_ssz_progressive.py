"""Progressive SSZ types (EIP-7916/EIP-7495)
(reference: ssz/simple-serialize.md:58-99, :386-433)."""

import pytest

from eth_consensus_specs_tpu.ssz import (
    Bytes32,
    Container,
    hash_tree_root,
    serialize,
    uint8,
    uint64,
)
from eth_consensus_specs_tpu.ssz.hashing import hash_bytes
from eth_consensus_specs_tpu.ssz.merkle import merkleize_chunks, mix_in_length
from eth_consensus_specs_tpu.ssz.progressive import (
    ProgressiveBitlist,
    ProgressiveByteList,
    ProgressiveContainer,
    ProgressiveList,
    merkleize_progressive,
    mix_in_active_fields,
)


def test_merkleize_progressive_base_cases():
    assert merkleize_progressive([]) == b"\x00" * 32
    chunk = b"\x05" * 32
    # one chunk: hash(progressive(rest=[], 4), merkleize([chunk], 1))
    expected = hash_bytes(b"\x00" * 32 + chunk)
    assert merkleize_progressive([chunk]) == expected


def test_merkleize_progressive_recursion_shape():
    chunks = [bytes([i]) * 32 for i in range(6)]
    # spec recursion: hash(progressive(chunks[1:], 4), merkleize(chunks[:1], 1))
    inner = merkleize_progressive(chunks[1:], 4)
    expected = hash_bytes(inner + merkleize_chunks(chunks[:1], limit=1))
    assert merkleize_progressive(chunks) == expected
    # and the inner level: hash(progressive(chunks[5:], 16), merkleize(chunks[1:5], 4))
    inner2 = hash_bytes(
        merkleize_progressive(chunks[5:], 16) + merkleize_chunks(chunks[1:5], limit=4)
    )
    assert inner == inner2


def test_progressive_list_root_stability():
    """Roots are a pure function of contents — no capacity commitment."""
    PL = ProgressiveList[uint64]
    assert PL(range(10)).get_hash_tree_root() == PL(range(10)).get_hash_tree_root()
    assert PL(range(10)).get_hash_tree_root() != PL(range(11)).get_hash_tree_root()
    assert PL([]).get_hash_tree_root() == mix_in_length(b"\x00" * 32, 0)


def test_progressive_list_serialization_roundtrip():
    PL = ProgressiveList[uint64]
    v = PL(range(1000))
    data = serialize(v)
    assert len(data) == 8000
    assert list(PL.decode_bytes(data)) == list(v)


def test_progressive_list_of_composite():
    class Pair(Container):
        a: uint64
        b: Bytes32

    PL = ProgressiveList[Pair]
    v = PL([Pair(a=i, b=bytes([i]) * 32) for i in range(5)])
    roots = [bytes(hash_tree_root(p)) for p in v]
    expected = mix_in_length(merkleize_progressive(roots), 5)
    assert v.get_hash_tree_root() == expected
    assert list(PL.decode_bytes(serialize(v))) == list(v)


def test_progressive_list_append_unbounded():
    PL = ProgressiveList[uint8]
    v = PL([])
    for i in range(300):
        v.append(i % 256)
    assert len(v) == 300


def test_progressive_bitlist():
    bits = [True, False] * 500
    v = ProgressiveBitlist(bits)
    data = serialize(v)
    assert ProgressiveBitlist.decode_bytes(data) == v
    assert v.get_hash_tree_root() != ProgressiveBitlist(bits + [True]).get_hash_tree_root()


def test_progressive_byte_list():
    v = ProgressiveByteList(b"\xab" * 100)
    from eth_consensus_specs_tpu.ssz.merkle import pack_bytes

    expected = mix_in_length(merkleize_progressive(pack_bytes(b"\xab" * 100)), 100)
    assert v.get_hash_tree_root() == expected


def test_progressive_container_root():
    class PC(ProgressiveContainer([1, 0, 1])):
        a: uint64
        b: Bytes32

    x = PC(a=5, b=b"\x01" * 32)
    roots = [bytes(hash_tree_root(x.a)), bytes(hash_tree_root(x.b))]
    expected = mix_in_active_fields(merkleize_progressive(roots), [1, 0, 1])
    assert x.get_hash_tree_root() == expected
    # same fields, different active positions -> different root
    class PC2(ProgressiveContainer([1, 1])):
        a: uint64
        b: Bytes32

    assert PC2(a=5, b=b"\x01" * 32).get_hash_tree_root() != x.get_hash_tree_root()


def test_progressive_container_validation():
    with pytest.raises(AssertionError):
        ProgressiveContainer([])
    with pytest.raises(AssertionError):
        ProgressiveContainer([1, 0])  # must not end in 0
    with pytest.raises(AssertionError):
        ProgressiveContainer([1] * 257)
    with pytest.raises(TypeError):
        class Bad(ProgressiveContainer([1, 0, 1])):
            a: uint64  # 1 field vs 2 active bits
