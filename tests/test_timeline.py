"""Fleet timeline assembly and slot autopsy (obs/timeline.py).

The tier-1 acceptance story for the cross-process trace assembler:
deliberate clock skew between processes is corrected to truthful
nesting, a missing or truncated replica stream degrades to a
partial-but-valid trace (never a crash), a trace id re-used across runs
is split into episodes and the autopsy reads the latest one, and a
SIGKILL-respawn slot shows the ``recovery`` stage on its critical path
with >= 95% of the end-to-end wall attributed to named stages. All
synthetic streams, pure host — the shapes match exactly what
obs/registry.py stamps and serve/frontdoor.py emits.
"""

import json

import pytest

from eth_consensus_specs_tpu.obs import timeline
from eth_consensus_specs_tpu.obs.histogram import Histogram

FD_PID, R0_PID, R1_PID = 100, 200, 300
SKEW0, SKEW1 = 500.0, -250.0  # replica perf_counter epochs vs the parent's


def _fd(t_mono, **kw):
    kw.update(pid=FD_PID, tid=1, t_mono=t_mono, t_wall=1000.0 + t_mono)
    return kw


def _replica(pid, skew, t_parent, **kw):
    kw.update(pid=pid, tid=9, t_mono=t_parent + skew, t_wall=1000.0 + t_parent)
    return kw


def _sync(pid, skew, replica, t=10.0, src="probe"):
    return _fd(
        t + 0.002, kind="clock.sync", replica=replica, peer=pid,
        t_send=t, t_recv=t + 0.002, remote_mono=t + 0.001 + skew, src=src,
    )


def _request_done(t_end, slot, e2e_ms, ok=True, trace="t1-req1", **kw):
    ev = _fd(
        t_end, kind="frontdoor.request_done", req_kind="slot", trace=trace,
        e2e_ms=e2e_ms, ok=ok, hedged=False, slot=slot,
    )
    ev.update(kw)
    return ev


def _rpc_span(pid, skew, t_end, dur_s, trace="t1", parent="req1"):
    return _replica(
        pid, skew, t_end, kind="span", name="frontdoor.rpc", s=dur_s,
        depth=0, trace_id=trace, span_id="aaa", parent_span=parent,
    )


# ------------------------------------------------------------- clock skew --


def test_clock_skew_corrected_to_truthful_nesting():
    """A replica stream 500s AHEAD of the parent still nests inside the
    request envelope once the clock.sync offset is applied."""
    evs = [
        _sync(R0_PID, SKEW0, replica=0),
        _rpc_span(R0_PID, SKEW0, t_end=11.045, dur_s=0.040),
        _request_done(
            11.050, slot=7, e2e_ms=50.0,
            stages={"queue": 5.0, "device": 30.0, "resolve": 5.0, "total": 40.0},
        ),
    ]
    tl = timeline.Timeline(evs)
    trace = tl.perfetto()
    assert timeline.validate(trace) == []
    (x,) = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    (b,) = [e for e in trace["traceEvents"] if e["ph"] == "b"]
    (e_,) = [e for e in trace["traceEvents"] if e["ph"] == "e"]
    # the envelope [b, e] must CONTAIN the replica's rpc slice — with
    # raw (uncorrected) stamps the slice would sit 500s to the right
    assert b["ts"] <= x["ts"]
    assert x["ts"] + x["dur"] <= e_["ts"]
    # and the whole trace JSON-serializes (the artifact contract)
    json.dumps(trace)


def test_two_replicas_opposite_skews_one_timeline():
    """Two replicas skewed in OPPOSITE directions land on one timeline
    in parent order, each on its own named process track."""
    evs = [
        _sync(R0_PID, SKEW0, replica=0, t=5.0),
        _sync(R1_PID, SKEW1, replica=1, t=6.0),
        _rpc_span(R0_PID, SKEW0, t_end=11.0, dur_s=0.01, parent="req1"),
        _rpc_span(R1_PID, SKEW1, t_end=12.0, dur_s=0.01, parent="req2"),
    ]
    tl = timeline.Timeline(evs)
    trace = tl.perfetto()
    assert timeline.validate(trace) == []
    xs = sorted(
        (e for e in trace["traceEvents"] if e["ph"] == "X"),
        key=lambda e: e["ts"],
    )
    assert [x["pid"] for x in xs] == [R0_PID, R1_PID]  # parent order, not raw
    names = {
        e["pid"]: e["args"]["name"]
        for e in trace["traceEvents"] if e["ph"] == "M"
    }
    assert names[FD_PID] == "frontdoor"
    assert names[R0_PID] == "replica 0"
    assert names[R1_PID] == "replica 1"


def test_wall_anchor_fallback_without_sync():
    """A pid with NO clock.sync sample still lands via the wall/mono
    pair every stamped event carries (millisecond-grade, but on the
    timeline — a truncated stream must not vanish)."""
    evs = [
        # parent events establish the ref anchor
        _fd(10.0, kind="frontdoor.replica_spawned", replica=0),
        _fd(20.0, kind="frontdoor.closed"),
        _rpc_span(R0_PID, SKEW0, t_end=15.0, dur_s=0.01),
    ]
    tl = timeline.Timeline(evs)
    assert R0_PID not in tl.clock.synced_pids
    t = tl.clock.to_ref(R0_PID, 15.0 + SKEW0)
    assert abs(t - 15.0) < 0.05  # wall anchors, not the raw 500s skew
    assert timeline.validate(tl.perfetto()) == []


# -------------------------------------------------------- partial streams --


def test_truncated_and_missing_streams_partial_valid_trace(tmp_path):
    """A torn JSONL line (SIGKILL mid-write) is skipped, a missing
    sibling is an empty stream, and the assembly stays valid."""
    parent = tmp_path / "run.jsonl"
    with open(parent, "w") as fh:
        fh.write(json.dumps(_sync(R0_PID, SKEW0, replica=0)) + "\n")
        fh.write(json.dumps(_request_done(11.0, slot=1, e2e_ms=10.0)) + "\n")
        fh.write("not json at all\n")
        fh.write('{"kind": "span", "name": "torn')  # no newline, no brace
    with open(tmp_path / "run.slot-fd-r0.jsonl", "w") as fh:
        fh.write(json.dumps(_rpc_span(R0_PID, SKEW0, 10.999, 0.008)) + "\n")
        fh.write('{"torn": ')
    # r1's stream never made it to disk at all — only r0's sibling exists
    tl = timeline.Timeline.from_path(str(parent))
    assert len(tl.events) == 3  # garbage dropped, good lines kept
    trace = tl.perfetto()
    assert timeline.validate(trace) == []
    assert {e["pid"] for e in tl.events} == {FD_PID, R0_PID}
    rep = tl.autopsy(slot=1)
    assert rep is not None and rep["coverage"] > 0.0


def test_missing_file_is_empty_stream(tmp_path):
    assert timeline.load_stream(str(tmp_path / "nope.jsonl")) == []
    assert timeline.Timeline.from_path(str(tmp_path / "nope.jsonl")).events == []
    assert timeline.assemble_to_file(
        str(tmp_path / "nope.jsonl"), str(tmp_path / "out.json")
    ) is None


# ------------------------------------------------------------- episodes --


def test_duplicate_trace_ids_across_runs_disambiguated():
    """The same trace id (and slot number) appended across two runs is
    split on the wall gap; the autopsy reads the LATEST episode and its
    monotonic stamps never mix with the first boot's."""
    run1 = [
        _sync(R0_PID, SKEW0, replica=0, t=5.0),
        _request_done(10.0, slot=3, e2e_ms=40.0, trace="tX-req1"),
    ]
    # second run: same trace id, same slot, 10 minutes later, NEW
    # monotonic epoch (the process restarted — small t_mono again)
    run2 = [
        {**_request_done(9.0, slot=3, e2e_ms=80.0, trace="tX-req1"),
         "t_wall": 1000.0 + 10.0 + 600.0},
    ]
    tl = timeline.Timeline(run1 + run2)
    attempts = tl.slot_attempts(3)
    assert len(attempts) == 1  # the latest episode only
    assert attempts[0]["e2e_ms"] == 80.0
    rep = tl.autopsy(slot=3)
    assert rep["e2e_ms"] == pytest.approx(80.0)
    # flow ids of the two episodes must differ or Perfetto would draw
    # one arrow across a 10-minute void
    trace = tl.perfetto()
    assert timeline.validate(trace) == []
    ids = {e["id"] for e in trace["traceEvents"] if e["ph"] in ("b", "e")}
    assert ids == {"tX-req1", "tX-req1#1"}


def test_split_episodes_respects_gap_env(monkeypatch):
    monkeypatch.setenv("ETH_SPECS_OBS_TRACE_GAP_S", "10")
    items = [{"t_wall": 0.0}, {"t_wall": 5.0}, {"t_wall": 30.0}]
    assert [len(ep) for ep in timeline.split_episodes(items)] == [2, 1]
    monkeypatch.setenv("ETH_SPECS_OBS_TRACE_GAP_S", "100")
    assert [len(ep) for ep in timeline.split_episodes(items)] == [3]


# -------------------------------------------------------------- autopsy --


def test_sigkill_respawn_slot_shows_recovery_on_critical_path():
    """A slot whose owner was SIGKILLed mid-flight: shed attempt, an
    outage gap bounded by replica_lost/replica_recovered, then the
    successful retry. ``recovery`` must land on the critical path and
    named stages must cover >= 95% of the wall."""
    evs = [
        _sync(R0_PID, SKEW0, replica=0, t=5.0),
        # attempt 1: typed shed while the owner is dead (fast failure)
        _request_done(10.01, slot=9, e2e_ms=10.0, ok=False,
                      err="Overloaded", trace="tA-req1"),
        _fd(10.02, kind="frontdoor.replica_lost", replica=0, exitcode=-9),
        _fd(12.02, kind="frontdoor.replica_recovered", replica=0,
            recovery_ms=2000.0, resident=True),
        # attempt 2: resubmitted after the respawn, succeeds
        _request_done(
            12.30, slot=9, e2e_ms=200.0, trace="tA-req2",
            stages={"queue": 20.0, "device": 150.0, "resolve": 10.0,
                    "total": 180.0},
        ),
    ]
    tl = timeline.Timeline(evs)
    rep = tl.autopsy(slot=9)
    assert rep is not None
    assert len(rep["attempts"]) == 2
    assert rep["attempts"][0]["err"] == "Overloaded"
    stages = rep["stages_ms"]
    # the outage overlapped the inter-attempt gap: death 10.02 →
    # recovered 12.02 inside the gap [10.01, 12.10]
    assert stages["recovery"] == pytest.approx(2000.0, rel=0.01)
    assert "retry_shed" in stages
    path_stages = [row["stage"] for row in rep["critical_path"]]
    assert path_stages[0] == "recovery"  # the dominant stage BY FAR
    assert rep["coverage"] >= 0.95
    assert rep["verdict"] == "OVER BUDGET"  # 2.3s against the 1s budget
    assert rep["over_ms"] > 0


def test_autopsy_picks_worst_slot_by_default():
    evs = [
        _request_done(10.0, slot=1, e2e_ms=10.0, trace="t1-a"),
        _request_done(11.0, slot=2, e2e_ms=500.0, trace="t2-a",
                      stages={"device": 450.0, "total": 450.0}),
        _request_done(12.0, slot=3, e2e_ms=20.0, trace="t3-a"),
    ]
    rep = timeline.Timeline(evs).autopsy()
    assert rep["slot"] == 2
    assert rep["stages_ms"]["device"] == pytest.approx(450.0)


def test_checkpoint_carved_out_of_containing_stage():
    evs = [
        _sync(R0_PID, SKEW0, replica=0, t=5.0),
        _request_done(
            11.0, slot=4, e2e_ms=100.0, trace="t4-a",
            stages={"device": 80.0, "resolve": 10.0, "total": 90.0},
        ),
        # a 30ms durable checkpoint inside the attempt window, stamped
        # on the OWNER's skewed clock
        _replica(R0_PID, SKEW0, 10.95, kind="span",
                 name="resident.checkpoint", s=0.030, depth=2),
    ]
    rep = timeline.Timeline(evs).autopsy(slot=4)
    assert rep["stages_ms"]["checkpoint"] == pytest.approx(30.0, rel=0.01)
    assert rep["stages_ms"]["device"] == pytest.approx(50.0, rel=0.01)
    # carving re-attributes, never inflates: the sum is unchanged
    assert sum(rep["stages_ms"].values()) == pytest.approx(100.0, rel=0.01)


def test_autopsy_by_trace_id_and_render():
    evs = [_request_done(10.0, slot=5, e2e_ms=25.0, trace="feed-beef")]
    tl = timeline.Timeline(evs)
    rep = tl.autopsy(trace_id="feed")
    assert rep is not None and rep["e2e_ms"] == pytest.approx(25.0)
    text = timeline.render_autopsy(rep)
    assert "within budget" in text and "critical path" in text
    assert tl.autopsy(trace_id="no-such-trace") is None
    assert timeline.Timeline([]).autopsy() is None


# ----------------------------------------------------------------- diff --


def _hist_snapshot(values):
    h = Histogram()
    for v in values:
        h.record(v)
    return h.snapshot()


def test_diff_names_the_regressing_stage():
    a = {"stage_hist": {
        "serve.stage_ms.queue": _hist_snapshot([1.0] * 50),
        "serve.stage_ms.device": _hist_snapshot([10.0] * 50),
    }}
    b = {"stage_hist": {
        "serve.stage_ms.queue": _hist_snapshot([1.0] * 50),
        "serve.stage_ms.device": _hist_snapshot([40.0] * 50),  # 4x
    }}
    d = timeline.diff_reports(a, b)
    assert [r["stage"] for r in d["regressed"]] == ["device"]
    assert "device" in d["verdict"]
    assert not d["improved"]
    # the reverse comparison reads as an improvement, not a regression
    back = timeline.diff_reports(b, a)
    assert not back["regressed"]
    assert [r["stage"] for r in back["improved"]] == ["device"]
    assert "no regression" in back["verdict"]
    text = timeline.render_diff(d)
    assert "REGRESSED" in text and "device" in text


def test_diff_attributes_replica_movement():
    a = {"stage_hist": {}, "autopsy": {"replica_device_ms": {
        "replica 0": 100.0, "replica 1": 100.0}}}
    b = {"stage_hist": {}, "autopsy": {"replica_device_ms": {
        "replica 0": 100.0, "replica 1": 400.0}}}
    d = timeline.diff_reports(a, b)
    assert d["replicas_moved"][0]["replica"] == "replica 1"
    assert d["replicas_moved"][0]["delta_ms"] == pytest.approx(300.0)


# ------------------------------------------------------------ validation --


def test_validate_rejects_broken_traces():
    assert timeline.validate({}) == ["traceEvents is not a list"]
    bad_nest = {"traceEvents": [
        {"ph": "X", "name": "a", "pid": 1, "tid": 1, "ts": 0, "dur": 1000},
        {"ph": "X", "name": "b", "pid": 1, "tid": 1, "ts": 500, "dur": 1000},
    ]}
    assert any("nest" in p for p in timeline.validate(bad_nest))
    dangling = {"traceEvents": [
        {"ph": "f", "id": "x", "pid": 1, "tid": 1, "ts": 0, "bp": "e"},
    ]}
    assert any("before s" in p for p in timeline.validate(dangling))
    unbalanced = {"traceEvents": [
        {"ph": "b", "cat": "request", "id": "r", "name": "q", "pid": 1,
         "tid": 1, "ts": 0},
    ]}
    assert any("without end" in p for p in timeline.validate(unbalanced))


def test_assemble_to_file_writes_loadable_trace(tmp_path):
    parent = tmp_path / "run.jsonl"
    with open(parent, "w") as fh:
        for ev in (
            _sync(R0_PID, SKEW0, replica=0),
            _rpc_span(R0_PID, SKEW0, 11.045, 0.040),
            _request_done(11.05, slot=7, e2e_ms=50.0,
                          stages={"device": 40.0, "total": 40.0}),
        ):
            fh.write(json.dumps(ev) + "\n")
    out = tmp_path / "run.trace.json"
    summary = timeline.assemble_to_file(str(parent), str(out))
    assert summary["processes"] == 2
    assert summary["synced_pids"] == 1
    trace = json.load(open(out))
    assert timeline.validate(trace) == []
    assert trace["displayTimeUnit"] == "ms"
