"""Finality scenarios: Casper FFG justification/finalization rules driven
through whole epochs of blocks-with-attestations
(reference: eth2spec/test/phase0/finality/test_finality.py).

Timing note: with the genesis guard (`current_epoch <= GENESIS_EPOCH + 1`
skips justification processing), the first two transitions evaluate
nothing; epochs 1 and 2 justify together at the 2->3 transition."""

import pytest

# multi-epoch finality walks — nightly lane (make test-full)
pytestmark = pytest.mark.slow

from eth_consensus_specs_tpu.test_infra.attestations import next_epoch_with_attestations
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_all_phases
from eth_consensus_specs_tpu.test_infra.state import next_epoch


def _epoch(spec, state):
    return int(spec.get_current_epoch(state))


@with_all_phases
@spec_state_test
def test_no_finality_at_genesis_epochs(spec, state):
    """The genesis guard blocks justification for the first two epochs."""
    yield "pre", state
    blocks = []
    for _ in range(2):
        _, bs, _ = next_epoch_with_attestations(spec, state, True, False)
        blocks.extend(bs)
    yield "blocks", blocks
    yield "post", state
    assert int(state.current_justified_checkpoint.epoch) == spec.GENESIS_EPOCH
    assert int(state.finalized_checkpoint.epoch) == spec.GENESIS_EPOCH


@with_all_phases
@spec_state_test
def test_finality_rule_4(spec, state):
    """Consecutive current-epoch justification finalizes the older of the
    pair (rule 4): after 4 full epochs, justified=3, finalized=2."""
    yield "pre", state
    blocks = []
    for _ in range(4):
        _, bs, _ = next_epoch_with_attestations(spec, state, True, False)
        blocks.extend(bs)
    yield "blocks", blocks
    yield "post", state
    assert _epoch(spec, state) == 4
    assert int(state.current_justified_checkpoint.epoch) == 3
    assert int(state.finalized_checkpoint.epoch) == 2
    assert [int(b) for b in state.justification_bits] == [1, 1, 1, 0]


@with_all_phases
@spec_state_test
def test_finality_rule_1_previous_epoch_attestations(spec, state):
    """Justification exclusively through previous-epoch attestations lags
    one epoch; finalization follows via rule 1 (prev_justified with bits
    [1..3] set)."""
    yield "pre", state
    blocks = []
    for _ in range(2):
        _, bs, _ = next_epoch_with_attestations(spec, state, True, False)
        blocks.extend(bs)
    for _ in range(3):
        _, bs, _ = next_epoch_with_attestations(spec, state, False, True)
        blocks.extend(bs)
    yield "blocks", blocks
    yield "post", state
    assert _epoch(spec, state) == 5
    assert int(state.current_justified_checkpoint.epoch) == 3
    assert int(state.finalized_checkpoint.epoch) == 1
    assert [int(b) for b in state.justification_bits] == [0, 1, 1, 1]


@with_all_phases
@spec_state_test
def test_no_attestations_no_justification(spec, state):
    """Empty epochs never move the checkpoints."""
    before = state.current_justified_checkpoint.copy()
    for _ in range(3):
        next_epoch(spec, state)
    assert state.current_justified_checkpoint == before
    assert int(state.finalized_checkpoint.epoch) == spec.GENESIS_EPOCH


@with_all_phases
@spec_state_test
def test_justification_bits_rotate(spec, state):
    """The 4-bit justification window shifts every epoch."""
    yield "pre", state
    blocks = []
    for _ in range(3):
        _, bs, _ = next_epoch_with_attestations(spec, state, True, False)
        blocks.extend(bs)
    yield "blocks", blocks
    yield "post", state
    assert [int(b) for b in state.justification_bits] == [1, 1, 0, 0]
    next_epoch(spec, state)  # an empty epoch shifts the window
    assert [int(b) for b in state.justification_bits] == [0, 1, 1, 0]


@with_all_phases
@spec_state_test
def test_finality_stalls_then_recovers(spec, state):
    """Finality stops during an empty period and resumes once attestations
    return (the liveness half of the FFG story)."""
    yield "pre", state
    blocks = []
    for _ in range(4):
        _, bs, _ = next_epoch_with_attestations(spec, state, True, False)
        blocks.extend(bs)
    finalized_before = int(state.finalized_checkpoint.epoch)
    assert finalized_before == 2
    for _ in range(2):
        next_epoch(spec, state)  # stall: spanned by the next block's slot jump
    assert int(state.finalized_checkpoint.epoch) == finalized_before
    for _ in range(3):
        _, bs, _ = next_epoch_with_attestations(spec, state, True, False)
        blocks.extend(bs)
    yield "blocks", blocks
    yield "post", state
    assert int(state.finalized_checkpoint.epoch) > finalized_before
