"""Dedicated vector runners (gen/runners/): bls, kzg, shuffling,
ssz_generic — tree layout, payload shape, and self-consistency."""

import os

import pytest
import yaml

from eth_consensus_specs_tpu.gen.gen_runner import run_generator
from eth_consensus_specs_tpu.gen.runners import RUNNER_MODULES, get_runner_cases


def test_all_runners_registered():
    assert set(RUNNER_MODULES) == {"bls", "kzg", "shuffling", "ssz_generic"}


def test_shuffling_runner_emits_mapping(tmp_path):
    cases = get_runner_cases(runners=("shuffling",))
    assert len(cases) == 4 * 8
    stats = run_generator(cases[:4], str(tmp_path))
    assert stats["written"] == 4 and stats["failed"] == 0
    found = []
    for root, _dirs, files in os.walk(tmp_path):
        if "mapping.yaml" in files:
            found.append(os.path.join(root, "mapping.yaml"))
    assert found
    data = yaml.safe_load(open(found[0]))
    assert set(data) == {"seed", "count", "mapping"}
    assert sorted(data["mapping"]) == list(range(data["count"]))


def test_shuffling_matches_spec_form(tmp_path):
    from eth_consensus_specs_tpu.forks import get_spec

    spec = get_spec("phase0", "minimal")
    cases = [c for c in get_runner_cases(runners=("shuffling",)) if c.case_name.endswith("_16")]
    run_generator(cases[:1], str(tmp_path))
    for root, _dirs, files in os.walk(tmp_path):
        if "mapping.yaml" in files:
            data = yaml.safe_load(open(os.path.join(root, "mapping.yaml")))
            seed = bytes.fromhex(data["seed"][2:])
            for i, v in enumerate(data["mapping"]):
                assert v == int(spec.compute_shuffled_index(i, data["count"], seed))
            return
    raise AssertionError("no mapping emitted")


def test_bls_runner_round_trips(tmp_path):
    cases = get_runner_cases(runners=("bls",))
    assert len(cases) >= 20
    stats = run_generator(cases, str(tmp_path))
    assert stats["failed"] == 0 and stats["written"] == len(cases)
    # verify one verify-case payload against the backend
    from eth_consensus_specs_tpu.utils import bls

    for root, _dirs, files in os.walk(tmp_path):
        if "data.yaml" in files and os.path.basename(root) == "verify_valid":
            data = yaml.safe_load(open(os.path.join(root, "data.yaml")))
            inp = data["input"]
            assert bls.Verify(
                bytes.fromhex(inp["pubkey"][2:]),
                bytes.fromhex(inp["message"][2:]),
                bytes.fromhex(inp["signature"][2:]),
            ) is data["output"]
            return
    raise AssertionError("verify_valid case not emitted")


def test_ssz_generic_runner(tmp_path):
    cases = get_runner_cases(runners=("ssz_generic",))
    stats = run_generator(cases, str(tmp_path))
    assert stats["failed"] == 0 and stats["written"] == len(cases)
    valid = invalid = 0
    for root, _dirs, files in os.walk(tmp_path):
        if "serialized.ssz_snappy" in files:
            if f"{os.sep}valid{os.sep}" in root + os.sep:
                valid += 1
            if f"{os.sep}invalid{os.sep}" in root + os.sep:
                invalid += 1
    assert valid >= 12 and invalid >= 5


@pytest.mark.slow
def test_kzg_runner(tmp_path):
    cases = get_runner_cases(runners=("kzg",))
    stats = run_generator(cases, str(tmp_path))
    assert stats["failed"] == 0 and stats["written"] == len(cases)
