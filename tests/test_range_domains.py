"""Boundary-value tests GENERATED from the registry's declared domains.

The ``Variant.domains`` declarations are load-bearing twice: they seed
rangelint's interval proof AND (here) they generate runtime corner
tests. Every corner value executed below is read out of the registry —
never hard-coded — so a stale or weakened declaration fails at runtime
against the family's host oracle, not just on paper.

Fast lane: declaration self-consistency for every variant, the cheap
hash-word families (sha256, merkle, merkle_many, shuffle) and the
host-side canonical-domain check for the pairing's prepared inputs.
Slow lane (nightly, like the rest of the device-crypto suite): the
minutes-scale compiles — state_root's post-epoch tree, and the
limb-arithmetic families executed at their Montgomery corners (fr_fft,
g1_msm, bls_msm, the pairing's active-mask corners)."""

from __future__ import annotations

import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eth_consensus_specs_tpu.analysis import kernels


def _variant(name):
    spec = kernels.by_name()[name]
    variants = spec.build_variants(None)
    assert variants, name
    return variants[0]


def _corners(dom):
    assert dom.corners, f"domain {dom.name!r} declares no corners"
    return list(dom.corners)


def _obj(a):
    return np.asarray(a).astype(object)


# ----------------------------------------------------- declaration hygiene


def test_every_variant_declares_domains_and_corners_are_members():
    """One Domain per traced input leaf, bounds inside the dtype lane,
    every declared corner a member of its own domain — the minimum for
    the corner tests below (and the prover's seeds) to mean anything."""
    checked = 0
    for spec in kernels.REGISTRY:
        for v in spec.build_variants(None):
            traced = [
                a
                for i, a in enumerate(v.args)
                if i not in (v.static_argnums or ())
            ]
            leaves = jax.tree_util.tree_leaves(traced)
            assert len(v.domains) == len(leaves), (spec.name, v.label)
            for dom, leaf in zip(v.domains, leaves):
                dt = np.dtype(leaf.dtype)
                lane_max = 1 if dt == np.bool_ else int(np.iinfo(dt).max)
                lo, hi = _obj(dom.lo), _obj(dom.hi)
                assert np.all(lo >= 0), (spec.name, dom.name)
                assert np.all(hi <= lane_max), (spec.name, dom.name)
                assert np.all(lo <= hi), (spec.name, dom.name)
                for lab, c in dom.corners:
                    c = _obj(c)
                    assert np.all(lo <= c) and np.all(c <= hi), (
                        spec.name,
                        dom.name,
                        lab,
                    )
                checked += 1
    assert checked >= 25, "registry lost domain coverage"


def test_montgomery_domains_declare_the_issue_corners():
    """The ISSUE's named boundary members, read back from the registry:
    all-zero limbs and p-1 everywhere, 2p-1 on the redundant domains —
    and NOT on the pairing's canonical (< p) domains, whose absence IS
    the declared _fat_p precondition."""
    msm = _variant("g1_msm")
    for dom in msm.domains[1:]:
        labels = {lab for lab, _ in _corners(dom)}
        assert {"zero", "p-1", "2p-1"} <= labels, dom.name
    for dom in _variant("pairing").domains[:3]:
        labels = {lab for lab, _ in _corners(dom)}
        assert "p-1" in labels and "2p-1" not in labels, dom.name


# ------------------------------------------------------- hash-word families


def test_sha256_word_corners_vs_hashlib():
    from eth_consensus_specs_tpu.ops.sha256 import sha256_64B_batch_np

    dom = _variant("sha256").domains[0]
    for label, w in _corners(dom):
        msg = np.full((16,), w, dtype=np.uint32).astype(">u4").view(np.uint8)
        out = sha256_64B_batch_np(msg.reshape(1, 64))
        assert out[0].tobytes() == hashlib.sha256(msg.tobytes()).digest(), label


def _host_tree_root(chunks: list[bytes]) -> bytes:
    while len(chunks) > 1:
        chunks = [
            hashlib.sha256(chunks[i] + chunks[i + 1]).digest()
            for i in range(0, len(chunks), 2)
        ]
    return chunks[0]


def test_merkle_leaf_corners_vs_hashlib():
    from eth_consensus_specs_tpu.ops.merkle import _tree_root_fused

    dom = _variant("merkle").domains[0]
    depth = 4
    for label, w in _corners(dom):
        leaves = np.full((1 << depth, 8), w, dtype=np.uint32)
        root = np.asarray(_tree_root_fused(jnp.asarray(leaves), depth))
        want = _host_tree_root([r.astype(">u4").tobytes() for r in leaves])
        assert root.astype(">u4").tobytes() == want, label


def test_merkle_many_batch_corners_vs_hashlib():
    from eth_consensus_specs_tpu.ops.merkle import _many_tree_root_fused

    dom = _variant("merkle_many").domains[0]
    depth, batch = 3, 4
    for label, w in _corners(dom):
        leaves = np.full((batch, 1 << depth, 8), w, dtype=np.uint32)
        roots = np.asarray(_many_tree_root_fused(jnp.asarray(leaves), depth))
        want = _host_tree_root([r.astype(">u4").tobytes() for r in leaves[0]])
        for b in range(batch):
            assert roots[b].astype(">u4").tobytes() == want, label


def test_merkle_inc_corners_vs_hashlib():
    """Forest-update corners from the registry's declared domains: the
    leaf/node lanes at their hash-word corners and the dirty mask at
    both of its corners (all-clean = identity, all-dirty = dense
    rebuild), against the host tree oracle."""
    from eth_consensus_specs_tpu.ops import merkle_inc as mi

    spec = kernels.by_name()["merkle_inc"]
    v = spec.build_variants(None)[0]
    words_dom, mask_dom = v.domains[0], v.domains[1]
    depth = 3
    n = 1 << depth
    for wlab, w in _corners(words_dom):
        leaves = np.full((n, 8), w, dtype=np.uint32)
        nodes = mi.build_forest(jnp.asarray(leaves), 1)
        want = _host_tree_root([r.astype(">u4").tobytes() for r in leaves])
        for mlab, m in _corners(mask_dom):
            mask = np.full((1, n), bool(m))
            out, root = mi._apply_kernel(depth, 2, 2)(
                nodes, jnp.asarray(mask), jnp.asarray(leaves[None])
            )
            assert np.asarray(root).astype(">u4").tobytes() == want, (wlab, mlab)
            nodes = out


def test_shuffle_corners_stay_bijective():
    """Swap-or-not at every (decision-word, pivot) corner pair: whatever
    the digest bits say, the output must remain a permutation — the
    property the consensus shuffle's invertibility rests on."""
    from eth_consensus_specs_tpu.ops.shuffle import _device_shuffle_kernel

    v = _variant("shuffle")
    words_dom, pivot_dom = v.domains
    n = int(pivot_dom.hi) + 1  # declared: pivots in [0, n)
    rounds = v.args[1].shape[0]
    num_chunks = v.args[0].shape[0] // rounds
    kern = _device_shuffle_kernel(n, rounds, num_chunks)
    for wlab, w in _corners(words_dom):
        for plab, pv in _corners(pivot_dom):
            blocks = np.full((rounds * num_chunks, 16), w, np.uint32)
            pivots = np.full((rounds,), pv, np.int32)
            idx = np.asarray(kern(jnp.asarray(blocks), jnp.asarray(pivots)))
            assert sorted(idx.tolist()) == list(range(n)), (wlab, plab)


@pytest.mark.slow  # two full post-epoch tree compiles, ~90 s on CPU
def test_state_root_u64_corners_vs_host_oracle():
    from eth_consensus_specs_tpu.forks import get_spec
    from eth_consensus_specs_tpu.ops import state_root_host as srh
    from eth_consensus_specs_tpu.ops.state_columns import JustificationState
    from eth_consensus_specs_tpu.ops.state_root import (
        StateRootArrays,
        post_epoch_state_root,
        synthetic_static,
    )

    v = _variant("state_root")
    # the three u64 columns (balances/effective_balance/inactivity) share
    # one declared full-lane domain; exercise BOTH its corners
    bal_dom = v.domains[6]
    assert "u64" in bal_dom.name

    spec = get_spec("altair", "minimal")
    n = 32
    arrays, meta = synthetic_static(spec, n)
    arrays_np = StateRootArrays(*[np.asarray(a) for a in arrays])
    zero32 = np.zeros(32, np.uint8)
    just = JustificationState(
        current_epoch=jnp.uint64(5),
        justification_bits=jnp.asarray([True, False, True, False]),
        prev_justified_epoch=jnp.uint64(3),
        prev_justified_root=jnp.asarray(zero32),
        cur_justified_epoch=jnp.uint64(4),
        cur_justified_root=jnp.asarray(zero32),
        finalized_epoch=jnp.uint64(2),
        finalized_root=jnp.asarray(zero32),
        block_root_prev=jnp.asarray(zero32),
        block_root_cur=jnp.asarray(zero32),
        slashings_sum=jnp.uint64(0),
    )
    for label, cv in _corners(bal_dom):
        col_np = np.full((n,), np.uint64(cv), np.uint64)
        col = jnp.asarray(col_np)
        dev = np.asarray(post_epoch_state_root(arrays, meta, col, col, col, just))
        host = srh.post_epoch_state_root_np(
            arrays_np, meta, col_np, col_np, col_np, just
        )
        assert np.array_equal(dev, host), label


def test_pairing_prepared_inputs_live_in_the_declared_canonical_domain():
    """The pairing declares its prepared inputs canonical (< p) — the
    precondition _fat_p's lend cover is sized from. Check the REAL
    host-side preparation against the declared caps, limb by limb, so
    the declaration can never drift from what runtime actually feeds."""
    from eth_consensus_specs_tpu.crypto.curve import g1_generator, g2_generator
    from eth_consensus_specs_tpu.ops import pairing_device as dev

    coeff_dom, px_dom, py_dom, _mask = _variant("pairing").domains
    p1, q1 = g1_generator().mul(7), g2_generator().mul(11)
    row = dev.prepare_g2(q1)
    assert np.all(row.astype(object) <= _obj(coeff_dom.hi)), coeff_dom.name
    px, py = dev.g1_affine_limbs(p1)
    assert np.all(px.astype(object) <= _obj(px_dom.hi)), px_dom.name
    assert np.all(py.astype(object) <= _obj(py_dom.hi)), py_dom.name


# -------------------------------------------------- limb-arithmetic families
# device double-and-add / FFT executions — nightly lane like their suites


@pytest.mark.slow
def test_fr_fft_montgomery_corners_vs_host_fft():
    from eth_consensus_specs_tpu.crypto import das
    from eth_consensus_specs_tpu.crypto.kzg import compute_roots_of_unity
    from eth_consensus_specs_tpu.ops.fr_fft import FR, batch_fft_mont

    v = _variant("fr_fft")
    vals_dom = v.domains[0]
    n = v.args[0].shape[1]
    roots = compute_roots_of_unity(n)
    for label, cv in _corners(vals_dom):
        row = (
            np.zeros(FR.n_limbs, np.uint64)
            if np.ndim(cv) == 0
            else np.asarray(cv, np.uint64)
        )
        if np.ndim(cv) == 0:
            assert int(cv) == 0, "scalar Montgomery corners must be zero"
        vals = np.broadcast_to(row, (1, n, FR.n_limbs))
        out = np.asarray(batch_fft_mont(jnp.asarray(vals), roots))
        a = FR.from_mont_int(row)
        want = das.fft_field([a] * n, roots)
        got = [FR.from_mont_int(out[0, i]) for i in range(n)]
        assert got == want, label


def _limbs_value(limbs, limb_bits=30):
    return sum(int(x) << (limb_bits * i) for i, x in enumerate(limbs))


@pytest.mark.slow
def test_g1_msm_scalar_corners_and_redundant_coordinates_vs_host():
    """Scalar-bit corners (all-zero -> infinity, all-one -> the max
    scalar) and the redundant [p, 2p) coordinate encodings the domain's
    2p-1 corner admits: the kernel must produce the same group element
    the host oracle computes from the canonical values."""
    from eth_consensus_specs_tpu.crypto.curve import g1_generator, g1_infinity
    from eth_consensus_specs_tpu.crypto.fields import P as P_INT
    from eth_consensus_specs_tpu.crypto.msm import msm_g1
    from eth_consensus_specs_tpu.ops import g1_msm as gm
    from eth_consensus_specs_tpu.ops.field_limbs import int_to_limbs

    v = _variant("g1_msm")
    bits_dom, coord_dom = v.domains[0], v.domains[1]
    lanes = v.args[1].shape[0]
    G = g1_generator()
    pts = [G.mul(k + 1) for k in range(lanes)]
    X, Y, Z = gm._points_to_limbs(pts)

    for label, bit in _corners(bits_dom):
        bits = np.full((lanes, gm.SCALAR_BITS), bit, np.uint64)
        out = gm.msm_kernel(
            jnp.asarray(bits), jnp.asarray(X), jnp.asarray(Y), jnp.asarray(Z)
        )
        got = gm._jacobian_to_point(*(np.asarray(o) for o in out))
        k = 0 if int(bit) == 0 else (1 << gm.SCALAR_BITS) - 1
        assert got == msm_g1(pts, [k] * lanes), label

    # redundant encodings: value + p, still limb-wise inside the domain
    def red(arr):
        out = np.stack([int_to_limbs(_limbs_value(r) + P_INT) for r in arr])
        assert np.all(out.astype(object) <= _obj(coord_dom.hi)), (
            "redundant encoding escaped the declared [0, 2p) domain"
        )
        return out

    ones = np.ones((lanes, gm.SCALAR_BITS), np.uint64)
    out = gm.msm_kernel(
        jnp.asarray(ones), jnp.asarray(red(X)), jnp.asarray(red(Y)), jnp.asarray(red(Z))
    )
    got = gm._jacobian_to_point(*(np.asarray(o) for o in out))
    kmax = (1 << gm.SCALAR_BITS) - 1
    assert got == msm_g1(pts, [kmax] * lanes)

    # the all-zero coordinate corner: Z = 0 lanes ARE the infinity encoding
    zero = np.zeros_like(X)
    out = gm.msm_kernel(jnp.asarray(ones), jnp.asarray(zero), jnp.asarray(zero), jnp.asarray(zero))
    assert gm._jacobian_to_point(*(np.asarray(o) for o in out)) == g1_infinity()


@pytest.mark.slow
def test_bls_msm_per_item_sums_at_corners_vs_host():
    from eth_consensus_specs_tpu.crypto.curve import g1_generator, g1_infinity
    from eth_consensus_specs_tpu.crypto.msm import msm_g1
    from eth_consensus_specs_tpu.ops import g1_msm as gm

    v = _variant("bls_msm")
    items, lanes = v.args[0].shape[:2]
    assert items >= 2
    G = g1_generator()
    pts = [G.mul(j + 1) for j in range(lanes)]
    X = np.zeros((items, lanes, 13), np.uint64)
    Y = np.zeros_like(X)
    Z = np.zeros_like(X)
    X[0], Y[0], Z[0] = gm._points_to_limbs(pts)
    # item 1..: all-zero lanes — the declared zero corner, i.e. infinity
    outX, outY, outZ = (
        np.asarray(o)
        for o in gm.sum_many_kernel(jnp.asarray(X), jnp.asarray(Y), jnp.asarray(Z))
    )
    assert gm._jacobian_to_point(outX[0], outY[0], outZ[0]) == msm_g1(
        pts, [1] * lanes
    )
    for i in range(1, items):
        assert gm._jacobian_to_point(outX[i], outY[i], outZ[i]) == g1_infinity()


def test_g2_aggregate_domains_declare_redundant_corners():
    """The G2 aggregation family declares the REDUNDANT [0, 2p) range
    (its scan carry crosses the boundary < 2p), so all three Jacobian
    coordinate domains must carry the zero / p-1 / 2p-1 corners."""
    for dom in _variant("g2_aggregate").domains:
        labels = {lab for lab, _ in _corners(dom)}
        assert {"zero", "p-1", "2p-1"} <= labels, dom.name


@pytest.mark.slow
def test_g2_aggregate_corners_vs_host():
    """Kernel execution at the declared corners: the all-zero corner is
    the infinity encoding (Z = 0 -> every sum infinity), and redundant
    [p, 2p) coordinate encodings — admitted by the 2p-1 corner — must
    produce the same group elements as the canonical host fold."""
    from eth_consensus_specs_tpu.crypto.curve import g2_generator, g2_infinity
    from eth_consensus_specs_tpu.crypto.fields import P as P_INT
    from eth_consensus_specs_tpu.crypto.signature import _sum_g2
    from eth_consensus_specs_tpu.ops import g2_aggregate as ga
    from eth_consensus_specs_tpu.ops import lazy_limbs as lz

    coord_dom = _variant("g2_aggregate").domains[0]
    items, lanes = 2, 4

    # zero corner: all-zero limbs everywhere == every lane at infinity
    zero = np.zeros((items, lanes, 2, lz.N_LIMBS), np.uint64)
    rX, rY, rZ = (
        np.asarray(o)
        for o in ga.g2_sum_many_kernel(*(jnp.asarray(zero),) * 3)
    )
    for i in range(items):
        assert ga._jacobian_to_point(rX[i], rY[i], rZ[i]) == g2_infinity()

    # redundant encodings: every Fq limb row re-encoded as value + p,
    # still limb-wise inside the declared [0, 2p) domain
    pts = [[g2_generator().mul(k + 1) for k in range(lanes)] for _ in range(items)]
    X, Y, Z = ga._points_to_lanes(pts, items, lanes)

    def red(arr):
        out = np.empty_like(arr)
        for idx in np.ndindex(arr.shape[:-1]):
            row = arr[idx]
            if not row.any():
                out[idx] = row  # infinity lanes stay the zero encoding
                continue
            val = lz.limbs_to_int(row) + P_INT
            out[idx] = lz.int_to_limbs(val)
            assert np.all(out[idx].astype(object) <= _obj(coord_dom.hi)), (
                "redundant encoding escaped the declared [0, 2p) domain"
            )
        return out

    rX, rY, rZ = (
        np.asarray(o)
        for o in ga.g2_sum_many_kernel(
            jnp.asarray(red(X)), jnp.asarray(red(Y)), jnp.asarray(red(Z))
        )
    )
    for i in range(items):
        assert ga._jacobian_to_point(rX[i], rY[i], rZ[i]) == _sum_g2(pts[i])


@pytest.mark.slow
def test_pairing_active_mask_corners_vs_host_miller():
    """Both corners of the declared active-mask domain in one chunk:
    active lanes fold their host Miller values, inactive lanes (the
    all-zero-limb rows _fill_chunks leaves behind) fold as one — and an
    all-inactive chunk is EXACTLY Fq12.one()."""
    from eth_consensus_specs_tpu.crypto import pairing as host_pairing
    from eth_consensus_specs_tpu.crypto.curve import g1_generator, g2_generator
    from eth_consensus_specs_tpu.ops import fq12_tower as tw
    from eth_consensus_specs_tpu.ops import pairing_device as dev

    mask_dom = _variant("pairing").domains[3]
    assert {int(c) for _, c in _corners(mask_dom)} == {0, 1}

    pairs = [
        (g1_generator().mul(7), g2_generator().mul(11)),
        (g1_generator().mul(5), g2_generator().mul(3)),
    ]
    dev._prepare_all(pairs)
    coeffs, px, py, active = dev._fill_chunks(pairs, 1)
    assert active[0].tolist() == [True, True] + [False] * (dev._CHUNK - 2)
    f = dev._miller_chunk_fold(
        jnp.asarray(coeffs[0]),
        jnp.asarray(px[0]),
        jnp.asarray(py[0]),
        jnp.asarray(active[0]),
    )
    want = host_pairing.miller_loop(
        pairs[0][0], host_pairing.untwist(pairs[0][1])
    ) * host_pairing.miller_loop(pairs[1][0], host_pairing.untwist(pairs[1][1]))
    assert tw.limbs_to_fq12(np.asarray(f)) == want

    coeffs, px, py, active = dev._fill_chunks([], 1)
    f = dev._miller_chunk_fold(
        jnp.asarray(coeffs[0]),
        jnp.asarray(px[0]),
        jnp.asarray(py[0]),
        jnp.asarray(active[0]),
    )
    one = type(want).one()
    assert tw.limbs_to_fq12(np.asarray(f)) == one


def test_kzg_msm_domains_declare_the_corners():
    """The 12th family (the KZG RLC fold's batched multi-MSM) declares
    the same contract as g1_msm: scalar bits in {0, 1} and redundant
    [0, 2p) Jacobian coordinates with the zero / p-1 / 2p-1 corners —
    the zero coordinate corner IS the infinity-lane encoding the blob
    batch pads with."""
    v = _variant("kzg_msm")
    assert {int(c) for _, c in _corners(v.domains[0])} == {0, 1}
    for dom in v.domains[1:]:
        labels = {lab for lab, _ in _corners(dom)}
        assert {"zero", "p-1", "2p-1"} <= labels, dom.name


@pytest.mark.slow
def test_kzg_msm_per_item_msms_at_corners_vs_host():
    """msm_many_kernel at the declared corners, against the host
    Pippenger oracle: all-zero scalar bits -> every item infinity,
    all-one bits -> the max 256-bit scalar per lane, and an item of
    all-zero coordinate lanes (the declared zero corner = the infinity
    padding the blob flush uses) -> infinity regardless of bits."""
    from eth_consensus_specs_tpu.crypto.curve import g1_generator, g1_infinity
    from eth_consensus_specs_tpu.crypto.msm import msm_g1
    from eth_consensus_specs_tpu.ops import g1_msm as gm

    v = _variant("kzg_msm")
    items, lanes = v.args[0].shape[:2]
    assert items >= 2
    G = g1_generator()
    pts = [G.mul(j + 1) for j in range(lanes)]
    pX, pY, pZ = gm._points_to_limbs(pts)
    X = np.zeros((items, lanes, 13), np.uint64)
    Y = np.zeros_like(X)
    Z = np.zeros_like(X)
    # item 0 carries real points; item 1.. stays the all-zero coordinate
    # corner (infinity lanes)
    X[0], Y[0], Z[0] = pX, pY, pZ
    bits_dom = v.domains[0]
    for label, bit in _corners(bits_dom):
        bits = np.full((items, lanes, gm.SCALAR_BITS), bit, np.uint64)
        oX, oY, oZ = (
            np.asarray(o)
            for o in gm.msm_many_kernel(
                jnp.asarray(bits), jnp.asarray(X), jnp.asarray(Y), jnp.asarray(Z)
            )
        )
        k = 0 if int(bit) == 0 else (1 << gm.SCALAR_BITS) - 1
        assert gm._jacobian_to_point(oX[0], oY[0], oZ[0]) == msm_g1(
            pts, [k] * lanes
        ), label
        for i in range(1, items):
            assert gm._jacobian_to_point(oX[i], oY[i], oZ[i]) == g1_infinity(), label


@pytest.mark.slow
def test_kzg_challenge_evaluation_at_fr_root_of_unity_edges_vs_host_oracle():
    """The kzg_batch evaluation path at the Fr roots-of-unity EDGE
    values (w^0 = 1, w^1, w^(n-1) — the boundary members of the
    evaluation domain) and at the field's own edges (0, r-1) as
    challenges: the device inverse-FFT + Horner value must equal the
    crypto/kzg.py barycentric oracle bit for bit, in-domain special
    case included."""
    from eth_consensus_specs_tpu.crypto import kzg
    from eth_consensus_specs_tpu.ops import kzg_batch

    n = kzg.FIELD_ELEMENTS_PER_BLOB
    roots = kzg.compute_roots_of_unity(n)
    poly = [(j * 7919 + 3) % kzg.BLS_MODULUS for j in range(n)]
    blob = b"".join(kzg.bls_field_to_bytes(x) for x in poly)
    base = kzg_batch.parse_item((blob, kzg.G1_POINT_AT_INFINITY,
                                 kzg.G1_POINT_AT_INFINITY))
    assert base is not None
    edges = [roots[0], roots[1], roots[n - 1], 0, kzg.BLS_MODULUS - 1]
    parsed = []
    for z in edges:
        row = list(base)
        row[4] = z
        parsed.append(tuple(row))
    got = kzg_batch.challenge_evaluations(parsed)
    want = [kzg.evaluate_polynomial_in_evaluation_form(poly, z) for z in edges]
    assert got == want
