"""Mesh-sharded hot-kernel dispatch: bit-parity chips=1 vs chips=N over
the 8-virtual-device CPU mesh conftest.py forces, mesh-aware serve
buckets, signed warmup keys, and the host_local_slice remainder fix.

Cheap parity tests (sum kernels, sharded merkleization, the bisection
path over host pairing) run in tier-1; the scalar-MSM and device-pairing
sharded compiles are minutes on XLA:CPU and ride the nightly slow lane.
"""

import threading

import jax
import numpy as np
import pytest

from eth_consensus_specs_tpu import obs
from eth_consensus_specs_tpu.crypto.curve import g1_generator
from eth_consensus_specs_tpu.ops.g1_msm import (
    many_sum_shape,
    mesh_lane_pad,
    sum_g1_device,
    sum_g1_many_device,
)
from eth_consensus_specs_tpu.ops.merkle import merkleize_many_device
from eth_consensus_specs_tpu.parallel import make_mesh, mesh_ops, multihost
from eth_consensus_specs_tpu.serve import buckets
from eth_consensus_specs_tpu.utils import bls

N_DEVICES = 8
G = g1_generator()


def _mesh(n=N_DEVICES):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices (conftest forces them on CPU)")
    return make_mesh(n)


def _counter(name: str) -> float:
    return obs.snapshot()["counters"].get(name, 0)


# ------------------------------------------------------------- helpers --


def test_mesh_helpers_and_signature():
    mesh = _mesh()
    assert mesh_ops.shard_count(None) == 1
    assert mesh_ops.shard_count(mesh) == N_DEVICES
    sig = mesh_ops.mesh_signature(mesh)
    assert sig == f"cpu{mesh.shape['dp']}x{mesh.shape['sp']}"
    assert mesh_ops.mesh_signature(None) == ""  # single-device keys unsigned
    assert mesh_ops.pad_to_shards(5, 8) == 8
    assert mesh_ops.pad_to_shards(16, 8) == 16


def test_pad_to_shards_degenerate_pads_one_per_shard():
    """shards > items (per-shard count would be 0): every shard still
    gets at least one (padding) item — a zero-extent shard axis is an
    invalid shard_map operand shape, so the floor is `shards`, never 0."""
    assert mesh_ops.pad_to_shards(0, 8) == 8
    assert mesh_ops.pad_to_shards(1, 8) == 8
    assert mesh_ops.pad_to_shards(3, 8) == 8
    for n in range(0, 20):
        padded = mesh_ops.pad_to_shards(n, 8)
        assert padded % 8 == 0 and padded // 8 >= 1  # non-empty shards
        assert padded >= n


def test_mesh_batch_bucket_degenerate_pads_one_per_shard():
    from eth_consensus_specs_tpu.serve import buckets

    cfg = (1, 2, 4, 8, 16, 32, 64)
    # fewer trees than shards: the PER-SHARD count buckets to 1, the
    # dispatch pads to shards x 1 — never an empty shard
    for n in (1, 2, 3, 7):
        assert buckets.mesh_batch_bucket(n, 8, cfg) == 8
    assert buckets.mesh_batch_bucket(0, 8, cfg) == 8
    # and the mesh-aware live key fn agrees with the dispatch padding
    mesh = _mesh()
    key = buckets.merkle_many_key(3, 10, cfg, mesh=mesh)
    assert key[0] == "merkle_many" and key[1] == N_DEVICES
    assert key[3] == mesh_ops.mesh_signature(mesh)
    per_shard = mesh_ops.pad_to_shards(key[1], N_DEVICES) // N_DEVICES
    assert per_shard >= 1


def test_serve_mesh_env_gates(monkeypatch):
    _mesh()
    monkeypatch.setenv("ETH_SPECS_MESH", "0")
    assert mesh_ops.serve_mesh() is None
    monkeypatch.delenv("ETH_SPECS_MESH", raising=False)
    assert mesh_ops.serve_mesh(1) is None  # one chip = single-device path
    m = mesh_ops.serve_mesh(4)
    assert m is not None and mesh_ops.shard_count(m) == 4
    monkeypatch.setenv("ETH_SPECS_SERVE_CHIPS", "2")
    assert mesh_ops.shard_count(mesh_ops.serve_mesh()) == 2


def test_mesh_batch_bucket_per_shard_padding():
    bkts = (1, 2, 4, 8, 16, 32, 64)
    # pow2 shard counts: identical total padding to the global bucket
    assert buckets.mesh_batch_bucket(5, 8, bkts) == 8
    assert buckets.mesh_batch_bucket(20, 8, bkts) == 32
    assert buckets.mesh_batch_bucket(3, 1, bkts) == buckets.batch_bucket(3, bkts)
    # non-pow2 meshes pad strictly less than the global pow2 would
    assert buckets.mesh_batch_bucket(20, 6, bkts) == 24 < buckets.batch_bucket(20, bkts)


def test_many_sum_shape_and_lane_pad():
    assert many_sum_shape(5, 3) == (8, 4)
    assert many_sum_shape(5, 3, shards=8) == (8, 4)  # pow2 shards == global pow2
    assert many_sum_shape(9, 3, shards=6) == (12, 4)  # per-shard pow2, less padding
    assert mesh_lane_pad(10, 1) == 16
    assert mesh_lane_pad(10, 6) == 12


# ------------------------------------------------- sharded merkleization --


def test_merkleize_many_sharded_parity_non_pow2_batch():
    mesh = _mesh()
    rng = np.random.default_rng(11)
    depth = 6
    # 5 trees (non-pow2) with ragged leaf counts: the sharded dispatch
    # pads the tree axis to the mesh, the single-device one to the same
    # pad_batch — roots must be byte-identical
    trees = [
        rng.integers(0, 256, size=(int(rng.integers(1, 65)), 32)).astype(np.uint8)
        for _ in range(5)
    ]
    before = _counter("mesh.dispatches")
    single = merkleize_many_device(trees, depth, pad_batch=8)
    sharded = merkleize_many_device(trees, depth, pad_batch=8, mesh=mesh)
    assert sharded == single
    assert _counter("mesh.dispatches") == before + 1
    # a pad_batch that does not divide the mesh rounds up instead of
    # truncating a shard
    assert merkleize_many_device(trees, depth, pad_batch=5, mesh=mesh) == single


# --------------------------------------------------------- sharded MSM --


def test_sum_g1_many_sharded_parity_ragged_committees():
    mesh = _mesh()
    lists = [[G.mul(13 * i + j + 1) for j in range(3 + (i % 4))] for i in range(6)]
    per_item = [sum_g1_device(pts) for pts in lists]
    assert sum_g1_many_device(lists) == per_item
    assert sum_g1_many_device(lists, mesh=mesh) == per_item


def test_sum_g1_many_handles_infinity_lanes():
    from eth_consensus_specs_tpu.crypto.curve import g1_infinity

    mesh = _mesh()
    lists = [[g1_infinity(), G.mul(7)], [g1_infinity()], [G.mul(5), G.mul(5)]]
    want = [G.mul(7), g1_infinity(), G.mul(10)]
    assert sum_g1_many_device(lists) == want
    assert sum_g1_many_device(lists, mesh=mesh) == want


@pytest.mark.slow
def test_msm_sharded_scalar_parity():
    # the 256-bit double-and-add lanes + cross-shard Jacobian reduction:
    # one heavy shard_map compile — nightly lane
    from eth_consensus_specs_tpu.crypto.msm import msm_g1
    from eth_consensus_specs_tpu.ops.g1_msm import msm_g1_device

    mesh = _mesh()
    pts = [G.mul(i + 2) for i in range(6)]
    ks = [(1 << 63) + 101 * i for i in range(6)]
    assert msm_g1_device(pts, ks, mesh=mesh) == msm_g1_device(pts, ks) == msm_g1(pts, ks)


# --------------------------------------- verify_many over the mesh (RLC) --


def _bls_items(n, committee=3, invalid=()):
    from eth_consensus_specs_tpu.crypto import signature as sig_mod

    sks = list(range(5, 5 + committee))
    pks = [sig_mod.sk_to_pk(sk) for sk in sks]
    msgs = [bytes([m + 1]) * 32 for m in range(3)]
    items = []
    for i in range(n):
        m = msgs[i % len(msgs)]
        sig = bls.Aggregate([bls.Sign(sk, m) for sk in sks])
        if i in invalid:
            sig = b"\x01" + bytes(sig)[1:]
        items.append((pks, m, bytes(sig)))
    return items


def test_verify_many_mesh_bisection_bit_identical(monkeypatch):
    """The serving batch entry point over the mesh: sharded per-item G1
    terms (device sum kernel under the tpu backend switch), host pairing
    (ETH_SPECS_TPU_NO_DEVICE_PAIRING — the Miller compile rides the slow
    lane), invalid items exercising the bisection — verdicts must be
    bit-identical to the single-device path and to direct singleton
    calls."""
    from eth_consensus_specs_tpu.ops import bls_batch

    mesh = _mesh()
    monkeypatch.setenv("ETH_SPECS_TPU_NO_DEVICE_PAIRING", "1")
    prior_active, prior_backend = bls.bls_active, bls.backend_name()
    bls.bls_active = True
    bls.use_tpu()
    try:
        items = _bls_items(7, invalid={2, 5})
        direct = [bls_batch.batch_verify_aggregates([it]) for it in items]
        assert direct == [i not in {2, 5} for i in range(7)]
        assert bls_batch.verify_many(items) == direct
        before = _counter("mesh.dispatches")
        assert bls_batch.verify_many(items, mesh=mesh) == direct
        assert _counter("mesh.dispatches") > before
    finally:
        bls.bls_active = prior_active
        if prior_backend == "pyspec":
            bls.use_pyspec()


@pytest.mark.slow
def test_verify_many_sharded_pairing_bisection(monkeypatch):
    """Full sharded path: per-shard partial Miller products + psum-style
    Fq12 combine, with an invalid item forcing bisection re-checks
    through the SAME sharded pairing — minutes of XLA:CPU compile,
    nightly lane."""
    from eth_consensus_specs_tpu.ops import bls_batch

    mesh = _mesh(2)
    monkeypatch.setenv("ETH_SPECS_TPU_DEVICE_PAIRING", "1")
    items = _bls_items(17, invalid={7})
    direct = bls_batch.verify_many(items)
    assert direct == [i != 7 for i in range(17)]
    assert bls_batch.verify_many(items, mesh=mesh) == direct


# ------------------------------------------- serve buckets + warmup keys --


def test_mesh_signed_warmup_keys_roundtrip(tmp_path, monkeypatch):
    mesh = _mesh()
    sig = mesh_ops.mesh_signature(mesh)
    monkeypatch.setattr(buckets, "_SEEN_SHAPES", set())
    assert buckets.note_dispatch("merkle_many", 8, 4, sig) is True
    assert buckets.note_dispatch("merkle_many", 8, 4, sig) is False  # dedupes
    assert buckets.note_dispatch("merkle_many", 8, 4) is True  # unsigned differs
    path = str(tmp_path / "warm.jsonl")
    buckets.write_warmup(path)
    keys = buckets.load_warmup(path)
    assert ("merkle_many", 8, 4, sig) in keys and ("merkle_many", 8, 4) in keys


def test_precompile_skips_alien_mesh_signature(tmp_path, monkeypatch):
    _mesh()
    monkeypatch.setattr(buckets, "_SEEN_SHAPES", set())
    # a key signed by a mesh this process is not running must be skipped,
    # not compiled wrong
    warmed = buckets.precompile([("merkle_many", 8, 4, "tpu64x2")])
    assert warmed == 0
    events = [
        e for e in obs.get_registry().events if e.get("kind") == "serve.precompile_skipped"
    ]
    assert events and events[-1]["reason"] == "mesh-signature mismatch"


def test_precompile_replays_current_mesh_signature(monkeypatch):
    mesh = _mesh()
    sig = mesh_ops.mesh_signature(mesh)
    monkeypatch.setattr(buckets, "_SEEN_SHAPES", set())
    before = _counter("serve.compiles")
    assert buckets.precompile([("merkle_many", 8, 4, sig)]) == 1
    assert _counter("serve.compiles") == before + 1
    # the replayed shape is now warm: the real dispatch pays no compile
    assert buckets.note_dispatch("merkle_many", 8, 4, sig) is False


# ------------------------------------------------- service end to end --


def test_mesh_dispatch_worthwhile_crossover():
    # pinned like the device/host crossover: toy flushes stay on the
    # single-device path, bucket-sized ones shard
    assert not buckets.mesh_dispatch_worthwhile(1 << 6, trees=8)  # 512 chunks
    assert buckets.mesh_dispatch_worthwhile(1 << 10, trees=8)
    assert buckets.MESH_SUBTREE_THRESHOLD == 2048


def test_service_mesh_dispatch_end_to_end(monkeypatch):
    from eth_consensus_specs_tpu import serve
    from eth_consensus_specs_tpu.ops.merkle import merkleize_subtree_device
    from eth_consensus_specs_tpu.serve.config import ServeConfig

    _mesh()
    # depth-4 toy trees sit below the mesh crossover; force the sharded
    # path so the test exercises it without bucket-sized compiles
    monkeypatch.setattr(buckets, "MESH_SUBTREE_THRESHOLD", 0)
    rng = np.random.default_rng(3)
    depth = 4
    # leaf counts in (2**(d-1), 2**d] so every request lands at depth 4
    # (submit_hash_tree_root derives depth per tree) and one flush
    # co-batches all eight
    trees = [
        rng.integers(0, 256, size=(int(rng.integers(9, 17)), 32)).astype(np.uint8)
        for _ in range(8)
    ]
    direct = [merkleize_subtree_device(t, depth) for t in trees]
    cfg = ServeConfig(
        max_batch=8, max_wait_ms=100.0, buckets=(1, 2, 4, 8), mesh_chips=N_DEVICES
    )
    before = _counter("mesh.dispatches")
    with serve.VerifyService(cfg, name="mesh-test") as svc:
        futs = [svc.submit_hash_tree_root(t) for t in trees]
        got = [f.result(timeout=60) for f in futs]
    assert got == direct
    assert _counter("mesh.dispatches") > before
    sig = mesh_ops.mesh_signature(mesh_ops.serve_mesh(N_DEVICES))
    signed = [k for k in buckets.seen_shapes() if k[0] == "merkle_many" and sig in k]
    assert signed, f"no mesh-signed merkle_many compile key in {buckets.seen_shapes()}"


def test_service_mesh_chips_one_stays_single_device():
    from eth_consensus_specs_tpu import serve
    from eth_consensus_specs_tpu.ops.merkle import merkleize_subtree_device
    from eth_consensus_specs_tpu.serve.config import ServeConfig

    _mesh()
    rng = np.random.default_rng(4)
    depth = 4
    trees = [rng.integers(0, 256, size=(16, 32)).astype(np.uint8) for _ in range(4)]
    direct = [merkleize_subtree_device(t, depth) for t in trees]
    before = _counter("mesh.dispatches")
    cfg = ServeConfig(max_batch=4, max_wait_ms=50.0, buckets=(1, 2, 4), mesh_chips=1)
    with serve.VerifyService(cfg, name="mesh1-test") as svc:
        futs = [svc.submit_hash_tree_root(t) for t in trees]
        assert [f.result(timeout=60) for f in futs] == direct
    assert _counter("mesh.dispatches") == before  # single-device path


# --------------------------------------------------- host_local_slice --


def test_host_local_slice_remainder_raises_typed_and_counts():
    mesh = _mesh()
    before = _counter("multihost.slice_remainder")
    with pytest.raises(multihost.ShardRemainderError) as ei:
        multihost.host_local_slice(mesh, 1027)
    assert ei.value.remainder == 1027 % 8
    assert _counter("multihost.slice_remainder") == before + 1027 % 8


def test_host_local_slice_pad_covers_every_row():
    mesh = _mesh()
    lo, hi = multihost.host_local_slice(mesh, 1027, pad=True)
    padded = multihost.padded_global(1027, 8)
    assert padded == 1032
    # single process owns the whole padded domain — nothing truncated
    assert (lo, hi) == (0, padded)
    # divisible splits are untouched by the fix
    assert multihost.host_local_slice(mesh, 1024) == (0, 1024)


def test_perf_track_ingests_mesh_scaling(tmp_path):
    """perf_track treats the per-chip scaling factors as platform-aware
    secondary metrics: a cpu virtual-mesh round never compares against
    accelerator history, and a scaling regression is an advisory."""
    import importlib.util
    import json
    import os

    spec = importlib.util.spec_from_file_location(
        "perf_track", os.path.join(os.path.dirname(__file__), "..", "scripts", "perf_track.py")
    )
    pt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pt)
    for rnd, factor in ((1, 1.8), (2, 0.5)):
        (tmp_path / f"BENCH_r{rnd:02d}.json").write_text(json.dumps({
            "rc": 0,
            "parsed": {
                "metric": "hashes_per_sec", "value": 100.0, "platform": "cpu",
                "mesh": {"chips": 8, "chip_scaling": factor, "merkle_scaling": factor},
            },
        }))
    entries = pt.load_rounds(str(tmp_path))
    assert entries[0]["metrics"]["mesh_chip_scaling"] == 1.8
    assert entries[0]["metrics"]["mesh_merkle_scaling"] == 1.8
    assert "mesh_chips" not in entries[0]["metrics"]  # config, not a metric
    regressions, advisories = pt.compare(entries, threshold=0.30, strict=False)
    assert not regressions  # secondaries never gate by default
    assert any(a["metric"] == "mesh_chip_scaling" for a in advisories)


def test_sharded_dispatch_thread_safety():
    """Two threads racing the same sharded entry must both get correct
    roots (the per-(mesh, depth) fn cache is shared)."""
    mesh = _mesh()
    rng = np.random.default_rng(9)
    depth = 5
    trees = [rng.integers(0, 256, size=(32, 32)).astype(np.uint8) for _ in range(8)]
    want = merkleize_many_device(trees, depth, pad_batch=8)
    results = [None, None]

    def run(i):
        results[i] = merkleize_many_device(trees, depth, pad_batch=8, mesh=mesh)

    ts = [threading.Thread(target=run, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert results[0] == want and results[1] == want
