"""Genesis initialization and validity
(reference: eth2spec/test/phase0/genesis/test_{initialization,validity}.py)."""

from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.context import spec_test, with_phases
from eth_consensus_specs_tpu.test_infra.deposits import (
    build_deposit,
)
from eth_consensus_specs_tpu.test_infra.genesis import bls_withdrawal_credentials
from eth_consensus_specs_tpu.test_infra.keys import privkeys, pubkeys


def _genesis_deposits(spec, count: int):
    deposit_data_list = []
    deposits = []
    for i in range(count):
        deposit, root, deposit_data_list = build_deposit(
            spec,
            deposit_data_list,
            pubkeys[i],
            privkeys[i],
            spec.MAX_EFFECTIVE_BALANCE,
            bls_withdrawal_credentials(spec, i),
            signed=True,
        )
        deposits.append(deposit)
    return deposits, root


@with_phases(["phase0"])
@spec_test
def test_initialize_beacon_state_from_eth1(spec):
    count = spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
    deposits, _root = _genesis_deposits(spec, count)
    eth1_block_hash = b"\x12" * 32
    eth1_timestamp = spec.config.MIN_GENESIS_TIME
    state = spec.initialize_beacon_state_from_eth1(
        eth1_block_hash, eth1_timestamp, deposits
    )
    assert int(state.genesis_time) == eth1_timestamp + spec.config.GENESIS_DELAY
    assert len(state.validators) == count
    assert int(state.eth1_deposit_index) == count
    assert bytes(state.eth1_data.block_hash) == eth1_block_hash
    assert int(state.eth1_data.deposit_count) == count
    for v in state.validators:
        assert int(v.effective_balance) == spec.MAX_EFFECTIVE_BALANCE
        assert int(v.activation_epoch) == spec.GENESIS_EPOCH
    # genesis_validators_root commits to the registry
    assert bytes(state.genesis_validators_root) == bytes(hash_tree_root(state.validators))


@with_phases(["phase0"])
@spec_test
def test_initialize_ignores_invalid_deposit_signature(spec):
    """A deposit with a bad signature contributes no validator but still
    advances the deposit index (spec apply_deposit semantics)."""
    count = 4
    from eth_consensus_specs_tpu.utils import bls

    prior = bls.bls_active
    bls.bls_active = True  # real signatures both when building and checking
    try:
        deposit_data_list = []
        deposits = []
        for i in range(count):
            deposit, _root, deposit_data_list = build_deposit(
                spec,
                deposit_data_list,
                pubkeys[i],
                privkeys[i],
                spec.MAX_EFFECTIVE_BALANCE,
                bls_withdrawal_credentials(spec, i),
                signed=(i != 2),  # deposit 2 unsigned -> invalid proof-of-possession
            )
            deposits.append(deposit)
        state = spec.initialize_beacon_state_from_eth1(b"\x12" * 32, 0, deposits)
    finally:
        bls.bls_active = prior
    assert len(state.validators) == count - 1
    assert int(state.eth1_deposit_index) == count


@with_phases(["phase0"])
@spec_test
def test_genesis_validity_thresholds(spec):
    count = spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT
    deposits, _ = _genesis_deposits(spec, count)
    state = spec.initialize_beacon_state_from_eth1(
        b"\x12" * 32, spec.config.MIN_GENESIS_TIME, deposits
    )
    assert spec.is_valid_genesis_state(state)

    # too early
    early = state.copy()
    early.genesis_time = spec.config.MIN_GENESIS_TIME - 1
    assert not spec.is_valid_genesis_state(early)

    # not enough active validators
    deposits_few, _ = _genesis_deposits(spec, count - 1)
    state_few = spec.initialize_beacon_state_from_eth1(
        b"\x12" * 32, spec.config.MIN_GENESIS_TIME, deposits_few
    )
    assert not spec.is_valid_genesis_state(state_few)
