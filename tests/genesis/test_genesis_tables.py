"""Genesis initialization/validity tables (reference analogue:
test/phase0/genesis/test_initialization.py and test_validity.py; spec:
specs/phase0/beacon-chain.md:1276-1337)."""

from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.deposits import build_deposit
from eth_consensus_specs_tpu.test_infra.genesis import bls_withdrawal_credentials
from eth_consensus_specs_tpu.test_infra.keys import privkeys, pubkeys

PHASE0 = ["phase0"]


def _genesis_inputs(spec, count):
    deposit_data_list = []
    deposits = []
    for i in range(count):
        deposit, _root, deposit_data_list = build_deposit(
            spec,
            deposit_data_list,
            pubkeys[i],
            privkeys[i],
            int(spec.MAX_EFFECTIVE_BALANCE),
            bls_withdrawal_credentials(spec, i),
            signed=True,
        )
        deposits.append(deposit)
    eth1_block_hash = b"\x12" * 32
    eth1_timestamp = int(spec.config.MIN_GENESIS_TIME)
    return eth1_block_hash, eth1_timestamp, deposits


@with_phases(PHASE0)
@spec_state_test
def test_initialize_sets_genesis_time_with_delay(spec, state):
    h, t, deposits = _genesis_inputs(spec, 4)
    out = spec.initialize_beacon_state_from_eth1(h, t, deposits)
    assert int(out.genesis_time) == t + int(spec.config.GENESIS_DELAY)


@with_phases(PHASE0)
@spec_state_test
def test_initialize_onboards_all_deposits(spec, state):
    h, t, deposits = _genesis_inputs(spec, 6)
    out = spec.initialize_beacon_state_from_eth1(h, t, deposits)
    assert len(out.validators) == 6
    assert int(out.eth1_deposit_index) == 6


@with_phases(PHASE0)
@spec_state_test
def test_initialize_activates_full_balance_validators(spec, state):
    h, t, deposits = _genesis_inputs(spec, 4)
    out = spec.initialize_beacon_state_from_eth1(h, t, deposits)
    for v in out.validators:
        assert int(v.activation_epoch) == int(spec.GENESIS_EPOCH)
        assert int(v.effective_balance) == int(spec.MAX_EFFECTIVE_BALANCE)


@with_phases(PHASE0)
@spec_state_test
def test_initialize_eth1_data_recorded(spec, state):
    h, t, deposits = _genesis_inputs(spec, 4)
    out = spec.initialize_beacon_state_from_eth1(h, t, deposits)
    assert bytes(out.eth1_data.block_hash) == h
    assert int(out.eth1_data.deposit_count) == 4


@with_phases(PHASE0)
@spec_state_test
def test_validity_needs_min_validator_count(spec, state):
    h, t, deposits = _genesis_inputs(
        spec, int(spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT)
    )
    out = spec.initialize_beacon_state_from_eth1(h, t, deposits)
    assert spec.is_valid_genesis_state(out)


@with_phases(PHASE0)
@spec_state_test
def test_validity_too_few_validators(spec, state):
    need = int(spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT)
    h, t, deposits = _genesis_inputs(spec, max(need - 1, 1))
    out = spec.initialize_beacon_state_from_eth1(h, t, deposits)
    assert not spec.is_valid_genesis_state(out)


@with_phases(PHASE0)
@spec_state_test
def test_validity_too_early_time(spec, state):
    need = int(spec.config.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT)
    h, t, deposits = _genesis_inputs(spec, need)
    out = spec.initialize_beacon_state_from_eth1(h, t, deposits)
    out.genesis_time = int(spec.config.MIN_GENESIS_TIME) - 1
    assert not spec.is_valid_genesis_state(out)
