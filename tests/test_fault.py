"""Fault subsystem: spec grammar, deterministic injection windows,
retry backoff, and the device->host degradation guard's bit-exactness
(ops/state_root.py and ops/block_epoch.py falling back to their host
oracles under injected device failure)."""

import numpy as np
import pytest

from eth_consensus_specs_tpu import fault, obs
from eth_consensus_specs_tpu.fault import FaultInjected


# ------------------------------------------------------------- grammar --


def test_parse_grammar_defaults_and_keys():
    rules = fault.parse(
        "gen.case:raise; state_root.*:stall:nth=3:times=2:delay=0.5;"
        "gen.dump_bytes:corrupt:times=inf"
    )
    assert [r.mode for r in rules] == ["raise", "stall", "corrupt"]
    assert (rules[0].nth, rules[0].times) == (1, 1)
    assert (rules[1].nth, rules[1].times, rules[1].delay) == (3, 2, 0.5)
    assert rules[2].times == float("inf")
    assert rules[1].matches("state_root.device")
    assert not rules[1].matches("block_epoch.device")


@pytest.mark.parametrize(
    "bad",
    ["nosite", "site:explode", "site:raise:nth", "site:raise:widget=1", ":raise"],
)
def test_parse_rejects_malformed(bad):
    with pytest.raises(ValueError):
        fault.parse(bad)


def test_check_fires_in_window_only():
    with fault.injected("probe.site:raise:nth=2:times=2"):
        fault.check("probe.site")  # hit 1: before window
        with pytest.raises(FaultInjected):
            fault.check("probe.site")  # hit 2
        with pytest.raises(FaultInjected):
            fault.check("probe.site")  # hit 3
        fault.check("probe.site")  # hit 4: window exhausted
        fault.check("other.site")  # never matches
    fault.check("probe.site")  # rules restored: no-op


def test_latch_fires_once_across_rules(tmp_path):
    latch = str(tmp_path / "latch")
    with fault.injected(f"a.site:raise:times=inf:latch={latch}"):
        with pytest.raises(FaultInjected):
            fault.check("a.site")
        fault.check("a.site")  # latch already taken: silent


def test_corrupt_flips_one_byte_then_restores():
    data = bytes(range(32))
    with fault.injected("bytes.site:corrupt"):
        mutated = fault.corrupt("bytes.site", data)
        assert mutated != data and len(mutated) == len(data)
        assert sum(a != b for a, b in zip(mutated, data)) == 1
        assert fault.corrupt("bytes.site", data) == data  # window exhausted
    assert fault.corrupt("bytes.site", data) == data


# --------------------------------------------------------------- retry --


def test_retrying_recovers_and_counts():
    calls = {"n": 0}
    slept = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    before = obs.snapshot()["counters"].get("fault.retries", 0)
    out = fault.retrying(flaky, name="t", attempts=4, retry_on=OSError, sleep=slept.append)
    assert out == "ok" and calls["n"] == 3
    assert len(slept) == 2
    assert obs.snapshot()["counters"]["fault.retries"] - before == 2


def test_retrying_exhausts_and_respects_filter():
    def always():
        raise OSError("nope")

    with pytest.raises(OSError):
        fault.retrying(always, attempts=3, retry_on=OSError, sleep=lambda _s: None)

    calls = {"n": 0}

    def wrong_kind():
        calls["n"] += 1
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        fault.retrying(wrong_kind, attempts=5, retry_on=OSError, sleep=lambda _s: None)
    assert calls["n"] == 1  # non-matching error: no retry


def test_backoff_deterministic_capped_jittered():
    a = fault.backoff_delays("site-a", 6, base_delay=0.1, max_delay=0.8, jitter=0.5)
    assert a == fault.backoff_delays("site-a", 6, base_delay=0.1, max_delay=0.8, jitter=0.5)
    b = fault.backoff_delays("site-b", 6, base_delay=0.1, max_delay=0.8, jitter=0.5)
    assert a != b  # name de-syncs concurrent retriers
    for i, d in enumerate(a):
        lo = min(0.1 * 2**i, 0.8)
        assert lo <= d <= lo * 1.5


# ------------------------------------------------------------- degrade --


def test_degrade_falls_back_on_device_failure_only():
    before = obs.snapshot()["counters"].get("fault.degraded", 0)

    def dead_device():
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory while compiling")

    assert fault.degrade("probe.degrade", dead_device, lambda: "host") == "host"
    assert obs.snapshot()["counters"]["fault.degraded"] - before == 1

    def logic_bug():
        raise KeyError("not a device failure")

    with pytest.raises(KeyError):
        fault.degrade("probe.degrade", logic_bug, lambda: "host")


def test_degrade_retry_recovers_transient_device_failure():
    # one-shot injection: the retry leg succeeds, NO degradation happens
    with fault.injected("probe.transient:raise:nth=1:times=1"):
        before = obs.snapshot()["counters"].get("fault.degraded", 0)

        def device():
            fault.check("probe.transient")
            return "device"

        assert fault.degrade("probe.transient", device, lambda: "host") == "device"
        assert obs.snapshot()["counters"].get("fault.degraded", 0) == before


def test_is_device_failure_classification():
    assert fault.is_device_failure(FaultInjected("x"))
    assert fault.is_device_failure(MemoryError())
    assert fault.is_device_failure(RuntimeError("INTERNAL: failed to allocate 1GB"))
    assert not fault.is_device_failure(ValueError("shape mismatch"))
    assert not fault.is_device_failure(AssertionError("spec violated"))


# ------------------------------------------------ multihost guards --


def test_multihost_init_failure_leaves_breadcrumb(monkeypatch):
    import jax

    from eth_consensus_specs_tpu.parallel import multihost

    def boom():
        raise RuntimeError("coordinator unreachable")

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(jax.distributed, "initialize", boom)
    before = obs.snapshot()["counters"].get("multihost.init_failures", 0)
    assert multihost._initialize_distributed(None, None, None) is False
    assert obs.snapshot()["counters"]["multihost.init_failures"] - before == 1
    events = [e for e in obs.get_registry().events if e.get("kind") == "multihost.init_failed"]
    assert events and "coordinator unreachable" in events[-1]["error"]


def test_host_local_slice_empty_process_owns_nothing(monkeypatch):
    import jax

    from eth_consensus_specs_tpu.parallel import make_mesh, multihost

    mesh = make_mesh()
    # a process owning no devices of the mesh gets an empty block, not a
    # min()-over-empty-set ValueError
    monkeypatch.setattr(jax, "process_index", lambda: 10**9)
    assert multihost.host_local_slice(mesh, 1024) == (0, 0)


# ----------------------------------------- kernel degradation parity --


def _mk_just(rng):
    import jax.numpy as jnp

    from eth_consensus_specs_tpu.ops.state_columns import JustificationState

    def root():
        return jnp.asarray(rng.integers(0, 256, 32, dtype=np.int64).astype(np.uint8))

    return JustificationState(
        current_epoch=jnp.uint64(5),
        justification_bits=jnp.asarray([True, False, True, False]),
        prev_justified_epoch=jnp.uint64(3),
        prev_justified_root=root(),
        cur_justified_epoch=jnp.uint64(4),
        cur_justified_root=root(),
        finalized_epoch=jnp.uint64(2),
        finalized_root=root(),
        block_root_prev=root(),
        block_root_cur=root(),
        slashings_sum=jnp.uint64(0),
    )


@pytest.mark.slow  # the eager device tree at n=32 is ~1 min on CPU (same
# lane as test_state_root_device.py); block_epoch parity below covers the
# degrade machinery in tier-1
def test_state_root_degrades_bit_exact():
    import jax.numpy as jnp

    from eth_consensus_specs_tpu.forks import get_spec
    from eth_consensus_specs_tpu.ops.state_root import post_epoch_state_root, synthetic_static

    spec = get_spec("altair", "minimal")
    n = 32
    arrays, meta = synthetic_static(spec, n, seed=3)
    rng = np.random.default_rng(0)
    bal = jnp.asarray(rng.integers(1, 2**40, n, dtype=np.int64).astype(np.uint64))
    eff = jnp.asarray(rng.integers(1, 32, n, dtype=np.int64).astype(np.uint64) * 10**9)
    scores = jnp.asarray(rng.integers(0, 100, n, dtype=np.int64).astype(np.uint64))
    just = _mk_just(np.random.default_rng(1))
    clean = np.asarray(post_epoch_state_root(arrays, meta, bal, eff, scores, just))
    before = obs.snapshot()["counters"].get("fault.degraded.state_root.device", 0)
    with fault.injected("state_root.device:raise:times=inf"):
        degraded = np.asarray(post_epoch_state_root(arrays, meta, bal, eff, scores, just))
    assert (clean == degraded).all()
    after = obs.snapshot()["counters"]["fault.degraded.state_root.device"]
    assert after - before == 1


@pytest.mark.slow  # make_root_ctx's eager device trees are ~1 min on CPU
def test_block_epoch_degraded_slot_roots_bit_exact():
    """The degraded path's per-slot root chain (block_epoch_host.
    slot_root_fn_from_ctx) must xor-chain to the device kernel's acc."""
    import jax.numpy as jnp

    from eth_consensus_specs_tpu.forks import get_spec
    from eth_consensus_specs_tpu.ops import block_epoch as be
    from eth_consensus_specs_tpu.ops.state_root import synthetic_static

    spec = get_spec("capella", "minimal")
    n = 64
    cols, st0, static = be.synthetic_block_columns(spec, n, seed=1, atts_per_slot=4)
    arrays, meta = synthetic_static(spec, n, seed=2)
    rng = np.random.default_rng(3)
    scores = jnp.asarray(rng.integers(0, 100, n, dtype=np.int64).astype(np.uint64))
    just = _mk_just(np.random.default_rng(4))
    params = be.BlockEpochParams.from_spec(spec)
    ctx = be.make_root_ctx(spec, arrays, meta, static, scores, just)
    _st_c, acc_c = be.block_epoch_chain(params, n, st0, cols, static, root_ctx=ctx)
    with fault.injected("block_epoch.device:raise:times=inf"):
        _st_h, acc_h = be.block_epoch_chain(params, n, st0, cols, static, root_ctx=ctx)
    assert np.asarray(acc_c).any()  # non-trivial root chain
    assert (np.asarray(acc_c) == np.asarray(acc_h)).all()


def test_block_epoch_degrades_bit_exact():
    from eth_consensus_specs_tpu.forks import get_spec
    from eth_consensus_specs_tpu.ops import block_epoch as be

    spec = get_spec("capella", "minimal")
    n = 64
    cols, st0, static = be.synthetic_block_columns(spec, n, seed=0, atts_per_slot=4)
    params = be.BlockEpochParams.from_spec(spec)
    st_c, _acc_c = be.block_epoch_chain(params, n, st0, cols, static)
    before = obs.snapshot()["counters"].get("fault.degraded.block_epoch.device", 0)
    with fault.injected("block_epoch.device:raise:times=inf"):
        st_h, _acc_h = be.block_epoch_chain(params, n, st0, cols, static)
    assert (np.asarray(st_c.balance) == np.asarray(st_h.balance)).all()
    assert (np.asarray(st_c.cur_part) == np.asarray(st_h.cur_part)).all()
    assert (np.asarray(st_c.prev_part) == np.asarray(st_h.prev_part)).all()
    assert int(st_c.next_wd_index) == int(st_h.next_wd_index)
    assert int(st_c.next_wd_validator) == int(st_h.next_wd_validator)
    assert obs.snapshot()["counters"]["fault.degraded.block_epoch.device"] - before == 1
