"""Model-based fork-choice compliance scenarios
(reference: tests/generators/compliance_runners/fork_choice/)."""

import pytest

# fork-choice compliance enumeration — nightly lane (make test-full)
pytestmark = pytest.mark.slow

import random

from eth_consensus_specs_tpu.gen.compliance import (
    MUTATIONS,
    enumerate_block_trees,
    instantiate_scenario,
    mutate_reorder_parent_after_child,
    run_scenario,
)
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases


def test_enumerate_block_trees_counts():
    # n=1: just the root; n=2: one tree; n=3: chain + fork = 2
    assert list(enumerate_block_trees(1)) == [(0,)]
    assert list(enumerate_block_trees(2)) == [(0, 0)]
    assert sorted(enumerate_block_trees(3)) == [(0, 0, 0), (0, 0, 1)]
    # n=4 with branching cap 2: parents[i] < i with count(p) <= 2
    trees = list(enumerate_block_trees(4))
    assert len(trees) == len(set(trees))
    for tree in trees:
        assert all(tree[i] < i for i in range(1, 4))
        # tree[0] is node 0's placeholder, not a child edge
        assert all(tree[1:].count(p) <= 2 for p in range(4))


@with_phases(["phase0", "altair", "electra"])
@spec_state_test
def test_all_four_block_trees_replay(spec, state):
    """Every 4-node tree shape instantiates and replays cleanly with the
    universal invariants holding."""
    rng = random.Random(7)
    for tree in enumerate_block_trees(4):
        steps = instantiate_scenario(spec, state, tree, rng=rng)
        result = run_scenario(spec, state, steps)
        assert result["applied"] == len(tree) - 1
        assert result["rejected"] == 0


@with_phases(["phase0", "electra"])
@spec_state_test
def test_mutated_scenarios_replay(spec, state):
    """Mutations (parent-after-child reordering, duplicated attestations)
    keep the store consistent: the early orphan is rejected, the ordered
    redelivery lands, and the final head invariants hold."""
    rng = random.Random(11)
    for tree in [(0, 0, 1), (0, 0, 0), (0, 0, 1, 2)]:
        base = instantiate_scenario(spec, state, tree, rng=rng)
        for mutate in MUTATIONS:
            steps = mutate(base, rng)
            result = run_scenario(spec, state, steps)
            assert result["applied"] == len(tree) - 1


@with_phases(["phase0"])
@spec_state_test
def test_forked_tree_head_is_leaf(spec, state):
    rng = random.Random(3)
    steps = instantiate_scenario(spec, state, (0, 0, 0), attest=False, rng=rng)
    result = run_scenario(spec, state, steps)
    # two siblings: head must be one of them (max root tiebreak), not genesis
    import eth_consensus_specs_tpu.ssz as ssz

    blocks = [s["block"].message for s in steps if "block" in s]
    leaf_roots = {bytes(ssz.hash_tree_root(b)) for b in blocks}
    assert result["head"] in leaf_roots


# ------------------------------------------------------------- SM links --


def test_enumerate_sm_links_constraints():
    """Every enumerated link set satisfies the reference SM_links.mzn
    constraints (model/SM_links.mzn): source < target, chainable sources,
    strictly increasing targets, no surround votes, no (1, 2) link."""
    from eth_consensus_specs_tpu.gen.compliance import enumerate_sm_links

    seen = set()
    for links in enumerate_sm_links(n_epochs=5, max_links=4):
        assert links not in seen
        seen.add(links)
        targets = [t for _, t in links]
        assert targets == sorted(set(targets)), "targets strictly increase"
        for s, t in links:
            assert s < t
            assert s == 0 or s in targets, "source anchors or chains"
            assert (s, t) != (1, 2)
        for i, (s1, t1) in enumerate(links):
            for j, (s2, t2) in enumerate(links):
                if i != j:
                    assert not (s1 < s2 and t2 < t1), "surround vote"
    assert len(seen) == 15  # all non-empty target subsets of {1,2,3,4}


def test_expected_justification_automaton():
    from eth_consensus_specs_tpu.gen.compliance import (
        enumerate_sm_links,
        expected_justification,
    )

    # fill every epoch 1..4 -> justified 4, finalized 3 by end of 5
    links = [l for l in enumerate_sm_links() if [t for _, t in l] == [1, 2, 3, 4]][0]
    assert expected_justification(links, 5) == (4, 3)
    # a lone early justification never finalizes
    links = [l for l in enumerate_sm_links() if [t for _, t in l] == [2]][0]
    assert expected_justification(links, 5) == (2, 0)


@with_phases(["electra"])
@spec_state_test
def test_sm_links_store_reaches_modeled_checkpoints(spec, state):
    """THE SM-links compliance gate: every single-chain-realizable
    justification pattern, instantiated with real blocks/attestations and
    replayed through the store, must land exactly on the justified and
    finalized epochs the abstract finality automaton predicts
    (reference: compliance_runners/fork_choice/model/SM_links.mzn +
    instantiators)."""
    from eth_consensus_specs_tpu.gen.compliance import (
        enumerate_sm_links,
        expected_justification,
        instantiate_sm_links,
        replay_blocks_into_store,
    )

    for links in enumerate_sm_links(n_epochs=4, max_links=3):
        chain_state = state.copy()
        blocks, last = instantiate_sm_links(spec, chain_state, links)
        exp_j, exp_f = expected_justification(links, last)
        store = replay_blocks_into_store(spec, state, blocks, tick_to_epoch=last + 1)
        assert int(store.justified_checkpoint.epoch) == exp_j, (
            f"links={links}: store justified "
            f"{int(store.justified_checkpoint.epoch)} != modeled {exp_j}"
        )
        assert int(store.finalized_checkpoint.epoch) == exp_f, (
            f"links={links}: store finalized "
            f"{int(store.finalized_checkpoint.epoch)} != modeled {exp_f}"
        )
        # the realized chain itself must agree with the store
        assert int(chain_state.current_justified_checkpoint.epoch) == exp_j
        assert int(chain_state.finalized_checkpoint.epoch) == exp_f


# ----------------------------------------------------------- block cover --


@with_phases(["electra", "fulu"])
@spec_state_test
def test_block_cover_predicates_realized(spec, state):
    """THE block-cover compliance gate: each scenario's store must realize
    exactly the filter_block_tree predicate combination it was built for
    (reference: compliance_runners/fork_choice/model/Block_cover.mzn),
    and get_head must still run clean on the resulting store."""
    from eth_consensus_specs_tpu.gen.compliance import (
        block_cover_scenarios,
        evaluate_block_cover_predicates,
        replay_blocks_into_store,
    )

    combos_seen = set()
    count = 0
    for sc in block_cover_scenarios(spec, state):
        store = replay_blocks_into_store(
            spec, state, sc["blocks"], tick_to_epoch=sc["tick_to_epoch"]
        )
        actual = evaluate_block_cover_predicates(spec, store, sc["target_root"])
        assert actual == sc["expect"], f"{sc['name']}: {actual} != {sc['expect']}"
        combos_seen.add(tuple(sorted(sc["expect"].items())))
        head = spec.get_head_root(store)
        assert head in store.blocks
        count += 1
    assert count == 12
    assert len(combos_seen) == 12, "every satisfiable predicate combo covered once"
