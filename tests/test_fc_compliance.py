"""Model-based fork-choice compliance scenarios
(reference: tests/generators/compliance_runners/fork_choice/)."""

import pytest

# fork-choice compliance enumeration — nightly lane (make test-full)
pytestmark = pytest.mark.slow

import random

from eth_consensus_specs_tpu.gen.compliance import (
    MUTATIONS,
    enumerate_block_trees,
    instantiate_scenario,
    mutate_reorder_parent_after_child,
    run_scenario,
)
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases


def test_enumerate_block_trees_counts():
    # n=1: just the root; n=2: one tree; n=3: chain + fork = 2
    assert list(enumerate_block_trees(1)) == [(0,)]
    assert list(enumerate_block_trees(2)) == [(0, 0)]
    assert sorted(enumerate_block_trees(3)) == [(0, 0, 0), (0, 0, 1)]
    # n=4 with branching cap 2: parents[i] < i with count(p) <= 2
    trees = list(enumerate_block_trees(4))
    assert len(trees) == len(set(trees))
    for tree in trees:
        assert all(tree[i] < i for i in range(1, 4))
        # tree[0] is node 0's placeholder, not a child edge
        assert all(tree[1:].count(p) <= 2 for p in range(4))


@with_phases(["phase0", "altair", "electra"])
@spec_state_test
def test_all_four_block_trees_replay(spec, state):
    """Every 4-node tree shape instantiates and replays cleanly with the
    universal invariants holding."""
    rng = random.Random(7)
    for tree in enumerate_block_trees(4):
        steps = instantiate_scenario(spec, state, tree, rng=rng)
        result = run_scenario(spec, state, steps)
        assert result["applied"] == len(tree) - 1
        assert result["rejected"] == 0


@with_phases(["phase0", "electra"])
@spec_state_test
def test_mutated_scenarios_replay(spec, state):
    """Mutations (parent-after-child reordering, duplicated attestations)
    keep the store consistent: the early orphan is rejected, the ordered
    redelivery lands, and the final head invariants hold."""
    rng = random.Random(11)
    for tree in [(0, 0, 1), (0, 0, 0), (0, 0, 1, 2)]:
        base = instantiate_scenario(spec, state, tree, rng=rng)
        for mutate in MUTATIONS:
            steps = mutate(base, rng)
            result = run_scenario(spec, state, steps)
            assert result["applied"] == len(tree) - 1


@with_phases(["phase0"])
@spec_state_test
def test_forked_tree_head_is_leaf(spec, state):
    rng = random.Random(3)
    steps = instantiate_scenario(spec, state, (0, 0, 0), attest=False, rng=rng)
    result = run_scenario(spec, state, steps)
    # two siblings: head must be one of them (max root tiebreak), not genesis
    import eth_consensus_specs_tpu.ssz as ssz

    blocks = [s["block"].message for s in steps if "block" in s]
    leaf_roots = {bytes(ssz.hash_tree_root(b)) for b in blocks}
    assert result["head"] in leaf_roots
