"""jaxlint — trace-level rules, kernel registry, baseline, key injectivity.

Rule mechanics run on tiny synthetic kernels (hermetic specs, no
registry); the registry tests trace only the CHEAP families in the
tier-1 lane (sha256/merkle/merkle_many/shuffle/fr_fft — sub-second
jaxprs) and leave the full 9-family sweep, whose MSM/pairing traces
cost ~10 s each, to the @slow lane and CI's static-analysis job. The
deliberate key-collision test is the acceptance criterion for the
recompile-surface rule: a key function that drops a discriminating
dimension MUST fire."""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eth_consensus_specs_tpu.analysis import jaxlint, kernels
from eth_consensus_specs_tpu.analysis.kernels import KernelSpec, Variant

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spec(name="t", dtypes=("float32", "int32", "bool"), donate=(),
          waiver="test kernel", variants=None, key_grid=None, suppress=()):
    return KernelSpec(
        name=name,
        help="synthetic",
        dtypes=frozenset(dtypes),
        donate=tuple(donate),
        donation_waiver=waiver,
        suppress=tuple(suppress),
        build_variants=(lambda mesh: variants) if variants is not None else None,
        key_grid=key_grid,
    )


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def _run(spec, mesh=None, rules=None):
    findings, _ = jaxlint.analyze(mesh=mesh, rules=rules, registry=(spec,))
    return findings


# ------------------------------------------------------------ transfer-free


def test_transfer_free_flags_explicit_device_put_and_callback():
    dev = jax.devices()[0]

    def moves(x):
        return jax.device_put(x, dev) + 1

    def calls_back(x):
        return jax.pure_callback(
            lambda v: np.asarray(v), jax.ShapeDtypeStruct(x.shape, x.dtype), x
        )

    spec = _spec(variants=[
        Variant("single", jax.jit(moves), (_sds((8,), jnp.float32),)),
        Variant("cb", jax.jit(calls_back), (_sds((8,), jnp.float32),)),
    ])
    findings = _run(spec, rules={"transfer-free"})
    details = sorted(f.symbol for f in findings)
    assert details == ["cb:pure_callback", "single:device_put"]
    assert all(f.fingerprint == f"t::transfer-free::{f.symbol}" for f in findings)


def test_transfer_free_exempts_alias_annotations():
    # jnp.asarray of a numpy constant leaves devices=[None]/ALIAS
    # device_put annotations behind — they move nothing and must pass
    const = np.arange(8, dtype=np.float32)

    def benign(x):
        return x + jnp.asarray(const)

    spec = _spec(variants=[Variant("single", jax.jit(benign), (_sds((8,), jnp.float32),))])
    assert _run(spec, rules={"transfer-free"}) == []


# ----------------------------------------------------------- donation-audit


def test_donation_audit_opportunity_waiver_and_declared():
    big = (1 << 18,)  # 1 MiB of f32 — exactly the default threshold

    def inplaceable(x):
        return x + 1

    mk = lambda fn, **kw: [Variant("single", jax.jit(fn, **kw), (_sds(big, jnp.float32),))]

    # missed opportunity, no waiver -> finding
    spec = _spec(waiver=None, variants=mk(inplaceable))
    [f] = _run(spec, rules={"donation-audit"})
    assert f.symbol == "opportunity:arg0"

    # reviewed waiver silences it
    spec = _spec(waiver="buffer reused by caller", variants=mk(inplaceable))
    assert _run(spec, rules={"donation-audit"}) == []

    # declared AND actually donated -> clean
    spec = _spec(waiver=None, donate=(0,), variants=mk(inplaceable, donate_argnums=(0,)))
    assert _run(spec, rules={"donation-audit"}) == []

    # declared in the registry but the jit does not donate -> finding
    spec = _spec(waiver=None, donate=(0,), variants=mk(inplaceable))
    [f] = _run(spec, rules={"donation-audit"})
    assert f.symbol == "declared:arg0:not-donated"


def test_donation_audit_unusable_donation_flagged():
    # donated input whose aval matches no output: XLA drops it silently
    def shrinks(x):
        return x[:4]

    spec = _spec(
        waiver=None, donate=(0,),
        variants=[Variant("single", jax.jit(shrinks, donate_argnums=(0,)),
                          (_sds((1 << 18,), jnp.float32),))],
    )
    [f] = _run(spec, rules={"donation-audit"})
    assert f.symbol == "declared:arg0:unusable"


# --------------------------------------------------------- collective-audit


def test_collective_audit_single_device_collective_fires():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh1 = Mesh(np.array(jax.devices()[:1]), ("m",))
    fn = jax.jit(
        shard_map(
            lambda x: jax.lax.psum(x, "m"),
            mesh=mesh1, in_specs=P("m"), out_specs=P(),
        )
    )
    # registered as the SINGLE-device variant (mesh=None): any
    # collective is a finding
    spec = _spec(variants=[Variant("single", fn, (_sds((8,), jnp.float32),))])
    findings = _run(spec, rules={"collective-audit"})
    assert [f.symbol for f in findings] == ["single:psum"]


def test_collective_audit_unbound_axis_and_alien_mesh():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from eth_consensus_specs_tpu.parallel.mesh_ops import serve_mesh

    serve = serve_mesh()
    if serve is None:
        pytest.skip("needs >= 2 devices (conftest forces 8 on CPU)")
    rogue = Mesh(np.array(jax.devices()[:1]), ("rogue",))
    fn = jax.jit(
        shard_map(
            lambda x: jax.lax.psum(x, "rogue"),
            mesh=rogue, in_specs=P("rogue"), out_specs=P(),
        )
    )
    # registered as a mesh variant of the SERVE mesh (dp, sp): the body
    # binds an axis the declared mesh does not have
    spec = _spec(variants=[Variant("mesh", fn, (_sds((8,), jnp.float32),), mesh=serve)])
    symbols = sorted(f.symbol for f in _run(spec, rules={"collective-audit"}))
    assert symbols == ["mesh:alien-mesh", "mesh:psum:rogue"]


# ----------------------------------------------------------- constant-bloat


def test_constant_bloat_flags_big_closure_const():
    big_const = np.zeros((64, 1024), np.float32)  # 256 KiB

    def bloated(x):
        return x + jnp.asarray(big_const)[0, :8]

    spec = _spec(variants=[Variant("single", jax.jit(bloated), (_sds((8,), jnp.float32),))])
    variant = spec.build_variants(None)[0]
    closed = jaxlint.trace_variant(variant)
    findings = jaxlint.rule_constant_bloat(spec, variant, closed, limit=1024)
    assert findings and "constant-bloat" == findings[0].rule
    assert "262144 B" in findings[0].message
    # default threshold (1 MiB) lets it pass
    assert jaxlint.rule_constant_bloat(spec, variant, closed) == []


# --------------------------------------------------------------- x64-drift


def test_x64_drift_flags_upcast_and_exempts_weak_scalars():
    def drifts(x):
        return (x.astype(jnp.float64) + 1.0).astype(jnp.float32)

    spec = _spec(dtypes=("float32",), variants=[
        Variant("single", jax.jit(drifts), (_sds((8,), jnp.float32),))
    ])
    findings = _run(spec, rules={"x64-drift"})
    assert [f.symbol for f in findings] == ["single:float64"]

    # a python-int mask rides as a 0-d WEAK i64 scalar — exempt
    def masked(x):
        return x & 0xFF

    spec = _spec(dtypes=("uint64",), variants=[
        Variant("single", jax.jit(masked), (_sds((8,), jnp.uint64),))
    ])
    assert _run(spec, rules={"x64-drift"}) == []


def test_x64_drift_weak_float_scalar_is_not_exempt():
    """The weak-scalar exemption is INTEGER-only: a python float creeping
    into an integer kernel rides as a 0-d weak f32/f64 — exactly the
    drift class the rule exists for — and must fire even though it never
    materializes as an array."""

    def drifts(flags):
        # select between two python-float literals under a traced bool:
        # the result is a 0-d WEAK float that would have slipped through
        # a blanket 0-d-weak exemption
        v = jnp.where(flags[0], 1.5, 2.5)
        return (v > jnp.float64(2.0)).astype(jnp.uint32) + flags.astype(jnp.uint32)

    spec = _spec(dtypes=("uint32", "bool"), variants=[
        Variant("single", jax.jit(drifts), (_sds((4,), jnp.bool_),))
    ])
    findings = _run(spec, rules={"x64-drift"})
    assert findings, "a 0-d weak float in an integer kernel MUST fire"
    assert all(f.symbol.startswith("single:float") for f in findings)

    # the companion negative: the same shape of kernel whose 0-d weak
    # scalar is an INTEGER (a python shift amount) stays exempt
    def int_weak(flags):
        return flags.astype(jnp.uint32) << 3

    spec = _spec(dtypes=("uint32", "bool"), variants=[
        Variant("single", jax.jit(int_weak), (_sds((4,), jnp.bool_),))
    ])
    assert _run(spec, rules={"x64-drift"}) == []


# --------------------------------------------------------- recompile-surface


def test_recompile_surface_deliberate_key_collision_fires():
    """Acceptance: a key function that drops a discriminating dimension
    (here: depth — the shape the jit cache keys on) MUST be flagged."""

    def broken_grid(mesh):
        out = []
        for depth in (4, 10):
            for n in (1, 8):
                key = ("merkle_many", max(n, 8))  # depth DROPPED from the key
                sig = (((max(n, 8), 1 << depth, 8), "uint32"), depth)
                out.append((key, sig))
        return out

    spec = _spec(key_grid=broken_grid)
    findings = jaxlint.rule_recompile_surface(spec, None)
    assert any(f.symbol.startswith("collision:") for f in findings)
    assert all(f.rule == "recompile-surface" for f in findings)


def test_recompile_surface_live_serve_keys_injective():
    """The LIVE key functions (serve/buckets.merkle_many_key,
    bls_msm_key, ops/state_root.state_root_compile_key) over the real
    bucket grids, single-device AND mesh-signed."""
    from eth_consensus_specs_tpu.parallel.mesh_ops import serve_mesh

    mesh = serve_mesh()
    by_name = kernels.by_name()
    for name in ("merkle_many", "bls_msm", "state_root"):
        findings = jaxlint.rule_recompile_surface(by_name[name], mesh)
        assert findings == [], [f.message for f in findings]


def test_mesh_signature_is_what_keeps_keys_injective():
    """Dropping the mesh signature from the live merkle key collides a
    mesh-signed bucket with the single-device one — the PR 8 bug class
    the rule exists for."""
    from eth_consensus_specs_tpu.parallel.mesh_ops import (
        mesh_signature,
        pad_to_shards,
        serve_mesh,
        shard_count,
    )
    from eth_consensus_specs_tpu.serve import buckets

    mesh = serve_mesh()
    if mesh is None:
        pytest.skip("needs >= 2 devices (conftest forces 8 on CPU)")

    def unsigned_grid(_):
        cfg = (1, 2, 4, 8, 16, 32, 64)
        out = []
        for m in (None, mesh):
            shards = shard_count(m)
            key = buckets.merkle_many_key(8, 10, cfg, mesh=m)[:3]  # sig DROPPED
            batch = pad_to_shards(key[1], shards) if m is not None else key[1]
            sig = (((batch, 1 << 10, 8), "uint32"), 10, mesh_signature(m))
            out.append((key, sig))
        return out

    spec = _spec(key_grid=unsigned_grid)
    findings = jaxlint.rule_recompile_surface(spec, mesh)
    assert any(f.symbol.startswith("collision:") for f in findings)


# ------------------------------------------------------- registry contract


def test_registry_donation_policy_is_total():
    """Every registered family declares donated argnums or a reviewed
    waiver — the 'explicit donation/transfer declarations on all kernel
    families' contract."""
    assert len(kernels.REGISTRY) >= 8
    for spec in kernels.REGISTRY:
        assert spec.donate or spec.donation_waiver, spec.name
    # mesh-ness is derived from the builders (no duplicate flag):
    # the big three + the serve bls_msm seam shard over a live mesh
    from eth_consensus_specs_tpu.parallel.mesh_ops import serve_mesh

    mesh = serve_mesh()
    if mesh is not None:
        fams = kernels.mesh_families(mesh)
        assert {"merkle_many", "g1_msm", "bls_msm", "pairing"} <= fams
    # fr_fft is the family that actually donates (the fixed finding)
    assert kernels.by_name()["fr_fft"].donate == (0,)


def test_cheap_families_analyze_clean_with_mesh_variant():
    """Tier-1 lane: the sub-second families (incl. the merkle_many mesh
    variant) are finding-free under every rule."""
    from eth_consensus_specs_tpu.parallel.mesh_ops import serve_mesh

    mesh = serve_mesh()
    findings, stats = jaxlint.analyze(
        mesh=mesh, only={"sha256", "merkle", "merkle_many", "shuffle", "fr_fft"}
    )
    assert findings == [], [f.to_dict() for f in findings]
    assert stats["kernels"] == 5
    if mesh is not None:
        assert stats["mesh_variants"] >= 1
    assert stats["keys"] > 0  # merkle_many's live grid ran


@pytest.mark.slow
def test_full_registry_clean():
    """The acceptance gate: every family (>= 8, incl. >= 3 mesh
    variants on the 8-virtual-device mesh) analyzes with ZERO findings
    against the EMPTY baseline. CI's static-analysis job runs the same
    sweep through the CLI."""
    from eth_consensus_specs_tpu.analysis import lint
    from eth_consensus_specs_tpu.parallel.mesh_ops import serve_mesh

    mesh = serve_mesh()
    findings, stats = jaxlint.analyze(mesh=mesh)
    assert findings == [], [f.to_dict() for f in findings]
    assert stats["kernels"] >= 8
    if mesh is not None:
        assert stats["mesh_variants"] >= 3
    baseline = lint.load_baseline(os.path.join(REPO_ROOT, "jaxlint_baseline.json"))
    assert baseline == {}, "jaxlint baseline must ship EMPTY"


def test_baseline_empty_and_hard_rules_never_baselined():
    with open(os.path.join(REPO_ROOT, "jaxlint_baseline.json")) as fh:
        base = json.load(fh)["findings"]
    assert base == {}, "jaxlint findings are fixed in-PR, never baselined"
    for fp in base:
        for rule in jaxlint.HARD_RULES:
            assert f"::{rule}::" not in fp


# ----------------------------------------------------- shared CLI front end


def test_speclint_and_jaxlint_share_one_front_end():
    """The two CLIs build their flag sets from analysis/cli.py — same
    destinations, same baseline/json/write-baseline contract."""
    import argparse

    from eth_consensus_specs_tpu.analysis import cli, lint

    specs, jaxs = argparse.ArgumentParser(), argparse.ArgumentParser()
    cli.add_common_args(specs, default_baseline="s.json", all_rules=lint.ALL_RULES)
    cli.add_common_args(jaxs, default_baseline="j.json", all_rules=jaxlint.ALL_RULES)
    for ap in (specs, jaxs):
        flags = {a.dest for a in ap._actions}
        assert {"json_out", "rules", "baseline", "write_baseline", "force"} <= flags
    # --update-baseline stays as a compatibility alias for speclint users
    args = specs.parse_args(["--update-baseline"])
    assert args.write_baseline

    with pytest.raises(ValueError, match="unknown rules"):
        ns = specs.parse_args(["--rules", "not-a-rule"])
        cli.parse_rules(ns, lint.ALL_RULES)


def test_cli_finish_exit_codes_and_report(tmp_path):
    from eth_consensus_specs_tpu.analysis import cli, lint

    class Args:
        json_out = str(tmp_path / "r.json")
        baseline = str(tmp_path / "b.json")
        write_baseline = False
        force = False

    f = lint.Finding("x64-drift", "merkle", 0, "single:int64", "drift")
    assert cli.finish(Args(), [f], tool="jaxlint", extra={"kernels": 1}) == 2
    report = json.loads((tmp_path / "r.json").read_text())
    assert report["tool"] == "jaxlint"
    assert report["counts_by_rule"] == {"x64-drift": 1}
    assert report["extra"] == {"kernels": 1}
    assert report["new"][0]["fingerprint"] == "merkle::x64-drift::single:int64"

    # baseline the finding -> exit 0; ratchet refuses growth -> exit 1
    Args.write_baseline = True
    assert cli.finish(Args(), [f], tool="jaxlint") == 0
    g = lint.Finding("x64-drift", "shuffle", 0, "single:int64", "drift")
    assert cli.finish(Args(), [f, g], tool="jaxlint") == 1
