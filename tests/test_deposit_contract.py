"""Deposit contract model + native runtime parity
(reference: solidity_deposit_contract/deposit_contract.sol and its
foundry tests; spec constants from specs/phase0/deposit-contract.md)."""

import hashlib
import os
import random

import pytest

from eth_consensus_specs_tpu import native
from eth_consensus_specs_tpu.deposit_contract import (
    DEPOSIT_CONTRACT_TREE_DEPTH,
    DepositContract,
)
from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.ssz import List, hash_tree_root
from eth_consensus_specs_tpu.test_infra.deposits import build_deposit_data
from eth_consensus_specs_tpu.test_infra.genesis import bls_withdrawal_credentials
from eth_consensus_specs_tpu.test_infra.keys import privkeys, pubkeys


def _contract_and_ssz_roots(spec, n):
    contract = DepositContract()
    data_list = []
    for i in range(n):
        data = build_deposit_data(
            spec,
            pubkeys[i],
            privkeys[i],
            spec.MAX_EFFECTIVE_BALANCE,
            bls_withdrawal_credentials(spec, i),
            signed=True,
        )
        data_list.append(data)
        contract.deposit(
            bytes(data.pubkey),
            bytes(data.withdrawal_credentials),
            int(data.amount),
            bytes(data.signature),
        )
    DepositDataList = List[spec.DepositData, 2**DEPOSIT_CONTRACT_TREE_DEPTH]
    return contract, bytes(hash_tree_root(DepositDataList(data_list)))


def test_contract_root_matches_ssz_list_root():
    """The invariant the consensus layer relies on: the contract's root
    equals hash_tree_root(List[DepositData, 2**32]) of the same deposits."""
    spec = get_spec("phase0", "minimal")
    for n in (1, 2, 3, 7, 8):
        contract, ssz_root = _contract_and_ssz_roots(spec, n)
        assert contract.get_deposit_root() == ssz_root, n
        assert contract.get_deposit_count() == n.to_bytes(8, "little")


def test_empty_contract_root():
    spec = get_spec("phase0", "minimal")
    contract = DepositContract()
    DepositDataList = List[spec.DepositData, 2**DEPOSIT_CONTRACT_TREE_DEPTH]
    assert contract.get_deposit_root() == bytes(hash_tree_root(DepositDataList([])))


def test_deposit_leaf_is_deposit_data_root():
    spec = get_spec("phase0", "minimal")
    data = build_deposit_data(
        spec, pubkeys[0], privkeys[0], spec.MAX_EFFECTIVE_BALANCE,
        bls_withdrawal_credentials(spec, 0), signed=True,
    )
    contract = DepositContract()
    leaf = contract.deposit(
        bytes(data.pubkey), bytes(data.withdrawal_credentials),
        int(data.amount), bytes(data.signature),
    )
    assert leaf == bytes(hash_tree_root(data))


def test_deposit_input_validation():
    contract = DepositContract()
    with pytest.raises(AssertionError):
        contract.deposit(b"\x00" * 47, b"\x00" * 32, 10**9, b"\x00" * 96)
    with pytest.raises(AssertionError):
        contract.deposit(b"\x00" * 48, b"\x00" * 31, 10**9, b"\x00" * 96)
    with pytest.raises(AssertionError):
        contract.deposit(b"\x00" * 48, b"\x00" * 32, 10**9, b"\x00" * 95)
    with pytest.raises(AssertionError):
        contract.deposit(b"\x00" * 48, b"\x00" * 32, 10**9 - 1, b"\x00" * 96)


def test_native_and_python_paths_agree():
    if not native.available():
        pytest.skip("no C compiler available")
    rng = random.Random(5)
    leaves = [bytes(rng.randrange(256) for _ in range(32)) for _ in range(9)]

    import eth_consensus_specs_tpu.native as nat

    saved = nat._lib
    nat._lib = None  # forces the pure-Python fallback (get_lib caches)
    nat._tried = True
    try:
        py_contract = DepositContract()
        for leaf in leaves:
            py_contract.insert_leaf(leaf)
        py_root = py_contract.get_deposit_root()
    finally:
        nat._lib = saved
        nat._tried = True

    c_contract = DepositContract()
    for leaf in leaves:
        c_contract.insert_leaf(leaf)
    assert c_contract.get_deposit_root() == py_root


def test_native_sha256_matches_hashlib():
    if not native.available():
        pytest.skip("no C compiler available")
    rng = random.Random(6)
    msgs = [bytes(rng.randrange(256) for _ in range(64)) for _ in range(32)]
    flat = b"".join(msgs)
    digests = native.sha256_pairs(flat)
    for i, msg in enumerate(msgs):
        assert digests[32 * i : 32 * (i + 1)] == hashlib.sha256(msg).digest()
