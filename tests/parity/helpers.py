"""Differential-parity plumbing.

Each case runs the same scenario through two independent executables:

* ``spec``  — this framework's class-based fork spec (forks/),
* ``ref``   — the reference's markdown, compiled by specc/ straight from
  /root/reference/specs (the normative text IS the oracle; the
  reference's own pyspec is this same text run through pysetup).

State/objects cross the boundary as SSZ bytes, and agreement is asserted
on the OUTCOME (valid/invalid) and, for valid transitions, on the
byte-identical ``hash_tree_root`` of the post-state — BASELINE.json's
"bit-exact reftest parity" gate, evidenced case by case.
"""

from __future__ import annotations

from functools import lru_cache

import pytest

from eth_consensus_specs_tpu import ssz
from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.specc import compile_fork, compiled_forks
from eth_consensus_specs_tpu.test_infra.genesis import create_genesis_state
from eth_consensus_specs_tpu.utils import bls

PARITY_FORKS = compiled_forks()  # phase0 .. gloas

# Preset axis: the reference builds every fork x {minimal, mainnet}
# (reference Makefile:5-17). test_parity.py runs under minimal; the
# mainnet re-collection module flips this seam for the same cases.
_CURRENT_PRESET = "minimal"


class preset_override:
    def __init__(self, preset: str):
        self.preset = preset

    def __enter__(self):
        global _CURRENT_PRESET
        self._prev = _CURRENT_PRESET
        _CURRENT_PRESET = self.preset

    def __exit__(self, *exc):
        global _CURRENT_PRESET
        _CURRENT_PRESET = self._prev


def current_preset() -> str:
    return _CURRENT_PRESET


def specs(fork: str, preset: str | None = None):
    """(class-spec, compiled-reference-spec) pair for a fork."""
    return _specs(fork, preset or _CURRENT_PRESET)


@lru_cache(maxsize=None)
def _specs(fork: str, preset: str):
    return get_spec(fork, preset), compile_fork(fork, preset)


def genesis_state(fork: str):
    """Fresh framework-side genesis state (deserialized from the cached
    serialization, so mutation in one test never leaks into another)."""
    spec, _ = specs(fork)
    return ssz.deserialize(spec.BeaconState, _genesis_bytes(fork, _CURRENT_PRESET))


@lru_cache(maxsize=None)
def _genesis_bytes(fork: str, preset: str, n_validators: int = 64) -> bytes:
    spec, _ = specs(fork, preset)
    prev = bls.bls_active
    bls.bls_active = False
    try:
        state = create_genesis_state(
            spec, [spec.MAX_EFFECTIVE_BALANCE] * n_validators, spec.MAX_EFFECTIVE_BALANCE
        )
    finally:
        bls.bls_active = prev
    return bytes(ssz.serialize(state))


def to_ref(ref, obj, type_name: str | None = None):
    """Move an object across the boundary as SSZ bytes."""
    name = type_name or type(obj).__name__.split("[")[0]
    ref_type = getattr(ref, name)
    return ssz.deserialize(ref_type, ssz.serialize(obj))


def roots_equal(ours, ref_mod, theirs) -> bool:
    return bytes(ssz.hash_tree_root(ours)) == bytes(ref_mod.hash_tree_root(theirs))


_SPEC_FAILURES = (AssertionError, IndexError, ValueError, ZeroDivisionError, KeyError)


def run_both(spec, ref, state, callable_name: str, *args, ref_args=None):
    """Run ``spec.<name>(state, *args)`` and ``ref.<name>(ref_state, ...)``;
    assert same outcome; on success assert byte-identical post-state roots.
    Returns (outcome_ok, our_post_state)."""
    ref_state = to_ref(ref, state, "BeaconState")
    if ref_args is None:
        ref_args = [to_ref(ref, a) if isinstance(a, ssz.View) else a for a in args]
    ours = state.copy()
    ok_ours, err_ours = True, None
    try:
        getattr(spec, callable_name)(ours, *args)
    except _SPEC_FAILURES as e:
        ok_ours, err_ours = False, e
    ok_ref, err_ref = True, None
    try:
        getattr(ref, callable_name)(ref_state, *ref_args)
    except _SPEC_FAILURES as e:
        ok_ref, err_ref = False, e
    assert ok_ours == ok_ref, (
        f"{callable_name}: outcome diverged — ours={'ok' if ok_ours else err_ours!r} "
        f"ref={'ok' if ok_ref else err_ref!r}"
    )
    if ok_ours:
        assert roots_equal(ours, ref, ref_state), f"{callable_name}: post-state roots diverge"
    return ok_ours, ours


def forks_from(first: str) -> list[str]:
    return PARITY_FORKS[PARITY_FORKS.index(first) :]


def parametrize_forks(first: str = "phase0"):
    return pytest.mark.parametrize("fork", forks_from(first))
