"""The parity suite re-collected under the MAINNET preset.

The reference builds and nightly-tests every fork under mainnet as well as
minimal (reference Makefile:5-17, .github/workflows/nightly-tests.yml:25-50);
this module replays the differential-parity cases against mainnet-preset
compiled oracles. The randomized-chain cases stay minimal-only (they walk
2 epochs x 3 seeds x 8 forks; at 32 slots/epoch that is wall-clock, not
coverage).
"""

from __future__ import annotations

import pytest

# mainnet-preset differential lane — nightly/full lane (make test-full)
pytestmark = pytest.mark.slow

from . import helpers
from .test_parity import *  # noqa: F401,F403 — re-collect the suite
from .test_parity import _bls_off  # noqa: F401 — star-import skips _names

# drop the long randomized chains from the mainnet lane
test_randomized_chain_parity = None  # noqa: F811
del test_randomized_chain_parity


@pytest.fixture(autouse=True)
def _mainnet():
    with helpers.preset_override("mainnet"):
        yield
