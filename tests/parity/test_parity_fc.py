"""Fork-choice parity: this framework's Store/handlers vs the reference's
fork-choice.md compiled by specc (Store dataclass + on_tick/on_block/
on_attestation; reference: specs/phase0/fork-choice.md:162-811 and the
per-fork fork-choice deltas through gloas).

A replayed event sequence — ticks, signed blocks, attestations — is fed to
both stores; agreement is asserted on head root, justified/finalized
checkpoints, and the proposer-boost root after every step (the observable
surface the reference's fork_choice vector format checks:
tests/formats/fork_choice/README.md)."""

from __future__ import annotations

import pytest

from eth_consensus_specs_tpu import ssz
from eth_consensus_specs_tpu.specc import compile_fork
from eth_consensus_specs_tpu.test_infra import attestations as att_h
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.utils import bls

from .helpers import PARITY_FORKS, current_preset, genesis_state, specs, to_ref


@pytest.fixture(autouse=True)
def _bls_off():
    prev = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = prev


FC_FORKS = [f for f in PARITY_FORKS if f != "gloas"]
# gloas restructures on_block around payload envelopes (bids processed in
# the block, payloads revealed separately); its replay needs envelope
# events and is covered by test_gloas_store_bootstrap below.


def _ref_fc(fork: str):
    return compile_fork(fork, current_preset(), None, True)


def _bootstrap(spec, ref, fork):
    state = genesis_state(fork)
    block = spec.BeaconBlock(state_root=ssz.hash_tree_root(state))
    store = spec.get_forkchoice_store(state.copy(), block)
    ref_state = to_ref(ref, state, "BeaconState")
    ref_block = to_ref(ref, block, "BeaconBlock")
    ref_store = ref.get_forkchoice_store(ref_state, ref_block)
    return state, store, ref_store


def _assert_store_agreement(spec, ref, store, ref_store, ctx=""):
    ours_head = bytes(spec.get_head_root(store))
    theirs_head = bytes(ref.get_head(ref_store))
    assert ours_head == theirs_head, f"head diverged {ctx}"
    for cp in ("justified_checkpoint", "finalized_checkpoint"):
        ours = getattr(store, cp)
        theirs = getattr(ref_store, cp)
        assert (int(ours.epoch), bytes(ours.root)) == (
            int(theirs.epoch),
            bytes(theirs.root),
        ), f"{cp} diverged {ctx}"
    assert bytes(store.proposer_boost_root) == bytes(ref_store.proposer_boost_root), (
        f"proposer_boost_root diverged {ctx}"
    )


@pytest.mark.parametrize("fork", FC_FORKS)
def test_store_bootstrap_parity(fork):
    spec, _ = specs(fork)
    ref = _ref_fc(fork)
    _, store, ref_store = _bootstrap(spec, ref, fork)
    _assert_store_agreement(spec, ref, store, ref_store, "at anchor")
    assert int(store.time) == int(ref_store.time)


@pytest.mark.parametrize("fork", FC_FORKS)
def test_on_tick_on_block_replay_parity(fork):
    """One epoch of blocks driven through both stores tick by tick."""
    spec, _ = specs(fork)
    ref = _ref_fc(fork)
    state, store, ref_store = _bootstrap(spec, ref, fork)
    seconds_per_slot = int(spec.config.SECONDS_PER_SLOT)
    genesis_time = int(store.genesis_time)
    for _ in range(int(spec.SLOTS_PER_EPOCH)):
        target_slot = int(state.slot) + 1
        t = genesis_time + target_slot * seconds_per_slot
        spec.on_tick(store, t)
        ref.on_tick(ref_store, t)
        block = build_empty_block_for_next_slot(spec, state)
        signed = state_transition_and_sign_block(spec, state, block)
        spec.on_block(store, signed)
        ref.on_block(ref_store, to_ref(ref, signed, "SignedBeaconBlock"))
        _assert_store_agreement(spec, ref, store, ref_store, f"at slot {target_slot}")


@pytest.mark.parametrize("fork", ["phase0", "altair", "electra"])
def test_on_attestation_parity(fork):
    """A valid unaggregated attestation shifts latest messages (and thus
    potentially the head) identically in both stores."""
    spec, _ = specs(fork)
    ref = _ref_fc(fork)
    state, store, ref_store = _bootstrap(spec, ref, fork)
    seconds_per_slot = int(spec.config.SECONDS_PER_SLOT)
    genesis_time = int(store.genesis_time)
    # two competing chains is overkill here; one block + attestation to it
    t = genesis_time + (int(state.slot) + 1) * seconds_per_slot
    spec.on_tick(store, t)
    ref.on_tick(ref_store, t)
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    spec.on_block(store, signed)
    ref.on_block(ref_store, to_ref(ref, signed, "SignedBeaconBlock"))
    att = att_h.get_valid_attestation(spec, state, signed=True)
    # move past the attestation slot so it is no longer "from the future"
    t2 = genesis_time + (int(att.data.slot) + 2) * seconds_per_slot
    spec.on_tick(store, t2)
    ref.on_tick(ref_store, t2)
    spec.on_attestation(store, att)
    ref.on_attestation(ref_store, to_ref(ref, att, "Attestation"))
    _assert_store_agreement(spec, ref, store, ref_store, "after attestation")
    lm_ours = {int(k): (int(v.epoch), bytes(v.root)) for k, v in store.latest_messages.items()}
    lm_theirs = {
        int(k): (int(v.epoch), bytes(v.root)) for k, v in ref_store.latest_messages.items()
    }
    assert lm_ours == lm_theirs


def test_gloas_store_bootstrap():
    """gloas bootstraps its restructured store (payload-status tracking)
    from the same anchor on both sides."""
    fork = "gloas"
    spec, _ = specs(fork)
    ref = _ref_fc(fork)
    state = genesis_state(fork)
    block = spec.BeaconBlock(state_root=ssz.hash_tree_root(state))
    store = spec.get_forkchoice_store(state.copy(), block)
    ref_store = ref.get_forkchoice_store(
        to_ref(ref, state, "BeaconState"), to_ref(ref, block, "BeaconBlock")
    )
    # gloas get_head returns a ForkChoiceNode (root + payload status)
    theirs = ref.get_head(ref_store)
    assert bytes(spec.get_head_root(store)) == bytes(theirs.root)
    assert int(store.time) == int(ref_store.time)
