"""Vector-file parity: dump compiled-reference pre/post states as
``.ssz_snappy`` through the generator dumper, re-ingest through the snappy
codec, and replay through the class spec.

Exercises the exact on-disk format clients consume (reference:
gen_base/dumper.py:48-78, tests/formats/README.md) end to end: compiled
reference spec -> vector files -> framework — closing round-2's "upstream
vector reader is claimed but untested" gap with reference-shaped inputs.
"""

from __future__ import annotations

import os

import pytest

from eth_consensus_specs_tpu import ssz
from eth_consensus_specs_tpu.gen.snappy_codec import (
    frame_compress as compress,
    frame_decompress as decompress,
)
from eth_consensus_specs_tpu.test_infra.state import next_slots
from eth_consensus_specs_tpu.utils import bls

from .helpers import PARITY_FORKS, genesis_state, roots_equal, specs, to_ref


@pytest.fixture(autouse=True)
def _bls_off():
    prev = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = prev


@pytest.mark.parametrize("fork", PARITY_FORKS)
def test_ssz_snappy_state_roundtrip(fork, tmp_path):
    """pre.ssz_snappy / post.ssz_snappy written from the compiled reference
    spec must replay byte-identically through the framework spec."""
    spec, ref = specs(fork)
    state = genesis_state(fork)
    ref_state = to_ref(ref, state, "BeaconState")
    target = int(state.slot) + int(spec.SLOTS_PER_EPOCH)
    ref.process_slots(ref_state, target)

    pre_path = tmp_path / "pre.ssz_snappy"
    post_path = tmp_path / "post.ssz_snappy"
    pre_path.write_bytes(compress(bytes(ssz.serialize(to_ref(ref, state, "BeaconState")))))
    post_path.write_bytes(compress(bytes(ssz.serialize(ref_state))))

    # ingest through the codec as a client would, replay through our spec
    pre = ssz.deserialize(spec.BeaconState, decompress(pre_path.read_bytes()))
    expected_post = ssz.deserialize(spec.BeaconState, decompress(post_path.read_bytes()))
    spec.process_slots(pre, target)
    assert bytes(ssz.hash_tree_root(pre)) == bytes(ssz.hash_tree_root(expected_post))


@pytest.mark.parametrize("fork", PARITY_FORKS)
def test_operation_vector_roundtrip(fork, tmp_path):
    """An operations-format case (pre + operation + post) emitted from the
    compiled reference and consumed by the framework."""
    from eth_consensus_specs_tpu.test_infra import attestations as att_h

    spec, ref = specs(fork)
    state = genesis_state(fork)
    next_slots(spec, state, 10)
    att = att_h.get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))

    ref_state = to_ref(ref, state, "BeaconState")
    ref_att = to_ref(ref, att, "Attestation")
    (tmp_path / "pre.ssz_snappy").write_bytes(compress(bytes(ssz.serialize(ref_state))))
    (tmp_path / "attestation.ssz_snappy").write_bytes(compress(bytes(ssz.serialize(ref_att))))
    ref.process_attestation(ref_state, ref_att)
    (tmp_path / "post.ssz_snappy").write_bytes(compress(bytes(ssz.serialize(ref_state))))

    pre = ssz.deserialize(
        spec.BeaconState, decompress((tmp_path / "pre.ssz_snappy").read_bytes())
    )
    op = ssz.deserialize(
        spec.Attestation, decompress((tmp_path / "attestation.ssz_snappy").read_bytes())
    )
    post = ssz.deserialize(
        spec.BeaconState, decompress((tmp_path / "post.ssz_snappy").read_bytes())
    )
    spec.process_attestation(pre, op)
    assert bytes(ssz.hash_tree_root(pre)) == bytes(ssz.hash_tree_root(post))
