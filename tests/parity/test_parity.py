"""Bit-exact parity: class-based spec (forks/) vs the reference markdown
compiled by specc/ — operations, epoch processing, sanity transitions and
fork upgrades, phase0..electra, minimal preset.

This suite is the round-3 answer to BASELINE.json's "bit-exact reftest
parity" gate (round-2 verdict Missing #1): every case replays one scenario
through both executables and asserts byte-identical post-state roots (or
agreement that the input is invalid).
"""

from __future__ import annotations

import random

import pytest

from eth_consensus_specs_tpu import ssz
from eth_consensus_specs_tpu.test_infra import attestations as att_h
from eth_consensus_specs_tpu.test_infra import slashings as slash_h
from eth_consensus_specs_tpu.test_infra import voluntary_exits as exit_h
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.deposits import prepare_state_and_deposit
from eth_consensus_specs_tpu.test_infra.forks import is_post_altair, is_post_electra
from eth_consensus_specs_tpu.test_infra.state import next_epoch, next_slots
from eth_consensus_specs_tpu.utils import bls

from .helpers import (
    PARITY_FORKS,
    forks_from,
    genesis_state,
    parametrize_forks,
    roots_equal,
    run_both,
    specs,
    to_ref,
)


@pytest.fixture(autouse=True)
def _bls_off():
    prev = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = prev


# --- slots & epoch boundaries ---------------------------------------------


@parametrize_forks()
@pytest.mark.parametrize("slots", [1, "epoch", "3epochs"])
def test_process_slots_parity(fork, slots):
    spec, ref = specs(fork)
    state = genesis_state(fork)
    n = {1: 1, "epoch": int(spec.SLOTS_PER_EPOCH), "3epochs": 3 * int(spec.SLOTS_PER_EPOCH)}[
        slots
    ]
    target = int(state.slot) + n
    ref_state = to_ref(ref, state, "BeaconState")
    spec.process_slots(state, target)
    ref.process_slots(ref_state, target)
    assert roots_equal(state, ref, ref_state)


@parametrize_forks()
def test_epoch_processing_with_full_participation(fork):
    spec, ref = specs(fork)
    state = genesis_state(fork)
    next_epoch(spec, state)
    _, _, state = att_h.next_epoch_with_attestations(spec, state, True, False)
    ref_state = to_ref(ref, state, "BeaconState")
    target = int(state.slot) + int(spec.SLOTS_PER_EPOCH)
    spec.process_slots(state, target)
    ref.process_slots(ref_state, target)
    assert roots_equal(state, ref, ref_state)


# --- block-level sanity ----------------------------------------------------


@parametrize_forks()
def test_empty_signed_block_parity(fork):
    spec, ref = specs(fork)
    state = genesis_state(fork)
    bls.bls_active = True
    block = build_empty_block_for_next_slot(spec, state.copy())
    pre = state.copy()
    signed = state_transition_and_sign_block(spec, state, block)
    ref_state = to_ref(ref, pre, "BeaconState")
    ref_signed = to_ref(ref, signed, "SignedBeaconBlock")
    ref.state_transition(ref_state, ref_signed, True)
    assert roots_equal(state, ref, ref_state)


@parametrize_forks()
def test_block_with_attestations_parity(fork):
    spec, ref = specs(fork)
    state = genesis_state(fork)
    next_slots(spec, state, int(spec.SLOTS_PER_EPOCH) + 2)
    atts = att_h.get_valid_attestations_at_slot(
        spec, state, int(state.slot) - int(spec.MIN_ATTESTATION_INCLUSION_DELAY)
    )
    block = build_empty_block_for_next_slot(spec, state)
    for a in atts:
        block.body.attestations.append(a)
    pre = state.copy()
    # stub-signature mode on both sides (the kill switch is shared runtime)
    signed = state_transition_and_sign_block(spec, state, block)
    ref_state = to_ref(ref, pre, "BeaconState")
    ref.state_transition(ref_state, to_ref(ref, signed, "SignedBeaconBlock"), True)
    assert roots_equal(state, ref, ref_state)


# --- operations ------------------------------------------------------------


def _att_state(spec):
    state = genesis_state(spec_fork(spec))
    next_slots(spec, state, 10)
    return state


def spec_fork(spec):
    return spec.fork if isinstance(spec.fork, str) else str(spec.fork)


@parametrize_forks()
@pytest.mark.parametrize(
    "variant", ["valid", "bad_source", "future_slot", "empty_bits", "wrong_index"]
)
def test_process_attestation_parity(fork, variant):
    spec, ref = specs(fork)
    state = genesis_state(fork)
    next_slots(spec, state, 10)
    att = att_h.get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))
    if variant == "bad_source":
        att.data.source.epoch = 99
    elif variant == "future_slot":
        att.data.slot = state.slot + 10
    elif variant == "empty_bits":
        for i in range(len(att.aggregation_bits)):
            att.aggregation_bits[i] = False
    elif variant == "wrong_index":
        if is_post_electra(spec):
            att.committee_bits[0] = False
            att.committee_bits[len(att.committee_bits) - 1] = True
        else:
            att.data.index = 9999
    ok, _ = run_both(spec, ref, state, "process_attestation", att)
    assert ok == (variant == "valid")


@parametrize_forks()
@pytest.mark.parametrize("variant", ["valid", "same_header", "unsigned"])
def test_process_proposer_slashing_parity(fork, variant):
    spec, ref = specs(fork)
    state = genesis_state(fork)
    slashing = slash_h.get_valid_proposer_slashing(spec, state, signed_1=True, signed_2=True)
    if variant == "same_header":
        slashing.signed_header_2 = slashing.signed_header_1.copy()
    elif variant == "unsigned":
        bls.bls_active = True  # force real signature checking on garbage sigs
        slashing.signed_header_2.signature = spec.BLSSignature(b"\x01" * 96)
    ok, _ = run_both(spec, ref, state, "process_proposer_slashing", slashing)
    assert ok == (variant == "valid")


@parametrize_forks()
@pytest.mark.parametrize("variant", ["valid", "no_intersection"])
def test_process_attester_slashing_parity(fork, variant):
    spec, ref = specs(fork)
    state = genesis_state(fork)
    slashing = slash_h.get_valid_attester_slashing(spec, state, signed_1=True, signed_2=True)
    if variant == "no_intersection":
        keep = [int(i) for i in slashing.attestation_2.attesting_indices][:0]
        slashing.attestation_2.attesting_indices = type(
            slashing.attestation_2.attesting_indices
        )(keep)
    ok, _ = run_both(spec, ref, state, "process_attester_slashing", slashing)
    assert ok == (variant == "valid")


@parametrize_forks()
@pytest.mark.parametrize("variant", ["valid", "not_active_long_enough", "already_exited"])
def test_process_voluntary_exit_parity(fork, variant):
    spec, ref = specs(fork)
    state = genesis_state(fork)
    next_slots(
        spec, state, int(spec.config.SHARD_COMMITTEE_PERIOD) * int(spec.SLOTS_PER_EPOCH)
    )
    (exit_,) = exit_h.prepare_signed_exits(spec, state, [3])
    if variant == "not_active_long_enough":
        state = genesis_state(fork)
        (exit_,) = exit_h.prepare_signed_exits(spec, state, [3])
    elif variant == "already_exited":
        state.validators[3].exit_epoch = spec.get_current_epoch(state) + 1
    ok, _ = run_both(spec, ref, state, "process_voluntary_exit", exit_)
    assert ok == (variant == "valid")


@parametrize_forks()
@pytest.mark.parametrize("variant", ["top_up", "new_validator", "bad_proof"])
def test_process_deposit_parity(fork, variant):
    spec, ref = specs(fork)
    state = genesis_state(fork)
    amount = int(spec.MAX_EFFECTIVE_BALANCE) // 4
    index = 5 if variant == "top_up" else len(state.validators)
    deposit = prepare_state_and_deposit(spec, state, index, amount, signed=True)
    if variant == "bad_proof":
        deposit.proof[0] = ssz.Bytes32(b"\xff" * 32)
    ok, _ = run_both(spec, ref, state, "process_deposit", deposit)
    assert ok == (variant != "bad_proof")


@parametrize_forks()
def test_process_block_header_parity(fork):
    spec, ref = specs(fork)
    state = genesis_state(fork)
    block = build_empty_block_for_next_slot(spec, state)
    spec.process_slots(state, int(block.slot))
    ok, _ = run_both(spec, ref, state, "process_block_header", block)
    assert ok


@parametrize_forks()
def test_process_randao_parity(fork):
    spec, ref = specs(fork)
    state = genesis_state(fork)
    bls.bls_active = True
    block = build_empty_block_for_next_slot(spec, state)
    from eth_consensus_specs_tpu.test_infra.keys import privkey_of

    spec.process_slots(state, int(block.slot))
    proposer = int(spec.get_beacon_proposer_index(state))
    epoch = spec.get_current_epoch(state)
    domain = spec.get_domain(state, spec.DOMAIN_RANDAO, epoch)
    signing_root = spec.compute_signing_root(spec.Epoch(epoch), domain)
    block.body.randao_reveal = bls.Sign(privkey_of(proposer), signing_root)
    ok, _ = run_both(spec, ref, state, "process_randao", block.body)
    assert ok


@parametrize_forks()
def test_process_eth1_data_parity(fork):
    spec, ref = specs(fork)
    state = genesis_state(fork)
    block = build_empty_block_for_next_slot(spec, state)
    ok, _ = run_both(spec, ref, state, "process_eth1_data", block.body)
    assert ok


# --- altair+ sync aggregate -----------------------------------------------


@parametrize_forks("altair")
@pytest.mark.parametrize("participation", ["full", "empty"])
def test_process_sync_aggregate_parity(fork, participation):
    spec, ref = specs(fork)
    state = genesis_state(fork)
    next_slots(spec, state, 1)
    from eth_consensus_specs_tpu.test_infra.keys import pubkey_to_privkey

    comm_pubkeys = list(state.current_sync_committee.pubkeys)
    if participation == "full":
        bls.bls_active = True
        bits = [True] * len(comm_pubkeys)
        prev_slot = int(state.slot) - 1
        root = spec.get_block_root_at_slot(state, prev_slot)
        domain = spec.get_domain(
            state, spec.DOMAIN_SYNC_COMMITTEE, spec.compute_epoch_at_slot(prev_slot)
        )
        signing_root = spec.compute_signing_root(spec.Root(root), domain)
        sigs = [
            bls.Sign(pubkey_to_privkey(bytes(pk)), signing_root) for pk in comm_pubkeys
        ]
        agg = bls.Aggregate(sigs)
    else:
        bits = [False] * len(comm_pubkeys)
        agg = spec.BLSSignature(b"\xc0" + b"\x00" * 95)
    sync_aggregate = spec.SyncAggregate(
        sync_committee_bits=bits, sync_committee_signature=agg
    )
    ok, _ = run_both(spec, ref, state, "process_sync_aggregate", sync_aggregate)
    assert ok


# --- capella+ --------------------------------------------------------------


@parametrize_forks("capella")
def test_process_bls_to_execution_change_parity(fork):
    spec, ref = specs(fork)
    state = genesis_state(fork)
    from eth_consensus_specs_tpu.test_infra.keys import privkeys, pubkeys

    index = 4
    bls.bls_active = True
    change = spec.BLSToExecutionChange(
        validator_index=index,
        from_bls_pubkey=pubkeys[index],
        to_execution_address=b"\x11" * 20,
    )
    # withdrawal credentials must be the BLS hash of the from pubkey
    from eth_consensus_specs_tpu.ssz.hashing import hash_bytes

    state.validators[index].withdrawal_credentials = (
        spec.BLS_WITHDRAWAL_PREFIX + hash_bytes(bytes(pubkeys[index]))[1:]
    )
    domain = spec.compute_domain(
        spec.DOMAIN_BLS_TO_EXECUTION_CHANGE,
        spec.config.GENESIS_FORK_VERSION
        if hasattr(spec, "config")
        else spec.GENESIS_FORK_VERSION,
        state.genesis_validators_root,
    )
    signing_root = spec.compute_signing_root(change, domain)
    signed = spec.SignedBLSToExecutionChange(
        message=change, signature=bls.Sign(privkeys[index], signing_root)
    )
    ok, _ = run_both(spec, ref, state, "process_bls_to_execution_change", signed)
    assert ok


@parametrize_forks("capella")
def test_get_expected_withdrawals_parity(fork):
    spec, ref = specs(fork)
    state = genesis_state(fork)
    # make a validator fully withdrawable so the sweep finds something
    state.validators[2].withdrawal_credentials = (
        spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11 + b"\x22" * 20
    )
    state.validators[2].withdrawable_epoch = spec.get_current_epoch(state)
    ref_state = to_ref(ref, state, "BeaconState")
    ours = spec.get_expected_withdrawals(state)
    theirs = ref.get_expected_withdrawals(ref_state)
    ours_list = ours[0] if isinstance(ours, tuple) else ours
    theirs_list = theirs[0] if isinstance(theirs, tuple) else theirs
    assert [bytes(ssz.serialize(w)) for w in ours_list] == [
        bytes(ssz.serialize(w)) for w in theirs_list
    ]


# --- electra ---------------------------------------------------------------


def test_process_consolidation_request_parity():
    fork = "electra"
    spec, ref = specs(fork)
    state = genesis_state(fork)
    src, dst = 1, 2
    for idx in (src, dst):
        state.validators[idx].withdrawal_credentials = (
            spec.COMPOUNDING_WITHDRAWAL_PREFIX + b"\x00" * 11 + bytes([0x30 + idx]) * 20
        )
    addr = bytes(state.validators[src].withdrawal_credentials[12:])
    req = spec.ConsolidationRequest(
        source_address=addr,
        source_pubkey=state.validators[src].pubkey,
        target_pubkey=state.validators[dst].pubkey,
    )
    next_slots(
        spec, state, int(spec.config.SHARD_COMMITTEE_PERIOD) * int(spec.SLOTS_PER_EPOCH)
    )
    ok, _ = run_both(spec, ref, state, "process_consolidation_request", req)
    assert ok


def test_process_withdrawal_request_parity():
    fork = "electra"
    spec, ref = specs(fork)
    state = genesis_state(fork)
    idx = 3
    state.validators[idx].withdrawal_credentials = (
        spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11 + b"\x44" * 20
    )
    req = spec.WithdrawalRequest(
        source_address=b"\x44" * 20,
        validator_pubkey=state.validators[idx].pubkey,
        amount=spec.FULL_EXIT_REQUEST_AMOUNT,
    )
    next_slots(
        spec, state, int(spec.config.SHARD_COMMITTEE_PERIOD) * int(spec.SLOTS_PER_EPOCH)
    )
    ok, _ = run_both(spec, ref, state, "process_withdrawal_request", req)
    assert ok


def test_process_deposit_request_parity():
    fork = "electra"
    spec, ref = specs(fork)
    state = genesis_state(fork)
    from eth_consensus_specs_tpu.test_infra.keys import pubkeys

    req = spec.DepositRequest(
        pubkey=pubkeys[len(state.validators)],
        withdrawal_credentials=b"\x00" * 32,
        amount=spec.MIN_ACTIVATION_BALANCE,
        signature=b"\x00" * 96,
        index=0,
    )
    ok, _ = run_both(spec, ref, state, "process_deposit_request", req)
    assert ok


# --- fork upgrades ---------------------------------------------------------


@pytest.mark.parametrize("fork", forks_from("altair"))
def test_fork_upgrade_parity(fork):
    prev = PARITY_FORKS[PARITY_FORKS.index(fork) - 1]
    spec_prev, _ = specs(prev)
    spec, ref = specs(fork)
    state = genesis_state(prev)
    next_epoch(spec_prev, state)
    # NOTE: since round 5 BOTH sides compute the real aggregate pubkey
    # regardless of the bls switch (specc preamble _SpecBLSProxy ungates
    # AggregatePKs to match forks/altair.py eth_aggregate_pubkeys — state
    # bytes must not depend on a test switch), so no bls-on workaround is
    # needed here anymore.
    ours = spec.upgrade_from_parent(state.copy())
    # the compiled module reads the pre-state with the PREVIOUS fork's type
    from eth_consensus_specs_tpu.specc import compile_fork

    ref_prev = compile_fork(prev, "minimal")
    ref_state = ssz.deserialize(ref_prev.BeaconState, ssz.serialize(state))
    theirs = getattr(ref, f"upgrade_to_{fork}")(ref_state)
    assert bytes(ssz.hash_tree_root(ours)) == bytes(ref.hash_tree_root(theirs))


# --- randomized short chains ----------------------------------------------


@parametrize_forks()
@pytest.mark.parametrize("seed", [1, 2, 3])
def test_randomized_chain_parity(fork, seed):
    """Two epochs of randomized blocks (attestations included at random)
    replayed through the compiled reference spec block by block."""
    rng = random.Random(seed * 1000 + len(fork))
    spec, ref = specs(fork)
    state = genesis_state(fork)
    next_slots(spec, state, 3)
    ref_state = to_ref(ref, state, "BeaconState")
    for _ in range(2 * int(spec.SLOTS_PER_EPOCH)):
        block = build_empty_block_for_next_slot(spec, state)
        if rng.random() < 0.6:
            slot = int(state.slot) - int(spec.MIN_ATTESTATION_INCLUSION_DELAY)
            if slot >= 0:
                try:
                    atts = att_h.get_valid_attestations_at_slot(spec, state, slot)
                except AssertionError:
                    atts = []
                for a in atts[:2]:
                    block.body.attestations.append(a)
        signed = state_transition_and_sign_block(spec, state, block)
        ref.state_transition(ref_state, to_ref(ref, signed, "SignedBeaconBlock"), False)
        assert roots_equal(state, ref, ref_state), f"diverged at slot {state.slot}"
