"""Fulu PeerDAS parity: this framework's DAS stack (crypto/das.py, backed
by the device BLS-field FFT) vs the reference's fulu sampling markdown
compiled by specc (specs/fulu/polynomial-commitments-sampling.md:617-828
and das-core.md:137-190 — the normative cell/recovery math)."""

from __future__ import annotations

import random

import pytest

# pure-python 8192-point DAS math — nightly/full lane (make test-full)
pytestmark = pytest.mark.slow

from eth_consensus_specs_tpu.utils import bls

from .helpers import specs


@pytest.fixture(autouse=True)
def _bls_on():
    # KZG math needs real group arithmetic
    prev = bls.bls_active
    bls.bls_active = True
    yield
    bls.bls_active = prev


def _spec_pair():
    return specs("fulu")


def _random_blob(spec, seed: int) -> bytes:
    rng = random.Random(seed)
    n = int(spec.FIELD_ELEMENTS_PER_BLOB)
    modulus = int(spec.BLS_MODULUS)
    return b"".join(
        rng.randrange(modulus).to_bytes(32, "big") for _ in range(n)
    )


def test_compute_cells_and_kzg_proofs_parity():
    spec, ref = _spec_pair()
    blob = _random_blob(spec, 1)
    ours_cells, ours_proofs = spec.compute_cells_and_kzg_proofs(blob)
    ref_cells, ref_proofs = ref.compute_cells_and_kzg_proofs(ref.Blob(blob))
    assert [bytes(c) for c in ours_cells] == [bytes(c) for c in ref_cells]
    assert [bytes(p) for p in ours_proofs] == [bytes(p) for p in ref_proofs]


def test_recover_cells_and_kzg_proofs_parity():
    """Drop every other column; both sides must recover identical cells
    AND proofs (exercises the device FFT against the markdown's
    coset_fft_field/recover path)."""
    spec, ref = _spec_pair()
    blob = _random_blob(spec, 2)
    cells, _proofs = spec.compute_cells_and_kzg_proofs(blob)
    n = len(cells)
    keep = list(range(0, n, 2))
    ours_cells, ours_proofs = spec.recover_cells_and_kzg_proofs(
        keep, [cells[i] for i in keep]
    )
    ref_cells, ref_proofs = ref.recover_cells_and_kzg_proofs(
        [ref.CellIndex(i) for i in keep], [ref.Cell(bytes(cells[i])) for i in keep]
    )
    assert [bytes(c) for c in ours_cells] == [bytes(c) for c in ref_cells]
    assert [bytes(p) for p in ours_proofs] == [bytes(p) for p in ref_proofs]


@pytest.mark.parametrize("tamper", [False, True])
def test_verify_cell_kzg_proof_batch_parity(tamper):
    spec, ref = _spec_pair()
    blob = _random_blob(spec, 3)
    commitment = spec.blob_to_kzg_commitment(blob)
    cells, proofs = spec.compute_cells_and_kzg_proofs(blob)
    idxs = [0, 1, 5]
    sel_cells = [bytes(cells[i]) for i in idxs]
    if tamper:
        bad = bytearray(sel_cells[1])
        bad[0] ^= 1
        sel_cells[1] = bytes(bad)
    commitments = [bytes(commitment)] * len(idxs)
    sel_proofs = [bytes(proofs[i]) for i in idxs]
    ours = spec.verify_cell_kzg_proof_batch(commitments, idxs, sel_cells, sel_proofs)
    theirs = ref.verify_cell_kzg_proof_batch(
        [ref.Bytes48(c) for c in commitments],
        [ref.CellIndex(i) for i in idxs],
        [ref.Cell(c) for c in sel_cells],
        [ref.Bytes48(p) for p in sel_proofs],
    )
    assert bool(ours) == bool(theirs) == (not tamper)


def test_compute_and_recover_matrix_parity():
    spec, ref = _spec_pair()
    blobs = [_random_blob(spec, 10), _random_blob(spec, 11)]
    ours_matrix = spec.compute_matrix(blobs)
    ref_matrix = ref.compute_matrix([ref.Blob(b) for b in blobs])
    ours_flat = [
        (int(e.row_index), int(e.column_index), bytes(e.cell), bytes(e.kzg_proof))
        for e in ours_matrix
    ]
    ref_flat = [
        (int(e.row_index), int(e.column_index), bytes(e.cell), bytes(e.kzg_proof))
        for e in ref_matrix
    ]
    assert ours_flat == ref_flat

    # drop half of each row, recover on both sides
    half = [e for e in ours_matrix if int(e.column_index) % 2 == 0]
    ours_rec = spec.recover_matrix(half, len(blobs))
    ref_half = [e for e in ref_matrix if int(e.column_index) % 2 == 0]
    ref_rec = ref.recover_matrix(ref_half, len(blobs))
    assert [
        (int(e.row_index), int(e.column_index), bytes(e.cell)) for e in ours_rec
    ] == [(int(e.row_index), int(e.column_index), bytes(e.cell)) for e in ref_rec]


def test_custody_group_parity():
    spec, ref = _spec_pair()
    for node_seed in (b"\x01" * 32, b"\xaa" * 32):
        node_id = int.from_bytes(node_seed, "big") % 2**256
        count = 4
        ours = spec.get_custody_groups(node_id, count)
        theirs = ref.get_custody_groups(ref.NodeID(node_id), ref.uint64(count))
        assert [int(g) for g in ours] == [int(g) for g in theirs]
