"""First-payload (merge transition) vs regular-payload families
(reference analogue: test/bellatrix/block_processing/
test_process_execution_payload.py — the first/regular split, gap slots,
zero-length transactions, randomized non-validated fields).

'First payload' = state whose latest_execution_payload_header is empty
(merge not yet complete): parent-hash linkage is NOT checked there
(specs/bellatrix/beacon-chain.md process_execution_payload)."""

import random

from eth_consensus_specs_tpu.ssz import Bytes32
from eth_consensus_specs_tpu.test_infra.context import (
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.execution_payload import (
    build_empty_execution_payload,
    compute_el_block_hash,
)
from eth_consensus_specs_tpu.test_infra.state import next_slot, next_slots
from eth_consensus_specs_tpu.test_infra.template import instantiate

BELLATRIX = ["bellatrix"]


def _incomplete_transition(spec, state):
    """Wipe the header: merge not complete (reference:
    helpers/execution_payload.py build_state_with_incomplete_transition)."""
    state.latest_execution_payload_header = spec.ExecutionPayloadHeader()
    assert not spec.is_merge_transition_complete(state)


def _build_payload(spec, state, first: bool):
    if first:
        _incomplete_transition(spec, state)
    payload = build_empty_execution_payload(spec, state)
    if first:
        # transition block: parent is an arbitrary PoW hash, not the header
        payload.parent_hash = Bytes32(b"\x77" * 32)
        payload.block_hash = Bytes32(compute_el_block_hash(spec, payload))
    return payload


def _process(spec, state, payload, valid=True):
    body = spec.BeaconBlockBody(execution_payload=payload)
    if valid:
        spec.process_execution_payload(state, body, spec.EXECUTION_ENGINE)
    else:
        expect_assertion_error(
            lambda: spec.process_execution_payload(state, body, spec.EXECUTION_ENGINE)
        )


def _success_case(first: bool, gap: bool):
    @with_phases(BELLATRIX)
    @spec_state_test
    def case(spec, state):
        next_slots(spec, state, 4 if gap else 1)
        payload = _build_payload(spec, state, first)
        _process(spec, state, payload)
        assert state.latest_execution_payload_header.block_hash == payload.block_hash

    kind = "first" if first else "regular"
    suffix = "_with_gap_slot" if gap else ""
    return case, f"test_success_{kind}_payload{suffix}"


for _first in (True, False):
    for _gap in (False, True):
        instantiate(_success_case, _first, _gap)


@with_phases(BELLATRIX)
@spec_state_test
def test_first_payload_skips_parent_hash_check(spec, state):
    """Pre-merge the parent-hash linkage is unchecked: any parent works."""
    next_slot(spec, state)
    payload = _build_payload(spec, state, first=True)
    payload.parent_hash = Bytes32(b"\x12" * 32)
    payload.block_hash = Bytes32(compute_el_block_hash(spec, payload))
    _process(spec, state, payload)


@with_phases(BELLATRIX)
@spec_state_test
def test_invalid_parent_hash_regular_payload(spec, state):
    next_slot(spec, state)
    payload = _build_payload(spec, state, first=False)
    payload.parent_hash = Bytes32(b"\x12" * 32)
    payload.block_hash = Bytes32(compute_el_block_hash(spec, payload))
    _process(spec, state, payload, valid=False)


def _bad_field_case(first: bool, field: str):
    @with_phases(BELLATRIX)
    @spec_state_test
    def case(spec, state):
        next_slot(spec, state)
        payload = _build_payload(spec, state, first)
        if field == "prev_randao":
            payload.prev_randao = Bytes32(b"\x13" * 32)
        elif field == "timestamp_future":
            payload.timestamp = int(payload.timestamp) + 1000
        elif field == "timestamp_past":
            payload.timestamp = max(0, int(payload.timestamp) - 1000)
        else:  # everything
            payload.prev_randao = Bytes32(b"\x13" * 32)
            payload.timestamp = int(payload.timestamp) + 7
            if not first:
                payload.parent_hash = Bytes32(b"\x14" * 32)
        payload.block_hash = Bytes32(compute_el_block_hash(spec, payload))
        _process(spec, state, payload, valid=False)

    kind = "first" if first else "regular"
    return case, f"test_invalid_{field}_{kind}_payload"


for _first in (True, False):
    for _field in ("prev_randao", "timestamp_future", "timestamp_past", "everything"):
        instantiate(_bad_field_case, _first, _field)


def _transactions_case(first: bool, shape: str):
    """Opaque transaction payloads are NOT validated by the CL — any byte
    strings (including zero-length) pass; only the engine judges them."""

    @with_phases(BELLATRIX)
    @spec_state_test
    def case(spec, state):
        next_slot(spec, state)
        payload = _build_payload(spec, state, first)
        if shape == "nonempty":
            payload.transactions = [b"\x02" + b"\x55" * 30, b"\x01" * 12]
        else:
            payload.transactions = [b""]
        payload.block_hash = Bytes32(compute_el_block_hash(spec, payload))
        _process(spec, state, payload)

    kind = "first" if first else "regular"
    return case, f"test_{shape}_transactions_{kind}_payload"


for _first in (True, False):
    for _shape in ("nonempty", "zero_length"):
        instantiate(_transactions_case, _first, _shape)


def _randomized_nonvalidated_case(first: bool, execution_valid: bool, seed: int):
    """Fuzz the fields the CL never reads (fee_recipient, state_root,
    receipts_root, logs_bloom, extra_data, gas fields): processing outcome
    depends only on the engine verdict."""

    @with_phases(BELLATRIX)
    @spec_state_test
    def case(spec, state):
        rng = random.Random(seed)
        next_slot(spec, state)
        payload = _build_payload(spec, state, first)
        payload.fee_recipient = bytes(rng.getrandbits(8) for _ in range(20))
        payload.state_root = bytes(rng.getrandbits(8) for _ in range(32))
        payload.receipts_root = bytes(rng.getrandbits(8) for _ in range(32))
        payload.logs_bloom = bytes(rng.getrandbits(8) for _ in range(256))
        payload.extra_data = bytes(rng.getrandbits(8) for _ in range(rng.randint(0, 32)))
        payload.gas_limit = rng.randint(0, 2**32)
        payload.gas_used = rng.randint(0, int(payload.gas_limit))
        payload.block_hash = Bytes32(compute_el_block_hash(spec, payload))

        class FlakyEngine(type(spec.EXECUTION_ENGINE)):
            def notify_new_payload(self, *args, **kwargs) -> bool:
                return execution_valid

            def verify_and_notify_new_payload(self, *args, **kwargs) -> bool:
                return execution_valid

        body = spec.BeaconBlockBody(execution_payload=payload)
        if execution_valid:
            spec.process_execution_payload(state, body, FlakyEngine())
        else:
            expect_assertion_error(
                lambda: spec.process_execution_payload(state, body, FlakyEngine())
            )

    kind = "first" if first else "regular"
    verdict = "execution_valid" if execution_valid else "execution_invalid"
    return case, f"test_randomized_non_validated_fields_{kind}_payload_{verdict}"


for _first in (True, False):
    for _ok in (True, False):
        instantiate(
            _randomized_nonvalidated_case, _first, _ok, seed=7 + int(_first) * 2 + int(_ok)
        )
