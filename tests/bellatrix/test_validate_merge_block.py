"""validate_merge_block unit suite (reference analogue:
test/bellatrix/unittests/test_validate_merge_block.py — terminal
total-difficulty and terminal-block-hash-override families; spec:
specs/bellatrix/fork-choice.md validate_merge_block)."""

from eth_consensus_specs_tpu.test_infra.block import build_empty_block_for_next_slot
from eth_consensus_specs_tpu.test_infra.context import (
    expect_assertion_error,
    spec_state_test,
    with_config_overrides,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.pow_block import (
    prepare_random_pow_chain,
    pow_block_store,
)

BELLATRIX = ["bellatrix"]
TTD = 10  # tests run with a tiny overridden terminal total difficulty


def _merge_block(spec, state, parent_hash):
    block = build_empty_block_for_next_slot(spec, state)
    block.body.execution_payload.parent_hash = parent_hash
    block.body.execution_payload.block_hash = b"\x42" * 32
    return block


@with_phases(BELLATRIX)
@with_config_overrides({"TERMINAL_TOTAL_DIFFICULTY": TTD})
@spec_state_test
def test_validate_merge_block_success(spec, state):
    chain = prepare_random_pow_chain(spec, 2)
    chain.head(-1).total_difficulty = TTD - 1
    chain.head().total_difficulty = TTD
    block = _merge_block(spec, state, chain.head().block_hash)
    with pow_block_store(spec, chain):
        spec.validate_merge_block(block)


@with_phases(BELLATRIX)
@with_config_overrides({"TERMINAL_TOTAL_DIFFICULTY": TTD})
@spec_state_test
def test_validate_merge_block_fail_block_lookup(spec, state):
    chain = prepare_random_pow_chain(spec, 2)
    block = _merge_block(spec, state, b"\x11" * 32)  # unknown hash
    with pow_block_store(spec, chain):
        expect_assertion_error(lambda: spec.validate_merge_block(block))


@with_phases(BELLATRIX)
@with_config_overrides({"TERMINAL_TOTAL_DIFFICULTY": TTD})
@spec_state_test
def test_validate_merge_block_fail_parent_block_lookup(spec, state):
    # chain of one: the terminal block's parent can't be found
    chain = prepare_random_pow_chain(spec, 1)
    chain.head().total_difficulty = TTD
    block = _merge_block(spec, state, chain.head().block_hash)
    with pow_block_store(spec, chain):
        expect_assertion_error(lambda: spec.validate_merge_block(block))


@with_phases(BELLATRIX)
@with_config_overrides({"TERMINAL_TOTAL_DIFFICULTY": TTD})
@spec_state_test
def test_validate_merge_block_fail_after_terminal(spec, state):
    # parent of the referenced block ALREADY crossed TTD: not terminal
    chain = prepare_random_pow_chain(spec, 2)
    chain.head(-1).total_difficulty = TTD
    chain.head().total_difficulty = TTD + 1
    block = _merge_block(spec, state, chain.head().block_hash)
    with pow_block_store(spec, chain):
        expect_assertion_error(lambda: spec.validate_merge_block(block))


@with_phases(BELLATRIX)
@with_config_overrides({"TERMINAL_TOTAL_DIFFICULTY": TTD})
@spec_state_test
def test_validate_merge_block_fail_difficulty_not_reached(spec, state):
    chain = prepare_random_pow_chain(spec, 2)
    chain.head(-1).total_difficulty = TTD - 2
    chain.head().total_difficulty = TTD - 1
    block = _merge_block(spec, state, chain.head().block_hash)
    with pow_block_store(spec, chain):
        expect_assertion_error(lambda: spec.validate_merge_block(block))


# ------------------------------------------- terminal-block-hash override


_TBH = b"\x66" * 32


@with_phases(BELLATRIX)
@with_config_overrides({
        "TERMINAL_BLOCK_HASH": _TBH,
        "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": 0,
    })
@spec_state_test
def test_validate_merge_block_tbh_override_success(spec, state):
    block = _merge_block(spec, state, _TBH)
    # no PoW store needed: the override path never consults it
    spec.validate_merge_block(block)


@with_phases(BELLATRIX)
@with_config_overrides({
        "TERMINAL_BLOCK_HASH": _TBH,
        "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": 0,
    })
@spec_state_test
def test_validate_merge_block_fail_parent_hash_is_not_tbh(spec, state):
    block = _merge_block(spec, state, b"\x67" * 32)
    expect_assertion_error(lambda: spec.validate_merge_block(block))


@with_phases(BELLATRIX)
@with_config_overrides({
        "TERMINAL_BLOCK_HASH": _TBH,
        "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": 1000,
    })
@spec_state_test
def test_validate_merge_block_tbh_activation_not_reached(spec, state):
    block = _merge_block(spec, state, _TBH)
    expect_assertion_error(lambda: spec.validate_merge_block(block))


@with_phases(BELLATRIX)
@with_config_overrides({
        "TERMINAL_BLOCK_HASH": _TBH,
        "TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH": 1000,
    })
@spec_state_test
def test_validate_merge_block_tbh_activation_not_reached_and_wrong_hash(spec, state):
    block = _merge_block(spec, state, b"\x67" * 32)
    expect_assertion_error(lambda: spec.validate_merge_block(block))
