"""Fork-choice gate for the merge-transition block.

[New in Bellatrix] `on_block` must run `validate_merge_block` for a block
whose body carries the FIRST execution payload, judged against the parent
(pre) state — the terminal PoW block referenced by the payload must reach
TERMINAL_TOTAL_DIFFICULTY while its own parent stays below it.  Reference
surface: specs/bellatrix/fork-choice.md on_block:271-304 +
validate_merge_block:236-268; scenario analogue:
eth2spec/test/bellatrix/fork_choice/test_on_merge_block.py.
"""

from __future__ import annotations

from eth_consensus_specs_tpu.ssz import Bytes32
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.execution_payload import (
    build_empty_execution_payload,
    compute_el_block_hash,
)
from eth_consensus_specs_tpu.test_infra.fork_choice import (
    get_genesis_forkchoice_store,
    tick_and_add_block,
)
from eth_consensus_specs_tpu.test_infra.pow_block import (
    pow_block_store,
    prepare_random_pow_chain,
)

BELLATRIX = ["bellatrix"]


def _ttd(spec) -> int:
    return int(spec.config.TERMINAL_TOTAL_DIFFICULTY)


def _merge_chain(spec, pow_td: int, parent_td: int):
    """Two-block fake PoW chain with chosen total difficulties."""
    chain = prepare_random_pow_chain(spec, 2)
    chain.head(-1).total_difficulty = parent_td
    chain.head().total_difficulty = pow_td
    return chain


def _run_transition_block(spec, state, chain, drop_pow_block=False, valid=True):
    """Drive the transition block through fork-choice on_block with the
    fake PoW accessor installed."""
    state.latest_execution_payload_header = spec.ExecutionPayloadHeader()
    assert not spec.is_merge_transition_complete(state)
    store, _ = get_genesis_forkchoice_store(spec, state)

    block = build_empty_block_for_next_slot(spec, state)
    shifted = state.copy()
    spec.process_slots(shifted, block.slot)  # payload fields are slot-relative
    payload = build_empty_execution_payload(spec, shifted)
    payload.parent_hash = Bytes32(bytes(chain.head().block_hash))
    payload.block_hash = Bytes32(compute_el_block_hash(spec, payload))
    block.body.execution_payload = payload
    # fills the post-state root and signs; the PoW gate lives only in the
    # fork-choice handler, so signing succeeds even for gated blocks
    signed = state_transition_and_sign_block(spec, state.copy(), block)

    blocks = chain.blocks[:-1] if drop_pow_block else chain.blocks
    with pow_block_store(spec, type(chain)(blocks)):
        root = tick_and_add_block(spec, store, signed, valid=valid)
    if valid:
        assert root is not None
    return store, block


@with_phases(BELLATRIX)
@spec_state_test
def test_on_merge_block_all_valid(spec, state):
    chain = _merge_chain(spec, pow_td=_ttd(spec), parent_td=_ttd(spec) - 1)
    _run_transition_block(spec, state, chain, valid=True)


@with_phases(BELLATRIX)
@spec_state_test
def test_on_merge_block_pow_lookup_failed(spec, state):
    chain = _merge_chain(spec, pow_td=_ttd(spec), parent_td=_ttd(spec) - 1)
    _run_transition_block(spec, state, chain, drop_pow_block=True, valid=False)


@with_phases(BELLATRIX)
@spec_state_test
def test_on_merge_block_too_early(spec, state):
    # terminal candidate has not reached TTD yet
    chain = _merge_chain(spec, pow_td=_ttd(spec) - 1, parent_td=_ttd(spec) - 2)
    _run_transition_block(spec, state, chain, valid=False)


@with_phases(BELLATRIX)
@spec_state_test
def test_on_merge_block_too_late(spec, state):
    # parent already reached TTD: the referenced block is not terminal
    chain = _merge_chain(spec, pow_td=_ttd(spec) + 1, parent_td=_ttd(spec))
    _run_transition_block(spec, state, chain, valid=False)


@with_phases(BELLATRIX)
@spec_state_test
def test_on_merge_block_post_merge_no_gate(spec, state):
    """A regular post-merge block never consults the PoW accessor — the
    gate keys off is_merge_transition_block(pre_state, body)."""
    assert spec.is_merge_transition_complete(state)
    store, _ = get_genesis_forkchoice_store(spec, state)
    block = build_empty_block_for_next_slot(spec, state)
    shifted = state.copy()
    spec.process_slots(shifted, block.slot)
    block.body.execution_payload = build_empty_execution_payload(spec, shifted)
    signed = state_transition_and_sign_block(spec, state.copy(), block)

    def exploding_accessor(block_hash):
        raise AssertionError("post-merge on_block must not fetch PoW blocks")

    original = spec.get_pow_block
    spec.get_pow_block = exploding_accessor
    try:
        tick_and_add_block(spec, store, signed, valid=True)
    finally:
        spec.get_pow_block = original
