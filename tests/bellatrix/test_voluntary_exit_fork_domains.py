"""Pre-EIP-7044 voluntary-exit domain selection.

Before deneb, the exit's signing domain follows the fork version ACTIVE AT
THE EXIT'S EPOCH (get_domain with epoch=exit.epoch picks previous_version
for epochs before state.fork.epoch) — so after an upgrade, old exits
remain valid only under the old fork version and new exits only under the
new one.  Deneb then freezes the domain at capella
(tests/deneb/test_voluntary_exit_domain_table.py covers that side).
Reference analogue: eth2spec/test/bellatrix/block_processing/
test_process_voluntary_exit.py; spec: specs/phase0/beacon-chain.md
get_domain + process_voluntary_exit.
"""

from __future__ import annotations

from eth_consensus_specs_tpu.test_infra.context import (
    always_bls,
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.keys import privkeys
from eth_consensus_specs_tpu.test_infra.state import transition_to
from eth_consensus_specs_tpu.test_infra.voluntary_exits import sign_voluntary_exit

PRE_7044 = ["bellatrix", "capella"]


def _setup(spec, state, exit_epoch_before_fork: bool):
    """Age the validator set past the shard-committee period and place the
    state's fork boundary so the exit epoch falls on the requested side."""
    transition_to(
        spec,
        state,
        int(spec.config.SHARD_COMMITTEE_PERIOD) * int(spec.SLOTS_PER_EPOCH) + 1,
    )
    current = int(spec.get_current_epoch(state))
    if exit_epoch_before_fork:
        # pretend the current fork activated last epoch; exit one before
        state.fork.epoch = current
        exit_epoch = current - 1
    else:
        state.fork.epoch = 0
        exit_epoch = current
    return spec.VoluntaryExit(epoch=exit_epoch, validator_index=1)


def _run(spec, state, exit_msg, fork_version, valid: bool):
    signed = sign_voluntary_exit(
        spec, state, exit_msg, privkeys[1], fork_version=fork_version
    )
    if valid:
        spec.process_voluntary_exit(state, signed)
        assert state.validators[1].exit_epoch != spec.FAR_FUTURE_EPOCH
    else:
        expect_assertion_error(lambda: spec.process_voluntary_exit(state, signed))


@with_phases(PRE_7044)
@always_bls
@spec_state_test
def test_exit_before_fork_epoch_signed_with_previous_version(spec, state):
    exit_msg = _setup(spec, state, exit_epoch_before_fork=True)
    _run(spec, state, exit_msg, state.fork.previous_version, valid=True)


@with_phases(PRE_7044)
@always_bls
@spec_state_test
def test_exit_before_fork_epoch_signed_with_current_version_invalid(spec, state):
    exit_msg = _setup(spec, state, exit_epoch_before_fork=True)
    _run(spec, state, exit_msg, state.fork.current_version, valid=False)


@with_phases(PRE_7044)
@always_bls
@spec_state_test
def test_exit_after_fork_epoch_signed_with_current_version(spec, state):
    exit_msg = _setup(spec, state, exit_epoch_before_fork=False)
    _run(spec, state, exit_msg, state.fork.current_version, valid=True)


@with_phases(PRE_7044)
@always_bls
@spec_state_test
def test_exit_after_fork_epoch_signed_with_previous_version_invalid(spec, state):
    exit_msg = _setup(spec, state, exit_epoch_before_fork=False)
    _run(spec, state, exit_msg, state.fork.previous_version, valid=False)
