"""Execution-payload processing (reference analogue:
test/bellatrix/block_processing/test_process_execution_payload.py)."""

from eth_consensus_specs_tpu.ssz import Bytes32
from eth_consensus_specs_tpu.test_infra.context import (
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.execution_payload import (
    build_empty_execution_payload,
    compute_el_block_hash,
)
from eth_consensus_specs_tpu.test_infra.state import next_slot


def run_execution_payload_processing(spec, state, payload, valid=True, execution_valid=True):
    """Dual-mode runner; `execution_valid` drives the (monkeypatched)
    engine verdict, `valid` the consensus-side checks."""

    class TestEngine(type(spec.EXECUTION_ENGINE)):
        def notify_new_payload(self, execution_payload) -> bool:
            return execution_valid

    body = spec.BeaconBlockBody(execution_payload=payload)
    yield "pre", state
    yield "execution", {"execution_valid": execution_valid}
    yield "body", body
    if not (valid and execution_valid):
        expect_assertion_error(
            lambda: spec.process_execution_payload(state, body, TestEngine())
        )
        yield "post", None
        return
    spec.process_execution_payload(state, body, TestEngine())
    yield "post", state
    assert state.latest_execution_payload_header.block_hash == payload.block_hash


@with_phases(["bellatrix"])
@spec_state_test
def test_execution_payload_success_first_payload(spec, state):
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    yield from run_execution_payload_processing(spec, state, payload)


@with_phases(["bellatrix"])
@spec_state_test
def test_execution_payload_invalid_wrong_randao(spec, state):
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.prev_randao = Bytes32(b"\x66" * 32)
    payload.block_hash = Bytes32(compute_el_block_hash(spec, payload))
    yield from run_execution_payload_processing(spec, state, payload, valid=False)


@with_phases(["bellatrix"])
@spec_state_test
def test_execution_payload_invalid_wrong_timestamp(spec, state):
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.timestamp = int(payload.timestamp) + 1
    payload.block_hash = Bytes32(compute_el_block_hash(spec, payload))
    yield from run_execution_payload_processing(spec, state, payload, valid=False)


@with_phases(["bellatrix"])
@spec_state_test
def test_execution_payload_invalid_wrong_parent_hash(spec, state):
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.parent_hash = Bytes32(b"\x77" * 32)
    payload.block_hash = Bytes32(compute_el_block_hash(spec, payload))
    yield from run_execution_payload_processing(spec, state, payload, valid=False)


@with_phases(["bellatrix"])
@spec_state_test
def test_execution_payload_engine_rejects(spec, state):
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    yield from run_execution_payload_processing(
        spec, state, payload, valid=True, execution_valid=False
    )


@with_phases(["bellatrix"])
@spec_state_test
def test_execution_payload_empty_transaction_accepted_by_test_engine(spec, state):
    """The injected test engine accepts zero-length transactions (reference
    vectors mark these VALID; reference: pysetup/spec_builders/
    bellatrix.py:60-62) — the normative composite still rejects them."""
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.transactions = [b""]
    payload.block_hash = Bytes32(compute_el_block_hash(spec, payload))
    assert not spec.EXECUTION_ENGINE.spec_composite_verify(
        spec.NewPayloadRequest(execution_payload=payload)
    )
    yield from run_execution_payload_processing(spec, state, payload, valid=True)


@with_phases(["bellatrix"])
@spec_state_test
def test_merge_transition_predicates(spec, state):
    # genesis test state is merge-complete; a pre-merge state is not
    assert spec.is_merge_transition_complete(state)
    pre_merge = state.copy()
    pre_merge.latest_execution_payload_header = spec.ExecutionPayloadHeader()
    assert not spec.is_merge_transition_complete(pre_merge)
    empty_body = spec.BeaconBlockBody()
    assert not spec.is_merge_transition_block(pre_merge, empty_body)
    assert not spec.is_execution_enabled(pre_merge, empty_body)
    body = spec.BeaconBlockBody()
    body.execution_payload.block_number = 1
    assert spec.is_merge_transition_block(pre_merge, body)
    assert spec.is_execution_enabled(pre_merge, body)
