"""altair -> bellatrix state upgrade (spec: specs/bellatrix/fork.md)."""

from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.state import next_epoch


@with_phases(["altair"])
@spec_state_test
def test_upgrade_to_bellatrix_basic(spec, state):
    bell = get_spec("bellatrix", spec.preset_name)
    next_epoch(spec, state)
    post = bell.upgrade_from_parent(state)
    assert bytes(post.fork.current_version) == bytes(bell.config.BELLATRIX_FORK_VERSION)
    assert bytes(post.fork.previous_version) == bytes(state.fork.current_version)
    assert hash_tree_root(post.validators) == hash_tree_root(state.validators)
    assert hash_tree_root(post.current_sync_committee) == hash_tree_root(
        state.current_sync_committee
    )
    # empty payload header: the merge has not happened yet on upgrade
    assert not bell.is_merge_transition_complete(post)
    # the upgraded state runs under the bellatrix machine
    next_epoch(bell, post)
