"""Dense execution-payload mutation table, bellatrix..deneb (reference
analogue: the ~25-variant tables in test/bellatrix/block_processing/
test_process_execution_payload.py and its capella/deneb revisions)."""

from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.execution_payload import (
    build_empty_execution_payload,
    compute_el_block_hash,
)
from eth_consensus_specs_tpu.test_infra.state import next_slot, next_slots

EL_FORKS = ["bellatrix", "capella", "deneb"]


def run_execution_payload_processing(spec, state, payload, valid=True, execution_valid=True):
    """Fork-generic dual-mode runner (the bellatrix-only runner in
    test_execution_payload.py predates the deneb engine signature)."""
    from eth_consensus_specs_tpu.test_infra.context import expect_assertion_error

    class TestEngine(type(spec.EXECUTION_ENGINE)):
        def notify_new_payload(self, *args, **kwargs) -> bool:
            return execution_valid

        def verify_and_notify_new_payload(self, *args, **kwargs) -> bool:
            return execution_valid

    body = spec.BeaconBlockBody(execution_payload=payload)
    yield "pre", state
    yield "execution", {"execution_valid": execution_valid}
    yield "body", body
    if not (valid and execution_valid):
        expect_assertion_error(
            lambda: spec.process_execution_payload(state, body, TestEngine())
        )
        yield "post", None
        return
    spec.process_execution_payload(state, body, TestEngine())
    yield "post", state
    assert state.latest_execution_payload_header.block_hash == payload.block_hash


def _payload(spec, state):
    next_slot(spec, state)
    return build_empty_execution_payload(spec, state)


@with_phases(EL_FORKS)
@spec_state_test
def test_payload_basic_success(spec, state):
    payload = _payload(spec, state)
    yield from run_execution_payload_processing(spec, state, payload)


@with_phases(EL_FORKS)
@spec_state_test
def test_payload_second_in_a_row(spec, state):
    payload = _payload(spec, state)
    for part in run_execution_payload_processing(spec, state, payload):
        pass
    next_slot(spec, state)
    payload2 = build_empty_execution_payload(spec, state)
    yield from run_execution_payload_processing(spec, state, payload2)


@with_phases(EL_FORKS)
@spec_state_test
def test_invalid_bad_parent_hash_regular_payload(spec, state):
    payload = _payload(spec, state)
    payload.parent_hash = b"\x55" * 32
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload, valid=False)


@with_phases(EL_FORKS)
@spec_state_test
def test_invalid_randao_of_wrong_epoch(spec, state):
    payload = _payload(spec, state)
    # a PAST epoch's mix: wrong after enough slots
    next_slots(spec, state, 2 * int(spec.SLOTS_PER_EPOCH))
    wrong = spec.get_randao_mix(state, spec.get_current_epoch(state) - 2)
    payload.prev_randao = wrong
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload, valid=False)


@with_phases(EL_FORKS)
@spec_state_test
def test_invalid_timestamp_past(spec, state):
    payload = _payload(spec, state)
    payload.timestamp = int(payload.timestamp) - 1
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload, valid=False)


@with_phases(EL_FORKS)
@spec_state_test
def test_invalid_timestamp_future(spec, state):
    payload = _payload(spec, state)
    payload.timestamp = int(payload.timestamp) + int(spec.config.SECONDS_PER_SLOT)
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload, valid=False)


@with_phases(EL_FORKS)
@spec_state_test
def test_invalid_engine_verdict_false(spec, state):
    payload = _payload(spec, state)
    yield from run_execution_payload_processing(
        spec, state, payload, execution_valid=False
    )


@with_phases(EL_FORKS)
@spec_state_test
def test_payload_with_gas_fields_mutated_still_valid(spec, state):
    """gas_used/gas_limit are EL-validated, not consensus-checked: a
    mutated-but-hash-consistent payload must still pass."""
    payload = _payload(spec, state)
    payload.gas_used = 21_000
    payload.gas_limit = 30_000_000
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload)


@with_phases(EL_FORKS)
@spec_state_test
def test_payload_nonzero_extra_data_valid(spec, state):
    payload = _payload(spec, state)
    payload.extra_data = b"framework"
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload)


@with_phases(EL_FORKS)
@spec_state_test
def test_payload_fee_recipient_arbitrary_valid(spec, state):
    payload = _payload(spec, state)
    payload.fee_recipient = b"\xaa" * 20
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload)


@with_phases(["capella", "deneb"])
@spec_state_test
def test_invalid_withdrawals_mismatch_in_payload(spec, state):
    """capella+: process_withdrawals runs before the payload import; a
    payload whose withdrawals differ from the state's expectation fails
    the block path (driven through process_withdrawals)."""
    from eth_consensus_specs_tpu.test_infra.context import expect_assertion_error

    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    w = spec.Withdrawal(index=0, validator_index=0, address=b"\x01" * 20, amount=1)
    payload.withdrawals.append(w)
    payload.block_hash = compute_el_block_hash(spec, payload)
    expect_assertion_error(lambda: spec.process_withdrawals(state, payload))


@with_phases(["deneb"])
@spec_state_test
def test_deneb_payload_with_blob_fields(spec, state):
    payload = _payload(spec, state)
    payload.blob_gas_used = 0
    payload.excess_blob_gas = 0
    payload.block_hash = compute_el_block_hash(spec, payload)
    yield from run_execution_payload_processing(spec, state, payload)
