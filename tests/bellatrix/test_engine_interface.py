"""ExecutionEngine protocol semantics through process_execution_payload.

The engine is the one implementation-defined seam of the state machine;
these unittests pin how verdicts and the composite verify flow couple
into block processing (reference surface: specs/bellatrix/beacon-chain.md
process_execution_payload + the engine protocol; scenario analogue:
eth2spec/test/bellatrix/unittests/test_execution_engine_interface.py).
"""

from __future__ import annotations

from eth_consensus_specs_tpu.ssz import Bytes32
from eth_consensus_specs_tpu.test_infra.context import (
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.execution_payload import (
    build_empty_execution_payload,
    compute_el_block_hash,
)
from eth_consensus_specs_tpu.test_infra.forks import is_post_deneb
from eth_consensus_specs_tpu.test_infra.state import next_slot

BELLATRIX_ON = ["bellatrix", "capella", "deneb", "electra", "fulu"]
POST_DENEB = ["deneb", "electra", "fulu"]


class VerdictEngine:
    """Test double recording calls and returning scripted verdicts."""

    def __init__(self, spec, notify=True, block_hash=True, versioned=True):
        self._spec = spec
        self.notify_verdict = notify
        self.block_hash_verdict = block_hash
        self.versioned_verdict = versioned
        self.calls: list[str] = []

    def notify_new_payload(self, execution_payload, *args) -> bool:
        self.calls.append("notify_new_payload")
        return self.notify_verdict

    def is_valid_block_hash(self, execution_payload, *args) -> bool:
        self.calls.append("is_valid_block_hash")
        return self.block_hash_verdict

    def is_valid_versioned_hashes(self, new_payload_request) -> bool:
        self.calls.append("is_valid_versioned_hashes")
        return self.versioned_verdict

    def verify_and_notify_new_payload(self, new_payload_request) -> bool:
        self.calls.append("verify_and_notify_new_payload")
        return self.spec_composite_verify(new_payload_request)


def _engine(spec, **verdicts):
    eng = VerdictEngine(spec, **verdicts)
    # Bind the PHASE'S normative composite so the flow under test is the
    # real per-fork one, with this double supplying the sub-verdicts.
    # Bellatrix/capella keep the normative flow in spec_composite_verify
    # (their injected verify_and_notify is the permissive test engine);
    # deneb+ engines' verify_and_notify_new_payload IS the normative
    # composite (adds is_valid_versioned_hashes, electra adds requests).
    cls = type(spec.EXECUTION_ENGINE)
    if is_post_deneb(spec):
        composite = cls.verify_and_notify_new_payload
    else:
        composite = cls.spec_composite_verify
    eng.spec_composite_verify = composite.__get__(eng)
    return eng


def _payload_body(spec, state):
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    return spec.BeaconBlockBody(execution_payload=payload), payload


@with_phases(BELLATRIX_ON)
@spec_state_test
def test_engine_accept_updates_header(spec, state):
    body, payload = _payload_body(spec, state)
    eng = _engine(spec)
    spec.process_execution_payload(state, body, eng)
    assert "verify_and_notify_new_payload" in eng.calls
    assert state.latest_execution_payload_header.block_hash == payload.block_hash


@with_phases(BELLATRIX_ON)
@spec_state_test
def test_engine_notify_reject_invalidates_block(spec, state):
    body, _ = _payload_body(spec, state)
    eng = _engine(spec, notify=False)
    expect_assertion_error(lambda: spec.process_execution_payload(state, body, eng))
    assert "notify_new_payload" in eng.calls


@with_phases(BELLATRIX_ON)
@spec_state_test
def test_engine_bad_block_hash_short_circuits_notify(spec, state):
    """The composite checks the block hash BEFORE notifying — a payload
    with an invalid hash must never reach the engine's notifier."""
    body, _ = _payload_body(spec, state)
    eng = _engine(spec, block_hash=False)
    expect_assertion_error(lambda: spec.process_execution_payload(state, body, eng))
    assert "is_valid_block_hash" in eng.calls
    assert "notify_new_payload" not in eng.calls


@with_phases(BELLATRIX_ON)
@spec_state_test
def test_engine_empty_transaction_rejected_by_composite(spec, state):
    """A zero-length transaction is malformed RLP by definition; the
    normative composite rejects it before any engine callback."""
    body, _ = _payload_body(spec, state)
    body.execution_payload.transactions = [b""]
    body.execution_payload.block_hash = Bytes32(
        compute_el_block_hash(spec, body.execution_payload)
    )
    eng = _engine(spec)
    expect_assertion_error(lambda: spec.process_execution_payload(state, body, eng))
    assert "notify_new_payload" not in eng.calls


@with_phases(POST_DENEB)
@spec_state_test
def test_engine_bad_versioned_hashes_invalidates_block(spec, state):
    """Deneb+: the versioned-hash check sits between the block-hash check
    and the notifier in the normative flow."""
    body, _ = _payload_body(spec, state)
    eng = _engine(spec, versioned=False)
    expect_assertion_error(lambda: spec.process_execution_payload(state, body, eng))
    assert "is_valid_block_hash" in eng.calls
    assert "is_valid_versioned_hashes" in eng.calls
    assert "notify_new_payload" not in eng.calls


@with_phases(BELLATRIX_ON)
@spec_state_test
def test_engine_noop_accepts_everything(spec, state):
    """The injected test engine mirrors the reference's NoopExecutionEngine:
    every verdict is True, so an empty payload body processes cleanly."""
    body, payload = _payload_body(spec, state)
    spec.process_execution_payload(state, body, spec.EXECUTION_ENGINE)
    assert state.latest_execution_payload_header.block_hash == payload.block_hash
