"""Continuous-telemetry plane: series ring mechanics, anomaly detectors
on synthetic series (including a pinned zero-false-positive budget on
clean noise), windowed SLO burn rate, and the known-answer canary
scheduler's parity/exclusion contracts.

Everything here is synthetic and in-process — no replicas, no device
compiles (the one real-service test uses the bls canary, whose CPU path
is the host verifier). The detector tests ARE the documentation of each
detector's firing horizon: if a threshold changes, the pinned horizons
here must change with it.
"""

from __future__ import annotations

import concurrent.futures

import numpy as np
import pytest

from eth_consensus_specs_tpu import obs
from eth_consensus_specs_tpu.obs import anomaly, slo, tsdb
from eth_consensus_specs_tpu.obs import canary as canary_mod
from eth_consensus_specs_tpu.obs.delta import DeltaShipper
from eth_consensus_specs_tpu.obs.histogram import Histogram

CFG = anomaly.AnomalyConfig()  # the documented defaults, not env state


def wait_hist(values) -> dict:
    h = Histogram()
    for v in values:
        h.record(float(v))
    return h.snapshot()


def mk_sample(t, wait=None, rate=None, events=(), counters=None, dt=1.0):
    """One synthetic telemetry window (1s wide by default)."""
    counters = dict(counters or {})
    rates = {k: v / dt for k, v in counters.items()}
    if rate is not None:
        rates["frontdoor.requests"] = rate
        counters["frontdoor.requests"] = rate * dt
    hists = {}
    if wait is not None:
        hists["serve.wait_ms"] = wait_hist(wait)
        hists["frontdoor.e2e_ms"] = wait_hist(wait)
    return tsdb.Sample(t=t, dt=dt, counters=counters, rates=rates,
                       hists=hists, events=list(events))


def feed(det, samples, ring=None):
    """Run a detector over samples; returns (fires, fire_indices)."""
    ring = ring or tsdb.SeriesRing(64)
    fires, idxs = [], []
    for i, s in enumerate(samples):
        ring.append(s)
        found = det.step(s, ring)
        fires.extend(found)
        idxs.extend([i] * len(found))
    return fires, idxs


# ------------------------------------------------------------- series ring --


def test_series_ring_bounded_and_ordered():
    ring = tsdb.SeriesRing(8)
    for i in range(13):
        ring.append(mk_sample(float(i), counters={"x": i}))
    assert len(ring) == 8
    assert ring.capacity == 8
    assert ring.samples()[0].t == 5.0  # oldest five evicted
    assert ring.span_s() == 7.0
    assert [s.t for s in ring.last(3)] == [10.0, 11.0, 12.0]


def test_sample_from_delta_rates_and_events():
    delta = {
        "counters": {"serve.requests": 10},
        "gauges": {"g": {"last": 3.0, "max": 5.0}},
        "histograms": {"serve.wait_ms": wait_hist([1.0, 2.0])},
        "flight": [{"kind": "frontdoor.replica_lost", "replica": 1}],
    }
    s = tsdb.sample_from_delta(delta, t=10.0, dt=2.0)
    assert s.rates["serve.requests"] == pytest.approx(5.0)
    assert s.hist_count("serve.wait_ms") == 2
    assert s.events[0]["replica"] == 1
    assert s.quantile("serve.wait_ms", 0.5) is not None
    assert s.quantile("missing", 0.99) is None


def test_gauge_series_carries_level_forward():
    ring = tsdb.SeriesRing(8)
    s0 = mk_sample(0.0)
    s0.gauges["canary.pass_rate"] = {"last": 1.0, "max": 1.0}
    ring.append(s0)
    ring.append(mk_sample(1.0))  # gauge unchanged: delta ships nothing
    s2 = mk_sample(2.0)
    s2.gauges["canary.pass_rate"] = {"last": 0.5, "max": 1.0}
    ring.append(s2)
    series = ring.gauge_series("canary.pass_rate")
    assert [v for _, v in series] == [1.0, 1.0, 0.5]


def test_quantile_series_skips_empty_windows():
    ring = tsdb.SeriesRing(8)
    ring.append(mk_sample(0.0, wait=[10.0]))
    ring.append(mk_sample(1.0))  # quiet window: no latency, not zero
    ring.append(mk_sample(2.0, wait=[20.0]))
    series = ring.quantile_series("serve.wait_ms", 0.99)
    assert [t for t, _ in series] == [0.0, 2.0]


def test_sampler_owns_cursor_and_counts():
    ship = DeltaShipper()
    ship.delta()
    sampler = tsdb.Sampler(capacity=16)
    obs.count("tsdb_test.marker", 1)
    s = sampler.sample(t=100.0)
    assert s.counters.get("tsdb_test.marker") == 1
    # the sampler's own tsdb.samples bump lands in the NEXT window, and
    # a separate consumer's cursor still sees it (no window stealing)
    assert ship.delta()["counters"].get("tsdb.samples") == 1


# ----------------------------------------------------- structural detectors --


def test_dead_replica_fires_with_attribution():
    det = anomaly.DeadReplica(CFG)
    ev = {"kind": "frontdoor.replica_lost", "replica": 2, "exitcode": -9}
    fires, idxs = feed(det, [mk_sample(0.0), mk_sample(1.0, events=[ev])])
    assert len(fires) == 1 and idxs == [1]
    a = fires[0]
    assert a.replica == 2 and a.stage == "recovery"
    assert a.severity == "page" and a.windows == 1  # same-window horizon


def test_probe_stall_needs_consecutive_failures():
    det = anomaly.ProbeStall(CFG)
    fail = {"kind": "frontdoor.probe_failed", "replica": 1}
    # a success between failures resets the streak
    fires, _ = feed(det, [
        mk_sample(0.0, events=[fail]), mk_sample(1.0),
        mk_sample(2.0, events=[fail]),
    ])
    assert fires == []
    fires, idxs = feed(anomaly.ProbeStall(CFG), [
        mk_sample(0.0, events=[fail]), mk_sample(1.0, events=[fail]),
    ])
    assert len(fires) == 1 and idxs == [CFG.confirm - 1]
    assert fires[0].replica == 1 and fires[0].stage == "wire"


def test_completion_stall_fires_at_horizon_and_compiles_reset_it():
    det = anomaly.CompletionStall(CFG, "frontdoor.requests", "frontdoor.e2e_ms")
    samples = [mk_sample(0.0, rate=5.0)]
    samples += [mk_sample(float(i)) for i in range(1, CFG.stall_windows + 1)]
    fires, idxs = feed(det, samples)
    assert len(fires) == 1
    assert idxs == [CFG.stall_windows - 1]  # documented horizon, exactly
    # a cold-compile wall is not a stall: the compile delta resets it
    det = anomaly.CompletionStall(CFG, "frontdoor.requests", "frontdoor.e2e_ms")
    samples = [mk_sample(0.0, rate=5.0)]
    samples += [mk_sample(float(i)) for i in range(1, CFG.stall_windows - 1)]
    samples.append(mk_sample(99.0, counters={"serve.compiles": 1}))
    samples += [mk_sample(100.0 + i) for i in range(CFG.stall_windows - 1)]
    fires, _ = feed(det, samples)
    assert fires == []


# ---------------------------------------------------- statistical detectors --


def test_latency_step_fires_within_confirm_windows():
    rng = np.random.default_rng(7)
    det = anomaly.LatencyStep(CFG, "serve.wait_ms")
    base = [mk_sample(float(i), wait=rng.uniform(8, 12, 16))
            for i in range(CFG.warmup + 5)]
    stepped = [mk_sample(100.0 + i, wait=rng.uniform(95, 110, 16))
               for i in range(CFG.confirm + 1)]
    fires, idxs = feed(det, base + stepped)
    assert len(fires) == 1
    # documented horizon: within `confirm` windows of the step
    assert idxs[0] < len(base) + CFG.confirm
    assert fires[0].detector == "latency_step"


def test_latency_drift_fires_within_documented_horizon():
    rng = np.random.default_rng(8)
    det = anomaly.LatencyDrift(CFG, "serve.wait_ms")
    base = [mk_sample(float(i), wait=rng.uniform(9, 11, 16))
            for i in range(CFG.warmup)]
    # 8%/window exponential creep: crosses drift_ratio (3x) in
    # log(3)/log(1.08) ~ 14 windows; the EWMA lags a few more
    drift = [mk_sample(50.0 + i, wait=[10.0 * (1.08 ** i)] * 16)
             for i in range(40)]
    fires, idxs = feed(det, base + drift)
    assert fires, "drift never detected"
    horizon = idxs[0] - len(base)
    assert 14 <= horizon <= 25, f"drift horizon {horizon} outside documented band"


def test_rate_spike_and_stall():
    det = anomaly.RateSpike(CFG, "frontdoor.requests")
    base = [mk_sample(float(i), rate=100.0) for i in range(CFG.warmup + 3)]
    spike = [mk_sample(50.0 + i, rate=1500.0) for i in range(CFG.confirm)]
    fires, _ = feed(det, base + spike)
    assert len(fires) == 1 and fires[0].detector == "rate_spike"

    det = anomaly.RateStall(CFG, "frontdoor.requests")
    stall = [mk_sample(50.0 + i, rate=2.0) for i in range(CFG.confirm)]
    fires, _ = feed(det, base + stall)
    assert len(fires) == 1 and fires[0].detector == "rate_stall"
    # full idleness (rate 0) is NOT a stall — quiet fleets are healthy
    det = anomaly.RateStall(CFG, "frontdoor.requests")
    idle = [mk_sample(50.0 + i, rate=0.0) for i in range(20)]
    fires, _ = feed(det, base + idle)
    assert fires == []


def test_clean_noise_fires_nothing_fp_budget_zero():
    """The pinned false-positive budget: 500 windows of healthy jittery
    traffic must produce ZERO fires across the entire detector set."""
    rng = np.random.default_rng(20260807)
    slo.reset_windows_for_tests()
    dets = anomaly.default_detectors(CFG, "frontdoor", anomaly.ALL)
    ring = tsdb.SeriesRing(64)
    fired = []
    for i in range(500):
        s = mk_sample(float(i), wait=rng.uniform(8, 14, 24),
                      rate=float(rng.uniform(80, 120)))
        ring.append(s)
        for det in dets:
            fired.extend(det.step(s, ring))
    assert fired == [], f"false positives on clean noise: {fired}"


def test_engine_refractory_suppresses_repeat_fires():
    reg_before = obs.snapshot()["counters"].get("anomaly.fires", 0)
    eng = anomaly.Engine(CFG, detectors=[anomaly.DeadReplica(CFG)],
                         source="frontdoor", capture=False)
    ev = {"kind": "frontdoor.replica_lost", "replica": 0, "exitcode": -9}
    ring = tsdb.SeriesRing(16)
    ring.append(mk_sample(0.0, events=[ev]))
    assert len(eng.step(ring)) == 1
    # same replica again inside the refractory window: suppressed
    ring.append(mk_sample(1.0, events=[ev]))
    assert eng.step(ring) == []
    # a DIFFERENT replica is a different key: fires
    ev2 = {"kind": "frontdoor.replica_lost", "replica": 1, "exitcode": -9}
    ring.append(mk_sample(2.0, events=[ev2]))
    assert len(eng.step(ring)) == 1
    assert eng.fire_counts() == {"dead_replica": 2}
    assert obs.snapshot()["counters"].get("anomaly.fires", 0) == reg_before + 2
    rep = eng.report()
    assert rep["total"] == 2
    assert {f["replica"] for f in rep["fired"]} == {0, 1}


# ----------------------------------------------------------- slo burn rate --


def test_burn_rate_windowed():
    import time as _time

    slo.reset_windows_for_tests()
    assert slo.burn_rate(window_s=60.0) is None
    slo.note_window(True)  # a single live window is its own burn rate
    one = slo.burn_rate(window_s=60.0)
    assert one["windows"] == 1 and one["burn_rate"] == pytest.approx(1.0)
    slo.reset_windows_for_tests()
    now = _time.monotonic()
    slo.note_window(True, t=now - 120.0)  # ancient: outside the cap
    slo.note_window(True, t=now - 1.0)
    slo.note_window(False, t=now)
    capped = slo.burn_rate(window_s=60.0)
    assert capped["windows"] == 2 and capped["breached"] == 1
    assert capped["burn_rate"] == pytest.approx(0.5)
    assert capped["window_s"] == 60.0
    slo.reset_windows_for_tests()


def test_burn_rate_counters_path_unchanged():
    snap = {"counters": {"slo.windows": 10, "slo.windows_breached": 3}}
    overall = slo.burn_rate(snap)
    assert overall["windows"] == 10 and overall["breached"] == 3
    assert overall["burn_rate"] == pytest.approx(0.3)
    assert slo.burn_rate({"counters": {}}) is None


# ------------------------------------------------------------------ canary --


class FakeClient:
    """Resolves every canary instantly with a configurable result."""

    def __init__(self, result="correct"):
        self.mode = result
        self.calls = 0

    def submit_hash_tree_root(self, chunks, canary=False):
        assert canary is True
        self.calls += 1
        fut = concurrent.futures.Future()
        if self.mode == "correct":
            from eth_consensus_specs_tpu.obs.watchdog import host_tree_root_words
            from eth_consensus_specs_tpu.ops.merkle import _chunks_to_words

            fut.set_result(
                host_tree_root_words(_chunks_to_words(chunks, chunks.shape[0])))
        elif self.mode == "wrong":
            fut.set_result(b"\x00" * 32)
        elif self.mode == "error":
            fut.set_exception(RuntimeError("shed"))
        else:  # hang
            pass
        return fut


def test_canary_pass_and_pass_rate():
    sched = canary_mod.CanaryScheduler(FakeClient(), interval_s=100.0,
                                       shapes=("htr",))
    sched._next_t = 0.0
    sched.pump(now=1.0)  # send
    sched.pump(now=1.1)  # reap (next send not due for 100s)
    st = sched.stats()
    assert st["sent"] == 1 and st["ok"] == 1
    assert st["parity_failures"] == 0 and st["pass_rate"] == 1.0


def test_canary_parity_failure_counts_and_pages():
    before = obs.snapshot()["counters"].get("canary.parity_failures", 0)
    sched = canary_mod.CanaryScheduler(FakeClient("wrong"), interval_s=0.0,
                                       shapes=("htr",))
    sched._next_t = 0.0
    sched.pump(now=1.0)
    sched.pump(now=1.1)
    st = sched.stats()
    assert st["parity_failures"] == 1 and st["ok"] == 0
    assert st["pass_rate"] == 0.0
    after = obs.snapshot()["counters"].get("canary.parity_failures", 0)
    assert after == before + 1


def test_canary_error_and_timeout_are_degraded_not_parity():
    sched = canary_mod.CanaryScheduler(FakeClient("error"), interval_s=0.0,
                                       shapes=("htr",))
    sched._next_t = 0.0
    sched.pump(now=1.0)
    sched.pump(now=1.1)
    assert sched.stats()["errors"] == 1
    assert sched.stats()["parity_failures"] == 0

    sched = canary_mod.CanaryScheduler(FakeClient("hang"), interval_s=0.0,
                                       timeout_s=5.0, shapes=("htr",))
    sched._next_t = 0.0
    sched.pump(now=1.0)
    sched.pump(now=2.0)  # still pending, inside timeout
    assert sched.stats()["errors"] == 0
    sched.pump(now=7.1)  # past timeout
    assert sched.stats()["errors"] == 1
    assert sched.stats()["parity_failures"] == 0


def test_canary_at_most_one_in_flight():
    client = FakeClient("hang")
    sched = canary_mod.CanaryScheduler(client, interval_s=0.0, shapes=("htr",))
    sched._next_t = 0.0
    for i in range(5):
        sched.pump(now=1.0 + i * 0.01)
    assert client.calls == 1  # the hang blocks further sends


def test_canary_warm_keys_are_fixed_shapes():
    keys = canary_mod.warm_keys(("bls", "htr", "agg"))
    assert ("merkle_many", 1, 6) in keys
    assert ("bls_msm", 1, 4) in keys
    assert ("g2_agg", 1, 4) in keys
    kzg = canary_mod.warm_keys(("kzg",))
    assert ("kzg", 4) in kzg
    assert ("fr_fft", 1, 4096) in kzg


def test_canary_excluded_from_serving_metrics():
    """The exclusion contract end to end on a real in-process service:
    a canary never lands in serve.requests / serve.wait_ms / admission,
    and lives in the canary.* family instead. bls only — its CPU path
    is the host verifier, so this compiles nothing."""
    from eth_consensus_specs_tpu.serve.config import ServeConfig
    from eth_consensus_specs_tpu.serve.service import VerifyService

    svc = VerifyService(ServeConfig(max_batch=4, max_wait_ms=2))
    try:
        ship = DeltaShipper()
        ship.delta()  # baseline
        payload, expected = canary_mod._BUILDERS["bls"]()
        got = svc.submit_bls_aggregate(*payload, canary=True).result(timeout=30)
        assert canary_mod.bits(got) == canary_mod.bits(expected)
        assert svc.admission.depth() == 0  # exempt: never admitted
        d = ship.delta()
        assert d["counters"].get("canary.requests", 0) == 1
        assert d["counters"].get("serve.requests", 0) == 0
        hists = d.get("histograms", {})
        assert hists.get("serve.wait_ms", {}).get("count", 0) == 0
        assert hists.get("canary.wait_ms", {}).get("count", 0) == 1
    finally:
        svc.close()
