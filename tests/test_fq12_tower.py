"""Device Fq2/Fq6/Fq12 tower vs the host oracle (crypto/fields.py).

Every op is checked batched over random elements for bit-exact agreement
after canonicalization (the lazy limb kernel's redundant values are
normalized at the host boundary — from_mont_int reduces mod p)."""

import random

import numpy as np
import pytest

from eth_consensus_specs_tpu.crypto.fields import P, Fq, Fq2, Fq6, Fq12
from eth_consensus_specs_tpu.ops import fq12_tower as tw
from eth_consensus_specs_tpu.ops import lazy_limbs as lz
from eth_consensus_specs_tpu.ops.lazy_limbs import lf

rng = random.Random(1234)


def rand_fq2() -> Fq2:
    return Fq2(Fq(rng.randrange(P)), Fq(rng.randrange(P)))


def rand_fq6() -> Fq6:
    return Fq6(rand_fq2(), rand_fq2(), rand_fq2())


def rand_fq12() -> Fq12:
    return Fq12(rand_fq6(), rand_fq6())


def fq6_to_limbs(a: Fq6) -> np.ndarray:
    return np.stack([tw.fq2_to_limbs(c) for c in (a.c0, a.c1, a.c2)])


def limbs_to_fq6(arr) -> Fq6:
    a = np.asarray(arr)
    return Fq6(*[tw.limbs_to_fq2(a[i]) for i in range(3)])


def out(x) -> np.ndarray:
    """LF -> host array (lazy values are fine: from_mont_int reduces)."""
    return np.asarray(lz.norm(x).v)


BATCH = 4


class TestFq2:
    def test_mul_sqr_inv(self):
        xs = [rand_fq2() for _ in range(BATCH)]
        ys = [rand_fq2() for _ in range(BATCH)]
        dx = lf(np.stack([tw.fq2_to_limbs(x) for x in xs]), val=P - 1)
        dy = lf(np.stack([tw.fq2_to_limbs(y) for y in ys]), val=P - 1)
        got_mul = out(tw.fq2_mul(dx, dy))
        got_sqr = out(tw.fq2_sqr(dx))
        got_inv = out(tw.fq2_inv(dx))
        got_xi = out(tw.fq2_mul_xi(dx))
        from eth_consensus_specs_tpu.crypto.fields import XI

        for i, (x, y) in enumerate(zip(xs, ys)):
            assert tw.limbs_to_fq2(got_mul[i]) == x * y
            assert tw.limbs_to_fq2(got_sqr[i]) == x.square()
            assert tw.limbs_to_fq2(got_inv[i]) == x.inv()
            assert tw.limbs_to_fq2(got_xi[i]) == x * XI

    def test_conj_neg_addsub(self):
        x, y = rand_fq2(), rand_fq2()
        dx = lf(tw.fq2_to_limbs(x), val=P - 1)
        dy = lf(tw.fq2_to_limbs(y), val=P - 1)
        assert tw.limbs_to_fq2(out(tw.fq2_add(dx, dy))) == x + y
        assert tw.limbs_to_fq2(out(tw.fq2_sub(dx, dy))) == x - y
        assert tw.limbs_to_fq2(out(tw.fq2_conj(dx))) == x.conjugate()
        assert tw.limbs_to_fq2(out(tw.fq2_neg(dx))) == -x


class TestFq6:
    def test_mul_inv_v(self):
        xs = [rand_fq6() for _ in range(BATCH)]
        ys = [rand_fq6() for _ in range(BATCH)]
        dx = lf(np.stack([fq6_to_limbs(x) for x in xs]), val=P - 1)
        dy = lf(np.stack([fq6_to_limbs(y) for y in ys]), val=P - 1)
        got_mul = out(tw.fq6_mul(dx, dy))
        got_inv = out(tw.fq6_inv(dx))
        got_v = out(tw.fq6_mul_v(dx))
        for i, (x, y) in enumerate(zip(xs, ys)):
            assert limbs_to_fq6(got_mul[i]) == x * y
            assert limbs_to_fq6(got_inv[i]) * x == Fq6.one()
            assert limbs_to_fq6(got_v[i]) == x.mul_by_xi_shift()


class TestFq12:
    def test_mul_sqr_inv_conj(self):
        xs = [rand_fq12() for _ in range(BATCH)]
        ys = [rand_fq12() for _ in range(BATCH)]
        dx = lf(np.stack([tw.fq12_to_limbs(x) for x in xs]), val=P - 1)
        dy = lf(np.stack([tw.fq12_to_limbs(y) for y in ys]), val=P - 1)
        got_mul = out(tw.fq12_mul(dx, dy))
        got_sqr = out(tw.fq12_sqr(dx))
        got_inv = out(tw.fq12_inv(dx))
        got_conj = out(tw.fq12_conj(dx))
        for i, (x, y) in enumerate(zip(xs, ys)):
            assert tw.limbs_to_fq12(got_mul[i]) == x * y
            assert tw.limbs_to_fq12(got_sqr[i]) == x.square()
            assert tw.limbs_to_fq12(got_inv[i]) == x.inv()
            assert tw.limbs_to_fq12(got_conj[i]) == x.conjugate()

    def test_frobenius(self):
        x = rand_fq12()
        dx = lf(tw.fq12_to_limbs(x), val=P - 1)
        assert tw.limbs_to_fq12(out(tw.fq12_frobenius(dx))) == x.frobenius()
        assert (
            tw.limbs_to_fq12(out(tw.fq12_frobenius2(dx)))
            == x.frobenius().frobenius()
        )

    def test_powx_matches_pow(self):
        from eth_consensus_specs_tpu.crypto.fields import BLS_X

        # powx assumes the cyclotomic subgroup (inverse == conjugate):
        # use a pairing-like element g^((p^6-1)(p^2+1)) to land there
        g = rand_fq12()
        m = g.conjugate() * g.inv()
        m = m.frobenius().frobenius() * m
        dm = lf(tw.fq12_to_limbs(m), val=P - 1)
        got = tw.limbs_to_fq12(out(tw.fq12_powx(dm)))
        assert got == m.pow(BLS_X)

    def test_is_one(self):
        one = tw.fq12_one()
        assert bool(np.asarray(tw.fq12_is_one(one)))
        x = rand_fq12()
        assert not bool(
            np.asarray(tw.fq12_is_one(lf(tw.fq12_to_limbs(x), val=P - 1)))
        )
