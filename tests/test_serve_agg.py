"""The serve-layer `aggregate` op: bucket model, keys, routing, and the
host rungs of the degrade ladder — everything that gates WITHOUT paying
the G2 kernel's scan-body compile (the device dispatch itself is
covered by the slow lane in tests/test_g2_aggregate.py and the
agg-smoke CI job)."""

from __future__ import annotations

import pytest

from eth_consensus_specs_tpu import fault, obs, serve
from eth_consensus_specs_tpu.crypto import signature as sig_mod
from eth_consensus_specs_tpu.crypto.curve import g2_generator, g2_to_bytes
from eth_consensus_specs_tpu.serve import buckets
from eth_consensus_specs_tpu.serve.config import ServeConfig


# ------------------------------------------------------- bucket model --


def test_agg_lane_bucket_reuses_mesh_batch_bucket_semantics():
    """Single-shard lanes bucket plain pow2; sharded lanes bucket the
    PER-SHARD count and multiply back — every result divisible by
    shards, >= n, per-shard pow2 (the butterfly fold's requirement)."""
    for n in (1, 2, 3, 5, 9, 17, 33, 100):
        assert buckets.agg_lane_bucket(n) == buckets.pow2_bucket(n)
        for shards in (2, 3, 4, 5, 6, 7, 8):
            pad = buckets.agg_lane_bucket(n, shards)
            assert pad >= n
            assert pad % shards == 0
            per = pad // shards
            assert per == buckets.pow2_bucket(per), (n, shards, pad)


def test_agg_lane_bucket_non_pow2_shards_pad_strictly_less_than_global():
    """The regression the ISSUE pins: a non-pow2 mesh bucketing its RAW
    lane count pads strictly less than bucketing the GLOBAL pow2 would
    (pad-of-pad) — the same non-idempotence class that once produced
    cold compiles on 6-shard replicas in the bls family."""
    for n, shards in ((33, 6), (9, 6), (17, 3), (33, 5), (65, 7)):
        raw = buckets.agg_lane_bucket(n, shards)
        of_global = buckets.agg_lane_bucket(buckets.pow2_bucket(n), shards)
        assert raw < of_global, (n, shards, raw, of_global)
    # pow2 shard counts ARE pad-of-pad idempotent — that equality is
    # what lets warm-key widening enumerate from the pow2 lane bucket
    for n, shards in ((33, 4), (9, 8), (100, 2), (5, 4)):
        raw = buckets.agg_lane_bucket(n, shards)
        of_global = buckets.agg_lane_bucket(buckets.pow2_bucket(n), shards)
        assert raw == of_global, (n, shards, raw, of_global)


def test_g2_agg_key_forms_and_profile_agreement():
    assert buckets.g2_agg_key(3, 5) == ("g2_agg", 4, 8)
    assert buckets.g2_agg_key_from_profile(3, 5) == ("g2_agg", 4, 8)
    signed = buckets.g2_agg_key_from_profile(3, 33, 6, "cpu3x2")
    assert signed == ("g2_agg", 4, 48, "cpu3x2")
    # shards without a signature stay unsigned (single-device form)
    assert buckets.g2_agg_key_from_profile(3, 33, 6, "") == ("g2_agg", 4, 64)
    # the shared shape model in ops agrees with the serve key fn
    from eth_consensus_specs_tpu.ops.g2_aggregate import g2_many_sum_shape

    for items, lanes, shards in ((1, 1, 1), (3, 5, 1), (3, 33, 6), (9, 100, 8)):
        shape = g2_many_sum_shape(items, lanes, shards)
        key = buckets.g2_agg_key_from_profile(items, lanes, shards, "sig")
        assert shape == (key[1], key[2]), (items, lanes, shards)


def test_route_shape_and_route_wide_for_agg(monkeypatch):
    assert buckets.route_shape_of_key(("g2_agg", 4, 8)) == ("g2_agg", 8)
    assert buckets.route_shape_of_key(("g2_agg", 4, 48, "cpu3x2")) == ("g2_agg", 48)
    # lane-crossover policy: wide iff the pow2 lane bucket clears it,
    # REGARDLESS of flush size (the lane axis is what shards)
    monkeypatch.delenv("ETH_SPECS_AGG_MESH_LANES", raising=False)
    assert buckets.route_wide("agg", 8, 1)
    assert not buckets.route_wide("agg", 4, 64)
    monkeypatch.setenv("ETH_SPECS_AGG_MESH_LANES", "4")
    assert buckets.route_wide("agg", 4, 1)


def test_widen_warm_keys_emits_signed_g2_agg_shapes():
    cfg = ServeConfig(max_batch=4, buckets=(1, 2, 4))
    out = buckets.widen_warm_keys([("g2_agg", 2, 16)], cfg, 6, "cpu3x2")
    signed = [k for k in out if k[0] == "g2_agg" and len(k) == 4]
    assert signed, "no signed g2_agg keys widened"
    assert all(k[3] == "cpu3x2" for k in signed)
    # lane pads come from the RAW counts that bucket to 16, under 6
    # shards: ceil(9..16 / 6) in {2, 3} -> pow2 {2, 4} -> pads {12, 24}
    assert {k[2] for k in signed} == {12, 24}
    assert {k[1] for k in signed} == {1, 2, 4}
    # lanes below the crossover never shard: nothing signed to widen
    out = buckets.widen_warm_keys([("g2_agg", 2, 4)], cfg, 6, "cpu3x2")
    assert [k for k in out if k[0] == "g2_agg" and len(k) == 4] == []


def test_precompile_skips_alien_signed_g2_agg_key(monkeypatch):
    """A mesh-signed g2_agg key replayed without that live mesh must be
    SKIPPED (never compiled wrong) — and the skip costs no compile, so
    this stays in the fast lane."""
    monkeypatch.setenv("ETH_SPECS_MESH", "0")
    buckets.reset_for_tests()
    before = obs.snapshot()["counters"].get("serve.compiles", 0)
    warmed = buckets.precompile([("g2_agg", 2, 48, "nosuch6x1")])
    assert warmed == 0
    assert obs.snapshot()["counters"].get("serve.compiles", 0) == before


# ------------------------------------------------- service host rungs --


def _mk_sigs(n: int) -> list[bytes]:
    G2 = g2_generator()
    return [g2_to_bytes(G2.mul(k + 1)) for k in range(n)]


def test_submit_aggregate_error_parity_without_dispatch():
    """Empty and malformed inputs resolve exceptionally in _prep — the
    exact ValueErrors the direct signature.aggregate call raises, and
    no device dispatch ever happens (fast-lane safe)."""
    with serve.VerifyService(ServeConfig(max_batch=2, max_wait_ms=1.0), name="t-agg-err") as svc:
        with pytest.raises(ValueError, match="zero signatures"):
            svc.submit_aggregate([]).result(timeout=30)
        with pytest.raises(ValueError, match="invalid signature"):
            svc.submit_aggregate([b"\x01" * 96]).result(timeout=30)


def test_submit_aggregate_host_degrade_parity():
    """Device death degrades the whole flush to the host
    signature.aggregate fold — byte-identical results, no XLA anywhere
    (which is also why this runs in the fast lane: the injected fault
    fires BEFORE the kernel would compile)."""
    sig_sets = [_mk_sigs(3), _mk_sigs(5), _mk_sigs(1)]
    want = [sig_mod.aggregate(s) for s in sig_sets]
    before = obs.snapshot()["counters"].get("serve.degraded_items", 0)
    with fault.injected("serve.dispatch:raise:times=inf"):
        with serve.VerifyService(ServeConfig(max_batch=4, max_wait_ms=1.0), name="t-agg-deg") as svc:
            futs = [svc.submit_aggregate(s) for s in sig_sets]
            got = [f.result(timeout=60) for f in futs]
    assert got == want
    assert obs.snapshot()["counters"].get("serve.degraded_items", 0) >= before + 3


def test_frontdoor_host_execute_agg_parity():
    from eth_consensus_specs_tpu.serve.frontdoor import _host_execute

    sigs = _mk_sigs(4)
    assert _host_execute("agg", (tuple(sigs),)) == sig_mod.aggregate(sigs)
