"""RFC 9380 hash-to-G2 known-answer tests.

The RO_ suite vectors use the RFC's test DST; matching them end-to-end
(expand_message -> hash_to_field -> SSWU -> isogeny -> clear_cofactor)
pins byte-level interop with every conforming BLS implementation
(reference backends: milagro/arkworks/py_ecc, utils/bls.py:57-68).
"""

from eth_consensus_specs_tpu.crypto.hash_to_curve import (
    DST_G2,
    expand_message_xmd,
    hash_to_field_fq2,
    hash_to_g2,
    map_to_curve_g2,
)
from eth_consensus_specs_tpu.crypto.curve import g2_to_bytes, g2_from_bytes, in_subgroup

RFC_DST = b"QUUX-V01-CS02-with-BLS12381G2_XMD:SHA-256_SSWU_RO_"


def test_rfc9380_g2_ro_abc():
    """RFC 9380 Appendix J.10.1, msg="abc"."""
    p = hash_to_g2(b"abc", RFC_DST)
    assert p.x.c0.n == int(
        "02c2d18e033b960562aae3cab37a27ce00d80ccd5ba4b7fe0e7a210245129dbe"
        "c7780ccc7954725f4168aff2787776e6",
        16,
    )
    assert p.x.c1.n == int(
        "139cddbccdc5e91b9623efd38c49f81a6f83f175e80b06fc374de9eb4b41dfe4"
        "ca3a230ed250fbe3a2acf73a41177fd8",
        16,
    )
    assert p.y.c0.n == int(
        "1787327b68159716a37440985269cf584bcb1e621d3a7202be6ea05c4cfe244a"
        "eb197642555a0645fb87bf7466b2ba48",
        16,
    )
    assert p.y.c1.n == int(
        "00aa65dae3c8d732d10ecd2c50f8a1baf3001578f71c694e03866e9f3d49ac1e"
        "1ce70dd94a733534f106d4cec0eddd16",
        16,
    )


def test_hash_to_g2_deterministic_and_in_subgroup():
    for msg in [b"", b"abc", b"a" * 512, bytes(range(256))]:
        p = hash_to_g2(msg)
        q = hash_to_g2(msg)
        assert p == q
        assert p.is_on_curve()
        assert in_subgroup(p)
        # round-trips through compressed serialization
        assert g2_from_bytes(g2_to_bytes(p)) == p


def test_distinct_messages_distinct_points():
    seen = set()
    for i in range(16):
        seen.add(g2_to_bytes(hash_to_g2(i.to_bytes(4, "big"))))
    assert len(seen) == 16


def test_dst_separates_domains():
    assert hash_to_g2(b"msg", RFC_DST) != hash_to_g2(b"msg", DST_G2)


def test_expand_message_xmd_length_and_determinism():
    out = expand_message_xmd(b"msg", RFC_DST, 0x80)
    assert len(out) == 0x80
    assert out == expand_message_xmd(b"msg", RFC_DST, 0x80)


def test_map_to_curve_on_curve():
    for u in hash_to_field_fq2(b"map-probe", 4):
        q = map_to_curve_g2(u)
        assert q.is_on_curve()
