"""KZG against the ceremony testing trusted setup (when available).

The framework defaults to a self-generated insecure setup; this suite
re-runs the commit/prove/verify cycle under the official-format ceremony
testing setup file so commitments/proofs are cross-checkable with
published deneb KZG vectors (ADVICE r1; reference:
presets/mainnet/trusted_setups/trusted_setup_4096.json)."""

import os

import pytest

from eth_consensus_specs_tpu.crypto import kzg

CEREMONY_SETUP = "/root/reference/presets/mainnet/trusted_setups/trusted_setup_4096.json"

pytestmark = pytest.mark.skipif(
    not os.path.exists(CEREMONY_SETUP), reason="ceremony setup file not present"
)


@pytest.fixture(autouse=True)
def _ceremony_setup():
    kzg.set_trusted_setup(CEREMONY_SETUP)
    yield
    kzg.set_trusted_setup(None)


def _blob(seed: int) -> bytes:
    # valid field elements: keep each 32-byte chunk < BLS_MODULUS
    out = bytearray()
    for i in range(kzg.FIELD_ELEMENTS_PER_BLOB):
        v = (seed * 2_654_435_761 + i) % kzg.BLS_MODULUS
        out += v.to_bytes(32, kzg.KZG_ENDIANNESS)
    return bytes(out)


def test_known_commitment_for_zero_blob():
    """The zero polynomial commits to the point at infinity under ANY
    setup — a setup-independent known answer proving the ceremony file
    parsed into usable points."""
    commitment = kzg.blob_to_kzg_commitment(b"\x00" * kzg.BYTES_PER_BLOB)
    assert commitment == kzg.G1_POINT_AT_INFINITY


def test_commit_prove_verify_under_ceremony_setup():
    blob = _blob(7)
    commitment = kzg.blob_to_kzg_commitment(blob)
    proof = kzg.compute_blob_kzg_proof(blob, commitment)
    assert kzg.verify_blob_kzg_proof(blob, commitment, proof)
    # a different blob under the same commitment/proof must fail
    assert not kzg.verify_blob_kzg_proof(_blob(8), commitment, proof)
    # tampered commitment (a different valid commitment) must fail
    other_commitment = kzg.blob_to_kzg_commitment(_blob(8))
    assert not kzg.verify_blob_kzg_proof(blob, other_commitment, proof)


def test_point_eval_under_ceremony_setup():
    blob = _blob(3)
    commitment = kzg.blob_to_kzg_commitment(blob)
    z = (123456789).to_bytes(32, kzg.KZG_ENDIANNESS)
    proof, y = kzg.compute_kzg_proof(blob, z)
    assert kzg.verify_kzg_proof(commitment, z, y, proof)
    wrong_y = ((int.from_bytes(y, "big") + 1) % kzg.BLS_MODULUS).to_bytes(32, "big")
    assert not kzg.verify_kzg_proof(commitment, z, wrong_y, proof)


def test_setup_differs_from_insecure_default():
    """Ceremony and insecure setups must produce different commitments for
    the same nonzero blob (otherwise the override is not taking effect)."""
    blob = _blob(1)
    under_ceremony = kzg.blob_to_kzg_commitment(blob)
    kzg.set_trusted_setup(None)
    under_insecure = kzg.blob_to_kzg_commitment(blob)
    assert under_ceremony != under_insecure
