"""Two-tier fleet: heterogeneous replicas × mesh in one front door.

Contract under test (serve/frontdoor.py + router.py + buckets.py +
prejax.py): a 1-chip and a mesh-sliced replica coexist in one fleet,
each spawned with its OWN forced device count; the router keys on
(compile-shape, mesh-signature) — big requests land on the wide tier,
toy requests on the narrow one, and a replica that would cold-compile a
shape is never picked while a warm sibling is routable; a SIGKILLed
replica's respawned replacement replays ONLY its own mesh's warmup
keys; and the SLO evaluator's second actuator demonstrably grows and
retires replicas.

The spawn-heavy tests share ONE module-scoped heterogeneous fleet (the
SIGKILL test runs last in the module and leaves the fleet healed).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time

import numpy as np
import pytest

from eth_consensus_specs_tpu import obs, prejax
from eth_consensus_specs_tpu.ops import merkle as ops_merkle
from eth_consensus_specs_tpu.parallel import mesh_ops
from eth_consensus_specs_tpu.serve import buckets
from eth_consensus_specs_tpu.serve.config import FrontDoorConfig, ServeConfig
from eth_consensus_specs_tpu.serve.frontdoor import FrontDoor
from eth_consensus_specs_tpu.serve.router import Router

TOY_DEPTH = 5
WIDE_DEPTH = 9  # 512 chunks x max_batch 4 = 2048 clears MESH_SUBTREE_THRESHOLD
WIDE_CHIPS = 2
WIDE_SIG = "cpu1x2"  # make_mesh(2) lays (dp, sp) = (1, 2)


def _counter(name: str) -> float:
    return obs.snapshot()["counters"].get(name, 0)


def _serve_cfg(**kw) -> ServeConfig:
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_ms", 5)
    kw.setdefault("buckets", (1, 2, 4))
    return ServeConfig.from_env(**kw)


def _fd_cfg(**kw) -> FrontDoorConfig:
    kw.setdefault("hedge_ms", 0.0)
    kw.setdefault("probe_interval_ms", 100.0)
    kw.setdefault("slo_shedding", False)
    return FrontDoorConfig.from_env(**kw)


def _trees(n: int, depth: int, seed: int = 0) -> list[np.ndarray]:
    rng = np.random.default_rng(seed)
    cap = 1 << depth
    return [
        rng.integers(0, 256, size=(int(rng.integers(cap // 2 + 1, cap + 1)), 32))
        .astype(np.uint8)
        for _ in range(n)
    ]


def _direct(trees: list, depth: int) -> list:
    return [ops_merkle.merkleize_subtree_device(t, depth) for t in trees]


# ------------------------------------------------------------------ units --


def test_prejax_replica_chips_env_is_authoritative():
    """A spawned replica inherits the parent's XLA_FLAGS; its own chip
    count must REPLACE an inherited device-count flag, not defer."""
    env = {"JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8 --keep"}
    out = prejax.replica_chips_env(2, env)
    assert out == {"XLA_FLAGS": "--keep --xla_force_host_platform_device_count=2"}
    # chips=1 strips the flag entirely (platform default = one device)
    assert prejax.replica_chips_env(1, env) == {"XLA_FLAGS": "--keep"}
    # off-cpu the device count is real hardware: leave it alone
    assert prejax.replica_chips_env(8, {"JAX_PLATFORMS": "tpu"}) == {}


def test_prejax_preparse_chips_replicas_matrix():
    argv = ["x", "--chips", "4", "--replicas=3", "--chips-matrix", "1,8"]
    assert prejax.parse_chips(argv) == 4
    assert prejax.parse_replicas(argv) == 3
    assert prejax.parse_chips_matrix(argv) == (1, 8)
    assert prejax.parse_chips_matrix(["x"]) == ()


def test_profile_key_fns_agree_with_mesh_key_fns():
    """The router predicts sibling compile keys from (shards, sig); the
    profile-form and mesh-form of the LIVE key fns must agree (jaxlint's
    recompile-surface grid runs both — this is the in-tree pin)."""
    cfg = (1, 2, 4, 8)
    for n in (1, 3, 5, 8):
        for depth in (5, 9, 12):
            assert buckets.merkle_many_key(n, depth, cfg, mesh=None) == (
                buckets.merkle_many_key_from_profile(n, depth, cfg, 1, "")
            )
    assert buckets.merkle_many_key_from_profile(3, 9, cfg, 2, WIDE_SIG) == (
        "merkle_many", buckets.mesh_batch_bucket(3, 2, cfg), 9, WIDE_SIG
    )
    for items, lanes in ((1, 3), (5, 8), (9, 64)):
        assert buckets.bls_msm_key(items, lanes, mesh=None) == (
            buckets.bls_msm_key_from_profile(items, lanes, 1, "")
        )


def test_route_wide_policy_matches_mesh_crossover():
    """Big flushes belong on the wide tier exactly when the steady-state
    flush clears the measured mesh crossover; toy flushes never do."""
    assert buckets.route_wide("htr", WIDE_DEPTH, 4)  # 512*4 >= 2048
    assert not buckets.route_wide("htr", TOY_DEPTH, 4)  # 32*4 = 128
    assert not buckets.route_wide("htr", WIDE_DEPTH, 1)  # 512*1 < 2048
    assert buckets.route_wide("bls", 4, 8)  # item-axis sharding: full flush


def test_widen_warm_keys_signs_only_worthwhile_pads():
    cfg = _serve_cfg()
    base = [("merkle_many", b, WIDE_DEPTH) for b in cfg.buckets] + [
        ("merkle_many", b, TOY_DEPTH) for b in cfg.buckets
    ]
    narrow = buckets.widen_warm_keys(base, cfg, 1, "")
    assert narrow == [tuple(k) for k in base]
    wide = buckets.widen_warm_keys(base, cfg, 2, WIDE_SIG)
    signed = [k for k in wide if len(k) == 4]
    assert signed  # the wide depth gets mesh-signed pads...
    assert all(k[3] == WIDE_SIG for k in signed)
    # ...but the toy depth shards never (sub-crossover at every flush)
    assert all(k[2] == WIDE_DEPTH for k in signed if k[0] == "merkle_many")
    assert len(set(wide)) == len(wide)  # deduped


def test_router_tier_warm_and_retire():
    """Pure-router policy: wide requests land on the wide tier, the
    warm-cache map vetoes cold candidates while a warm sibling exists,
    retired slots never route, and with no profiles the original
    affinity walk is unchanged."""
    r = Router(3)
    r.set_profile(0, chips=1, signature="", warm_keys=[("merkle_many", 2, TOY_DEPTH)])
    r.set_profile(1, chips=WIDE_CHIPS, signature=WIDE_SIG,
                  warm_keys=[("merkle_many", 4, WIDE_DEPTH, WIDE_SIG),
                             ("merkle_many", 2, WIDE_DEPTH)])
    r.set_profile(2, chips=1, signature="", warm_keys=[("merkle_many", 2, TOY_DEPTH)])
    for _ in range(8):
        assert r.pick(("merkle_many", WIDE_DEPTH), wide=True) == 1
        assert r.pick(("merkle_many", TOY_DEPTH), wide=False) in (0, 2)
    # warm veto: the wide replica is the ONLY one warm for the wide
    # shape, so even with NO tier preference the cold candidates lose
    assert r.pick(("merkle_many", WIDE_DEPTH), wide=None) == 1
    r.set_retired(1, True)
    assert r.pick(("merkle_many", WIDE_DEPTH), wide=True) != 1
    r.set_retired(1, False)
    assert r.pick(("merkle_many", WIDE_DEPTH), wide=True) == 1
    idx = r.add_replica()
    assert idx == 3 and len(r) == 4
    snap = r.snapshot()
    assert snap[1]["chips"] == WIDE_CHIPS and snap[1]["signature"] == WIDE_SIG
    assert snap[1]["picks"] > 0


def test_frontdoor_config_fleet_knobs(monkeypatch):
    monkeypatch.setenv("ETH_SPECS_SERVE_CHIPS_MATRIX", "1,8")
    monkeypatch.setenv("ETH_SPECS_SERVE_DOWN_COOLDOWN_MS", "250")
    monkeypatch.setenv("ETH_SPECS_SERVE_DRAINING_TTL_S", "2.5")
    monkeypatch.setenv("ETH_SPECS_SERVE_AUTOSCALE", "1")
    monkeypatch.setenv("ETH_SPECS_SERVE_MAX_REPLICAS", "5")
    monkeypatch.setenv("ETH_SPECS_SERVE_GROW_WINDOWS", "2")
    monkeypatch.setenv("ETH_SPECS_SERVE_RETIRE_WINDOWS", "7")
    monkeypatch.setenv("ETH_SPECS_SERVE_SCALE_COOLDOWN_S", "0.5")
    monkeypatch.setenv("ETH_SPECS_SERVE_MIN_REPLICAS", "2")
    cfg = FrontDoorConfig.from_env()
    assert cfg.chips_matrix == (1, 8)
    assert [cfg.chips_for(i) for i in range(4)] == [1, 8, 1, 8]
    assert cfg.down_cooldown_s == 0.25
    assert cfg.draining_ttl_s == 2.5
    assert cfg.autoscale and cfg.max_replicas == 5 and cfg.min_replicas == 2
    assert cfg.grow_windows == 2 and cfg.retire_windows == 7
    assert cfg.scale_cooldown_s == 0.5
    assert FrontDoorConfig().chips_for(3, 4) == 4  # empty matrix: default


def test_perf_track_ingests_fleet_matrix(tmp_path):
    """The fleet matrix rides the perf trajectory as platform-aware
    secondaries: cells are advisories, never cross-platform gates."""
    import importlib.util
    import json

    spec = importlib.util.spec_from_file_location(
        "perf_track",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "perf_track.py"),
    )
    pt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pt)
    for rnd, factor in ((1, 1.6), (2, 0.4)):
        (tmp_path / f"BENCH_r{rnd:02d}.json").write_text(json.dumps({
            "rc": 0,
            "parsed": {
                "metric": "hashes_per_sec", "value": 100.0, "platform": "cpu",
                "fleet": {"grown": 1, "retired": 1,
                          "r3x8_rps": 40.0 * factor, "r3x8_scaling": factor},
            },
        }))
    entries = pt.load_rounds(str(tmp_path))
    assert entries[0]["metrics"]["fleet_r3x8_scaling"] == 1.6
    assert entries[0]["metrics"]["fleet_r3x8_rps"] == 64.0
    assert "fleet_grown" not in entries[0]["metrics"]  # event count, not perf
    regressions, advisories = pt.compare(entries, threshold=0.30, strict=False)
    assert not regressions
    assert any(a["metric"] == "fleet_r3x8_scaling" for a in advisories)


# ------------------------------------------------- heterogeneous fleet --


@pytest.fixture(scope="module")
def het_fd(tmp_path_factory):
    """One heterogeneous fleet for the spawn-heavy tests: a 1-chip and a
    2-chip replica, each pre-warmed for both depths under ITS profile."""
    tmp = tmp_path_factory.mktemp("fleet")
    warm = [("merkle_many", b, d) for d in (TOY_DEPTH, WIDE_DEPTH) for b in (1, 2, 4)]
    fd = FrontDoor(
        replicas=2,
        chips=[1, WIDE_CHIPS],
        config=_serve_cfg(),
        fd_config=_fd_cfg(),
        warmup_path=str(tmp / "warmup.jsonl"),
        warm_keys=warm,
        name="fleet-test",
    )
    try:
        yield fd
    finally:
        fd.close()


def _wait_probed(fd, n: int, timeout: float = 20.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if sum(1 for s in fd.replica_stats() if s is not None) >= n:
            return
        time.sleep(0.1)
    raise AssertionError("fleet never fully probed")


def test_het_profiles_parity_and_zero_cold_compiles(het_fd):
    """Both tiers report their mesh profile; toy and wide requests are
    bit-identical to direct ops calls; nothing cold-compiles after
    ready on either tier."""
    fd = het_fd
    profiles = fd.replica_profiles()
    assert profiles[0]["signature"] == "" and profiles[0]["chips"] == 1
    assert profiles[1]["signature"] == WIDE_SIG
    assert profiles[1]["shards"] == WIDE_CHIPS
    toy, wide = _trees(6, TOY_DEPTH, 1), _trees(6, WIDE_DEPTH, 2)
    futs = [fd.submit_hash_tree_root(t) for t in toy + wide]
    got = [f.result(timeout=120) for f in futs]
    assert got == _direct(toy, TOY_DEPTH) + _direct(wide, WIDE_DEPTH)
    _wait_probed(fd, 2)
    time.sleep(fd.fdcfg.probe_interval_s * 3)
    for s in fd.replica_stats():
        assert s is not None and s["compiles_after_ready"] == 0


def test_big_requests_land_on_the_wide_replica(het_fd):
    """Signature-aware routing: wide-classified requests go to the mesh
    tier (frontdoor.route.affinity/mesh_affinity assert), toy requests
    to the narrow tier — observable per-replica via router picks."""
    fd = het_fd
    before = {r["signature"]: r["picks"] for r in fd.router.snapshot()}
    mesh_aff0 = _counter("frontdoor.route.mesh_affinity")
    aff0 = _counter("frontdoor.route.affinity")
    wide = _trees(8, WIDE_DEPTH, 3)
    got = [fd.submit_hash_tree_root(t).result(timeout=120) for t in wide]
    assert got == _direct(wide, WIDE_DEPTH)
    after = {r["signature"]: r["picks"] for r in fd.router.snapshot()}
    assert after[WIDE_SIG] - before[WIDE_SIG] >= len(wide)
    assert after[""] == before[""]  # narrow tier saw none of them
    assert _counter("frontdoor.route.mesh_affinity") - mesh_aff0 >= len(wide)
    assert _counter("frontdoor.route.affinity") >= aff0  # monotone sanity
    toy = _trees(4, TOY_DEPTH, 4)
    got = [fd.submit_hash_tree_root(t).result(timeout=120) for t in toy]
    assert got == _direct(toy, TOY_DEPTH)
    final = {r["signature"]: r["picks"] for r in fd.router.snapshot()}
    assert final[""] - after[""] >= len(toy)  # toys stayed narrow


def test_sigkill_respawn_replays_only_its_own_keys(het_fd):
    """SIGKILL the wide replica mid-load: zero requests lost, bit
    parity held, and the respawned replacement replays ONLY its own
    mesh-signed warmup keys (runs last: leaves the fleet healed)."""
    fd = het_fd
    wide = _trees(10, WIDE_DEPTH, 6)
    want = _direct(wide, WIDE_DEPTH)
    victim_pid = fd._procs[1].pid
    results: list = [None] * len(wide)

    def submit_all():
        for i, t in enumerate(wide):
            results[i] = fd.submit_hash_tree_root(t).result(timeout=180)

    th = threading.Thread(target=submit_all, daemon=True)
    th.start()
    time.sleep(0.15)  # let a few land, then kill mid-load
    os.kill(victim_pid, signal.SIGKILL)
    th.join(timeout=240)
    assert not th.is_alive()
    assert results == want  # zero lost, bit-identical through the failover
    # wait for the supervised respawn + its profile reinstall
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        proc = fd._procs[1]
        if proc is not None and proc.is_alive() and proc.pid != victim_pid:
            if (fd.replica_profiles()[1] or {}).get("warm_keys"):
                break
        time.sleep(0.2)
    profile = fd.replica_profiles()[1]
    assert profile and profile["signature"] == WIDE_SIG
    signed = [k for k in profile["warm_keys"] if any(isinstance(d, str) for d in k[1:])]
    assert signed  # it replayed its own mesh-signed keys...
    assert all(WIDE_SIG in k for k in signed)  # ...and ONLY its own
    assert _counter("frontdoor.replicas_replaced") >= 1
    # the replacement is warm: traffic through it pays no cold compile
    time.sleep(fd.fdcfg.probe_interval_s * 3)
    more = _trees(4, WIDE_DEPTH, 8)
    got = [fd.submit_hash_tree_root(t).result(timeout=120) for t in more]
    assert got == _direct(more, WIDE_DEPTH)
    time.sleep(fd.fdcfg.probe_interval_s * 3)
    stats = fd.replica_stats()
    assert stats[1] is not None and stats[1]["compiles_after_ready"] == 0


def test_autoscaler_grows_then_retires(tmp_path, monkeypatch):
    """The SLO evaluator's second actuator end to end: a sustained
    (forced) p99 breach grows a pre-warmed replica; a sustained idle
    window retires it through the zero-shed drain rollover."""
    monkeypatch.setenv("ETH_SPECS_SLO_WAIT_P99_MS", "0.001")
    fd = FrontDoor(
        replicas=1,
        chips=[1],
        config=_serve_cfg(),
        fd_config=_fd_cfg(
            probe_interval_ms=80.0,
            slo_shedding=False,  # isolate the SECOND actuator
            autoscale=True,
            min_replicas=1,
            max_replicas=2,
            grow_windows=1,
            retire_windows=2,
            scale_cooldown_s=0.3,
        ),
        warmup_path=str(tmp_path / "warmup.jsonl"),
        warm_keys=[("merkle_many", b, TOY_DEPTH) for b in (1, 2, 4)],
        name="fleet-scale",
    )
    try:
        toy = _trees(4, TOY_DEPTH, 9)
        want = _direct(toy, TOY_DEPTH)
        grown0 = _counter("frontdoor.replicas_grown")
        retired0 = _counter("frontdoor.replicas_retired")
        deadline = time.monotonic() + 60
        while _counter("frontdoor.replicas_grown") == grown0:
            assert time.monotonic() < deadline, "autoscaler never grew"
            # every window carries waits, every wait breaches 0.001ms
            assert [fd.submit_hash_tree_root(t).result(timeout=60) for t in toy] == want
            time.sleep(fd.fdcfg.probe_interval_s)
        assert len(fd.live_replicas()) == 2
        monkeypatch.setenv("ETH_SPECS_SLO_WAIT_P99_MS", "250")
        deadline = time.monotonic() + 60
        while _counter("frontdoor.replicas_retired") == retired0:
            assert time.monotonic() < deadline, "autoscaler never retired"
            time.sleep(fd.fdcfg.probe_interval_s)  # idle: no traffic
        assert len(fd.live_replicas()) == 1
        # the survivor still serves, bit-identically
        assert [fd.submit_hash_tree_root(t).result(timeout=60) for t in toy] == want
    finally:
        fd.close()