"""Lazy-reduction limb kernel (ops/lazy_limbs.py) vs plain python ints.

Randomized add/sub/mul chains with interleaved lazy accumulation, checking
both the value (mod p) and the static bound discipline (limbs must stay
under the tracked bound; values under the tracked value bound)."""

import random

import numpy as np

from eth_consensus_specs_tpu.crypto.fields import P
from eth_consensus_specs_tpu.ops import lazy_limbs as lz

rng = random.Random(99)


def _wrap(x: int):
    return lz.lf(np.asarray(lz.to_mont(x)), val=P - 1), x


def _value(e) -> int:
    return lz.from_mont_int(np.asarray(lz.norm(e).v))


def test_add_sub_mul_chain_matches_ints():
    for _ in range(5):
        a_int = rng.randrange(P)
        b_int = rng.randrange(P)
        c_int = rng.randrange(P)
        a, _ = _wrap(a_int)
        b, _ = _wrap(b_int)
        c, _ = _wrap(c_int)
        # lazy chain: ((a+b)*c - b + a) * (a - c)
        t = lz.mul(lz.add(a, b), c)
        t = lz.add(lz.sub(t, b), a)
        u = lz.sub(a, c)
        out = lz.mul(t, u)
        R = lz.R_INT
        am, bm, cm = (v * R % P for v in (a_int, b_int, c_int))
        tm = ((am + bm) * cm * pow(R, -1, P)) % P
        tm = (tm - bm + am) % P
        um = (am - cm) % P
        outm = (tm * um * pow(R, -1, P)) % P
        got = lz.limbs_to_int(np.asarray(lz.norm(out).v)) % P
        assert got == outm


def test_bounds_are_respected():
    a, a_int = _wrap(rng.randrange(P))
    b, b_int = _wrap(rng.randrange(P))
    acc = a
    for _ in range(6):
        acc = lz.add(acc, b)
    arr = np.asarray(acc.v)
    assert int(arr.max()) <= acc.max
    assert lz.from_mont_int(np.asarray(lz.norm(acc).v)) == (a_int + 6 * b_int) % P


def test_shrink_reduces_below_2p():
    a, a_int = _wrap(P - 3)
    acc = a
    for _ in range(20):
        acc = lz.add(acc, a)
    red = lz.shrink(acc)
    assert red.val < 2 * P
    assert lz.from_mont_int(np.asarray(red.v)) == (21 * a_int) % P


def test_sub_borrow_free_on_lazy_subtrahend():
    a, a_int = _wrap(5)
    b, b_int = _wrap(P - 7)
    lazy_b = lz.add(lz.add(b, b), b)  # 3b, lazy limbs
    out = lz.sub(a, lazy_b)
    got = lz.from_mont_int(np.asarray(lz.norm(out).v))
    assert got == (a_int - 3 * b_int) % P


def test_sub_of_deep_lazy_sum_auto_shrinks_under_the_lend_cap():
    """A 15-term canonical sum has val = 15p (under sub's 16p shrink
    trigger) but max ~15*2^26 — a fat cover for THAT would break the
    2^30 lend cap. sub must auto-shrink the subtrahend and stay exact,
    not crash on a chain the lazy design explicitly allows."""
    x, x_int = _wrap(11)
    b, b_int = _wrap(P - 13)
    acc = b
    for _ in range(14):
        acc = lz.add(acc, b)
    assert acc.val < 16 * P, "repro needs the val-triggered shrink to skip"
    assert acc.max + 3 * (1 << lz.LIMB_BITS) > lz._LEND_LIMB_CAP
    out = lz.sub(x, acc)
    assert out.max <= lz.NORM_MAX + lz._LEND_LIMB_CAP
    assert int(np.asarray(out.v).max()) <= out.max
    assert lz.from_mont_int(np.asarray(lz.norm(out).v)) == (x_int - 15 * b_int) % P


def test_fat_p_encodings():
    for bound in (1 << 26, 1 << 28, 1 << 30, (1 << 30) + 12345):
        fat, fat_max, c = lz._fat_p(bound, bound >> 9)
        assert lz.limbs_to_int(fat) == 0 or True
        total = sum(int(fat[i]) << (lz.LIMB_BITS * i) for i in range(lz.N_LIMBS))
        assert total % P == 0 and total // P == c
        assert all(int(fat[i]) >= bound for i in range(lz.N_LIMBS - 1))


def test_claimed_bounds_match_execution_through_dbl_chains():
    """Runtime half of the rangelint lazy-bound audit (ISSUE 10): the
    audit proves claimed max_limb == inferred interval abstractly; here
    a dbl chain from the p-1 boundary value runs up to the add-shrink
    threshold and at EVERY step the claim follows the exact doubling
    algebra while the executed limbs stay under it."""
    a, a_int = _wrap(P - 1)  # the declared p-1 domain corner
    b, b_int = _wrap(rng.randrange(P))
    acc = a
    expect_max = lz.NORM_MAX
    steps = 0
    while 2 * acc.val < lz.R_INT // 4:  # the add() reduction threshold
        acc = lz.dbl(acc)
        steps += 1
        expect_max *= 2
        assert acc.max == expect_max, "dbl's claim IS the doubling algebra"
        assert int(np.asarray(acc.v).max()) <= acc.max
    assert steps >= 5, "the lazy window shrank — the audit chains are stale"
    out = lz.add(acc, b)  # crossing the threshold triggers the shrink
    assert int(np.asarray(out.v).max()) <= out.max
    assert _value(out) == ((1 << steps) * a_int + b_int) % P
