"""Gloas payload-status-aware fork choice
(reference: specs/gloas/fork-choice.md and
eth2spec/test/gloas/fork_choice/)."""

from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    build_signed_execution_payload_envelope,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import (
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.fork_choice import (
    get_genesis_forkchoice_store,
    tick_and_add_block,
)


def _add_block(spec, store, working_state):
    block = build_empty_block_for_next_slot(spec, working_state)
    signed = state_transition_and_sign_block(spec, working_state, block)
    root = tick_and_add_block(spec, store, signed)
    return root, signed


@with_phases(["gloas"])
@spec_state_test
def test_store_tracks_payload_state_maps(spec, state):
    store, anchor = get_genesis_forkchoice_store(spec, state)
    assert bytes(anchor) in store.execution_payload_states
    assert bytes(anchor) in store.ptc_vote
    working = state.copy()
    root, _ = _add_block(spec, store, working)
    assert root in store.ptc_vote
    assert store.ptc_vote[root] == [False] * spec.PTC_SIZE
    # no envelope imported yet -> no payload state
    assert root not in store.execution_payload_states


@with_phases(["gloas"])
@spec_state_test
def test_head_empty_until_payload_reveal(spec, state):
    store, _ = get_genesis_forkchoice_store(spec, state)
    working = state.copy()
    root, _ = _add_block(spec, store, working)
    head = spec.get_head(store)
    assert bytes(head.root) == root
    assert head.payload_status == spec.PAYLOAD_STATUS_EMPTY

    env = build_signed_execution_payload_envelope(spec, working)
    spec.on_execution_payload(store, env)
    assert root in store.execution_payload_states
    # FULL branch now exists as a child of the PENDING node
    node = spec.ForkChoiceNode(root=root, payload_status=spec.PAYLOAD_STATUS_PENDING)
    children = spec.get_node_children(store, spec.get_filtered_block_tree(store), node)
    statuses = {c.payload_status for c in children}
    assert statuses == {spec.PAYLOAD_STATUS_EMPTY, spec.PAYLOAD_STATUS_FULL}


@with_phases(["gloas"])
@spec_state_test
def test_on_execution_payload_unknown_block_invalid(spec, state):
    store, _ = get_genesis_forkchoice_store(spec, state)
    working = state.copy()
    _add_block(spec, store, working)
    env = build_signed_execution_payload_envelope(spec, working)
    env.message.beacon_block_root = b"\x13" * 32
    expect_assertion_error(lambda: spec.on_execution_payload(store, env))


@with_phases(["gloas"])
@spec_state_test
def test_ptc_votes_make_payload_timely(spec, state):
    store, _ = get_genesis_forkchoice_store(spec, state)
    working = state.copy()
    root, _ = _add_block(spec, store, working)
    env = build_signed_execution_payload_envelope(spec, working)
    spec.on_execution_payload(store, env)
    assert not spec.is_payload_timely(store, root)

    block_state = store.block_states[root]
    ptc = spec.get_ptc(block_state, int(block_state.slot))
    data = spec.PayloadAttestationData(
        beacon_block_root=root,
        slot=int(block_state.slot),
        payload_present=True,
        blob_data_available=True,
    )
    for v in dict.fromkeys(ptc):  # unique validators, preserve order
        msg = spec.PayloadAttestationMessage(validator_index=v, data=data)
        spec.on_payload_attestation_message(store, msg, is_from_block=True)
    assert spec.is_payload_timely(store, root)


@with_phases(["gloas"])
@spec_state_test
def test_ptc_message_from_non_member_invalid(spec, state):
    store, _ = get_genesis_forkchoice_store(spec, state)
    working = state.copy()
    root, _ = _add_block(spec, store, working)
    block_state = store.block_states[root]
    ptc = spec.get_ptc(block_state, int(block_state.slot))
    outsider = next(i for i in range(len(state.validators)) if i not in ptc)
    data = spec.PayloadAttestationData(
        beacon_block_root=root,
        slot=int(block_state.slot),
        payload_present=True,
        blob_data_available=True,
    )
    msg = spec.PayloadAttestationMessage(validator_index=outsider, data=data)
    expect_assertion_error(
        lambda: spec.on_payload_attestation_message(store, msg, is_from_block=True)
    )


@with_phases(["gloas"])
@spec_state_test
def test_chain_over_full_parent(spec, state):
    """Build -> reveal -> build: the second block chains on the FULL branch
    and the head follows it."""
    store, _ = get_genesis_forkchoice_store(spec, state)
    working = state.copy()
    r1, _ = _add_block(spec, store, working)
    env = build_signed_execution_payload_envelope(spec, working)
    spec.on_execution_payload(store, env)
    spec.process_execution_payload(working, env, spec.EXECUTION_ENGINE)

    r2, blk2 = _add_block(spec, store, working)
    assert spec.get_parent_payload_status(store, blk2.message) == spec.PAYLOAD_STATUS_FULL
    head = spec.get_head(store)
    assert bytes(head.root) == r2


@with_phases(["gloas"])
@spec_state_test
def test_chain_over_empty_parent(spec, state):
    """Without a payload reveal the child must chain the grandparent hash
    (EMPTY branch) and on_block accepts it from the consensus state."""
    store, _ = get_genesis_forkchoice_store(spec, state)
    working = state.copy()
    r1, _ = _add_block(spec, store, working)
    # no envelope: next block sees parent EMPTY, latest_block_hash unchanged
    r2, blk2 = _add_block(spec, store, working)
    assert spec.get_parent_payload_status(store, blk2.message) == spec.PAYLOAD_STATUS_EMPTY
    head = spec.get_head(store)
    assert bytes(head.root) == r2


@with_phases(["gloas"])
@spec_state_test
def test_get_ancestor_carries_payload_status(spec, state):
    store, _ = get_genesis_forkchoice_store(spec, state)
    working = state.copy()
    r1, _ = _add_block(spec, store, working)
    env = build_signed_execution_payload_envelope(spec, working)
    spec.on_execution_payload(store, env)
    spec.process_execution_payload(working, env, spec.EXECUTION_ENGINE)
    r2, _ = _add_block(spec, store, working)

    node = spec.get_ancestor(store, r2, int(store.blocks[r1].slot))
    assert bytes(node.root) == r1
    assert node.payload_status == spec.PAYLOAD_STATUS_FULL
    # at its own slot: PENDING
    node = spec.get_ancestor(store, r2, int(store.blocks[r2].slot))
    assert bytes(node.root) == r2
    assert node.payload_status == spec.PAYLOAD_STATUS_PENDING
