"""Builder pending-payment settlement and payment weighting
(reference: specs/gloas/beacon-chain.md:698-717, :1093-1141, :624-634)."""

from eth_consensus_specs_tpu.test_infra.attestations import (
    get_valid_attestation,
)
from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.state import next_epoch, next_slot, next_slots


def _seed_payment(spec, state, slot: int, amount: int, current_epoch: bool = True):
    index = (spec.SLOTS_PER_EPOCH if current_epoch else 0) + slot % spec.SLOTS_PER_EPOCH
    payment = state.builder_pending_payments[index].copy()
    payment.withdrawal.amount = amount
    payment.withdrawal.builder_index = 0
    payment.withdrawal.fee_recipient = b"\x77" * 20
    payment.withdrawal.withdrawable_epoch = spec.FAR_FUTURE_EPOCH
    state.builder_pending_payments[index] = payment
    return index


@with_phases(["gloas"])
@spec_state_test
def test_quorum_threshold_value(spec, state):
    per_slot = spec.get_total_active_balance(state) // spec.SLOTS_PER_EPOCH
    expected = per_slot * spec.BUILDER_PAYMENT_THRESHOLD_NUMERATOR
    expected //= spec.BUILDER_PAYMENT_THRESHOLD_DENOMINATOR
    assert spec.get_builder_payment_quorum_threshold(state) == expected


@with_phases(["gloas"])
@spec_state_test
def test_above_quorum_payment_settles_at_epoch(spec, state):
    quorum = spec.get_builder_payment_quorum_threshold(state)
    idx = _seed_payment(spec, state, 0, spec.EFFECTIVE_BALANCE_INCREMENT, current_epoch=False)
    payment = state.builder_pending_payments[idx].copy()
    payment.weight = quorum + 1
    state.builder_pending_payments[idx] = payment

    spec.process_builder_pending_payments(state)
    assert len(state.builder_pending_withdrawals) == 1
    assert int(state.builder_pending_withdrawals[0].amount) == spec.EFFECTIVE_BALANCE_INCREMENT
    # window shifted: last epoch's boxes are all empty defaults
    for p in list(state.builder_pending_payments)[spec.SLOTS_PER_EPOCH :]:
        assert int(p.withdrawal.amount) == 0


@with_phases(["gloas"])
@spec_state_test
def test_below_quorum_payment_dropped(spec, state):
    quorum = spec.get_builder_payment_quorum_threshold(state)
    idx = _seed_payment(spec, state, 0, spec.EFFECTIVE_BALANCE_INCREMENT, current_epoch=False)
    payment = state.builder_pending_payments[idx].copy()
    payment.weight = quorum  # strictly-greater required
    state.builder_pending_payments[idx] = payment

    spec.process_builder_pending_payments(state)
    assert len(state.builder_pending_withdrawals) == 0


@with_phases(["gloas"])
@spec_state_test
def test_same_slot_attestation_weights_payment(spec, state):
    """Attesters for the current slot's block add their effective balance
    to that slot's pending payment (:1119-1127). Same-slot requires a real
    block at the attested slot (root differs from the previous slot)."""
    from eth_consensus_specs_tpu.test_infra.block import (
        build_empty_block_for_next_slot,
        state_transition_and_sign_block,
    )

    block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, block)
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slot(spec, state)  # satisfy MIN_ATTESTATION_INCLUSION_DELAY
    assert spec.is_attestation_same_slot(state, attestation.data)

    slot = int(attestation.data.slot)
    idx = _seed_payment(spec, state, slot, spec.EFFECTIVE_BALANCE_INCREMENT)
    before = int(state.builder_pending_payments[idx].weight)

    spec.process_attestation(state, attestation)
    after = int(state.builder_pending_payments[idx].weight)
    attesters = spec.get_attesting_indices(state, attestation)
    expected = sum(int(state.validators[i].effective_balance) for i in attesters)
    assert after - before == expected


@with_phases(["gloas"])
@spec_state_test
def test_attestation_without_payment_adds_no_weight(spec, state):
    from eth_consensus_specs_tpu.test_infra.block import (
        build_empty_block_for_next_slot,
        state_transition_and_sign_block,
    )

    block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, block)
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slot(spec, state)
    slot = int(attestation.data.slot)
    idx = spec.SLOTS_PER_EPOCH + slot % spec.SLOTS_PER_EPOCH
    spec.process_attestation(state, attestation)
    assert int(state.builder_pending_payments[idx].weight) == 0
