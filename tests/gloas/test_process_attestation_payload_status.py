"""Gloas attestation payload-status families (reference analogue:
test/gloas/block_processing/test_process_attestation.py — the 13-variant
data.index-as-payload-availability file; spec: specs/gloas/beacon-chain.md
process_attestation / get_attestation_participation_flag_indices)."""

from eth_consensus_specs_tpu.test_infra.attestations import get_valid_attestation
from eth_consensus_specs_tpu.test_infra.context import (
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.state import next_slots

GLOAS = ["gloas"]


def _aged_attestation(spec, state, index_value=0, available=None):
    next_slots(spec, state, 5)
    attestation = get_valid_attestation(spec, state, signed=True)
    slot_index = int(attestation.data.slot) % int(spec.SLOTS_PER_HISTORICAL_ROOT)
    if available is not None:
        state.execution_payload_availability[slot_index] = available
    attestation.data.index = index_value
    next_slots(spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))
    return attestation


@with_phases(GLOAS)
@spec_state_test
def test_invalid_index_too_high(spec, state):
    attestation = _aged_attestation(spec, state)
    attestation.data.index = 2
    expect_assertion_error(lambda: spec.process_attestation(state, attestation))


@with_phases(GLOAS)
@spec_state_test
def test_index_zero_previous_slot_payload_absent(spec, state):
    """index=0 (payload absent) matches an availability bit of 0."""
    attestation = _aged_attestation(spec, state, index_value=0, available=0)
    spec.process_attestation(state, attestation)
    participation = (
        state.current_epoch_participation
        if attestation.data.target.epoch == spec.get_current_epoch(state)
        else state.previous_epoch_participation
    )
    attesters = spec.get_attesting_indices(state, attestation)
    assert all(
        spec.has_flag(participation[i], spec.TIMELY_TARGET_FLAG_INDEX)
        for i in attesters
    )


@with_phases(GLOAS)
@spec_state_test
def test_index_one_previous_slot_payload_present(spec, state):
    attestation = _aged_attestation(spec, state, index_value=1, available=1)
    spec.process_attestation(state, attestation)


@with_phases(GLOAS)
@spec_state_test
def test_mismatched_payload_status_no_head_flag(spec, state):
    """index disagreeing with the availability bit: attestation is still
    VALID (target counts) but earns no head credit."""
    attestation = _aged_attestation(spec, state, index_value=1, available=0)
    spec.process_attestation(state, attestation)
    participation = (
        state.current_epoch_participation
        if attestation.data.target.epoch == spec.get_current_epoch(state)
        else state.previous_epoch_participation
    )
    attesters = spec.get_attesting_indices(state, attestation)
    assert all(
        not spec.has_flag(participation[i], spec.TIMELY_HEAD_FLAG_INDEX)
        for i in attesters
    )


@with_phases(GLOAS)
@spec_state_test
def test_matching_payload_gets_head_flag(spec, state):
    """index agreeing with the availability bit + timely inclusion + right
    head root earns the head flag."""
    attestation = _aged_attestation(spec, state, index_value=1, available=1)
    spec.process_attestation(state, attestation)
    participation = (
        state.current_epoch_participation
        if attestation.data.target.epoch == spec.get_current_epoch(state)
        else state.previous_epoch_participation
    )
    attesters = spec.get_attesting_indices(state, attestation)
    assert all(
        spec.has_flag(participation[i], spec.TIMELY_HEAD_FLAG_INDEX)
        for i in attesters
    )


def _same_slot_attestation(spec, state, index_value):
    """An attestation voting for the block PROPOSED AT its own slot: apply a
    real block so the slot's root differs from its parent's, then attest to
    it (is_attestation_same_slot, specs/gloas/beacon-chain.md:362-374)."""
    from eth_consensus_specs_tpu.test_infra.block import apply_empty_block

    next_slots(spec, state, 4)
    apply_empty_block(spec, state, int(state.slot) + 1)
    attestation = get_valid_attestation(spec, state, slot=int(state.slot), signed=True)
    attestation.data.index = index_value
    next_slots(spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))
    assert spec.is_attestation_same_slot(state, attestation.data)
    return attestation


@with_phases(GLOAS)
@spec_state_test
def test_same_slot_attestation_index_zero_valid(spec, state):
    attestation = _same_slot_attestation(spec, state, index_value=0)
    spec.process_attestation(state, attestation)


@with_phases(GLOAS)
@spec_state_test
def test_same_slot_attestation_index_one_invalid(spec, state):
    """Same-slot attestations must carry index 0 — the payload for that
    slot can't be known at attestation time."""
    attestation = _same_slot_attestation(spec, state, index_value=1)
    slot_index = int(attestation.data.slot) % int(spec.SLOTS_PER_HISTORICAL_ROOT)
    state.execution_payload_availability[slot_index] = 1
    expect_assertion_error(lambda: spec.process_attestation(state, attestation))
