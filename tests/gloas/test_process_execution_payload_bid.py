"""Execution payload bid processing (EIP-7732)
(reference: specs/gloas/beacon-chain.md:944-1007 and
eth2spec/test/gloas/block_processing/test_process_execution_payload_bid.py)."""

from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    build_empty_signed_execution_payload_bid,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import (
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.state import next_slot
from eth_consensus_specs_tpu.utils import bls


def _prepared_block(spec, state):
    """Block for the next slot with a fresh self-build bid; state advanced
    to the block's slot so process_execution_payload_bid can run directly."""
    block = build_empty_block_for_next_slot(spec, state)
    spec.process_slots(state, block.slot)
    return block


def _make_builder(spec, state, index: int, balance: int):
    creds = bytes(spec.BUILDER_WITHDRAWAL_PREFIX) + b"\x00" * 11 + b"\x42" * 20
    state.validators[index].withdrawal_credentials = creds
    state.balances[index] = balance
    state.validators[index].effective_balance = min(
        balance - balance % spec.EFFECTIVE_BALANCE_INCREMENT, spec.MAX_EFFECTIVE_BALANCE_ELECTRA
    )


@with_phases(["gloas"])
@spec_state_test
def test_self_build_zero_bid(spec, state):
    block = _prepared_block(spec, state)
    spec.process_execution_payload_bid(state, block)
    bid = block.body.signed_execution_payload_bid.message
    assert state.latest_execution_payload_bid == bid


@with_phases(["gloas"])
@spec_state_test
def test_self_build_nonzero_value_invalid(spec, state):
    block = _prepared_block(spec, state)
    block.body.signed_execution_payload_bid.message.value = 1
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(["gloas"])
@spec_state_test
def test_self_build_wrong_signature_invalid(spec, state):
    block = _prepared_block(spec, state)
    block.body.signed_execution_payload_bid.signature = b"\x11" * 96
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(["gloas"])
@spec_state_test
def test_builder_bid_records_pending_payment(spec, state):
    block = _prepared_block(spec, state)
    proposer = int(block.proposer_index)
    builder_index = (proposer + 1) % len(state.validators)
    _make_builder(spec, state, builder_index, 2 * spec.MIN_ACTIVATION_BALANCE)

    bid = block.body.signed_execution_payload_bid.message
    bid.builder_index = builder_index
    bid.value = spec.EFFECTIVE_BALANCE_INCREMENT
    signed = spec.SignedExecutionPayloadBid(message=bid, signature=b"\x00" * 96)
    # bls is off in this suite: Verify stubs true, matching the reference's
    # bls_switch convention for non-@always_bls tests
    block.body.signed_execution_payload_bid = signed

    spec.process_execution_payload_bid(state, block)
    payment = state.builder_pending_payments[
        spec.SLOTS_PER_EPOCH + int(bid.slot) % spec.SLOTS_PER_EPOCH
    ]
    assert int(payment.withdrawal.amount) == int(bid.value)
    assert int(payment.withdrawal.builder_index) == builder_index
    assert int(payment.weight) == 0


@with_phases(["gloas"])
@spec_state_test
def test_builder_bid_without_builder_credential_invalid(spec, state):
    block = _prepared_block(spec, state)
    proposer = int(block.proposer_index)
    builder_index = (proposer + 1) % len(state.validators)
    # no 0x03 credential installed
    bid = block.body.signed_execution_payload_bid.message
    bid.builder_index = builder_index
    bid.value = 0
    block.body.signed_execution_payload_bid = spec.SignedExecutionPayloadBid(
        message=bid, signature=b"\x00" * 96
    )
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(["gloas"])
@spec_state_test
def test_builder_bid_insufficient_balance_invalid(spec, state):
    block = _prepared_block(spec, state)
    proposer = int(block.proposer_index)
    builder_index = (proposer + 1) % len(state.validators)
    _make_builder(spec, state, builder_index, spec.MIN_ACTIVATION_BALANCE)  # no excess

    bid = block.body.signed_execution_payload_bid.message
    bid.builder_index = builder_index
    bid.value = spec.EFFECTIVE_BALANCE_INCREMENT
    block.body.signed_execution_payload_bid = spec.SignedExecutionPayloadBid(
        message=bid, signature=b"\x00" * 96
    )
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(["gloas"])
@spec_state_test
def test_slashed_builder_invalid(spec, state):
    block = _prepared_block(spec, state)
    proposer = int(block.proposer_index)
    builder_index = (proposer + 1) % len(state.validators)
    _make_builder(spec, state, builder_index, 2 * spec.MIN_ACTIVATION_BALANCE)
    state.validators[builder_index].slashed = True

    bid = block.body.signed_execution_payload_bid.message
    bid.builder_index = builder_index
    block.body.signed_execution_payload_bid = spec.SignedExecutionPayloadBid(
        message=bid, signature=b"\x00" * 96
    )
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(["gloas"])
@spec_state_test
def test_bid_wrong_parent_hash_invalid(spec, state):
    block = _prepared_block(spec, state)
    block.body.signed_execution_payload_bid.message.parent_block_hash = b"\x13" * 32
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(["gloas"])
@spec_state_test
def test_bid_wrong_slot_invalid(spec, state):
    block = _prepared_block(spec, state)
    block.body.signed_execution_payload_bid.message.slot = int(block.slot) + 1
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))
