"""Execution payload bid processing (EIP-7732)
(reference: specs/gloas/beacon-chain.md:944-1007 and
eth2spec/test/gloas/block_processing/test_process_execution_payload_bid.py)."""

from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    build_empty_signed_execution_payload_bid,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import (
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.state import next_slot
from eth_consensus_specs_tpu.utils import bls


def _prepared_block(spec, state):
    """Block for the next slot with a fresh self-build bid; state advanced
    to the block's slot so process_execution_payload_bid can run directly."""
    block = build_empty_block_for_next_slot(spec, state)
    spec.process_slots(state, block.slot)
    return block


def _make_builder(spec, state, index: int, balance: int):
    creds = bytes(spec.BUILDER_WITHDRAWAL_PREFIX) + b"\x00" * 11 + b"\x42" * 20
    state.validators[index].withdrawal_credentials = creds
    state.balances[index] = balance
    state.validators[index].effective_balance = min(
        balance - balance % spec.EFFECTIVE_BALANCE_INCREMENT, spec.MAX_EFFECTIVE_BALANCE_ELECTRA
    )


@with_phases(["gloas"])
@spec_state_test
def test_self_build_zero_bid(spec, state):
    block = _prepared_block(spec, state)
    spec.process_execution_payload_bid(state, block)
    bid = block.body.signed_execution_payload_bid.message
    assert state.latest_execution_payload_bid == bid


@with_phases(["gloas"])
@spec_state_test
def test_self_build_nonzero_value_invalid(spec, state):
    block = _prepared_block(spec, state)
    block.body.signed_execution_payload_bid.message.value = 1
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(["gloas"])
@spec_state_test
def test_self_build_wrong_signature_invalid(spec, state):
    block = _prepared_block(spec, state)
    block.body.signed_execution_payload_bid.signature = b"\x11" * 96
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(["gloas"])
@spec_state_test
def test_builder_bid_records_pending_payment(spec, state):
    block = _prepared_block(spec, state)
    proposer = int(block.proposer_index)
    builder_index = (proposer + 1) % len(state.validators)
    _make_builder(spec, state, builder_index, 2 * spec.MIN_ACTIVATION_BALANCE)

    bid = block.body.signed_execution_payload_bid.message
    bid.builder_index = builder_index
    bid.value = spec.EFFECTIVE_BALANCE_INCREMENT
    signed = spec.SignedExecutionPayloadBid(message=bid, signature=b"\x00" * 96)
    # bls is off in this suite: Verify stubs true, matching the reference's
    # bls_switch convention for non-@always_bls tests
    block.body.signed_execution_payload_bid = signed

    spec.process_execution_payload_bid(state, block)
    payment = state.builder_pending_payments[
        spec.SLOTS_PER_EPOCH + int(bid.slot) % spec.SLOTS_PER_EPOCH
    ]
    assert int(payment.withdrawal.amount) == int(bid.value)
    assert int(payment.withdrawal.builder_index) == builder_index
    assert int(payment.weight) == 0


@with_phases(["gloas"])
@spec_state_test
def test_builder_bid_without_builder_credential_invalid(spec, state):
    block = _prepared_block(spec, state)
    proposer = int(block.proposer_index)
    builder_index = (proposer + 1) % len(state.validators)
    # no 0x03 credential installed
    bid = block.body.signed_execution_payload_bid.message
    bid.builder_index = builder_index
    bid.value = 0
    block.body.signed_execution_payload_bid = spec.SignedExecutionPayloadBid(
        message=bid, signature=b"\x00" * 96
    )
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(["gloas"])
@spec_state_test
def test_builder_bid_insufficient_balance_invalid(spec, state):
    block = _prepared_block(spec, state)
    proposer = int(block.proposer_index)
    builder_index = (proposer + 1) % len(state.validators)
    _make_builder(spec, state, builder_index, spec.MIN_ACTIVATION_BALANCE)  # no excess

    bid = block.body.signed_execution_payload_bid.message
    bid.builder_index = builder_index
    bid.value = spec.EFFECTIVE_BALANCE_INCREMENT
    block.body.signed_execution_payload_bid = spec.SignedExecutionPayloadBid(
        message=bid, signature=b"\x00" * 96
    )
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(["gloas"])
@spec_state_test
def test_slashed_builder_invalid(spec, state):
    block = _prepared_block(spec, state)
    proposer = int(block.proposer_index)
    builder_index = (proposer + 1) % len(state.validators)
    _make_builder(spec, state, builder_index, 2 * spec.MIN_ACTIVATION_BALANCE)
    state.validators[builder_index].slashed = True

    bid = block.body.signed_execution_payload_bid.message
    bid.builder_index = builder_index
    block.body.signed_execution_payload_bid = spec.SignedExecutionPayloadBid(
        message=bid, signature=b"\x00" * 96
    )
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(["gloas"])
@spec_state_test
def test_bid_wrong_parent_hash_invalid(spec, state):
    block = _prepared_block(spec, state)
    block.body.signed_execution_payload_bid.message.parent_block_hash = b"\x13" * 32
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(["gloas"])
@spec_state_test
def test_bid_wrong_slot_invalid(spec, state):
    block = _prepared_block(spec, state)
    block.body.signed_execution_payload_bid.message.slot = int(block.slot) + 1
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


# == round-4 extensions: balance boundaries with outstanding obligations ===


def _builder_bid(spec, state, block, builder_index: int, value: int):
    bid = block.body.signed_execution_payload_bid.message
    bid.builder_index = builder_index
    bid.value = value
    block.body.signed_execution_payload_bid = spec.SignedExecutionPayloadBid(
        message=bid, signature=b"\x00" * 96
    )
    return bid


@with_phases(["gloas"])
@spec_state_test
def test_builder_bid_zero_value_valid(spec, state):
    """An external builder may bid zero: no payment is recorded but the
    bid is committed."""
    block = _prepared_block(spec, state)
    builder_index = (int(block.proposer_index) + 1) % len(state.validators)
    _make_builder(spec, state, builder_index, 2 * spec.MIN_ACTIVATION_BALANCE)
    bid = _builder_bid(spec, state, block, builder_index, 0)
    payments_before = state.builder_pending_payments.copy()
    spec.process_execution_payload_bid(state, block)
    assert state.latest_execution_payload_bid == bid
    assert state.builder_pending_payments == payments_before


@with_phases(["gloas"])
@spec_state_test
def test_builder_bid_inactive_builder_invalid(spec, state):
    block = _prepared_block(spec, state)
    builder_index = (int(block.proposer_index) + 1) % len(state.validators)
    _make_builder(spec, state, builder_index, 2 * spec.MIN_ACTIVATION_BALANCE)
    state.validators[builder_index].activation_epoch = (
        spec.get_current_epoch(state) + 1
    )
    _builder_bid(spec, state, block, builder_index, spec.EFFECTIVE_BALANCE_INCREMENT)
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(["gloas"])
@spec_state_test
def test_builder_bid_exact_balance_boundary(spec, state):
    """balance == value + MIN_ACTIVATION_BALANCE is exactly sufficient;
    one Gwei less is not."""
    pristine = state.copy()  # BEFORE part 1 dirties payments/bid state
    block = _prepared_block(spec, state)
    builder_index = (int(block.proposer_index) + 1) % len(state.validators)
    value = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _make_builder(
        spec, state, builder_index, value + int(spec.MIN_ACTIVATION_BALANCE)
    )
    _builder_bid(spec, state, block, builder_index, value)
    spec.process_execution_payload_bid(state, block)

    # fresh pristine state, one Gwei short — no carried pending payment
    state2 = pristine
    block2 = _prepared_block(spec, state2)
    builder2 = (int(block2.proposer_index) + 1) % len(state2.validators)
    _make_builder(
        spec, state2, builder2, value + int(spec.MIN_ACTIVATION_BALANCE) - 1
    )
    _builder_bid(spec, state2, block2, builder2, value)
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state2, block2))


@with_phases(["gloas"])
@spec_state_test
def test_builder_bid_insufficient_with_pending_payments(spec, state):
    """Outstanding pending payments count against the builder's cover."""
    block = _prepared_block(spec, state)
    builder_index = (int(block.proposer_index) + 1) % len(state.validators)
    value = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _make_builder(
        spec, state, builder_index, value + int(spec.MIN_ACTIVATION_BALANCE)
    )
    # an outstanding payment eats the headroom
    state.builder_pending_payments[0] = spec.BuilderPendingPayment(
        weight=0,
        withdrawal=spec.BuilderPendingWithdrawal(
            fee_recipient=b"\x42" * 20,
            amount=1,
            builder_index=builder_index,
            withdrawable_epoch=spec.FAR_FUTURE_EPOCH,
        ),
    )
    _builder_bid(spec, state, block, builder_index, value)
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(["gloas"])
@spec_state_test
def test_builder_bid_sufficient_with_pending_payments(spec, state):
    block = _prepared_block(spec, state)
    builder_index = (int(block.proposer_index) + 1) % len(state.validators)
    value = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    outstanding = 5
    _make_builder(
        spec,
        state,
        builder_index,
        value + outstanding + int(spec.MIN_ACTIVATION_BALANCE),
    )
    state.builder_pending_payments[0] = spec.BuilderPendingPayment(
        weight=0,
        withdrawal=spec.BuilderPendingWithdrawal(
            fee_recipient=b"\x42" * 20,
            amount=outstanding,
            builder_index=builder_index,
            withdrawable_epoch=spec.FAR_FUTURE_EPOCH,
        ),
    )
    _builder_bid(spec, state, block, builder_index, value)
    spec.process_execution_payload_bid(state, block)  # must not raise


@with_phases(["gloas"])
@spec_state_test
def test_builder_bid_insufficient_with_pending_withdrawals(spec, state):
    """Queued builder withdrawals also count against the cover."""
    block = _prepared_block(spec, state)
    builder_index = (int(block.proposer_index) + 1) % len(state.validators)
    value = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _make_builder(
        spec, state, builder_index, value + int(spec.MIN_ACTIVATION_BALANCE)
    )
    state.builder_pending_withdrawals.append(
        spec.BuilderPendingWithdrawal(
            fee_recipient=b"\x42" * 20,
            amount=1,
            builder_index=builder_index,
            withdrawable_epoch=spec.FAR_FUTURE_EPOCH,
        )
    )
    _builder_bid(spec, state, block, builder_index, value)
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(["gloas"])
@spec_state_test
def test_builder_bid_sufficient_with_pending_withdrawals(spec, state):
    block = _prepared_block(spec, state)
    builder_index = (int(block.proposer_index) + 1) % len(state.validators)
    value = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    outstanding = 7
    _make_builder(
        spec,
        state,
        builder_index,
        value + outstanding + int(spec.MIN_ACTIVATION_BALANCE),
    )
    state.builder_pending_withdrawals.append(
        spec.BuilderPendingWithdrawal(
            fee_recipient=b"\x42" * 20,
            amount=outstanding,
            builder_index=builder_index,
            withdrawable_epoch=spec.FAR_FUTURE_EPOCH,
        )
    )
    _builder_bid(spec, state, block, builder_index, value)
    spec.process_execution_payload_bid(state, block)  # must not raise


@with_phases(["gloas"])
@spec_state_test
def test_bid_wrong_parent_block_root_invalid(spec, state):
    block = _prepared_block(spec, state)
    bid = block.body.signed_execution_payload_bid.message
    bid.parent_block_root = b"\x66" * 32
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(["gloas"])
@spec_state_test
def test_bid_wrong_prev_randao_invalid(spec, state):
    block = _prepared_block(spec, state)
    bid = block.body.signed_execution_payload_bid.message
    bid.prev_randao = b"\x77" * 32
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))
