"""Envelope import — the second half of the split transition
(reference: specs/gloas/beacon-chain.md:1221-1318 and
eth2spec/test/gloas/block_processing/test_process_execution_payload.py)."""

from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    build_signed_execution_payload_envelope,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import (
    expect_assertion_error,
    spec_state_test,
    with_phases,
)


def _state_with_committed_bid(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, block)
    return block


@with_phases(["gloas"])
@spec_state_test
def test_envelope_import_basic(spec, state):
    _state_with_committed_bid(spec, state)
    env = build_signed_execution_payload_envelope(spec, state)
    spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    assert spec.is_parent_block_full(state)
    slot_index = int(state.slot) % spec.SLOTS_PER_HISTORICAL_ROOT
    assert int(state.execution_payload_availability[slot_index]) == 1
    assert bytes(state.latest_block_hash) == bytes(env.message.payload.block_hash)


@with_phases(["gloas"])
@spec_state_test
def test_envelope_wrong_builder_invalid(spec, state):
    _state_with_committed_bid(spec, state)
    env = build_signed_execution_payload_envelope(spec, state)
    env.message.builder_index = (int(env.message.builder_index) + 1) % len(state.validators)
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_wrong_slot_invalid(spec, state):
    _state_with_committed_bid(spec, state)
    env = build_signed_execution_payload_envelope(spec, state)
    env.message.slot = int(state.slot) + 1
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_wrong_block_hash_invalid(spec, state):
    _state_with_committed_bid(spec, state)
    env = build_signed_execution_payload_envelope(spec, state)
    env.message.payload.block_hash = b"\x66" * 32
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_commitments_root_mismatch_invalid(spec, state):
    _state_with_committed_bid(spec, state)
    env = build_signed_execution_payload_envelope(spec, state)
    env.message.blob_kzg_commitments = [b"\xc0" + b"\x00" * 47]
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_withdrawals_root_mismatch_invalid(spec, state):
    _state_with_committed_bid(spec, state)
    env = build_signed_execution_payload_envelope(spec, state)
    env.message.payload.withdrawals = [
        spec.Withdrawal(index=0, validator_index=0, address=b"\x01" * 20, amount=1)
    ]
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_state_root_mismatch_invalid(spec, state):
    _state_with_committed_bid(spec, state)
    env = build_signed_execution_payload_envelope(spec, state)
    env.message.state_root = b"\x99" * 32
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_queues_builder_payment(spec, state):
    """A pending payment for the current slot becomes a pending withdrawal
    when the payload is revealed (:1298-1309)."""
    block = _state_with_committed_bid(spec, state)
    payment_index = spec.SLOTS_PER_EPOCH + int(state.slot) % spec.SLOTS_PER_EPOCH
    payment = state.builder_pending_payments[payment_index].copy()
    payment.withdrawal.amount = spec.EFFECTIVE_BALANCE_INCREMENT
    payment.withdrawal.builder_index = int(block.proposer_index)
    payment.withdrawal.withdrawable_epoch = spec.FAR_FUTURE_EPOCH
    state.builder_pending_payments[payment_index] = payment

    env = build_signed_execution_payload_envelope(spec, state)
    spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)

    assert len(state.builder_pending_withdrawals) == 1
    w = state.builder_pending_withdrawals[0]
    assert int(w.amount) == spec.EFFECTIVE_BALANCE_INCREMENT
    assert int(w.withdrawable_epoch) < spec.FAR_FUTURE_EPOCH
    # the slot's payment box is cleared
    assert int(state.builder_pending_payments[payment_index].withdrawal.amount) == 0
