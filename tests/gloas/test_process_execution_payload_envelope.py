"""Envelope import — the second half of the split transition
(reference: specs/gloas/beacon-chain.md:1221-1318 and
eth2spec/test/gloas/block_processing/test_process_execution_payload.py)."""

from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    build_signed_execution_payload_envelope,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import (
    expect_assertion_error,
    spec_state_test,
    with_phases,
)


def _state_with_committed_bid(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, block)
    return block


@with_phases(["gloas"])
@spec_state_test
def test_envelope_import_basic(spec, state):
    _state_with_committed_bid(spec, state)
    env = build_signed_execution_payload_envelope(spec, state)
    spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    assert spec.is_parent_block_full(state)
    slot_index = int(state.slot) % spec.SLOTS_PER_HISTORICAL_ROOT
    assert int(state.execution_payload_availability[slot_index]) == 1
    assert bytes(state.latest_block_hash) == bytes(env.message.payload.block_hash)


@with_phases(["gloas"])
@spec_state_test
def test_envelope_wrong_builder_invalid(spec, state):
    _state_with_committed_bid(spec, state)
    env = build_signed_execution_payload_envelope(spec, state)
    env.message.builder_index = (int(env.message.builder_index) + 1) % len(state.validators)
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_wrong_slot_invalid(spec, state):
    _state_with_committed_bid(spec, state)
    env = build_signed_execution_payload_envelope(spec, state)
    env.message.slot = int(state.slot) + 1
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_wrong_block_hash_invalid(spec, state):
    _state_with_committed_bid(spec, state)
    env = build_signed_execution_payload_envelope(spec, state)
    env.message.payload.block_hash = b"\x66" * 32
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_commitments_root_mismatch_invalid(spec, state):
    _state_with_committed_bid(spec, state)
    env = build_signed_execution_payload_envelope(spec, state)
    env.message.blob_kzg_commitments = [b"\xc0" + b"\x00" * 47]
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_withdrawals_root_mismatch_invalid(spec, state):
    _state_with_committed_bid(spec, state)
    env = build_signed_execution_payload_envelope(spec, state)
    env.message.payload.withdrawals = [
        spec.Withdrawal(index=0, validator_index=0, address=b"\x01" * 20, amount=1)
    ]
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_state_root_mismatch_invalid(spec, state):
    _state_with_committed_bid(spec, state)
    env = build_signed_execution_payload_envelope(spec, state)
    env.message.state_root = b"\x99" * 32
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_queues_builder_payment(spec, state):
    """A pending payment for the current slot becomes a pending withdrawal
    when the payload is revealed (:1298-1309)."""
    block = _state_with_committed_bid(spec, state)
    payment_index = spec.SLOTS_PER_EPOCH + int(state.slot) % spec.SLOTS_PER_EPOCH
    payment = state.builder_pending_payments[payment_index].copy()
    payment.withdrawal.amount = spec.EFFECTIVE_BALANCE_INCREMENT
    payment.withdrawal.builder_index = int(block.proposer_index)
    payment.withdrawal.withdrawable_epoch = spec.FAR_FUTURE_EPOCH
    state.builder_pending_payments[payment_index] = payment

    env = build_signed_execution_payload_envelope(spec, state)
    spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)

    assert len(state.builder_pending_withdrawals) == 1
    w = state.builder_pending_withdrawals[0]
    assert int(w.amount) == spec.EFFECTIVE_BALANCE_INCREMENT
    assert int(w.withdrawable_epoch) < spec.FAR_FUTURE_EPOCH
    # the slot's payment box is cleared
    assert int(state.builder_pending_payments[payment_index].withdrawal.amount) == 0


# == round-4 extensions: remaining consistency checks ======================


def _envelope_after_bid(spec, state):
    _state_with_committed_bid(spec, state)
    return build_signed_execution_payload_envelope(spec, state)


@with_phases(["gloas"])
@spec_state_test
def test_envelope_wrong_gas_limit_invalid(spec, state):
    env = _envelope_after_bid(spec, state)
    env.message.payload.gas_limit = int(env.message.payload.gas_limit) + 1
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_wrong_parent_hash_invalid(spec, state):
    env = _envelope_after_bid(spec, state)
    env.message.payload.parent_hash = b"\x99" * 32
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_wrong_prev_randao_invalid(spec, state):
    env = _envelope_after_bid(spec, state)
    env.message.payload.prev_randao = b"\x88" * 32
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_wrong_timestamp_invalid(spec, state):
    env = _envelope_after_bid(spec, state)
    env.message.payload.timestamp = int(env.message.payload.timestamp) + 1
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_wrong_beacon_block_root_invalid(spec, state):
    env = _envelope_after_bid(spec, state)
    env.message.beacon_block_root = b"\x55" * 32
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_engine_rejection_invalid(spec, state):
    """The engine's verdict gates the import (invalid EL payload)."""
    env = _envelope_after_bid(spec, state)

    class _Rejecting:
        def verify_and_notify_new_payload(self, request) -> bool:
            return False

    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, _Rejecting())
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_too_many_blob_commitments_invalid(spec, state):
    """Commitment count above the epoch's blob cap fails even when the
    committed bid agreed to it (the cap is a consensus rule)."""
    _state_with_committed_bid(spec, state)
    # freeze the header root first: later bid mutation must not shift the
    # beacon_block_root the envelope binds to
    state.latest_block_header.state_root = hash_tree_root(state)

    cap = int(spec.get_blob_parameters(spec.get_current_epoch(state)).max_blobs_per_block)
    oversized = spec.ExecutionPayloadEnvelope().blob_kzg_commitments
    for _ in range(cap + 1):
        oversized.append(b"\xc0" + b"\x00" * 47)
    bid = state.latest_execution_payload_bid
    bid.blob_kzg_commitments_root = hash_tree_root(oversized)

    # hand-built envelope (the normal builder's dry run would itself trip
    # the cap): every check BEFORE the cap assert is satisfied, and the
    # state_root check sits after it, so only the cap can fail
    payload = spec.ExecutionPayload(
        parent_hash=state.latest_block_hash,
        fee_recipient=bid.fee_recipient,
        prev_randao=bid.prev_randao,
        block_number=1,
        gas_limit=bid.gas_limit,
        gas_used=0,
        timestamp=spec.compute_timestamp_at_slot(state, state.slot),
        base_fee_per_gas=0,
        block_hash=bid.block_hash,
        transactions=[],
        withdrawals=[],
    )
    env = spec.SignedExecutionPayloadEnvelope(
        message=spec.ExecutionPayloadEnvelope(
            payload=payload,
            builder_index=bid.builder_index,
            beacon_block_root=hash_tree_root(state.latest_block_header),
            slot=state.slot,
            blob_kzg_commitments=oversized,
        )
    )
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(["gloas"])
@spec_state_test
def test_envelope_self_build_zero_value_no_payment(spec, state):
    """A self-build import must leave the builder payment queues alone."""
    _state_with_committed_bid(spec, state)
    payments_before = state.builder_pending_payments.copy()
    withdrawals_before = len(state.builder_pending_withdrawals)
    env = build_signed_execution_payload_envelope(spec, state)
    spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    assert state.builder_pending_payments == payments_before
    assert len(state.builder_pending_withdrawals) == withdrawals_before
