"""PTC payload attestations (reference: specs/gloas/beacon-chain.md:584-622,
:1146-1163)."""

from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import (
    always_bls,
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.keys import privkeys
from eth_consensus_specs_tpu.test_infra.state import next_slot
from eth_consensus_specs_tpu.utils import bls


def _valid_payload_attestation(spec, state, payload_present=True):
    """PTC attestation for the parent block at the previous slot."""
    data = spec.PayloadAttestationData(
        beacon_block_root=state.latest_block_header.parent_root,
        slot=int(state.slot) - 1,
        payload_present=payload_present,
        blob_data_available=payload_present,
    )
    ptc = spec.get_ptc(state, int(data.slot))
    bits = [True] * len(ptc)
    domain = spec.get_domain(state, spec.DOMAIN_PTC_ATTESTER, None)
    signing_root = spec.compute_signing_root(data, domain)
    sigs = [bls.Sign(privkeys[i], signing_root) for i in sorted(set(ptc))]
    return spec.PayloadAttestation(
        aggregation_bits=bits, data=data, signature=bls.Aggregate(sigs)
    )


def _advance_two_blocks(spec, state):
    for _ in range(2):
        block = build_empty_block_for_next_slot(spec, state)
        state_transition_and_sign_block(spec, state, block)


@with_phases(["gloas"])
@spec_state_test
def test_ptc_is_deterministic_and_sized(spec, state):
    next_slot(spec, state)
    ptc = spec.get_ptc(state, int(state.slot))
    assert len(ptc) == spec.PTC_SIZE
    assert ptc == spec.get_ptc(state, int(state.slot))
    for v in ptc:
        assert 0 <= int(v) < len(state.validators)


@with_phases(["gloas"])
@spec_state_test
def test_process_payload_attestation_basic(spec, state):
    _advance_two_blocks(spec, state)
    att = _valid_payload_attestation(spec, state)
    spec.process_payload_attestation(state, att)


@with_phases(["gloas"])
@spec_state_test
def test_payload_attestation_wrong_root_invalid(spec, state):
    _advance_two_blocks(spec, state)
    att = _valid_payload_attestation(spec, state)
    att.data.beacon_block_root = b"\x21" * 32
    expect_assertion_error(lambda: spec.process_payload_attestation(state, att))


@with_phases(["gloas"])
@spec_state_test
def test_payload_attestation_wrong_slot_invalid(spec, state):
    _advance_two_blocks(spec, state)
    att = _valid_payload_attestation(spec, state)
    att.data.slot = int(state.slot)  # must be previous slot
    expect_assertion_error(lambda: spec.process_payload_attestation(state, att))


@with_phases(["gloas"])
@spec_state_test
def test_indexed_payload_attestation_sorted(spec, state):
    _advance_two_blocks(spec, state)
    att = _valid_payload_attestation(spec, state)
    indexed = spec.get_indexed_payload_attestation(state, int(att.data.slot), att)
    idx = [int(i) for i in indexed.attesting_indices]
    assert idx == sorted(idx)
    assert spec.is_valid_indexed_payload_attestation(state, indexed)


@with_phases(["gloas"])
@spec_state_test
def test_indexed_payload_attestation_empty_invalid(spec, state):
    _advance_two_blocks(spec, state)
    att = _valid_payload_attestation(spec, state)
    att.aggregation_bits = [False] * spec.PTC_SIZE
    indexed = spec.get_indexed_payload_attestation(state, int(att.data.slot), att)
    assert not spec.is_valid_indexed_payload_attestation(state, indexed)


@with_phases(["gloas"])
@spec_state_test
def test_block_carries_payload_attestation(spec, state):
    """End-to-end: a block including a PTC attestation for its parent."""
    block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, block)

    block = build_empty_block_for_next_slot(spec, state)
    # data targets the parent block (previous slot) as seen when the new
    # block's header is in place during processing
    probe = state.copy()
    spec.process_slots(probe, block.slot)
    data = spec.PayloadAttestationData(
        beacon_block_root=block.parent_root,
        slot=int(block.slot) - 1,
        payload_present=False,
        blob_data_available=False,
    )
    ptc = spec.get_ptc(probe, int(data.slot))
    domain = spec.get_domain(probe, spec.DOMAIN_PTC_ATTESTER, None)
    signing_root = spec.compute_signing_root(data, domain)
    sigs = [bls.Sign(privkeys[i], signing_root) for i in sorted(set(ptc))]
    att = spec.PayloadAttestation(
        aggregation_bits=[True] * len(ptc), data=data, signature=bls.Aggregate(sigs)
    )
    block.body.payload_attestations = [att]
    state_transition_and_sign_block(spec, state, block)


# == round-4: PTC duty helpers (specs/gloas/validator.md:57-73, 213-219) ===


@with_phases(["gloas"])
@spec_state_test
def test_ptc_assignment_covers_every_member(spec, state):
    """Every PTC member maps back to a slot whose committee contains it
    (the FIRST such slot in the epoch)."""
    epoch = spec.get_current_epoch(state)
    start = int(spec.compute_start_slot_at_epoch(epoch))
    for slot in range(start, start + 2):  # two slots keep it cheap
        for member in set(spec.get_ptc(state, slot)):
            assigned = spec.get_ptc_assignment(state, epoch, member)
            assert assigned is not None
            # the assignment is a slot whose PTC really contains the member
            assert int(member) in set(
                int(i) for i in spec.get_ptc(state, int(assigned))
            )


@with_phases(["gloas"])
@spec_state_test
def test_ptc_assignment_next_epoch_allowed_beyond_rejected(spec, state):
    epoch = spec.get_current_epoch(state)
    spec.get_ptc_assignment(state, epoch + 1, 0)  # computable one ahead
    expect_assertion_error(lambda: spec.get_ptc_assignment(state, epoch + 2, 0))


@with_phases(["gloas"])
@spec_state_test
def test_ptc_assignment_none_for_unassigned(spec, state):
    """An index on no PTC of the epoch gets None."""
    epoch = spec.get_current_epoch(state)
    start = int(spec.compute_start_slot_at_epoch(epoch))
    members = set()
    for slot in range(start, start + int(spec.SLOTS_PER_EPOCH)):
        members.update(int(i) for i in spec.get_ptc(state, slot))
    outsiders = [i for i in range(len(state.validators)) if i not in members]
    if outsiders:
        assert spec.get_ptc_assignment(state, epoch, outsiders[0]) is None


@with_phases(["gloas"])
@always_bls
@spec_state_test
def test_payload_attestation_message_signature_verifies(spec, state):
    """Signature verifies under the slot-epoch domain; within one epoch
    (the PTC's same-slot regime) it equals the on-chain verifier's
    current-epoch domain — the upstream asymmetry pinned here."""
    data = spec.PayloadAttestationData(
        beacon_block_root=b"\x12" * 32,
        slot=state.slot,
        payload_present=True,
        blob_data_available=True,
    )
    msg = spec.PayloadAttestationMessage(
        validator_index=3, data=data, signature=b"\x00" * 96
    )
    sig = spec.get_payload_attestation_message_signature(state, msg, privkeys[3])
    helper_domain = spec.get_domain(
        state, spec.DOMAIN_PTC_ATTESTER, spec.compute_epoch_at_slot(data.slot)
    )
    verifier_domain = spec.get_domain(state, spec.DOMAIN_PTC_ATTESTER, None)
    assert bytes(helper_domain) == bytes(verifier_domain)  # same-epoch regime
    assert bls.Verify(
        state.validators[3].pubkey,
        spec.compute_signing_root(data, helper_domain),
        sig,
    )
