"""process_builder_pending_payments epoch table, gloas (reference
analogue: test/gloas/epoch_processing/test_process_builder_pending_payments.py
— quorum boundaries, queue rotation, churn impact; spec:
specs/gloas/beacon-chain.md process_builder_pending_payments)."""

from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.template import instantiate

GLOAS = ["gloas"]
GWEI = 1_000_000_000


def _payment(spec, state, slot_pos: int, weight: int, amount: int, builder: int = 1):
    payment = spec.BuilderPendingPayment(
        weight=weight,
        withdrawal=spec.BuilderPendingWithdrawal(
            fee_recipient=b"\x42" * 20,
            amount=amount,
            builder_index=builder,
        ),
    )
    state.builder_pending_payments[slot_pos] = payment
    return payment


@with_phases(GLOAS)
@spec_state_test
def test_empty_queue_rotates(spec, state):
    slots = int(spec.SLOTS_PER_EPOCH)
    assert len(state.builder_pending_payments) == 2 * slots
    spec.process_builder_pending_payments(state)
    assert len(state.builder_pending_payments) == 2 * slots
    assert len(state.builder_pending_withdrawals) == 0
    assert all(
        int(p.weight) == 0 and int(p.withdrawal.amount) == 0
        for p in state.builder_pending_payments
    )


def _quorum_case(relation: str):
    @with_phases(GLOAS)
    @spec_state_test
    def case(spec, state):
        quorum = int(spec.get_builder_payment_quorum_threshold(state))
        weight = {
            "below": max(quorum - 1, 0),
            "equal": quorum,
            "above": quorum + 1,
        }[relation]
        _payment(spec, state, 0, weight, 7 * GWEI)
        spec.process_builder_pending_payments(state)
        settled = len(state.builder_pending_withdrawals)
        # STRICTLY-above quorum settles; equal and below are dropped
        assert settled == (1 if relation == "above" else 0)
        if relation == "above":
            w = state.builder_pending_withdrawals[0]
            assert int(w.amount) == 7 * GWEI
            assert int(w.withdrawable_epoch) >= int(
                spec.get_current_epoch(state)
            ) + int(spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)

    return case, f"test_payment_{relation}_quorum"


for _relation in ("below", "equal", "above"):
    instantiate(_quorum_case, _relation)


@with_phases(GLOAS)
@spec_state_test
def test_multiple_above_quorum_all_settle(spec, state):
    quorum = int(spec.get_builder_payment_quorum_threshold(state))
    slots = int(spec.SLOTS_PER_EPOCH)
    for pos in range(min(3, slots)):
        _payment(spec, state, pos, quorum + 1, (pos + 1) * GWEI, builder=pos + 1)
    spec.process_builder_pending_payments(state)
    assert len(state.builder_pending_withdrawals) == min(3, slots)
    amounts = [int(w.amount) for w in state.builder_pending_withdrawals]
    assert amounts == [(i + 1) * GWEI for i in range(min(3, slots))]


@with_phases(GLOAS)
@spec_state_test
def test_mixed_weights_settle_selectively(spec, state):
    quorum = int(spec.get_builder_payment_quorum_threshold(state))
    _payment(spec, state, 0, quorum + 5, 2 * GWEI)
    _payment(spec, state, 1, max(quorum - 5, 0), 3 * GWEI)
    _payment(spec, state, 2, quorum + 1, 4 * GWEI)
    spec.process_builder_pending_payments(state)
    amounts = [int(w.amount) for w in state.builder_pending_withdrawals]
    assert amounts == [2 * GWEI, 4 * GWEI]


@with_phases(GLOAS)
@spec_state_test
def test_only_previous_epoch_window_settles(spec, state):
    """Only the FIRST SLOTS_PER_EPOCH entries (previous epoch) settle;
    current-epoch entries rotate into the previous-epoch window."""
    quorum = int(spec.get_builder_payment_quorum_threshold(state))
    slots = int(spec.SLOTS_PER_EPOCH)
    _payment(spec, state, slots, quorum + 1, 9 * GWEI)  # current-epoch slot 0
    spec.process_builder_pending_payments(state)
    assert len(state.builder_pending_withdrawals) == 0
    # rotated into the settlement window, preserved
    assert int(state.builder_pending_payments[0].withdrawal.amount) == 9 * GWEI
    # a second epoch pass settles it
    spec.process_builder_pending_payments(state)
    assert len(state.builder_pending_withdrawals) == 1


@with_phases(GLOAS)
@spec_state_test
def test_queue_rotation_clears_tail(spec, state):
    quorum = int(spec.get_builder_payment_quorum_threshold(state))
    slots = int(spec.SLOTS_PER_EPOCH)
    for pos in range(2 * slots):
        _payment(spec, state, pos, quorum + 1, GWEI)
    spec.process_builder_pending_payments(state)
    # previous window settled; current window shifted down; tail zeroed
    assert len(state.builder_pending_withdrawals) == slots
    assert all(
        int(p.withdrawal.amount) == GWEI
        for p in state.builder_pending_payments[:slots]
    )
    assert all(
        int(p.withdrawal.amount) == 0
        for p in state.builder_pending_payments[slots:]
    )


@with_phases(GLOAS)
@spec_state_test
def test_large_amount_consumes_exit_churn(spec, state):
    """A settled payment larger than the per-epoch churn pushes
    earliest_exit_epoch out — builder payments share the EIP-7251 exit
    churn budget."""
    quorum = int(spec.get_builder_payment_quorum_threshold(state))
    churn = int(spec.get_activation_exit_churn_limit(state))
    _payment(spec, state, 0, quorum + 1, 3 * churn)
    pre_earliest = int(state.earliest_exit_epoch)
    spec.process_builder_pending_payments(state)
    assert len(state.builder_pending_withdrawals) == 1
    assert int(state.earliest_exit_epoch) >= max(
        pre_earliest,
        int(spec.compute_activation_exit_epoch(spec.get_current_epoch(state))),
    ) + 2


@with_phases(GLOAS)
@spec_state_test
def test_settled_withdrawable_epoch_tracks_churned_exit(spec, state):
    quorum = int(spec.get_builder_payment_quorum_threshold(state))
    _payment(spec, state, 0, quorum + 1, GWEI)
    spec.process_builder_pending_payments(state)
    w = state.builder_pending_withdrawals[0]
    assert int(w.withdrawable_epoch) == int(state.earliest_exit_epoch) + int(
        spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    )
