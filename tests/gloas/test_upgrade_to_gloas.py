"""fulu -> gloas state upgrade (spec: specs/gloas/fork.md:34-110)."""

from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.state import next_epoch


@with_phases(["fulu"])
@spec_state_test
def test_upgrade_to_gloas_basic(spec, state):
    gloas = get_spec("gloas", spec.preset_name)
    next_epoch(spec, state)
    pre_header_hash = bytes(state.latest_execution_payload_header.block_hash)
    post = gloas.upgrade_from_parent(state)
    assert bytes(post.fork.current_version) == bytes(gloas.config.GLOAS_FORK_VERSION)
    assert bytes(post.latest_execution_payload_bid.block_hash) == pre_header_hash
    assert bytes(post.latest_block_hash) == pre_header_hash
    assert gloas.is_parent_block_full(post)
    assert all(int(b) == 1 for b in post.execution_payload_availability)
    assert len(post.builder_pending_withdrawals) == 0
    assert all(
        int(p.withdrawal.amount) == 0 for p in post.builder_pending_payments
    )
    assert hash_tree_root(post.validators) == hash_tree_root(state.validators)
    # the post-state remains executable
    next_epoch(gloas, post)


@with_phases(["fulu"])
@spec_state_test
def test_upgrade_to_gloas_preserves_lookahead(spec, state):
    gloas = get_spec("gloas", spec.preset_name)
    post = gloas.upgrade_from_parent(state)
    assert [int(x) for x in post.proposer_lookahead] == [
        int(x) for x in state.proposer_lookahead
    ]
