"""Honest-builder flows per the gloas builder document — bid construction
(reference: specs/gloas/builder.md:90-136), envelope construction with the
verify=False state-root dry run (:210-256), becoming a builder via the
builder withdrawal prefix (:33-77), and honest payload-withheld messages
(:258+). Each flow is driven end-to-end through the spec's processing
functions with real signatures."""

from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    build_signed_execution_payload_envelope,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import (
    always_bls,
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.keys import privkey_of, pubkeys
from eth_consensus_specs_tpu.utils import bls

GLOAS = ["gloas"]


def _make_builder(spec, state, index: int, balance: int | None = None):
    creds = bytes(spec.BUILDER_WITHDRAWAL_PREFIX) + b"\x00" * 11 + b"\x42" * 20
    state.validators[index].withdrawal_credentials = creds
    if balance is not None:
        state.balances[index] = balance
        state.validators[index].effective_balance = min(
            balance - balance % spec.EFFECTIVE_BALANCE_INCREMENT,
            spec.MAX_EFFECTIVE_BALANCE_ELECTRA,
        )


def _honest_bid(spec, state, builder_index: int, slot=None, value=0):
    """Construct a bid exactly per builder.md:90-123 (head hashes from the
    state, builder's own index, current-or-next slot)."""
    from eth_consensus_specs_tpu.ssz import List

    header = state.latest_block_header.copy()
    if bytes(header.state_root) == b"\x00" * 32:
        header.state_root = hash_tree_root(state)
    target_slot = int(state.slot) + 1 if slot is None else int(slot)
    empty_commitments = List[spec.KZGCommitment, spec.MAX_BLOB_COMMITMENTS_PER_BLOCK]([])
    return spec.ExecutionPayloadBid(
        parent_block_hash=state.latest_block_hash,
        parent_block_root=hash_tree_root(header),
        block_hash=spec.hash(
            bytes(state.latest_block_hash) + target_slot.to_bytes(8, "little")
        ),
        prev_randao=spec.get_randao_mix(state, spec.get_current_epoch(state)),
        fee_recipient=b"\x00" * 20,
        gas_limit=30_000_000,
        builder_index=builder_index,
        slot=target_slot,
        value=value,
        execution_payment=0,
        blob_kzg_commitments_root=hash_tree_root(empty_commitments),
    )


def _sign_bid(spec, state, bid, privkey):
    """builder.md:126-133 — DOMAIN_BEACON_BUILDER at the bid's slot epoch."""
    domain = spec.get_domain(
        state, spec.DOMAIN_BEACON_BUILDER, spec.compute_epoch_at_slot(int(bid.slot))
    )
    return bls.Sign(privkey, spec.compute_signing_root(bid, domain))


@with_phases(GLOAS)
@always_bls
@spec_state_test
def test_builder_constructs_and_signs_bid(spec, state):
    """Full builder.md bid flow: construct from head state, sign with the
    builder key, commit through process_execution_payload_bid."""
    builder = 11
    _make_builder(spec, state, builder, int(spec.MIN_ACTIVATION_BALANCE) * 3)
    block = build_empty_block_for_next_slot(spec, state)
    spec.process_slots(state, block.slot)
    bid = _honest_bid(spec, state, builder, slot=int(block.slot), value=1000)
    sig = _sign_bid(spec, state, bid, privkey_of(builder))
    block.body.signed_execution_payload_bid = spec.SignedExecutionPayloadBid(
        message=bid, signature=sig
    )
    spec.process_execution_payload_bid(state, block)
    payment = state.builder_pending_payments[
        spec.SLOTS_PER_EPOCH + int(bid.slot) % spec.SLOTS_PER_EPOCH
    ]
    assert int(payment.withdrawal.amount) == 1000
    assert int(payment.withdrawal.builder_index) == builder


@with_phases(GLOAS)
@always_bls
@spec_state_test
def test_builder_bid_bad_signature_rejected(spec, state):
    builder = 11
    _make_builder(spec, state, builder, int(spec.MIN_ACTIVATION_BALANCE) * 3)
    block = build_empty_block_for_next_slot(spec, state)
    spec.process_slots(state, block.slot)
    bid = _honest_bid(spec, state, builder, slot=int(block.slot), value=1000)
    sig = _sign_bid(spec, state, bid, privkey_of(builder + 1))  # wrong key
    block.body.signed_execution_payload_bid = spec.SignedExecutionPayloadBid(
        message=bid, signature=sig
    )
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(GLOAS)
@always_bls
@spec_state_test
def test_builder_bid_for_wrong_domain_rejected(spec, state):
    builder = 11
    _make_builder(spec, state, builder, int(spec.MIN_ACTIVATION_BALANCE) * 3)
    block = build_empty_block_for_next_slot(spec, state)
    spec.process_slots(state, block.slot)
    bid = _honest_bid(spec, state, builder, slot=int(block.slot), value=1)
    domain = spec.get_domain(
        state, spec.DOMAIN_BEACON_PROPOSER, spec.compute_epoch_at_slot(int(bid.slot))
    )
    sig = bls.Sign(privkey_of(builder), spec.compute_signing_root(bid, domain))
    block.body.signed_execution_payload_bid = spec.SignedExecutionPayloadBid(
        message=bid, signature=sig
    )
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(GLOAS)
@spec_state_test
def test_bid_value_must_cover_pending_payments(spec, state):
    """builder.md:118-120 — the builder must have excess balance for this
    bid AND all pending payments; a bid whose value exceeds
    balance-minus-pending must be rejected."""
    builder = 11
    balance = int(spec.MIN_ACTIVATION_BALANCE) * 2
    _make_builder(spec, state, builder, balance)
    # enqueue an existing pending payment eating most of the excess
    pending = int(spec.MIN_ACTIVATION_BALANCE)
    payments = list(state.builder_pending_payments)
    payments[0] = spec.BuilderPendingPayment(
        weight=0,
        withdrawal=spec.BuilderPendingWithdrawal(
            fee_recipient=b"\x01" * 20,
            amount=pending,
            builder_index=builder,
            withdrawable_epoch=0,
        ),
    )
    state.builder_pending_payments = payments
    block = build_empty_block_for_next_slot(spec, state)
    spec.process_slots(state, block.slot)
    bid = _honest_bid(spec, state, builder, slot=int(block.slot), value=balance - pending + 1)
    block.body.signed_execution_payload_bid = spec.SignedExecutionPayloadBid(
        message=bid, signature=_sign_bid(spec, state, bid, privkey_of(builder))
    )
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(GLOAS)
@spec_state_test
def test_bid_for_next_slot_allowed_shape(spec, state):
    """builder.md:117 — bids target the current OR next slot; the
    processing asserts the committed bid matches the block's slot, so a
    stale bid (previous slot) must be rejected."""
    builder = 11
    _make_builder(spec, state, builder, int(spec.MIN_ACTIVATION_BALANCE) * 3)
    block = build_empty_block_for_next_slot(spec, state)
    spec.process_slots(state, block.slot)
    bid = _honest_bid(spec, state, builder, slot=int(block.slot) - 1, value=0)
    block.body.signed_execution_payload_bid = spec.SignedExecutionPayloadBid(
        message=bid, signature=_sign_bid(spec, state, bid, privkey_of(builder))
    )
    expect_assertion_error(lambda: spec.process_execution_payload_bid(state, block))


@with_phases(GLOAS)
@spec_state_test
def test_envelope_flow_state_root_dry_run(spec, state):
    """builder.md:210-246 — the envelope's state_root comes from a
    verify=False dry run; the signed envelope then imports cleanly."""
    block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, block)
    env = build_signed_execution_payload_envelope(spec, state)
    # the dry-run-produced root must match a fresh trial import
    trial = state.copy()
    unsigned = spec.SignedExecutionPayloadEnvelope(message=env.message.copy())
    spec.process_execution_payload(trial, unsigned, spec.EXECUTION_ENGINE, verify=False)
    assert bytes(env.message.state_root) == bytes(hash_tree_root(trial))
    spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    assert spec.is_parent_block_full(state)


@with_phases(GLOAS)
@spec_state_test
def test_envelope_wrong_state_root_rejected(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, block)
    env = build_signed_execution_payload_envelope(spec, state)
    env.message.state_root = b"\x66" * 32
    expect_assertion_error(
        lambda: spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    )


@with_phases(GLOAS)
@spec_state_test
def test_withheld_payload_leaves_state_empty(spec, state):
    """builder.md:258+ — when the builder withholds, no envelope is
    imported: the parent stays non-full and availability stays 0."""
    block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, block)
    slot_index = int(state.slot) % spec.SLOTS_PER_HISTORICAL_ROOT
    assert int(state.execution_payload_availability[slot_index]) == 0
    assert not spec.is_parent_block_full(state)


@with_phases(GLOAS)
@spec_state_test
def test_becoming_a_builder_credential_flow(spec, state):
    """builder.md:33-77 — a validator with the builder withdrawal prefix
    is recognized as a builder; one without is not."""
    idx = 9
    assert not spec.is_builder_withdrawal_credential(
        state.validators[idx].withdrawal_credentials
    )
    _make_builder(spec, state, idx)
    assert spec.is_builder_withdrawal_credential(
        state.validators[idx].withdrawal_credentials
    )


@with_phases(GLOAS)
@always_bls
@spec_state_test
def test_payload_attestation_flow(spec, state):
    """PTC duty: a payload attestation over the imported envelope verifies
    through is_valid_indexed_payload_attestation (beacon-chain.md:376+)."""
    block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, block)
    env = build_signed_execution_payload_envelope(spec, state)
    spec.process_execution_payload(state, env, spec.EXECUTION_ENGINE)
    ptc = spec.get_ptc(state, state.slot)
    header = state.latest_block_header.copy()
    if bytes(header.state_root) == b"\x00" * 32:
        header.state_root = hash_tree_root(state)
    data = spec.PayloadAttestationData(
        beacon_block_root=hash_tree_root(header),
        slot=state.slot,
        payload_present=True,
        blob_data_available=True,
    )
    # sign with every PTC member
    domain = spec.get_domain(
        state, spec.DOMAIN_PTC_ATTESTER, spec.compute_epoch_at_slot(int(state.slot))
    )
    root = spec.compute_signing_root(data, domain)
    sigs = [bls.Sign(privkey_of(int(i)), root) for i in ptc]
    ipa = spec.IndexedPayloadAttestation(
        attesting_indices=sorted(int(i) for i in set(int(x) for x in ptc)),
        data=data,
        signature=bls.Aggregate(sigs) if sigs else spec.BLSSignature(b"\xc0" + b"\x00" * 95),
    )
    assert spec.is_valid_indexed_payload_attestation(state, ipa)
