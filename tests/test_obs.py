"""obs subsystem: registry (spans/counters/JSONL), gates, watchdog.

The tier-1 acceptance story: spans nest and aggregate, counters are
thread-safe totals, the JSONL sink round-trips, roofline verdicts attach
to any timing that declares work_bytes, and the watchdog records both
the clean path (divergences == 0 on CPU, where device == host by
construction) and the mismatch path (a corrupted device result MUST land
in watchdog.divergences — the metric round 4 was missing)."""

import json
import threading

import numpy as np
import pytest

from eth_consensus_specs_tpu import obs
from eth_consensus_specs_tpu.obs import gates, watchdog
from eth_consensus_specs_tpu.obs.registry import Registry


# ------------------------------------------------------------------ registry --


def test_counter_aggregation_thread_safe():
    reg = Registry()

    def bump():
        for _ in range(1000):
            reg.count("t.x", 1)
            reg.count("t.bytes", 64)

    threads = [threading.Thread(target=bump) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.counters["t.x"] == 8000
    assert reg.counters["t.bytes"] == 8 * 64000


def test_span_nesting_and_aggregation():
    reg = Registry()
    with reg.span("outer"):
        with reg.span("inner") as sp:
            sp.result = np.arange(4)
        with reg.span("inner"):
            pass
    snap_spans = reg.snapshot()["spans"]
    assert snap_spans["outer"]["count"] == 1
    assert snap_spans["inner"]["count"] == 2
    assert snap_spans["inner"]["parent"] == "outer"
    assert snap_spans["inner"]["depth"] == 1
    assert snap_spans["outer"]["depth"] == 0
    assert snap_spans["inner"]["total_s"] >= snap_spans["inner"]["min_s"] > 0


def test_span_roofline_verdict_attached():
    reg = Registry()
    with reg.span("k.fast", work_bytes=10**15):  # exabyte/s-class: impossible
        pass
    agg = reg.snapshot()["spans"]["k.fast"]
    assert agg["roofline_ok"] is False
    assert agg["roofline_violations"] == 1
    assert agg["implied_gbps"] > gates.ACCEL_ROOFLINE_BYTES_S / 1e9
    # a later clean timing cannot launder the aggregate verdict
    with reg.span("k.fast", work_bytes=96):
        pass
    agg = reg.snapshot()["spans"]["k.fast"]
    assert agg["roofline_ok"] is False and agg["roofline_violations"] == 1


def test_jsonl_round_trip(tmp_path):
    reg = Registry()
    sink = str(tmp_path / "events.jsonl")
    reg.configure_jsonl(sink)
    reg.count("x", 1)  # counters don't emit events
    with reg.span("roundtrip", work_bytes=96):
        pass
    reg.emit({"kind": "custom", "payload": 7})
    lines = [json.loads(ln) for ln in open(sink)]
    kinds = [ln["kind"] for ln in lines]
    assert "span" in kinds and "custom" in kinds
    span_ev = next(ln for ln in lines if ln["kind"] == "span")
    assert span_ev["name"] == "roundtrip"
    assert "implied_gbps" in span_ev and "roofline_ok" in span_ev
    reg.configure_jsonl(None)


def test_obs_disabled_is_noop(monkeypatch):
    from eth_consensus_specs_tpu.obs import registry as registry_mod

    monkeypatch.setenv("ETH_SPECS_OBS", "0")
    assert registry_mod.refresh_enabled() is False
    try:
        reg = Registry()
        reg.count("never", 1)
        with reg.span("never") as sp:
            sp.result = 3
        assert reg.counters == {} and reg.spans == {}
    finally:
        monkeypatch.setenv("ETH_SPECS_OBS", "1")
        assert registry_mod.refresh_enabled() is True


# --------------------------------------------------------------------- gates --


def test_gates_digest_bytes_and_ndarray_agree():
    arr = np.arange(16, dtype=np.uint32)
    assert gates.digest(arr) == gates.digest(arr.tobytes())
    assert len(gates.digest(b"x")) == 32


def test_gates_roofline_verdict():
    ok = gates.roofline_verdict(1e9, 1.0)
    assert ok["roofline_ok"] and ok["implied_gbps"] == 1.0
    bad = gates.roofline_verdict(1e15, 0.001)
    assert not bad["roofline_ok"]


def test_gates_apply_gates_matches_bench_semantics(capsys):
    frag = {"work_bytes": int(1e15), "unit_s": 0.001}
    gates.apply_gates("tree", frag, "unit_s")
    assert frag["roofline_ok"] is False
    # fragment without timing info passes through unjudged
    frag2 = {"work_bytes": 100}
    gates.apply_gates("tree", frag2, "unit_s")
    assert "roofline_ok" not in frag2


def test_gates_digests_match_refuses_missing():
    assert gates.digests_match("ab", "ab")
    assert not gates.digests_match(None, "ab")
    assert not gates.digests_match("ab", None)
    assert not gates.digests_match("ab", "cd")


def test_bench_imports_gate_logic_from_obs():
    """Acceptance: bench.py consumes obs/gates.py, no duplicated code."""
    import bench

    assert bench._apply_gates is gates.apply_gates
    assert bench._digest is gates.digest
    assert bench._UNIT_KEY is gates.UNIT_KEY
    assert bench.ACCEL_ROOFLINE_BYTES_S == gates.ACCEL_ROOFLINE_BYTES_S


# ------------------------------------------------------------------ watchdog --


@pytest.fixture(autouse=True)
def _fresh_watchdog_counters(monkeypatch):
    """Isolated registry + reset call counters: the mismatch-path tests
    below record divergences ON PURPOSE, and those must never leak into
    the process registry — the run-level obs_report.json (and the CI
    smoke on it) asserts the real kernels diverged zero times."""
    from eth_consensus_specs_tpu.obs import registry as registry_mod

    watchdog.reset_for_tests()
    monkeypatch.setattr(registry_mod, "_REGISTRY", Registry())
    yield
    watchdog.reset_for_tests()


def _wd_counters():
    c = obs.snapshot()["counters"]
    return (
        c.get("watchdog.checks", 0),
        c.get("watchdog.divergences", 0),
    )


def test_watchdog_sha256_clean_and_mismatch_paths():
    rng = np.random.default_rng(3)
    words = rng.integers(0, 2**32, size=(8, 16), dtype=np.uint64).astype(np.uint32)
    from eth_consensus_specs_tpu.ops.sha256 import sha256_64B_batch_np

    digests8 = (
        sha256_64B_batch_np(words.astype(">u4").view(np.uint8).reshape(8, 64))
        .view(">u4")
        .astype(np.uint32)
        .reshape(8, 8)
    )
    checks0, div0 = _wd_counters()
    assert watchdog.check_sha256_slice(words, digests8)
    checks1, div1 = _wd_counters()
    assert checks1 == checks0 + 1 and div1 == div0

    corrupted = digests8.copy()
    corrupted[0, 0] ^= 1  # the device "did" the wrong work
    assert not watchdog.check_sha256_slice(words, corrupted)
    checks2, div2 = _wd_counters()
    assert checks2 == checks1 + 1
    assert div2 == div1 + 1  # the mismatch is a first-class metric


def test_watchdog_merkle_full_replay_and_mismatch():
    rng = np.random.default_rng(4)
    words = rng.integers(0, 2**32, size=(16, 8), dtype=np.uint64).astype(np.uint32)
    root = watchdog.host_tree_root_words(words)
    assert watchdog.check_merkle_root(words, 4, root)
    _, div0 = _wd_counters()
    assert not watchdog.check_merkle_root(words, 4, b"\x00" * 32)
    _, div1 = _wd_counters()
    assert div1 == div0 + 1


def test_watchdog_shuffle_spec_loop_matches_device():
    from eth_consensus_specs_tpu.ops.shuffle import shuffle_permutation

    n, seed, rounds = 201, b"\x07" * 32, 10
    perm = shuffle_permutation(n, seed, rounds)
    assert watchdog.check_shuffle_slice(perm, n, seed, rounds)
    bad = perm.copy()
    bad[0] = (bad[0] + 1) % n
    _, div0 = _wd_counters()
    assert not watchdog.check_shuffle_slice(bad, n, seed, rounds)
    _, div1 = _wd_counters()
    assert div1 == div0 + 1


def test_watchdog_sampling_rate_env(monkeypatch):
    monkeypatch.setenv("ETH_SPECS_OBS_WATCHDOG", "0")
    assert not watchdog.should_check("never_kernel")
    monkeypatch.setenv("ETH_SPECS_OBS_WATCHDOG", "1")
    assert watchdog.should_check("always_kernel")
    assert watchdog.should_check("always_kernel")
    monkeypatch.setenv("ETH_SPECS_OBS_WATCHDOG", "0.5")
    hits = [watchdog.should_check("half_kernel") for _ in range(4)]
    assert hits == [True, False, True, False]


def test_watchdog_first_call_always_checked(monkeypatch):
    monkeypatch.setenv("ETH_SPECS_OBS_WATCHDOG", "0.01")
    assert watchdog.should_check("rare_kernel")  # call 1 of interval 100
    assert not watchdog.should_check("rare_kernel")


# ------------------------------------------------------ end-to-end kernel obs --


def test_kernel_counters_fixture_sees_device_tree(kernel_counters, monkeypatch):
    monkeypatch.setenv("ETH_SPECS_OBS_WATCHDOG", "1")
    from eth_consensus_specs_tpu.ops.merkle import merkleize_subtree_device

    rng = np.random.default_rng(5)
    chunks = rng.integers(0, 256, size=(32, 32), dtype=np.uint8)
    root = merkleize_subtree_device(chunks, 5)
    delta = kernel_counters()
    assert delta["merkle.trees"] == 1
    assert delta["merkle.leaf_chunks"] == 32
    assert delta.get("watchdog.merkle.checks", 0) >= 1
    assert delta.get("watchdog.merkle.divergences", 0) == 0
    # the watchdog's zero-XLA host oracle agrees with the device root
    words = chunks.view(">u4").astype(np.uint32).reshape(32, 8)
    assert watchdog.host_tree_root_words(words) == root
    spans = obs.snapshot()["spans"]
    assert "merkle.subtree_root" in spans
    assert "roofline_ok" in spans["merkle.subtree_root"]
