"""ssz_static-style coverage: every container type of every implemented
fork round-trips serialize/deserialize/hash_tree_root over randomized
values in all randomization modes (reference analogue: the ssz_static
vector family driven by eth2spec/debug/random_value.py)."""

from random import Random

import pytest

from eth_consensus_specs_tpu.debug import (
    RandomizationMode,
    decode,
    encode,
    get_random_ssz_object,
)
from eth_consensus_specs_tpu.forks import available_forks, get_spec
from eth_consensus_specs_tpu.ssz import deserialize, hash_tree_root, serialize
from eth_consensus_specs_tpu.ssz.types import Container


def _container_types(spec):
    seen = {}
    for name, typ in vars(spec).items():
        if isinstance(typ, type) and issubclass(typ, Container) and typ is not Container:
            seen[name] = typ
    return seen


@pytest.mark.parametrize("fork", available_forks())
def test_ssz_static_round_trip(fork):
    spec = get_spec(fork, "minimal")
    rng = Random(12345)
    types = _container_types(spec)
    assert types, f"no container types found for {fork}"
    for name, typ in types.items():
        for mode in (
            RandomizationMode.mode_random,
            RandomizationMode.mode_zero,
            RandomizationMode.mode_max,
        ):
            value = get_random_ssz_object(rng, typ, mode=mode)
            encoded = serialize(value)
            decoded = deserialize(typ, encoded)
            assert decoded == value, f"{fork}.{name} [{mode}] round-trip mismatch"
            assert hash_tree_root(decoded) == hash_tree_root(value)
            # byte-stability: re-serialization is identical
            assert serialize(decoded) == encoded


@pytest.mark.parametrize("fork", ["phase0", "electra"])
def test_ssz_static_encode_decode(fork):
    """debug.encode/decode round-trip through plain python structures."""
    spec = get_spec(fork, "minimal")
    rng = Random(999)
    for name, typ in _container_types(spec).items():
        value = get_random_ssz_object(rng, typ, mode=RandomizationMode.mode_random)
        plain = encode(value)
        rebuilt = decode(plain, typ)
        assert rebuilt == value, f"{fork}.{name} encode/decode mismatch"
        assert hash_tree_root(rebuilt) == hash_tree_root(value)


def test_random_modes_vary_counts():
    spec = get_spec("phase0", "minimal")
    rng = Random(7)
    t = spec.BeaconState.fields()["historical_roots"]
    nil = get_random_ssz_object(rng, t, mode=RandomizationMode.mode_nil_count)
    one = get_random_ssz_object(rng, t, mode=RandomizationMode.mode_one_count)
    assert len(nil) == 0
    assert len(one) == 1
