"""Device-resident multi-epoch API (parallel/resident.py)."""

import jax
import numpy as np
import pytest

# device resident-loop compiles — nightly lane (make test-full)
pytestmark = pytest.mark.slow

from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.parallel import resident
from eth_consensus_specs_tpu.test_infra.genesis import create_genesis_state
from eth_consensus_specs_tpu.utils import bls


@pytest.fixture(scope="module")
def altair_state():
    spec = get_spec("altair", "minimal")
    prev = bls.bls_active
    bls.bls_active = False
    try:
        state = create_genesis_state(
            spec, [spec.MAX_EFFECTIVE_BALANCE] * 64, spec.MAX_EFFECTIVE_BALANCE
        )
        # park one slot before an epoch boundary — the phase at which the
        # columnar extraction runs inside process_slots
        spec.process_slots(state, 2 * int(spec.SLOTS_PER_EPOCH) - 1)
    finally:
        bls.bls_active = prev
    return spec, state


def test_chaining_consistency(altair_state):
    """run_epochs(2) == run_epochs(1) applied twice."""
    spec, state = altair_state
    cols, just = resident.ingest(spec, state)
    two = resident.run_epochs(spec, cols, just, 2, with_root=False)
    one = resident.run_epochs(spec, cols, just, 1, with_root=False)
    one_again = resident.run_epochs(spec, one.cols, one.just, 1, with_root=False)
    assert (np.asarray(two.cols.balance) == np.asarray(one_again.cols.balance)).all()
    assert int(two.just.current_epoch) == int(one_again.just.current_epoch)


def test_root_chain_changes_with_balances(altair_state):
    spec, state = altair_state
    cols, just = resident.ingest(spec, state)
    a = resident.run_epochs(spec, cols, just, 1, with_root=True)
    salted = cols._replace(balance=cols.balance + jax.numpy.uint64(1))
    b = resident.run_epochs(spec, salted, just, 1, with_root=True)
    assert bytes(np.asarray(a.root_acc)) != bytes(np.asarray(b.root_acc))


def test_single_epoch_matches_kernel(altair_state):
    """One resident epoch == one direct kernel application."""
    from eth_consensus_specs_tpu.ops.altair_epoch import (
        AltairEpochParams,
        altair_epoch_accounting,
    )

    spec, state = altair_state
    cols, just = resident.ingest(spec, state)
    res = altair_epoch_accounting(AltairEpochParams.from_spec(spec), cols, just)
    out = resident.run_epochs(spec, cols, just, 1, with_root=False)
    assert (np.asarray(res.balance) == np.asarray(out.cols.balance)).all()
    assert (np.asarray(res.effective_balance) == np.asarray(out.cols.effective_balance)).all()


def test_writeback_applies(altair_state):
    spec, state = altair_state
    work = state.copy()
    cols, just = resident.ingest(spec, work)
    carry = resident.run_epochs(spec, cols, just, 1, with_root=False)
    resident.writeback(spec, work, carry)
    assert [int(b) for b in work.balances] == [
        int(x) for x in np.asarray(carry.cols.balance)
    ]


def _acc_bytes(carry) -> bytes:
    return bytes(np.asarray(carry.root_acc))


def test_incremental_root_bit_identical_to_full_chain(altair_state):
    """with_root="state_inc" == with_root="state" across N chained
    epochs — the incremental forest's xor-chain root_acc must be the
    full recompute's, bit for bit (64 validators: a NON-pow2-chunk
    registry — 16 balance chunks but 64 validator leaves — exercising
    the pad/fold corners)."""
    spec, state = altair_state
    cols, just, static = resident.ingest_full(spec, state)
    for epochs in (1, 3):
        full = resident.run_epochs(spec, cols, just, epochs, with_root="state", static=static)
        inc = resident.run_epochs(spec, cols, just, epochs, with_root="state_inc", static=static)
        assert _acc_bytes(inc) == _acc_bytes(full), f"epochs={epochs}"
        assert inc.forest is not None


def test_incremental_forest_chains_across_calls(altair_state):
    """carry.forest threads into the next run: 1+2 chained epochs'
    xor-accumulated roots equal one 3-epoch run's."""
    spec, state = altair_state
    cols, just, static = resident.ingest_full(spec, state)
    three = resident.run_epochs(spec, cols, just, 3, with_root="state_inc", static=static)
    one = resident.run_epochs(spec, cols, just, 1, with_root="state_inc", static=static)
    two = resident.run_epochs(
        spec, one.cols, one.just, 2, with_root="state_inc", static=static,
        forest=one.forest,
    )
    acc = np.asarray(one.root_acc) ^ np.asarray(two.root_acc)
    assert bytes(acc) == _acc_bytes(three)


def test_incremental_non_pow2_registry():
    """A 48-validator registry: non-pow2 validator leaves AND non-pow2
    chunk counts — pads must behave exactly like the full path's
    zero-chunk padding."""
    spec = get_spec("altair", "minimal")
    prev = bls.bls_active
    bls.bls_active = False
    try:
        state = create_genesis_state(
            spec, [spec.MAX_EFFECTIVE_BALANCE] * 48, spec.MAX_EFFECTIVE_BALANCE
        )
        spec.process_slots(state, 2 * int(spec.SLOTS_PER_EPOCH) - 1)
    finally:
        bls.bls_active = prev
    cols, just, static = resident.ingest_full(spec, state)
    full = resident.run_epochs(spec, cols, just, 2, with_root="state", static=static)
    inc = resident.run_epochs(spec, cols, just, 2, with_root="state_inc", static=static)
    assert _acc_bytes(inc) == _acc_bytes(full)


def test_incremental_mesh_parity(altair_state):
    """chips=1 vs chips=N: the forest's leaf axes shard over the
    suite's 8-virtual-device mesh and the root_acc stays bit-identical
    (per-shard path updates + the all-gather top combine)."""
    from eth_consensus_specs_tpu.parallel.mesh_ops import serve_mesh

    spec, state = altair_state
    mesh = serve_mesh()
    cols, just, static = resident.ingest_full(spec, state)
    plan = resident.forest_plan_for(static, mesh=mesh)
    if mesh is None or plan.shards <= 1:
        pytest.skip("needs the 8-virtual-device mesh")
    single = resident.run_epochs(spec, cols, just, 2, with_root="state_inc", static=static)
    sharded = resident.run_epochs(
        spec, cols, just, 2, with_root="state_inc", static=static, mesh=mesh
    )
    assert plan.shards > 1
    assert _acc_bytes(sharded) == _acc_bytes(single)
