"""Native batched G2 line-coefficient producer vs the host oracle.

`bls_g2_prepare_many` (native/bls12_381.c) walks all G2 points of a
pairing batch in lockstep — Montgomery batch inversions across walks,
limbs emitted directly in the device kernel's 2^390-Montgomery 26-bit
encoding — and must reproduce ops/pairing_device.prepare_g2 (the
per-point host oracle) BIT-FOR-BIT, because both feed the same device
Miller kernel.  Reference seam being accelerated: the per-verification
pairing inputs of utils/bls.py:224-296.
"""

from __future__ import annotations

import numpy as np
import pytest

from eth_consensus_specs_tpu.crypto import native_bridge as nb
from eth_consensus_specs_tpu.crypto.curve import g2_generator
from eth_consensus_specs_tpu.crypto.hash_to_curve import hash_to_g2

pytestmark = pytest.mark.skipif(
    not nb.enabled(), reason="native core unavailable"
)


def _tuples(q):
    return ((q.x.c0.n, q.x.c1.n), (q.y.c0.n, q.y.c1.n))


def test_native_prepare_matches_host_oracle():
    from eth_consensus_specs_tpu.ops.pairing_device import prepare_g2

    qs = [g2_generator().mul(i + 3) for i in range(3)]
    qs += [hash_to_g2(bytes([i])) for i in range(3)]
    rows = nb.g2_prepare_many([_tuples(q) for q in qs])
    assert rows is not None
    assert rows.shape[0] == len(qs)
    for i, q in enumerate(qs):
        ref = prepare_g2(q)
        assert ref.shape == rows[i].shape
        assert np.array_equal(ref, rows[i]), f"row mismatch for point {i}"


def test_native_prepare_rejects_infinity():
    # callers mask infinities before batching; the bridge refuses them
    assert nb.g2_prepare_many([None]) is None


def test_native_prepare_empty():
    assert nb.g2_prepare_many([]) is None


def test_prepare_all_fills_cache_identically():
    """The batch pre-fill path must leave exactly what per-point prepare
    would have computed (a wrong cache entry would silently corrupt every
    later pairing that hits it)."""
    from eth_consensus_specs_tpu.ops import pairing_device as pd

    g1 = __import__(
        "eth_consensus_specs_tpu.crypto.curve", fromlist=["g1_generator"]
    ).g1_generator()
    qs = [hash_to_g2(b"cache-%d" % i) for i in range(3)]
    pairs = [(g1, q) for q in qs]
    pd._PREP_CACHE.clear()
    pd._prepare_all(pairs)
    assert len(pd._PREP_CACHE) == len(qs)
    for q in qs:
        assert np.array_equal(pd._PREP_CACHE[(q.x, q.y)], pd.prepare_g2(q))
    pd._PREP_CACHE.clear()
