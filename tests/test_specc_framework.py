"""Framework self-tests for the spec-oracle compiler (reference analogue:
tests/infra/test_md_to_spec.py — the reference unit-tests its markdown->
spec pipeline as a first-class tier; SURVEY §4 tier 1)."""

import os

import pytest

from eth_consensus_specs_tpu.specc import compiler as c
from eth_consensus_specs_tpu.specc.parser import parse_doc

DOC = '''# Sample spec

## Custom types

| Name | SSZ equivalent | Description |
| - | - | - |
| `Widget` | `uint64` | a widget |

## Constants

| Name | Value |
| - | - |
| `WIDGET_LIMIT` | `uint64(2**4)` (= 16) |

## Containers

```python
class Box(Container):
    w: Widget
```

## Helpers

```python
def double_widget(w: Widget) -> Widget:
    return Widget(w * 2)
```

```python
def get_payload(self: ExecutionEngine, payload_id) -> bool:
    return True
```
'''


@pytest.fixture()
def doc(tmp_path):
    p = tmp_path / "sample.md"
    p.write_text(DOC)
    return parse_doc(str(p))


def test_parser_classifies_functions(doc):
    assert "double_widget" in doc.functions
    assert "double_widget" not in doc.protocol_methods


def test_parser_classifies_protocol_methods(doc):
    # first parameter `self` routes to the protocol bucket
    assert "get_payload" in doc.protocol_methods
    assert "get_payload" not in doc.functions


def test_parser_classifies_classes(doc):
    assert "Box" in doc.classes
    assert "class Box(Container):" in doc.classes["Box"]


def test_parser_table_items_in_document_order(doc):
    kinds = [k for k, _, _ in doc.table_items]
    names = [n for _, n, _ in doc.table_items]
    assert names == ["Widget", "WIDGET_LIMIT"]
    assert kinds == ["ctype", "const"]


def test_parser_constant_value_expression(doc):
    (_, _, expr) = [t for t in doc.table_items if t[1] == "WIDGET_LIMIT"][0]
    assert expr == "uint64(2**4)"


def test_parse_doc_from_text_matches_file(tmp_path):
    p = tmp_path / "b.md"
    p.write_text(DOC)
    via_file = parse_doc(str(p))
    via_text = parse_doc(str(p), text=DOC)
    assert via_file.functions.keys() == via_text.functions.keys()
    assert via_file.table_items == via_text.table_items


# == compiled-oracle structure ==============================================


def test_compile_fork_exposes_spec_surface():
    m = c.compile_fork("phase0", "minimal")
    assert callable(m.state_transition)
    assert callable(m.process_epoch)
    assert m.SLOTS_PER_EPOCH == 8  # minimal preset substitution


def test_compile_fork_preset_substitution_differs():
    minimal = c.compile_fork("phase0", "minimal")
    mainnet = c.compile_fork("phase0", "mainnet")
    assert int(minimal.SLOTS_PER_EPOCH) != int(mainnet.SLOTS_PER_EPOCH)


def test_compile_fork_lineage_override():
    """A later fork's markdown redefinition replaces the ancestor's."""
    p0 = c.compile_fork("phase0", "minimal")
    altair = c.compile_fork("altair", "minimal")
    # altair modifies process_epoch (adds inactivity/participation steps)
    assert p0.process_epoch.__code__.co_code != altair.process_epoch.__code__.co_code


def test_compile_fork_ancestor_modules_linked():
    electra = c.compile_fork("electra", "minimal")
    # upgrade functions address ancestors as modules
    assert hasattr(electra, "deneb")
    assert callable(electra.deneb.get_current_epoch)


def test_compile_fork_builder_classes_injected():
    deneb = c.compile_fork("deneb", "minimal")
    from eth_consensus_specs_tpu.utils.bls import Scalar

    assert issubclass(deneb.BLSFieldElement, Scalar)
    poly = deneb.Polynomial()
    assert len(poly) == int(deneb.FIELD_ELEMENTS_PER_BLOB)


def test_compile_fork_rejects_unknown_fork():
    with pytest.raises(ValueError):
        c.compile_fork("notafork", "minimal")


def test_fork_choice_namespace_layers_on_top():
    plain = c.compile_fork("phase0", "minimal")
    fc = c.compile_fork("phase0", "minimal", None, True)
    assert not hasattr(plain, "on_block")
    assert hasattr(fc, "on_block") and hasattr(fc, "Store")
    # beacon-chain surface identical in both
    assert plain.SLOTS_PER_EPOCH == fc.SLOTS_PER_EPOCH


def test_zero_skip_reports_across_lineage():
    for fork in c.CHAIN:
        rep = c.compile_fork(fork, "minimal").__specc_report__
        assert not rep.skipped_constants, (fork, rep.skipped_constants)
        assert not rep.skipped_types, (fork, rep.skipped_types)


# == content pinning ========================================================


def test_pins_cover_every_compiled_doc():
    pins = c._load_pins()
    for fork in c.CHAIN:
        for name in c.DOC_SETS[fork] + c.FC_DOCS.get(fork, []):
            rel = os.path.join("specs", fork, name)
            full = os.path.join(c.REFERENCE_SPECS, rel)
            if os.path.exists(full):
                assert rel in pins, f"unpinned compiled doc {rel}"


def test_read_pinned_rejects_tampered_content(tmp_path, monkeypatch):
    target = os.path.join(c.REFERENCE_SPECS, "specs", "phase0", "beacon-chain.md")
    tampered = tmp_path / "beacon-chain.md"
    tampered.write_text(open(target).read() + "\n<!-- tampered -->\n")

    real_relpath = os.path.relpath

    def fake_relpath(path, start):
        if str(tampered) in str(path):
            return os.path.join("specs", "phase0", "beacon-chain.md")
        return real_relpath(path, start)

    monkeypatch.setattr(os.path, "relpath", fake_relpath)
    with pytest.raises(RuntimeError, match="content hash"):
        c._read_pinned(str(tampered))


def test_read_pinned_rejects_unpinned_file(tmp_path):
    stray = tmp_path / "stray.md"
    stray.write_text("# not a spec\n")
    with pytest.raises(RuntimeError, match="not in pins.json"):
        c._read_pinned(str(stray))
