"""Columnar block-processing kernel (ops/block_epoch.py) vs the object
path and vs the numpy host oracle — BASELINE config #4's bit-exactness
gates (an epoch of blocks: attestations, sync rewards, deposits,
withdrawal sweep, per-slot dirty roots)."""

import numpy as np
import pytest

import jax.numpy as jnp

from eth_consensus_specs_tpu.ops import block_epoch as bek
from eth_consensus_specs_tpu.ops import block_epoch_host as bekh
from eth_consensus_specs_tpu.test_infra.attestations import get_valid_attestations_at_slot
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases


def _build_epoch_blocks(spec, state, n_blocks=None):
    """Blocks for slots epoch_start+1 .. epoch_start+n inside ONE epoch
    (no boundary crossing), full attestations each slot."""
    if n_blocks is None:
        n_blocks = int(spec.SLOTS_PER_EPOCH) - 1
    blocks = []
    for _ in range(n_blocks):
        block = build_empty_block_for_next_slot(spec, state)
        if int(state.slot) >= spec.MIN_ATTESTATION_INCLUSION_DELAY:
            slot_to_attest = int(state.slot) - spec.MIN_ATTESTATION_INCLUSION_DELAY + 1
            if slot_to_attest >= spec.compute_start_slot_at_epoch(
                spec.get_current_epoch(state)
            ):
                for att in get_valid_attestations_at_slot(spec, state, slot_to_attest):
                    block.body.attestations.append(att)
        blocks.append(state_transition_and_sign_block(spec, state, block))
    return blocks


def _static_from_state(spec, params, state):
    n = len(state.validators)
    eff = np.array([int(v.effective_balance) for v in state.validators], np.uint64)
    wd = np.array(
        [min(int(v.withdrawable_epoch), 2**64 - 1) for v in state.validators], np.uint64
    )
    cred = np.array(
        [bytes(v.withdrawal_credentials)[:1] == b"\x01" for v in state.validators], bool
    )
    static = bek.make_epoch_static(
        params,
        jnp.asarray(eff),
        jnp.asarray(wd),
        jnp.asarray(cred),
        int(spec.get_current_epoch(state)),
    )
    return static, eff, wd, cred


def _run_parity(spec, state, with_withdrawals):
    pre_state = state.copy()
    blocks = _build_epoch_blocks(spec, state)
    obj = state  # advanced in place by the builder

    params = bek.BlockEpochParams.from_spec(spec)
    n = len(pre_state.validators)
    cols, st0 = bek.extract_block_columns(spec, pre_state, blocks)
    static, eff, wd, cred = _static_from_state(spec, params, pre_state)

    st, _acc = bek.block_epoch_chain(
        params, n, st0, cols, static, root_ctx=None, with_withdrawals=with_withdrawals
    )

    assert np.array_equal(
        np.asarray(st.balance), np.array([int(b) for b in obj.balances], np.uint64)
    ), "balances diverge from the object path"
    assert np.array_equal(
        np.asarray(st.cur_part),
        np.array([int(f) for f in obj.current_epoch_participation], np.uint8),
    )
    assert np.array_equal(
        np.asarray(st.prev_part),
        np.array([int(f) for f in obj.previous_epoch_participation], np.uint8),
    )
    if with_withdrawals:
        assert int(np.asarray(st.next_wd_index)) == int(obj.next_withdrawal_index)
        assert int(np.asarray(st.next_wd_validator)) == int(
            obj.next_withdrawal_validator_index
        )

    # triangle leg 2: the numpy host oracle replays the same columns
    bal_h, cur_h, prev_h, wdi_h, wdv_h, _ = bekh.replay_block_epoch_np(
        params,
        n,
        st0,
        cols,
        eff,
        wd,
        cred,
        int(spec.get_current_epoch(pre_state)),
        with_withdrawals=with_withdrawals,
    )
    assert np.array_equal(np.asarray(st.balance), bal_h)
    assert np.array_equal(np.asarray(st.cur_part), cur_h)
    assert np.array_equal(np.asarray(st.prev_part), prev_h)
    if with_withdrawals:
        assert int(np.asarray(st.next_wd_index)) == wdi_h
        assert int(np.asarray(st.next_wd_validator)) == wdv_h


@with_phases(["altair"])
@spec_state_test
def test_block_epoch_parity_altair(spec, state):
    _run_parity(spec, state, with_withdrawals=False)


@with_phases(["electra"])
@spec_state_test
def test_block_epoch_parity_electra_onchain_aggregates(spec, state):
    """EIP-7549 on-chain aggregates: multi-committee attestations expand
    into per-committee rows with one proposer-reward division per
    aggregate (the carried-numerator path)."""
    _run_parity(spec, state, with_withdrawals=True)


@with_phases(["deneb"])
@spec_state_test
def test_block_epoch_parity_deneb_withdrawals(spec, state):
    # make the sweep actually pay: eth1 credentials + excess balances on a
    # stripe of validators, two fully-withdrawable ones
    for i in range(0, len(state.validators), 5):
        state.validators[i].withdrawal_credentials = b"\x01" + b"\x00" * 11 + bytes(
            [i % 256]
        ) * 20
        state.balances[i] = int(state.balances[i]) + 1_000_000_000
    state.validators[2].withdrawable_epoch = 0
    state.validators[7].withdrawable_epoch = 0
    _run_parity(spec, state, with_withdrawals=True)


def test_synthetic_chain_kernel_vs_oracle_with_roots():
    """Full synthetic chain at small n: device kernel with per-slot dirty
    roots == numpy oracle with native-SHA roots (the exact coupling the
    block_epoch bench section publishes under)."""
    from eth_consensus_specs_tpu.forks import get_spec
    from eth_consensus_specs_tpu.ops.state_root import synthetic_static

    spec = get_spec("deneb", "mainnet")
    n = 1 << 10
    cols, st0, static = bek.synthetic_block_columns(spec, n, seed=3, atts_per_slot=8)
    params = bek.BlockEpochParams.from_spec(spec)

    import __graft_entry__ as graft

    _, just = graft._example_altair_inputs(n)
    scores = jnp.asarray(
        np.random.default_rng(9).integers(0, 50, n, dtype=np.int64).astype(np.uint64)
    )
    arrays, meta = synthetic_static(spec, n)
    ctx = bek.make_root_ctx(spec, arrays, meta, static, scores, just)

    st, acc = bek.block_epoch_chain(params, n, st0, cols, static, root_ctx=ctx)

    root_fn = bekh.slot_root_fn_np(spec, arrays, meta, static, scores, just)
    bal_h, cur_h, prev_h, wdi_h, wdv_h, acc_h = bekh.replay_block_epoch_np(
        params,
        n,
        st0,
        cols,
        np.asarray(static.eff_balance),
        np.asarray(static.withdrawable_epoch),
        np.asarray(static.has_eth1_cred),
        int(np.asarray(static.epoch)),
        root_fn=root_fn,
    )
    assert np.array_equal(np.asarray(st.balance), bal_h)
    assert np.array_equal(np.asarray(st.cur_part), cur_h)
    assert np.array_equal(np.asarray(st.prev_part), prev_h)
    assert int(np.asarray(st.next_wd_index)) == wdi_h
    assert int(np.asarray(st.next_wd_validator)) == wdv_h
    assert np.array_equal(np.asarray(acc), acc_h), "per-slot root xor-chain diverges"
