"""Native BLS12-381 core vs the pure-Python oracle.

Every operation the C core (native/bls12_381.c) exports is cross-checked
against the first-party Python tower with the bridge disabled — the same
oracle discipline the reference applies between its milagro/arkworks
backends and py_ecc (reference: tests/core/pyspec/eth2spec/utils/bls.py).
"""

import random

import pytest

from eth_consensus_specs_tpu.crypto import curve, native_bridge as nb, pairing
from eth_consensus_specs_tpu.crypto.fields import Fq, Fq2, P, R
from eth_consensus_specs_tpu.crypto.hash_to_curve import (
    H_EFF,
    clear_cofactor_g2,
    hash_to_field_fq2,
    hash_to_g2,
    map_to_curve_g2,
)
from eth_consensus_specs_tpu.utils import bls

pytestmark = pytest.mark.skipif(not nb.enabled(), reason="native core unavailable")

_rng = random.Random(20260730)


def _rand_g1():
    return curve.g1_generator().mul(_rng.randrange(1, R))


def _rand_g2():
    return curve.g2_generator().mul(_rng.randrange(1, R))


def test_selftest():
    from eth_consensus_specs_tpu.native import get_bls_lib

    assert get_bls_lib().bls_selftest() == 0


def test_scalar_mul_matches_python():
    g1, g2 = curve.g1_generator(), curve.g2_generator()
    for k in [1, 2, 3, 0xFFFF, _rng.randrange(R), R - 1, R, R + 5, H_EFF]:
        native1 = g1.mul(k)
        native2 = g2.mul(k)
        with nb.disabled():
            assert native1 == g1.mul(k)
            assert native2 == g2.mul(k)


def test_field_inv_sqrt_match_python():
    for _ in range(5):
        a = Fq(_rng.randrange(1, P))
        b = Fq2(Fq(_rng.randrange(P)), Fq(_rng.randrange(1, P)))
        with nb.disabled():
            ia, ib = a.inv(), b.inv()
            sa, sb = a.square().sqrt(), b.square().sqrt()
        assert a.inv() == ia
        assert b.inv() == ib
        assert a.square().sqrt() == sa
        assert b.square().sqrt() == sb


def test_sqrt_nonresidue_agrees():
    hits = 0
    for i in range(8):
        a = Fq(_rng.randrange(1, P))
        with nb.disabled():
            expect = a.sqrt()
        got = a.sqrt()
        assert (got is None) == (expect is None)
        if expect is not None:
            assert got == expect
            hits += 1
    assert 0 < hits < 8 or True  # both residues and non-residues seen typically


def test_pairing_value_exact():
    p, q = _rand_g1(), _rand_g2()
    native = pairing.pairing(p, q)
    with nb.disabled():
        expect = pairing.pairing(p, q)
    assert native == expect


def test_pairing_check_bilinearity():
    g1, g2 = curve.g1_generator(), curve.g2_generator()
    a, b = _rng.randrange(1, 2**30), _rng.randrange(1, 2**30)
    good = [(g1.mul(a), g2.mul(b)), (-(g1.mul(a * b)), g2)]
    bad = [(g1.mul(a), g2.mul(b)), (g1.mul(a * b), g2)]
    assert pairing.pairing_check(good)
    assert not pairing.pairing_check(bad)
    with nb.disabled():
        assert pairing.pairing_check(good)
        assert not pairing.pairing_check(bad)


def test_g2_subgroup_check_vs_oracle():
    # uncleaned map_to_curve outputs are on E2 but not in G2
    for tag in [b"p0", b"p1"]:
        u = hash_to_field_fq2(tag, 2)
        raw = map_to_curve_g2(u[0]) + map_to_curve_g2(u[1])
        with nb.disabled():
            oracle = raw.mul(R).is_infinity()
        assert curve.in_subgroup(raw) == oracle
        assert not oracle
        cleared = clear_cofactor_g2(raw)
        assert curve.in_subgroup(cleared)
        with nb.disabled():
            assert cleared.mul(R).is_infinity()


def test_clear_cofactor_bit_exact():
    u = hash_to_field_fq2(b"cc", 2)
    raw = map_to_curve_g2(u[0]) + map_to_curve_g2(u[1])
    fast = clear_cofactor_g2(raw)
    with nb.disabled():
        assert fast == raw.mul(H_EFF)


def test_hash_to_g2_matches_python():
    msg = b"native-vs-python"
    native = hash_to_g2(msg)
    with nb.disabled():
        expect = hash_to_g2(msg)
    assert native == expect


def test_msm_matches_naive():
    pts = [_rand_g1() for _ in range(9)] + [curve.g1_infinity()]
    scalars = [_rng.randrange(R) for _ in range(10)]
    fast = bls.multi_exp(pts, scalars)
    with nb.disabled():
        expect = bls.multi_exp(pts, scalars)
    assert fast == expect
    pts2 = [_rand_g2() for _ in range(6)]
    scalars2 = [_rng.randrange(R) for _ in range(6)]
    fast2 = bls.multi_exp(pts2, scalars2)
    with nb.disabled():
        expect2 = bls.multi_exp(pts2, scalars2)
    assert fast2 == expect2


def test_aggregate_matches_python():
    sks = list(range(1, 12))
    msg = b"agg" * 10
    sigs = [bls.Sign(sk, msg) for sk in sks]
    pks = [bls.SkToPk(sk) for sk in sks]
    fast_sig = bls.Aggregate(sigs)
    fast_pk = bls.AggregatePKs(pks)
    with nb.disabled():
        assert bls.Aggregate(sigs) == fast_sig
        assert bls.AggregatePKs(pks) == fast_pk
    assert bls.FastAggregateVerify(pks, msg, fast_sig)
    assert not bls.FastAggregateVerify(pks, b"other", fast_sig)


def test_sign_verify_roundtrip_both_paths():
    msg = b"roundtrip"
    native_sig = bls.Sign(7, msg)
    with nb.disabled():
        oracle_sig = bls.Sign(7, msg)
        assert oracle_sig == native_sig
        assert bls.Verify(bls.SkToPk(7), msg, oracle_sig)
    assert bls.Verify(bls.SkToPk(7), msg, native_sig)
    assert not bls.Verify(bls.SkToPk(8), msg, native_sig)
