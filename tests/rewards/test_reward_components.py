"""Per-component reward/penalty tables (reference analogue: the dense
test/<fork>/rewards/ suites — basic/leak/random per component; spec:
specs/altair/beacon-chain.md get_flag_index_deltas,
specs/phase0/beacon-chain.md:1527+)."""

from eth_consensus_specs_tpu.test_infra.attestations import (
    next_epoch_with_attestations,
)
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.state import next_epoch

ALTAIR_PLUS = ["altair", "deneb", "electra"]
PHASE0 = ["phase0"]


def _full_participation_state(spec, state):
    next_epoch(spec, state)
    _, _, state = next_epoch_with_attestations(spec, state, True, False)
    return state


# == altair flag components ================================================


@with_phases(ALTAIR_PLUS)
@spec_state_test
def test_each_flag_component_rewards_full_participation(spec, state):
    state = _full_participation_state(spec, state)
    for flag_index in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
        rewards, penalties = spec.get_flag_index_deltas(state, flag_index)
        assert sum(int(r) for r in rewards) > 0
        assert all(int(p) == 0 for p in penalties)


@with_phases(ALTAIR_PLUS)
@spec_state_test
def test_head_flag_never_penalizes(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)  # zero participation for the previous epoch
    head_flag = int(spec.TIMELY_HEAD_FLAG_INDEX)
    rewards, penalties = spec.get_flag_index_deltas(state, head_flag)
    assert all(int(r) == 0 for r in rewards)
    assert all(int(p) == 0 for p in penalties)


@with_phases(ALTAIR_PLUS)
@spec_state_test
def test_source_and_target_penalize_nonparticipants(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    for flag_index in (
        int(spec.TIMELY_SOURCE_FLAG_INDEX),
        int(spec.TIMELY_TARGET_FLAG_INDEX),
    ):
        rewards, penalties = spec.get_flag_index_deltas(state, flag_index)
        assert all(int(r) == 0 for r in rewards)
        assert sum(int(p) for p in penalties) > 0


@with_phases(ALTAIR_PLUS)
@spec_state_test
def test_flag_reward_proportional_to_effective_balance(spec, state):
    state = _full_participation_state(spec, state)
    # halve one validator's effective balance; its reward share halves
    idx = 2
    state.validators[idx].effective_balance = int(
        spec.MAX_EFFECTIVE_BALANCE
    ) // 2
    rewards, _ = spec.get_flag_index_deltas(state, int(spec.TIMELY_SOURCE_FLAG_INDEX))
    other = 3
    assert 0 < int(rewards[idx]) < int(rewards[other])


@with_phases(ALTAIR_PLUS)
@spec_state_test
def test_slashed_validator_gets_no_flag_rewards(spec, state):
    state = _full_participation_state(spec, state)
    idx = 4
    state.validators[idx].slashed = True
    rewards, penalties = spec.get_flag_index_deltas(
        state, int(spec.TIMELY_SOURCE_FLAG_INDEX)
    )
    assert int(rewards[idx]) == 0
    assert int(penalties[idx]) > 0  # treated as non-participating


@with_phases(ALTAIR_PLUS)
@spec_state_test
def test_rewards_zero_during_leak(spec, state):
    next_epoch(spec, state)
    for _ in range(int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 2):
        next_epoch(spec, state)
    assert spec.is_in_inactivity_leak(state)
    _, _, state = next_epoch_with_attestations(spec, state, True, False)
    rewards, _ = spec.get_flag_index_deltas(state, int(spec.TIMELY_SOURCE_FLAG_INDEX))
    assert all(int(r) == 0 for r in rewards)  # participation earns nothing in a leak


@with_phases(ALTAIR_PLUS)
@spec_state_test
def test_inactivity_penalty_proportional_to_score(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    a, b = 2, 3
    state.inactivity_scores[a] = 100
    state.inactivity_scores[b] = 200
    _, penalties = spec.get_inactivity_penalty_deltas(state)
    assert 0 < int(penalties[a]) < int(penalties[b])


@with_phases(ALTAIR_PLUS)
@spec_state_test
def test_base_reward_per_increment_formula(spec, state):
    total = int(spec.get_total_active_balance(state))
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    expected = (
        incr
        * int(spec.BASE_REWARD_FACTOR)
        // int(spec.integer_squareroot(total))
    )
    assert int(spec.get_base_reward_per_increment(state)) == expected


@with_phases(ALTAIR_PLUS)
@spec_state_test
def test_base_reward_scales_with_increments(spec, state):
    """base_reward = increments * base_reward_per_increment (changing one
    validator's balance also shifts total-active-balance, so compare
    against the formula, not a fixed ratio)."""
    idx = 5
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    state.validators[idx].effective_balance = int(spec.MAX_EFFECTIVE_BALANCE) // 2
    expected = (int(spec.MAX_EFFECTIVE_BALANCE) // 2 // incr) * int(
        spec.get_base_reward_per_increment(state)
    )
    assert int(spec.get_base_reward(state, idx)) == expected


# == phase0 components =====================================================


@with_phases(PHASE0)
@spec_state_test
def test_phase0_inclusion_delay_reward_decays(spec, state):
    """Later inclusion earns a smaller proposer-share-adjusted reward."""
    next_epoch(spec, state)
    _, _, state = next_epoch_with_attestations(spec, state, True, False)
    rewards_fast, _ = spec.get_inclusion_delay_deltas(state)
    # rebuild with delayed inclusion by bumping stored inclusion delays
    for a in state.previous_epoch_attestations:
        a.inclusion_delay = int(a.inclusion_delay) + 3
    rewards_slow, _ = spec.get_inclusion_delay_deltas(state)
    assert sum(int(r) for r in rewards_slow) < sum(int(r) for r in rewards_fast)


@with_phases(PHASE0)
@spec_state_test
def test_phase0_attestation_component_penalties_cover_all_misses(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    rewards, penalties = spec.get_attestation_deltas(state)
    active = spec.get_active_validator_indices(state, spec.get_previous_epoch(state))
    for i in active:
        assert int(penalties[int(i)]) > 0
        assert int(rewards[int(i)]) == 0


@with_phases(PHASE0)
@spec_state_test
def test_phase0_leak_penalizes_by_base_rewards(spec, state):
    next_epoch(spec, state)
    for _ in range(int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 2):
        next_epoch(spec, state)
    assert spec.is_in_inactivity_leak(state)
    _, penalties = spec.get_attestation_deltas(state)
    assert sum(int(p) for p in penalties) > 0


@with_phases(PHASE0)
@spec_state_test
def test_phase0_proposer_reward_nonzero_with_attestations(spec, state):
    next_epoch(spec, state)
    _, _, state = next_epoch_with_attestations(spec, state, True, False)
    rewards, _ = spec.get_inclusion_delay_deltas(state)
    proposers = {int(a.proposer_index) for a in state.previous_epoch_attestations}
    assert any(int(rewards[p]) > 0 for p in proposers)
