"""Reward/penalty accounting at the delta level
(reference: eth2spec/test/phase0/rewards/* via rewards/helpers; altair+
flag-delta semantics specs/altair/beacon-chain.md:398-486)."""

import pytest

from eth_consensus_specs_tpu.test_infra.attestations import next_epoch_with_attestations
from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test,
    with_all_phases,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.forks import is_post_altair
from eth_consensus_specs_tpu.test_infra.state import next_epoch

ALTAIR_ON = ["altair", "bellatrix", "capella", "deneb", "electra", "fulu", "gloas"]


@pytest.mark.slow  # multi-epoch full-attestation drive across the fork matrix
@with_phases(ALTAIR_ON)
@spec_state_test
def test_flag_deltas_full_participation(spec, state):
    """Every unslashed active validator with all flags earns every flag's
    reward component; no penalties."""
    next_epoch_with_attestations(spec, state, True, False)
    next_epoch_with_attestations(spec, state, True, False)
    # previous epoch now has full participation recorded
    for flag_index in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
        rewards, penalties = spec.get_flag_index_deltas(state, flag_index)
        participating = spec.get_unslashed_participating_indices(
            state, flag_index, spec.get_previous_epoch(state)
        )
        assert len(participating) > 0
        for index in range(len(state.validators)):
            if index in participating:
                assert rewards[index] > 0, (flag_index, index)
                assert penalties[index] == 0
            else:
                assert rewards[index] == 0


@with_phases(ALTAIR_ON)
@spec_state_test
def test_flag_deltas_empty_participation(spec, state):
    """No participation: zero rewards; head flag carries no penalty, the
    source/target flags penalize everyone active."""
    next_epoch(spec, state)
    next_epoch(spec, state)
    for flag_index in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
        rewards, penalties = spec.get_flag_index_deltas(state, flag_index)
        assert all(r == 0 for r in rewards)
        if flag_index == spec.TIMELY_HEAD_FLAG_INDEX:
            assert all(p == 0 for p in penalties)
        else:
            active = spec.get_active_validator_indices(state, spec.get_previous_epoch(state))
            for index in active:
                assert penalties[index] > 0


@pytest.mark.slow  # multi-epoch full-attestation drive across the fork matrix
@with_phases(ALTAIR_ON)
@spec_state_test
def test_inactivity_deltas_zero_outside_leak(spec, state):
    """Inactivity penalties only bite while scores are nonzero; with full
    participation and zero scores the deltas vanish."""
    next_epoch_with_attestations(spec, state, True, False)
    next_epoch_with_attestations(spec, state, True, False)
    rewards, penalties = spec.get_inactivity_penalty_deltas(state)
    assert all(r == 0 for r in rewards)
    assert all(p == 0 for p in penalties)


@with_phases(ALTAIR_ON)
@spec_state_test
def test_inactivity_scores_grow_in_leak(spec, state):
    """Past MIN_EPOCHS_TO_INACTIVITY_PENALTY without finality, the scores
    of non-participants climb by INACTIVITY_SCORE_BIAS per epoch."""
    # age the chain without attestations until in a leak
    for _ in range(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY + 2):
        next_epoch(spec, state)
    assert spec.is_in_inactivity_leak(state)
    before = [int(s) for s in state.inactivity_scores]
    next_epoch(spec, state)
    after = [int(s) for s in state.inactivity_scores]
    active = set(spec.get_active_validator_indices(state, spec.get_previous_epoch(state)))
    for i in range(len(after)):
        if i in active:
            assert after[i] == before[i] + spec.config.INACTIVITY_SCORE_BIAS
    # and the inactivity deltas now penalize proportionally to the score
    rewards, penalties = spec.get_inactivity_penalty_deltas(state)
    assert all(r == 0 for r in rewards)
    assert any(p > 0 for p in penalties)


@pytest.mark.slow  # multi-epoch full-attestation drive across the fork matrix
@with_phases(ALTAIR_ON)
@spec_state_test
def test_rewards_and_penalties_conservation(spec, state):
    """process_rewards_and_penalties applies exactly the sum of flag and
    inactivity deltas to every balance."""
    next_epoch_with_attestations(spec, state, True, False)
    next_epoch_with_attestations(spec, state, True, False)
    expected = [int(b) for b in state.balances]
    for flag_index in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
        rewards, penalties = spec.get_flag_index_deltas(state, flag_index)
        for i in range(len(expected)):
            expected[i] = max(0, expected[i] + rewards[i] - penalties[i])
    rewards, penalties = spec.get_inactivity_penalty_deltas(state)
    for i in range(len(expected)):
        expected[i] = max(0, expected[i] + rewards[i] - penalties[i])
    spec.process_rewards_and_penalties(state)
    assert [int(b) for b in state.balances] == expected


@pytest.mark.slow  # multi-epoch full-attestation drive
@with_phases(["phase0"])
@spec_state_test
def test_phase0_attestation_deltas_full(spec, state):
    """phase0 pending-attestation path: full participation earns positive
    head/target/source components for every attester."""
    next_epoch_with_attestations(spec, state, True, False)
    next_epoch_with_attestations(spec, state, True, False)
    rewards, penalties = spec.get_attestation_deltas(state)
    attesters = spec.get_unslashed_attesting_indices(
        state, spec.get_matching_source_attestations(state, spec.get_previous_epoch(state))
    )
    assert len(attesters) > 0
    for index in attesters:
        assert rewards[index] > 0
        assert penalties[index] == 0
