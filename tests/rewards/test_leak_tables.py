"""Inactivity-leak reward/penalty tables.

During a leak (finality older than MIN_EPOCHS_TO_INACTIVITY_PENALTY),
attestation REWARDS vanish while penalties and the inactivity-score
quadratic penalty keep draining non-participants — so full participants
tread water (post-altair: exactly zero attestation delta) and everyone
else bleeds proportionally to score x effective balance.  Reference
analogue: eth2spec/test/phase0/rewards/test_leak.py (leak variants of the
participation classes); spec: specs/altair/beacon-chain.md
get_flag_index_deltas + process_inactivity_updates,
specs/phase0/beacon-chain.md get_attestation_deltas leak branch.
"""

from __future__ import annotations

from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test,
    with_all_phases,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.state import next_epoch
from eth_consensus_specs_tpu.test_infra.template import instantiate

POST_ALTAIR = ["altair", "bellatrix", "capella", "deneb", "electra", "fulu", "gloas"]


def _enter_leak(spec, state):
    state.finalized_checkpoint.epoch = 0
    target = int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 3
    while int(spec.get_current_epoch(state)) < target:
        next_epoch(spec, state)
    assert spec.is_in_inactivity_leak(state)


def _set_participation(spec, state, fraction: float, flags: int = 0b0000_0111):
    n = len(state.validators)
    cut = int(n * fraction)
    for i in range(n):
        state.previous_epoch_participation[i] = flags if i < cut else 0
    return cut


def _epoch_boundary_deltas(spec, state):
    pre = [int(b) for b in state.balances]
    boundary = int(state.slot) + (
        spec.SLOTS_PER_EPOCH - int(state.slot) % spec.SLOTS_PER_EPOCH
    )
    spec.process_slots(state, boundary)
    return [int(b) - a for a, b in zip(pre, state.balances)]


def _leak_participation_case(fraction: float):
    @with_phases(POST_ALTAIR)
    @spec_state_test
    def case(spec, state):
        _enter_leak(spec, state)
        # fresh scores: participants decay to 0, absentees accumulate
        cut = _set_participation(spec, state, fraction)
        for i in range(len(state.inactivity_scores)):
            state.inactivity_scores[i] = 0 if i < cut else 20
        deltas = _epoch_boundary_deltas(spec, state)
        # full participants earn NO attestation rewards during a leak
        # (get_flag_index_deltas leak branch pays zero), so their balance
        # never grows
        for i in range(cut):
            assert deltas[i] <= 0
        # absentees additionally pay the quadratic inactivity penalty
        if cut < len(deltas):
            assert all(d < 0 for d in deltas[cut:])
        if 0 < cut < len(deltas):
            # a participant never loses more than an absentee of equal EB
            assert max(deltas[cut:]) <= min(deltas[:cut])

    return case, f"test_leak_participation_{int(fraction * 100)}pct"


for _f in (1.0, 0.75, 0.5, 0.25, 0.0):
    instantiate(_leak_participation_case, _f)


@with_phases(POST_ALTAIR)
@spec_state_test
def test_leak_inactivity_penalty_scales_with_score(spec, state):
    """Equal-balance absentees with different scores: the higher score
    pays the strictly larger quadratic penalty."""
    _enter_leak(spec, state)
    _set_participation(spec, state, 0.0)
    state.inactivity_scores[1] = 8
    state.inactivity_scores[2] = 64
    deltas = _epoch_boundary_deltas(spec, state)
    assert deltas[2] < deltas[1] < 0


@with_phases(POST_ALTAIR)
@spec_state_test
def test_leak_scores_grow_for_absentees_only(spec, state):
    _enter_leak(spec, state)
    cut = _set_participation(spec, state, 0.5)
    for i in range(len(state.inactivity_scores)):
        state.inactivity_scores[i] = 12
    _epoch_boundary_deltas(spec, state)
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    for i, score in enumerate(state.inactivity_scores):
        if i < cut:
            # timely-target participants decay by 1 in-leak (no recovery)
            assert int(score) == 11
        else:
            assert int(score) == 12 + bias


@with_all_phases
@spec_state_test
def test_leak_ends_exactly_at_threshold(spec, state):
    """is_in_inactivity_leak flips exactly when finality_delay exceeds
    MIN_EPOCHS_TO_INACTIVITY_PENALTY."""
    limit = int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY)
    while int(spec.get_current_epoch(state)) < limit + 3:
        next_epoch(spec, state)
    epoch = int(spec.get_previous_epoch(state))
    state.finalized_checkpoint.epoch = epoch - limit
    assert not spec.is_in_inactivity_leak(state)
    state.finalized_checkpoint.epoch = epoch - limit - 1
    assert spec.is_in_inactivity_leak(state)


@with_phases(POST_ALTAIR)
@spec_state_test
def test_leak_slashed_validator_gets_no_flag_rewards_after_leak(spec, state):
    """A slashed validator is excluded from unslashed participating sets
    both in and out of a leak: flag deltas never reward it."""
    _enter_leak(spec, state)
    _set_participation(spec, state, 1.0)
    epoch = int(spec.get_current_epoch(state))
    state.validators[3].slashed = True
    state.validators[3].withdrawable_epoch = epoch + 16
    for i in range(len(state.inactivity_scores)):
        state.inactivity_scores[i] = 0
    deltas = _epoch_boundary_deltas(spec, state)
    # slashed: treated as non-participating — penalized while peers tread water
    assert deltas[3] < 0
    assert deltas[4] <= 0  # unslashed participant: no growth in-leak
    assert deltas[3] < deltas[4]
