"""Reward/penalty property tables across the fork matrix (reference
analogue: test/phase0/rewards/ full/half/quarter participation classes
and leak variants)."""

from eth_consensus_specs_tpu.test_infra.attestations import next_epoch_with_attestations
from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test,
    with_all_phases,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.forks import is_post_altair
from eth_consensus_specs_tpu.test_infra.state import next_epoch

POST_ALTAIR = ["altair", "bellatrix", "capella", "deneb", "electra", "fulu", "gloas"]


def _epoch_delta(spec, state, fill=True):
    next_epoch(spec, state)
    pre = [int(b) for b in state.balances]
    _, _, out = next_epoch_with_attestations(spec, state, False, fill)
    # cross one more boundary so prev-epoch rewards apply
    _, _, out = next_epoch_with_attestations(spec, out, False, fill)
    post = [int(b) for b in out.balances]
    return pre, post, out


@with_all_phases
@spec_state_test
def test_full_participation_rewards_majority(spec, state):
    pre, post, _ = _epoch_delta(spec, state, fill=True)
    gained = sum(1 for a, b in zip(pre, post) if b > a)
    assert gained > len(pre) // 2


@with_all_phases
@spec_state_test
def test_no_participation_penalizes(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    pre = [int(b) for b in state.balances]
    next_epoch(spec, state)  # an epoch with zero attestations
    post = [int(b) for b in state.balances]
    assert sum(post) < sum(pre) or post == pre  # penalties (or none at genesis-edge)


@with_phases(POST_ALTAIR)
@spec_state_test
def test_participation_flags_drive_rewards(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    # hand-set full prev participation for half the validators
    n = len(state.validators)
    for i in range(n):
        state.previous_epoch_participation[i] = 0b0000_0111 if i < n // 2 else 0
    pre = [int(b) for b in state.balances]
    boundary = int(state.slot) + (
        spec.SLOTS_PER_EPOCH - int(state.slot) % spec.SLOTS_PER_EPOCH
    )
    spec.process_slots(state, boundary)
    post = [int(b) for b in state.balances]
    flagged = sum(post[i] - pre[i] for i in range(n // 2))
    unflagged = sum(post[i] - pre[i] for i in range(n // 2, n))
    assert flagged > unflagged


@with_phases(POST_ALTAIR)
@spec_state_test
def test_leak_burns_unflagged_only_more(spec, state):
    # drive into an inactivity leak
    for _ in range(int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 3):
        next_epoch(spec, state)
    n = len(state.validators)
    for i in range(n // 2):
        state.previous_epoch_participation[i] = 0b0000_0111
    for i in range(n // 2):
        state.inactivity_scores[i] = 0
    pre = [int(b) for b in state.balances]
    boundary = int(state.slot) + (
        spec.SLOTS_PER_EPOCH - int(state.slot) % spec.SLOTS_PER_EPOCH
    )
    spec.process_slots(state, boundary)
    post = [int(b) for b in state.balances]
    loss_flagged = sum(pre[i] - post[i] for i in range(n // 2))
    loss_unflagged = sum(pre[i] - post[i] for i in range(n // 2, n))
    assert loss_unflagged > loss_flagged


@with_all_phases
@spec_state_test
def test_rewards_zero_for_exited_validators(spec, state):
    next_epoch(spec, state)
    idx = 2
    state.validators[idx].exit_epoch = spec.get_current_epoch(state)
    state.validators[idx].withdrawable_epoch = spec.get_current_epoch(state) + 1
    pre = int(state.balances[idx])
    _, _, out = next_epoch_with_attestations(spec, state, False, True)
    # an exited validator neither earns attestation rewards nor pays
    # attestation penalties after withdrawability
    assert abs(int(out.balances[idx]) - pre) <= pre // 1000


@with_phases(POST_ALTAIR)
@spec_state_test
def test_slashed_validators_cannot_earn(spec, state):
    next_epoch(spec, state)
    idx = 3
    state.validators[idx].slashed = True
    state.previous_epoch_participation[idx] = 0b0000_0111
    pre = int(state.balances[idx])
    boundary = int(state.slot) + (
        spec.SLOTS_PER_EPOCH - int(state.slot) % spec.SLOTS_PER_EPOCH
    )
    spec.process_slots(state, boundary)
    assert int(state.balances[idx]) <= pre
