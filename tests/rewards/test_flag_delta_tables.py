"""Per-index flag-delta oracle table, altair+ (reference analogue:
test/altair/rewards/* + rewards/test_basic.py's participation-fraction
matrix — empty/quarter/half/almost-full/full, with slashed and exited
overlays; spec: specs/altair/beacon-chain.md get_flag_index_deltas).

Each case paints previous-epoch participation to a target fraction, then
checks EVERY validator's (reward, penalty) for EVERY flag against an
independent oracle of the spec formula."""

import random

from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.state import next_epoch
from eth_consensus_specs_tpu.test_infra.template import instantiate

ALTAIR_PLUS = ["altair", "bellatrix", "capella", "deneb", "electra"]
ALL_FLAGS = 0b0000_0111


def _paint_participation(spec, state, rng, fraction: float):
    for i in range(len(state.previous_epoch_participation)):
        state.previous_epoch_participation[i] = (
            ALL_FLAGS if rng.random() < fraction else 0
        )


def _oracle_flag_deltas(spec, state, flag_index: int):
    """Independent restatement of get_flag_index_deltas (beacon-chain.md)."""
    previous_epoch = spec.get_previous_epoch(state)
    unslashed = spec.get_unslashed_participating_indices(
        state, flag_index, previous_epoch
    )
    weight = int(spec.PARTICIPATION_FLAG_WEIGHTS[flag_index])
    wd = int(spec.WEIGHT_DENOMINATOR)
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    unslashed_increments = (
        sum(int(state.validators[i].effective_balance) for i in unslashed) // inc
    )
    active_increments = int(spec.get_total_active_balance(state)) // inc
    in_leak = spec.is_in_inactivity_leak(state)
    rewards = [0] * len(state.validators)
    penalties = [0] * len(state.validators)
    for index in spec.get_eligible_validator_indices(state):
        base_reward = int(spec.get_base_reward(state, index))
        if index in unslashed:
            if in_leak:
                continue
            reward_numerator = base_reward * weight * unslashed_increments
            rewards[index] = reward_numerator // (active_increments * wd)
        elif flag_index != int(spec.TIMELY_HEAD_FLAG_INDEX):
            penalties[index] = base_reward * weight // wd
    return rewards, penalties


def _check_all_flags(spec, state):
    for flag_index in range(len(spec.PARTICIPATION_FLAG_WEIGHTS)):
        got_rewards, got_penalties = spec.get_flag_index_deltas(state, flag_index)
        want_rewards, want_penalties = _oracle_flag_deltas(spec, state, flag_index)
        assert [int(r) for r in got_rewards] == want_rewards, f"flag {flag_index} rewards"
        assert [int(p) for p in got_penalties] == want_penalties, f"flag {flag_index} penalties"


def _fraction_case(name: str, fraction: float, overlay: str, leak: bool, seed: int):
    @with_phases(ALTAIR_PLUS)
    @spec_state_test
    def case(spec, state):
        rng = random.Random(seed)
        next_epoch(spec, state)
        next_epoch(spec, state)
        if leak:
            state.finalized_checkpoint.epoch = 0
            target = int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 3
            while int(spec.get_current_epoch(state)) < target:
                next_epoch(spec, state)
            assert spec.is_in_inactivity_leak(state)
        _paint_participation(spec, state, rng, fraction)
        n = len(state.validators)
        if overlay == "slashed":
            for i in rng.sample(range(n), n // 8):
                state.validators[i].slashed = True
        elif overlay == "exited":
            epoch = int(spec.get_current_epoch(state))
            for i in rng.sample(range(n), n // 8):
                state.validators[i].exit_epoch = max(epoch - 1, 0)
                state.validators[i].withdrawable_epoch = epoch + 16
        elif overlay == "mixed_balance":
            inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
            cap = int(spec.MAX_EFFECTIVE_BALANCE)
            for i in rng.sample(range(n), n // 4):
                state.validators[i].effective_balance = rng.choice(
                    [cap // 2, cap - inc, cap]
                )
        _check_all_flags(spec, state)

    leak_tag = "_leak" if leak else ""
    return case, f"test_deltas_{name}_{overlay}{leak_tag}"


_CASES = [
    ("empty", 0.0, "none", False, 1),
    ("quarter", 0.25, "none", False, 2),
    ("half", 0.5, "none", False, 3),
    ("almost_full", 0.9, "none", False, 4),
    ("full", 1.0, "none", False, 5),
    ("half", 0.5, "slashed", False, 6),
    ("half", 0.5, "exited", False, 7),
    ("half", 0.5, "mixed_balance", False, 8),
    ("full", 1.0, "slashed", False, 9),
    ("empty", 0.0, "none", True, 10),
    ("half", 0.5, "none", True, 11),
    ("full", 1.0, "none", True, 12),
    ("half", 0.5, "mixed_balance", True, 13),
]

for _name, _fraction, _overlay, _leak, _seed in _CASES:
    instantiate(_fraction_case, _name, _fraction, _overlay, _leak, _seed)
