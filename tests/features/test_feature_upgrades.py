"""Feature fork upgrades (reference: specs/_features/*/fork.md)."""

from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.forks.features import get_feature_spec
from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.context import (
    default_activation_threshold,
    default_balances,
)
from eth_consensus_specs_tpu.test_infra.genesis import create_genesis_state
from eth_consensus_specs_tpu.utils import bls


def test_upgrade_to_eip7441():
    bls.bls_active = False
    capella = get_spec("capella", "minimal")
    whisk = get_feature_spec("eip7441", "minimal")
    pre = create_genesis_state(
        capella, default_balances(capella), default_activation_threshold(capella)
    )
    post = whisk.upgrade_from_parent(pre)
    assert bytes(post.fork.current_version) == bytes(whisk.config.EIP7441_FORK_VERSION)
    assert len(post.whisk_trackers) == len(pre.validators)
    assert len(post.whisk_k_commitments) == len(pre.validators)
    assert len(post.whisk_proposer_trackers) == whisk.PROPOSER_TRACKERS_COUNT
    # registry carries over (the reference doc's stale `validators=[]` is
    # corrected)
    assert hash_tree_root(post.validators) == hash_tree_root(pre.validators)
    # initial trackers are (G, k*G) with the counter-0 k
    k0 = whisk.get_initial_whisk_k(0, 0)
    assert bytes(post.whisk_k_commitments[0]) == whisk.get_k_commitment(k0)
    assert bytes(post.whisk_trackers[0].r_G) == whisk.BLS_G1_GENERATOR
    # candidate/proposer trackers were selected (non-zero)
    assert any(
        bytes(t.r_G) != b"\x00" * 48 for t in post.whisk_candidate_trackers
    )


def test_upgrade_to_eip7928():
    bls.bls_active = False
    fulu = get_spec("fulu", "minimal")
    feat = get_feature_spec("eip7928", "minimal")
    pre = create_genesis_state(
        fulu, default_balances(fulu), default_activation_threshold(fulu)
    )
    post = feat.upgrade_from_parent(pre)
    assert bytes(post.fork.current_version) == bytes(feat.config.EIP7928_FORK_VERSION)
    hdr = post.latest_execution_payload_header
    assert bytes(hdr.block_access_list_root) == b"\x00" * 32
    assert bytes(hdr.block_hash) == bytes(pre.latest_execution_payload_header.block_hash)
    assert hash_tree_root(post.validators) == hash_tree_root(pre.validators)


def test_upgrade_to_eip6800():
    bls.bls_active = False
    deneb = get_spec("deneb", "minimal")
    feat = get_feature_spec("eip6800", "minimal")
    pre = create_genesis_state(
        deneb, default_balances(deneb), default_activation_threshold(deneb)
    )
    post = feat.upgrade_from_parent(pre)
    assert bytes(post.fork.current_version) == bytes(feat.config.EIP6800_FORK_VERSION)
    assert bytes(post.fork.previous_version) == bytes(pre.fork.current_version)
    hdr = post.latest_execution_payload_header
    assert bytes(hdr.execution_witness_root) == b"\x00" * 32
    assert hash_tree_root(post.validators) == hash_tree_root(pre.validators)
