"""EIP-7928 block access lists
(reference: specs/_features/eip7928/beacon-chain.md)."""

from eth_consensus_specs_tpu.forks.features import get_feature_spec
from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import (
    default_activation_threshold,
    default_balances,
)
from eth_consensus_specs_tpu.test_infra.genesis import create_genesis_state
from eth_consensus_specs_tpu.utils import bls


def _spec_state():
    bls.bls_active = False
    spec = get_feature_spec("eip7928", "minimal")
    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec)
    )
    return spec, state


def test_payload_carries_access_list():
    spec, state = _spec_state()
    block = build_empty_block_for_next_slot(spec, state)
    bal = b"\xde\xad\xbe\xef" * 8
    block.body.execution_payload.block_access_list = bal
    state_transition_and_sign_block(spec, state, block)
    header = state.latest_execution_payload_header
    assert bytes(header.block_access_list_root) == bytes(
        hash_tree_root(spec.BlockAccessList(bal))
    )


def test_empty_access_list_root_differs_from_nonempty():
    spec, state = _spec_state()
    empty_root = hash_tree_root(spec.BlockAccessList(b""))
    nonempty_root = hash_tree_root(spec.BlockAccessList(b"\x01"))
    assert bytes(empty_root) != bytes(nonempty_root)


def test_header_round_trips_through_blocks():
    spec, state = _spec_state()
    for i in range(2):
        block = build_empty_block_for_next_slot(spec, state)
        block.body.execution_payload.block_access_list = bytes([i]) * 4
        state_transition_and_sign_block(spec, state, block)
    assert int(state.slot) == 2
