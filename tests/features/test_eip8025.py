"""EIP-8025 zkEVM execution proofs
(reference: specs/_features/eip8025/{beacon-chain,zkevm}.md)."""

from eth_consensus_specs_tpu.forks.features import get_feature_spec
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import (
    default_activation_threshold,
    default_balances,
    expect_assertion_error,
)
from eth_consensus_specs_tpu.test_infra.genesis import create_genesis_state
from eth_consensus_specs_tpu.test_infra.keys import privkeys
from eth_consensus_specs_tpu.utils import bls


def _spec_state():
    bls.bls_active = False
    spec = get_feature_spec("eip8025", "minimal")
    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec)
    )
    return spec, state


def _signed_proof(spec, state, block_hash, parent_hash, validator_index=0, proof_id=1):
    zk = spec.generate_zkevm_proof(block_hash, parent_hash, proof_id)
    message = spec.ExecutionProof(
        beacon_root=b"\x00" * 32, zk_proof=zk, validator_index=validator_index
    )
    signing_root = spec.compute_signing_root(
        message, spec.get_domain(state, spec.DOMAIN_EXECUTION_PROOF)
    )
    sig = bls.Sign(privkeys[validator_index], signing_root)
    return spec.SignedExecutionProof(message=message, signature=sig)


def test_zkevm_proof_roundtrip():
    spec, state = _spec_state()
    bh, ph = b"\x01" * 32, b"\x02" * 32
    zk = spec.generate_zkevm_proof(bh, ph, 1)
    assert spec.verify_zkevm_proof(zk, ph, bh, spec.PROGRAM + b"\x01")
    # wrong block hash binding fails
    assert not spec.verify_zkevm_proof(zk, ph, b"\x03" * 32, spec.PROGRAM + b"\x01")
    assert not spec.verify_zkevm_proof(zk, b"\x04" * 32, bh, spec.PROGRAM + b"\x01")


def test_verify_execution_proof_signature_gate():
    spec, state = _spec_state()
    bh, ph = b"\x01" * 32, b"\x02" * 32
    bls.bls_active = True
    try:
        signed = _signed_proof(spec, state, bh, ph)
        assert spec.verify_execution_proof(signed, ph, bh, state, spec.PROGRAM)
        bad = spec.SignedExecutionProof(message=signed.message, signature=b"\x11" * 96)
        assert not spec.verify_execution_proof(bad, ph, bh, state, spec.PROGRAM)
    finally:
        bls.bls_active = False


def test_stateless_validation_path():
    spec, state = _spec_state()
    block = build_empty_block_for_next_slot(spec, state)
    payload = block.body.execution_payload
    probe = state.copy()
    spec.process_slots(probe, block.slot)

    # no proofs retrievable -> stateless validation rejects
    expect_assertion_error(
        lambda: spec.process_execution_payload(
            probe.copy(), block.body, spec.EXECUTION_ENGINE, stateless_validation=True
        )
    )

    # register a retriever with a valid proof -> accepted
    signed = _signed_proof(
        spec, probe, bytes(payload.block_hash), bytes(payload.parent_hash)
    )
    spec.retrieve_execution_proofs = lambda block_hash: [signed]
    try:
        spec.process_execution_payload(
            probe.copy(), block.body, spec.EXECUTION_ENGINE, stateless_validation=True
        )
    finally:
        del spec.retrieve_execution_proofs


def test_stateful_path_unchanged():
    spec, state = _spec_state()
    block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, block)
    assert int(state.slot) == 1
