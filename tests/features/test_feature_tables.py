"""Feature-fork property tables COMPLEMENTING the per-feature suites —
cases the sibling files don't cover: FOCIL view-freeze and wrong-root
gossip rejection, cross-slot store isolation, eip6914 reuse boundary
epochs and balance gate, eip8025 proof-id key separation, eip6800
witness root sensitivity (reference analogue: the deeper variants in
test/_features/...)."""

from eth_consensus_specs_tpu.forks.features import get_feature_spec as get_spec
from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.context import (
    default_activation_threshold,
    default_balances,
    expect_assertion_error,
)
from eth_consensus_specs_tpu.test_infra.genesis import create_genesis_state
from eth_consensus_specs_tpu.utils import bls


def _state(spec):
    prev = bls.bls_active
    bls.bls_active = False
    try:
        return create_genesis_state(
            spec, default_balances(spec), default_activation_threshold(spec)
        )
    finally:
        bls.bls_active = prev


def _focil_setup():
    spec = get_spec("eip7805", "minimal")
    state = _state(spec)
    store = spec.get_inclusion_list_store()
    comm = [int(i) for i in spec.get_inclusion_list_committee(state, state.slot)]
    root = hash_tree_root(spec._committee_vector_type()(comm))
    return spec, state, store, comm, root


def _il(spec, state, validator, root, txs):
    return spec.InclusionList(
        slot=state.slot,
        validator_index=validator,
        inclusion_list_committee_root=root,
        transactions=txs,
    )


# == eip7805 (FOCIL) =======================================================


def test_focil_store_records_under_committee_root_key():
    spec, state, store, comm, root = _focil_setup()
    il = _il(spec, state, comm[0], root, [b"\x09"])
    spec.process_inclusion_list(store, il, True)
    key = (int(state.slot), bytes(root))
    assert key in store.inclusion_lists
    assert any(
        bytes(t) == b"\x09" for lst in store.inclusion_lists[key] for t in lst.transactions
    )


def test_focil_after_view_freeze_not_stored():
    spec, state, store, comm, root = _focil_setup()
    il = _il(spec, state, comm[0], root, [b"\x0a"])
    spec.process_inclusion_list(store, il, False)  # past the deadline
    txs = spec.get_inclusion_list_transactions(store, state, state.slot)
    assert b"\x0a" not in [bytes(t) for t in txs]


def test_focil_wrong_committee_root_isolated():
    """A list stored under a stale/wrong committee root never surfaces in
    the canonical slot view."""
    spec, state, store, comm, root = _focil_setup()
    il = _il(spec, state, comm[0], b"\x00" * 32, [b"\x0b"])
    spec.process_inclusion_list(store, il, True)
    txs = spec.get_inclusion_list_transactions(store, state, state.slot)
    assert b"\x0b" not in [bytes(t) for t in txs]


def test_focil_gossip_rejects_wrong_root():
    spec, state, store, comm, root = _focil_setup()
    signed = spec.SignedInclusionList(
        message=_il(spec, state, comm[0], b"\x00" * 32, []),
    )
    expect_assertion_error(
        lambda: spec.on_inclusion_list(None, store, state, signed, True)
    )


def test_focil_cross_slot_isolation():
    spec, state, store, comm, root = _focil_setup()
    il = _il(spec, state, comm[0], root, [b"\x0c"])
    spec.process_inclusion_list(store, il, True)
    other = state.copy()
    other.slot = int(state.slot) + 1
    txs = spec.get_inclusion_list_transactions(store, other, other.slot)
    assert b"\x0c" not in [bytes(t) for t in txs]


# == eip6914 (validator index reuse) =======================================


def test_reuse_boundary_epoch_exclusive():
    """Reuse opens strictly AFTER withdrawable + SAFE_EPOCHS."""
    spec = get_spec("eip6914", "minimal")
    state = _state(spec)
    v = state.validators[1]
    v.withdrawable_epoch = 0
    v.exit_epoch = 0
    safe = int(spec.SAFE_EPOCHS_TO_REUSE_INDEX)
    assert not spec.is_reusable_validator(v, 0, safe)  # boundary: not yet
    assert spec.is_reusable_validator(v, 0, safe + 1)


def test_reuse_blocked_by_nonzero_balance():
    spec = get_spec("eip6914", "minimal")
    state = _state(spec)
    v = state.validators[1]
    v.withdrawable_epoch = 0
    v.exit_epoch = 0
    safe = int(spec.SAFE_EPOCHS_TO_REUSE_INDEX)
    assert not spec.is_reusable_validator(v, 1, safe + 1)  # one gwei blocks


def test_reuse_prefers_lowest_index():
    spec = get_spec("eip6914", "minimal")
    state = _state(spec)
    epoch = int(spec.SAFE_EPOCHS_TO_REUSE_INDEX) + 2
    state.slot = epoch * int(spec.SLOTS_PER_EPOCH)
    for idx in (5, 3):
        v = state.validators[idx]
        v.withdrawable_epoch = 0
        v.exit_epoch = 0
        state.balances[idx] = 0
    assert int(spec.get_index_for_new_validator(state)) == 3


# == eip8025 (zkEVM execution proofs) ======================================


def test_proof_public_input_binding():
    """The (stand-in) verifier binds the proof to its PUBLIC INPUTS —
    wrong block or parent hash must fail (the proof-system internals are
    a placeholder in the EIP itself)."""
    spec = get_spec("eip8025", "minimal")
    block_hash, parent_hash = b"\x11" * 32, b"\x22" * 32
    proof = spec.generate_zkevm_proof(block_hash, parent_hash, 1)
    assert spec.verify_zkevm_proof(proof, parent_hash, block_hash, spec.PROGRAM)
    assert not spec.verify_zkevm_proof(proof, parent_hash, b"\x33" * 32, spec.PROGRAM)
    assert not spec.verify_zkevm_proof(proof, b"\x33" * 32, block_hash, spec.PROGRAM)


def test_proof_size_gate():
    spec = get_spec("eip8025", "minimal")
    block_hash, parent_hash = b"\x11" * 32, b"\x22" * 32
    proof = spec.generate_zkevm_proof(block_hash, parent_hash, 1)
    oversized = proof.copy()
    try:
        oversized.proof_data = b"\x01" * (int(spec.MAX_PROOF_SIZE) + 1)
    except Exception:
        return  # the type itself rejects oversize — equally fail-closed
    assert not spec.verify_zkevm_proof(oversized, parent_hash, block_hash, spec.PROGRAM)


# == eip6800 (Verkle) ======================================================


def test_witness_root_sensitive_to_state_diff():
    spec = get_spec("eip6800", "minimal")
    w1 = spec.ExecutionWitness()
    w2 = spec.ExecutionWitness()
    w2.state_diff.append(spec.StemStateDiff(stem=b"\x01" * 31, suffix_diffs=[]))
    assert bytes(hash_tree_root(w1)) != bytes(hash_tree_root(w2))
