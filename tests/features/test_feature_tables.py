"""Feature-fork property tables — FOCIL inclusion lists (eip7805),
validator index reuse (eip6914), execution proofs (eip8025), Verkle
types (eip6800) (reference analogue: the per-feature suites under
test/_features/...)."""

from eth_consensus_specs_tpu.forks.features import get_feature_spec as get_spec
from eth_consensus_specs_tpu.test_infra.genesis import create_genesis_state
from eth_consensus_specs_tpu.utils import bls


def _state(spec, n=64):
    prev = bls.bls_active
    bls.bls_active = False
    try:
        return create_genesis_state(
            spec, [spec.MAX_EFFECTIVE_BALANCE] * n, spec.MAX_EFFECTIVE_BALANCE
        )
    finally:
        bls.bls_active = prev


# == eip7805 (FOCIL) =======================================================


def test_focil_committee_deterministic():
    spec = get_spec("eip7805", "minimal")
    state = _state(spec)
    a = [int(i) for i in spec.get_inclusion_list_committee(state, state.slot)]
    b = [int(i) for i in spec.get_inclusion_list_committee(state, state.slot)]
    assert a == b
    assert len(a) == int(spec.INCLUSION_LIST_COMMITTEE_SIZE)


def test_focil_committee_members_are_validators():
    spec = get_spec("eip7805", "minimal")
    state = _state(spec)
    comm = [int(i) for i in spec.get_inclusion_list_committee(state, state.slot)]
    assert all(0 <= i < len(state.validators) for i in comm)


def test_focil_store_accepts_committee_member_list():
    spec = get_spec("eip7805", "minimal")
    state = _state(spec)
    store = spec.get_inclusion_list_store()
    comm = [int(i) for i in spec.get_inclusion_list_committee(state, state.slot)]
    from eth_consensus_specs_tpu.ssz import hash_tree_root

    root = hash_tree_root(spec._committee_vector_type()(comm))
    il = spec.InclusionList(
        slot=state.slot,
        validator_index=comm[0],
        inclusion_list_committee_root=root,
        transactions=[],
    )
    spec.process_inclusion_list(store, il, True)
    assert True  # no exception: accepted into the store


def test_focil_transactions_deduplicated():
    spec = get_spec("eip7805", "minimal")
    state = _state(spec)
    store = spec.get_inclusion_list_store()
    comm = [int(i) for i in spec.get_inclusion_list_committee(state, state.slot)]
    from eth_consensus_specs_tpu.ssz import hash_tree_root

    root = hash_tree_root(spec._committee_vector_type()(comm))
    tx = b"\x01\x02\x03"
    for v in comm[:2]:
        il = spec.InclusionList(
            slot=state.slot,
            validator_index=v,
            inclusion_list_committee_root=root,
            transactions=[tx],
        )
        spec.process_inclusion_list(store, il, True)
    txs = spec.get_inclusion_list_transactions(store, state, state.slot)
    assert list(txs).count(tx) == 1


# == eip6914 (validator index reuse) =======================================


def test_reuse_requires_withdrawable_and_empty():
    spec = get_spec("eip6914", "minimal")
    state = _state(spec)
    epoch = spec.get_current_epoch(state)
    v = state.validators[1]
    assert not spec.is_reusable_validator(v, int(state.balances[1]), epoch)
    v.withdrawable_epoch = 0
    v.exit_epoch = 0
    assert spec.is_reusable_validator(v, 0, int(spec.SAFE_EPOCHS_TO_REUSE_INDEX) + 1)


def test_new_validator_reuses_reusable_slot():
    spec = get_spec("eip6914", "minimal")
    state = _state(spec)
    epoch = spec.get_current_epoch(state) + int(spec.SAFE_EPOCHS_TO_REUSE_INDEX) + 1
    # fast-forward the clock by faking slot
    state.slot = int(epoch) * int(spec.SLOTS_PER_EPOCH)
    v = state.validators[2]
    v.withdrawable_epoch = 0
    v.exit_epoch = 0
    state.balances[2] = 0
    assert int(spec.get_index_for_new_validator(state)) == 2


def test_no_reusable_slot_appends():
    spec = get_spec("eip6914", "minimal")
    state = _state(spec)
    assert int(spec.get_index_for_new_validator(state)) == len(state.validators)


# == eip8025 (execution proofs) ============================================


def test_execution_proof_keygen_deterministic():
    spec = get_spec("eip8025", "minimal")
    vk1 = spec.generate_verification_key(b"\x00\x01", 1)
    vk2 = spec.generate_verification_key(b"\x00\x01", 1)
    assert bytes(vk1) == bytes(vk2)
    assert bytes(vk1) != bytes(spec.generate_verification_key(b"\x00\x01", 2))


def test_execution_proof_roundtrip():
    spec = get_spec("eip8025", "minimal")
    block_hash, parent_hash = b"\x11" * 32, b"\x22" * 32
    proof = spec.generate_zkevm_proof(block_hash, parent_hash, 1)
    assert spec.verify_zkevm_proof(proof, parent_hash, block_hash, spec.PROGRAM)
    # tampered public input fails
    assert not spec.verify_zkevm_proof(proof, parent_hash, b"\x33" * 32, spec.PROGRAM)


# == eip6800 (Verkle) ======================================================


def test_verkle_payload_carries_execution_witness():
    spec = get_spec("eip6800", "minimal")
    payload = spec.ExecutionPayload()
    assert hasattr(payload, "execution_witness")


def test_verkle_types_merkleize():
    from eth_consensus_specs_tpu.ssz import hash_tree_root

    spec = get_spec("eip6800", "minimal")
    w = spec.ExecutionWitness()
    assert len(bytes(hash_tree_root(w))) == 32
