"""EIP-7441 Whisk SSLE
(reference: specs/_features/eip7441/beacon-chain.md; proofs are the
first-party backends described in forks/features/eip7441.py)."""

import pytest

from eth_consensus_specs_tpu.crypto.curve import g1_from_bytes, g1_generator, g1_to_bytes
from eth_consensus_specs_tpu.forks.features import get_feature_spec
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import (
    default_activation_threshold,
    default_balances,
)
from eth_consensus_specs_tpu.test_infra.genesis import create_genesis_state
from eth_consensus_specs_tpu.utils import bls


def _spec_state():
    bls.bls_active = False
    spec = get_feature_spec("eip7441", "minimal")
    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec)
    )
    return spec, state


_validator_k_cache: dict[int, int] = {}


def _validator_k(spec, index: int) -> int:
    """Replay the deterministic genesis k assignment for validator
    `index` (uniqueness trial matches initialize_feature_state order)."""
    for i in range(index + 1):
        if i in _validator_k_cache:
            continue
        counter = 0
        while True:
            k = spec.get_initial_whisk_k(i, counter)
            commitment = spec.get_k_commitment(k)
            earlier = [
                spec.get_k_commitment(_validator_k_cache[j]) for j in range(i)
            ]
            if all(bytes(e) != bytes(commitment) for e in earlier):
                _validator_k_cache[i] = k
                break
            counter += 1
    return _validator_k_cache[index]


def _proposer_for_slot(spec, state, slot: int):
    """Find (validator_index, k) able to open the slot's proposer tracker."""
    tracker = state.whisk_proposer_trackers[slot % spec.PROPOSER_TRACKERS_COUNT]
    for index in range(len(state.validators)):
        if bytes(state.whisk_k_commitments[index]) == bytes(tracker.k_r_G) and bytes(
            tracker.r_G
        ) == spec.BLS_G1_GENERATOR:
            return index, _validator_k(spec, index)
    raise AssertionError("no initial-tracker proposer for this slot")


def test_opening_proof_roundtrip():
    spec, state = _spec_state()
    idx, k = _proposer_for_slot(spec, state, 1)
    tracker = state.whisk_proposer_trackers[1 % spec.PROPOSER_TRACKERS_COUNT]
    commitment = state.whisk_k_commitments[idx]
    proof = spec.whisk_generate_opening_proof(k, tracker)
    assert spec.IsValidWhiskOpeningProof(tracker, commitment, proof)
    # wrong k fails
    bad = spec.whisk_generate_opening_proof(k + 1, tracker)
    assert not spec.IsValidWhiskOpeningProof(tracker, commitment, bad)
    # tampered proof fails
    tampered = bytearray(proof)
    tampered[-1] ^= 1
    assert not spec.IsValidWhiskOpeningProof(tracker, commitment, bytes(tampered))


def test_shuffle_proof_roundtrip_transparent_testonly():
    """Legacy transparent byte format: verifies only under the explicit
    test-only opt-in, never by default."""
    spec, state = _spec_state()
    pre = [state.whisk_candidate_trackers[i] for i in range(spec.VALIDATORS_PER_SHUFFLE)]
    perm = list(reversed(range(len(pre))))
    scalars = [3 + i for i in range(len(pre))]  # distinct -> transparent
    import pytest

    with pytest.raises(AssertionError):
        # generation is gated too — no silent generate-then-fail roundtrip
        spec.whisk_generate_shuffle_proof(pre, perm, scalars)
    spec.ALLOW_TRANSPARENT_SHUFFLE_PROOFS = True
    post, proof = spec.whisk_generate_shuffle_proof(pre, perm, scalars)
    spec.ALLOW_TRANSPARENT_SHUFFLE_PROOFS = False
    assert not spec.IsValidWhiskShuffleProof(pre, post, proof), (
        "transparent proofs must be rejected without the test-only opt-in"
    )
    spec.ALLOW_TRANSPARENT_SHUFFLE_PROOFS = True
    try:
        assert spec.IsValidWhiskShuffleProof(pre, post, proof)
        # tampering with a post tracker fails
        bad_post = [t.copy() for t in post]
        bad_post[0].r_G = g1_to_bytes(g1_generator())
        assert not spec.IsValidWhiskShuffleProof(pre, bad_post, proof)
        # non-permutation (duplicate source) fails
        dup_proof = proof[:40] + proof[:40] + proof[80:]
        assert not spec.IsValidWhiskShuffleProof(pre, post, dup_proof)
    finally:
        spec.ALLOW_TRANSPARENT_SHUFFLE_PROOFS = False


def test_shuffle_proof_roundtrip_zk():
    """The production ZK backend: a uniform rerandomization scalar (the
    Whisk relation) yields a curdleproofs-class proof that verifies by
    default and reveals neither the permutation nor k."""
    from eth_consensus_specs_tpu.crypto import curdleproofs

    spec, state = _spec_state()
    pre = [state.whisk_candidate_trackers[i] for i in range(spec.VALIDATORS_PER_SHUFFLE)]
    perm = [2, 0, 3, 1] if len(pre) == 4 else list(reversed(range(len(pre))))
    k = 0x5EC12E7

    post, proof = spec.whisk_generate_shuffle_proof(pre, perm, [k] * len(pre))
    assert proof[:4] == curdleproofs.MAGIC
    assert len(proof) <= spec.MAX_SHUFFLE_PROOF_SIZE
    assert spec.IsValidWhiskShuffleProof(pre, post, proof)

    # the proof is not the transparent serialization: neither the
    # permutation indices nor k appear anywhere in the bytes
    assert int(k).to_bytes(32, "big") not in bytes(proof)

    # tampered post tracker rejected
    bad_post = [t.copy() for t in post]
    bad_post[0].r_G = g1_to_bytes(g1_generator())
    assert not spec.IsValidWhiskShuffleProof(pre, bad_post, proof)

    # swapped post elements (wrong permutation for this proof) rejected
    swapped = [t.copy() for t in post]
    swapped[0], swapped[1] = swapped[1], swapped[0]
    assert not spec.IsValidWhiskShuffleProof(pre, swapped, proof)

    # any single proof bit flip rejected (spot-check a few offsets)
    for off in (10, 200, len(proof) - 5):
        flipped = bytearray(proof)
        flipped[off] ^= 1
        assert not spec.IsValidWhiskShuffleProof(pre, post, bytes(flipped))

    # two proofs of the same statement differ (blinders are random) and
    # both verify — the bytes carry no deterministic witness image
    post2, proof2 = spec.whisk_generate_shuffle_proof(pre, perm, [k] * len(pre))
    assert [bytes(t.r_G) for t in post2] == [bytes(t.r_G) for t in post]
    assert proof != proof2
    assert spec.IsValidWhiskShuffleProof(pre, post, proof2)


def test_whisk_full_block():
    """A block carrying an opening proof, an identity shuffle, and a
    first-proposal registration applies end to end."""
    spec, state = _spec_state()
    slot = 1
    idx, k = _proposer_for_slot(spec, state, slot)
    block = build_empty_block(spec, state, slot=slot, proposer_index=idx)

    # opening proof over the slot's proposer tracker
    tracker = state.whisk_proposer_trackers[slot % spec.PROPOSER_TRACKERS_COUNT]
    block.body.whisk_opening_proof = spec.whisk_generate_opening_proof(k, tracker)

    # shuffle: permute the randao-derived candidates (transparent proof)
    shuffle_indices = spec.get_shuffle_indices(block.body.randao_reveal)
    pre = [state.whisk_candidate_trackers[i] for i in shuffle_indices]
    perm = list(range(len(pre)))
    scalars = [2] * len(pre)
    post, proof = spec.whisk_generate_shuffle_proof(pre, perm, scalars)
    block.body.whisk_post_shuffle_trackers = post
    block.body.whisk_shuffle_proof = proof

    # first proposal: register a fresh tracker under a new secret
    k_new = 0x1234567
    r = 0xABCDEF
    g = g1_generator()
    fresh = spec.WhiskTracker(
        r_G=g1_to_bytes(g.mul(r)), k_r_G=g1_to_bytes(g.mul(r * k_new % spec.BLS_MODULUS))
    )
    block.body.whisk_k_commitment = spec.get_k_commitment(k_new)
    block.body.whisk_registration_proof = spec.whisk_generate_opening_proof(k_new, fresh)
    block.body.whisk_tracker = fresh

    state_transition_and_sign_block(spec, state, block)
    assert int(state.slot) == slot
    assert bytes(state.whisk_trackers[idx].r_G) == bytes(fresh.r_G)
    assert bytes(state.whisk_k_commitments[idx]) == bytes(spec.get_k_commitment(k_new))
    # the shuffled candidates were rerandomized in place
    for i, si in enumerate(shuffle_indices):
        assert bytes(state.whisk_candidate_trackers[si].r_G) == bytes(post[i].r_G)


def test_whisk_block_rejects_bad_opening():
    spec, state = _spec_state()
    slot = 1
    idx, k = _proposer_for_slot(spec, state, slot)
    block = build_empty_block(spec, state, slot=slot, proposer_index=idx)
    block.body.whisk_opening_proof = b"\x00" * 128  # garbage
    from eth_consensus_specs_tpu.test_infra.context import expect_assertion_error

    from eth_consensus_specs_tpu.test_infra.block import transition_unsigned_block

    expect_assertion_error(lambda: transition_unsigned_block(spec, state.copy(), block))


def test_registration_requires_unique_commitment():
    spec, state = _spec_state()
    slot = 1
    idx, k = _proposer_for_slot(spec, state, slot)
    block = build_empty_block(spec, state, slot=slot, proposer_index=idx)
    tracker = state.whisk_proposer_trackers[slot % spec.PROPOSER_TRACKERS_COUNT]
    block.body.whisk_opening_proof = spec.whisk_generate_opening_proof(k, tracker)
    shuffle_indices = spec.get_shuffle_indices(block.body.randao_reveal)
    pre = [state.whisk_candidate_trackers[i] for i in shuffle_indices]
    post, proof = spec.whisk_generate_shuffle_proof(
        pre, list(range(len(pre))), [2] * len(pre)
    )
    block.body.whisk_post_shuffle_trackers = post
    block.body.whisk_shuffle_proof = proof
    # register with ANOTHER validator's existing k -> non-unique commitment
    other_k = _validator_k(spec, (idx + 1) % len(state.validators))
    fresh = spec.WhiskTracker(
        r_G=g1_to_bytes(g1_generator().mul(5)),
        k_r_G=g1_to_bytes(g1_generator().mul(5 * other_k % spec.BLS_MODULUS)),
    )
    block.body.whisk_tracker = fresh
    block.body.whisk_k_commitment = spec.get_k_commitment(other_k)
    block.body.whisk_registration_proof = spec.whisk_generate_opening_proof(other_k, fresh)

    from eth_consensus_specs_tpu.test_infra.block import transition_unsigned_block
    from eth_consensus_specs_tpu.test_infra.context import expect_assertion_error

    expect_assertion_error(lambda: transition_unsigned_block(spec, state.copy(), block))
