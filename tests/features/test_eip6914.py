"""EIP-6914 validator-index reuse
(reference: specs/_features/eip6914/ and
eth2spec/test/eip6914/unittests/)."""

from eth_consensus_specs_tpu.forks.features import get_feature_spec
from eth_consensus_specs_tpu.test_infra.context import default_balances, default_activation_threshold
from eth_consensus_specs_tpu.test_infra.genesis import create_genesis_state


def _spec_state():
    spec = get_feature_spec("eip6914", "minimal")
    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec)
    )
    return spec, state


def test_is_reusable_validator_rules():
    spec, state = _spec_state()
    v = state.validators[0]
    epoch = spec.get_current_epoch(state)
    # active validator: not reusable
    assert not spec.is_reusable_validator(v, state.balances[0], epoch)
    # withdrawn long ago but balance remains: not reusable
    v.withdrawable_epoch = 0
    assert not spec.is_reusable_validator(v, state.balances[0], spec.SAFE_EPOCHS_TO_REUSE_INDEX + 1)
    # withdrawn long ago and drained: reusable
    assert spec.is_reusable_validator(v, 0, spec.SAFE_EPOCHS_TO_REUSE_INDEX + 1)
    # not yet past the safety window
    assert not spec.is_reusable_validator(v, 0, spec.SAFE_EPOCHS_TO_REUSE_INDEX)


def test_get_index_for_new_validator_reuses_slot():
    spec, state = _spec_state()
    assert spec.get_index_for_new_validator(state) == len(state.validators)
    # drain + age validator 3
    state.validators[3].withdrawable_epoch = 0
    state.balances[3] = 0
    state.slot = (spec.SAFE_EPOCHS_TO_REUSE_INDEX + 2) * spec.SLOTS_PER_EPOCH
    assert spec.get_index_for_new_validator(state) == 3


def test_on_reused_index_clears_equivocation():
    spec, state = _spec_state()
    from eth_consensus_specs_tpu.test_infra.fork_choice import get_genesis_forkchoice_store

    store, _ = get_genesis_forkchoice_store(spec, state)
    store.equivocating_indices.add(7)
    spec.on_reused_index(store, 7)
    assert 7 not in store.equivocating_indices
    spec.on_reused_index(store, 9)  # absent index is a no-op
