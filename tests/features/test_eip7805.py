"""EIP-7805 FOCIL inclusion lists
(reference: specs/_features/eip7805/ and eth2spec/test/eip7805/)."""

import pytest

from eth_consensus_specs_tpu.forks.features import get_feature_spec
from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.context import (
    default_activation_threshold,
    default_balances,
)
from eth_consensus_specs_tpu.test_infra.genesis import create_genesis_state
from eth_consensus_specs_tpu.test_infra.keys import privkeys
from eth_consensus_specs_tpu.utils import bls


def _spec_state():
    bls.bls_active = False
    spec = get_feature_spec("eip7805", "minimal")
    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec)
    )
    return spec, state


def _committee_root(spec, committee):
    return hash_tree_root(spec._committee_vector_type()(committee))


def test_committee_is_deterministic_and_sized():
    spec, state = _spec_state()
    committee = spec.get_inclusion_list_committee(state, 3)
    assert len(committee) == spec.INCLUSION_LIST_COMMITTEE_SIZE
    assert committee == spec.get_inclusion_list_committee(state, 3)
    assert committee != spec.get_inclusion_list_committee(state, 4)


def test_signature_validation():
    spec, state = _spec_state()
    committee = spec.get_inclusion_list_committee(state, 1)
    idx = committee[0]
    message = spec.InclusionList(
        slot=1,
        validator_index=idx,
        inclusion_list_committee_root=_committee_root(spec, committee),
        transactions=[b"\x01"],
    )
    bls.bls_active = True
    try:
        domain = spec.get_domain(
            state, spec.DOMAIN_INCLUSION_LIST_COMMITTEE, spec.compute_epoch_at_slot(1)
        )
        signing_root = spec.compute_signing_root(message, domain)
        sig = bls.Sign(privkeys[idx], signing_root)
        signed = spec.SignedInclusionList(message=message, signature=sig)
        assert spec.is_valid_inclusion_list_signature(state, signed)
        wrong = spec.SignedInclusionList(message=message, signature=b"\x11" * 96)
        assert not spec.is_valid_inclusion_list_signature(state, wrong)
    finally:
        bls.bls_active = False


def test_store_collects_and_dedupes_transactions():
    spec, state = _spec_state()
    committee = spec.get_inclusion_list_committee(state, 1)
    root = _committee_root(spec, committee)
    store = spec.get_inclusion_list_store()
    il1 = spec.InclusionList(
        slot=1, validator_index=committee[0],
        inclusion_list_committee_root=root, transactions=[b"\xaa", b"\xbb"],
    )
    il2 = spec.InclusionList(
        slot=1, validator_index=committee[1],
        inclusion_list_committee_root=root, transactions=[b"\xbb", b"\xcc"],
    )
    spec.process_inclusion_list(store, il1, True)
    spec.process_inclusion_list(store, il2, True)
    txs = sorted(spec.get_inclusion_list_transactions(store, state, 1))
    assert txs == [b"\xaa", b"\xbb", b"\xcc"]


def test_equivocation_removes_validator_lists():
    spec, state = _spec_state()
    committee = spec.get_inclusion_list_committee(state, 1)
    root = _committee_root(spec, committee)
    store = spec.get_inclusion_list_store()
    il = spec.InclusionList(
        slot=1, validator_index=committee[0],
        inclusion_list_committee_root=root, transactions=[b"\xaa"],
    )
    spec.process_inclusion_list(store, il, True)
    altered = il.copy()
    altered.transactions = [b"\xff"]
    spec.process_inclusion_list(store, altered, True)
    key = (1, bytes(root))
    assert committee[0] in store.equivocators[key]
    assert spec.get_inclusion_list_transactions(store, state, 1) == []
    # further lists from the equivocator are ignored
    spec.process_inclusion_list(store, il, True)
    assert spec.get_inclusion_list_transactions(store, state, 1) == []


def test_late_lists_not_stored():
    spec, state = _spec_state()
    committee = spec.get_inclusion_list_committee(state, 1)
    root = _committee_root(spec, committee)
    store = spec.get_inclusion_list_store()
    il = spec.InclusionList(
        slot=1, validator_index=committee[0],
        inclusion_list_committee_root=root, transactions=[b"\xaa"],
    )
    spec.process_inclusion_list(store, il, is_before_view_freeze_deadline=False)
    assert spec.get_inclusion_list_transactions(store, state, 1) == []


def test_on_inclusion_list_validates_membership():
    spec, state = _spec_state()
    committee = spec.get_inclusion_list_committee(state, 1)
    root = _committee_root(spec, committee)
    store = spec.get_inclusion_list_store()
    non_member = next(
        i for i in range(len(state.validators)) if i not in committee
    )
    message = spec.InclusionList(
        slot=1, validator_index=non_member,
        inclusion_list_committee_root=root, transactions=[],
    )
    signed = spec.SignedInclusionList(message=message)
    with pytest.raises(AssertionError):
        spec.on_inclusion_list(None, store, state, signed, True)
