"""EIP-6800 Verkle execution witnesses
(reference: specs/_features/eip6800/beacon-chain.md)."""

from eth_consensus_specs_tpu.forks.features import get_feature_spec
from eth_consensus_specs_tpu.ssz import hash_tree_root, serialize
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import (
    default_activation_threshold,
    default_balances,
)
from eth_consensus_specs_tpu.test_infra.genesis import create_genesis_state
from eth_consensus_specs_tpu.utils import bls


def _spec_state():
    bls.bls_active = False
    spec = get_feature_spec("eip6800", "minimal")
    state = create_genesis_state(
        spec, default_balances(spec), default_activation_threshold(spec)
    )
    return spec, state


def test_witness_types_roundtrip():
    spec, _ = _spec_state()
    OptionalBytes32 = spec.SuffixStateDiff.fields()["current_value"]
    diff = spec.SuffixStateDiff(
        suffix=b"\x07",
        current_value=OptionalBytes32(selector=1, value=b"\x01" * 32),
        new_value=OptionalBytes32(selector=0, value=None),
    )
    stem_diff = spec.StemStateDiff(stem=b"\x02" * 31, suffix_diffs=[diff])
    witness = spec.ExecutionWitness(state_diff=[stem_diff])
    data = serialize(witness)
    back = spec.ExecutionWitness.decode_bytes(data)
    assert hash_tree_root(back) == hash_tree_root(witness)


def test_header_commits_to_witness():
    spec, state = _spec_state()
    block = build_empty_block_for_next_slot(spec, state)
    diff = spec.StemStateDiff(stem=b"\x09" * 31)
    block.body.execution_payload.execution_witness = spec.ExecutionWitness(
        state_diff=[diff]
    )
    state_transition_and_sign_block(spec, state, block)
    header = state.latest_execution_payload_header
    assert bytes(header.execution_witness_root) == bytes(
        hash_tree_root(block.body.execution_payload.execution_witness)
    )


def test_empty_witness_block_applies():
    spec, state = _spec_state()
    block = build_empty_block_for_next_slot(spec, state)
    state_transition_and_sign_block(spec, state, block)
    assert int(state.slot) == 1
