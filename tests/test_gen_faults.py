"""Chaos paths of the vector generator (gen/gen_runner.py crash-safe
pool + gen/manifest.py + gen/dumper.py atomic writes):

* a SIGKILLed pool worker mid-run still yields ALL vectors (the lost
  case re-dispatches, a replacement worker spawns);
* a case past its wall-clock deadline is marked failed without hanging
  the pool;
* --resume after a simulated SIGKILL regenerates only the missing
  cases, rewriting zero already-durable ones;
* a fault-injected run's vectors are byte-identical (part digests) to a
  clean run's;
* corrupt-injected writes are caught by the dumper's read-back
  verification and retried — never left torn on disk.
"""

import multiprocessing as mp
import os
import signal
import time

import pytest

from eth_consensus_specs_tpu import fault, obs
from eth_consensus_specs_tpu.gen import (
    discover_test_cases,
    load_manifest,
    manifest_path,
    run_generator,
)


@pytest.fixture(scope="module")
def att_cases():
    cases = discover_test_cases(
        presets=("minimal",), forks=("phase0",), runners=("operations",)
    )
    cases = [c for c in cases if c.handler == "attestation"]
    assert len(cases) >= 5, "need a handful of attestation cases for chaos runs"
    return cases


def _digests(out_dir: str) -> dict:
    return {k: r["parts"] for k, r in load_manifest(manifest_path(out_dir)).items()}


def _counter(name: str) -> float:
    return obs.snapshot()["counters"].get(name, 0)


def test_pool_worker_kill_still_yields_all_vectors(att_cases, tmp_path):
    sub = att_cases[:6]
    clean_dir, chaos_dir = str(tmp_path / "clean"), str(tmp_path / "chaos")
    clean = run_generator(sub, clean_dir)
    assert clean["failed"] == 0

    replaced0, retried0 = _counter("gen.workers_replaced"), _counter("gen.cases_retried")
    latch = tmp_path / "kill.latch"
    # one worker SIGKILLs itself on its 2nd case (latch: exactly one kill
    # across the whole pool); forked workers inherit the installed rules
    with fault.injected(f"gen.case:kill:nth=2:latch={latch}"):
        chaos = run_generator(sub, chaos_dir, workers=2, case_retries=3)

    assert chaos["written"] == clean["written"]
    assert chaos["failed"] == 0
    assert _counter("gen.workers_replaced") - replaced0 >= 1
    assert _counter("gen.cases_retried") - retried0 >= 1
    # fault-injected vectors are byte-identical to the clean run's
    assert _digests(chaos_dir) == _digests(clean_dir)


def test_case_timeout_fails_without_hanging_pool(att_cases, tmp_path):
    sub = att_cases[:4]
    latch = tmp_path / "stall.latch"
    timeouts0 = _counter("gen.cases_timeout")
    t0 = time.monotonic()
    # one case stalls 60s against a 3s deadline, zero retries: the sweep
    # must kill the hung worker, fail the case, and finish the rest
    with fault.injected(f"gen.case:stall:nth=1:delay=60:latch={latch}"):
        stats = run_generator(
            sub, str(tmp_path / "out"), workers=2, case_timeout=3.0, case_retries=0
        )
    assert time.monotonic() - t0 < 45, "pool hung on the stalled case"
    assert stats["failed"] == 1
    assert stats["written"] + stats["skipped"] == len(sub) - 1
    assert _counter("gen.cases_timeout") - timeouts0 == 1


def test_timed_out_case_recovers_within_retry_budget(att_cases, tmp_path):
    sub = att_cases[:4]
    latch = tmp_path / "stall.latch"
    with fault.injected(f"gen.case:stall:nth=1:delay=60:latch={latch}"):
        stats = run_generator(
            sub, str(tmp_path / "out"), workers=2, case_timeout=3.0, case_retries=2
        )
    # the latch makes the stall one-shot: the re-dispatched case runs clean
    assert stats["failed"] == 0
    assert stats["written"] + stats["skipped"] == len(sub)


def test_resume_regenerates_only_missing_cases(att_cases, tmp_path):
    sub = att_cases[:5]
    out = str(tmp_path / "out")
    latch = str(tmp_path / "kill.latch")

    def interrupted():
        # sequential run that SIGKILLs itself on its 4th case — the
        # "operator's generation box died mid-run" scenario
        fault.install(f"gen.case:kill:nth=4:latch={latch}")
        run_generator(sub, out)

    proc = mp.get_context("fork").Process(target=interrupted)
    proc.start()
    proc.join(300)
    assert proc.exitcode == -signal.SIGKILL

    durable = load_manifest(manifest_path(out))
    assert 0 < len(durable) < len(sub)
    # snapshot every durable byte: resume must not rewrite any of them
    mtimes = {}
    for rec in durable.values():
        if rec["dir"] is None:
            continue
        case_dir = os.path.join(out, rec["dir"])
        for name in os.listdir(case_dir):
            p = os.path.join(case_dir, name)
            mtimes[p] = os.stat(p).st_mtime_ns

    stats = run_generator(sub, out, resume=True)
    assert stats["resumed"] == len(durable)
    assert stats["failed"] == 0
    assert stats["written"] + stats["skipped"] == len(sub) - len(durable)
    for p, mt in mtimes.items():
        assert os.stat(p).st_mtime_ns == mt, f"resume rewrote durable {p}"
    # the resumed tree is complete and matches a clean run byte-for-byte
    assert len(load_manifest(manifest_path(out))) == len(sub)
    clean_dir = str(tmp_path / "clean")
    run_generator(sub, clean_dir)
    assert _digests(out) == _digests(clean_dir)


def test_corrupt_write_is_caught_and_retried(att_cases, tmp_path):
    from eth_consensus_specs_tpu.gen.snappy_codec import frame_decompress

    sub = att_cases[:2]
    retries0 = _counter("gen.torn_writes")
    with fault.injected("gen.dump_bytes:corrupt:nth=1"):
        stats = run_generator(sub, str(tmp_path / "out"))
    assert stats["failed"] == 0
    assert _counter("gen.torn_writes") - retries0 == 1
    # nothing torn survived: every emitted part snappy-decodes
    checked = 0
    for root, _dirs, files in os.walk(tmp_path / "out"):
        for name in files:
            if name.endswith(".ssz_snappy"):
                with open(os.path.join(root, name), "rb") as f:
                    frame_decompress(f.read())
                checked += 1
            assert not name.endswith(".tmp"), f"stray tmp file {name}"
    assert checked > 0


def test_systemic_worker_death_aborts_instead_of_spinning(att_cases, tmp_path):
    # every worker dies on its first case (no latch) and the retry budget
    # can't be exhausted fast: the pool's circuit breaker must abort
    # loudly rather than respawn workers forever
    with fault.injected("gen.case:kill:nth=1:times=inf"):
        with pytest.raises(RuntimeError, match="failing systematically"):
            run_generator(att_cases[:3], str(tmp_path), workers=2, case_retries=50)


def test_stale_tmp_cleanup_restores_orphaned_overwrite_stash(tmp_path):
    from eth_consensus_specs_tpu.gen.dumper import OLD_SUFFIX
    from eth_consensus_specs_tpu.gen.manifest import clean_stale_tmp

    out = tmp_path / "tree"
    # killed mid-staging: uncommitted tmp dir -> deleted
    (out / "a" / "case.__tmp123").mkdir(parents=True)
    # killed between an overwrite's two renames: the stash is the only
    # copy of the durable vector -> restored to the final name
    orphan = out / "a" / ("case2" + OLD_SUFFIX)
    orphan.mkdir(parents=True)
    (orphan / "pre.ssz_snappy").write_bytes(b"x")
    # normal leftover stash next to a committed dir -> deleted
    (out / "a" / "case3").mkdir(parents=True)
    (out / "a" / ("case3" + OLD_SUFFIX)).mkdir(parents=True)

    clean_stale_tmp(str(out))
    assert not (out / "a" / "case.__tmp123").exists()
    assert (out / "a" / "case2" / "pre.ssz_snappy").read_bytes() == b"x"
    assert not (out / "a" / ("case2" + OLD_SUFFIX)).exists()
    assert (out / "a" / "case3").exists()
    assert not (out / "a" / ("case3" + OLD_SUFFIX)).exists()


def test_workers_auto_survives_unknown_cpu_count(att_cases, tmp_path, monkeypatch):
    # os.cpu_count() may return None: "auto" must fall back to one
    # worker, not crash on None - 1
    monkeypatch.setattr(os, "cpu_count", lambda: None)
    stats = run_generator(att_cases[:2], str(tmp_path), workers="auto")
    assert stats["failed"] == 0
    assert stats["written"] + stats["skipped"] == 2
