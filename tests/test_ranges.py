"""ranges — the interval interpreter: transfer functions, loops, rules.

Unit tests for the pure transfer functions (exact python-int interval
arithmetic — the foundation everything else trusts), then the
DELIBERATE-FINDING acceptance tests: a synthetic kernel built to
overflow MUST fire lane-overflow, a sha256-style wrap with its ``Wrap``
declaration removed MUST fire, a scan whose declared invariant is not
inductive MUST fire, and a mask over an unproven magnitude MUST fire
mask-consistency. A prover whose alarms never ring proves nothing."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eth_consensus_specs_tpu.analysis.ranges import (
    Ival,
    RangeInterp,
    Wrap,
    ival_binop,
    ival_join,
    ival_leq,
)


def _run(fn, in_ivals, *args, wraps=(), widen_steps=None):
    closed = jax.make_jaxpr(fn)(*args)
    interp = RangeInterp(wraps=wraps, widen_steps=widen_steps)
    outs = interp.run(closed, in_ivals)
    return outs, interp


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


# ------------------------------------------------------ transfer functions


def test_binop_add_sub_mul_exact():
    a, b = Ival(2, 5), Ival(10, 20)
    assert (ival_binop("add", a, b).lo, ival_binop("add", a, b).hi) == (12, 25)
    assert (ival_binop("sub", a, b).lo, ival_binop("sub", a, b).hi) == (-18, -5)
    assert (ival_binop("mul", a, b).lo, ival_binop("mul", a, b).hi) == (20, 100)
    # mixed-sign mul takes the corner extrema
    m = ival_binop("mul", Ival(-3, 2), Ival(-5, 7))
    assert (m.lo, m.hi) == (-21, 15)


def test_binop_arbitrary_precision_never_wraps():
    # the whole point: bounds are python ints, not numpy lanes
    big = (1 << 64) - 1
    iv = ival_binop("mul", Ival(0, big), Ival(0, big))
    assert iv.hi == big * big  # > 2^127, exact


def test_binop_shifts():
    a = Ival(8, 1024)
    assert (ival_binop("shift_right_logical", a, Ival(2, 3)).lo,
            ival_binop("shift_right_logical", a, Ival(2, 3)).hi) == (1, 256)
    s = ival_binop("shift_left", a, Ival(1, 4), dtype=jnp.uint64)
    assert (s.lo, s.hi) == (16, 16384)


def test_binop_shifts_negative_operands_stay_sound():
    # shift_left: a negative lo moves AWAY from zero as the shift grows,
    # so [-4, 1] << [0, 3] must cover -32 (not stop at -4)
    s = ival_binop("shift_left", Ival(-4, 1), Ival(0, 3), dtype=jnp.int64)
    assert (s.lo, s.hi) == (-32, 8)
    # ...and a fully-negative hi uses the SMALL shift for its max
    s = ival_binop("shift_left", Ival(-4, -2), Ival(1, 3), dtype=jnp.int64)
    assert (s.lo, s.hi) == (-32, -4)
    # shift_right_arithmetic: negatives move TOWARD zero as the shift
    # grows — [-100, -8] >> [0, 2] reaches -100 (lo@smin) and -2 (hi@smax)
    s = ival_binop("shift_right_arithmetic", Ival(-100, -8), Ival(0, 2))
    assert (s.lo, s.hi) == (-100, -2)
    # shift_right_logical reinterprets the bit pattern: a possibly-
    # negative int32 input covers the huge-positive result, not [0, 0]
    s = ival_binop("shift_right_logical", Ival(-1, 5), Ival(4, 8),
                   dtype=jnp.int32)
    assert s.lo == 0 and s.hi == ((1 << 32) - 1) >> 4
    # nonneg inputs keep the exact bounds
    s = ival_binop("shift_right_logical", Ival(16, 64), Ival(2, 4))
    assert (s.lo, s.hi) == (1, 16)


def test_binop_and_or_xor_masks():
    a = Ival(0, 0xABC)
    mask = Ival(0xFF, 0xFF)
    assert ival_binop("and", a, mask).hi == 0xFF  # min of the his
    o = ival_binop("or", a, mask, dtype=jnp.uint32)
    assert o.hi == 0xABC + 0xFF  # x|y <= x+y for nonneg
    assert o.lo == 0xFF  # or can only set bits
    assert ival_binop("xor", a, mask, dtype=jnp.uint32).lo == 0


def test_binop_elementwise_bounds():
    hi = np.array([3, 5, 7], dtype=object)
    iv = ival_binop("add", Ival(0, hi), Ival(1, 1))
    assert list(iv.hi) == [4, 6, 8]


def test_interval_join_and_leq():
    a, b = Ival(2, 5), Ival(4, 9)
    j = ival_join(a, b)
    assert (j.lo, j.hi) == (2, 9)
    assert ival_leq(a, j) and ival_leq(b, j)
    assert not ival_leq(j, a)
    # taint is ordered: tainted ⊄ untainted
    assert not ival_leq(Ival(0, 1, tainted=True), Ival(0, 1))
    assert ival_leq(Ival(0, 1), Ival(0, 1, tainted=True))


def test_select_and_concat_transfer():
    def sel(c, a, b):
        return jnp.where(c, a, b)

    outs, interp = _run(
        sel,
        [Ival(0, 1), Ival(5, 10), Ival(100, 200)],
        _sds((4,), jnp.bool_), _sds((4,), jnp.uint32), _sds((4,), jnp.uint32),
    )
    assert interp.events == []
    assert (int(np.min(outs[0].lo)), int(np.max(outs[0].hi))) == (5, 200)

    def cat(a, b):
        return jnp.concatenate([a, b])

    outs, interp = _run(
        cat,
        [Ival(0, 7), Ival(0, 1000)],
        _sds((2,), jnp.uint32), _sds((3,), jnp.uint32),
    )
    # positional structure preserved: first rows keep the tight bound
    hi = np.asarray(outs[0].hi)
    assert [int(x) for x in hi] == [7, 7, 1000, 1000, 1000]


# ------------------------------------------------- deliberate lane-overflow


def test_column_sum_proof_30_bits_clean_31_bits_fires():
    """THE proof from the field_limbs comment, both directions: a column
    of 13 products of 30-bit limbs plus carries stays under 2^64 — and
    at 31-bit limbs it does NOT, which must fire lane-overflow."""

    def column(a, b):
        acc = jnp.zeros(a.shape[:-1], jnp.uint64)
        for i in range(13):
            acc = acc + a[..., i] * b[..., 12 - i]
        return acc

    args = (_sds((4, 13), jnp.uint64), _sds((4, 13), jnp.uint64))

    lim30 = Ival(0, (1 << 30) - 1)
    outs, interp = _run(column, [lim30, lim30], *args)
    assert interp.events == [], [e.message for e in interp.events]
    assert int(np.max(np.asarray(outs[0].hi))) == 13 * ((1 << 30) - 1) ** 2

    lim31 = Ival(0, (1 << 31) - 1)
    _, interp = _run(column, [lim31, lim31], *args)
    kinds = {e.kind for e in interp.events}
    assert "overflow" in kinds, "13-term column at 31-bit limbs MUST overflow"


def test_unsanctioned_wrap_fires_and_wrap_declaration_silences():
    """A sha256-style mod-2^32 add: without the Wrap declaration it is a
    lane-overflow finding; with the per-site declaration it is clean."""

    def wrapping_add(a, b):
        return a + b  # mod 2^32 by design — but is the design DECLARED?

    args = (_sds((8,), jnp.uint32), _sds((8,), jnp.uint32))
    full = Ival(0, 0xFFFFFFFF)

    _, interp = _run(wrapping_add, [full, full], *args)
    assert any(e.kind == "overflow" and e.prim == "add" for e in interp.events)

    _, interp = _run(
        wrapping_add, [full, full], *args,
        wraps=(Wrap("add", "test_ranges.py::wrapping_add"),),
    )
    assert interp.events == []
    assert interp.stats["wrap_hits"] == 1


def test_wrap_site_matching_is_per_site_not_blanket():
    """The Wrap declaration names ONE function — a different overflow in
    the same file still fires."""

    def other_add(a, b):
        return a + b

    args = (_sds((8,), jnp.uint32), _sds((8,), jnp.uint32))
    full = Ival(0, 0xFFFFFFFF)
    _, interp = _run(
        other_add, [full, full], *args,
        wraps=(Wrap("add", "test_ranges.py::wrapping_add"),),
    )
    assert any(e.kind == "overflow" for e in interp.events)


def test_underflow_on_unsigned_fires():
    def sub(a, b):
        return a - b

    args = (_sds((4,), jnp.uint64), _sds((4,), jnp.uint64))
    _, interp = _run(sub, [Ival(0, 10), Ival(0, 20)], *args)
    assert any("underflows" in e.message for e in interp.events)


# --------------------------------------------------------------- scan loops


def test_converging_carry_recurrence_is_inductive():
    """The carry-sweep recurrence carry' = (col + carry) >> 30 stabilizes
    in a few joins — no widening, no findings, and the final carry bound
    is the fixed point."""

    def sweep(cols):
        def step(carry, col):
            cur = col + carry
            return cur >> jnp.uint64(30), cur & jnp.uint64((1 << 30) - 1)

        carry, out = jax.lax.scan(step, jnp.zeros((4,), jnp.uint64), cols)
        return carry, out

    col_hi = 13 * ((1 << 30) - 1) ** 2  # the column bound proved above
    outs, interp = _run(
        sweep, [Ival(0, col_hi)], _sds((25, 4), jnp.uint64)
    )
    assert interp.events == [], [e.message for e in interp.events]
    assert interp.stats["widened_loops"] == 0
    # fixed point: carry <= (col_hi + carry) >> 30 (+ the second-order
    # carry-of-carry term, itself < 64)
    assert int(np.max(np.asarray(outs[0].hi))) <= (col_hi >> 30) + 64


def test_non_inductive_scan_invariant_fires_widened():
    """A genuinely growing carry (doubling per step, data-dependent so
    unrolling can't rescue it) has NO inductive interval: the carry must
    widen to dtype-top and fire the unproven-loop finding."""

    def grower(xs):
        def step(carry, x):
            nxt = carry + carry + x  # doubles every step: no fixed point
            return nxt, nxt

        return jax.lax.scan(step, jnp.ones((2,), jnp.uint64), xs)

    _, interp = _run(
        grower, [Ival(0, 1 << 32)], _sds((64, 2), jnp.uint64), widen_steps=6
    )
    assert interp.stats["widened_loops"] == 1
    assert any(e.kind == "widened" for e in interp.events), (
        "a non-inductive carry MUST be reported as unproven"
    )


def test_concrete_xs_scan_unrolls_to_exact_proof():
    """A scan indexed by arange xs (the Montgomery red_step shape) whose
    carry genuinely grows per-step unrolls with static indices instead of
    widening — the per-position proof survives."""

    def shifter(t):
        def step(t, i):
            upd = jax.lax.dynamic_slice_in_dim(t, i, 1, axis=-1)[..., 0] + 1
            return jax.lax.dynamic_update_slice_in_dim(
                t, upd[..., None], i, axis=-1
            ), None

        out, _ = jax.lax.scan(step, t, jnp.arange(8, dtype=jnp.int32))
        return out

    outs, interp = _run(shifter, [Ival(0, 100)], _sds((2, 8), jnp.uint64))
    assert interp.events == []
    assert interp.stats["unrolled_scans"] == 1
    # exact result: every position bumped exactly once, nothing widened
    assert int(np.max(np.asarray(outs[0].hi))) == 101
    assert int(np.min(np.asarray(outs[0].lo))) == 1


# --------------------------------------------------------- mask-consistency


def test_masking_unproven_magnitude_fires_masked_taint():
    """AND-ing dtype-top taint (here: from a widened loop) with a low-bit
    mask pretends to extract a limb of a magnitude nothing proved."""

    def launder(xs):
        def step(carry, x):
            nxt = carry + carry + x
            return nxt, nxt

        grown, _ = jax.lax.scan(step, jnp.ones((2,), jnp.uint64), xs)
        return grown & jnp.uint64((1 << 26) - 1)

    _, interp = _run(
        launder, [Ival(0, 1 << 32)], _sds((64, 2), jnp.uint64), widen_steps=4
    )
    assert any(e.kind == "masked-taint" for e in interp.events), (
        "masking an unproven value MUST fire mask-consistency"
    )


def test_masking_taint_with_array_shaped_mask_still_fires():
    """A broadcast constant mask reaches the AND eqn with an exact
    elementwise interval — uniform array masks must not be a blind spot
    the taint can hide under."""

    def launder(xs):
        def step(carry, x):
            nxt = carry + carry + x
            return nxt, nxt

        grown, _ = jax.lax.scan(step, jnp.ones((2,), jnp.uint64), xs)
        return grown & jnp.full((2,), (1 << 26) - 1, jnp.uint64)

    _, interp = _run(
        launder, [Ival(0, 1 << 32)], _sds((64, 2), jnp.uint64), widen_steps=4
    )
    assert any(e.kind == "masked-taint" for e in interp.events), (
        "an array-shaped uniform mask over taint MUST still fire"
    )


def test_while_cond_arithmetic_is_checked():
    """The cond jaxpr runs on device once per iteration — an overflowing
    multiply inside it must fire even when the body is clean."""

    def loop(x):
        def cond(c):
            return c * jnp.uint64(1 << 40) < jnp.uint64(1 << 63)

        def body(c):
            return c

        return jax.lax.while_loop(cond, body, x)

    _, interp = _run(loop, [Ival(0, 1 << 32)], _sds((), jnp.uint64))
    assert any(e.kind == "overflow" for e in interp.events), (
        "u64 overflow inside a while COND must fire lane-overflow"
    )


def test_reduce_or_and_are_bitwise_not_minmax():
    """1|2 = 3 exceeds the elementwise max and 1&2 = 0 undershoots the
    elementwise min — the reduce transfer must cover the bit union."""

    def red_or(x):
        return jnp.bitwise_or.reduce(x, axis=0)

    def red_and(x):
        return jnp.bitwise_and.reduce(x, axis=0)

    outs, _ = _run(red_or, [Ival(0, 2)], _sds((4,), jnp.uint32))
    assert int(np.max(np.asarray(outs[0].hi))) >= 3  # bit-union cover
    outs, _ = _run(red_and, [Ival(1, 2)], _sds((4,), jnp.int32))
    assert int(np.min(np.asarray(outs[0].lo))) == 0  # AND can clear bits
    # bools keep the exact and==min transfer (jnp.all -> reduce_and)
    outs, _ = _run(lambda x: jnp.all(x, axis=0), [Ival(1, 1)],
                   _sds((4,), jnp.bool_))
    assert int(np.min(np.asarray(outs[0].lo))) == 1


def test_scan_widening_one_carry_rechecks_the_others():
    """Widening c1 to top can un-stabilize a dependent carry (c0 =
    c1 >> 32 is [0, 0] while c1 stays small): the kept carries must be
    re-checked against the WIDENED environment, or the analyzer
    certifies a tight interval runtime values escape."""

    def loop(xs):
        def step(carry, x):
            c0, c1 = carry
            # c1 >> 40 stays exactly 0 while c1 is small (pre-widening
            # c0 looks perfectly inductive) but reaches ~2^24 once c1
            # is topped — only the re-check can catch it
            return (c1 >> jnp.uint64(40), c1 + x), c0

        return jax.lax.scan(
            step, (jnp.zeros((2,), jnp.uint64), jnp.ones((2,), jnp.uint64)), xs
        )

    outs, interp = _run(
        loop, [Ival(0, 1 << 32)], _sds((64, 2), jnp.uint64), widen_steps=4
    )
    c0 = outs[0]
    assert c0.tainted or int(np.max(np.asarray(c0.hi))) >= (1 << 20), (
        f"non-inductive dependent carry kept a stale tight interval: {c0}"
    )


def test_length_zero_scan_output_covers_init():
    """A length-0 scan never runs its body: the carry output IS init, so
    the stable path must join init in (a body like ``c & 0xFF`` would
    otherwise certify [0, 255] for an un-reduced 2^30 init)."""

    def loop(c):
        out, _ = jax.lax.scan(
            lambda c, _: (c & jnp.uint64(0xFF), None), c, None, length=0
        )
        return out

    outs, _ = _run(loop, [Ival(0, 1 << 30)], _sds((2,), jnp.uint64))
    assert int(np.max(np.asarray(outs[0].hi))) >= (1 << 30), (
        f"length-0 scan output must cover init: {outs[0]}"
    )


def test_add_any_is_an_add_not_a_crash():
    """Transpose-of-fan-out accumulation (grad) emits ``add_any`` — it
    must go through the add transfer, not KeyError the whole run."""
    fn = jax.grad(lambda x: jnp.sum(x) + jnp.sum(x * 2.0))
    outs, interp = _run(fn, [Ival(0, 0)], _sds((4,), jnp.float32))
    assert not any(e.kind == "unhandled" for e in interp.events)


def test_div_rem_possibly_negative_divisors_stay_sound():
    # x // -1 = -x: a negative divisor flips the quotient's sign
    d = ival_binop("div", Ival(0, 10), Ival(-5, 5))
    assert d.lo <= -10 and d.hi >= 10
    # |rem| reaches |divisor| - 1 for the LARGEST-magnitude divisor
    r = ival_binop("rem", Ival(0, 200), Ival(-100, 5))
    assert r.lo <= -99 and r.hi >= 99
    # ...but never exceeds |dividend|
    r = ival_binop("rem", Ival(0, 3), Ival(-100, 5))
    assert (r.lo, r.hi) == (-3, 3)
    # the nonneg fast path stays exact
    d = ival_binop("div", Ival(10, 100), Ival(2, 5))
    assert (d.lo, d.hi) == (2, 50)
    r = ival_binop("rem", Ival(0, 200), Ival(1, 7))
    assert (r.lo, r.hi) == (0, 6)


def test_masking_proven_carry_separation_is_clean():
    """The legitimate pattern: (x & mask) with (x >> bits) separately
    carried — the interval proves the mask only truncates carry bits."""

    def split(a, b):
        s = a + b  # provably < 2^27, in-lane
        return s & jnp.uint64((1 << 26) - 1), s >> jnp.uint64(26)

    norm = Ival(0, (1 << 26) - 1)
    outs, interp = _run(
        split, [norm, norm], _sds((4,), jnp.uint64), _sds((4,), jnp.uint64)
    )
    assert interp.events == []
    assert int(np.max(np.asarray(outs[0].hi))) == (1 << 26) - 1
    assert int(np.max(np.asarray(outs[1].hi))) == 1  # the carry bit, exact


# ------------------------------------------------------------ trusted bound


def test_wrap_bound_declares_trusted_invariant():
    """Wrap(bound=B) clamps a sanctioned site's result to [0, B] — the
    borrow-restore idiom: transient underflow, restored under the mask."""

    def borrow_restore(a, b):
        cur = a - b  # transient underflow by design
        under = cur >> jnp.uint64(63)
        return cur + (under << jnp.uint64(30))

    norm = Ival(0, (1 << 30) - 1)
    wraps = (
        Wrap("sub", "test_ranges.py::borrow_restore"),
        Wrap("add", "test_ranges.py::borrow_restore", bound=(1 << 30) - 1),
    )
    outs, interp = _run(
        borrow_restore, [norm, norm],
        _sds((4,), jnp.uint64), _sds((4,), jnp.uint64), wraps=wraps,
    )
    assert interp.events == []
    assert int(np.max(np.asarray(outs[0].hi))) == (1 << 30) - 1


# ------------------------------------------------------------ pjit nesting


def test_intervals_flow_through_jit_boundaries():
    @jax.jit
    def inner(a):
        return a * a

    def outer(a):
        return inner(a) + 1

    outs, interp = _run(outer, [Ival(0, 100)], _sds((4,), jnp.uint64))
    assert interp.events == []
    assert int(np.max(np.asarray(outs[0].hi))) == 10001


def test_domain_seed_mismatch_is_loud():
    def f(a, b):
        return a + b

    closed = jax.make_jaxpr(f)(_sds((4,), jnp.uint32), _sds((4,), jnp.uint32))
    with pytest.raises(ValueError, match="domain seed mismatch"):
        RangeInterp().run(closed, [Ival(0, 1)])
