"""Vector emission + replay: the generator writes the canonical
config/fork/runner/handler/suite/case tree, and a consumer can replay an
operations vector against the spec and land on the emitted post state
(the conformance contract, reference: tests/formats/README.md)."""

import os

import pytest

from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.gen import discover_test_cases, run_generator
from eth_consensus_specs_tpu.gen.snappy_codec import frame_decompress
from eth_consensus_specs_tpu.ssz import deserialize, hash_tree_root
from eth_consensus_specs_tpu.utils import bls


@pytest.fixture(autouse=True)
def _bls_off():
    """Vectors are generated under the default bls kill-switch (stub
    signatures — real-signature vectors land with the device BLS backend),
    so replay must run under the same switch."""
    prior = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = prior


def _read_ssz(case_dir, name, typ):
    path = os.path.join(case_dir, f"{name}.ssz_snappy")
    with open(path, "rb") as f:
        return deserialize(typ, frame_decompress(f.read()))


def test_generator_emits_attestation_vectors(tmp_path):
    cases = discover_test_cases(
        presets=("minimal",), forks=("phase0",), runners=("operations",)
    )
    att_cases = [c for c in cases if c.handler == "attestation"]
    assert att_cases, "no attestation cases discovered"
    stats = run_generator(att_cases, str(tmp_path))
    assert stats["failed"] == 0
    assert stats["written"] > 0

    base = tmp_path / "minimal" / "phase0" / "operations" / "attestation" / "pyspec_tests"
    assert base.is_dir()
    case_dirs = sorted(p for p in base.iterdir() if p.is_dir())
    assert case_dirs

    import yaml

    spec = get_spec("phase0", "minimal")
    replayed = 0
    for case_dir in case_dirs:
        pre_path = case_dir / "pre.ssz_snappy"
        att_path = case_dir / "attestation.ssz_snappy"
        if not (pre_path.exists() and att_path.exists()):
            continue
        meta = {}
        if (case_dir / "meta.yaml").exists():
            meta = yaml.safe_load((case_dir / "meta.yaml").read_text())
        # honor the vector's bls_setting (1 = must verify signatures)
        bls.bls_active = meta.get("bls_setting", 0) == 1
        pre = _read_ssz(case_dir, "pre", spec.BeaconState)
        attestation = _read_ssz(case_dir, "attestation", spec.Attestation)
        post_path = case_dir / "post.ssz_snappy"
        if post_path.exists():
            post = _read_ssz(case_dir, "post", spec.BeaconState)
            spec.process_attestation(pre, attestation)
            assert hash_tree_root(pre) == hash_tree_root(post), case_dir.name
        else:
            # invalid-case convention: processing must reject
            try:
                spec.process_attestation(pre, attestation)
            except (AssertionError, IndexError, ValueError):
                pass
            else:
                raise AssertionError(f"{case_dir.name}: expected rejection")
        replayed += 1
    assert replayed > 0


def test_generator_sanity_blocks_replay(tmp_path):
    cases = discover_test_cases(presets=("minimal",), forks=("phase0",), runners=("sanity",))
    assert cases
    stats = run_generator(cases, str(tmp_path))
    assert stats["failed"] == 0

    base = tmp_path / "minimal" / "phase0" / "sanity" / "blocks" / "pyspec_tests"
    spec = get_spec("phase0", "minimal")
    replayed = 0
    for case_dir in sorted(p for p in base.iterdir() if p.is_dir()):
        if not (case_dir / "pre.ssz_snappy").exists():
            continue
        if not (case_dir / "post.ssz_snappy").exists():
            continue
        import yaml

        meta = {}
        meta_path = case_dir / "meta.yaml"
        if meta_path.exists():
            meta = yaml.safe_load(meta_path.read_text())
        n_blocks = int(meta.get("blocks_count", 0))
        assert n_blocks > 0, f"{case_dir.name}: blocks case without blocks"
        pre = _read_ssz(case_dir, "pre", spec.BeaconState)
        post = _read_ssz(case_dir, "post", spec.BeaconState)
        for i in range(n_blocks):
            block = _read_ssz(case_dir, f"blocks_{i}", spec.SignedBeaconBlock)
            spec.state_transition(pre, block, validate_result=False)
        assert hash_tree_root(pre) == hash_tree_root(post), case_dir.name
        replayed += 1
    assert replayed > 0


def test_manifest_overrides_runner_map(tmp_path):
    """@manifest coordinates must win over the module-map fallback
    (the seam the reference's Manifest provides, tests/infra/manifest.py)."""
    from eth_consensus_specs_tpu.gen.gen_from_tests import discover_test_cases
    from eth_consensus_specs_tpu.test_infra.manifest import vector_location_of

    cases = discover_test_cases(presets=("minimal",), forks=["phase0"])
    by_name = {}
    for c in cases:
        by_name.setdefault(c.case_name, c)
    # upgrade tests are pinned via the prefix map to transition/core
    transitions = [c for c in cases if c.runner == "transition"]
    assert all(c.handler == "core" for c in transitions)

    # a function-level @manifest must override both coordinates
    import types

    from eth_consensus_specs_tpu.gen import gen_from_tests as g
    from eth_consensus_specs_tpu.test_infra.manifest import manifest

    mod = types.ModuleType("tests.test_manifest_probe")

    @manifest(runner="pinned_runner", handler="pinned_handler", suite="special")
    def test_probe(generator_mode=False, phase=None, preset=None):
        return iter(())

    test_probe.phases = ["phase0"]
    mod.test_probe = test_probe

    real_iter = g._iter_test_modules
    g._iter_test_modules = lambda package_name="tests": iter([mod])
    try:
        found = g.discover_test_cases(presets=("minimal",))
    finally:
        g._iter_test_modules = real_iter
    assert len(found) == 1
    case = found[0]
    assert (case.runner, case.handler, case.suite) == (
        "pinned_runner",
        "pinned_handler",
        "special",
    )
    assert vector_location_of(test_probe).runner == "pinned_runner"
