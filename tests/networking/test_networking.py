"""Networking helper coverage (the reference's `networking` runner
category): subnet selection for attestations, long-lived subscriptions,
sync committees, blob and data-column sidecars.

reference: specs/phase0/validator.md:703-714, p2p-interface.md:1344-1361,
altair/validator.md:378-397, deneb/validator.md:197, electra/validator.md:321,
fulu/p2p-interface.md:173."""

import pytest

from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.forks import is_post_electra

ALL_FORKS = ["phase0", "altair", "bellatrix", "capella", "deneb", "electra", "fulu", "gloas"]


@pytest.mark.parametrize("fork", ALL_FORKS)
def test_compute_subnet_for_attestation_range_and_layout(fork):
    spec = get_spec(fork, "minimal")
    count = int(spec.config.ATTESTATION_SUBNET_COUNT)
    seen = set()
    cps = 4
    for slot in range(int(spec.SLOTS_PER_EPOCH)):
        for index in range(cps):
            subnet = spec.compute_subnet_for_attestation(cps, slot, index)
            assert 0 <= subnet < count
            seen.add(subnet)
    # consecutive committees in one slot get consecutive subnets
    assert spec.compute_subnet_for_attestation(cps, 0, 1) == (
        spec.compute_subnet_for_attestation(cps, 0, 0) + 1
    ) % count
    # next slot advances by committees_per_slot
    assert spec.compute_subnet_for_attestation(cps, 1, 0) == (
        spec.compute_subnet_for_attestation(cps, 0, 0) + cps
    ) % count


@pytest.mark.parametrize("fork", ["phase0", "electra"])
def test_compute_subscribed_subnets_deterministic_and_bounded(fork):
    spec = get_spec(fork, "minimal")
    cfg = spec.config
    node_id = 0xDEADBEEF << 200
    subnets = spec.compute_subscribed_subnets(node_id, epoch=100)
    assert len(subnets) == int(cfg.SUBNETS_PER_NODE)
    assert all(0 <= s < int(cfg.ATTESTATION_SUBNET_COUNT) for s in subnets)
    assert subnets == spec.compute_subscribed_subnets(node_id, epoch=100)
    # subscriptions rotate across periods but are stable inside one
    period = int(cfg.EPOCHS_PER_SUBNET_SUBSCRIPTION)
    node_offset = node_id % period
    same_period_epoch = 100 + (period - 1 - ((100 + node_offset) % period))
    assert subnets == spec.compute_subscribed_subnets(node_id, same_period_epoch)
    rotations = {
        tuple(spec.compute_subscribed_subnets(node_id, e)) for e in range(0, period * 8, period)
    }
    assert len(rotations) > 1


def test_subscribed_subnet_indices_are_consecutive_on_ring():
    spec = get_spec("phase0", "minimal")
    cfg = spec.config
    node_id = 12345
    subnets = spec.compute_subscribed_subnets(node_id, epoch=7)
    count = int(cfg.ATTESTATION_SUBNET_COUNT)
    for a, b in zip(subnets, subnets[1:]):
        assert b == (a + 1) % count


@with_phases(["altair", "bellatrix", "capella", "deneb", "electra"])
@spec_state_test
def test_compute_subnets_for_sync_committee(spec, state):
    member_pk = state.current_sync_committee.pubkeys[0]
    member_index = next(
        i for i, v in enumerate(state.validators) if bytes(v.pubkey) == bytes(member_pk)
    )
    subnets = spec.compute_subnets_for_sync_committee(state, member_index)
    bound = spec.SYNC_COMMITTEE_SUBNET_COUNT
    assert subnets and all(0 <= s < bound for s in subnets)
    # a validator in no sync committee gets no subnets
    committee_pks = {bytes(pk) for pk in state.current_sync_committee.pubkeys} | {
        bytes(pk) for pk in state.next_sync_committee.pubkeys
    }
    outsider = next(
        (
            i
            for i, v in enumerate(state.validators)
            if bytes(v.pubkey) not in committee_pks
        ),
        None,
    )
    if outsider is not None:
        assert spec.compute_subnets_for_sync_committee(state, outsider) == set()


@pytest.mark.parametrize("fork,expected_count_key", [
    ("deneb", "BLOB_SIDECAR_SUBNET_COUNT"),
    ("electra", "BLOB_SIDECAR_SUBNET_COUNT_ELECTRA"),
    ("fulu", "BLOB_SIDECAR_SUBNET_COUNT_ELECTRA"),
])
def test_compute_subnet_for_blob_sidecar(fork, expected_count_key):
    spec = get_spec(fork, "minimal")
    count = int(getattr(spec.config, expected_count_key))
    assert spec.compute_subnet_for_blob_sidecar(0) == 0
    assert spec.compute_subnet_for_blob_sidecar(count) == 0
    assert spec.compute_subnet_for_blob_sidecar(count + 3) == 3
    if is_post_electra(spec):
        assert count == 9


@pytest.mark.parametrize("fork", ["fulu", "gloas"])
def test_compute_subnet_for_data_column_sidecar(fork):
    spec = get_spec(fork, "minimal")
    count = int(spec.config.DATA_COLUMN_SIDECAR_SUBNET_COUNT)
    for col in [0, 1, count - 1, count, 3 * count + 5]:
        assert spec.compute_subnet_for_data_column_sidecar(col) == col % count


@pytest.mark.parametrize("fork", ALL_FORKS)
def test_fork_digest_distinct_per_fork(fork):
    spec = get_spec(fork, "minimal")
    from eth_consensus_specs_tpu.test_infra.forks import fork_version_of

    digest = spec.compute_fork_digest(fork_version_of(spec), b"\x00" * 32)
    assert len(bytes(digest)) == 4
    other = spec.compute_fork_digest(b"\xff\xff\xff\xff", b"\x00" * 32)
    assert bytes(digest) != bytes(other)
