"""Networking pure-function tables — fork digests, domains, subnet
subscription (reference analogue: the `networking` vector runner and
test/phase0/unittests/test_networking.py; spec:
specs/phase0/p2p-interface.md:1344+, validator.md subnet math)."""

from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test,
    with_all_phases,
    with_phases,
)


@with_all_phases
@spec_state_test
def test_fork_digest_depends_on_version(spec, state):
    root = bytes(state.genesis_validators_root)
    a = bytes(spec.compute_fork_digest(b"\x00\x00\x00\x00", root))
    b = bytes(spec.compute_fork_digest(b"\x01\x00\x00\x00", root))
    assert a != b and len(a) == 4


@with_all_phases
@spec_state_test
def test_fork_digest_depends_on_genesis_root(spec, state):
    v = b"\x00\x00\x00\x00"
    a = bytes(spec.compute_fork_digest(v, b"\x01" * 32))
    b = bytes(spec.compute_fork_digest(v, b"\x02" * 32))
    assert a != b


@with_all_phases
@spec_state_test
def test_fork_data_root_prefix_is_digest(spec, state):
    root = bytes(state.genesis_validators_root)
    v = bytes(state.fork.current_version)
    data_root = bytes(spec.compute_fork_data_root(v, root))
    digest = bytes(spec.compute_fork_digest(v, root)) if not hasattr(
        spec, "get_blob_parameters"
    ) else None
    if digest is not None:
        assert data_root[:4] == digest


@with_all_phases
@spec_state_test
def test_compute_domain_mixes_fork_digest(spec, state):
    root = bytes(state.genesis_validators_root)
    d1 = bytes(
        spec.compute_domain(spec.DOMAIN_BEACON_PROPOSER, b"\x00\x00\x00\x00", root)
    )
    d2 = bytes(
        spec.compute_domain(spec.DOMAIN_BEACON_PROPOSER, b"\x09\x00\x00\x00", root)
    )
    assert d1[:4] == bytes(spec.DOMAIN_BEACON_PROPOSER)
    assert d1 != d2


@with_all_phases
@spec_state_test
def test_get_domain_previous_epoch_uses_previous_fork(spec, state):
    """After a fork-version bump, messages for the previous epoch verify
    under the PREVIOUS version."""
    state.fork.epoch = spec.get_current_epoch(state)
    state.fork.previous_version = b"\x0a\x00\x00\x00"
    state.fork.current_version = b"\x0b\x00\x00\x00"
    if int(spec.get_current_epoch(state)) == 0:
        return
    d_prev = bytes(
        spec.get_domain(
            state, spec.DOMAIN_BEACON_ATTESTER, spec.get_previous_epoch(state)
        )
    )
    root = bytes(state.genesis_validators_root)
    expected = bytes(
        spec.compute_domain(
            spec.DOMAIN_BEACON_ATTESTER, b"\x0a\x00\x00\x00", root
        )
    )
    assert d_prev == expected


@with_all_phases
@spec_state_test
def test_subscribed_subnets_deterministic_shape(spec, state):
    node = 0x1234_5678_9ABC
    epoch = spec.get_current_epoch(state)
    subs = [int(s) for s in spec.compute_subscribed_subnets(node, epoch)]
    assert len(subs) == int(spec.config.SUBNETS_PER_NODE)
    assert subs == [int(s) for s in spec.compute_subscribed_subnets(node, epoch)]
    assert all(0 <= s < int(spec.config.ATTESTATION_SUBNET_COUNT) for s in subs)


@with_all_phases
@spec_state_test
def test_subscribed_subnets_node_dependence(spec, state):
    epoch = spec.get_current_epoch(state)
    base = [int(s) for s in spec.compute_subscribed_subnets(1, epoch)]
    # some node among a spread of ids lands on different subnets
    assert any(
        [int(s) for s in spec.compute_subscribed_subnets(node, epoch)] != base
        for node in (2, 3**50, 2**200, 2**255 - 19)
    )


@with_phases(["fulu", "gloas"])
@spec_state_test
def test_fulu_fork_digest_epoch_dependent_on_bpo(spec, state):
    """Fulu's digest folds the blob schedule: with an empty schedule the
    digest is stable across epochs."""
    root = bytes(state.genesis_validators_root)
    a = bytes(spec.compute_fork_digest(root, spec.get_current_epoch(state)))
    b = bytes(spec.compute_fork_digest(root, spec.get_current_epoch(state) + 1))
    if not len(spec.config.BLOB_SCHEDULE):
        assert a == b
