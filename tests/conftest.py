"""Test-session environment: force JAX onto CPU with 8 virtual devices so
multi-chip sharding (mesh/pjit/shard_map paths) is exercised without TPU
hardware. Must run before the first `import jax` anywhere in the suite."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The axon sitecustomize pins jax_platforms programmatically (config beats
# env), so force the config back to cpu before any backend initializes.
import jax

jax.config.update("jax_platforms", "cpu")

from eth_consensus_specs_tpu.utils.cache import enable_persistent_cache

enable_persistent_cache()

# Observability: per-test kernel counters + run-level obs_report.json
# (eth_consensus_specs_tpu/test_infra/obs_plugin.py). The fixture import
# makes `kernel_counters` available suite-wide.
from eth_consensus_specs_tpu.test_infra.obs_plugin import (  # noqa: E402,F401
    ObsPlugin,
    kernel_counters,
)


def pytest_configure(config):
    config.pluginmanager.register(ObsPlugin(str(config.rootpath)), "eth-specs-obs")
