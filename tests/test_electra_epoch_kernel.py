"""Electra accounting-kernel semantics: per-increment slashing rounding
and the per-validator MaxEB ceiling
(reference: specs/electra/beacon-chain.md:893-920 process_slashings,
:921-941 process_effective_balance_updates)."""

import pytest

# device epoch kernel compiles — nightly lane (make test-full)
pytestmark = pytest.mark.slow

import numpy as np

from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.ops.altair_epoch import (
    AltairEpochParams,
    altair_epoch_accounting,
)

import __graft_entry__ as graft


def _run(fork: str, electra_cols: bool):
    spec = get_spec(fork, "mainnet")
    params = AltairEpochParams.from_spec(spec)
    cols, just = graft._example_altair_inputs(512, electra=electra_cols)
    res = altair_epoch_accounting(params, cols, just)
    return spec, params, cols, just, res


def test_electra_slashing_rounding_differs_from_deneb():
    _, p_deneb, cols, just, res_deneb = _run("deneb", False)
    _, p_electra, _, _, res_electra = _run("electra", False)
    assert p_electra.electra_slashing and not p_deneb.electra_slashing
    # same inputs, different slashing rounding -> some slashed balances differ
    assert not np.array_equal(np.asarray(res_deneb.balance), np.asarray(res_electra.balance))


def test_electra_slashing_matches_spec_formula():
    """deneb and electra params differ ONLY in the slashing rounding for
    these inputs, so the per-validator balance delta between the two runs
    must equal exactly altair_penalty - electra_penalty at slashed
    validators inside the penalty window, and zero elsewhere."""
    spec_d, p_d, cols, just, res_d = _run("deneb", False)
    spec_e, p_e, _, _, res_e = _run("electra", False)
    assert (
        p_d.inactivity_penalty_quotient == p_e.inactivity_penalty_quotient
        and p_d.proportional_slashing_multiplier == p_e.proportional_slashing_multiplier
    ), "precondition: only the slashing rounding differs"

    incr = spec_e.EFFECTIVE_BALANCE_INCREMENT
    eff = [int(x) for x in np.asarray(cols.effective_balance)]
    active = (np.asarray(cols.activation_epoch) <= int(just.current_epoch)) & (
        int(just.current_epoch) < np.asarray(cols.exit_epoch)
    )
    total = max(sum(e for e, a in zip(eff, active) if a) // incr * incr, incr)
    adjusted = min(int(just.slashings_sum) * p_e.proportional_slashing_multiplier, total)
    per_increment = adjusted // (total // incr)
    half = p_e.epochs_per_slashings_vector // 2
    slash_now = np.asarray(cols.slashed) & (
        int(just.current_epoch) + half == np.asarray(cols.withdrawable_epoch)
    )

    bal_d = np.asarray(res_d.balance)
    bal_e = np.asarray(res_e.balance)
    for i in range(len(eff)):
        if slash_now[i]:
            altair_penalty = eff[i] // incr * adjusted // total * incr
            electra_penalty = per_increment * (eff[i] // incr)
            assert int(bal_d[i]) - int(bal_e[i]) == electra_penalty - altair_penalty, i
        else:
            assert bal_d[i] == bal_e[i], i
    assert slash_now.any(), "fixture must exercise the slashing window"


def test_per_validator_max_effective_balance_caps_hysteresis():
    spec, params, cols, just, res = _run("electra", True)
    eff_out = np.asarray(res.effective_balance)
    max_eff = np.asarray(cols.max_effective_balance)
    assert (eff_out <= max_eff).all()
    # without the column, everything is capped at the scalar 32 ETH
    _, _, cols0, _, res0 = _run("electra", False)
    assert (np.asarray(res0.effective_balance) <= 32_000_000_000).all()


def test_column_and_scalar_agree_when_uniform():
    """A uniform 32-ETH MaxEB column must reproduce the scalar path
    bit-exactly."""
    spec = get_spec("electra", "mainnet")
    params = AltairEpochParams.from_spec(spec)
    cols, just = graft._example_altair_inputs(256, electra=False)
    uniform = cols._replace(
        max_effective_balance=np.full(256, 32_000_000_000, np.uint64)
    )
    a = altair_epoch_accounting(params, cols, just)
    b = altair_epoch_accounting(params, uniform, just)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
