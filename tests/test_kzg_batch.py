"""Device-batched KZG blob verification (ops/kzg_batch) + its serve
wiring and the insecure-setup provenance round-trip.

Fast lane: parse/verdict semantics against the host oracle's reject
surface, the live fr_fft/kzg compile-key fns, the generated setup's
embedded provenance + generation-math round-trip at a toy size, the
host-level full-size setup round-trip, and the serve degrade path
(fault-forced — no XLA compiles anywhere in the fast lane).

Slow lane (nightly, like the rest of the device-crypto suite): the
device pipeline end to end — batched inverse-FFT challenge evaluation,
the ONE RLC multi-MSM, bisection isolation — bit-identical to
crypto/kzg.py, and the device half of the setup round-trip."""

from __future__ import annotations

from concurrent.futures import wait

import pytest

from eth_consensus_specs_tpu import fault, serve
from eth_consensus_specs_tpu.crypto import kzg, kzg_setup
from eth_consensus_specs_tpu.crypto.curve import (
    g1_from_bytes,
    g1_generator,
    g2_from_bytes,
    g2_generator,
)
from eth_consensus_specs_tpu.crypto.fields import R
from eth_consensus_specs_tpu.ops import kzg_batch
from eth_consensus_specs_tpu.serve import buckets
from eth_consensus_specs_tpu.serve.config import ServeConfig

# the ONE sparse-monomial construction, shared with scripts/das_bench.py
# — this suite exercises exactly what the bench runs
from eth_consensus_specs_tpu.test_infra.blob import sparse_blob_triple


@pytest.fixture(scope="module")
def triples():
    return [sparse_blob_triple(i) for i in range(3)]


# ----------------------------------------------------- verdict semantics --


def test_parse_rejects_exactly_what_the_host_oracle_rejects(triples):
    """parse_item's None set must equal verify_blob_host's False-by-
    malformation set — the serve layer's per-item verdict contract."""
    blob, c, p = triples[0]
    assert kzg_batch.parse_item((blob, c, p)) is not None
    bad = [
        (blob[:-1], c, p),  # short blob
        (blob, c[:-1], p),  # short commitment
        (blob, c, p + b"\x00"),  # long proof
        # field element >= modulus in the first blob slot
        (R.to_bytes(32, "big") + blob[32:], c, p),
        # not-a-point commitment (flipped compression bits)
        (blob, b"\x01" * 48, p),
        (blob, c, b"\x01" * 48),
    ]
    for item in bad:
        assert kzg_batch.parse_item(item) is None
        assert kzg_batch.verify_blob_host(*item) is False
    # infinity is a VALID G1 encoding for commitment and proof
    inf = kzg.G1_POINT_AT_INFINITY
    assert kzg_batch.parse_item((blob, inf, inf)) is not None


def test_host_verdicts_on_valid_and_tampered(triples):
    blob, c, p = triples[0]
    assert kzg_batch.verify_blob_host(blob, c, p) is True
    _, _, bad = sparse_blob_triple(0, tamper=True)
    assert kzg_batch.verify_blob_host(blob, c, bad) is False


# ------------------------------------------------------------- key fns --


def test_kzg_key_fns_bucket_and_sign():
    # a flush of n blobs folds into 2n+1 lanes, item-bucketed pow2
    assert buckets.kzg_lane_bucket(1) == 4
    assert buckets.kzg_lane_bucket(2) == 8
    assert buckets.kzg_lane_bucket(3) == 16  # pow2(3)=4 -> 2*4+1 -> 16
    assert buckets.kzg_msm_key(3) == ("kzg", 16)
    # flush sizes sharing an item bucket share a compile
    assert buckets.kzg_msm_key(5) == buckets.kzg_msm_key(8)
    # profile form agrees with the unsigned live form
    assert buckets.kzg_msm_key_from_profile(3) == buckets.kzg_msm_key(3)
    signed = buckets.kzg_msm_key_from_profile(8, shards=4, sig="cpu2x2")
    assert signed[0] == "kzg" and signed[-1] == "cpu2x2"
    # fr_fft: pow2 batch bucket + the intrinsic FFT size
    assert buckets.fr_fft_key(3, 4096) == ("fr_fft", 4, 4096)
    assert buckets.fr_fft_key_from_profile(3, 4096, 4, "cpu2x2") == (
        "fr_fft", 4, 4096, "cpu2x2",
    )
    # the router sees the lane bucket / FFT size as the warmable shape
    assert buckets.route_shape_of_key(("kzg", 16)) == ("kzg", 16)
    assert buckets.route_shape_of_key(("fr_fft", 4, 4096)) == ("fr_fft", 4096)
    # wide routing keys on the lane crossover
    assert buckets.route_wide("kzg", buckets.kzg_lane_bucket(8), 8)
    assert not buckets.route_wide("kzg", buckets.kzg_lane_bucket(1), 1)


def test_widen_warm_keys_generates_signed_kzg_and_fft_variants():
    cfg = ServeConfig(max_batch=8, buckets=(1, 2, 4, 8))
    base = [("kzg", 4), ("fr_fft", 1, 4096)]
    out = buckets.widen_warm_keys(base, cfg, shards=4, sig="cpu2x2")
    assert ("kzg", 4) in out and ("fr_fft", 1, 4096) in out
    signed_kzg = [k for k in out if k[0] == "kzg" and k[-1] == "cpu2x2"]
    signed_fft = [k for k in out if k[0] == "fr_fft" and k[-1] == "cpu2x2"]
    assert signed_kzg, "no signed kzg lane shapes for the wide profile"
    assert signed_fft, "no signed fr_fft batch shapes for the wide profile"
    # narrow profiles get the unsigned list verbatim
    assert buckets.widen_warm_keys(base, cfg, shards=1, sig="") == base


# ------------------------------------------------ setup provenance --


def test_generated_setup_embeds_provenance_and_round_trips_tiny():
    """generate_setup's first key documents the insecure provenance,
    and the generation math round-trips: monomial points are tau-power
    multiples of G, the Lagrange points interpolate them (checked via
    the L_i(tau) scalar identity), and g2[1] = tau*G2."""
    setup = kzg_setup.generate_setup(n=4, g2_length=2)
    assert list(setup)[0] == "provenance"
    assert "INSECURE" in setup["provenance"]
    assert "public" in setup["provenance"]
    assert setup["provenance"] == kzg_setup.PROVENANCE
    tau = kzg_setup.testing_tau()
    G, G2 = g1_generator(), g2_generator()
    for i in range(4):
        assert g1_from_bytes(bytes.fromhex(setup["g1_monomial"][i][2:])) == G.mul(
            pow(tau, i, R)
        )
    assert g2_from_bytes(bytes.fromhex(setup["g2_monomial"][1][2:])) == G2.mul(tau)
    # Lagrange identity: sum_i L_i(tau) = 1, so the lagrange points sum to G
    acc = None
    for h in setup["g1_lagrange"]:
        p = g1_from_bytes(bytes.fromhex(h[2:]))
        acc = p if acc is None else acc + p
    assert acc == G


def test_committed_setup_file_carries_provenance_and_verifies_host(triples):
    """The committed full-size artifact: provenance embedded, and a
    known blob round-trips through the HOST path under it (the device
    half is the slow-lane test below)."""
    import json

    raw = json.load(open(kzg_setup.setup_path(kzg.FIELD_ELEMENTS_PER_BLOB)))
    assert raw.get("provenance") == kzg_setup.PROVENANCE
    blob, c, p = triples[0]
    assert kzg.verify_blob_kzg_proof(blob, c, p)


# ------------------------------------------------------- serve wiring --


def test_submit_blob_verify_degraded_path_matches_host(triples):
    """The whole-flush host degrade: with the device path fault-killed,
    submit_blob_verify futures must resolve to exactly the
    verify_blob_host verdicts (valid True, tampered False, malformed
    False) — no XLA anywhere."""
    items = [
        triples[0],
        sparse_blob_triple(1, tamper=True),
        (triples[2][0][:-1], triples[2][1], triples[2][2]),  # malformed
    ]
    want = [kzg_batch.verify_blob_host(*it) for it in items]
    assert want == [True, False, False]
    with fault.injected("serve.dispatch:raise:times=inf"):
        with serve.VerifyService(
            ServeConfig.from_env(max_batch=4, max_wait_ms=5)
        ) as svc:
            futs = [svc.submit_blob_verify(*it) for it in items]
            wait(futs, timeout=120)
            assert [f.result() for f in futs] == want


def test_frontdoor_host_rung_serves_kzg(triples):
    from eth_consensus_specs_tpu.serve.frontdoor import _host_execute

    blob, c, p = triples[0]
    assert _host_execute("kzg", (blob, c, p)) is True
    _, _, bad = sparse_blob_triple(0, tamper=True)
    assert _host_execute("kzg", (blob, c, bad)) is False


def test_blob_admission_accounts_full_blob_bytes(triples):
    """Admission at blob scale: one blob costs ~131 KiB, so a small
    byte cap sheds the second submit while the queue cap never would."""
    from eth_consensus_specs_tpu.serve.admission import AdmissionController, Overloaded

    blob, c, p = triples[0]
    cost = len(blob) + len(c) + len(p)
    assert cost == kzg.BYTES_PER_BLOB + 96
    ctrl = AdmissionController(max_queue=1024, max_bytes=cost + 10)
    ctrl.admit(cost)
    with pytest.raises(Overloaded) as exc_info:
        ctrl.admit(cost)
    assert exc_info.value.reason == "bytes"
    ctrl.release(cost)


# ------------------------------------------------------- device parity --
# real kernel dispatches — nightly lane like the rest of device crypto


@pytest.mark.slow
def test_verify_many_blobs_device_parity_and_bisection(triples):
    """Device verdicts bit-identical to the host oracle, tampered item
    isolated via bisection, malformed item False without poisoning the
    flush, and the batch twin equal to verify_blob_kzg_proof_batch."""
    items = [
        triples[0],
        sparse_blob_triple(1, tamper=True),
        triples[2],
    ]
    want = [kzg_batch.verify_blob_host(*it) for it in items]
    assert kzg_batch.verify_many_blobs(items) == want == [True, False, True]
    blobs, cs, ps = map(list, zip(*[triples[0], triples[2]]))
    assert kzg_batch.verify_blob_kzg_proof_batch_device(blobs, cs, ps) is True
    assert kzg.verify_blob_kzg_proof_batch(blobs, cs, ps) is True
    # malformed rides along as False
    mixed = items + [(triples[0][0][:100], triples[0][1], triples[0][2])]
    assert kzg_batch.verify_many_blobs(mixed) == want + [False]


@pytest.mark.slow
def test_device_challenge_evaluation_matches_barycentric(triples):
    """The batched inverse-FFT Lagrange path: y values bit-identical to
    the host barycentric oracle, including a challenge that lands ON a
    root of unity (the host's special case; coefficient form needs
    none)."""
    blob, c, p = triples[0]
    parsed = kzg_batch.parse_item((blob, c, p))
    poly, z = parsed[3], parsed[4]
    (y,) = kzg_batch.challenge_evaluations([parsed])
    assert y == kzg.evaluate_polynomial_in_evaluation_form(poly, z)
    in_domain = list(parsed)
    in_domain[4] = kzg._roots_brp(kzg.FIELD_ELEMENTS_PER_BLOB)[7]
    (y2,) = kzg_batch.challenge_evaluations([tuple(in_domain)])
    assert y2 == poly[7]


@pytest.mark.slow
def test_generated_setup_round_trips_on_device(triples):
    """The setup round-trip's device half: the same known blob that
    verifies under the host oracle verifies through the device pipeline
    (FFT evaluation + RLC multi-MSM + pairing) under the generated
    setup."""
    blob, c, p = triples[0]
    assert kzg_batch.verify_many_blobs([(blob, c, p)]) == [True]
