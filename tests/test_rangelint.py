"""rangelint engine tests — the rules fire through the REGISTRY path.

test_ranges.py proves the interpreter (transfer functions, loops, the
interpreter-level deliberate findings). This file proves the ENGINE that
CI actually gates on: a registered family whose ``wraps`` declaration is
stripped fires lane-overflow, the synthetic 13-term column kernel fires
through ``analyze`` at 31-bit limbs and is clean at 30, a non-inductive
scan carry surfaces as an unproven-loop finding, the lazy-bound audit is
CLEAN on the shipped lazy_limbs (the regression pinning inferred ==
claimed for add/dbl chains) and fires on a deliberately lying claim, and
the shipped baseline is empty with lane-overflow unbaselinable."""

from __future__ import annotations

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eth_consensus_specs_tpu.analysis import kernels, rangelint
from eth_consensus_specs_tpu.analysis.ranges import Domain


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def _family(name):
    return kernels.by_name()[name]


def _small_sha(wraps):
    """The sha256 family restricted to its small tile — same kernel,
    same domains, cheap enough for a unit test — with ``wraps`` under
    the test's control."""
    spec = _family("sha256")
    small = [v for v in spec.build_variants(None) if v.label.endswith("tile2048")]
    assert small, "sha256 registry lost its 2048 tile"
    return dataclasses.replace(spec, wraps=wraps, build_variants=lambda mesh: small)


def _synth_spec(name, fn, args, domains, **kw):
    return kernels.KernelSpec(
        name=name,
        help="synthetic rangelint test kernel",
        dtypes=frozenset({"uint64"}),
        donation_waiver="synthetic test kernel — nothing to donate",
        build_variants=lambda mesh: [
            kernels.Variant("single", fn, args, domains=domains)
        ],
        **kw,
    )


# ------------------------------------------------- deliberate engine findings


def test_sha256_with_wraps_removed_fires_lane_overflow():
    """The acceptance deliberate-finding: strip the per-site Wrap
    declarations from sha256 and its mod-2^32 adds MUST surface as
    lane-overflow through the registry engine; with the declarations
    restored the very same variant proves clean."""
    findings, _ = rangelint.analyze(
        registry=(_small_sha(wraps=()),), rules={"lane-overflow"}
    )
    assert findings, "undeclared sha256 wraps MUST fire lane-overflow"
    assert {f.rule for f in findings} == {"lane-overflow"}
    assert {f.path for f in findings} == {"sha256"}

    findings, stats = rangelint.analyze(
        registry=(_small_sha(wraps=_family("sha256").wraps),),
        rules={"lane-overflow", "mask-consistency"},
    )
    assert findings == [], [f.message for f in findings]
    assert stats["wrap_hits"] > 0, "the declared sites must actually be hit"


def test_synthetic_column_sum_31_bits_fires_through_engine():
    """ISSUE acceptance kernel: a 13-term u64 column sum is provably
    in-lane at 30-bit limbs and MUST overflow at 31 — through the full
    registry path (domains seed the intervals, findings get kernel::rule
    fingerprints)."""

    def column(a, b):
        acc = jnp.zeros(a.shape[:-1], jnp.uint64)
        for i in range(13):
            acc = acc + a[..., i] * b[..., 12 - i]
        return acc

    args = (_sds((4, 13), jnp.uint64),) * 2

    def spec(bits):
        dom = Domain(f"{bits}-bit limbs", hi=(1 << bits) - 1)
        return _synth_spec(f"synth_column{bits}", column, args, (dom, dom))

    clean, _ = rangelint.analyze(registry=(spec(30),), rules={"lane-overflow"})
    assert clean == [], [f.message for f in clean]

    dirty, _ = rangelint.analyze(registry=(spec(31),), rules={"lane-overflow"})
    assert any(f.rule == "lane-overflow" for f in dirty), (
        "13-term column at 31-bit limbs MUST fire through the engine"
    )
    assert all(f.fingerprint.startswith("synth_column31::") for f in dirty)


def test_lane_overflow_ships_even_under_narrowed_rules():
    """--rules mask-consistency is not an opt-out: an overflow surfaced
    while the narrowed sweep runs must ship anyway (HARD_RULES)."""

    def column(a, b):
        acc = jnp.zeros(a.shape[:-1], jnp.uint64)
        for i in range(13):
            acc = acc + a[..., i] * b[..., 12 - i]
        return acc

    dom = Domain("31-bit limbs", hi=(1 << 31) - 1)
    spec = _synth_spec(
        "synth_column31n",
        column,
        (_sds((4, 13), jnp.uint64),) * 2,
        (dom, dom),
    )
    findings, _ = rangelint.analyze(
        registry=(spec,), rules={"mask-consistency"}
    )
    assert any(f.rule == "lane-overflow" for f in findings), (
        "a narrowed rule set must not filter the hard rule"
    )


def test_non_inductive_scan_fires_through_engine():
    """A doubling scan carry has no inductive interval: the engine must
    report the widened loop as an unproven lane-overflow finding."""

    def grower(xs):
        def step(carry, x):
            nxt = carry + carry + x
            return nxt, nxt

        return jax.lax.scan(step, jnp.ones((2,), jnp.uint64), xs)

    spec = _synth_spec(
        "synth_grower",
        grower,
        (_sds((64, 2), jnp.uint64),),
        (Domain("u32-ish inputs", hi=1 << 32),),
    )
    findings, stats = rangelint.analyze(
        registry=(spec,), rules={"lane-overflow"}, widen_steps=4
    )
    assert any(f.rule == "lane-overflow" for f in findings)
    assert stats["widened_loops"] >= 1


def test_timeout_is_an_unproven_lane_overflow_finding():
    """An exhausted analysis budget may not pass silently: the family is
    UNPROVEN, which the engine reports under the never-baselined rule."""
    findings, _ = rangelint.analyze(
        registry=(_small_sha(wraps=_family("sha256").wraps),),
        rules={"lane-overflow"},
        timeout_s=0.0,
    )
    assert any(f.symbol.endswith(":timeout") for f in findings)
    assert {f.rule for f in findings} == {"lane-overflow"}


# ----------------------------------------------------------- lazy-bound-audit


def test_lazy_bound_audit_clean_is_the_regression():
    """Satellite pin: on the shipped lazy_limbs every audited chain's
    claimed max_limb equals (up to the sanctioned NORM_MAX floor) the
    interval the interpreter infers — add/dbl growth, the sub lend path
    under a grown subtrahend, and the Montgomery mul output."""
    findings, stats = rangelint.audit_lazy_bounds()
    assert findings == [], [f.message for f in findings]
    assert stats["chains"] == 7


def test_lazy_bound_audit_fires_on_tighter_claim(monkeypatch):
    """A claim TIGHTER than the inferred reachable bound is a soundness
    bug and must fire — downstream preconditions trust the claim."""
    from eth_consensus_specs_tpu.ops import lazy_limbs as lz

    real_add = lz.add

    def lying_add(x, y):
        out = real_add(x, y)
        return lz.LF(out.v, max(out.max // 2, 1), out.val)

    monkeypatch.setattr(lz, "add", lying_add)
    findings, _ = rangelint.audit_lazy_bounds()
    assert any(f.symbol == "add:claim-tight" for f in findings), [
        f.symbol for f in findings
    ]
    assert all(f.rule == "lazy-bound-audit" for f in findings)


def test_lazy_bound_audit_fires_on_looser_claim(monkeypatch):
    """A claim LOOSER than inferred (above the NORM_MAX floor) is waste
    — it forces premature shrink/norm sweeps — and must fire too."""
    from eth_consensus_specs_tpu.ops import lazy_limbs as lz

    real_dbl = lz.dbl

    def padded_dbl(x):
        out = real_dbl(x)
        return lz.LF(out.v, out.max * 4, out.val)

    monkeypatch.setattr(lz, "dbl", padded_dbl)
    findings, _ = rangelint.audit_lazy_bounds()
    assert any(
        f.symbol.endswith(":claim-loose") and f.symbol.startswith("dbl")
        for f in findings
    ), [f.symbol for f in findings]


def test_audit_surfaced_overflow_is_a_lane_overflow_finding(monkeypatch):
    """An actual in-lane wrap inside an audited chain is a LANE bug the
    audit happened to surface: it must fingerprint as ``lane-overflow``
    (HARD_RULES, never baselinable), not as baselinable audit debt —
    and it must ship even when --rules narrows to lazy-bound-audit."""
    from eth_consensus_specs_tpu.ops import lazy_limbs as lz

    real_add = lz.add

    def overflowing_add(x, y):
        out = real_add(x, y)
        # a raw << 40 pushes a ~2^27-bounded lane past 2^64: a real
        # unsanctioned u64 wrap inside the chain, not a lying claim
        return lz.LF(out.v + (out.v << 40), out.max, out.val)

    monkeypatch.setattr(lz, "add", overflowing_add)
    findings, _ = rangelint.audit_lazy_bounds()
    lane = [f for f in findings if f.rule == "lane-overflow"]
    assert lane, [f"{f.rule}:{f.symbol}" for f in findings]
    assert all("::lane-overflow::" in f.fingerprint for f in lane)
    # the engine keeps hard-rule findings even under a narrowed rule set
    narrowed, _ = rangelint.analyze(
        registry=(), rules={"lazy-bound-audit"}, only={"lazy_limbs"}
    )
    assert any(f.rule == "lane-overflow" for f in narrowed), [
        f"{f.rule}:{f.symbol}" for f in narrowed
    ]


def test_lend_cap_constant_is_pinned_to_the_wrap_declaration():
    """sub's trace-time assertion and the analyzer's trusted bound for
    the ``fat - y`` lend site must be the SAME number — if they drift,
    one of them is lying about the other's guarantee."""
    from eth_consensus_specs_tpu.ops import lazy_limbs as lz

    lend = [
        w
        for w in kernels.lazy_lend_wraps()
        if w.site == "lazy_limbs.py::sub" and w.prim == "sub"
    ]
    assert len(lend) == 1
    assert lend[0].bound == lz._LEND_LIMB_CAP


def test_sub_auto_shrinks_an_over_fat_subtrahend():
    """The bound-growth guard on the _fat_p lend path: a subtrahend
    whose static bound would push the fat cover past the declared cap is
    auto-shrunk (the module's violations-insert-a-sweep contract), never
    silently covered with an out-of-cap limb."""
    from eth_consensus_specs_tpu.ops import lazy_limbs as lz

    x = lz.lf(jnp.zeros((lz.N_LIMBS,), jnp.uint64))
    fat_y = lz.LF(
        jnp.asarray(lz.to_mont(7)),
        lz._LEND_LIMB_CAP * 4,
        2 * lz.P_INT - 1,
    )
    out = lz.sub(x, fat_y)
    assert out.max <= lz.lf(x.v).max + lz._LEND_LIMB_CAP
    assert lz.from_mont_int(np.asarray(lz.shrink(out).v)) == lz.P_INT - 7


# ------------------------------------------------------------------- contract


def test_shipped_baseline_is_empty_and_lane_overflow_is_hard():
    root = os.path.join(os.path.dirname(__file__), os.pardir)
    with open(os.path.join(root, "rangelint_baseline.json")) as fh:
        base = json.load(fh)
    assert base["findings"] == {}, "rangelint ships an EMPTY baseline"
    assert "lane-overflow" in rangelint.HARD_RULES


def test_registry_wrap_declarations_are_per_site_never_blanket():
    """Every registered Wrap names one primitive at one file::function
    site — a bare filename (or empty site) would be a blanket sanction,
    exactly what the rule design forbids."""
    for spec in kernels.REGISTRY:
        for w in spec.wraps:
            assert w.prim and "::" in w.site and not w.site.startswith("::"), (
                spec.name,
                w,
            )
