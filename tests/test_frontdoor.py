"""Replicated front door — the failure matrix.

Contract under test (serve/frontdoor.py + replica.py + router.py +
wire.py): every admitted request resolves to exactly what the direct
ops call returns — through a healthy fleet, through a SIGKILLed
replica, through a stalled replica (hedged), through corrupt frames,
through a planned rollover (zero shed), and with no replica at all
(host-oracle last rung). Admission slots release exactly once however
many legs race.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from eth_consensus_specs_tpu import fault, obs, serve
from eth_consensus_specs_tpu.obs import timeline, trace
from eth_consensus_specs_tpu.ops import bls_batch
from eth_consensus_specs_tpu.ops import merkle as ops_merkle
from eth_consensus_specs_tpu.serve import buckets, wire
from eth_consensus_specs_tpu.serve.admission import AdmissionController, Overloaded
from eth_consensus_specs_tpu.serve.config import FrontDoorConfig, ServeConfig
from eth_consensus_specs_tpu.serve.frontdoor import FrontDoor, FrontDoorClient
from eth_consensus_specs_tpu.serve.router import Router
from eth_consensus_specs_tpu.utils import bls

TREE_DEPTH = 5


def _counter(name: str) -> float:
    return obs.snapshot()["counters"].get(name, 0)


def _serve_cfg(**kw) -> ServeConfig:
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_ms", 5)
    kw.setdefault("buckets", (1, 2, 4))
    return ServeConfig.from_env(**kw)


def _fd_cfg(**kw) -> FrontDoorConfig:
    kw.setdefault("hedge_ms", 0.0)  # hedging is its own test
    kw.setdefault("probe_interval_ms", 100.0)
    kw.setdefault("slo_shedding", False)  # _slo_step driven by hand
    kw.setdefault("down_cooldown_ms", 200.0)
    return FrontDoorConfig.from_env(**kw)


@pytest.fixture(scope="module")
def trees():
    rng = np.random.default_rng(11)
    cap = 1 << TREE_DEPTH
    return [
        rng.integers(0, 256, size=(n, 32)).astype(np.uint8)
        for n in (cap // 2 + 1, cap - 3, cap, 19, 23, 29)
    ]


@pytest.fixture(scope="module")
def bls_items():
    sks = [1, 2, 3]
    pks = [bls.SkToPk(sk) for sk in sks]
    msgs = [bytes([i + 1]) * 32 for i in range(2)]
    items = []
    for i in range(4):
        m = msgs[i % 2]
        sig = bls.Aggregate([bls.Sign(sk, m) for sk in sks])
        if i == 2:
            sig = b"\x01" + bytes(sig)[1:]  # tampered: must verify False
        items.append((pks, m, sig))
    return items


def _direct(trees, bls_items):
    roots = [
        ops_merkle.merkleize_subtree_device(t, buckets.subtree_depth(t.shape[0]))
        for t in trees
    ]
    verdicts = [
        bls_batch.batch_verify_aggregates([(list(map(bytes, p)), m, bytes(s))])
        for p, m, s in bls_items
    ]
    return roots, verdicts


@pytest.fixture(scope="module")
def shared_fd(tmp_path_factory):
    """One fleet for the healthy-path tests: 2 replicas, a shippable
    warmup artifact, and a shared JSONL sink configured BEFORE the fork
    so replica events land in the same stream as the parent's."""
    tmp = tmp_path_factory.mktemp("frontdoor")
    jsonl = tmp / "events.jsonl"
    # spawned replicas configure their JSONL sink from the env at
    # import — what makes the cross-process stitching test possible
    old_jsonl = os.environ.get("ETH_SPECS_OBS_JSONL")
    os.environ["ETH_SPECS_OBS_JSONL"] = str(jsonl)
    warmup = tmp / "warmup.jsonl"
    fd = FrontDoor(
        replicas=2,
        config=_serve_cfg(),
        fd_config=_fd_cfg(),
        warmup_path=str(warmup),
        warm_keys=[("merkle_many", b, TREE_DEPTH) for b in (1, 2, 4)],
        name="fd-test",
    )
    try:
        yield fd, jsonl, warmup
    finally:
        fd.close()
        if old_jsonl is None:
            os.environ.pop("ETH_SPECS_OBS_JSONL", None)
        else:
            os.environ["ETH_SPECS_OBS_JSONL"] = old_jsonl


# ------------------------------------------------------------------ units --


def test_wire_roundtrip_and_corrupt_detection():
    a, b = socket.socketpair()
    try:
        wire.send_frame(a, {"op": "x", "blob": b"\x00" * 1000})
        assert wire.recv_frame(b, timeout_s=5)["op"] == "x"
        # a corrupt-mode rule flips a payload byte after the digest:
        # the receiver must detect it, never deliver it
        with fault.injected("frontdoor.rpc:corrupt"):
            wire.send_frame(a, {"op": "y"})
        before = _counter("frontdoor.corrupt_frames")
        with pytest.raises(wire.CorruptFrame):
            wire.recv_frame(b, timeout_s=5)
        assert _counter("frontdoor.corrupt_frames") == before + 1
        # the stream stays in sync: the next clean frame still parses
        wire.send_frame(a, {"op": "z"})
        assert wire.recv_frame(b, timeout_s=5)["op"] == "z"
    finally:
        a.close()
        b.close()


def test_admission_retry_after_accounts_for_queue_depth():
    ctrl = AdmissionController(max_queue=4, max_bytes=1 << 30)
    for _ in range(4):
        ctrl.admit(10)
    with pytest.raises(Overloaded) as exc_info:
        ctrl.admit(10)
    # 4 requests ahead at the (seeded) 10ms EWMA: the hint must scale
    # with the queue ahead, not quote a bare per-request service time
    assert exc_info.value.reason == "queue"
    assert exc_info.value.retry_after_s >= 4 * 0.01 * 0.9
    for _ in range(4):
        ctrl.release(10, service_s=0.01)


def test_admission_retry_after_bytes_reason_scales_with_overshoot():
    ctrl = AdmissionController(max_queue=100, max_bytes=100)
    ctrl.admit(90)
    with pytest.raises(Overloaded) as exc_info:
        ctrl.admit(50)
    assert exc_info.value.reason == "bytes"
    # one release of the (avg 90-byte) in-flight payload frees the
    # overshoot: the hint is ~1 release, not the 1-deep queue times x
    assert 0 < exc_info.value.retry_after_s < 1.0
    ctrl.release(90, service_s=0.005)


def test_admission_retry_after_floors_at_stall_age():
    ctrl = AdmissionController(max_queue=1, max_bytes=1 << 30)
    ctrl.admit(1)
    time.sleep(0.15)  # nothing releases: the service is stalled
    with pytest.raises(Overloaded) as exc_info:
        ctrl.admit(1)
    # EWMA says 10ms — but nothing has released for 150ms, and a hint
    # below the observed stall age is a lie
    assert exc_info.value.retry_after_s >= 0.14
    ctrl.release(1)


def test_admission_resize_gates_new_admissions_only():
    ctrl = AdmissionController(max_queue=8, max_bytes=1 << 30)
    for _ in range(6):
        ctrl.admit(1)
    ctrl.resize(2)  # SLO shed: below current depth — nothing is evicted
    assert ctrl.depth() == 6
    with pytest.raises(Overloaded):
        ctrl.admit(1)
    for _ in range(6):
        ctrl.release(1)
    ctrl.resize(8)
    ctrl.admit(1)
    ctrl.release(1)


def test_router_affinity_backoff_and_draining():
    r = Router(3, down_cooldown_s=0.1)
    key = ("merkle_many", 5)
    home = r.pick(key)
    assert all(r.pick(key) == home for _ in range(5))  # stable affinity
    # a shed's retry_after is HONORED: the home replica is skipped until
    # the backoff elapses, siblings serve meanwhile
    r.note_shed(home, 0.15)
    sibling = r.pick(key)
    assert sibling is not None and sibling != home
    assert r.backoff_remaining_s() > 0
    time.sleep(0.16)
    assert r.pick(key) == home
    # draining replicas take no new work at all
    r.set_draining(home, True)
    assert r.pick(key) != home
    r.set_draining(home, False)
    # a client-OBSERVED "draining" reply expires on its own: a
    # supervisor-less client must not blackhole the replica forever
    r.note_draining(home, ttl_s=0.1)
    assert r.pick(key) != home
    time.sleep(0.12)
    assert r.pick(key) == home
    # a down replica is skipped, then probed half-open after cooldown
    r.mark_down(home)
    assert r.pick(key) != home
    r.note_failure(home)  # failure path: cooldown-gated, not supervisor-gated
    assert r.pick(key) != home
    time.sleep(0.11)
    assert r.pick(key) == home  # one half-open trial
    assert r.pick(key) != home  # next trial gated again
    r.mark_up(home)
    assert r.pick(key) == home


def test_all_replicas_shedding_propagates_typed_overloaded():
    client = FrontDoorClient(
        ["127.0.0.1:9", "127.0.0.1:10"], config=_serve_cfg(), fd_config=_fd_cfg()
    )
    client._rpc_submit = lambda idx, req, hedge: {
        "ok": False, "err": "overloaded", "reason": "queue", "retry_after_s": 0.07,
    }
    fut = client.submit_hash_tree_root(np.zeros((4, 32), np.uint8))
    with pytest.raises(Overloaded) as exc_info:
        fut.result(timeout=30)
    # flow control propagates typed, with the smallest honest hint —
    # absorbing an overload on the host oracle would defeat backpressure
    assert exc_info.value.retry_after_s == pytest.approx(0.07)
    assert client.admission.depth() == 0  # the slot released exactly once
    client.close()


def test_host_oracle_is_the_last_rung(trees, bls_items):
    """No replica listening at all: every submit still resolves,
    bit-identical, via the front door's own host oracle."""
    direct_roots, direct_verdicts = _direct(trees[:2], bls_items[:2])
    degraded_before = _counter("frontdoor.degraded_to_host")
    client = FrontDoorClient(
        ["127.0.0.1:9"], config=_serve_cfg(), fd_config=_fd_cfg()
    )
    roots = [client.submit_hash_tree_root(t).result(timeout=60) for t in trees[:2]]
    verdicts = [
        client.submit_bls_aggregate(*it).result(timeout=60) for it in bls_items[:2]
    ]
    client.close()
    assert roots == direct_roots
    assert verdicts == direct_verdicts
    assert _counter("frontdoor.degraded_to_host") - degraded_before == 4
    assert client.admission.depth() == 0


# ------------------------------------------------------------ healthy path --


def test_parity_bit_identical_through_replicas(shared_fd, trees, bls_items):
    fd, _, _ = shared_fd
    direct_roots, direct_verdicts = _direct(trees, bls_items)
    degraded_before = _counter("frontdoor.degraded_to_host")
    rfuts = [fd.submit_hash_tree_root(t) for t in trees]
    bfuts = [fd.submit_bls_aggregate(*it) for it in bls_items]
    assert [f.result(timeout=60) for f in rfuts] == direct_roots
    assert [f.result(timeout=60) for f in bfuts] == direct_verdicts
    # served by the fleet, not by the fallback rung
    assert _counter("frontdoor.degraded_to_host") == degraded_before
    assert _counter("frontdoor.route.affinity") > 0


def test_warmup_artifact_zero_cold_compiles_on_consumers(shared_fd, trees):
    """The artifact is the shippable warmup: replica 0 wrote it, every
    other replica replayed it at boot — traffic then causes ZERO cold
    compiles on any replica."""
    fd, _, warmup = shared_fd
    for t in trees:
        fd.submit_hash_tree_root(t).result(timeout=60)
    keys = {tuple(k) for k in buckets.load_warmup(str(warmup))}
    assert {("merkle_many", b, TREE_DEPTH) for b in (1, 2, 4)} <= keys
    deadline = time.monotonic() + 10
    stats = fd.replica_stats()
    while (
        any(s is None for s in stats) and time.monotonic() < deadline
    ):  # wait for one probe round
        time.sleep(0.1)
        stats = fd.replica_stats()
    assert all(s is not None for s in stats), stats
    for s in stats:
        assert s["compiles_after_ready"] == 0, stats


def test_trace_stitches_across_the_process_boundary(shared_fd, trees):
    """A submit under an active trace context reaches the replica with
    the same trace_id: its frontdoor.rpc span — in the replica's own
    sibling stream next to the configured parent sink (obs/timeline.py
    fleet layout) — is a child of the caller's trace."""
    fd, jsonl, _ = shared_fd
    ctx = trace.new_trace()
    with trace.activate(ctx):
        fd.submit_hash_tree_root(trees[0]).result(timeout=60)
    deadline = time.monotonic() + 10
    spans = []
    while not spans and time.monotonic() < deadline:
        time.sleep(0.1)
        lines = timeline.load_fleet(str(jsonl))
        spans = [
            e
            for e in lines
            if e.get("name") == "frontdoor.rpc" and e.get("trace_id") == ctx.trace_id
        ]
    assert spans, "no replica-side span carried the caller's trace id"
    boot_events = [e for e in lines if e.get("kind") == "frontdoor.replica_ready"]
    assert boot_events, "replica boot events missing from the fleet streams"


def test_corrupt_request_frame_detected_counted_retried(shared_fd, trees):
    """frontdoor.rpc:corrupt on the client's next submit frame: the
    replica detects the digest mismatch, answers typed, the client
    resends — the result is still bit-identical, never silent garbage."""
    fd, _, _ = shared_fd
    direct = ops_merkle.merkleize_subtree_device(
        trees[3], buckets.subtree_depth(trees[3].shape[0])
    )
    retries_before = _counter("frontdoor.corrupt_retries")
    with fault.injected("frontdoor.rpc:corrupt"):
        root = fd.submit_hash_tree_root(trees[3]).result(timeout=60)
    assert root == direct
    assert _counter("frontdoor.corrupt_retries") - retries_before >= 1


def test_router_backoff_honored_before_rerouting(shared_fd, trees):
    """Both replicas shedding (simulated backoff): the dispatcher waits
    out the soonest retry_after instead of hammering, then serves."""
    fd, _, _ = shared_fd
    direct = ops_merkle.merkleize_subtree_device(
        trees[4], buckets.subtree_depth(trees[4].shape[0])
    )
    fd.router.note_shed(0, 0.3)
    fd.router.note_shed(1, 0.3)
    t0 = time.monotonic()
    assert fd.submit_hash_tree_root(trees[4]).result(timeout=60) == direct
    assert time.monotonic() - t0 >= 0.25


def test_slo_breach_shrinks_admission_and_recovers(shared_fd, monkeypatch):
    """SLO breaches drive shedding: a breached probe window halves the
    effective admission cap; clean windows grow it back to the ceiling."""
    fd, _, _ = shared_fd
    monkeypatch.setenv("ETH_SPECS_SLO_WAIT_P99_MS", "5")
    base = fd._base_max_queue
    fd._slo_shipper.delta()  # start a fresh window
    for _ in range(20):
        obs.observe("serve.wait_ms", 50.0)  # way past the 5ms objective
    fd._slo_step(shed=True)  # hand-driven: config shedding stays off
    shrunk = fd.admission.max_queue
    assert shrunk == base // 2
    assert _counter("frontdoor.slo_sheds") >= 1
    # clean windows: additive recovery back to the configured ceiling
    for _ in range(30):
        fd._slo_step(shed=True)
        if fd.admission.max_queue == base:
            break
    assert fd.admission.max_queue == base


def test_drain_on_restart_zero_shed(shared_fd, trees):
    """Planned rollover under continuous traffic: no request is shed,
    no request fails, every result stays bit-identical."""
    fd, _, _ = shared_fd
    direct = [
        ops_merkle.merkleize_subtree_device(t, buckets.subtree_depth(t.shape[0]))
        for t in trees
    ]
    rejected_before = _counter("serve.rejected")
    stop = threading.Event()
    errors: list = []
    done = [0]

    def submitter():
        i = 0
        while not stop.is_set():
            idx = i % len(trees)
            try:
                got = fd.submit_hash_tree_root(trees[idx]).result(timeout=60)
                if got != direct[idx]:
                    errors.append(f"mismatch at {i}")
            except Exception as exc:  # noqa: BLE001
                errors.append(repr(exc))
            i += 1
            done[0] = i

    t = threading.Thread(target=submitter, daemon=True)
    t.start()
    time.sleep(0.3)
    fd.restart_replica(0, timeout_s=5)
    time.sleep(0.3)
    stop.set()
    t.join(timeout=60)
    assert not errors, errors[:3]
    assert done[0] > 0
    assert _counter("serve.rejected") == rejected_before
    assert _counter("frontdoor.planned_restarts") >= 1


# ------------------------------------------------------------ chaos paths --


def test_replica_sigkill_mid_batch_every_future_resolves(
    tmp_path, monkeypatch, trees
):
    """frontdoor.rpc:kill on a replica's 3rd request, a burst in flight:
    every future (including the ones mid-batch on the killed replica)
    resolves bit-identically via failover; the supervisor respawns the
    replica and the parent leaves a postmortem bundle for it."""
    pm_dir = tmp_path / "postmortems"
    monkeypatch.setenv("ETH_SPECS_OBS_POSTMORTEM_DIR", str(pm_dir))
    replaced_before = _counter("frontdoor.replicas_replaced")
    payloads = [trees[i % len(trees)] for i in range(10)]
    direct = [
        ops_merkle.merkleize_subtree_device(t, buckets.subtree_depth(t.shape[0]))
        for t in payloads
    ]
    fd = FrontDoor(
        replicas=2,
        config=_serve_cfg(),
        fd_config=_fd_cfg(),
        replica_fault_spec=(
            f"frontdoor.rpc:kill:nth=3:latch={tmp_path / 'kill.latch'}"
        ),
        name="fd-kill",
    )
    try:
        futs = [fd.submit_hash_tree_root(t) for t in payloads]
        got = [f.result(timeout=120) for f in futs]
        assert got == direct  # zero lost, bit-identical through the kill
        deadline = time.monotonic() + 15
        while (
            _counter("frontdoor.replicas_replaced") == replaced_before
            and time.monotonic() < deadline
        ):
            time.sleep(0.1)
        assert _counter("frontdoor.replicas_replaced") > replaced_before
        # the replacement serves traffic again (routed, not host oracle)
        assert fd.submit_hash_tree_root(payloads[0]).result(timeout=60) == direct[0]
    finally:
        fd.close()
    bundles = sorted(pm_dir.glob("postmortem-*.json")) if pm_dir.exists() else []
    lost = [
        b for b in bundles if json.load(open(b))["trigger"] == "frontdoor.replica_lost"
    ]
    assert lost, f"no replica_lost postmortem bundle in {bundles}"
    assert fd.admission.depth() == 0


def test_hedged_failover_one_result_wins_no_double_release(tmp_path, trees):
    """One replica stalls past the hedge deadline (exactly once, latch):
    the hedge re-dispatches to the sibling, the first result wins, the
    late duplicate is suppressed, and the admission slot releases
    exactly once."""
    hedges_before = _counter("frontdoor.hedges")
    wins_before = _counter("frontdoor.hedge_wins")
    dup_before = _counter("frontdoor.duplicates_suppressed")
    stall_s = 2.0
    fd = FrontDoor(
        replicas=2,
        config=_serve_cfg(),
        fd_config=_fd_cfg(hedge_ms=120.0),
        replica_fault_spec=(
            f"frontdoor.rpc:stall:delay={stall_s}:latch={tmp_path / 'stall.latch'}"
        ),
        name="fd-hedge",
    )
    try:
        direct = ops_merkle.merkleize_subtree_device(
            trees[0], buckets.subtree_depth(trees[0].shape[0])
        )
        t0 = time.monotonic()
        got = fd.submit_hash_tree_root(trees[0]).result(timeout=60)
        elapsed = time.monotonic() - t0
        assert got == direct
        # the hedge beat the stall: well under the stall duration
        assert elapsed < stall_s, f"hedge never rescued the request ({elapsed:.2f}s)"
        assert _counter("frontdoor.hedges") > hedges_before
        assert _counter("frontdoor.hedge_wins") > wins_before
        # wait for the stalled primary's late reply: suppressed, slot
        # NOT double-released
        deadline = time.monotonic() + stall_s + 3
        while (
            _counter("frontdoor.duplicates_suppressed") == dup_before
            and time.monotonic() < deadline
        ):
            time.sleep(0.1)
        assert _counter("frontdoor.duplicates_suppressed") > dup_before
        assert fd.admission.depth() == 0
    finally:
        fd.close()


def test_gen_worker_routing_through_frontdoor(shared_fd, bls_items, monkeypatch):
    """The gen-pool client mode: ETH_SPECS_SERVE_FRONTDOOR set, a
    FrontDoorClient installs as the routed verifier and
    utils/bls.FastAggregateVerify crosses the process boundary."""
    fd, _, _ = shared_fd
    monkeypatch.setenv("ETH_SPECS_SERVE_FRONTDOOR", ",".join(fd.addresses()))
    pks, msg, sig = bls_items[0]
    direct = bls.FastAggregateVerify(pks, msg, sig)
    before = _counter("frontdoor.requests.bls")
    client = serve.maybe_frontdoor_client(name="fd-worker-test")
    assert client is not None
    serve.install_routing(client)
    try:
        assert bls.FastAggregateVerify(pks, msg, sig) == direct
        assert bls.FastAggregateVerify(*bls_items[2]) is False  # tampered
    finally:
        serve.uninstall_routing()
        client.close()
    assert _counter("frontdoor.requests.bls") - before == 2
