"""BLS signature-suite edge tables (reference analogue: the bls vector
runner's edge classes — infinity points, empty aggregates, tampered
encodings; reference utils/bls.py surface + IETF BLS test-vector
conventions)."""

import pytest

from eth_consensus_specs_tpu.crypto import signature as sig
from eth_consensus_specs_tpu.utils import bls

MSG = b"\x21" * 32


@pytest.fixture(autouse=True)
def _bls_on():
    prev = bls.bls_active
    bls.bls_active = True
    yield
    bls.bls_active = prev


def test_verify_rejects_infinity_pubkey():
    # the point-at-infinity pubkey must NEVER verify (KeyValidate)
    inf_pk = b"\xc0" + b"\x00" * 47
    s = bls.Sign(1, MSG)
    assert not bls.Verify(inf_pk, MSG, s)


def test_verify_rejects_infinity_signature_for_real_key():
    pk = sig.sk_to_pk(7)
    inf_sig = b"\xc0" + b"\x00" * 95
    assert not bls.Verify(pk, MSG, inf_sig)


def test_aggregate_empty_list_raises_or_none():
    with pytest.raises(Exception):
        bls.Aggregate([])


def test_aggregate_single_is_identity():
    s = bls.Sign(5, MSG)
    assert bytes(bls.Aggregate([s])) == bytes(s)


def test_aggregate_order_independent():
    s1, s2, s3 = (bls.Sign(k, MSG) for k in (5, 6, 7))
    a = bytes(bls.Aggregate([s1, s2, s3]))
    b = bytes(bls.Aggregate([s3, s1, s2]))
    assert a == b


def test_fast_aggregate_verify_empty_pubkeys_false():
    s = bls.Sign(5, MSG)
    assert not bls.FastAggregateVerify([], MSG, s)


def test_aggregate_verify_distinct_messages():
    msgs = [bytes([i]) * 32 for i in range(3)]
    keys = [11, 12, 13]
    sigs = [bls.Sign(k, m) for k, m in zip(keys, msgs)]
    pks = [sig.sk_to_pk(k) for k in keys]
    agg = bls.Aggregate(sigs)
    assert bls.AggregateVerify(pks, msgs, agg)
    # swapped message order must fail
    assert not bls.AggregateVerify(pks, list(reversed(msgs)), agg)


def test_verify_rejects_bad_pubkey_encoding():
    bad_pk = b"\xff" * 48  # not a valid compressed point
    s = bls.Sign(1, MSG)
    assert not bls.Verify(bad_pk, MSG, s)


def test_verify_rejects_bad_signature_encoding():
    pk = sig.sk_to_pk(1)
    assert not bls.Verify(pk, MSG, b"\xff" * 96)


def test_verify_rejects_non_subgroup_signature():
    """A 96-byte encoding of a curve point OUTSIDE the r-order subgroup
    must be rejected by subgroup validation."""
    from eth_consensus_specs_tpu.crypto.curve import g2_to_bytes
    from eth_consensus_specs_tpu.crypto import curve as c
    from eth_consensus_specs_tpu.crypto.fields import Fq, Fq2

    # find a point on the twist not in the subgroup: take a random x and
    # solve; cofactor != 1 makes non-subgroup points overwhelming
    from eth_consensus_specs_tpu.crypto.curve import Point

    x = Fq2(Fq(3), Fq(1))
    pt = None
    for _ in range(64):
        rhs = x * x * x + c.B2
        y = rhs.sqrt()
        if y is not None:
            cand = Point(x, y, c.B2)
            if not c.in_subgroup(cand):
                pt = cand
                break
        x = Fq2(x.c0 + Fq(1), x.c1)
    if pt is None:
        pytest.skip("no non-subgroup point found in the probe window")
    enc = g2_to_bytes(pt)
    pk = sig.sk_to_pk(1)
    assert not bls.Verify(pk, MSG, enc)


def test_sign_deterministic():
    assert bytes(bls.Sign(42, MSG)) == bytes(bls.Sign(42, MSG))


def test_eth_fast_aggregate_verify_infinity_with_empty_set():
    """altair's eth_fast_aggregate_verify accepts the G2 infinity
    signature for an EMPTY pubkey set (unlike the IETF base suite)."""
    from eth_consensus_specs_tpu.forks import get_spec

    spec = get_spec("altair", "minimal")
    inf_sig = b"\xc0" + b"\x00" * 95
    assert spec.eth_fast_aggregate_verify([], MSG, inf_sig)
    assert not bls.FastAggregateVerify([], MSG, inf_sig)
