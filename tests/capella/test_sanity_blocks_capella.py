"""Capella whole-block sanity: BLS-to-execution changes and withdrawals
interacting with other operations inside full blocks (reference analogue:
eth2spec/test/capella/sanity/test_blocks.py; spec:
specs/capella/beacon-chain.md process_withdrawals +
process_bls_to_execution_change inside process_operations)."""

from eth_consensus_specs_tpu.ssz.hashing import hash_bytes as sha256
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.keys import privkeys, pubkeys
from eth_consensus_specs_tpu.test_infra.state import next_epoch, transition_to
from eth_consensus_specs_tpu.test_infra.sync_committee import committee_indices
from eth_consensus_specs_tpu.test_infra.voluntary_exits import sign_voluntary_exit
from eth_consensus_specs_tpu.test_infra.withdrawals import (
    set_validator_fully_withdrawable,
    set_validator_partially_withdrawable,
)
from eth_consensus_specs_tpu.utils import bls

# the BTEC/withdrawal block mechanics are capella-born and carry through
# the execution era (electra's pending-queue variants have their own suite)
CAPELLA_ON = ["capella", "deneb", "electra"]

TO_ADDRESS = b"\x59" * 20


def _non_sync_committee_index(spec, state) -> int:
    """A validator outside the current sync committee: empty blocks carry a
    zero-participation sync aggregate, which penalizes committee members
    and would perturb exact balance assertions."""
    members = {int(i) for i in committee_indices(spec, state)}
    return next(i for i in range(len(state.validators)) if i not in members)


def _set_bls_creds(spec, state, index: int):
    state.validators[index].withdrawal_credentials = (
        spec.BLS_WITHDRAWAL_PREFIX + sha256(bytes(pubkeys[index]))[1:]
    )


def _signed_change(spec, state, index: int, to_address: bytes = TO_ADDRESS):
    change = spec.BLSToExecutionChange(
        validator_index=index,
        from_bls_pubkey=pubkeys[index],
        to_execution_address=to_address,
    )
    domain = spec.compute_domain(
        spec.DOMAIN_BLS_TO_EXECUTION_CHANGE,
        genesis_validators_root=state.genesis_validators_root,
    )
    return spec.SignedBLSToExecutionChange(
        message=change,
        signature=bls.Sign(privkeys[index], spec.compute_signing_root(change, domain)),
    )


def _apply_block(spec, state, mutate, expect_fail=False):
    block = build_empty_block_for_next_slot(spec, state)
    mutate(block)
    return state_transition_and_sign_block(spec, state, block, expect_fail=expect_fail)


# == BTEC in blocks ========================================================


@with_phases(CAPELLA_ON)
@spec_state_test
def test_block_bls_change(spec, state):
    index = 1
    _set_bls_creds(spec, state, index)
    signed_change = _signed_change(spec, state, index)
    _apply_block(spec, state, lambda b: b.body.bls_to_execution_changes.append(signed_change))
    creds = bytes(state.validators[index].withdrawal_credentials)
    assert creds[:1] == bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX)
    assert creds[12:] == TO_ADDRESS


@with_phases(CAPELLA_ON)
@spec_state_test
def test_block_exit_and_bls_change_same_block(spec, state):
    """A voluntary exit and a credential change for the same validator in
    one block: both apply."""
    index = 1
    _set_bls_creds(spec, state, index)
    transition_to(
        spec,
        state,
        int(spec.config.SHARD_COMMITTEE_PERIOD) * int(spec.SLOTS_PER_EPOCH),
    )
    signed_change = _signed_change(spec, state, index)
    exit_msg = spec.VoluntaryExit(
        epoch=spec.get_current_epoch(state), validator_index=index
    )
    signed_exit = sign_voluntary_exit(spec, state, exit_msg, privkeys[index])

    def mutate(b):
        b.body.voluntary_exits.append(signed_exit)
        b.body.bls_to_execution_changes.append(signed_change)

    _apply_block(spec, state, mutate)
    assert state.validators[index].exit_epoch != spec.FAR_FUTURE_EPOCH
    creds = bytes(state.validators[index].withdrawal_credentials)
    assert creds[:1] == bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX)


@with_phases(CAPELLA_ON)
@spec_state_test
def test_block_invalid_duplicate_bls_changes(spec, state):
    """The same change twice in one block: second application fails (creds
    already rotated)."""
    index = 1
    _set_bls_creds(spec, state, index)
    signed_change = _signed_change(spec, state, index)

    def mutate(b):
        b.body.bls_to_execution_changes.append(signed_change)
        b.body.bls_to_execution_changes.append(signed_change.copy())

    _apply_block(spec, state, mutate, expect_fail=True)


@with_phases(CAPELLA_ON)
@spec_state_test
def test_block_invalid_two_changes_different_addresses(spec, state):
    """Two changes for one validator to different addresses in one block:
    the second must fail against the already-rotated credential."""
    index = 1
    _set_bls_creds(spec, state, index)
    change_a = _signed_change(spec, state, index, to_address=b"\x11" * 20)
    change_b = _signed_change(spec, state, index, to_address=b"\x22" * 20)

    def mutate(b):
        b.body.bls_to_execution_changes.append(change_a)
        b.body.bls_to_execution_changes.append(change_b)

    _apply_block(spec, state, mutate, expect_fail=True)


# == withdrawals at the epoch boundary =====================================


@with_phases(CAPELLA_ON)
@spec_state_test
def test_full_withdrawal_in_epoch_transition(spec, state):
    """A fully-withdrawable validator is swept by the first block of the
    next epoch; its balance zeroes."""
    index = 0
    set_validator_fully_withdrawable(spec, state, index)
    assert int(state.balances[index]) > 0

    transition_to(
        spec, state, int(state.slot) + int(spec.SLOTS_PER_EPOCH) - 1
    )
    _apply_block(spec, state, lambda b: None)
    assert int(state.balances[index]) == 0


@with_phases(CAPELLA_ON)
@spec_state_test
def test_partial_withdrawal_in_epoch_transition(spec, state):
    """An over-cap validator sheds exactly the excess in the sweep."""
    index = _non_sync_committee_index(spec, state)
    excess = 1_000_000_000
    set_validator_partially_withdrawable(spec, state, index, excess_balance=excess)
    cap = int(state.validators[index].effective_balance)

    _apply_block(spec, state, lambda b: None)
    # swept down to the max effective balance for its credential type
    assert int(state.balances[index]) == cap


@with_phases(CAPELLA_ON)
@spec_state_test
def test_withdrawals_across_two_blocks(spec, state):
    """The withdrawal index advances monotonically across consecutive
    blocks sweeping different validators."""
    set_validator_partially_withdrawable(spec, state, 0)
    set_validator_partially_withdrawable(spec, state, 1)
    start_index = int(state.next_withdrawal_index)
    _apply_block(spec, state, lambda b: None)
    mid_index = int(state.next_withdrawal_index)
    _apply_block(spec, state, lambda b: None)
    end_index = int(state.next_withdrawal_index)
    assert start_index < mid_index <= end_index


@with_phases(CAPELLA_ON)
@spec_state_test
def test_bls_change_then_swept_next_epoch(spec, state):
    """A validator whose creds rotate via BTEC becomes sweepable: rotate,
    make it over-cap, and the next epoch's block withdraws the excess."""
    index = _non_sync_committee_index(spec, state)
    _set_bls_creds(spec, state, index)
    signed_change = _signed_change(spec, state, index)
    _apply_block(spec, state, lambda b: b.body.bls_to_execution_changes.append(signed_change))

    cap = int(state.validators[index].effective_balance)
    state.balances[index] = cap + 777_000_000
    next_epoch(spec, state)
    # the target may have rotated INTO the new epoch's committee
    if index in {int(i) for i in committee_indices(spec, state)}:
        return
    # aim the bounded sweep window (MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)
    # at the target so one block suffices
    state.next_withdrawal_validator_index = index
    _apply_block(spec, state, lambda b: None)
    assert int(state.balances[index]) == cap


@with_phases(CAPELLA_ON)
@spec_state_test
def test_historical_summary_accumulates(spec, state):
    """Crossing a SLOTS_PER_HISTORICAL_ROOT boundary appends a historical
    summary (capella's replacement for historical roots)."""
    period = int(spec.SLOTS_PER_HISTORICAL_ROOT)
    before = len(state.historical_summaries)
    transition_to(spec, state, period)
    assert len(state.historical_summaries) == before + 1
