"""bellatrix -> capella state upgrade + historical summaries
(spec: specs/capella/fork.md, beacon-chain.md:307-319)."""

from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.state import next_epoch, transition_to


@with_phases(["bellatrix"])
@spec_state_test
def test_upgrade_to_capella_basic(spec, state):
    cap = get_spec("capella", spec.preset_name)
    next_epoch(spec, state)
    post = cap.upgrade_from_parent(state)
    assert bytes(post.fork.current_version) == bytes(cap.config.CAPELLA_FORK_VERSION)
    assert int(post.next_withdrawal_index) == 0
    assert int(post.next_withdrawal_validator_index) == 0
    assert len(post.historical_summaries) == 0
    # header carries over with a zero withdrawals_root appended
    assert (
        post.latest_execution_payload_header.block_hash
        == state.latest_execution_payload_header.block_hash
    )
    next_epoch(cap, post)


@with_phases(["capella"])
@spec_state_test
def test_historical_summaries_accumulate(spec, state):
    period_epochs = spec.SLOTS_PER_HISTORICAL_ROOT // spec.SLOTS_PER_EPOCH
    # advance to the epoch whose transition appends the first summary
    target_slot = period_epochs * spec.SLOTS_PER_EPOCH
    transition_to(spec, state, target_slot)
    assert len(state.historical_summaries) == 1
    assert len(state.historical_roots) == 0
    # summary root is HistoricalBatch-compatible by construction
    batch = spec.HistoricalBatch(
        block_roots=state.block_roots, state_roots=state.state_roots
    )
    # roots snapshotted at the boundary differ now; only shape is asserted
    assert len(bytes(hash_tree_root(state.historical_summaries[0]))) == 32
