"""Withdrawals processing (reference analogue:
test/capella/block_processing/test_process_withdrawals.py)."""

from eth_consensus_specs_tpu.test_infra.context import (
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.execution_payload import (
    build_empty_execution_payload,
)
from eth_consensus_specs_tpu.test_infra.state import next_slot

ETH1_ADDRESS = b"\x42" * 20


def set_eth1_credentials(spec, state, index: int) -> None:
    state.validators[index].withdrawal_credentials = (
        bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX) + b"\x00" * 11 + ETH1_ADDRESS
    )


def prepare_partial_withdrawal(spec, state, index: int, excess: int = 10**9) -> None:
    set_eth1_credentials(spec, state, index)
    state.balances[index] = spec.MAX_EFFECTIVE_BALANCE + excess
    state.validators[index].effective_balance = spec.MAX_EFFECTIVE_BALANCE


def prepare_full_withdrawal(spec, state, index: int) -> None:
    set_eth1_credentials(spec, state, index)
    state.validators[index].withdrawable_epoch = spec.get_current_epoch(state)
    state.validators[index].exit_epoch = spec.get_current_epoch(state)


def run_withdrawals_processing(spec, state, payload, valid=True):
    yield "pre", state
    yield "execution_payload", payload
    if not valid:
        expect_assertion_error(lambda: spec.process_withdrawals(state, payload))
        yield "post", None
        return
    spec.process_withdrawals(state, payload)
    yield "post", state


@with_phases(["capella"])
@spec_state_test
def test_withdrawals_none_expected(spec, state):
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == 0
    yield from run_withdrawals_processing(spec, state, payload)
    # partial sweep: index jumps by the sweep window
    assert int(state.next_withdrawal_validator_index) == (
        spec.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP % len(state.validators)
    )


@with_phases(["capella"])
@spec_state_test
def test_withdrawals_partial(spec, state):
    next_slot(spec, state)
    prepare_partial_withdrawal(spec, state, 1, excess=7 * 10**8)
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == 1
    assert int(payload.withdrawals[0].amount) == 7 * 10**8
    pre_balance = int(state.balances[1])
    yield from run_withdrawals_processing(spec, state, payload)
    assert int(state.balances[1]) == pre_balance - 7 * 10**8
    assert int(state.next_withdrawal_index) == 1


@with_phases(["capella"])
@spec_state_test
def test_withdrawals_full(spec, state):
    next_slot(spec, state)
    prepare_full_withdrawal(spec, state, 2)
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == 1
    assert int(payload.withdrawals[0].amount) == int(state.balances[2])
    yield from run_withdrawals_processing(spec, state, payload)
    assert int(state.balances[2]) == 0


@with_phases(["capella"])
@spec_state_test
def test_withdrawals_full_payload_advances_sweep(spec, state):
    next_slot(spec, state)
    for i in range(spec.MAX_WITHDRAWALS_PER_PAYLOAD + 2):
        prepare_partial_withdrawal(spec, state, i)
    payload = build_empty_execution_payload(spec, state)
    assert len(payload.withdrawals) == spec.MAX_WITHDRAWALS_PER_PAYLOAD
    yield from run_withdrawals_processing(spec, state, payload)
    # full payload: sweep resumes after the last paid validator
    last_paid = int(payload.withdrawals[-1].validator_index)
    assert int(state.next_withdrawal_validator_index) == (last_paid + 1) % len(
        state.validators
    )


@with_phases(["capella"])
@spec_state_test
def test_withdrawals_invalid_missing(spec, state):
    next_slot(spec, state)
    prepare_partial_withdrawal(spec, state, 1)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals = []
    yield from run_withdrawals_processing(spec, state, payload, valid=False)


@with_phases(["capella"])
@spec_state_test
def test_withdrawals_invalid_wrong_amount(spec, state):
    next_slot(spec, state)
    prepare_partial_withdrawal(spec, state, 1)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals[0].amount = int(payload.withdrawals[0].amount) + 1
    yield from run_withdrawals_processing(spec, state, payload, valid=False)
