"""Withdrawal-sweep tables (spec: specs/capella/beacon-chain.md
get_expected_withdrawals/process_withdrawals; reference analogue:
test/capella/block_processing/test_process_withdrawals.py)."""

from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.execution_payload import (
    build_empty_execution_payload,
)
from eth_consensus_specs_tpu.test_infra.forks import is_post_electra

CAPELLA_PLUS = ["capella", "deneb", "electra"]


def _eth1_creds(spec, state, index: int, tag: int = 0x51):
    address = bytes([tag]) * 20
    state.validators[index].withdrawal_credentials = (
        spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11 + address
    )
    return address


def _withdrawals_of(spec, state):
    w = spec.get_expected_withdrawals(state)
    return w[0] if isinstance(w, tuple) else w


@with_phases(CAPELLA_PLUS)
@spec_state_test
def test_no_withdrawals_without_execution_creds(spec, state):
    assert list(_withdrawals_of(spec, state)) == []


@with_phases(CAPELLA_PLUS)
@spec_state_test
def test_full_withdrawal_when_withdrawable(spec, state):
    idx = 2
    _eth1_creds(spec, state, idx)
    state.validators[idx].withdrawable_epoch = spec.get_current_epoch(state)
    ws = _withdrawals_of(spec, state)
    assert [int(w.validator_index) for w in ws] == [idx]
    assert int(ws[0].amount) == int(state.balances[idx])


@with_phases(CAPELLA_PLUS)
@spec_state_test
def test_partial_withdrawal_above_max(spec, state):
    idx = 3
    _eth1_creds(spec, state, idx)
    excess = 7 * 10**9
    state.balances[idx] = int(spec.MAX_EFFECTIVE_BALANCE) + excess
    ws = _withdrawals_of(spec, state)
    assert [int(w.validator_index) for w in ws] == [idx]
    assert int(ws[0].amount) == excess


@with_phases(CAPELLA_PLUS)
@spec_state_test
def test_withdrawal_address_comes_from_credentials(spec, state):
    idx = 4
    address = _eth1_creds(spec, state, idx, tag=0x77)
    state.validators[idx].withdrawable_epoch = spec.get_current_epoch(state)
    ws = _withdrawals_of(spec, state)
    assert bytes(ws[0].address) == address


@with_phases(CAPELLA_PLUS)
@spec_state_test
def test_withdrawal_indices_are_sequential(spec, state):
    for i, idx in enumerate((2, 3)):
        _eth1_creds(spec, state, idx, tag=0x60 + i)
        state.validators[idx].withdrawable_epoch = spec.get_current_epoch(state)
    ws = _withdrawals_of(spec, state)
    assert len(ws) == 2
    assert int(ws[1].index) == int(ws[0].index) + 1


@with_phases(CAPELLA_PLUS)
@spec_state_test
def test_process_withdrawals_applies_and_advances_sweep(spec, state):
    idx = 5
    _eth1_creds(spec, state, idx)
    state.validators[idx].withdrawable_epoch = spec.get_current_epoch(state)
    pre_balance = int(state.balances[idx])
    payload = build_empty_execution_payload(spec, state)
    spec.process_withdrawals(state, payload)
    assert int(state.balances[idx]) == 0 or int(state.balances[idx]) < pre_balance
    assert int(state.next_withdrawal_index) >= 1


@with_phases(CAPELLA_PLUS)
@spec_state_test
def test_process_withdrawals_rejects_mismatched_list(spec, state):
    from eth_consensus_specs_tpu.test_infra.context import expect_assertion_error

    idx = 5
    _eth1_creds(spec, state, idx)
    state.validators[idx].withdrawable_epoch = spec.get_current_epoch(state)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals = type(payload.withdrawals)([])  # drop the expected one
    expect_assertion_error(lambda: spec.process_withdrawals(state, payload))


@with_phases(["electra"])
@spec_state_test
def test_electra_partial_sweep_respects_pending_queue_cap(spec, state):
    """Electra bounds processed pending partial withdrawals per sweep."""
    idx = 6
    _eth1_creds(spec, state, idx)
    state.balances[idx] = int(spec.MAX_EFFECTIVE_BALANCE) + 10**9
    ws = _withdrawals_of(spec, state)
    assert is_post_electra(spec)
    assert len(ws) <= int(spec.MAX_WITHDRAWALS_PER_PAYLOAD)


@with_phases(CAPELLA_PLUS)
@spec_state_test
def test_sweep_bound_limits_scan(spec, state):
    """No more than MAX_WITHDRAWALS_PER_PAYLOAD come out of one sweep."""
    count = int(spec.MAX_WITHDRAWALS_PER_PAYLOAD) + 2
    for k in range(count):
        _eth1_creds(spec, state, k, tag=0x30 + k)
        state.validators[k].withdrawable_epoch = spec.get_current_epoch(state)
    ws = _withdrawals_of(spec, state)
    assert len(ws) == int(spec.MAX_WITHDRAWALS_PER_PAYLOAD)
