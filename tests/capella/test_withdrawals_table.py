"""Dense withdrawal-sweep and BLS-to-execution-change tables, capella+
(reference analogue: test/capella/block_processing/test_process_withdrawals.py
~40 variants and test_process_bls_to_execution_change.py)."""

from eth_consensus_specs_tpu.ssz.hashing import hash_bytes
from eth_consensus_specs_tpu.test_infra.context import (
    always_bls,
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.execution_payload import (
    build_empty_execution_payload,
    compute_el_block_hash,
)
from eth_consensus_specs_tpu.test_infra.keys import privkeys, pubkeys
from eth_consensus_specs_tpu.test_infra.state import next_slot
from eth_consensus_specs_tpu.utils import bls

CAPELLA_FORKS = ["capella", "deneb"]


def _eth1_credentials(spec, state, idx: int, address: bytes = b"\x42" * 20):
    state.validators[idx].withdrawal_credentials = (
        bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX) + b"\x00" * 11 + address
    )


def _fully_withdrawable(spec, state, idx: int):
    _eth1_credentials(spec, state, idx)
    state.validators[idx].withdrawable_epoch = spec.get_current_epoch(state)


def _partially_withdrawable(spec, state, idx: int):
    _eth1_credentials(spec, state, idx)
    state.balances[idx] = int(spec.MAX_EFFECTIVE_BALANCE) + 1_000_000
    state.validators[idx].effective_balance = spec.MAX_EFFECTIVE_BALANCE


def _apply_expected(spec, state):
    next_slot(spec, state)
    # build_empty_execution_payload already carries the expected sweep
    payload = build_empty_execution_payload(spec, state)
    return payload, list(payload.withdrawals)


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_sweep_full_withdrawal_zeroes_balance(spec, state):
    _fully_withdrawable(spec, state, 1)
    payload, expected = _apply_expected(spec, state)
    assert len(expected) == 1
    spec.process_withdrawals(state, payload)
    assert int(state.balances[1]) == 0


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_sweep_partial_withdrawal_to_max_effective(spec, state):
    _partially_withdrawable(spec, state, 2)
    payload, expected = _apply_expected(spec, state)
    assert len(expected) == 1
    spec.process_withdrawals(state, payload)
    assert int(state.balances[2]) == int(spec.MAX_EFFECTIVE_BALANCE)


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_sweep_mixed_full_and_partial(spec, state):
    _fully_withdrawable(spec, state, 1)
    _partially_withdrawable(spec, state, 2)
    payload, expected = _apply_expected(spec, state)
    assert len(expected) == 2
    spec.process_withdrawals(state, payload)
    assert int(state.next_withdrawal_index) == 2


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_sweep_advances_validator_cursor(spec, state):
    _fully_withdrawable(spec, state, 3)
    payload, expected = _apply_expected(spec, state)
    pre_cursor = int(state.next_withdrawal_validator_index)
    spec.process_withdrawals(state, payload)
    assert int(state.next_withdrawal_validator_index) != pre_cursor


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_invalid_extra_withdrawal_in_payload(spec, state):
    payload, expected = _apply_expected(spec, state)
    payload.withdrawals.append(
        spec.Withdrawal(index=99, validator_index=0, address=b"\x01" * 20, amount=1)
    )
    expect_assertion_error(lambda: spec.process_withdrawals(state, payload))


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_invalid_withdrawal_wrong_validator_index(spec, state):
    _fully_withdrawable(spec, state, 1)
    payload, expected = _apply_expected(spec, state)
    payload.withdrawals[0].validator_index = 7
    expect_assertion_error(lambda: spec.process_withdrawals(state, payload))


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_invalid_withdrawal_wrong_address(spec, state):
    _fully_withdrawable(spec, state, 1)
    payload, expected = _apply_expected(spec, state)
    payload.withdrawals[0].address = b"\x99" * 20
    expect_assertion_error(lambda: spec.process_withdrawals(state, payload))


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_invalid_withdrawal_wrong_index_counter(spec, state):
    _fully_withdrawable(spec, state, 1)
    payload, expected = _apply_expected(spec, state)
    payload.withdrawals[0].index = int(payload.withdrawals[0].index) + 1
    expect_assertion_error(lambda: spec.process_withdrawals(state, payload))


# == BLS-to-execution change table =========================================


def _signed_change(spec, state, idx: int, from_privkey=None, to_address=b"\x11" * 20):
    from_privkey = privkeys[idx] if from_privkey is None else from_privkey
    from_pubkey = pubkeys[idx] if from_privkey is privkeys[idx] else bls.SkToPk(from_privkey)
    change = spec.BLSToExecutionChange(
        validator_index=idx,
        from_bls_pubkey=from_pubkey,
        to_execution_address=to_address,
    )
    state.validators[idx].withdrawal_credentials = (
        bytes(spec.BLS_WITHDRAWAL_PREFIX) + hash_bytes(bytes(from_pubkey))[1:]
    )
    domain = spec.compute_domain(
        spec.DOMAIN_BLS_TO_EXECUTION_CHANGE,
        spec.config.GENESIS_FORK_VERSION,
        state.genesis_validators_root,
    )
    sig = bls.Sign(from_privkey, spec.compute_signing_root(change, domain))
    return spec.SignedBLSToExecutionChange(message=change, signature=sig)


@with_phases(CAPELLA_FORKS)
@always_bls
@spec_state_test
def test_change_applies_eth1_prefix(spec, state):
    signed = _signed_change(spec, state, 4)
    spec.process_bls_to_execution_change(state, signed)
    creds = bytes(state.validators[4].withdrawal_credentials)
    assert creds[:1] == bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX)
    assert creds[12:] == b"\x11" * 20


@with_phases(CAPELLA_FORKS)
@always_bls
@spec_state_test
def test_invalid_change_wrong_pubkey_hash(spec, state):
    signed = _signed_change(spec, state, 4)
    state.validators[4].withdrawal_credentials = (
        bytes(spec.BLS_WITHDRAWAL_PREFIX) + hash_bytes(bytes(pubkeys[5]))[1:]
    )
    expect_assertion_error(lambda: spec.process_bls_to_execution_change(state, signed))


@with_phases(CAPELLA_FORKS)
@always_bls
@spec_state_test
def test_invalid_change_already_eth1_credentials(spec, state):
    signed = _signed_change(spec, state, 4)
    _eth1_credentials(spec, state, 4)
    expect_assertion_error(lambda: spec.process_bls_to_execution_change(state, signed))


@with_phases(CAPELLA_FORKS)
@always_bls
@spec_state_test
def test_invalid_change_bad_signature(spec, state):
    signed = _signed_change(spec, state, 4)
    signed.signature = bls.Sign(privkeys[9], b"\x00" * 32)
    expect_assertion_error(lambda: spec.process_bls_to_execution_change(state, signed))


@with_phases(CAPELLA_FORKS)
@always_bls
@spec_state_test
def test_invalid_change_out_of_range_index(spec, state):
    signed = _signed_change(spec, state, 4)
    signed.message.validator_index = len(state.validators) + 5
    expect_assertion_error(lambda: spec.process_bls_to_execution_change(state, signed))


@with_phases(CAPELLA_FORKS)
@always_bls
@spec_state_test
def test_change_signature_checked_against_genesis_fork(spec, state):
    """The change domain pins GENESIS_FORK_VERSION even after forks —
    signing with the current fork version must fail."""
    signed = _signed_change(spec, state, 4)
    wrong_domain = spec.compute_domain(
        spec.DOMAIN_BLS_TO_EXECUTION_CHANGE,
        state.fork.current_version,
        state.genesis_validators_root,
    )
    if bytes(wrong_domain) == bytes(
        spec.compute_domain(
            spec.DOMAIN_BLS_TO_EXECUTION_CHANGE,
            spec.config.GENESIS_FORK_VERSION,
            state.genesis_validators_root,
        )
    ):
        return  # fork version equals genesis (pure-capella genesis state)
    signed.signature = bls.Sign(
        privkeys[4], spec.compute_signing_root(signed.message, wrong_domain)
    )
    expect_assertion_error(lambda: spec.process_bls_to_execution_change(state, signed))
