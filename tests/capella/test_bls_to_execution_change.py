"""BLS-to-execution credential changes (reference analogue:
test/capella/block_processing/test_process_bls_to_execution_change.py)."""

from eth_consensus_specs_tpu.test_infra.context import (
    always_bls,
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.keys import privkeys, pubkeys
from eth_consensus_specs_tpu.utils import bls

TO_ADDRESS = b"\x59" * 20


def make_signed_address_change(spec, state, index: int, key_index: int | None = None):
    """Sign with key `key_index` (defaults to the credential's own key)."""
    if key_index is None:
        key_index = index
    from_pubkey = pubkeys[key_index]
    change = spec.BLSToExecutionChange(
        validator_index=index, from_bls_pubkey=from_pubkey, to_execution_address=TO_ADDRESS
    )
    domain = spec.compute_domain(
        spec.DOMAIN_BLS_TO_EXECUTION_CHANGE,
        genesis_validators_root=state.genesis_validators_root,
    )
    signing_root = spec.compute_signing_root(change, domain)
    return spec.SignedBLSToExecutionChange(
        message=change, signature=bls.Sign(privkeys[key_index], signing_root)
    )


def run_bls_change_processing(spec, state, signed_change, valid=True):
    yield "pre", state
    yield "address_change", signed_change
    if not valid:
        expect_assertion_error(
            lambda: spec.process_bls_to_execution_change(state, signed_change)
        )
        yield "post", None
        return
    spec.process_bls_to_execution_change(state, signed_change)
    yield "post", state
    creds = bytes(state.validators[int(signed_change.message.validator_index)].withdrawal_credentials)
    assert creds[:1] == spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX
    assert creds[12:] == bytes(signed_change.message.to_execution_address)


@with_phases(["capella"])
@always_bls
@spec_state_test
def test_bls_change_success(spec, state):
    yield from run_bls_change_processing(spec, state, make_signed_address_change(spec, state, 0))


@with_phases(["capella"])
@always_bls
@spec_state_test
def test_bls_change_invalid_wrong_key(spec, state):
    # credentials commit to key 0; signing (and claiming) key 1 must fail
    signed = make_signed_address_change(spec, state, 0, key_index=1)
    yield from run_bls_change_processing(spec, state, signed, valid=False)


@with_phases(["capella"])
@always_bls
@spec_state_test
def test_bls_change_invalid_already_eth1(spec, state):
    state.validators[0].withdrawal_credentials = (
        bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX) + b"\x00" * 11 + b"\x11" * 20
    )
    signed = make_signed_address_change(spec, state, 0)
    yield from run_bls_change_processing(spec, state, signed, valid=False)


@with_phases(["capella"])
@always_bls
@spec_state_test
def test_bls_change_invalid_bad_signature(spec, state):
    signed = make_signed_address_change(spec, state, 0)
    signed.signature = bls.Sign(privkeys[0], b"\x99" * 32)
    yield from run_bls_change_processing(spec, state, signed, valid=False)


@with_phases(["capella"])
@spec_state_test
def test_bls_change_then_withdrawable(spec, state):
    # after the change, the validator has eth1 credentials and can be swept
    signed = make_signed_address_change(spec, state, 3)
    spec.process_bls_to_execution_change(state, signed)
    assert spec.has_eth1_withdrawal_credential(state.validators[3])
