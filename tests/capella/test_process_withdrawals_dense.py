"""Dense process_withdrawals suite, capella+deneb (reference analogue:
test/capella/block_processing/test_process_withdrawals.py — the ~56-variant
file; this covers its sweep-saturation, too-few-in-payload, per-field
corruption, zero-balance edge, validator-lifecycle partial-withdrawable,
legacy-boundary, and randomized-sweep families)."""

import random

from eth_consensus_specs_tpu.test_infra.template import instantiate
from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.execution_payload import (
    build_empty_execution_payload,
    compute_el_block_hash,
)
from eth_consensus_specs_tpu.test_infra.state import next_slot
from eth_consensus_specs_tpu.test_infra.withdrawals import (
    prepare_expected_withdrawals,
    run_withdrawals_processing,
    set_eth1_withdrawal_credential_with_balance,
    set_validator_fully_withdrawable,
    set_validator_partially_withdrawable,
)

CAPELLA_FORKS = ["capella", "deneb"]


def _payload(spec, state):
    next_slot(spec, state)
    return build_empty_execution_payload(spec, state)


def _drain(gen):
    """Drain a dual-mode runner in pytest mode."""
    for _ in gen:
        pass


# ------------------------------------------------------------------ success


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_success_zero_expected_withdrawals(spec, state):
    payload = _payload(spec, state)
    assert len(payload.withdrawals) == 0
    _drain(run_withdrawals_processing(spec, state, payload, num_expected_withdrawals=0))


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_success_max_per_slot_sweep(spec, state):
    # Saturate: more withdrawable than MAX_WITHDRAWALS_PER_PAYLOAD
    cap = int(spec.MAX_WITHDRAWALS_PER_PAYLOAD)
    rng = random.Random(42)
    prepare_expected_withdrawals(
        spec, state, rng, num_full_withdrawals=cap, num_partial_withdrawals=cap
    )
    payload = _payload(spec, state)
    assert len(payload.withdrawals) == cap
    _drain(run_withdrawals_processing(spec, state, payload))


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_success_all_fully_withdrawable_in_one_sweep(spec, state):
    count = min(int(spec.MAX_WITHDRAWALS_PER_PAYLOAD), len(state.validators))
    rng = random.Random(7)
    prepare_expected_withdrawals(spec, state, rng, num_full_withdrawals=count)
    payload = _payload(spec, state)
    _drain(
        run_withdrawals_processing(
            spec, state, payload, num_expected_withdrawals=count
        )
    )


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_success_all_partially_withdrawable_in_one_sweep(spec, state):
    count = min(int(spec.MAX_WITHDRAWALS_PER_PAYLOAD), len(state.validators))
    rng = random.Random(8)
    prepare_expected_withdrawals(spec, state, rng, num_partial_withdrawals=count)
    payload = _payload(spec, state)
    _drain(
        run_withdrawals_processing(
            spec, state, payload, num_expected_withdrawals=count
        )
    )


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_success_sweep_cursor_wraps_around(spec, state):
    # Point the cursor near the end of the registry; the sweep must wrap.
    state.next_withdrawal_validator_index = len(state.validators) - 1
    set_validator_fully_withdrawable(spec, state, 0)
    payload = _payload(spec, state)
    assert len(payload.withdrawals) == 1
    assert int(payload.withdrawals[0].validator_index) == 0
    _drain(run_withdrawals_processing(spec, state, payload))


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_success_withdrawal_index_strictly_increments(spec, state):
    rng = random.Random(11)
    prepare_expected_withdrawals(spec, state, rng, num_full_withdrawals=3)
    payload = _payload(spec, state)
    indices = [int(w.index) for w in payload.withdrawals]
    assert indices == list(range(indices[0], indices[0] + len(indices)))
    _drain(run_withdrawals_processing(spec, state, payload))


# ----------------------------------------------------- lifecycle partials


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_success_no_excess_balance_not_partial(spec, state):
    # balance exactly at max effective: not partially withdrawable
    set_eth1_withdrawal_credential_with_balance(spec, state, 1)
    payload = _payload(spec, state)
    _drain(run_withdrawals_processing(spec, state, payload, num_expected_withdrawals=0))


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_success_excess_balance_but_low_effective_not_partial(spec, state):
    # excess balance but effective balance below cap: not partially withdrawable
    set_eth1_withdrawal_credential_with_balance(
        spec,
        state,
        1,
        balance=int(spec.MAX_EFFECTIVE_BALANCE) + 1_000_000_000,
        effective_balance=int(spec.MAX_EFFECTIVE_BALANCE)
        - int(spec.EFFECTIVE_BALANCE_INCREMENT),
    )
    payload = _payload(spec, state)
    _drain(run_withdrawals_processing(spec, state, payload, num_expected_withdrawals=0))


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_success_partial_withdrawable_not_yet_active(spec, state):
    set_validator_partially_withdrawable(spec, state, 2)
    state.validators[2].activation_epoch = int(spec.get_current_epoch(state)) + 4
    payload = _payload(spec, state)
    assert len(payload.withdrawals) == 1
    _drain(run_withdrawals_processing(spec, state, payload))


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_success_partial_withdrawable_in_exit_queue(spec, state):
    set_validator_partially_withdrawable(spec, state, 2)
    state.validators[2].exit_epoch = int(spec.get_current_epoch(state)) + 2
    payload = _payload(spec, state)
    assert len(payload.withdrawals) == 1
    _drain(run_withdrawals_processing(spec, state, payload))


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_success_partial_withdrawable_active_and_slashed(spec, state):
    set_validator_partially_withdrawable(spec, state, 2)
    state.validators[2].slashed = True
    payload = _payload(spec, state)
    assert len(payload.withdrawals) == 1
    _drain(run_withdrawals_processing(spec, state, payload))


# -------------------------------------------------------- zero-balance edges


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_withdrawable_epoch_but_0_balance(spec, state):
    set_validator_fully_withdrawable(spec, state, 3)
    state.validators[3].effective_balance = 10_000_000_000
    state.balances[3] = 0
    payload = _payload(spec, state)
    # nothing to withdraw: balance 0 never enters the sweep
    _drain(run_withdrawals_processing(spec, state, payload, num_expected_withdrawals=0))


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_withdrawable_epoch_but_0_effective_balance_nonzero_balance(spec, state):
    set_validator_fully_withdrawable(spec, state, 3)
    state.validators[3].effective_balance = 0
    state.balances[3] = 100_000_000
    payload = _payload(spec, state)
    assert len(payload.withdrawals) == 1
    _drain(run_withdrawals_processing(spec, state, payload, num_expected_withdrawals=1))


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_no_withdrawals_but_some_next_epoch(spec, state):
    # withdrawable next epoch, not this one
    epoch = int(spec.get_current_epoch(state))
    set_validator_fully_withdrawable(spec, state, 4, withdrawable_epoch=epoch + 1)
    payload = _payload(spec, state)
    _drain(run_withdrawals_processing(spec, state, payload, num_expected_withdrawals=0))


# ------------------------------------------------------------------ invalid


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_invalid_non_withdrawable_non_empty_withdrawals(spec, state):
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals = [
        spec.Withdrawal(index=0, validator_index=0, address=b"\x01" * 20, amount=1)
    ]
    _drain(run_withdrawals_processing(spec, state, payload, valid=False))


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_invalid_one_expected_but_empty_payload(spec, state):
    set_validator_fully_withdrawable(spec, state, 1)
    payload = _payload(spec, state)
    payload.withdrawals = []
    _drain(run_withdrawals_processing(spec, state, payload, valid=False))


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_invalid_duplicate_withdrawal_in_payload(spec, state):
    set_validator_fully_withdrawable(spec, state, 1)
    payload = _payload(spec, state)
    payload.withdrawals = [payload.withdrawals[0], payload.withdrawals[0]]
    _drain(run_withdrawals_processing(spec, state, payload, valid=False))


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_invalid_max_per_slot_one_less_in_payload(spec, state):
    cap = int(spec.MAX_WITHDRAWALS_PER_PAYLOAD)
    rng = random.Random(21)
    prepare_expected_withdrawals(spec, state, rng, num_full_withdrawals=cap + 2)
    payload = _payload(spec, state)
    payload.withdrawals = list(payload.withdrawals)[:-1]
    _drain(run_withdrawals_processing(spec, state, payload, valid=False))


def _corrupted_field_case(kind: str, field: str):
    """Factory: corrupt one field of the first expected withdrawal; the
    payload must be rejected (per-field invalid table, reference:
    test_process_withdrawals.py:375-438)."""

    @with_phases(CAPELLA_FORKS)
    @spec_state_test
    def case(spec, state):
        if kind == "full":
            set_validator_fully_withdrawable(spec, state, 1)
        else:
            set_validator_partially_withdrawable(spec, state, 2)
        payload = _payload(spec, state)
        w = payload.withdrawals[0]
        if field == "address":
            w.address = b"\xee" * 20
        else:
            setattr(w, field, int(getattr(w, field)) + 1)
        payload.withdrawals[0] = w
        _drain(run_withdrawals_processing(spec, state, payload, valid=False))

    return case, f"test_invalid_incorrect_{field}_{kind}"


for _kind in ("full", "partial"):
    for _field in ("index", "validator_index", "amount", "address"):
        instantiate(_corrupted_field_case, _kind, _field)


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_invalid_one_of_many_corrupted(spec, state):
    rng = random.Random(31)
    prepare_expected_withdrawals(spec, state, rng, num_full_withdrawals=4)
    payload = _payload(spec, state)
    mid = len(payload.withdrawals) // 2
    w = payload.withdrawals[mid]
    w.amount = int(w.amount) + 1
    payload.withdrawals[mid] = w
    _drain(run_withdrawals_processing(spec, state, payload, valid=False))


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_invalid_reordered_withdrawals(spec, state):
    rng = random.Random(32)
    prepare_expected_withdrawals(spec, state, rng, num_full_withdrawals=3)
    payload = _payload(spec, state)
    ws = list(payload.withdrawals)
    if len(ws) >= 2 and bytes(ws[0].address) != bytes(ws[1].address):
        payload.withdrawals = [ws[1], ws[0]] + ws[2:]
        _drain(run_withdrawals_processing(spec, state, payload, valid=False))


# ---------------------------------------------------------------- randomized


def _random_sweep_case(mode: str, seed: int):
    """Factory: seeded random full/mixed sweep (reference:
    test_process_withdrawals.py:643-667, 910-944)."""

    @with_phases(CAPELLA_FORKS)
    @spec_state_test
    def case(spec, state):
        rng = random.Random(seed)
        if mode == "full":
            count = rng.randint(1, min(8, len(state.validators) // 2))
            prepare_expected_withdrawals(spec, state, rng, num_full_withdrawals=count)
        else:
            prepare_expected_withdrawals(
                spec,
                state,
                rng,
                num_full_withdrawals=rng.randint(0, 4),
                num_partial_withdrawals=rng.randint(0, 4),
            )
        payload = _payload(spec, state)
        _drain(run_withdrawals_processing(spec, state, payload))

    return case, f"test_random_{mode}_withdrawals_{seed}"


for _seed in (0, 1, 2, 3):
    instantiate(_random_sweep_case, "full", _seed)
for _seed in (10, 11, 12, 13):
    instantiate(_random_sweep_case, "mixed", _seed)


# -------------------------------------------------------------- block hash


@with_phases(CAPELLA_FORKS)
@spec_state_test
def test_withdrawals_change_el_block_hash(spec, state):
    """The EL block hash commits to the withdrawals trie — two payloads
    differing only in withdrawals hash differently (EIP-4895)."""
    set_validator_fully_withdrawable(spec, state, 1)
    payload = _payload(spec, state)
    with_sweep = compute_el_block_hash(spec, payload, state)
    empty = payload.copy()
    empty.withdrawals = []
    assert compute_el_block_hash(spec, empty, state) != with_sweep
