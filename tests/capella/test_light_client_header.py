"""Light-client header validity across the capella+ execution era
(reference analogue: test/capella/light_client/test_single_merkle_proof.py
+ per-fork light_client suites; spec:
specs/capella/light-client/sync-protocol.md:129-156)."""

from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.block import (
    apply_empty_block,
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test_with_matching_config,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.state import next_slot

EXECUTION_FORKS = ["capella", "deneb", "electra"]


def _header_from_block(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    return spec.block_to_light_client_header(signed)


@with_phases(EXECUTION_FORKS)
@spec_state_test_with_matching_config
def test_header_from_real_block_is_valid(spec, state):
    header = _header_from_block(spec, state)
    assert spec.is_valid_light_client_header(header)


@with_phases(EXECUTION_FORKS)
@spec_state_test_with_matching_config
def test_header_execution_root_matches_payload_header(spec, state):
    header = _header_from_block(spec, state)
    assert bytes(spec.get_lc_execution_root(header)) == bytes(
        hash_tree_root(header.execution)
    )


@with_phases(EXECUTION_FORKS)
@spec_state_test_with_matching_config
def test_header_corrupted_branch_invalid(spec, state):
    header = _header_from_block(spec, state)
    branch = list(header.execution_branch)
    branch[0] = b"\xaa" * 32
    header.execution_branch = branch
    assert not spec.is_valid_light_client_header(header)


@with_phases(EXECUTION_FORKS)
@spec_state_test_with_matching_config
def test_header_mutated_execution_invalid(spec, state):
    header = _header_from_block(spec, state)
    header.execution.gas_limit = int(header.execution.gas_limit) + 1
    assert not spec.is_valid_light_client_header(header)


@with_phases(EXECUTION_FORKS)
@spec_state_test_with_matching_config
def test_header_execution_branch_depth_matches_gindex(spec, state):
    from eth_consensus_specs_tpu.forks.light_client import floorlog2

    header = _header_from_block(spec, state)
    assert len(header.execution_branch) == floorlog2(spec.EXECUTION_PAYLOAD_GINDEX)


@with_phases(["deneb", "electra"])
@spec_state_test_with_matching_config
def test_header_carries_blob_gas_fields(spec, state):
    """Deneb LC headers surface blob_gas_used/excess_blob_gas — mutating
    them breaks the proof."""
    header = _header_from_block(spec, state)
    assert hasattr(header.execution, "blob_gas_used")
    header.execution.excess_blob_gas = int(header.execution.excess_blob_gas) + 1
    assert not spec.is_valid_light_client_header(header)


@with_phases(EXECUTION_FORKS)
@spec_state_test_with_matching_config
def test_header_valid_after_multiple_blocks(spec, state):
    for _ in range(3):
        apply_empty_block(spec, state, int(state.slot) + 1)
    next_slot(spec, state)
    header = _header_from_block(spec, state)
    assert spec.is_valid_light_client_header(header)


@with_phases(EXECUTION_FORKS)
@spec_state_test_with_matching_config
def test_bootstrap_header_roundtrip(spec, state):
    """A bootstrap built from a block's header initializes a store whose
    finalized header passes validity."""
    header = _header_from_block(spec, state)
    bootstrap = spec.LightClientBootstrap(
        header=header,
        current_sync_committee=state.current_sync_committee,
    )
    # current-sync-committee branch for the bootstrap state
    from eth_consensus_specs_tpu.ssz.merkle import compute_merkle_proof

    gindex = spec.current_sync_committee_gindex_at_slot(int(state.slot))
    branch = compute_merkle_proof(state, gindex)
    bootstrap.current_sync_committee_branch = spec.normalize_merkle_branch(
        branch, gindex
    )
    trusted_root = bytes(hash_tree_root(header.beacon))
    store = spec.initialize_light_client_store(trusted_root, bootstrap)
    assert spec.is_valid_light_client_header(store.finalized_header)
