"""The batched G2 many-sum kernel and the committee-tree pipeline.

Fast lane: g2_jacobian boundary-value coverage from the registry's
declared domains (infinity lanes, the P+P doubling path, P+(-P) -> inf)
executed EAGERLY against the crypto/curve host oracle, plus the host
tiers of the committee tree against the flat signature fold.

Slow lane (nightly, like the rest of the device-crypto suite): the
kernel's scan-body compile — ragged/infinity lane parity, the device
pipeline tier-by-tier, verification + bisection isolation of injected
invalid committees, and mesh (lane-axis sharded) parity."""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from eth_consensus_specs_tpu.crypto import signature as sig_mod
from eth_consensus_specs_tpu.crypto.curve import (
    Point,
    g1_generator,
    g2_generator,
    g2_infinity,
    g2_to_bytes,
)
from eth_consensus_specs_tpu.crypto.fields import Fq2
from eth_consensus_specs_tpu.ops import agg_tree
from eth_consensus_specs_tpu.ops import fq12_tower as tw
from eth_consensus_specs_tpu.ops import g2_jacobian as gj
from eth_consensus_specs_tpu.ops import lazy_limbs as lz
from eth_consensus_specs_tpu.ops.g2_aggregate import (
    _jacobian_to_point,
    g2_many_sum_shape,
    sum_g2_device,
    sum_g2_many_device,
)
from eth_consensus_specs_tpu.ops.lazy_limbs import lf

G1 = g1_generator()
G2 = g2_generator()


def _g2j(points: list[Point]) -> gj.G2J:
    """Affine host points -> one batched Jacobian lane array (infinity
    lanes where the point is at infinity)."""
    n = len(points)
    x = np.zeros((n, 2, lz.N_LIMBS), np.uint64)
    y = np.zeros_like(x)
    z = np.zeros_like(x)
    one = tw.fq2_to_limbs(Fq2.one())
    for i, p in enumerate(points):
        if p.is_infinity():
            continue
        x[i] = tw.fq2_to_limbs(p.x)
        y[i] = tw.fq2_to_limbs(p.y)
        z[i] = one
    return gj.G2J(lf(jnp.asarray(x)), lf(jnp.asarray(y)), lf(jnp.asarray(z)))


def _to_points(p: gj.G2J) -> list[Point]:
    X = np.asarray(gj._canon(p.x).v)
    Y = np.asarray(gj._canon(p.y).v)
    Z = np.asarray(gj._canon(p.z).v)
    return [_jacobian_to_point(X[i], Y[i], Z[i]) for i in range(X.shape[0])]


# -------------------------------------------- g2_jacobian corner lanes --


def test_g2_add_corner_lanes_vs_curve_oracle():
    """One eager batched g2_add over every masked case at once: generic
    add, P+P (the doubling fallback), P+(-P) -> infinity, and both
    infinity passthroughs — each lane bit-equal to the host curve
    oracle after canonical affine conversion."""
    P7, P11 = G2.mul(7), G2.mul(11)
    a = [P7, P7, P7, g2_infinity(), P11, g2_infinity()]
    b = [P11, P7, -P7, P11, g2_infinity(), g2_infinity()]
    got = _to_points(gj.g2_add(_g2j(a), _g2j(b)))
    want = [x + y for x, y in zip(a, b)]
    assert got == want


def test_g2_dbl_corner_lanes_vs_curve_oracle():
    """Doubling at the declared corners: a generic point, infinity
    (Z = 0 in, Z3 = 0 out), and an order-2-style Y = 0 lane is absent
    from BLS12-381 G2 — the curve group has odd order — so the oracle
    set is {2P, inf}."""
    pts = [G2.mul(5), g2_infinity(), G2]
    got = _to_points(gj.g2_dbl(_g2j(pts)))
    assert got == [p + p for p in pts]


@pytest.mark.slow  # the scan ladder compiles its step body (~a minute on cpu)
def test_g2_mul_z_ladder_on_small_multiples_vs_curve_oracle():
    """The fixed [|x|]-ladder on small multiples k*G2: value-equal to
    the host mul for every lane of one batch."""
    ks = [1, 2, 3, 7]
    pts = [G2.mul(k) for k in ks]
    got = _to_points(gj.g2_mul_z(_g2j(pts)))
    assert got == [p.mul(gj.BLS_X_ABS) for p in pts]


# ------------------------------------------------------- shape model --


def test_g2_many_sum_shape_is_the_serve_bucket_model():
    from eth_consensus_specs_tpu.serve import buckets

    assert g2_many_sum_shape(3, 5) == (4, 8)
    assert g2_many_sum_shape(3, 33, 6) == (4, buckets.agg_lane_bucket(33, 6))
    item_pad, lane_pad = g2_many_sum_shape(9, 100, 8)
    assert item_pad == 16 and lane_pad % 8 == 0 and lane_pad >= 100


# ------------------------------------------------ host committee tree --


def _mk_atts(n_subnets=3, committees=2, committee=4, n_roots=2, start=1):
    atts, k = [], start
    for subnet in range(n_subnets):
        for c in range(committees):
            root = bytes([1 + (c % n_roots)]) * 32
            bits = [True] * committee
            bits[1] = False  # ragged participation
            sigs = tuple(G2.mul(k + j) for j in range(committee - 1))
            pks = tuple(G1.mul(k + j) for j in range(committee - 1))
            k += committee
            atts.append(
                agg_tree.CommitteeAttestation(
                    subnet, root, pks, sigs, tuple(bits)
                )
            )
    return atts


def test_host_tree_tiers_equal_flat_signature_fold():
    """The committee tree's host oracle is associativity-trustworthy:
    every global aggregate equals the FLAT signature.aggregate over the
    same members, and participation bits concatenate (subnet,
    committee)-deterministically to the full registry width."""
    atts = _mk_atts()
    slot, subs = agg_tree.aggregate_slot_host(atts)
    assert len(subs) == 6  # 3 subnets x 2 roots
    for sa in slot:
        members = [
            g2_to_bytes(p)
            for a in atts
            if bytes(a.root) == sa.root
            for p in a.sigs
        ]
        assert sa.sig_bytes == sig_mod.aggregate(members)
        n_bits = sum(len(a.bits) for a in atts if bytes(a.root) == sa.root)
        assert sa.bits.shape == (n_bits,)
        assert int(sa.bits.sum()) == sum(
            len(a.sigs) for a in atts if bytes(a.root) == sa.root
        )


def test_subnet_count_env_snapshot(monkeypatch):
    monkeypatch.delenv("ETH_SPECS_AGG_SUBNETS", raising=False)
    assert agg_tree.subnet_count() == 64
    monkeypatch.setenv("ETH_SPECS_AGG_SUBNETS", "8")
    assert agg_tree.subnet_count() == 8
    monkeypatch.setenv("ETH_SPECS_AGG_SUBNETS", "junk")
    assert agg_tree.subnet_count() == 64


# ------------------------------------------------- device slow lane --


@pytest.mark.slow
def test_sum_g2_many_device_parity_ragged_and_corners():
    """Ragged committees, infinity members, duplicate points (the
    doubling path inside the butterfly), and a P + (-P) committee — all
    in ONE dispatch, each sum bit-equal to the host fold and the
    compressed bytes equal to signature.aggregate."""
    lists = [
        [G2.mul(k + 1) for k in range(5)],
        [G2.mul(7), g2_infinity(), G2.mul(9)],
        [g2_infinity()],
        [G2.mul(3), -G2.mul(3)],
        [G2.mul(4), G2.mul(4)],  # equal lanes -> doubling fallback
    ]
    got = sum_g2_many_device(lists)
    for pl, g in zip(lists, got):
        assert g == sig_mod._sum_g2(list(pl))
    # bytes-level parity where aggregate() accepts the input
    real = [g2_to_bytes(p) for p in lists[0]]
    assert g2_to_bytes(sum_g2_device(lists[0])) == sig_mod.aggregate(real)


@pytest.mark.slow
def test_device_pipeline_tiers_and_isolation():
    """Device tiers bit-equal to the host oracle, verification of what
    was just built, and bisection isolation of one injected invalid
    committee."""
    from eth_consensus_specs_tpu.crypto.hash_to_curve import hash_to_g2

    root = b"\x05" * 32
    H = hash_to_g2(root)
    atts = []
    for subnet in range(2):
        sks = list(range(1 + 4 * subnet, 5 + 4 * subnet))
        atts.append(
            agg_tree.CommitteeAttestation(
                subnet, root,
                tuple(G1.mul(sk) for sk in sks),
                tuple(H.mul(sk) for sk in sks),
                (True,) * 4,
            )
        )
    slot_d, subs_d = agg_tree.aggregate_slot(atts)
    slot_h, subs_h = agg_tree.aggregate_slot_host(atts)
    for d, h in zip(subs_d, subs_h):
        assert (d.subnet, d.root, d.sig, d.pubkey) == (h.subnet, h.root, h.sig, h.pubkey)
        assert np.array_equal(d.bits, h.bits)
    for d, h in zip(slot_d, slot_h):
        assert (d.root, d.sig_bytes, d.pubkey_bytes) == (h.root, h.sig_bytes, h.pubkey_bytes)
    assert agg_tree.verify_slot(slot_d) == [True]
    assert agg_tree.isolate_invalid_subnets(subs_d) == []

    bad = agg_tree.CommitteeAttestation(
        1, root, atts[1].pubkeys,
        tuple(p + G2 for p in atts[1].sigs), atts[1].bits,
    )
    slot2, subs2 = agg_tree.aggregate_slot([atts[0], bad])
    assert agg_tree.verify_slot(slot2) == [False]
    assert agg_tree.isolate_invalid_subnets(subs2) == [(1, root)]


@pytest.mark.slow
def test_mesh_lane_sharded_parity():
    """The lane-axis-sharded dispatch returns byte-identical points to
    the single-device kernel — any shard count, including the
    all-gather + replicated-top combine."""
    import jax

    from eth_consensus_specs_tpu.parallel import mesh_ops

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices (conftest forces them on CPU)")
    mesh = mesh_ops.serve_mesh(4)
    assert mesh is not None
    lists = [
        [G2.mul(k + 1) for k in range(9)],
        [G2.mul(31), g2_infinity(), G2.mul(33), -G2.mul(31)],
    ]
    single = sum_g2_many_device(lists)
    sharded = sum_g2_many_device(lists, mesh=mesh)
    assert single == sharded
    assert [g2_to_bytes(p) for p in single] == [g2_to_bytes(p) for p in sharded]
