"""Dense apply_pending_deposit suite, electra+ (reference analogue:
test/electra/epoch_processing/pending_deposits/test_apply_pending_deposit.py
— the 26-variant file: effective-balance boundary arithmetic per
credential kind, signature gating for new deposits vs top-ups, and
malformed-pubkey robustness; spec: specs/electra/beacon-chain.md
apply_pending_deposit)."""

from eth_consensus_specs_tpu.test_infra.context import (
    always_bls,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.keys import privkeys, pubkeys
from eth_consensus_specs_tpu.test_infra.template import instantiate
from eth_consensus_specs_tpu.utils import bls

ELECTRA_FORKS = ["electra", "fulu"]
GWEI = 1_000_000_000

ETH1_CREDS = b"\x01" + b"\x00" * 11 + b"\x42" * 20
COMPOUNDING_CREDS = b"\x02" + b"\x00" * 11 + b"\x42" * 20
BLS_CREDS = b"\x00" + b"\x99" * 31  # non-versioned / legacy


def _new_key_index(state):
    """A keypair index not present in the registry."""
    return len(state.validators) + 10


def _signed_new_deposit(spec, state, creds, amount, privkey_index=None, good_sig=True):
    idx = privkey_index if privkey_index is not None else _new_key_index(state)
    pubkey = pubkeys[idx]
    message = spec.DepositMessage(
        pubkey=pubkey, withdrawal_credentials=creds, amount=amount
    )
    domain = spec.compute_domain(spec.DOMAIN_DEPOSIT)
    root = spec.compute_signing_root(message, domain)
    key = privkeys[idx] if good_sig else privkeys[idx + 1]
    return spec.PendingDeposit(
        pubkey=pubkey,
        withdrawal_credentials=creds,
        amount=amount,
        signature=bls.Sign(key, root),
        slot=spec.GENESIS_SLOT,
    )


# ----------------------------------------- new-validator balance boundaries


def _boundary_case(creds_kind: str, where: str):
    @with_phases(ELECTRA_FORKS)
    @always_bls
    @spec_state_test
    def case(spec, state):
        inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
        if creds_kind == "compounding":
            creds = COMPOUNDING_CREDS
            cap = int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA)
        else:
            creds = ETH1_CREDS
            cap = int(spec.MIN_ACTIVATION_BALANCE)
        amount = {
            "under": cap - inc,
            "at": cap,
            "over": cap + inc,
            "over_next_increment": cap + inc + inc // 2,
        }[where]
        deposit = _signed_new_deposit(spec, state, creds, amount)
        pre_count = len(state.validators)
        spec.apply_pending_deposit(state, deposit)
        assert len(state.validators) == pre_count + 1
        new = state.validators[pre_count]
        assert int(state.balances[pre_count]) == amount
        # effective balance: floor to increment, clamp at the creds cap
        assert int(new.effective_balance) == min(amount - amount % inc, cap)

    return case, f"test_new_deposit_{creds_kind}_{where}_cap"


for _kind in ("eth1", "compounding"):
    for _where in ("under", "at", "over", "over_next_increment"):
        instantiate(_boundary_case, _kind, _where)


@with_phases(ELECTRA_FORKS)
@always_bls
@spec_state_test
def test_new_deposit_non_versioned_credentials(spec, state):
    """Legacy 0x00 creds still register; cap is MIN_ACTIVATION_BALANCE."""
    amount = int(spec.MIN_ACTIVATION_BALANCE) + 2 * int(
        spec.EFFECTIVE_BALANCE_INCREMENT
    )
    deposit = _signed_new_deposit(spec, state, BLS_CREDS, amount)
    pre_count = len(state.validators)
    spec.apply_pending_deposit(state, deposit)
    new = state.validators[pre_count]
    assert int(new.effective_balance) == int(spec.MIN_ACTIVATION_BALANCE)


# -------------------------------------------------------- signature gating


@with_phases(ELECTRA_FORKS)
@always_bls
@spec_state_test
def test_new_deposit_bad_signature_dropped(spec, state):
    deposit = _signed_new_deposit(
        spec, state, ETH1_CREDS, 32 * GWEI, good_sig=False
    )
    pre_count = len(state.validators)
    spec.apply_pending_deposit(state, deposit)
    # silently skipped: no registry growth, no balance anywhere
    assert len(state.validators) == pre_count


@with_phases(ELECTRA_FORKS)
@always_bls
@spec_state_test
def test_top_up_skips_signature_check(spec, state):
    """Top-ups to a known pubkey apply WITHOUT signature verification —
    possession was proven by the original deposit."""
    v = state.validators[3]
    deposit = spec.PendingDeposit(
        pubkey=v.pubkey,
        withdrawal_credentials=v.withdrawal_credentials,
        amount=GWEI,
        signature=b"\xde" * 96,  # garbage signature
        slot=spec.GENESIS_SLOT,
    )
    pre = int(state.balances[3])
    spec.apply_pending_deposit(state, deposit)
    assert int(state.balances[3]) == pre + GWEI


@with_phases(ELECTRA_FORKS)
@always_bls
@spec_state_test
def test_top_up_ignores_mismatched_credentials(spec, state):
    """A top-up's credentials are NOT checked against the registry's."""
    v = state.validators[3]
    deposit = spec.PendingDeposit(
        pubkey=v.pubkey,
        withdrawal_credentials=COMPOUNDING_CREDS,
        amount=GWEI,
        signature=b"\xde" * 96,
        slot=spec.GENESIS_SLOT,
    )
    pre_creds = bytes(v.withdrawal_credentials)
    pre = int(state.balances[3])
    spec.apply_pending_deposit(state, deposit)
    assert int(state.balances[3]) == pre + GWEI
    assert bytes(state.validators[3].withdrawal_credentials) == pre_creds


@with_phases(ELECTRA_FORKS)
@always_bls
@spec_state_test
def test_top_up_does_not_change_effective_balance(spec, state):
    """apply_pending_deposit only raises the raw balance; the effective
    balance catches up at process_effective_balance_updates."""
    v = state.validators[3]
    pre_eff = int(v.effective_balance)
    deposit = spec.PendingDeposit(
        pubkey=v.pubkey,
        withdrawal_credentials=v.withdrawal_credentials,
        amount=5 * GWEI,
        signature=bls.G2_POINT_AT_INFINITY,
        slot=spec.GENESIS_SLOT,
    )
    spec.apply_pending_deposit(state, deposit)
    assert int(state.validators[3].effective_balance) == pre_eff


@with_phases(ELECTRA_FORKS)
@always_bls
@spec_state_test
def test_top_up_to_withdrawn_validator_applies(spec, state):
    """Even fully-withdrawn validators accept top-ups (the sweep will
    reclaim them next slot)."""
    epoch = int(spec.get_current_epoch(state))
    state.validators[3].exit_epoch = max(0, epoch - 1)
    state.validators[3].withdrawable_epoch = max(0, epoch - 1)
    state.balances[3] = 0
    v = state.validators[3]
    deposit = spec.PendingDeposit(
        pubkey=v.pubkey,
        withdrawal_credentials=v.withdrawal_credentials,
        amount=GWEI,
        signature=bls.G2_POINT_AT_INFINITY,
        slot=spec.GENESIS_SLOT,
    )
    spec.apply_pending_deposit(state, deposit)
    assert int(state.balances[3]) == GWEI


# ------------------------------------------------------- malformed pubkeys


@with_phases(ELECTRA_FORKS)
@always_bls
@spec_state_test
def test_new_deposit_invalid_pubkey_decompression_dropped(spec, state):
    """A pubkey that fails point decompression must be skipped, not crash
    (reference: apply_pending_deposit_key_validate_invalid_decompression)."""
    deposit = spec.PendingDeposit(
        pubkey=b"\xff" * 48,  # invalid compression flags
        withdrawal_credentials=ETH1_CREDS,
        amount=32 * GWEI,
        signature=b"\xaa" * 96,
        slot=spec.GENESIS_SLOT,
    )
    pre_count = len(state.validators)
    spec.apply_pending_deposit(state, deposit)
    assert len(state.validators) == pre_count


@with_phases(ELECTRA_FORKS)
@always_bls
@spec_state_test
def test_new_deposit_identity_pubkey_dropped(spec, state):
    """The G1 identity is not a valid deposit pubkey (KeyValidate)."""
    identity = b"\xc0" + b"\x00" * 47
    deposit = spec.PendingDeposit(
        pubkey=identity,
        withdrawal_credentials=ETH1_CREDS,
        amount=32 * GWEI,
        signature=b"\xaa" * 96,
        slot=spec.GENESIS_SLOT,
    )
    pre_count = len(state.validators)
    spec.apply_pending_deposit(state, deposit)
    assert len(state.validators) == pre_count


# ------------------------------------------------------------ queue driver


@with_phases(ELECTRA_FORKS)
@always_bls
@spec_state_test
def test_process_pending_deposits_new_validator_signature_checked(spec, state):
    """End-to-end through the queue: good-sig deposit registers, bad-sig is
    consumed without registering."""
    good = _signed_new_deposit(spec, state, ETH1_CREDS, 32 * GWEI)
    bad = _signed_new_deposit(
        spec, state, ETH1_CREDS, 32 * GWEI, privkey_index=_new_key_index(state) + 3,
        good_sig=False,
    )
    state.pending_deposits.append(good)
    state.pending_deposits.append(bad)
    pre_count = len(state.validators)
    spec.process_pending_deposits(state)
    assert len(state.validators) == pre_count + 1
    assert len(state.pending_deposits) == 0
