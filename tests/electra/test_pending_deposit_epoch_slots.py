"""Pending deposits applied through EPOCH TRANSITIONS driven by empty
slot processing (reference analogue:
eth2spec/test/electra/sanity/test_slots.py — queue semantics observable
without any blocks; spec: specs/electra/beacon-chain.md
process_pending_deposits inside process_epoch)."""

from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.deposits import build_deposit_data
from eth_consensus_specs_tpu.test_infra.keys import privkeys, pubkeys
from eth_consensus_specs_tpu.test_infra.state import next_epoch

ELECTRA_ON = ["electra", "fulu"]

ETH1_CREDS = lambda spec: (  # noqa: E731
    spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11 + b"\x42" * 20
)
COMP_CREDS = lambda spec: (  # noqa: E731
    spec.COMPOUNDING_WITHDRAWAL_PREFIX + b"\x00" * 11 + b"\x42" * 20
)


def _queue_deposit(spec, state, key_index: int, amount: int, creds=None, signed=True):
    data = build_deposit_data(
        spec,
        bytes(pubkeys[key_index]),
        privkeys[key_index],
        amount,
        creds if creds is not None else ETH1_CREDS(spec),
        signed=signed,
    )
    state.pending_deposits.append(
        spec.PendingDeposit(
            pubkey=data.pubkey,
            withdrawal_credentials=data.withdrawal_credentials,
            amount=amount,
            signature=data.signature,
            slot=spec.GENESIS_SLOT,
        )
    )


@with_phases(ELECTRA_ON)
@spec_state_test
def test_pending_deposit_extra_gwei(spec, state):
    """A non-increment amount lands gwei-exact in the balance."""
    n = len(state.validators)
    amount = int(spec.MIN_ACTIVATION_BALANCE) + 1  # 1 extra gwei
    _queue_deposit(spec, state, n + 1, amount)
    next_epoch(spec, state)
    assert len(state.validators) == n + 1
    assert int(state.balances[n]) == amount


@with_phases(ELECTRA_ON)
@spec_state_test
def test_multiple_pending_deposits_same_pubkey(spec, state):
    """First deposit creates the validator; the rest top up — one new
    validator total."""
    n = len(state.validators)
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _queue_deposit(spec, state, n + 1, int(spec.MIN_ACTIVATION_BALANCE))
    _queue_deposit(spec, state, n + 1, inc)
    _queue_deposit(spec, state, n + 1, inc)
    next_epoch(spec, state)
    assert len(state.validators) == n + 1
    assert int(state.balances[n]) == int(spec.MIN_ACTIVATION_BALANCE) + 2 * inc


@with_phases(ELECTRA_ON)
@spec_state_test
def test_multiple_same_pubkey_second_signature_invalid(spec, state):
    """Top-ups skip signature verification: a second deposit with a BAD
    signature still credits the existing validator."""
    n = len(state.validators)
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _queue_deposit(spec, state, n + 1, int(spec.MIN_ACTIVATION_BALANCE), signed=True)
    _queue_deposit(spec, state, n + 1, inc, signed=False)  # junk signature
    next_epoch(spec, state)
    assert len(state.validators) == n + 1
    assert int(state.balances[n]) == int(spec.MIN_ACTIVATION_BALANCE) + inc


@with_phases(ELECTRA_ON)
@spec_state_test
def test_same_pubkey_compounding_creds_from_first_deposit(spec, state):
    """The FIRST applied deposit fixes the credentials; later deposits
    with different creds only top up."""
    n = len(state.validators)
    _queue_deposit(
        spec, state, n + 1, int(spec.MIN_ACTIVATION_BALANCE), creds=COMP_CREDS(spec)
    )
    _queue_deposit(
        spec,
        state,
        n + 1,
        int(spec.EFFECTIVE_BALANCE_INCREMENT),
        creds=ETH1_CREDS(spec),
    )
    next_epoch(spec, state)
    assert len(state.validators) == n + 1
    creds = bytes(state.validators[n].withdrawal_credentials)
    assert creds[:1] == bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX)


@with_phases(ELECTRA_ON)
@spec_state_test
def test_top_up_below_upward_hysteresis_threshold(spec, state):
    """A small top-up below the hysteresis window leaves the effective
    balance untouched at the next update."""
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    upward = inc // int(spec.HYSTERESIS_QUOTIENT) * int(
        spec.HYSTERESIS_UPWARD_MULTIPLIER
    )
    target = 0
    from eth_consensus_specs_tpu.test_infra.withdrawals import (
        set_compounding_withdrawal_credential_with_balance,
    )

    # compounding creds: the cap sits far above, so only the hysteresis
    # window can hold the effective balance back
    start = int(spec.MIN_ACTIVATION_BALANCE)
    set_compounding_withdrawal_credential_with_balance(
        spec, state, target, balance=start, effective_balance=start
    )
    _queue_deposit(spec, state, target, upward - 1)
    next_epoch(spec, state)
    assert int(state.validators[target].effective_balance) == start


@with_phases(ELECTRA_ON)
@spec_state_test
def test_top_up_above_upward_hysteresis_threshold(spec, state):
    """Crossing the upward threshold re-floors the effective balance to
    the full new balance (not a single-increment step)."""
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    upward = inc // int(spec.HYSTERESIS_QUOTIENT) * int(
        spec.HYSTERESIS_UPWARD_MULTIPLIER
    )
    target = 0
    from eth_consensus_specs_tpu.test_infra.withdrawals import (
        set_compounding_withdrawal_credential_with_balance,
    )

    start = int(spec.MIN_ACTIVATION_BALANCE)
    set_compounding_withdrawal_credential_with_balance(
        spec, state, target, balance=start, effective_balance=start
    )
    _queue_deposit(spec, state, target, inc + upward)
    next_epoch(spec, state)
    # new balance = start + inc + upward; effective re-floors to whole
    # increments: start + 2*inc (upward = 1.25 inc on mainnet params)
    expected = (start + inc + upward) // inc * inc
    assert int(state.validators[target].effective_balance) == expected


@with_phases(ELECTRA_ON)
@spec_state_test
def test_pending_consolidation_through_slots(spec, state):
    """A matured pending consolidation sweeps the source balance into the
    target at the epoch boundary, no blocks involved."""
    src, dst = 1, 2
    from eth_consensus_specs_tpu.test_infra.withdrawals import (
        set_compounding_withdrawal_credential_with_balance,
    )

    set_compounding_withdrawal_credential_with_balance(spec, state, dst)
    state.validators[src].exit_epoch = spec.get_current_epoch(state)
    state.validators[src].withdrawable_epoch = spec.get_current_epoch(state) + 1
    state.pending_consolidations.append(
        spec.PendingConsolidation(source_index=src, target_index=dst)
    )
    src_balance = int(state.balances[src])
    src_effective = int(state.validators[src].effective_balance)
    dst_balance = int(state.balances[dst])
    moved = min(src_balance, src_effective)

    next_epoch(spec, state)
    assert len(state.pending_consolidations) == 0
    assert int(state.balances[dst]) == dst_balance + moved
    assert int(state.balances[src]) == src_balance - moved
