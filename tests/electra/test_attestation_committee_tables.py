"""EIP-7549 committee-bit attestation combination tables, electra+
(reference analogue: test/electra/block_processing/
test_process_attestation.py multi-committee variants)."""

from eth_consensus_specs_tpu.test_infra.attestations import (
    get_valid_attestation,
    get_valid_attestations_at_slot,
    run_attestation_processing,
)
from eth_consensus_specs_tpu.test_infra.context import (
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.state import next_slots

ELECTRA_FORKS = ["electra", "fulu"]


def _fresh(spec, state):
    next_slots(spec, state, 10)
    atts = get_valid_attestations_at_slot(spec, state, int(state.slot))
    next_slots(spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))
    return atts


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_single_committee_attestation(spec, state):
    next_slots(spec, state, 10)
    att = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))
    assert sum(map(bool, att.committee_bits)) == 1
    yield from run_attestation_processing(spec, state, att)


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_on_chain_aggregate_all_committees(spec, state):
    """The electra on-chain form: one attestation spanning EVERY slot
    committee via compute_on_chain_aggregate semantics."""
    next_slots(spec, state, 10)
    slot = int(state.slot)
    atts = get_valid_attestations_at_slot(spec, state, slot, signed=True)
    next_slots(spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))
    if len(atts) == 1:
        att = atts[0]
    elif hasattr(spec, "compute_on_chain_aggregate"):
        att = spec.compute_on_chain_aggregate(atts)
    else:
        return
    assert sum(map(bool, att.committee_bits)) >= 1
    yield from run_attestation_processing(spec, state, att)


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_invalid_nonzero_data_index(spec, state):
    next_slots(spec, state, 10)
    att = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))
    att.data.index = 1  # must be 0 post-electra
    yield from run_attestation_processing(spec, state, att, valid=False)


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_invalid_zero_committee_bits(spec, state):
    next_slots(spec, state, 10)
    att = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))
    for i in range(len(att.committee_bits)):
        att.committee_bits[i] = False
    yield from run_attestation_processing(spec, state, att, valid=False)


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_invalid_bits_shorter_than_committee_span(spec, state):
    next_slots(spec, state, 10)
    att = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))
    bits_t = type(att.aggregation_bits)
    att.aggregation_bits = bits_t(list(att.aggregation_bits)[:-1])
    yield from run_attestation_processing(spec, state, att, valid=False)


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_invalid_empty_participation_in_claimed_committee(spec, state):
    next_slots(spec, state, 10)
    att = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))
    for i in range(len(att.aggregation_bits)):
        att.aggregation_bits[i] = False
    yield from run_attestation_processing(spec, state, att, valid=False)


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_get_attesting_indices_matches_bits(spec, state):
    next_slots(spec, state, 10)
    att = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))
    idxs = spec.get_attesting_indices(state, att)
    assert len(idxs) == sum(map(bool, att.aggregation_bits))
