"""Execution-layer requests: withdrawal, deposit, consolidation (spec:
specs/electra/beacon-chain.md:1653-1864; reference analogue:
test/electra/block_processing/test_process_{withdrawal,deposit,
consolidation}_request.py)."""

from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.keys import pubkey
from eth_consensus_specs_tpu.test_infra.state import next_epoch

ELECTRA = ["electra"]


def _execution_creds(spec, state, index: int, prefix: bytes):
    address = b"\x42" * 20
    state.validators[index].withdrawal_credentials = prefix + b"\x00" * 11 + address
    return address


def _age_validator(spec, state, index: int):
    """Make the validator old enough to exit."""
    state.validators[index].activation_epoch = 0
    if spec.get_current_epoch(state) < spec.config.SHARD_COMMITTEE_PERIOD:
        state.slot = spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH


# == withdrawal requests ===================================================


@with_phases(ELECTRA)
@spec_state_test
def test_withdrawal_request_full_exit(spec, state):
    index = 1
    address = _execution_creds(spec, state, index, spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX)
    _age_validator(spec, state, index)
    req = spec.WithdrawalRequest(
        source_address=address,
        validator_pubkey=state.validators[index].pubkey,
        amount=spec.FULL_EXIT_REQUEST_AMOUNT,
    )
    spec.process_withdrawal_request(state, req)
    assert state.validators[index].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_phases(ELECTRA)
@spec_state_test
def test_withdrawal_request_wrong_source_ignored(spec, state):
    index = 1
    _execution_creds(spec, state, index, spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX)
    _age_validator(spec, state, index)
    req = spec.WithdrawalRequest(
        source_address=b"\x99" * 20,  # not the credentialed address
        validator_pubkey=state.validators[index].pubkey,
        amount=spec.FULL_EXIT_REQUEST_AMOUNT,
    )
    spec.process_withdrawal_request(state, req)
    assert state.validators[index].exit_epoch == spec.FAR_FUTURE_EPOCH


@with_phases(ELECTRA)
@spec_state_test
def test_withdrawal_request_partial_compounding(spec, state):
    index = 1
    address = _execution_creds(spec, state, index, spec.COMPOUNDING_WITHDRAWAL_PREFIX)
    _age_validator(spec, state, index)
    excess = 3 * spec.EFFECTIVE_BALANCE_INCREMENT
    state.balances[index] = spec.MIN_ACTIVATION_BALANCE + excess
    amount = spec.EFFECTIVE_BALANCE_INCREMENT
    req = spec.WithdrawalRequest(
        source_address=address,
        validator_pubkey=state.validators[index].pubkey,
        amount=amount,
    )
    spec.process_withdrawal_request(state, req)
    assert len(state.pending_partial_withdrawals) == 1
    pw = state.pending_partial_withdrawals[0]
    assert int(pw.validator_index) == index
    assert int(pw.amount) == amount
    # validator keeps FAR_FUTURE exit (partial, not full)
    assert state.validators[index].exit_epoch == spec.FAR_FUTURE_EPOCH


@with_phases(ELECTRA)
@spec_state_test
def test_withdrawal_request_partial_needs_compounding_creds(spec, state):
    """0x01 credentials cannot take partial withdrawals via requests."""
    index = 1
    address = _execution_creds(spec, state, index, spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX)
    _age_validator(spec, state, index)
    state.balances[index] = spec.MIN_ACTIVATION_BALANCE + spec.EFFECTIVE_BALANCE_INCREMENT
    req = spec.WithdrawalRequest(
        source_address=address,
        validator_pubkey=state.validators[index].pubkey,
        amount=spec.EFFECTIVE_BALANCE_INCREMENT,
    )
    spec.process_withdrawal_request(state, req)
    assert len(state.pending_partial_withdrawals) == 0


@with_phases(ELECTRA)
@spec_state_test
def test_withdrawal_request_exit_blocked_by_pending_partials(spec, state):
    index = 1
    address = _execution_creds(spec, state, index, spec.COMPOUNDING_WITHDRAWAL_PREFIX)
    _age_validator(spec, state, index)
    state.pending_partial_withdrawals.append(
        spec.PendingPartialWithdrawal(
            validator_index=index, amount=1, withdrawable_epoch=10**6
        )
    )
    req = spec.WithdrawalRequest(
        source_address=address,
        validator_pubkey=state.validators[index].pubkey,
        amount=spec.FULL_EXIT_REQUEST_AMOUNT,
    )
    spec.process_withdrawal_request(state, req)
    assert state.validators[index].exit_epoch == spec.FAR_FUTURE_EPOCH


# == deposit requests ======================================================


@with_phases(ELECTRA)
@spec_state_test
def test_deposit_request_sets_start_index_and_queues(spec, state):
    assert int(state.deposit_requests_start_index) == spec.UNSET_DEPOSIT_REQUESTS_START_INDEX
    req = spec.DepositRequest(
        pubkey=pubkey(300),
        withdrawal_credentials=b"\x01" + b"\x00" * 11 + b"\x11" * 20,
        amount=spec.MIN_ACTIVATION_BALANCE,
        signature=b"\x00" * 96,
        index=77,
    )
    spec.process_deposit_request(state, req)
    assert int(state.deposit_requests_start_index) == 77
    assert len(state.pending_deposits) == 1
    assert int(state.pending_deposits[0].slot) == int(state.slot)
    # second request does not move the start index
    req2 = spec.DepositRequest(
        pubkey=pubkey(301),
        withdrawal_credentials=b"\x01" + b"\x00" * 11 + b"\x11" * 20,
        amount=spec.MIN_ACTIVATION_BALANCE,
        signature=b"\x00" * 96,
        index=78,
    )
    spec.process_deposit_request(state, req2)
    assert int(state.deposit_requests_start_index) == 77
    assert len(state.pending_deposits) == 2


# == consolidation requests ================================================


@with_phases(ELECTRA)
@spec_state_test
def test_consolidation_request_basic(spec, state):
    source, target = 1, 2
    src_addr = _execution_creds(spec, state, source, spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX)
    _execution_creds(spec, state, target, spec.COMPOUNDING_WITHDRAWAL_PREFIX)
    _age_validator(spec, state, source)
    req = spec.ConsolidationRequest(
        source_address=src_addr,
        source_pubkey=state.validators[source].pubkey,
        target_pubkey=state.validators[target].pubkey,
    )
    spec.process_consolidation_request(state, req)
    assert len(state.pending_consolidations) == 1
    pc = state.pending_consolidations[0]
    assert int(pc.source_index) == source and int(pc.target_index) == target
    assert state.validators[source].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_phases(ELECTRA)
@spec_state_test
def test_consolidation_request_switch_to_compounding(spec, state):
    index = 1
    addr = _execution_creds(spec, state, index, spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX)
    excess = 2 * spec.EFFECTIVE_BALANCE_INCREMENT
    state.balances[index] = spec.MIN_ACTIVATION_BALANCE + excess
    pk = state.validators[index].pubkey
    req = spec.ConsolidationRequest(
        source_address=addr, source_pubkey=pk, target_pubkey=pk
    )
    spec.process_consolidation_request(state, req)
    assert spec.has_compounding_withdrawal_credential(state.validators[index])
    # excess balance entered the deposit queue
    assert int(state.balances[index]) == spec.MIN_ACTIVATION_BALANCE
    assert len(state.pending_deposits) == 1
    assert int(state.pending_deposits[0].amount) == excess
    # no pending consolidation for a self-switch
    assert len(state.pending_consolidations) == 0


@with_phases(ELECTRA)
@spec_state_test
def test_consolidation_request_target_needs_compounding(spec, state):
    source, target = 1, 2
    src_addr = _execution_creds(spec, state, source, spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX)
    _execution_creds(spec, state, target, spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX)
    _age_validator(spec, state, source)
    req = spec.ConsolidationRequest(
        source_address=src_addr,
        source_pubkey=state.validators[source].pubkey,
        target_pubkey=state.validators[target].pubkey,
    )
    spec.process_consolidation_request(state, req)
    assert len(state.pending_consolidations) == 0
    assert state.validators[source].exit_epoch == spec.FAR_FUTURE_EPOCH


# == pending consolidation sweep ===========================================


@with_phases(ELECTRA)
@spec_state_test
def test_process_pending_consolidations_moves_balance(spec, state):
    source, target = 1, 2
    state.validators[source].withdrawable_epoch = spec.get_current_epoch(state)
    state.pending_consolidations.append(
        spec.PendingConsolidation(source_index=source, target_index=target)
    )
    src_balance = int(state.balances[source])
    tgt_balance = int(state.balances[target])
    eff = int(state.validators[source].effective_balance)
    moved = min(src_balance, eff)
    spec.process_pending_consolidations(state)
    assert int(state.balances[source]) == src_balance - moved
    assert int(state.balances[target]) == tgt_balance + moved
    assert len(state.pending_consolidations) == 0


@with_phases(ELECTRA)
@spec_state_test
def test_process_pending_consolidations_skips_slashed(spec, state):
    source, target = 1, 2
    state.validators[source].slashed = True
    state.validators[source].withdrawable_epoch = spec.get_current_epoch(state)
    state.pending_consolidations.append(
        spec.PendingConsolidation(source_index=source, target_index=target)
    )
    src_balance = int(state.balances[source])
    spec.process_pending_consolidations(state)
    assert int(state.balances[source]) == src_balance  # nothing moved
    assert len(state.pending_consolidations) == 0  # but the entry is consumed


# == round-4: typed flat encoding round-trip (validator.md:270-305) ========


@with_phases(ELECTRA)
@spec_state_test
def test_execution_requests_list_roundtrip(spec, state):
    """get_execution_requests inverts get_execution_requests_list."""
    reqs = spec.ExecutionRequests()
    reqs.withdrawals.append(
        spec.WithdrawalRequest(
            source_address=b"\x42" * 20,
            validator_pubkey=state.validators[1].pubkey,
            amount=spec.FULL_EXIT_REQUEST_AMOUNT,
        )
    )
    reqs.consolidations.append(
        spec.ConsolidationRequest(
            source_address=b"\x42" * 20,
            source_pubkey=state.validators[1].pubkey,
            target_pubkey=state.validators[2].pubkey,
        )
    )
    encoded = spec.get_execution_requests_list(reqs)
    # empty deposit list is omitted from the flat encoding
    assert len(encoded) == 2
    back = spec.get_execution_requests(encoded)
    from eth_consensus_specs_tpu.ssz import hash_tree_root

    assert hash_tree_root(back) == hash_tree_root(reqs)


@with_phases(ELECTRA)
@spec_state_test
def test_execution_requests_decode_rejects_disorder(spec, state):
    from eth_consensus_specs_tpu.test_infra.context import expect_assertion_error

    reqs = spec.ExecutionRequests()
    reqs.withdrawals.append(
        spec.WithdrawalRequest(
            source_address=b"\x42" * 20,
            validator_pubkey=state.validators[1].pubkey,
            amount=0,
        )
    )
    reqs.consolidations.append(
        spec.ConsolidationRequest(
            source_address=b"\x42" * 20,
            source_pubkey=state.validators[1].pubkey,
            target_pubkey=state.validators[2].pubkey,
        )
    )
    encoded = spec.get_execution_requests_list(reqs)
    # reversed type order must be refused
    expect_assertion_error(lambda: spec.get_execution_requests(encoded[::-1]))
    # duplicate type must be refused
    expect_assertion_error(
        lambda: spec.get_execution_requests([encoded[0], encoded[0]])
    )
    # empty payload must be refused
    expect_assertion_error(
        lambda: spec.get_execution_requests([bytes(spec.WITHDRAWAL_REQUEST_TYPE)])
    )


@with_phases(ELECTRA)
@spec_state_test
def test_eth1_pending_deposit_count_windows(spec, state):
    """Bridge draining: count tracks min(deposit_count, start_index) minus
    the consumed index, clamped by MAX_DEPOSITS."""
    state.eth1_data.deposit_count = 10
    state.deposit_requests_start_index = 6
    state.eth1_deposit_index = 4
    assert int(spec.get_eth1_pending_deposit_count(state)) == 2
    state.eth1_deposit_index = 6
    assert int(spec.get_eth1_pending_deposit_count(state)) == 0
    state.eth1_deposit_index = 0
    state.deposit_requests_start_index = 2**64 - 1  # pre-transition
    state.eth1_data.deposit_count = 100
    assert int(spec.get_eth1_pending_deposit_count(state)) == int(spec.MAX_DEPOSITS)
