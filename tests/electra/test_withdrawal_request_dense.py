"""Dense process_withdrawal_request suite, electra+ (reference analogue:
test/electra/block_processing/test_process_withdrawal_request.py — the
29-variant EIP-7002 file; this covers its partial-withdrawal amount
arithmetic, pending-queue interactions, noop gating, and churn families).

Spec: specs/electra/beacon-chain.md process_withdrawal_request — every
failed precondition is a silent noop (EL-sourced requests can't be
'invalid'), so assertions check state deltas, not exceptions."""

from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.withdrawals import (
    prepare_withdrawal_request,
    set_compounding_withdrawal_credential_with_balance,
    set_eth1_withdrawal_credential_with_balance,
)

ELECTRA_FORKS = ["electra", "fulu"]


def _mature(spec, state):
    """Jump past the SHARD_COMMITTEE_PERIOD activity gate. Direct slot bump:
    process_withdrawal_request reads only get_current_epoch(state), so full
    slot processing buys nothing here."""
    state.slot = int(spec.config.SHARD_COMMITTEE_PERIOD) * int(spec.SLOTS_PER_EPOCH)


def _unchanged(spec, state, fn):
    """Run fn and assert it was a perfect noop on exits and the partial queue."""
    pre_exits = [int(v.exit_epoch) for v in state.validators]
    pre_queue = len(state.pending_partial_withdrawals)
    fn()
    assert [int(v.exit_epoch) for v in state.validators] == pre_exits
    assert len(state.pending_partial_withdrawals) == pre_queue


def _compounding(spec, state, idx, excess=2_000_000_000):
    cap = int(spec.MIN_ACTIVATION_BALANCE)
    return set_compounding_withdrawal_credential_with_balance(
        spec, state, idx, balance=cap + excess, effective_balance=cap
    )


# ------------------------------------------------------------- full exits


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_full_exit_first_validator(spec, state):
    _mature(spec, state)
    req = prepare_withdrawal_request(spec, state, 0)
    spec.process_withdrawal_request(state, req)
    assert int(state.validators[0].exit_epoch) != int(spec.FAR_FUTURE_EPOCH)


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_full_exit_with_compounding_credentials(spec, state):
    _mature(spec, state)
    _compounding(spec, state, 3, excess=0)
    req = prepare_withdrawal_request(spec, state, 3)
    spec.process_withdrawal_request(state, req)
    assert int(state.validators[3].exit_epoch) != int(spec.FAR_FUTURE_EPOCH)


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_full_exit_blocked_by_pending_partial(spec, state):
    """A full exit while the validator still has a pending partial
    withdrawal is a noop (pending_balance_to_withdraw != 0)."""
    _mature(spec, state)
    addr = _compounding(spec, state, 4)
    state.pending_partial_withdrawals.append(
        spec.PendingPartialWithdrawal(
            validator_index=4, amount=1_000_000_000, withdrawable_epoch=10
        )
    )
    req = spec.WithdrawalRequest(
        source_address=addr,
        validator_pubkey=state.validators[4].pubkey,
        amount=spec.FULL_EXIT_REQUEST_AMOUNT,
    )
    spec.process_withdrawal_request(state, req)
    assert int(state.validators[4].exit_epoch) == int(spec.FAR_FUTURE_EPOCH)


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_full_exit_queue_full_still_processed(spec, state):
    """The pending-queue-full early return gates only PARTIAL requests;
    full exits still go through."""
    _mature(spec, state)
    limit = int(spec.PENDING_PARTIAL_WITHDRAWALS_LIMIT)
    if limit > 64:  # only the minimal preset makes saturation practical
        return
    for _ in range(limit):
        state.pending_partial_withdrawals.append(
            spec.PendingPartialWithdrawal(
                validator_index=9, amount=1, withdrawable_epoch=10
            )
        )
    req = prepare_withdrawal_request(spec, state, 0)
    spec.process_withdrawal_request(state, req)
    assert int(state.validators[0].exit_epoch) != int(spec.FAR_FUTURE_EPOCH)


# ------------------------------------------------------- partial arithmetic


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_partial_amount_below_excess(spec, state):
    _mature(spec, state)
    _compounding(spec, state, 2, excess=2_000_000_000)
    req = prepare_withdrawal_request(spec, state, 2, amount=1_000_000_000)
    spec.process_withdrawal_request(state, req)
    assert len(state.pending_partial_withdrawals) == 1
    # requested amount fits inside excess: withdraw exactly the request
    assert int(state.pending_partial_withdrawals[0].amount) == 1_000_000_000


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_partial_amount_above_excess_clamped(spec, state):
    _mature(spec, state)
    _compounding(spec, state, 2, excess=1_500_000_000)
    req = prepare_withdrawal_request(spec, state, 2, amount=5_000_000_000)
    spec.process_withdrawal_request(state, req)
    assert len(state.pending_partial_withdrawals) == 1
    # clamped to balance - MIN_ACTIVATION_BALANCE - pending
    assert int(state.pending_partial_withdrawals[0].amount) == 1_500_000_000


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_partial_with_pending_withdrawals_reduces_headroom(spec, state):
    _mature(spec, state)
    _compounding(spec, state, 2, excess=3_000_000_000)
    state.pending_partial_withdrawals.append(
        spec.PendingPartialWithdrawal(
            validator_index=2, amount=2_000_000_000, withdrawable_epoch=10
        )
    )
    req = prepare_withdrawal_request(spec, state, 2, amount=5_000_000_000)
    spec.process_withdrawal_request(state, req)
    assert len(state.pending_partial_withdrawals) == 2
    # headroom = 3 ETH excess - 2 ETH already pending = 1 ETH
    assert int(state.pending_partial_withdrawals[1].amount) == 1_000_000_000


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_partial_withdrawable_epoch_includes_delay(spec, state):
    _mature(spec, state)
    _compounding(spec, state, 2)
    req = prepare_withdrawal_request(spec, state, 2, amount=1_000_000_000)
    spec.process_withdrawal_request(state, req)
    pending = state.pending_partial_withdrawals[0]
    exit_epoch = int(pending.withdrawable_epoch) - int(
        spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    )
    assert exit_epoch >= int(spec.get_current_epoch(state))


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_partial_low_amount_exact(spec, state):
    _mature(spec, state)
    _compounding(spec, state, 2, excess=10_000_000_000)
    req = prepare_withdrawal_request(spec, state, 2, amount=1)
    spec.process_withdrawal_request(state, req)
    assert int(state.pending_partial_withdrawals[0].amount) == 1


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_two_partials_accumulate(spec, state):
    _mature(spec, state)
    _compounding(spec, state, 2, excess=4_000_000_000)
    req = prepare_withdrawal_request(spec, state, 2, amount=1_000_000_000)
    spec.process_withdrawal_request(state, req)
    spec.process_withdrawal_request(state, req)
    assert len(state.pending_partial_withdrawals) == 2
    # validator exit is NOT initiated by partial requests
    assert int(state.validators[2].exit_epoch) == int(spec.FAR_FUTURE_EPOCH)


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_partial_churn_shares_exit_queue(spec, state):
    """Successive partial withdrawals consume exit churn: a later large
    request lands at the same or later exit epoch, never earlier."""
    _mature(spec, state)
    _compounding(spec, state, 2, excess=50_000_000_000)
    req = prepare_withdrawal_request(spec, state, 2, amount=20_000_000_000)
    spec.process_withdrawal_request(state, req)
    first = int(state.pending_partial_withdrawals[0].withdrawable_epoch)
    spec.process_withdrawal_request(state, req)
    second = int(state.pending_partial_withdrawals[1].withdrawable_epoch)
    assert second >= first


# ----------------------------------------------------------- partial noops


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_partial_queue_full_noop(spec, state):
    _mature(spec, state)
    limit = int(spec.PENDING_PARTIAL_WITHDRAWALS_LIMIT)
    if limit > 64:
        return
    for _ in range(limit):
        state.pending_partial_withdrawals.append(
            spec.PendingPartialWithdrawal(
                validator_index=9, amount=1, withdrawable_epoch=10
            )
        )
    _compounding(spec, state, 2)
    req = prepare_withdrawal_request(spec, state, 2, amount=1_000_000_000)
    pre = len(state.pending_partial_withdrawals)
    spec.process_withdrawal_request(state, req)
    assert len(state.pending_partial_withdrawals) == pre


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_partial_without_compounding_creds_noop(spec, state):
    _mature(spec, state)
    # 0x01 credentials: full exits only, partial requests are noops
    set_eth1_withdrawal_credential_with_balance(
        spec,
        state,
        2,
        balance=int(spec.MIN_ACTIVATION_BALANCE) + 2_000_000_000,
        effective_balance=int(spec.MIN_ACTIVATION_BALANCE),
    )
    req = prepare_withdrawal_request(spec, state, 2, amount=1_000_000_000)
    _unchanged(spec, state, lambda: spec.process_withdrawal_request(state, req))


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_partial_no_excess_balance_noop(spec, state):
    _mature(spec, state)
    _compounding(spec, state, 2, excess=0)
    req = prepare_withdrawal_request(spec, state, 2, amount=1_000_000_000)
    _unchanged(spec, state, lambda: spec.process_withdrawal_request(state, req))


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_partial_pending_consumes_all_excess_noop(spec, state):
    _mature(spec, state)
    _compounding(spec, state, 2, excess=2_000_000_000)
    state.pending_partial_withdrawals.append(
        spec.PendingPartialWithdrawal(
            validator_index=2, amount=2_000_000_000, withdrawable_epoch=10
        )
    )
    req = prepare_withdrawal_request(spec, state, 2, amount=1_000_000_000)
    pre = len(state.pending_partial_withdrawals)
    spec.process_withdrawal_request(state, req)
    assert len(state.pending_partial_withdrawals) == pre


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_partial_insufficient_effective_balance_noop(spec, state):
    _mature(spec, state)
    cap = int(spec.MIN_ACTIVATION_BALANCE)
    set_compounding_withdrawal_credential_with_balance(
        spec,
        state,
        2,
        balance=cap + 2_000_000_000,
        effective_balance=cap - int(spec.EFFECTIVE_BALANCE_INCREMENT),
    )
    req = prepare_withdrawal_request(spec, state, 2, amount=1_000_000_000)
    _unchanged(spec, state, lambda: spec.process_withdrawal_request(state, req))


# --------------------------------------------------------- gating (shared)


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_partial_wrong_source_address_noop(spec, state):
    _mature(spec, state)
    _compounding(spec, state, 2)
    req = prepare_withdrawal_request(spec, state, 2, amount=1_000_000_000)
    req.source_address = b"\x99" * 20
    _unchanged(spec, state, lambda: spec.process_withdrawal_request(state, req))


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_partial_exit_initiated_noop(spec, state):
    _mature(spec, state)
    _compounding(spec, state, 2)
    spec.initiate_validator_exit(state, 2)
    req = prepare_withdrawal_request(spec, state, 2, amount=1_000_000_000)
    pre = len(state.pending_partial_withdrawals)
    spec.process_withdrawal_request(state, req)
    assert len(state.pending_partial_withdrawals) == pre


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_partial_activation_too_recent_noop(spec, state):
    _mature(spec, state)
    _compounding(spec, state, 2)
    state.validators[2].activation_epoch = int(spec.get_current_epoch(state))
    req = prepare_withdrawal_request(spec, state, 2, amount=1_000_000_000)
    _unchanged(spec, state, lambda: spec.process_withdrawal_request(state, req))


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_inactive_validator_noop(spec, state):
    _mature(spec, state)
    _compounding(spec, state, 2)
    state.validators[2].activation_epoch = int(spec.FAR_FUTURE_EPOCH)
    req = prepare_withdrawal_request(spec, state, 2, amount=1_000_000_000)
    _unchanged(spec, state, lambda: spec.process_withdrawal_request(state, req))


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_unknown_pubkey_noop(spec, state):
    _mature(spec, state)
    _compounding(spec, state, 2)
    req = prepare_withdrawal_request(spec, state, 2, amount=1_000_000_000)
    req.validator_pubkey = b"\xab" * 48
    _unchanged(spec, state, lambda: spec.process_withdrawal_request(state, req))
