"""Electra whole-block sanity: execution-layer requests interacting with
CL operations inside one block (reference analogue:
eth2spec/test/electra/sanity/blocks/test_blocks.py; spec:
specs/electra/beacon-chain.md process_operations + request processing)."""

from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.keys import privkeys, pubkeys
from eth_consensus_specs_tpu.test_infra.voluntary_exits import sign_voluntary_exit
from eth_consensus_specs_tpu.test_infra.withdrawals import (
    prepare_withdrawal_request,
    set_compounding_withdrawal_credential_with_balance,
    set_eth1_withdrawal_credential_with_balance,
)
from eth_consensus_specs_tpu.utils import bls

ELECTRA_ON = ["electra", "fulu"]

ADDRESS = b"\x42" * 20


def _give_execution_creds(spec, state, index, address=ADDRESS, compounding=False):
    if compounding:
        set_compounding_withdrawal_credential_with_balance(
            spec, state, index, address=address
        )
    else:
        set_eth1_withdrawal_credential_with_balance(spec, state, index, address=address)


def _age_state(spec, state):
    if spec.get_current_epoch(state) < spec.config.SHARD_COMMITTEE_PERIOD:
        state.slot = spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH


def _apply_block_with_requests(
    spec, state, withdrawals=(), deposits=(), consolidations=()
):
    block = build_empty_block_for_next_slot(spec, state)
    for r in withdrawals:
        block.body.execution_requests.withdrawals.append(r)
    for r in deposits:
        block.body.execution_requests.deposits.append(r)
    for r in consolidations:
        block.body.execution_requests.consolidations.append(r)
    return state_transition_and_sign_block(spec, state, block)


def _withdrawal_request(spec, state, index, amount, address=ADDRESS):
    return prepare_withdrawal_request(
        spec, state, index, address=address, amount=amount
    )


# == withdrawal requests in blocks =========================================


@with_phases(ELECTRA_ON)
@spec_state_test
def test_block_with_el_withdrawal_request(spec, state):
    index = 1
    _give_execution_creds(spec, state, index)
    _age_state(spec, state)
    req = _withdrawal_request(spec, state, index, spec.FULL_EXIT_REQUEST_AMOUNT)
    _apply_block_with_requests(spec, state, withdrawals=[req])
    assert state.validators[index].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_phases(ELECTRA_ON)
@spec_state_test
def test_block_cl_exit_and_el_withdrawal_same_validator(spec, state):
    """A voluntary exit and an EL full-exit request for the same validator
    in one block: the CL exit wins, the request becomes a no-op, and the
    block remains valid."""
    index = 1
    _give_execution_creds(spec, state, index)
    _age_state(spec, state)

    voluntary = spec.VoluntaryExit(
        epoch=spec.get_current_epoch(state), validator_index=index
    )
    signed_exit = sign_voluntary_exit(spec, state, voluntary, privkeys[index])
    req = _withdrawal_request(spec, state, index, spec.FULL_EXIT_REQUEST_AMOUNT)

    block = build_empty_block_for_next_slot(spec, state)
    block.body.voluntary_exits.append(signed_exit)
    block.body.execution_requests.withdrawals.append(req)
    state_transition_and_sign_block(spec, state, block)

    assert state.validators[index].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_phases(ELECTRA_ON)
@spec_state_test
def test_block_multiple_partials_same_validator(spec, state):
    """Two partial requests for one compounding validator in a single
    block both enter the pending queue."""
    index = 1
    _give_execution_creds(spec, state, index, compounding=True)
    _age_state(spec, state)
    state.balances[index] = spec.MIN_ACTIVATION_BALANCE + 3 * spec.EFFECTIVE_BALANCE_INCREMENT
    state.validators[index].effective_balance = spec.MIN_ACTIVATION_BALANCE

    reqs = [
        _withdrawal_request(spec, state, index, spec.EFFECTIVE_BALANCE_INCREMENT),
        _withdrawal_request(spec, state, index, spec.EFFECTIVE_BALANCE_INCREMENT),
    ]
    before = len(state.pending_partial_withdrawals)
    _apply_block_with_requests(spec, state, withdrawals=reqs)
    assert len(state.pending_partial_withdrawals) == before + 2


@with_phases(ELECTRA_ON)
@spec_state_test
def test_block_partials_different_validators(spec, state):
    for index in (1, 2):
        _give_execution_creds(spec, state, index, compounding=True)
        state.balances[index] = (
            spec.MIN_ACTIVATION_BALANCE + 2 * spec.EFFECTIVE_BALANCE_INCREMENT
        )
        state.validators[index].effective_balance = spec.MIN_ACTIVATION_BALANCE
    _age_state(spec, state)
    reqs = [
        _withdrawal_request(spec, state, 1, spec.EFFECTIVE_BALANCE_INCREMENT),
        _withdrawal_request(spec, state, 2, spec.EFFECTIVE_BALANCE_INCREMENT),
    ]
    _apply_block_with_requests(spec, state, withdrawals=reqs)
    assert len(state.pending_partial_withdrawals) == 2
    assert {int(w.validator_index) for w in state.pending_partial_withdrawals} == {1, 2}


# == BTEC ordering =========================================================


@with_phases(ELECTRA_ON)
@spec_state_test
def test_block_btec_then_el_withdrawal_request_same_block(spec, state):
    """BLS-to-execution changes process BEFORE execution requests inside
    one block, so a request against the fresh address takes effect."""
    index = 1
    _age_state(spec, state)

    # give the validator BLS credentials matching the test key
    from eth_consensus_specs_tpu.ssz.hashing import hash_bytes as sha256

    bls_pubkey = bytes(pubkeys[index])
    state.validators[index].withdrawal_credentials = (
        spec.BLS_WITHDRAWAL_PREFIX + sha256(bls_pubkey)[1:]
    )

    change = spec.BLSToExecutionChange(
        validator_index=index,
        from_bls_pubkey=bls_pubkey,
        to_execution_address=ADDRESS,
    )
    domain = spec.compute_domain(
        spec.DOMAIN_BLS_TO_EXECUTION_CHANGE,
        spec.config.GENESIS_FORK_VERSION,
        state.genesis_validators_root,
    )
    signed_change = spec.SignedBLSToExecutionChange(
        message=change,
        signature=bls.Sign(
            privkeys[index], spec.compute_signing_root(change, domain)
        ),
    )
    # raw request against the address the BTEC will install — built by hand
    # because prepare_withdrawal_request would overwrite the BLS creds
    req = spec.WithdrawalRequest(
        source_address=ADDRESS,
        validator_pubkey=state.validators[index].pubkey,
        amount=spec.FULL_EXIT_REQUEST_AMOUNT,
    )

    block = build_empty_block_for_next_slot(spec, state)
    block.body.bls_to_execution_changes.append(signed_change)
    block.body.execution_requests.withdrawals.append(req)
    state_transition_and_sign_block(spec, state, block)

    assert state.validators[index].exit_epoch != spec.FAR_FUTURE_EPOCH


# == deposit requests in blocks ============================================


def _deposit_request(spec, index, creds, amount, slot=0):
    pubkey_bytes = bytes(pubkeys[index])
    deposit_message = spec.DepositMessage(
        pubkey=pubkey_bytes, withdrawal_credentials=creds, amount=amount
    )
    domain = spec.compute_domain(spec.DOMAIN_DEPOSIT)
    signature = bls.Sign(
        privkeys[index], spec.compute_signing_root(deposit_message, domain)
    )
    return spec.DepositRequest(
        pubkey=pubkey_bytes,
        withdrawal_credentials=creds,
        amount=amount,
        signature=signature,
        index=slot,
    )


@with_phases(ELECTRA_ON)
@spec_state_test
def test_block_deposit_request_same_pubkey_different_creds(spec, state):
    """Two requests for one pubkey with different credentials both enter
    the pending queue (dedup happens at apply time, not enqueue)."""
    n = len(state.validators)
    creds_a = spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11 + b"\xaa" * 20
    creds_b = spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11 + b"\xbb" * 20
    reqs = [
        _deposit_request(spec, n + 1, creds_a, spec.MIN_ACTIVATION_BALANCE, slot=0),
        _deposit_request(spec, n + 1, creds_b, spec.EFFECTIVE_BALANCE_INCREMENT, slot=1),
    ]
    before = len(state.pending_deposits)
    _apply_block_with_requests(spec, state, deposits=reqs)
    assert len(state.pending_deposits) == before + 2


@with_phases(ELECTRA_ON)
@spec_state_test
def test_block_deposit_request_max_per_payload(spec, state):
    cap = int(spec.MAX_DEPOSIT_REQUESTS_PER_PAYLOAD)
    n = len(state.validators)
    creds = spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11 + ADDRESS
    reqs = [
        _deposit_request(
            spec, n + 1 + i, creds, spec.EFFECTIVE_BALANCE_INCREMENT, slot=i
        )
        for i in range(cap)
    ]
    before = len(state.pending_deposits)
    _apply_block_with_requests(spec, state, deposits=reqs)
    assert len(state.pending_deposits) == before + cap


# == consolidation requests in blocks ======================================


@with_phases(ELECTRA_ON)
@spec_state_test
def test_block_consolidation_request(spec, state):
    src, dst = 1, 2
    _give_execution_creds(spec, state, src)
    _give_execution_creds(spec, state, dst, compounding=True)
    _age_state(spec, state)
    req = spec.ConsolidationRequest(
        source_address=ADDRESS,
        source_pubkey=state.validators[src].pubkey,
        target_pubkey=state.validators[dst].pubkey,
    )
    before = len(state.pending_consolidations)
    _apply_block_with_requests(spec, state, consolidations=[req])
    assert len(state.pending_consolidations) == before + 1
    assert state.validators[src].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_phases(ELECTRA_ON)
@spec_state_test
def test_block_switch_to_compounding_request(spec, state):
    """source == target: an in-block switch request flips the credential
    prefix without queueing a consolidation."""
    index = 1
    _give_execution_creds(spec, state, index)
    _age_state(spec, state)
    req = spec.ConsolidationRequest(
        source_address=ADDRESS,
        source_pubkey=state.validators[index].pubkey,
        target_pubkey=state.validators[index].pubkey,
    )
    before = len(state.pending_consolidations)
    _apply_block_with_requests(spec, state, consolidations=[req])
    assert len(state.pending_consolidations) == before
    assert state.validators[index].withdrawal_credentials[:1] == (
        spec.COMPOUNDING_WITHDRAWAL_PREFIX
    )


@with_phases(ELECTRA_ON)
@spec_state_test
def test_block_requests_roundtrip_root(spec, state):
    """Blocks carrying requests merkleize deterministically — the body
    root changes with the request content."""
    index = 1
    _give_execution_creds(spec, state, index)
    _age_state(spec, state)

    block_a = build_empty_block_for_next_slot(spec, state)
    root_empty = hash_tree_root(block_a.body)
    block_a.body.execution_requests.withdrawals.append(
        _withdrawal_request(spec, state, index, spec.FULL_EXIT_REQUEST_AMOUNT)
    )
    assert hash_tree_root(block_a.body) != root_empty
