"""Electra epoch-processing and helper deltas: balance churn, registry
single-pass activation, MaxEB effective-balance updates, slashing quotients,
withdrawals with pending partials (spec: specs/electra/beacon-chain.md:
548-611, 865-920, 1049-1072, 1186-1303)."""

from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.state import next_epoch

ELECTRA = ["electra"]


@with_phases(ELECTRA)
@spec_state_test
def test_balance_churn_limits(spec, state):
    churn = spec.get_balance_churn_limit(state)
    assert churn % spec.EFFECTIVE_BALANCE_INCREMENT == 0
    assert churn >= spec.config.MIN_PER_EPOCH_CHURN_LIMIT_ELECTRA
    ae = spec.get_activation_exit_churn_limit(state)
    assert ae == min(spec.config.MAX_PER_EPOCH_ACTIVATION_EXIT_CHURN_LIMIT, churn)
    assert spec.get_consolidation_churn_limit(state) == churn - ae


@with_phases(ELECTRA)
@spec_state_test
def test_registry_single_pass_activation(spec, state):
    """Eligible validators activate in the same epoch sweep, uncapped by the
    old per-count churn (EIP-7251 moves rate limiting to the deposit queue)."""
    current_epoch = spec.get_current_epoch(state)
    n = 5
    for i in range(n):
        v = state.validators[i]
        v.activation_epoch = spec.FAR_FUTURE_EPOCH
        v.activation_eligibility_epoch = 0
    state.finalized_checkpoint.epoch = current_epoch  # eligibility is finalized
    spec.process_registry_updates(state)
    expected = spec.compute_activation_exit_epoch(current_epoch)
    for i in range(n):
        assert int(state.validators[i].activation_epoch) == expected


@with_phases(ELECTRA)
@spec_state_test
def test_effective_balance_cap_compounding(spec, state):
    """Compounding credentials raise the EB ceiling to MaxEB."""
    index = 0
    state.validators[index].withdrawal_credentials = (
        bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX)
        + bytes(state.validators[index].withdrawal_credentials)[1:]
    )
    state.balances[index] = 100 * 10**9  # 100 ETH
    spec.process_effective_balance_updates(state)
    assert int(state.validators[index].effective_balance) == 100 * 10**9

    other = 1  # 0x00 creds keep the MinEB ceiling
    state.balances[other] = 100 * 10**9
    spec.process_effective_balance_updates(state)
    assert int(state.validators[other].effective_balance) == spec.MIN_ACTIVATION_BALANCE


@with_phases(ELECTRA)
@spec_state_test
def test_slashing_quotients(spec, state):
    assert spec.min_slashing_penalty_quotient() == spec.MIN_SLASHING_PENALTY_QUOTIENT_ELECTRA
    assert spec.whistleblower_reward_quotient() == spec.WHISTLEBLOWER_REWARD_QUOTIENT_ELECTRA
    index = 4
    balance_before = int(state.balances[index])
    spec.slash_validator(state, index)
    eff = int(state.validators[index].effective_balance)
    expected_penalty = eff // spec.MIN_SLASHING_PENALTY_QUOTIENT_ELECTRA
    assert int(state.balances[index]) == balance_before - expected_penalty


@with_phases(ELECTRA)
@spec_state_test
def test_exit_churn_balance_accumulator(spec, state):
    """compute_exit_epoch_and_update_churn spreads a large exit over epochs."""
    per_epoch = spec.get_activation_exit_churn_limit(state)
    base_epoch = spec.compute_activation_exit_epoch(spec.get_current_epoch(state))
    # small exit fits in the first epoch
    e1 = spec.compute_exit_epoch_and_update_churn(state, spec.MIN_ACTIVATION_BALANCE)
    assert e1 == base_epoch
    # an exit larger than the remaining churn pushes the epoch out
    e2 = spec.compute_exit_epoch_and_update_churn(state, per_epoch * 3)
    assert e2 > e1


@with_phases(ELECTRA)
@spec_state_test
def test_expected_withdrawals_pending_partial(spec, state):
    index = 1
    address = b"\x42" * 20
    state.validators[index].withdrawal_credentials = (
        bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX) + b"\x00" * 11 + address
    )
    amount = spec.EFFECTIVE_BALANCE_INCREMENT
    state.balances[index] = spec.MIN_ACTIVATION_BALANCE + 2 * amount
    state.validators[index].effective_balance = spec.MIN_ACTIVATION_BALANCE
    state.pending_partial_withdrawals.append(
        spec.PendingPartialWithdrawal(
            validator_index=index,
            amount=amount,
            withdrawable_epoch=spec.get_current_epoch(state),
        )
    )
    withdrawals, processed = spec.get_expected_withdrawals(state)
    assert processed == 1
    assert any(
        int(w.validator_index) == index and int(w.amount) == amount for w in withdrawals
    )


@with_phases(ELECTRA)
@spec_state_test
def test_full_block_with_pending_partial_withdrawal(spec, state):
    """End-to-end: a queued partial withdrawal pays out through a block."""
    index = 1
    address = b"\x42" * 20
    state.validators[index].withdrawal_credentials = (
        bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX) + b"\x00" * 11 + address
    )
    amount = spec.EFFECTIVE_BALANCE_INCREMENT
    state.balances[index] = spec.MIN_ACTIVATION_BALANCE + 2 * amount
    state.validators[index].effective_balance = spec.MIN_ACTIVATION_BALANCE
    state.pending_partial_withdrawals.append(
        spec.PendingPartialWithdrawal(
            validator_index=index,
            amount=amount,
            withdrawable_epoch=spec.get_current_epoch(state),
        )
    )
    balance_before = int(state.balances[index])
    block = build_empty_block_for_next_slot(spec, state)
    signed = state_transition_and_sign_block(spec, state, block)
    assert len(state.pending_partial_withdrawals) == 0
    assert int(state.balances[index]) == balance_before - amount
    yield "blocks", [signed]
    yield "post", state


@with_phases(ELECTRA)
@spec_state_test
def test_epoch_transition_runs_pending_queues(spec, state):
    """process_epoch drains pending deposits in fork order."""
    amount = spec.EFFECTIVE_BALANCE_INCREMENT
    v = state.validators[0]
    from eth_consensus_specs_tpu.utils import bls as _bls

    state.pending_deposits.append(
        spec.PendingDeposit(
            pubkey=v.pubkey,
            withdrawal_credentials=v.withdrawal_credentials,
            amount=amount,
            signature=_bls.G2_POINT_AT_INFINITY,
            slot=spec.GENESIS_SLOT,
        )
    )
    balance_before = int(state.balances[0])
    next_epoch(spec, state)
    assert int(state.balances[0]) >= balance_before + amount  # + any rewards
    assert len(state.pending_deposits) == 0
