"""deneb -> electra state upgrade (spec: specs/electra/fork.md:42-144)."""

from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.state import next_epoch
from eth_consensus_specs_tpu.utils import bls


@with_phases(["deneb"])
@spec_state_test
def test_upgrade_to_electra_basic(spec, state):
    electra = get_spec("electra", spec.preset_name)
    next_epoch(spec, state)
    post = electra.upgrade_from_parent(state)
    assert bytes(post.fork.current_version) == bytes(electra.config.ELECTRA_FORK_VERSION)
    assert int(post.deposit_requests_start_index) == electra.UNSET_DEPOSIT_REQUESTS_START_INDEX
    assert int(post.deposit_balance_to_consume) == 0
    assert int(post.exit_balance_to_consume) == electra.get_activation_exit_churn_limit(post)
    assert int(post.consolidation_balance_to_consume) == electra.get_consolidation_churn_limit(
        post
    )
    # all genesis validators are active -> no pre-activation queue entries
    assert len(post.pending_deposits) == 0
    assert len(post.pending_partial_withdrawals) == 0
    assert len(post.pending_consolidations) == 0
    assert int(post.earliest_exit_epoch) == electra.compute_activation_exit_epoch(
        electra.get_current_epoch(post)
    ) + 1 or int(post.earliest_exit_epoch) >= 1
    next_epoch(electra, post)


@with_phases(["deneb"])
@spec_state_test
def test_upgrade_to_electra_pre_activation_queue(spec, state):
    """Validators not yet active are zeroed and re-enter via pending deposits."""
    electra = get_spec("electra", spec.preset_name)
    # make validator 0 pending-activation with an eligibility epoch
    v = state.validators[0]
    v.activation_epoch = spec.FAR_FUTURE_EPOCH
    v.activation_eligibility_epoch = 1
    balance_before = int(state.balances[0])
    post = electra.upgrade_from_parent(state)
    assert int(post.balances[0]) == 0
    assert int(post.validators[0].effective_balance) == 0
    assert post.validators[0].activation_eligibility_epoch == electra.FAR_FUTURE_EPOCH
    assert len(post.pending_deposits) == 1
    pd = post.pending_deposits[0]
    assert pd.pubkey == state.validators[0].pubkey
    assert int(pd.amount) == balance_before
    assert bytes(pd.signature) == bls.G2_POINT_AT_INFINITY
    assert int(pd.slot) == electra.GENESIS_SLOT


@with_phases(["deneb"])
@spec_state_test
def test_upgrade_to_electra_exit_epoch_carryover(spec, state):
    """earliest_exit_epoch starts one past the max existing exit epoch."""
    electra = get_spec("electra", spec.preset_name)
    state.validators[3].exit_epoch = 100
    state.validators[5].exit_epoch = 200
    post = electra.upgrade_from_parent(state)
    assert int(post.earliest_exit_epoch) == 201


@with_phases(["deneb"])
@spec_state_test
def test_upgrade_to_electra_compounding_adopter(spec, state):
    """0x02-credentialed validators queue their excess balance."""
    electra = get_spec("electra", spec.preset_name)
    creds = bytes(electra.COMPOUNDING_WITHDRAWAL_PREFIX) + bytes(
        state.validators[2].withdrawal_credentials
    )[1:]
    state.validators[2].withdrawal_credentials = creds
    excess = 5_000_000_000
    state.balances[2] = int(electra.MIN_ACTIVATION_BALANCE) + excess
    post = electra.upgrade_from_parent(state)
    assert int(post.balances[2]) == electra.MIN_ACTIVATION_BALANCE
    assert len(post.pending_deposits) == 1
    assert int(post.pending_deposits[0].amount) == excess
