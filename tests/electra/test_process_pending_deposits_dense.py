"""Pending-deposit queue DENSE table: eth1-bridge transition gating,
churn boundary cases, and skipped/exiting interleavings (reference
analogue: eth2spec/test/electra/epoch_processing/pending_deposits/
test_process_pending_deposits.py — the scenarios the basic suite in
test_pending_deposits.py does not cover; spec:
specs/electra/beacon-chain.md process_pending_deposits)."""

from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.utils import bls

ELECTRA = ["electra"]


def _pd(spec, state, index: int, amount: int, slot=None):
    v = state.validators[index]
    return spec.PendingDeposit(
        pubkey=v.pubkey,
        withdrawal_credentials=v.withdrawal_credentials,
        amount=amount,
        signature=bls.G2_POINT_AT_INFINITY,
        slot=spec.GENESIS_SLOT if slot is None else slot,
    )


def _total_balance(state) -> int:
    return sum(int(b) for b in state.balances)


# == eth1-bridge transition gating =========================================


@with_phases(ELECTRA)
@spec_state_test
def test_bridge_transition_pending_blocks_requests(spec, state):
    """While eth1_deposit_index < deposit_requests_start_index, post-genesis
    deposits (i.e. from deposit requests) stay queued."""
    state.deposit_requests_start_index = int(state.eth1_deposit_index) + 10
    pd = _pd(spec, state, 0, int(spec.EFFECTIVE_BALANCE_INCREMENT), slot=1)
    state.pending_deposits.append(pd)
    before = int(state.balances[0])
    spec.process_pending_deposits(state)
    assert int(state.balances[0]) == before
    assert len(state.pending_deposits) == 1


@with_phases(ELECTRA)
@spec_state_test
def test_bridge_transition_genesis_deposits_pass(spec, state):
    """GENESIS_SLOT deposits bypass the bridge gate even mid-transition."""
    state.deposit_requests_start_index = int(state.eth1_deposit_index) + 10
    pd = _pd(spec, state, 0, int(spec.EFFECTIVE_BALANCE_INCREMENT))
    state.pending_deposits.append(pd)
    before = int(state.balances[0])
    spec.process_pending_deposits(state)
    assert int(state.balances[0]) == before + int(spec.EFFECTIVE_BALANCE_INCREMENT)
    assert len(state.pending_deposits) == 0


@with_phases(ELECTRA)
@spec_state_test
def test_bridge_transition_complete_requests_pass(spec, state):
    """Once the bridge is drained (eth1_deposit_index >= start index),
    request-era deposits process normally (up to finality)."""
    state.deposit_requests_start_index = int(state.eth1_deposit_index)
    state.finalized_checkpoint.epoch = 1
    state.slot = 2 * int(spec.SLOTS_PER_EPOCH)
    pd = _pd(spec, state, 0, int(spec.EFFECTIVE_BALANCE_INCREMENT), slot=1)
    state.pending_deposits.append(pd)
    before = int(state.balances[0])
    spec.process_pending_deposits(state)
    assert int(state.balances[0]) == before + int(spec.EFFECTIVE_BALANCE_INCREMENT)


# == churn boundaries ======================================================


@with_phases(ELECTRA)
@spec_state_test
def test_balance_exactly_equal_churn(spec, state):
    """A deposit consuming EXACTLY the available churn processes fully
    and leaves zero to consume."""
    churn = int(spec.get_activation_exit_churn_limit(state))
    state.pending_deposits.append(_pd(spec, state, 0, churn))
    spec.process_pending_deposits(state)
    assert len(state.pending_deposits) == 0
    assert int(state.deposit_balance_to_consume) == 0


@with_phases(ELECTRA)
@spec_state_test
def test_balance_one_above_churn_postponed(spec, state):
    """churn+1 cannot process this epoch; the unconsumed churn carries."""
    churn = int(spec.get_activation_exit_churn_limit(state))
    state.pending_deposits.append(_pd(spec, state, 0, churn + 1))
    before = int(state.balances[0])
    spec.process_pending_deposits(state)
    assert int(state.balances[0]) == before
    assert len(state.pending_deposits) == 1
    assert int(state.deposit_balance_to_consume) == churn


@with_phases(ELECTRA)
@spec_state_test
def test_preexisting_churn_credit_unblocks(spec, state):
    """deposit_balance_to_consume from an earlier epoch adds headroom."""
    churn = int(spec.get_activation_exit_churn_limit(state))
    state.deposit_balance_to_consume = 2
    state.pending_deposits.append(_pd(spec, state, 0, churn + 1))
    before = int(state.balances[0])
    spec.process_pending_deposits(state)
    assert int(state.balances[0]) == before + churn + 1
    assert int(state.deposit_balance_to_consume) == 0


@with_phases(ELECTRA)
@spec_state_test
def test_multiple_below_churn_all_apply(spec, state):
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    for i in (0, 1, 2):
        state.pending_deposits.append(_pd(spec, state, i, inc))
    total_before = _total_balance(state)
    spec.process_pending_deposits(state)
    assert _total_balance(state) == total_before + 3 * inc
    assert len(state.pending_deposits) == 0


@with_phases(ELECTRA)
@spec_state_test
def test_multiple_above_churn_stops_at_boundary(spec, state):
    """Processing stops at the FIRST deposit that would cross the limit;
    later deposits wait even if they individually fit."""
    churn = int(spec.get_activation_exit_churn_limit(state))
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    state.pending_deposits.append(_pd(spec, state, 0, churn))
    state.pending_deposits.append(_pd(spec, state, 1, churn))  # crosses
    state.pending_deposits.append(_pd(spec, state, 2, inc))  # would fit alone
    before_1 = int(state.balances[1])
    before_2 = int(state.balances[2])
    spec.process_pending_deposits(state)
    assert int(state.balances[1]) == before_1
    assert int(state.balances[2]) == before_2
    assert len(state.pending_deposits) == 2


# == exiting/withdrawn interleavings =======================================


def _make_exiting(spec, state, index: int):
    state.validators[index].exit_epoch = int(spec.get_current_epoch(state)) + 4
    state.validators[index].withdrawable_epoch = int(
        state.validators[index].exit_epoch
    ) + int(spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)


def _make_withdrawn(spec, state, index: int):
    state.validators[index].exit_epoch = max(int(spec.get_current_epoch(state)) - 2, 0)
    state.validators[index].withdrawable_epoch = int(spec.get_current_epoch(state))


@with_phases(ELECTRA)
@spec_state_test
def test_exiting_validator_deposit_postponed_behind_normal(spec, state):
    """A deposit for an exiting validator is postponed to the queue TAIL;
    deposits after it still process this epoch."""
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    _make_exiting(spec, state, 0)
    state.pending_deposits.append(_pd(spec, state, 0, inc))
    state.pending_deposits.append(_pd(spec, state, 1, inc))
    before_1 = int(state.balances[1])
    spec.process_pending_deposits(state)
    assert int(state.balances[1]) == before_1 + inc
    # the postponed deposit survives at the tail
    assert len(state.pending_deposits) == 1
    assert bytes(state.pending_deposits[0].pubkey) == bytes(state.validators[0].pubkey)


@with_phases(ELECTRA)
@spec_state_test
def test_multiple_exiting_all_postponed_in_order(spec, state):
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    for i in (0, 1):
        _make_exiting(spec, state, i)
        state.pending_deposits.append(_pd(spec, state, i, inc))
    spec.process_pending_deposits(state)
    assert len(state.pending_deposits) == 2
    assert bytes(state.pending_deposits[0].pubkey) == bytes(state.validators[0].pubkey)
    assert bytes(state.pending_deposits[1].pubkey) == bytes(state.validators[1].pubkey)


@with_phases(ELECTRA)
@spec_state_test
def test_mixture_skipped_and_above_churn(spec, state):
    """An exiting-validator skip does NOT consume churn; a later over-limit
    deposit still stops the sweep with the skip preserved."""
    churn = int(spec.get_activation_exit_churn_limit(state))
    _make_exiting(spec, state, 0)
    state.pending_deposits.append(_pd(spec, state, 0, churn))  # skipped
    state.pending_deposits.append(_pd(spec, state, 1, churn))  # consumes all churn
    state.pending_deposits.append(_pd(spec, state, 2, churn))  # over limit now
    before_1 = int(state.balances[1])
    spec.process_pending_deposits(state)
    assert int(state.balances[1]) == before_1 + churn
    # remaining: the over-limit deposit (head) + postponed skip (tail)
    assert len(state.pending_deposits) == 2
    assert bytes(state.pending_deposits[0].pubkey) == bytes(state.validators[2].pubkey)
    assert bytes(state.pending_deposits[1].pubkey) == bytes(state.validators[0].pubkey)


@with_phases(ELECTRA)
@spec_state_test
def test_withdrawable_validator_bypasses_churn(spec, state):
    """A fully-withdrawn validator's deposit applies without consuming
    churn — the balance can never re-activate."""
    churn = int(spec.get_activation_exit_churn_limit(state))
    _make_withdrawn(spec, state, 0)
    state.pending_deposits.append(_pd(spec, state, 0, churn * 2))
    before = int(state.balances[0])
    spec.process_pending_deposits(state)
    assert int(state.balances[0]) == before + churn * 2
    assert int(state.deposit_balance_to_consume) == 0
    assert len(state.pending_deposits) == 0


@with_phases(ELECTRA)
@spec_state_test
def test_withdrawable_then_normal_churn_intact(spec, state):
    """The churn-free withdrawn-validator application leaves the full
    budget for subsequent normal deposits."""
    churn = int(spec.get_activation_exit_churn_limit(state))
    _make_withdrawn(spec, state, 0)
    state.pending_deposits.append(_pd(spec, state, 0, churn))
    state.pending_deposits.append(_pd(spec, state, 1, churn))
    before_1 = int(state.balances[1])
    spec.process_pending_deposits(state)
    assert int(state.balances[1]) == before_1 + churn
    assert len(state.pending_deposits) == 0
