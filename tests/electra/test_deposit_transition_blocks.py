"""Eth1-bridge deposit transition in whole blocks: legacy Merkle-proof
deposits and EIP-6110 deposit requests coexisting while the bridge drains
(reference analogue: eth2spec/test/electra/sanity/blocks/
test_deposit_transition.py; spec: specs/electra/beacon-chain.md
process_operations' eth1_deposit_index_limit interlock)."""

from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.deposits import (
    build_deposit_data,
    build_deposit_proof,
)
from eth_consensus_specs_tpu.test_infra.keys import privkeys, pubkeys
from eth_consensus_specs_tpu.utils import bls

ELECTRA_ON = ["electra", "fulu"]

CREDS = lambda spec: spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11 + b"\x42" * 20  # noqa: E731


def _bridge_deposits(spec, count: int, start_key: int):
    """`count` legacy bridge deposits whose proofs all verify against the
    FINAL tree root (proofs built after every leaf is known)."""
    deposit_data_list = [
        build_deposit_data(
            spec,
            bytes(pubkeys[start_key + i]),
            privkeys[start_key + i],
            int(spec.MIN_ACTIVATION_BALANCE),
            CREDS(spec),
            signed=True,
        )
        for i in range(count)
    ]
    deposits = []
    root = None
    for i in range(count):
        proof, root = build_deposit_proof(spec, deposit_data_list, i)
        deposits.append(spec.Deposit(proof=proof, data=deposit_data_list[i]))
    return deposits, root


def _deposit_request(spec, key_index: int, index: int):
    data = build_deposit_data(
        spec,
        bytes(pubkeys[key_index]),
        privkeys[key_index],
        int(spec.MIN_ACTIVATION_BALANCE),
        CREDS(spec),
        signed=True,
    )
    return spec.DepositRequest(
        pubkey=data.pubkey,
        withdrawal_credentials=data.withdrawal_credentials,
        amount=data.amount,
        signature=data.signature,
        index=index,
    )


def _mid_transition_state(
    spec, state, bridge_pending: int, start_key: int, start_index=None
):
    """State where `bridge_pending` legacy deposits are still undrained;
    `start_index` overrides deposit_requests_start_index (default: the
    full backlog)."""
    deposits, root = _bridge_deposits(spec, bridge_pending, start_key)
    state.eth1_deposit_index = 0
    state.eth1_data.deposit_root = root
    state.eth1_data.deposit_count = bridge_pending
    state.deposit_requests_start_index = (
        bridge_pending if start_index is None else start_index
    )
    return deposits


def _apply(spec, state, deposits=(), requests=(), expect_fail=False):
    block = build_empty_block_for_next_slot(spec, state)
    for d in deposits:
        block.body.deposits.append(d)
    for r in requests:
        block.body.execution_requests.deposits.append(r)
    return state_transition_and_sign_block(
        spec, state, block, expect_fail=expect_fail
    )


@with_phases(ELECTRA_ON)
@spec_state_test
def test_transition_block_drains_bridge_deposits(spec, state):
    """Undrained legacy deposits MUST ride the block (up to the limit);
    they enter the pending queue, not the balances directly."""
    n = len(state.validators)
    deposits = _mid_transition_state(spec, state, 2, n + 1)
    queued_before = len(state.pending_deposits)
    _apply(spec, state, deposits=deposits)
    assert int(state.eth1_deposit_index) == 2
    assert len(state.pending_deposits) == queued_before + 2


@with_phases(ELECTRA_ON)
@spec_state_test
def test_transition_block_missing_bridge_deposits_invalid(spec, state):
    """While the bridge holds deposits, a block without them is invalid."""
    n = len(state.validators)
    _mid_transition_state(spec, state, 2, n + 1)
    _apply(spec, state, deposits=(), expect_fail=True)


@with_phases(ELECTRA_ON)
@spec_state_test
def test_transition_block_too_many_bridge_deposits_invalid(spec, state):
    """More deposits than the remaining bridge backlog is invalid."""
    n = len(state.validators)
    # only 2 legacy slots remain but the block carries 3
    deposits = _mid_transition_state(spec, state, 3, n + 1, start_index=2)
    _apply(spec, state, deposits=deposits, expect_fail=True)


@with_phases(ELECTRA_ON)
@spec_state_test
def test_transition_block_requests_alongside_bridge(spec, state):
    """A block may carry BOTH the remaining legacy deposits and new
    deposit requests; both funnel into the pending queue in order."""
    n = len(state.validators)
    deposits = _mid_transition_state(spec, state, 1, n + 1)
    request = _deposit_request(spec, n + 5, 1)
    queued_before = len(state.pending_deposits)
    _apply(spec, state, deposits=deposits, requests=[request])
    assert len(state.pending_deposits) == queued_before + 2
    # bridge deposit first, request after
    assert bytes(state.pending_deposits[-2].pubkey) == bytes(pubkeys[n + 1])
    assert bytes(state.pending_deposits[-1].pubkey) == bytes(pubkeys[n + 5])


@with_phases(ELECTRA_ON)
@spec_state_test
def test_post_transition_requests_only(spec, state):
    """Bridge fully drained: blocks carry no legacy deposits and requests
    flow through alone."""
    n = len(state.validators)
    request = _deposit_request(spec, n + 7, 0)
    queued_before = len(state.pending_deposits)
    _apply(spec, state, requests=[request])
    assert len(state.pending_deposits) == queued_before + 1


@with_phases(ELECTRA_ON)
@spec_state_test
def test_post_transition_stray_bridge_deposit_invalid(spec, state):
    """After the bridge drained, a legacy deposit has no slot to fill —
    the per-block expected count is zero, so including one is invalid."""
    n = len(state.validators)
    deposits, _ = _bridge_deposits(spec, 1, n + 9)
    # state believes the bridge is fully consumed
    _apply(spec, state, deposits=deposits, expect_fail=True)


@with_phases(ELECTRA_ON)
@spec_state_test
def test_transition_same_pubkey_bridge_and_request(spec, state):
    """The same NEW pubkey via the bridge and a request in one block:
    both queue (dedup happens at apply time)."""
    n = len(state.validators)
    deposits = _mid_transition_state(spec, state, 1, n + 1)
    request = _deposit_request(spec, n + 1, 1)  # same key as the bridge deposit
    queued_before = len(state.pending_deposits)
    _apply(spec, state, deposits=deposits, requests=[request])
    assert len(state.pending_deposits) == queued_before + 2


@with_phases(ELECTRA_ON)
@spec_state_test
def test_eth1_vote_freezes_after_bridge_drained(spec, state):
    """[EIP-6110] eth1 polling ends with the bridge: the vote returns the
    state's own eth1_data verbatim even when a candidate chain with a
    DIFFERENT winning vote is available."""
    from ..phase0.test_eth1_vote import _candidate_chain

    chain = _candidate_chain(spec, state, 4)
    live_vote = spec.get_eth1_data(chain[-1])
    assert live_vote != state.eth1_data  # the chain would win if polled

    state.deposit_requests_start_index = int(state.eth1_deposit_index)  # drained
    assert spec.get_eth1_vote(state, chain) == state.eth1_data

    # mid-transition the normal voting path still tallies the chain
    state.deposit_requests_start_index = int(state.eth1_deposit_index) + 4
    assert spec.get_eth1_vote(state, chain) == live_vote
