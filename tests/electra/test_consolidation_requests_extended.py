"""Consolidation-request ignore table — EL-triggered requests never fail a
block; every invalid condition silently leaves the state unchanged
(spec: specs/electra/beacon-chain.md process_consolidation_request;
reference analogue: test/electra/block_processing/
test_process_consolidation_request.py)."""

from eth_consensus_specs_tpu import ssz
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.keys import pubkey
from eth_consensus_specs_tpu.test_infra.state import next_slots

ELECTRA = ["electra"]


def _compounding_creds(spec, state, index: int, tag: int):
    address = bytes([0x60 + tag]) * 20
    state.validators[index].withdrawal_credentials = (
        spec.COMPOUNDING_WITHDRAWAL_PREFIX + b"\x00" * 11 + address
    )
    return address


def _eth1_creds(spec, state, index: int, tag: int):
    address = bytes([0x60 + tag]) * 20
    state.validators[index].withdrawal_credentials = (
        spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX + b"\x00" * 11 + address
    )
    return address


def _age(spec, state):
    next_slots(
        spec, state, int(spec.config.SHARD_COMMITTEE_PERIOD) * int(spec.SLOTS_PER_EPOCH)
    )


def _request(spec, state, src: int, dst: int, source_address=None):
    return spec.ConsolidationRequest(
        source_address=(
            source_address
            if source_address is not None
            else bytes(state.validators[src].withdrawal_credentials)[12:]
        ),
        source_pubkey=state.validators[src].pubkey,
        target_pubkey=state.validators[dst].pubkey,
    )


def _assert_ignored(spec, state, req):
    pre = bytes(ssz.hash_tree_root(state))
    spec.process_consolidation_request(state, req)
    assert bytes(ssz.hash_tree_root(state)) == pre


@with_phases(ELECTRA)
@spec_state_test
def test_consolidation_enqueues(spec, state):
    _compounding_creds(spec, state, 1, 1)
    _compounding_creds(spec, state, 2, 2)
    _age(spec, state)
    req = _request(spec, state, 1, 2)
    spec.process_consolidation_request(state, req)
    assert len(state.pending_consolidations) == 1
    assert state.validators[1].exit_epoch != spec.FAR_FUTURE_EPOCH


@with_phases(ELECTRA)
@spec_state_test
def test_consolidation_ignored_same_source_target_noncompounding(spec, state):
    """source == target with eth1 creds is a disguised exit — ignored
    (with compounding creds it is a switch request instead)."""
    _eth1_creds(spec, state, 1, 1)
    _age(spec, state)
    req = _request(spec, state, 1, 1)
    pre_pending = len(state.pending_consolidations)
    spec.process_consolidation_request(state, req)
    assert len(state.pending_consolidations) == pre_pending
    assert state.validators[1].exit_epoch == spec.FAR_FUTURE_EPOCH


@with_phases(ELECTRA)
@spec_state_test
def test_consolidation_ignored_unknown_source(spec, state):
    _compounding_creds(spec, state, 2, 2)
    _age(spec, state)
    req = spec.ConsolidationRequest(
        source_address=b"\x61" * 20,
        source_pubkey=pubkey(len(state.validators) + 7),  # no such validator
        target_pubkey=state.validators[2].pubkey,
    )
    _assert_ignored(spec, state, req)


@with_phases(ELECTRA)
@spec_state_test
def test_consolidation_ignored_unknown_target(spec, state):
    _compounding_creds(spec, state, 1, 1)
    _age(spec, state)
    req = spec.ConsolidationRequest(
        source_address=bytes(state.validators[1].withdrawal_credentials)[12:],
        source_pubkey=state.validators[1].pubkey,
        target_pubkey=pubkey(len(state.validators) + 7),
    )
    _assert_ignored(spec, state, req)


@with_phases(ELECTRA)
@spec_state_test
def test_consolidation_ignored_wrong_source_address(spec, state):
    _compounding_creds(spec, state, 1, 1)
    _compounding_creds(spec, state, 2, 2)
    _age(spec, state)
    req = _request(spec, state, 1, 2, source_address=b"\x99" * 20)
    _assert_ignored(spec, state, req)


@with_phases(ELECTRA)
@spec_state_test
def test_consolidation_ignored_source_without_execution_creds(spec, state):
    # source keeps its default BLS credentials
    _compounding_creds(spec, state, 2, 2)
    _age(spec, state)
    req = _request(spec, state, 1, 2, source_address=b"\x61" * 20)
    _assert_ignored(spec, state, req)


@with_phases(ELECTRA)
@spec_state_test
def test_consolidation_ignored_target_not_compounding(spec, state):
    _compounding_creds(spec, state, 1, 1)
    _eth1_creds(spec, state, 2, 2)
    _age(spec, state)
    req = _request(spec, state, 1, 2)
    _assert_ignored(spec, state, req)


@with_phases(ELECTRA)
@spec_state_test
def test_consolidation_ignored_inactive_source(spec, state):
    _compounding_creds(spec, state, 1, 1)
    _compounding_creds(spec, state, 2, 2)
    _age(spec, state)
    state.validators[1].activation_epoch = spec.FAR_FUTURE_EPOCH
    req = _request(spec, state, 1, 2)
    _assert_ignored(spec, state, req)


@with_phases(ELECTRA)
@spec_state_test
def test_consolidation_ignored_inactive_target(spec, state):
    _compounding_creds(spec, state, 1, 1)
    _compounding_creds(spec, state, 2, 2)
    _age(spec, state)
    state.validators[2].activation_epoch = spec.FAR_FUTURE_EPOCH
    req = _request(spec, state, 1, 2)
    _assert_ignored(spec, state, req)


@with_phases(ELECTRA)
@spec_state_test
def test_consolidation_ignored_exiting_source(spec, state):
    _compounding_creds(spec, state, 1, 1)
    _compounding_creds(spec, state, 2, 2)
    _age(spec, state)
    state.validators[1].exit_epoch = spec.get_current_epoch(state) + 10
    req = _request(spec, state, 1, 2)
    _assert_ignored(spec, state, req)


@with_phases(ELECTRA)
@spec_state_test
def test_consolidation_ignored_exiting_target(spec, state):
    _compounding_creds(spec, state, 1, 1)
    _compounding_creds(spec, state, 2, 2)
    _age(spec, state)
    state.validators[2].exit_epoch = spec.get_current_epoch(state) + 10
    req = _request(spec, state, 1, 2)
    _assert_ignored(spec, state, req)


@with_phases(ELECTRA)
@spec_state_test
def test_consolidation_ignored_queue_full(spec, state):
    _compounding_creds(spec, state, 1, 1)
    _compounding_creds(spec, state, 2, 2)
    _age(spec, state)
    filler = spec.PendingConsolidation(source_index=3, target_index=4)
    while len(state.pending_consolidations) < spec.PENDING_CONSOLIDATIONS_LIMIT:
        state.pending_consolidations.append(filler)
    req = _request(spec, state, 1, 2)
    pre = len(state.pending_consolidations)
    spec.process_consolidation_request(state, req)
    assert len(state.pending_consolidations) == pre
    assert state.validators[1].exit_epoch == spec.FAR_FUTURE_EPOCH


@with_phases(ELECTRA)
@spec_state_test
def test_switch_to_compounding_via_self_request(spec, state):
    """source == target with eth1 creds on source + compounding request."""
    _eth1_creds(spec, state, 5, 5)
    _age(spec, state)
    req = _request(spec, state, 5, 5)
    spec.process_consolidation_request(state, req)
    assert bytes(state.validators[5].withdrawal_credentials)[:1] == bytes(
        spec.COMPOUNDING_WITHDRAWAL_PREFIX
    )
    assert len(state.pending_consolidations) == 0


@with_phases(ELECTRA)
@spec_state_test
def test_switch_request_wrong_address_ignored(spec, state):
    _eth1_creds(spec, state, 5, 5)
    _age(spec, state)
    req = _request(spec, state, 5, 5, source_address=b"\x98" * 20)
    _assert_ignored(spec, state, req)


@with_phases(ELECTRA)
@spec_state_test
def test_consolidation_source_exit_epoch_set_by_churn(spec, state):
    _compounding_creds(spec, state, 1, 1)
    _compounding_creds(spec, state, 2, 2)
    _age(spec, state)
    req = _request(spec, state, 1, 2)
    spec.process_consolidation_request(state, req)
    exit_epoch = int(state.validators[1].exit_epoch)
    assert exit_epoch >= int(
        spec.compute_activation_exit_epoch(spec.get_current_epoch(state))
    )
    assert int(state.validators[1].withdrawable_epoch) == exit_epoch + int(
        spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    )
