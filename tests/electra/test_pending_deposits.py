"""Pending-deposit queue processing (spec:
specs/electra/beacon-chain.md:922-1020; reference analogue:
test/electra/epoch_processing/test_process_pending_deposits.py)."""

from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.keys import pubkey
from eth_consensus_specs_tpu.utils import bls

ELECTRA = ["electra"]


def _pending_deposit_for(spec, state, index: int, amount: int):
    v = state.validators[index]
    return spec.PendingDeposit(
        pubkey=v.pubkey,
        withdrawal_credentials=v.withdrawal_credentials,
        amount=amount,
        signature=bls.G2_POINT_AT_INFINITY,
        slot=spec.GENESIS_SLOT,
    )


@with_phases(ELECTRA)
@spec_state_test
def test_pending_deposit_applied_to_existing_validator(spec, state):
    amount = spec.EFFECTIVE_BALANCE_INCREMENT
    state.pending_deposits.append(_pending_deposit_for(spec, state, 0, amount))
    balance_before = int(state.balances[0])
    spec.process_pending_deposits(state)
    assert int(state.balances[0]) == balance_before + amount
    assert len(state.pending_deposits) == 0
    assert int(state.deposit_balance_to_consume) == 0


@with_phases(ELECTRA)
@spec_state_test
def test_pending_deposit_not_finalized_waits(spec, state):
    """A deposit with slot beyond the finalized slot stays queued."""
    amount = spec.EFFECTIVE_BALANCE_INCREMENT
    pd = _pending_deposit_for(spec, state, 0, amount)
    pd.slot = 10_000  # far beyond finalized
    state.pending_deposits.append(pd)
    balance_before = int(state.balances[0])
    spec.process_pending_deposits(state)
    assert int(state.balances[0]) == balance_before
    assert len(state.pending_deposits) == 1


@with_phases(ELECTRA)
@spec_state_test
def test_pending_deposit_exited_validator_postponed(spec, state):
    amount = spec.EFFECTIVE_BALANCE_INCREMENT
    exit_epoch = spec.get_current_epoch(state) + 10
    state.validators[0].exit_epoch = exit_epoch
    state.validators[0].withdrawable_epoch = exit_epoch + 100
    state.pending_deposits.append(_pending_deposit_for(spec, state, 0, amount))
    balance_before = int(state.balances[0])
    spec.process_pending_deposits(state)
    # postponed: still queued, balance untouched
    assert int(state.balances[0]) == balance_before
    assert len(state.pending_deposits) == 1


@with_phases(ELECTRA)
@spec_state_test
def test_pending_deposit_withdrawn_validator_applied_without_churn(spec, state):
    amount = spec.EFFECTIVE_BALANCE_INCREMENT
    state.validators[0].exit_epoch = 0
    state.validators[0].withdrawable_epoch = 0
    state.pending_deposits.append(_pending_deposit_for(spec, state, 0, amount))
    balance_before = int(state.balances[0])
    spec.process_pending_deposits(state)
    assert int(state.balances[0]) == balance_before + amount
    assert len(state.pending_deposits) == 0


@with_phases(ELECTRA)
@spec_state_test
def test_pending_deposit_churn_limit_carries_over(spec, state):
    """Deposits beyond the activation-exit churn stay queued and the unused
    allowance accumulates in deposit_balance_to_consume."""
    churn = spec.get_activation_exit_churn_limit(state)
    big = churn + spec.EFFECTIVE_BALANCE_INCREMENT
    state.pending_deposits.append(_pending_deposit_for(spec, state, 0, big))
    balance_before = int(state.balances[0])
    spec.process_pending_deposits(state)
    assert int(state.balances[0]) == balance_before  # did not fit this epoch
    assert len(state.pending_deposits) == 1
    assert int(state.deposit_balance_to_consume) == churn
    # next epoch the accumulated churn covers it
    spec.process_pending_deposits(state)
    assert int(state.balances[0]) == balance_before + big
    assert len(state.pending_deposits) == 0
    assert int(state.deposit_balance_to_consume) == 0


@with_phases(ELECTRA)
@spec_state_test
def test_pending_deposit_max_per_epoch(spec, state):
    amount = spec.EFFECTIVE_BALANCE_INCREMENT
    count = spec.MAX_PENDING_DEPOSITS_PER_EPOCH + 2
    for _ in range(count):
        state.pending_deposits.append(_pending_deposit_for(spec, state, 0, amount))
    spec.process_pending_deposits(state)
    assert len(state.pending_deposits) == 2


@with_phases(ELECTRA)
@spec_state_test
def test_pending_deposit_new_validator_infinity_signature(spec, state):
    """A queued transfer (infinity signature) for an unknown pubkey fails
    proof-of-possession and is dropped without creating a validator."""
    n_before = len(state.validators)
    new_pub = pubkey(n_before + 7)
    pd = spec.PendingDeposit(
        pubkey=new_pub,
        withdrawal_credentials=b"\x01" + b"\x00" * 11 + b"\x22" * 20,
        amount=spec.MIN_ACTIVATION_BALANCE,
        signature=bls.G2_POINT_AT_INFINITY,
        slot=spec.GENESIS_SLOT,
    )
    state.pending_deposits.append(pd)
    prior = bls.bls_active
    bls.bls_active = True  # signature check must actually run
    try:
        spec.process_pending_deposits(state)
    finally:
        bls.bls_active = prior
    assert len(state.validators) == n_before
    assert len(state.pending_deposits) == 0
