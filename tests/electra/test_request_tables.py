"""Dense tables for the electra execution-layer request operations —
withdrawal requests (EIP-7002), consolidation requests (EIP-7251),
deposit requests (EIP-6110) (reference analogue:
test/electra/block_processing/test_process_withdrawal_request.py ~30
variants, test_process_consolidation_request.py ~40 variants)."""

from eth_consensus_specs_tpu.test_infra.context import (
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.keys import pubkeys
from eth_consensus_specs_tpu.test_infra.state import next_slots

ELECTRA_FORKS = ["electra", "fulu"]


def _mature(spec, state):
    next_slots(
        spec, state, int(spec.config.SHARD_COMMITTEE_PERIOD) * int(spec.SLOTS_PER_EPOCH)
    )


def _eth1_creds(spec, state, idx: int, address=b"\x44" * 20, compounding=False):
    prefix = (
        spec.COMPOUNDING_WITHDRAWAL_PREFIX if compounding else spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX
    )
    state.validators[idx].withdrawal_credentials = bytes(prefix) + b"\x00" * 11 + address


def _withdrawal_request(spec, state, idx: int, amount=None, address=b"\x44" * 20):
    return spec.WithdrawalRequest(
        source_address=address,
        validator_pubkey=state.validators[idx].pubkey,
        amount=spec.FULL_EXIT_REQUEST_AMOUNT if amount is None else amount,
    )


# == withdrawal requests (EIP-7002) ========================================


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_withdrawal_request_full_exit(spec, state):
    _mature(spec, state)
    _eth1_creds(spec, state, 3)
    req = _withdrawal_request(spec, state, 3)
    spec.process_withdrawal_request(state, req)
    assert int(state.validators[3].exit_epoch) != int(spec.FAR_FUTURE_EPOCH)


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_withdrawal_request_wrong_source_address_noop(spec, state):
    _mature(spec, state)
    _eth1_creds(spec, state, 3)
    req = _withdrawal_request(spec, state, 3, address=b"\x55" * 20)
    spec.process_withdrawal_request(state, req)  # EL requests no-op, not assert
    assert int(state.validators[3].exit_epoch) == int(spec.FAR_FUTURE_EPOCH)


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_withdrawal_request_unknown_pubkey_noop(spec, state):
    _mature(spec, state)
    _eth1_creds(spec, state, 3)
    req = spec.WithdrawalRequest(
        source_address=b"\x44" * 20,
        validator_pubkey=pubkeys[len(state.validators) + 10],
        amount=spec.FULL_EXIT_REQUEST_AMOUNT,
    )
    pre = state.copy()
    spec.process_withdrawal_request(state, req)
    assert state.validators == pre.validators


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_withdrawal_request_not_active_long_enough_noop(spec, state):
    _eth1_creds(spec, state, 3)  # NO maturity advance
    req = _withdrawal_request(spec, state, 3)
    spec.process_withdrawal_request(state, req)
    assert int(state.validators[3].exit_epoch) == int(spec.FAR_FUTURE_EPOCH)


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_withdrawal_request_already_exiting_noop(spec, state):
    _mature(spec, state)
    _eth1_creds(spec, state, 3)
    spec.initiate_validator_exit(state, 3)
    exit_epoch = int(state.validators[3].exit_epoch)
    req = _withdrawal_request(spec, state, 3)
    spec.process_withdrawal_request(state, req)
    assert int(state.validators[3].exit_epoch) == exit_epoch


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_partial_withdrawal_request_compounding(spec, state):
    _mature(spec, state)
    _eth1_creds(spec, state, 3, compounding=True)
    state.balances[3] = int(spec.MIN_ACTIVATION_BALANCE) + 2_000_000
    req = _withdrawal_request(spec, state, 3, amount=1_000_000)
    pre_len = len(state.pending_partial_withdrawals)
    spec.process_withdrawal_request(state, req)
    assert len(state.pending_partial_withdrawals) == pre_len + 1


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_partial_withdrawal_request_non_compounding_noop(spec, state):
    _mature(spec, state)
    _eth1_creds(spec, state, 3, compounding=False)
    state.balances[3] = int(spec.MIN_ACTIVATION_BALANCE) + 2_000_000
    req = _withdrawal_request(spec, state, 3, amount=1_000_000)
    pre_len = len(state.pending_partial_withdrawals)
    spec.process_withdrawal_request(state, req)
    assert len(state.pending_partial_withdrawals) == pre_len


# == consolidation requests (EIP-7251) =====================================


def _consolidation(spec, state, src: int, dst: int, address=None):
    addr = (
        bytes(state.validators[src].withdrawal_credentials[12:])
        if address is None
        else address
    )
    return spec.ConsolidationRequest(
        source_address=addr,
        source_pubkey=state.validators[src].pubkey,
        target_pubkey=state.validators[dst].pubkey,
    )


def _consolidation_ready(spec, state, src=1, dst=2):
    for idx in (src, dst):
        _eth1_creds(spec, state, idx, address=bytes([0x30 + idx]) * 20, compounding=True)
    _mature(spec, state)


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_consolidation_basic(spec, state):
    _consolidation_ready(spec, state)
    req = _consolidation(spec, state, 1, 2)
    pre_len = len(state.pending_consolidations)
    spec.process_consolidation_request(state, req)
    assert len(state.pending_consolidations) == pre_len + 1
    assert int(state.validators[1].exit_epoch) != int(spec.FAR_FUTURE_EPOCH)


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_consolidation_self_is_noop(spec, state):
    _consolidation_ready(spec, state)
    req = _consolidation(spec, state, 1, 1)
    pre_len = len(state.pending_consolidations)
    spec.process_consolidation_request(state, req)
    assert len(state.pending_consolidations) == pre_len


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_consolidation_wrong_source_address_noop(spec, state):
    _consolidation_ready(spec, state)
    req = _consolidation(spec, state, 1, 2, address=b"\x77" * 20)
    pre_len = len(state.pending_consolidations)
    spec.process_consolidation_request(state, req)
    assert len(state.pending_consolidations) == pre_len


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_consolidation_target_without_compounding_noop(spec, state):
    _consolidation_ready(spec, state)
    _eth1_creds(spec, state, 2, address=b"\x32" * 20, compounding=False)
    req = _consolidation(spec, state, 1, 2)
    pre_len = len(state.pending_consolidations)
    spec.process_consolidation_request(state, req)
    assert len(state.pending_consolidations) == pre_len


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_consolidation_exiting_source_noop(spec, state):
    _consolidation_ready(spec, state)
    spec.initiate_validator_exit(state, 1)
    req = _consolidation(spec, state, 1, 2)
    pre_len = len(state.pending_consolidations)
    spec.process_consolidation_request(state, req)
    assert len(state.pending_consolidations) == pre_len


# == deposit requests (EIP-6110) ===========================================


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_deposit_request_appends_pending(spec, state):
    req = spec.DepositRequest(
        pubkey=pubkeys[len(state.validators)],
        withdrawal_credentials=b"\x00" * 32,
        amount=spec.MIN_ACTIVATION_BALANCE,
        signature=b"\x00" * 96,
        index=0,
    )
    pre_len = len(state.pending_deposits)
    spec.process_deposit_request(state, req)
    assert len(state.pending_deposits) == pre_len + 1


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_deposit_request_sets_start_index(spec, state):
    assert int(state.deposit_requests_start_index) == int(
        spec.UNSET_DEPOSIT_REQUESTS_START_INDEX
    )
    req = spec.DepositRequest(
        pubkey=pubkeys[0],
        withdrawal_credentials=b"\x00" * 32,
        amount=spec.MIN_ACTIVATION_BALANCE,
        signature=b"\x00" * 96,
        index=7,
    )
    spec.process_deposit_request(state, req)
    assert int(state.deposit_requests_start_index) == 7


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_deposit_request_topup_existing_validator(spec, state):
    req = spec.DepositRequest(
        pubkey=state.validators[0].pubkey,
        withdrawal_credentials=b"\x00" * 32,
        amount=1_000_000,
        signature=b"\x00" * 96,
        index=0,
    )
    pre_len = len(state.pending_deposits)
    spec.process_deposit_request(state, req)
    # top-ups also ride the pending queue post-electra
    assert len(state.pending_deposits) == pre_len + 1
