"""Dense electra process_withdrawals suite: the pending-partial-withdrawal
queue drain interacting with the capella sweep (reference analogue:
test/electra/block_processing/test_process_withdrawals.py — the 27-variant
EIP-7251 file: skipped-vs-effective queue entries, per-sweep caps,
compounding boundary arithmetic, same-validator double drains).

Spec: specs/electra/beacon-chain.md get_expected_withdrawals — pending
partials are consumed FIRST (skippable per-entry), then the sweep runs on
balances net of what the queue already withdrew."""

from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.execution_payload import (
    build_empty_execution_payload,
)
from eth_consensus_specs_tpu.test_infra.state import next_slot
from eth_consensus_specs_tpu.test_infra.withdrawals import (
    run_withdrawals_processing,
    set_compounding_withdrawal_credential_with_balance,
    set_validator_fully_withdrawable,
    set_validator_partially_withdrawable,
)

ELECTRA_FORKS = ["electra", "fulu"]
GWEI = 1_000_000_000


def _queue(spec, state, index, amount, epochs_ahead=0):
    state.pending_partial_withdrawals.append(
        spec.PendingPartialWithdrawal(
            validator_index=index,
            amount=amount,
            withdrawable_epoch=int(spec.get_current_epoch(state)) + epochs_ahead,
        )
    )


def _compounding_with_excess(spec, state, index, excess):
    cap = int(spec.MIN_ACTIVATION_BALANCE)
    set_compounding_withdrawal_credential_with_balance(
        spec, state, index, balance=cap + excess, effective_balance=cap
    )


def _run(spec, state, valid=True):
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    for _ in run_withdrawals_processing(spec, state, payload, valid=valid):
        pass
    return payload


# ------------------------------------------------------------- queue drain


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_pending_withdrawal_effective(spec, state):
    _compounding_with_excess(spec, state, 1, 3 * GWEI)
    _queue(spec, state, 1, 2 * GWEI)
    payload = _run(spec, state)
    drained = [w for w in payload.withdrawals if int(w.validator_index) == 1]
    assert len(drained) == 1 and int(drained[0].amount) == 2 * GWEI
    assert len(state.pending_partial_withdrawals) == 0


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_pending_withdrawal_next_epoch_not_drained(spec, state):
    _compounding_with_excess(spec, state, 1, 3 * GWEI)
    _queue(spec, state, 1, 2 * GWEI, epochs_ahead=2)
    payload = _run(spec, state)
    assert not any(int(w.validator_index) == 1 for w in payload.withdrawals)
    assert len(state.pending_partial_withdrawals) == 1


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_pending_withdrawal_exiting_validator_skipped(spec, state):
    _compounding_with_excess(spec, state, 1, 3 * GWEI)
    _queue(spec, state, 1, 2 * GWEI)
    state.validators[1].exit_epoch = int(spec.get_current_epoch(state)) + 5
    payload = _run(spec, state)
    # entry is consumed (popped from the queue) but yields no withdrawal
    assert not any(int(w.validator_index) == 1 for w in payload.withdrawals)
    assert len(state.pending_partial_withdrawals) == 0


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_pending_withdrawal_low_effective_balance_skipped(spec, state):
    _compounding_with_excess(spec, state, 1, 3 * GWEI)
    state.validators[1].effective_balance = (
        int(spec.MIN_ACTIVATION_BALANCE) - int(spec.EFFECTIVE_BALANCE_INCREMENT)
    )
    _queue(spec, state, 1, 2 * GWEI)
    payload = _run(spec, state)
    assert not any(int(w.validator_index) == 1 for w in payload.withdrawals)
    assert len(state.pending_partial_withdrawals) == 0


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_pending_withdrawal_no_excess_balance_skipped(spec, state):
    _compounding_with_excess(spec, state, 1, 0)
    _queue(spec, state, 1, 2 * GWEI)
    payload = _run(spec, state)
    assert not any(int(w.validator_index) == 1 for w in payload.withdrawals)
    assert len(state.pending_partial_withdrawals) == 0


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_pending_one_skipped_one_effective(spec, state):
    _compounding_with_excess(spec, state, 1, 0)          # will be skipped
    _compounding_with_excess(spec, state, 2, 3 * GWEI)   # will drain
    _queue(spec, state, 1, GWEI)
    _queue(spec, state, 2, GWEI)
    payload = _run(spec, state)
    assert not any(int(w.validator_index) == 1 for w in payload.withdrawals)
    assert any(int(w.validator_index) == 2 for w in payload.withdrawals)
    assert len(state.pending_partial_withdrawals) == 0


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_pending_withdrawals_at_sweep_cap(spec, state):
    cap = int(spec.MAX_PENDING_PARTIALS_PER_WITHDRAWALS_SWEEP)
    for i in range(cap + 1):
        _compounding_with_excess(spec, state, i, 3 * GWEI)
        _queue(spec, state, i, GWEI)
    payload = _run(spec, state)
    queue_drains = [
        w for w in payload.withdrawals if int(w.validator_index) <= cap
    ]
    # only `cap` of the cap+1 queued entries drain this slot
    assert len(queue_drains) == cap
    assert len(state.pending_partial_withdrawals) == 1


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_pending_two_partials_same_validator_share_balance(spec, state):
    """Second queue entry for the same validator sees the balance NET of the
    first drain (total_withdrawn accounting)."""
    _compounding_with_excess(spec, state, 1, 3 * GWEI)
    _queue(spec, state, 1, 2 * GWEI)
    _queue(spec, state, 1, 2 * GWEI)
    payload = _run(spec, state)
    drained = [w for w in payload.withdrawals if int(w.validator_index) == 1]
    assert [int(w.amount) for w in drained] == [2 * GWEI, GWEI]
    assert len(state.pending_partial_withdrawals) == 0


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_pending_second_partial_same_validator_starved(spec, state):
    _compounding_with_excess(spec, state, 1, 2 * GWEI)
    _queue(spec, state, 1, 2 * GWEI)
    _queue(spec, state, 1, 2 * GWEI)
    payload = _run(spec, state)
    drained = [w for w in payload.withdrawals if int(w.validator_index) == 1]
    # first takes the whole excess; second finds no excess and is skipped
    assert [int(w.amount) for w in drained] == [2 * GWEI]


# ----------------------------------------------------- queue + sweep on top


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_pending_then_ineffective_sweep_same_validator(spec, state):
    """Queue drains the excess; the sweep then finds the SAME validator no
    longer partially withdrawable (balance net of queue = cap)."""
    cap = int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA)
    set_compounding_withdrawal_credential_with_balance(
        spec, state, 1, balance=cap + 2 * GWEI, effective_balance=cap
    )
    _queue(spec, state, 1, 2 * GWEI)
    payload = _run(spec, state)
    drains = [w for w in payload.withdrawals if int(w.validator_index) == 1]
    assert len(drains) == 1  # queue drain only, no sweep duplicate


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_pending_then_effective_sweep_same_validator(spec, state):
    """Excess larger than the queued amount: queue drains its part, the
    sweep withdraws the remainder above the compounding cap."""
    cap = int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA)
    set_compounding_withdrawal_credential_with_balance(
        spec, state, 1, balance=cap + 5 * GWEI, effective_balance=cap
    )
    _queue(spec, state, 1, 2 * GWEI)
    payload = _run(spec, state)
    drains = [w for w in payload.withdrawals if int(w.validator_index) == 1]
    assert len(drains) == 2
    assert int(drains[1].amount) == 3 * GWEI


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_pending_with_sweep_different_validator(spec, state):
    _compounding_with_excess(spec, state, 1, 3 * GWEI)
    _queue(spec, state, 1, 2 * GWEI)
    set_validator_partially_withdrawable(spec, state, 2)
    payload = _run(spec, state)
    assert any(int(w.validator_index) == 1 for w in payload.withdrawals)
    assert any(int(w.validator_index) == 2 for w in payload.withdrawals)


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_pending_mixed_with_fully_withdrawable_sweep(spec, state):
    _compounding_with_excess(spec, state, 1, 3 * GWEI)
    _queue(spec, state, 1, 2 * GWEI)
    set_validator_fully_withdrawable(spec, state, 3)
    pre_balance = int(state.balances[3])
    payload = _run(spec, state)
    assert any(int(w.validator_index) == 1 for w in payload.withdrawals)
    full = [w for w in payload.withdrawals if int(w.validator_index) == 3]
    assert len(full) == 1 and int(full[0].amount) == pre_balance
    # full withdrawal zeroes the balance
    assert int(state.balances[3]) == 0


# ------------------------------------------- compounding boundary arithmetic


def _boundary_case(delta: int, expect_partial: bool):
    from eth_consensus_specs_tpu.test_infra.template import instantiate  # noqa: F401

    @with_phases(ELECTRA_FORKS)
    @spec_state_test
    def case(spec, state):
        cap = int(spec.MAX_EFFECTIVE_BALANCE_ELECTRA)
        set_compounding_withdrawal_credential_with_balance(
            spec, state, 1, balance=cap + delta, effective_balance=cap
        )
        is_partial = spec.is_partially_withdrawable_validator(
            state.validators[1], state.balances[1]
        )
        assert is_partial == expect_partial
        payload = _run(spec, state)
        swept = [w for w in payload.withdrawals if int(w.validator_index) == 1]
        assert (len(swept) == 1) == expect_partial

    name = f"test_compounding_boundary_{'plus' if delta >= 0 else 'minus'}_{abs(delta)}"
    return case, name


from eth_consensus_specs_tpu.test_infra.template import instantiate  # noqa: E402

for _delta, _expect in ((1, True), (0, False), (-1, False)):
    instantiate(_boundary_case, _delta, _expect)


# ----------------------------------------------------------------- invalid


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_invalid_pending_drain_missing_from_payload(spec, state):
    _compounding_with_excess(spec, state, 1, 3 * GWEI)
    _queue(spec, state, 1, 2 * GWEI)
    next_slot(spec, state)
    payload = build_empty_execution_payload(spec, state)
    payload.withdrawals = []
    for _ in run_withdrawals_processing(spec, state, payload, valid=False):
        pass
