"""Consolidation churn-accounting suite, electra+ (reference analogue:
test/electra/block_processing/test_process_consolidation_request.py —
the churn-arithmetic families: current/new consolidation epoch,
preexisting churn, multi-epoch spillover, and the switch-to-compounding
excess-queueing flows).

Spec: specs/electra/beacon-chain.md compute_consolidation_epoch_and_update_churn
— consolidations consume a per-epoch balance budget
(get_consolidation_churn_limit); oversize balances push the exit epoch out
by whole epochs of budget."""

from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.state import next_slots

ELECTRA_FORKS = ["electra", "fulu"]
GWEI = 1_000_000_000


def _mature(spec, state):
    state.slot = int(spec.config.SHARD_COMMITTEE_PERIOD) * int(spec.SLOTS_PER_EPOCH)


def _compounding(spec, state, index, tag, balance=None):
    address = bytes([0x70 + tag]) * 20
    state.validators[index].withdrawal_credentials = (
        bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX) + b"\x00" * 11 + address
    )
    if balance is not None:
        state.validators[index].effective_balance = balance
        state.balances[index] = balance
    return address


def _eth1(spec, state, index, tag):
    address = bytes([0x80 + tag]) * 20
    state.validators[index].withdrawal_credentials = (
        bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX) + b"\x00" * 11 + address
    )
    return address


def _request(spec, state, src, dst):
    return spec.ConsolidationRequest(
        source_address=bytes(state.validators[src].withdrawal_credentials)[12:],
        source_pubkey=state.validators[src].pubkey,
        target_pubkey=state.validators[dst].pubkey,
    )


def _consolidate(spec, state, src=1, dst=2, src_balance=None):
    _mature(spec, state)
    _compounding(spec, state, src, src, balance=src_balance)
    _compounding(spec, state, dst, dst)
    req = _request(spec, state, src, dst)
    spec.process_consolidation_request(state, req)
    return state.validators[src]


# ----------------------------------------------------------- churn budget


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_consolidation_sets_earliest_epoch_floor(spec, state):
    source = _consolidate(spec, state)
    floor = int(spec.compute_activation_exit_epoch(spec.get_current_epoch(state)))
    assert int(source.exit_epoch) >= floor
    assert int(state.earliest_consolidation_epoch) == int(source.exit_epoch)
    assert int(source.withdrawable_epoch) == int(source.exit_epoch) + int(
        spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    )


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_consolidation_consumes_budget(spec, state):
    limit = int(spec.get_consolidation_churn_limit(state))
    source = _consolidate(spec, state)
    eb = int(source.effective_balance)
    # fresh epoch: budget = limit, consumed = effective balance
    assert int(state.consolidation_balance_to_consume) == limit - eb


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_second_consolidation_same_epoch_shares_budget(spec, state):
    _mature(spec, state)
    for i in (1, 2, 3, 4):
        _compounding(spec, state, i, i)
    spec.process_consolidation_request(state, _request(spec, state, 1, 2))
    first_epoch = int(state.validators[1].exit_epoch)
    budget_after_first = int(state.consolidation_balance_to_consume)
    spec.process_consolidation_request(state, _request(spec, state, 3, 4))
    eb = int(state.validators[3].effective_balance)
    if budget_after_first >= eb:
        # fits in the same epoch's leftover budget
        assert int(state.validators[3].exit_epoch) == first_epoch
        assert (
            int(state.consolidation_balance_to_consume) == budget_after_first - eb
        )
    else:
        assert int(state.validators[3].exit_epoch) > first_epoch


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_consolidation_with_preexisting_churn(spec, state):
    """Pre-seeded consolidation_balance_to_consume at the current earliest
    epoch is honored, not reset."""
    _mature(spec, state)
    floor = int(spec.compute_activation_exit_epoch(spec.get_current_epoch(state)))
    state.earliest_consolidation_epoch = floor
    preexisting = 2 * int(spec.EFFECTIVE_BALANCE_INCREMENT)
    state.consolidation_balance_to_consume = preexisting
    eb = int(state.validators[1].effective_balance)
    assert eb > preexisting  # source doesn't fit the leftover budget
    source = _consolidate(spec, state)
    # budget exhausted: epoch pushed past the floor
    assert int(source.exit_epoch) > floor


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_consolidation_balance_through_multiple_churn_epochs(spec, state):
    """Source balance worth several epochs of churn pushes earliest epoch
    out by ceil(balance/limit) epochs."""
    _mature(spec, state)
    limit = int(spec.get_consolidation_churn_limit(state))
    big = 3 * limit
    source = _consolidate(spec, state, src_balance=big)
    floor = int(spec.compute_activation_exit_epoch(spec.get_current_epoch(state)))
    assert int(source.exit_epoch) >= floor + 2
    # leftover budget for the final epoch is nonnegative and below the limit
    leftover = int(state.consolidation_balance_to_consume)
    assert 0 <= leftover < limit


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_consolidation_exact_churn_limit_balance(spec, state):
    _mature(spec, state)
    _compounding(spec, state, 1, 1)
    _compounding(spec, state, 2, 2)
    # fixpoint: the source's own effective balance feeds total active
    # balance, which feeds the churn limit — iterate until stable
    for _ in range(10):
        limit = int(spec.get_consolidation_churn_limit(state))
        if int(state.validators[1].effective_balance) == limit:
            break
        state.validators[1].effective_balance = limit
        state.balances[1] = limit
    assert int(state.validators[1].effective_balance) == limit
    spec.process_consolidation_request(state, _request(spec, state, 1, 2))
    floor = int(spec.compute_activation_exit_epoch(spec.get_current_epoch(state)))
    assert int(state.validators[1].exit_epoch) == floor
    assert int(state.consolidation_balance_to_consume) == 0


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_consolidation_source_below_max_effective_balance(spec, state):
    """A source with less than the eth1 cap still consolidates (its
    effective balance is what churns)."""
    small = int(spec.MIN_ACTIVATION_BALANCE) - 2 * int(
        spec.EFFECTIVE_BALANCE_INCREMENT
    )
    source = _consolidate(spec, state, src_balance=small)
    assert int(source.exit_epoch) != int(spec.FAR_FUTURE_EPOCH)
    assert len(state.pending_consolidations) == 1


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_consolidation_pending_entry_records_pair(spec, state):
    _consolidate(spec, state, src=5, dst=6)
    entry = state.pending_consolidations[0]
    assert int(entry.source_index) == 5 and int(entry.target_index) == 6


# ------------------------------------------------- switch to compounding


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_switch_to_compounding_queues_excess(spec, state):
    _mature(spec, state)
    _eth1(spec, state, 1, 1)
    extra = 3 * GWEI
    state.balances[1] = int(spec.MIN_ACTIVATION_BALANCE) + extra
    req = spec.ConsolidationRequest(
        source_address=bytes(state.validators[1].withdrawal_credentials)[12:],
        source_pubkey=state.validators[1].pubkey,
        target_pubkey=state.validators[1].pubkey,
    )
    pre_deposits = len(state.pending_deposits)
    spec.process_consolidation_request(state, req)
    creds = bytes(state.validators[1].withdrawal_credentials)
    assert creds[:1] == bytes(spec.COMPOUNDING_WITHDRAWAL_PREFIX)
    # excess moved to the pending-deposit queue, balance clipped to min
    assert int(state.balances[1]) == int(spec.MIN_ACTIVATION_BALANCE)
    assert len(state.pending_deposits) == pre_deposits + 1
    assert int(state.pending_deposits[-1].amount) == extra


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_switch_to_compounding_no_excess_no_deposit(spec, state):
    _mature(spec, state)
    _eth1(spec, state, 1, 1)
    state.balances[1] = int(spec.MIN_ACTIVATION_BALANCE)
    req = spec.ConsolidationRequest(
        source_address=bytes(state.validators[1].withdrawal_credentials)[12:],
        source_pubkey=state.validators[1].pubkey,
        target_pubkey=state.validators[1].pubkey,
    )
    pre_deposits = len(state.pending_deposits)
    spec.process_consolidation_request(state, req)
    assert bytes(state.validators[1].withdrawal_credentials)[:1] == bytes(
        spec.COMPOUNDING_WITHDRAWAL_PREFIX
    )
    assert len(state.pending_deposits) == pre_deposits


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_switch_to_compounding_works_when_pending_queue_full(spec, state):
    """Switch requests bypass the pending_consolidations limit — they never
    enqueue a consolidation."""
    limit = int(spec.PENDING_CONSOLIDATIONS_LIMIT)
    if limit > 64:
        return
    _mature(spec, state)
    for _ in range(limit):
        state.pending_consolidations.append(
            spec.PendingConsolidation(source_index=8, target_index=9)
        )
    _eth1(spec, state, 1, 1)
    req = spec.ConsolidationRequest(
        source_address=bytes(state.validators[1].withdrawal_credentials)[12:],
        source_pubkey=state.validators[1].pubkey,
        target_pubkey=state.validators[1].pubkey,
    )
    spec.process_consolidation_request(state, req)
    assert bytes(state.validators[1].withdrawal_credentials)[:1] == bytes(
        spec.COMPOUNDING_WITHDRAWAL_PREFIX
    )


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_switch_to_compounding_already_compounding_falls_through(spec, state):
    """Self-request from a validator already holding 0x02 creds is NOT a
    valid switch (needs 0x01) and then fails source==target — full noop."""
    _mature(spec, state)
    _compounding(spec, state, 1, 1)
    pre_root = bytes(spec.hash_tree_root(state)) if hasattr(spec, "hash_tree_root") else None
    req = spec.ConsolidationRequest(
        source_address=bytes(state.validators[1].withdrawal_credentials)[12:],
        source_pubkey=state.validators[1].pubkey,
        target_pubkey=state.validators[1].pubkey,
    )
    pre_deposits = len(state.pending_deposits)
    pre_pending = len(state.pending_consolidations)
    spec.process_consolidation_request(state, req)
    assert bytes(state.validators[1].withdrawal_credentials)[:1] == bytes(
        spec.COMPOUNDING_WITHDRAWAL_PREFIX
    )
    assert len(state.pending_deposits) == pre_deposits
    assert len(state.pending_consolidations) == pre_pending


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_switch_to_compounding_exited_source_noop(spec, state):
    _mature(spec, state)
    _eth1(spec, state, 1, 1)
    state.validators[1].exit_epoch = int(spec.get_current_epoch(state)) + 3
    req = spec.ConsolidationRequest(
        source_address=bytes(state.validators[1].withdrawal_credentials)[12:],
        source_pubkey=state.validators[1].pubkey,
        target_pubkey=state.validators[1].pubkey,
    )
    spec.process_consolidation_request(state, req)
    assert bytes(state.validators[1].withdrawal_credentials)[:1] == bytes(
        spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX
    )


# --------------------------------------------------------------- blockers


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_consolidation_blocked_by_pending_withdrawal(spec, state):
    _mature(spec, state)
    _compounding(spec, state, 1, 1)
    _compounding(spec, state, 2, 2)
    state.pending_partial_withdrawals.append(
        spec.PendingPartialWithdrawal(
            validator_index=1, amount=GWEI, withdrawable_epoch=10
        )
    )
    spec.process_consolidation_request(state, _request(spec, state, 1, 2))
    assert int(state.validators[1].exit_epoch) == int(spec.FAR_FUTURE_EPOCH)
    assert len(state.pending_consolidations) == 0


@with_phases(ELECTRA_FORKS)
@spec_state_test
def test_consolidation_source_too_young_noop(spec, state):
    # no _mature: activation + SHARD_COMMITTEE_PERIOD gate fails at genesis
    _compounding(spec, state, 1, 1)
    _compounding(spec, state, 2, 2)
    spec.process_consolidation_request(state, _request(spec, state, 1, 2))
    assert int(state.validators[1].exit_epoch) == int(spec.FAR_FUTURE_EPOCH)
    assert len(state.pending_consolidations) == 0