"""EIP-7549 committee-bit attestations (reference analogue:
test/electra/block_processing/test_process_attestation.py; spec:
specs/electra/beacon-chain.md:1435-1488)."""

from eth_consensus_specs_tpu.ssz import Bitlist
from eth_consensus_specs_tpu.test_infra.attestations import (
    get_valid_attestation,
    run_attestation_processing,
    sign_attestation,
)
from eth_consensus_specs_tpu.test_infra.context import (
    always_bls,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.state import next_slots

ELECTRA_ONWARD = ["electra"]


@with_phases(ELECTRA_ONWARD)
@spec_state_test
def test_one_basic_attestation(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation)


@with_phases(ELECTRA_ONWARD)
@always_bls
@spec_state_test
def test_one_attestation_real_signature(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation)


@with_phases(ELECTRA_ONWARD)
@spec_state_test
def test_invalid_nonzero_data_index(spec, state):
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    attestation.data.index = 1  # post-electra data.index must be 0
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_phases(ELECTRA_ONWARD)
@spec_state_test
def test_invalid_committee_index_out_of_range(spec, state):
    # shrink the active set so committee_count < MAX_COMMITTEES_PER_SLOT,
    # leaving head-room in the bitvector for an out-of-range index
    target_active = 2 * spec.SLOTS_PER_EPOCH * spec.TARGET_COMMITTEE_SIZE
    for i in range(target_active, len(state.validators)):
        state.validators[i].exit_epoch = 0
        state.validators[i].withdrawable_epoch = 0
    committee_count = spec.get_committee_count_per_slot(
        state, spec.get_current_epoch(state)
    )
    assert committee_count < spec.MAX_COMMITTEES_PER_SLOT
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    attestation.committee_bits = spec.Attestation.fields()["committee_bits"]()
    attestation.committee_bits[committee_count] = True
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_phases(ELECTRA_ONWARD)
@spec_state_test
def test_invalid_too_many_committee_bits(spec, state):
    """Extra committee bit set -> bitlist length no longer matches."""
    attestation = get_valid_attestation(spec, state, signed=True)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    attestation.committee_bits[1] = True
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_phases(ELECTRA_ONWARD)
@spec_state_test
def test_invalid_empty_participation(spec, state):
    attestation = get_valid_attestation(
        spec, state, filter_participant_set=lambda _: set()
    )
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    yield from run_attestation_processing(spec, state, attestation, valid=False)


@with_phases(ELECTRA_ONWARD)
@spec_state_test
def test_multi_committee_aggregate(spec, state):
    """One attestation carrying two committees' participation."""
    committees_per_slot = spec.get_committee_count_per_slot(
        state, spec.get_current_epoch(state)
    )
    if committees_per_slot < 2:
        return  # preset too small for a multi-committee aggregate
    slot = int(state.slot)
    c0 = spec.get_beacon_committee(state, slot, 0)
    c1 = spec.get_beacon_committee(state, slot, 1)
    attestation = get_valid_attestation(spec, state, slot=slot, index=0)
    attestation.committee_bits[1] = True
    bits_type = Bitlist[spec.MAX_VALIDATORS_PER_COMMITTEE * spec.MAX_COMMITTEES_PER_SLOT]
    attestation.aggregation_bits = bits_type([True] * (len(c0) + len(c1)))
    sign_attestation(spec, state, attestation)
    next_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
    attesting = spec.get_attesting_indices(state, attestation)
    assert attesting == {int(i) for i in c0} | {int(i) for i in c1}
    yield from run_attestation_processing(spec, state, attestation)
