"""serve/ — batching semantics, parity, backpressure, degradation.

The service's contract: every future resolves to exactly what the
direct per-request ops call returns — under concurrency, under load
shed, and on the degraded host path — while flush behavior (size /
deadline / pressure) stays observable through serve.* counters.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import wait

import numpy as np
import pytest

from eth_consensus_specs_tpu import fault, obs, serve
from eth_consensus_specs_tpu.ops import bls_batch
from eth_consensus_specs_tpu.ops import merkle as ops_merkle
from eth_consensus_specs_tpu.serve import buckets
from eth_consensus_specs_tpu.serve.admission import AdmissionController, Overloaded
from eth_consensus_specs_tpu.serve.config import ServeConfig
from eth_consensus_specs_tpu.utils import bls


def _counter(name: str) -> float:
    return obs.snapshot()["counters"].get(name, 0)


@pytest.fixture(scope="module")
def bls_items():
    """8 committee aggregates over 3 distinct messages, two invalid
    (tampered sig, wrong message)."""
    sks = [1, 2, 3]
    pks = [bls.SkToPk(sk) for sk in sks]
    msgs = [bytes([i + 1]) * 32 for i in range(3)]
    items = []
    for i in range(8):
        m = msgs[i % 3]
        sig = bls.Aggregate([bls.Sign(sk, m) for sk in sks])
        if i == 2:
            sig = b"\x01" + bytes(sig)[1:]  # tampered signature
        if i == 5:
            m = bytes([0xEE]) * 32  # signed message != claimed message
        items.append((pks, m, sig))
    return items


@pytest.fixture(scope="module")
def trees():
    rng = np.random.default_rng(7)
    return [
        rng.integers(0, 256, size=(n, 32)).astype(np.uint8) for n in (1, 5, 17, 64, 100)
    ]


def _direct_bls(items):
    return [bls_batch.batch_verify_aggregates([it]) for it in items]


def _direct_roots(trees):
    return [
        ops_merkle.merkleize_subtree_device(t, buckets.subtree_depth(t.shape[0]))
        for t in trees
    ]


# --------------------------------------------------------- cost model --


def test_crossover_shared_and_pinned():
    """ops/merkle and the bucket planner share ONE crossover constant,
    pinned: regressing either side silently would unshare the model."""
    assert buckets.DEVICE_SUBTREE_THRESHOLD == 4096
    assert ops_merkle.DEVICE_SUBTREE_THRESHOLD == buckets.DEVICE_SUBTREE_THRESHOLD
    assert ops_merkle.device_subtree_worthwhile is buckets.device_subtree_worthwhile
    assert not buckets.device_subtree_worthwhile(4095)
    assert buckets.device_subtree_worthwhile(4096)
    # a batched dispatch amortizes: total chunks across trees is what counts
    assert buckets.device_subtree_worthwhile(1024, trees=4)
    assert not buckets.device_subtree_worthwhile(1024, trees=3)


def test_bucket_helpers():
    assert [buckets.pow2_bucket(n) for n in (1, 2, 3, 5, 64, 65)] == [1, 2, 4, 8, 64, 128]
    assert buckets.batch_bucket(3, (1, 2, 4, 8)) == 4
    assert buckets.batch_bucket(9, (1, 2, 4, 8)) == 8  # capped at the top bucket
    assert [buckets.subtree_depth(n) for n in (1, 2, 3, 64, 100)] == [0, 1, 2, 6, 7]


def test_compile_accounting_dedupes(tmp_path, monkeypatch):
    monkeypatch.setenv("ETH_SPECS_SERVE_WARMUP", str(tmp_path / "warm.jsonl"))
    buckets.reset_for_tests()
    before = _counter("serve.compiles")

    def _hist_count():
        h = obs.histogram("serve.compile_ms")
        return h.count if h is not None else 0

    hist0 = _hist_count()
    # every serve.compiles bump goes through the timed first_dispatch
    # wrapper, so the compile_ms histogram count tracks the counter 1:1
    with buckets.first_dispatch("merkle_many", 4, 3) as fd:
        assert fd.first
    with buckets.first_dispatch("merkle_many", 4, 3) as fd:
        assert not fd.first  # same shape: no recount, no duration sample
    with buckets.first_dispatch("merkle_many", 8, 3) as fd:
        assert fd.first
    assert _counter("serve.compiles") - before == 2
    assert _hist_count() - hist0 == 2
    assert set(buckets.load_warmup()) == {("merkle_many", 4, 3), ("merkle_many", 8, 3)}
    # precompile replays the persisted list without crashing (each replay
    # is a first dispatch again after the reset: two more duration samples)
    buckets.reset_for_tests()
    assert buckets.precompile() == 2
    assert _hist_count() - hist0 == 4
    buckets.reset_for_tests()


# ------------------------------------------------------------- parity --


def test_concurrent_submitters_bit_identical(bls_items, trees):
    """N concurrent submitters through the service == direct ops calls,
    bit for bit, with at least one size flush under the burst."""
    direct_b, direct_r = _direct_bls(bls_items), _direct_roots(trees)
    flushes_before = _counter("serve.flushes")
    svc = serve.VerifyService(ServeConfig.from_env(max_batch=8, max_wait_ms=10))
    results_b = [None] * len(bls_items)
    results_r = [None] * len(trees)
    barrier = threading.Barrier(len(bls_items) + len(trees))

    def submit_bls(i):
        barrier.wait()
        results_b[i] = svc.submit_bls_aggregate(*bls_items[i]).result(timeout=60)

    def submit_htr(i):
        barrier.wait()
        results_r[i] = svc.submit_hash_tree_root(trees[i]).result(timeout=60)

    threads = [
        threading.Thread(target=submit_bls, args=(i,)) for i in range(len(bls_items))
    ] + [threading.Thread(target=submit_htr, args=(i,)) for i in range(len(trees))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    svc.close()
    assert results_b == direct_b
    assert results_r == direct_r
    assert _counter("serve.flushes") > flushes_before


def test_verify_many_parity(bls_items):
    direct = _direct_bls(bls_items)
    assert bls_batch.verify_many(bls_items) == direct
    # malformed inputs short-circuit to False without poisoning the batch
    bad = [(bls_items[0][0], bls_items[0][1], b"\x00" * 96), ([], b"\x01" * 32, b"\x00" * 96)]
    assert bls_batch.verify_many(bls_items + bad) == direct + [False, False]
    assert bls_batch.verify_many([]) == []


def test_merkleize_many_matches_single(trees):
    depth = 7
    many = ops_merkle.merkleize_many_device(trees, depth, pad_batch=8)
    single = [ops_merkle.merkleize_subtree_device(t, depth) for t in trees]
    assert many == single


# ---------------------------------------------------- flush semantics --


def test_deadline_flush_under_low_load(bls_items):
    """A lone request must not wait for co-riders that aren't coming."""
    deadline_before = _counter("serve.flush.deadline")
    with serve.VerifyService(ServeConfig.from_env(max_batch=64, max_wait_ms=15)) as svc:
        t0 = time.monotonic()
        assert svc.submit_bls_aggregate(*bls_items[0]).result(timeout=30) is True
        elapsed = time.monotonic() - t0
    assert _counter("serve.flush.deadline") > deadline_before
    assert elapsed < 10  # deadline-bounded, not size-starved


def test_idle_flush_single_submitter(bls_items):
    """idle_flush (the gen-worker mode): a lone synchronous submitter
    flushes immediately instead of paying the deadline every request."""
    idle_before = _counter("serve.flush.idle")
    cfg = ServeConfig.from_env(max_batch=64, max_wait_ms=500, idle_flush=True)
    with serve.VerifyService(cfg) as svc:
        t0 = time.monotonic()
        for _ in range(3):
            assert svc.submit_bls_aggregate(*bls_items[0]).result(timeout=30) is True
        elapsed = time.monotonic() - t0
    assert _counter("serve.flush.idle") > idle_before
    assert elapsed < 1.0  # 3 requests, 500ms deadline never paid


def test_config_direct_construction_keeps_bucket_invariant():
    """A directly-constructed config (not from_env) must still hold a
    full flush in its largest bucket."""
    cfg = ServeConfig(max_batch=128)
    assert cfg.buckets[-1] >= cfg.max_batch
    assert buckets.batch_bucket(cfg.max_batch, cfg.buckets) >= cfg.max_batch


def test_overloaded_at_cap(trees):
    """Past max_queue, submit raises a typed Overloaded with a
    retry-after hint; admitted work still completes correctly."""
    rejected_before = _counter("serve.rejected")
    with fault.injected("serve.dispatch:stall:delay=2:times=1"):
        svc = serve.VerifyService(
            ServeConfig.from_env(max_batch=2, max_wait_ms=1, max_queue=4)
        )
        futs, overload = [], None
        for _ in range(12):
            try:
                futs.append(svc.submit_hash_tree_root(trees[3]))
            except Overloaded as exc:
                overload = exc
                break
            time.sleep(0.005)
        assert overload is not None, "cap never shed"
        assert overload.retry_after_s > 0
        assert overload.reason == "queue"
        wait(futs, timeout=60)
        direct = ops_merkle.merkleize_subtree_device(trees[3], 6)
        assert all(f.result() == direct for f in futs)
        svc.close()
    assert _counter("serve.rejected") > rejected_before


def test_admission_bytes_cap_admits_singleton():
    """A request bigger than the whole byte budget is admitted when the
    service is empty (it could otherwise never run) but rejected when
    anything is in flight."""
    ctrl = AdmissionController(max_queue=10, max_bytes=100)
    ctrl.admit(1000)  # empty service: the budget is all yours
    with pytest.raises(Overloaded) as exc_info:
        ctrl.admit(50)
    assert exc_info.value.reason == "bytes"
    ctrl.release(1000)
    ctrl.admit(50)
    ctrl.release(50)


# --------------------------------------------------------- degradation --


def test_device_kill_degrades_whole_batch(bls_items, trees):
    """ETH_SPECS_FAULT=serve.dispatch:raise:times=inf kills the device
    path every attempt: the WHOLE batch must degrade to host oracles
    with bit-identical results and a fault.degraded breadcrumb."""
    direct_b, direct_r = _direct_bls(bls_items), _direct_roots(trees)
    degraded_before = _counter("fault.degraded.serve.dispatch")
    with fault.injected("serve.dispatch:raise:times=inf"):
        with serve.VerifyService(ServeConfig.from_env(max_batch=8, max_wait_ms=5)) as svc:
            bf = [svc.submit_bls_aggregate(*it) for it in bls_items]
            rf = [svc.submit_hash_tree_root(t) for t in trees]
            wait(bf + rf, timeout=120)
            assert [f.result() for f in bf] == direct_b
            assert [f.result() for f in rf] == direct_r
    assert _counter("fault.degraded.serve.dispatch") > degraded_before
    assert _counter("serve.degraded_items") > 0


# ------------------------------------------------------------- routing --


def test_routed_fast_aggregate_verify(bls_items):
    """With a routed service installed, utils/bls.FastAggregateVerify
    coalesces through it — same verdicts, serve.requests.bls counted."""
    pks, msg, sig = bls_items[0]
    direct = bls.FastAggregateVerify(pks, msg, sig)
    before = _counter("serve.requests.bls")
    svc = serve.VerifyService(ServeConfig.from_env(max_batch=4, max_wait_ms=2))
    serve.install_routing(svc)
    try:
        assert bls.FastAggregateVerify(pks, msg, sig) == direct
        assert bls.FastAggregateVerify(*bls_items[2]) is False  # tampered
    finally:
        serve.uninstall_routing()
        svc.close()
    assert _counter("serve.requests.bls") - before == 2
    assert serve.routed() is None


# ------------------------------------------------------- thread safety --


def test_h2g2_cache_concurrent_prime():
    """Concurrent primes under distinct DSTs must never corrupt the
    (dst, message)-keyed cache or blow its bound."""
    sentinel = object()

    def batch_fn(msgs, dst):
        return [sentinel] * len(msgs)

    errors = []

    def hammer(worker: int):
        try:
            for i in range(50):
                dst = b"DST-%d" % (worker % 3)
                msgs = [bytes([worker, i, j]) for j in range(8)]
                bls_batch._prime_h2g2_cache(msgs, batch_fn, dst=dst)
                for m in msgs:
                    with bls_batch._H2G2_LOCK:
                        hit = bls_batch._H2G2_CACHE.get((dst, m))
                    assert hit is None or hit is sentinel
        except Exception as exc:  # pragma: no cover - failure path
            errors.append(exc)

    threads = [threading.Thread(target=hammer, args=(w,)) for w in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors
    with bls_batch._H2G2_LOCK:
        size = len(bls_batch._H2G2_CACHE)
        bls_batch._H2G2_CACHE.clear()  # don't leak sentinels into later tests
    assert size <= 512 + 8  # bound holds modulo one in-flight batch per thread


def test_obs_gauge_last_and_max():
    obs.gauge("test.depth", 3)
    obs.gauge("test.depth", 7)
    obs.gauge("test.depth", 2)
    g = obs.snapshot()["gauges"]["test.depth"]
    assert g["last"] == 2 and g["max"] == 7
