"""SSZ type-system tests: serialization round-trips, known-answer roots, and
merkleization vs an independent in-test oracle (hashlib-only, no shared code
paths with ssz/merkle.py's batched implementation)."""

import hashlib

import pytest

from eth_consensus_specs_tpu.ssz import (
    Bitlist,
    Bitvector,
    ByteList,
    Bytes32,
    Bytes48,
    Container,
    DeserializationError,
    List,
    Union,
    Vector,
    boolean,
    deserialize,
    hash_tree_root,
    serialize,
    uint8,
    uint16,
    uint64,
    uint256,
)


def sha(x: bytes) -> bytes:
    return hashlib.sha256(x).digest()


def naive_merkleize(chunks: list[bytes], limit: int) -> bytes:
    """Independent oracle: full zero-padded binary tree, no batching."""
    padded = 1 if limit == 0 else 1 << max(limit - 1, 0).bit_length()
    nodes = list(chunks) + [b"\x00" * 32] * (padded - len(chunks))
    while len(nodes) > 1:
        nodes = [sha(nodes[i] + nodes[i + 1]) for i in range(0, len(nodes), 2)]
    return nodes[0]


# --- basic types -----------------------------------------------------------


def test_uint_serialization():
    assert serialize(uint64(0)) == b"\x00" * 8
    assert serialize(uint64(16)) == (16).to_bytes(8, "little")
    assert serialize(uint8(255)) == b"\xff"
    assert serialize(uint256(2**256 - 1)) == b"\xff" * 32
    assert deserialize(uint64, (12345).to_bytes(8, "little")) == 12345


def test_uint_range_checks():
    with pytest.raises(ValueError):
        uint8(256)
    with pytest.raises(ValueError):
        uint64(-1)
    with pytest.raises(ValueError):
        uint64(2**64)


def test_uint_hash_tree_root():
    assert bytes(hash_tree_root(uint64(17))) == (17).to_bytes(8, "little") + b"\x00" * 24
    assert bytes(hash_tree_root(uint256(5))) == (5).to_bytes(32, "little")
    assert bytes(hash_tree_root(boolean(True))) == b"\x01" + b"\x00" * 31


def test_boolean():
    assert serialize(boolean(True)) == b"\x01"
    assert serialize(boolean(False)) == b"\x00"
    with pytest.raises(ValueError):
        boolean(2)
    with pytest.raises(DeserializationError):
        deserialize(boolean, b"\x02")


def test_bytes_types():
    b = Bytes32(b"\x01" * 32)
    assert serialize(b) == b"\x01" * 32
    assert bytes(hash_tree_root(b)) == b"\x01" * 32
    with pytest.raises(ValueError):
        Bytes32(b"\x01" * 31)
    b48 = Bytes48()
    assert bytes(b48) == b"\x00" * 48
    # 48 bytes -> two chunks -> one hash
    assert bytes(hash_tree_root(b48)) == sha(b"\x00" * 64)


def test_bytelist():
    BL = ByteList[100]
    v = BL(b"hello")
    assert serialize(v) == b"hello"
    assert deserialize(BL, b"hello") == v
    limit_chunks = (100 + 31) // 32  # 4
    chunk = b"hello" + b"\x00" * 27
    expect = sha(naive_merkleize([chunk], limit_chunks) + (5).to_bytes(32, "little"))
    assert bytes(hash_tree_root(v)) == expect
    with pytest.raises(ValueError):
        BL(b"x" * 101)


# --- bitfields -------------------------------------------------------------


def test_bitvector():
    BV = Bitvector[10]
    v = BV([1, 0, 1, 0, 0, 0, 0, 0, 1, 1])
    assert serialize(v) == bytes([0b00000101, 0b00000011])
    assert deserialize(BV, serialize(v)) == v
    # padding bits beyond length must be zero on decode
    with pytest.raises(DeserializationError):
        deserialize(BV, bytes([0x05, 0xFF]))


def test_bitlist():
    BL = Bitlist[8]
    v = BL([1, 0, 1])
    # bits 101 + delimiter at index 3 -> 0b1101 = 13
    assert serialize(v) == bytes([0b1101])
    assert deserialize(BL, bytes([0b1101])) == v
    assert len(v) == 3
    empty = BL()
    assert serialize(empty) == b"\x01"
    assert deserialize(BL, b"\x01") == empty
    with pytest.raises(DeserializationError):
        deserialize(BL, b"\x00")  # no delimiter
    with pytest.raises(DeserializationError):
        deserialize(Bitlist[3], bytes([0b11111]))  # 4 bits > limit 3
    chunk = bytes([0b101]) + b"\x00" * 31
    expect = sha(naive_merkleize([chunk], 1) + (3).to_bytes(32, "little"))
    assert bytes(hash_tree_root(v)) == expect


# --- sequences -------------------------------------------------------------


def test_list_uint64():
    L = List[uint64, 1024]
    v = L(1, 2, 3)
    assert serialize(v) == b"".join(i.to_bytes(8, "little") for i in (1, 2, 3))
    assert deserialize(L, serialize(v)) == v
    chunks = [
        (1).to_bytes(8, "little") + (2).to_bytes(8, "little") + (3).to_bytes(8, "little") + b"\x00" * 8
    ]
    limit_chunks = 1024 * 8 // 32
    expect = sha(naive_merkleize(chunks, limit_chunks) + (3).to_bytes(32, "little"))
    assert bytes(hash_tree_root(v)) == expect
    v.append(4)
    assert len(v) == 4
    assert v[3] == 4
    with pytest.raises(ValueError):
        List[uint64, 2](1, 2, 3)


def test_list_append_invalidates_root():
    L = List[uint64, 64]
    v = L(1)
    r1 = hash_tree_root(v)
    v.append(2)
    r2 = hash_tree_root(v)
    assert r1 != r2
    v[1] = 3
    assert hash_tree_root(v) != r2


def test_vector():
    V = Vector[uint64, 4]
    v = V(1, 2, 3, 4)
    assert serialize(v) == b"".join(i.to_bytes(8, "little") for i in (1, 2, 3, 4))
    assert deserialize(V, serialize(v)) == v
    chunk = serialize(v)
    assert bytes(hash_tree_root(v)) == naive_merkleize([chunk], 1)
    d = V.default()
    assert list(d) == [0, 0, 0, 0]
    with pytest.raises(ValueError):
        V(1, 2, 3)
    with pytest.raises(DeserializationError):
        deserialize(V, b"\x00" * 31)


def test_vector_of_roots():
    V = Vector[Bytes32, 2]
    a, b = Bytes32(b"\xaa" * 32), Bytes32(b"\xbb" * 32)
    v = V(a, b)
    assert bytes(hash_tree_root(v)) == sha(bytes(a) + bytes(b))


# --- containers ------------------------------------------------------------


class Inner(Container):
    a: uint64
    b: Bytes32


class Outer(Container):
    x: uint8
    inner: Inner
    items: List[uint64, 32]


def test_container_basic():
    c = Inner(a=7, b=Bytes32(b"\x01" * 32))
    assert c.a == 7
    data = serialize(c)
    assert data == (7).to_bytes(8, "little") + b"\x01" * 32
    assert deserialize(Inner, data) == c
    expect = sha(bytes(hash_tree_root(uint64(7))) + b"\x01" * 32)
    assert bytes(hash_tree_root(c)) == expect


def test_container_variable_fields():
    o = Outer(x=1, inner=Inner(a=2), items=List[uint64, 32](5, 6))
    data = serialize(o)
    # fixed part: 1 (uint8) + 40 (Inner) + 4 (offset) = 45
    assert int.from_bytes(data[41:45], "little") == 45
    rt = deserialize(Outer, data)
    assert rt == o
    assert rt.items[1] == 6
    # container htr = merkleize of 3 field roots
    roots = [
        bytes(hash_tree_root(o.x)),
        bytes(hash_tree_root(o.inner)),
        bytes(hash_tree_root(o.items)),
    ]
    assert bytes(hash_tree_root(o)) == naive_merkleize(roots, 3)


def test_container_defaults_and_copy():
    o = Outer()
    assert o.x == 0 and o.inner.a == 0 and len(o.items) == 0
    c = o.copy()
    c.inner.a = 9
    c.items.append(1)
    assert o.inner.a == 0 and len(o.items) == 0
    assert c.inner.a == 9


def test_container_root_cache_invalidation():
    o = Outer(x=1)
    r1 = hash_tree_root(o)
    o.x = 2
    assert hash_tree_root(o) != r1
    # nested mutation through attribute access
    r2 = hash_tree_root(o)
    o.inner = Inner(a=5)
    assert hash_tree_root(o) != r2


def test_container_unknown_field():
    with pytest.raises(TypeError):
        Inner(zzz=1)
    o = Inner()
    with pytest.raises(AttributeError):
        o.zzz = 1


def test_container_trailing_bytes_rejected():
    c = Inner(a=7)
    with pytest.raises(DeserializationError):
        deserialize(Inner, serialize(c) + b"\x00")


# --- union -----------------------------------------------------------------


def test_union():
    U = Union[None, uint64, Bytes32]
    v = U(1, 42)
    assert serialize(v) == b"\x01" + (42).to_bytes(8, "little")
    assert deserialize(U, serialize(v)) == v
    n = U(0)
    assert serialize(n) == b"\x00"
    expect = sha(bytes(hash_tree_root(uint64(42))) + (1).to_bytes(32, "little"))
    assert bytes(hash_tree_root(v)) == expect
    with pytest.raises(DeserializationError):
        deserialize(U, b"\x05")


# --- list of containers (registry-shaped) ----------------------------------


def test_list_of_containers():
    L = List[Inner, 8]
    v = L(Inner(a=1), Inner(a=2))
    data = serialize(v)
    assert deserialize(L, data) == v
    roots = [bytes(hash_tree_root(e)) for e in v]
    expect = sha(naive_merkleize(roots, 8) + (2).to_bytes(32, "little"))
    assert bytes(hash_tree_root(v)) == expect


def test_nested_mutation_invalidates_ancestor_roots():
    """Regression: cached roots must not survive mutations made through a
    child reference (caught by runtime probing, not the original suite)."""
    o = Outer(items=List[uint64, 32](1, 2, 3), inner=Inner(a=1))
    r0 = bytes(hash_tree_root(o))
    o.items[0] = 99  # mutate child list element through parent reference
    r1 = bytes(hash_tree_root(o))
    assert r1 != r0
    o.inner.a = 42  # mutate grandchild field
    r2 = bytes(hash_tree_root(o))
    assert r2 != r1
    bl = Bitlist[16]([0, 0, 1])

    class WithBits(Container):
        bits: Bitlist[16]

    class Wrap(Container):
        lst: List[WithBits, 4]

    w = Wrap(lst=List[WithBits, 4](WithBits(bits=bl)))
    r0 = bytes(hash_tree_root(w))
    w.lst[0].bits[0] = True  # three levels deep
    assert bytes(hash_tree_root(w)) != r0


def test_large_list_merkleization_matches_oracle():
    L = List[uint64, 2**18]
    n = 1000
    v = L(range(n))
    data = serialize(v)
    chunks = [data[i : i + 32].ljust(32, b"\x00") for i in range(0, len(data), 32)]
    limit_chunks = 2**18 * 8 // 32
    expect = sha(naive_merkleize(chunks, limit_chunks) + n.to_bytes(32, "little"))
    assert bytes(hash_tree_root(v)) == expect
