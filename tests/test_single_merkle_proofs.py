"""Single-merkle-proof suites: inclusion branches for consensus objects
(reference analogue: test/deneb/unittests/test_single_merkle_proof.py,
test/fulu/unittests/ sidecar proofs, and the light-client proof suites;
proofs from ssz/merkle.compute_merkle_proof verified with the spec's own
is_valid_merkle_branch / normalized-branch verifiers)."""

from eth_consensus_specs_tpu import ssz
from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.ssz.gindex import get_generalized_index
from eth_consensus_specs_tpu.ssz.merkle import compute_merkle_proof
from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test,
    spec_state_test_with_matching_config,
    with_phases,
)

BLOB_FORKS = ["deneb", "electra", "fulu"]
LC_STATE_FORKS = ["altair", "capella", "deneb", "electra"]


def _floorlog2(x: int) -> int:
    return int(x).bit_length() - 1


# == blob_kzg_commitments inclusion in BeaconBlockBody (deneb..fulu) =======


@with_phases(BLOB_FORKS)
@spec_state_test
def test_blob_commitments_inclusion_proof(spec, state):
    body = spec.BeaconBlockBody()
    body.blob_kzg_commitments.append(b"\x01" * 48)
    gindex = get_generalized_index(type(body), "blob_kzg_commitments")
    branch = compute_merkle_proof(body, gindex)
    assert len(branch) == _floorlog2(gindex)
    leaf = hash_tree_root(body.blob_kzg_commitments)
    root = hash_tree_root(body)
    assert spec.is_valid_merkle_branch(
        leaf, branch, _floorlog2(gindex), int(gindex) % (1 << _floorlog2(gindex)), root
    )


@with_phases(BLOB_FORKS)
@spec_state_test
def test_blob_commitments_proof_rejects_tamper(spec, state):
    body = spec.BeaconBlockBody()
    body.blob_kzg_commitments.append(b"\x02" * 48)
    gindex = get_generalized_index(type(body), "blob_kzg_commitments")
    branch = list(compute_merkle_proof(body, gindex))
    branch[2] = b"\x77" * 32
    leaf = hash_tree_root(body.blob_kzg_commitments)
    root = hash_tree_root(body)
    assert not spec.is_valid_merkle_branch(
        leaf, branch, _floorlog2(gindex), int(gindex) % (1 << _floorlog2(gindex)), root
    )


@with_phases(BLOB_FORKS)
@spec_state_test
def test_single_commitment_element_proof(spec, state):
    """Proof of ONE commitment element inside the list (the blob sidecar
    shape: list element + length mix-in on the path)."""
    body = spec.BeaconBlockBody()
    for i in range(3):
        body.blob_kzg_commitments.append(bytes([i + 1]) * 48)
    gindex = get_generalized_index(type(body), "blob_kzg_commitments", 1)
    branch = compute_merkle_proof(body, gindex)
    leaf = hash_tree_root(ssz.Bytes48(bytes([2]) * 48))
    root = hash_tree_root(body)
    assert spec.is_valid_merkle_branch(
        leaf, branch, _floorlog2(gindex), int(gindex) % (1 << _floorlog2(gindex)), root
    )


# == fulu DataColumnSidecar commitment inclusion ===========================


@with_phases(["fulu"])
@spec_state_test
def test_data_column_sidecar_inclusion_depth_matches_spec(spec, state):
    body = spec.BeaconBlockBody()
    gindex = get_generalized_index(type(body), "blob_kzg_commitments")
    # the p2p constant the sidecar Vector is sized by (fulu
    # p2p-interface.md:82) must equal the real tree depth
    assert _floorlog2(gindex) == int(spec.KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH)


# == light-client state branches (altair..electra) =========================


@with_phases(LC_STATE_FORKS)
@spec_state_test_with_matching_config
def test_next_sync_committee_branch_depth(spec, state):
    raw_gindex = get_generalized_index(type(state), "next_sync_committee")
    branch = spec.normalize_merkle_branch(
        compute_merkle_proof(state, raw_gindex),
        spec.next_sync_committee_gindex_at_slot(state.slot),
    )
    assert spec.is_valid_normalized_merkle_branch(
        hash_tree_root(state.next_sync_committee),
        branch,
        spec.next_sync_committee_gindex_at_slot(state.slot),
        hash_tree_root(state),
    )


@with_phases(LC_STATE_FORKS)
@spec_state_test_with_matching_config
def test_finality_branch_wrong_leaf_rejected(spec, state):
    raw_gindex = get_generalized_index(type(state), "finalized_checkpoint", "root")
    gindex = spec.finalized_root_gindex_at_slot(state.slot)
    branch = spec.normalize_merkle_branch(compute_merkle_proof(state, raw_gindex), gindex)
    wrong_leaf = ssz.Bytes32(b"\x31" * 32)
    assert not spec.is_valid_normalized_merkle_branch(
        wrong_leaf, branch, gindex, hash_tree_root(state)
    )


@with_phases(LC_STATE_FORKS)
@spec_state_test
def test_state_field_proofs_roundtrip(spec, state):
    """Container-field proofs across a handful of BeaconState fields."""
    for field in ("fork", "latest_block_header", "finalized_checkpoint"):
        gindex = get_generalized_index(type(state), field)
        branch = compute_merkle_proof(state, gindex)
        leaf = hash_tree_root(getattr(state, field))
        assert spec.is_valid_merkle_branch(
            leaf,
            branch,
            _floorlog2(gindex),
            int(gindex) % (1 << _floorlog2(gindex)),
            hash_tree_root(state),
        )


@with_phases(["capella", "deneb", "electra"])
@spec_state_test
def test_execution_payload_header_field_proof(spec, state):
    """Execution branch of the LC header (capella+): payload header root
    inside the block body."""
    body = spec.BeaconBlockBody()
    gindex = get_generalized_index(type(body), "execution_payload")
    branch = compute_merkle_proof(body, gindex)
    leaf = hash_tree_root(body.execution_payload)
    assert spec.is_valid_merkle_branch(
        leaf,
        branch,
        _floorlog2(gindex),
        int(gindex) % (1 << _floorlog2(gindex)),
        hash_tree_root(body),
    )


@with_phases(["altair"])
@spec_state_test
def test_deep_gindex_proof_through_checkpoint(spec, state):
    """Multi-segment path: state -> finalized_checkpoint -> root."""
    state.finalized_checkpoint.root = b"\x2b" * 32
    gindex = get_generalized_index(type(state), "finalized_checkpoint", "root")
    branch = compute_merkle_proof(state, gindex)
    assert spec.is_valid_merkle_branch(
        ssz.Bytes32(state.finalized_checkpoint.root),
        branch,
        _floorlog2(gindex),
        int(gindex) % (1 << _floorlog2(gindex)),
        hash_tree_root(state),
    )
