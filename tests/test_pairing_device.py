"""Device pairing vs the host oracle (crypto/pairing.py).

The Miller value must match BIT-FOR-BIT (same line model and step order);
the exact final exponentiation must reproduce the oracle GT element; and
the fast membership check must agree with pairing_check on valid and
tampered pairings (the bilinearity relation e(aG1, bG2) = e(abG1, G2))."""

import pytest

# device pairing compiles are minutes-scale — nightly/full lane (make test-full)
pytestmark = pytest.mark.slow

import numpy as np

from eth_consensus_specs_tpu.crypto import pairing as host_pairing
from eth_consensus_specs_tpu.crypto.curve import g1_generator, g2_generator, g1_infinity, g2_infinity
from eth_consensus_specs_tpu.ops import pairing_device as dev


def test_miller_value_bit_exact():
    p = g1_generator().mul(7)
    q = g2_generator().mul(11)
    got = dev.miller_loop_device(p, q)
    want = host_pairing.miller_loop(p, host_pairing.untwist(q))
    assert got == want


def test_pairing_gt_parity():
    p = g1_generator().mul(5)
    q = g2_generator().mul(9)
    got = dev.pairing_device(p, q)
    want = host_pairing.pairing(p, q)
    assert got == want


def test_pairing_check_bilinearity():
    a, b = 23, 41
    g1, g2 = g1_generator(), g2_generator()
    # e(aG1, bG2) * e(-abG1, G2) == 1
    good = [(g1.mul(a), g2.mul(b)), (-(g1.mul(a * b)), g2)]
    assert dev.pairing_check_device(good)
    bad = [(g1.mul(a), g2.mul(b)), (-(g1.mul(a * b + 1)), g2)]
    assert not dev.pairing_check_device(bad)
    # host oracle agrees
    assert host_pairing.pairing_check(good)
    assert not host_pairing.pairing_check(bad)


def test_infinity_handling():
    g1, g2 = g1_generator(), g2_generator()
    # e(O, Q) = e(P, O) = 1 -> check passes with only-infinity pairs
    assert dev.pairing_check_device([(g1_infinity(), g2), (g1, g2_infinity())])


def test_signature_verify_shape():
    """A real BLS signature relation through the device check."""
    from eth_consensus_specs_tpu.crypto import signature as sig
    from eth_consensus_specs_tpu.crypto.curve import g1_from_bytes, g2_from_bytes
    from eth_consensus_specs_tpu.crypto.hash_to_curve import hash_to_g2

    sk = 42
    msg = b"\x07" * 32
    pk = g1_from_bytes(sig.sk_to_pk(sk))
    s = g2_from_bytes(sig.sign(sk, msg))
    h = hash_to_g2(msg)
    assert dev.pairing_check_device([(pk, h), (-g1_generator(), s)])
    assert not dev.pairing_check_device([(pk, hash_to_g2(b"\x08" * 32)), (-g1_generator(), s)])
