"""Dense process_sync_aggregate suite, altair+ (reference analogue:
test/altair/block_processing/sync_aggregate/test_process_sync_aggregate.py
— the 25-variant file: duplicate-committee reward accounting, exited /
withdrawable members, proposer-in-committee, domain binding, and
infinite-signature invalids)."""

from eth_consensus_specs_tpu.test_infra.context import (
    always_bls,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.keys import pubkeys
from eth_consensus_specs_tpu.test_infra.state import next_epoch, next_slot
from eth_consensus_specs_tpu.test_infra.sync_committee import (
    committee_indices,
    compute_sync_reward_and_penalty,
    make_sync_aggregate,
    run_sync_aggregate_processing,
    validate_sync_committee_rewards,
)
from eth_consensus_specs_tpu.test_infra.template import instantiate
from eth_consensus_specs_tpu.utils import bls

ALTAIR_FORKS = ["altair", "bellatrix", "capella"]


def _run_rewards_case(spec, state, bits):
    next_slot(spec, state)
    committee = committee_indices(spec, state)
    aggregate = make_sync_aggregate(spec, state, bits)
    pre = state.copy()
    proposer = int(spec.get_beacon_proposer_index(state))
    for _ in run_sync_aggregate_processing(spec, state, aggregate):
        pass
    validate_sync_committee_rewards(spec, pre, state, committee, bits, proposer)


# -------------------------------------------------------- reward accounting


@with_phases(ALTAIR_FORKS)
@spec_state_test
def test_rewards_nonduplicate_committee(spec, state):
    _run_rewards_case(spec, state, [True] * int(spec.SYNC_COMMITTEE_SIZE))


@with_phases(ALTAIR_FORKS)
@spec_state_test
def test_rewards_not_full_participants(spec, state):
    size = int(spec.SYNC_COMMITTEE_SIZE)
    _run_rewards_case(spec, state, [i % 4 != 0 for i in range(size)])


@with_phases(ALTAIR_FORKS)
@spec_state_test
def test_rewards_empty_participants(spec, state):
    _run_rewards_case(spec, state, [False] * int(spec.SYNC_COMMITTEE_SIZE))


def _duplicate_committee_case(participation: str):
    """Factory: every committee position points at the SAME validator —
    rewards/penalties stack once per position (reference:
    test_process_sync_aggregate.py duplicate_committee family)."""

    @with_phases(ALTAIR_FORKS)
    @spec_state_test
    def case(spec, state):
        size = int(spec.SYNC_COMMITTEE_SIZE)
        # point the whole committee at validator 0
        state.current_sync_committee.pubkeys = [pubkeys[0]] * size
        if participation == "full":
            bits = [True] * size
        elif participation == "half":
            bits = [i % 2 == 0 for i in range(size)]
        else:
            bits = [False] * size
        _run_rewards_case(spec, state, bits)

    return case, f"test_rewards_duplicate_committee_{participation}_participation"


for _participation in ("no", "half", "full"):
    instantiate(_duplicate_committee_case, _participation)


@with_phases(ALTAIR_FORKS)
@spec_state_test
def test_rewards_duplicate_committee_zero_balance_floor(spec, state):
    """A zero-balance duplicated non-participant is penalized once per
    position but floors at zero each time, not once at the end."""
    size = int(spec.SYNC_COMMITTEE_SIZE)
    state.current_sync_committee.pubkeys = [pubkeys[0]] * size
    state.balances[0] = 0
    _run_rewards_case(spec, state, [False] * size)
    assert int(state.balances[0]) == 0


@with_phases(ALTAIR_FORKS)
@spec_state_test
def test_proposer_in_committee_with_participation(spec, state):
    """When the proposer sits in the committee, it collects both the
    participant reward and its proposer cut."""
    next_slot(spec, state)
    proposer = int(spec.get_beacon_proposer_index(state))
    size = int(spec.SYNC_COMMITTEE_SIZE)
    state.current_sync_committee.pubkeys = [
        state.validators[proposer].pubkey
    ] * size
    committee = committee_indices(spec, state)
    bits = [True] * size
    aggregate = make_sync_aggregate(spec, state, bits)
    pre = state.copy()
    for _ in run_sync_aggregate_processing(spec, state, aggregate):
        pass
    validate_sync_committee_rewards(spec, pre, state, committee, bits, proposer)
    participant_reward, proposer_reward = compute_sync_reward_and_penalty(spec, pre)
    assert int(state.balances[proposer]) == int(pre.balances[proposer]) + size * (
        participant_reward + proposer_reward
    )


@with_phases(ALTAIR_FORKS)
@spec_state_test
def test_proposer_in_committee_without_participation(spec, state):
    next_slot(spec, state)
    proposer = int(spec.get_beacon_proposer_index(state))
    size = int(spec.SYNC_COMMITTEE_SIZE)
    state.current_sync_committee.pubkeys = [
        state.validators[proposer].pubkey
    ] * size
    bits = [False] * size
    aggregate = make_sync_aggregate(spec, state, bits)
    pre_balance = int(state.balances[proposer])
    participant_reward, _ = compute_sync_reward_and_penalty(spec, state)
    for _ in run_sync_aggregate_processing(spec, state, aggregate):
        pass
    assert int(state.balances[proposer]) == max(
        0, pre_balance - size * participant_reward
    )


# ------------------------------------------------------- lifecycle members


def _lifecycle_member_case(status: str, participating: bool):
    """Exited/withdrawable committee members still sign and still earn or
    lose — committee membership outlives the validator lifecycle within
    the period (reference: sync_committee_with_*_exited/withdrawable)."""

    @with_phases(ALTAIR_FORKS)
    @spec_state_test
    def case(spec, state):
        next_slot(spec, state)
        committee = committee_indices(spec, state)
        target = committee[0]
        validator = state.validators[target]
        epoch = int(spec.get_current_epoch(state))
        validator.exit_epoch = max(epoch - 1, 0)
        if status == "withdrawable":
            validator.withdrawable_epoch = max(epoch - 1, 0)
        else:
            validator.withdrawable_epoch = epoch + 4
        size = int(spec.SYNC_COMMITTEE_SIZE)
        bits = [True] * size
        if not participating:
            for position, idx in enumerate(committee):
                if idx == target:
                    bits[position] = False
        aggregate = make_sync_aggregate(spec, state, bits)
        pre = state.copy()
        proposer = int(spec.get_beacon_proposer_index(state))
        for _ in run_sync_aggregate_processing(spec, state, aggregate):
            pass
        validate_sync_committee_rewards(spec, pre, state, committee, bits, proposer)

    tag = "participating" if participating else "nonparticipating"
    return case, f"test_committee_with_{tag}_{status}_member"


for _status in ("exited", "withdrawable"):
    for _participating in (True, False):
        instantiate(_lifecycle_member_case, _status, _participating)


# ----------------------------------------------------------- domain binding


@with_phases(ALTAIR_FORKS)
@always_bls
@spec_state_test
def test_invalid_signature_bad_domain(spec, state):
    next_slot(spec, state)
    size = int(spec.SYNC_COMMITTEE_SIZE)
    bits = [True] * size
    previous_slot = int(state.slot) - 1
    block_root = spec.get_block_root_at_slot(state, previous_slot)
    # sign under the RANDAO domain instead of SYNC_COMMITTEE
    domain = spec.get_domain(
        state, spec.DOMAIN_RANDAO, spec.compute_epoch_at_slot(previous_slot)
    )
    signing_root = spec.compute_signing_root(spec.Root(block_root), domain)
    from eth_consensus_specs_tpu.test_infra.keys import pubkey_to_privkey

    sigs = [
        bls.Sign(pubkey_to_privkey(bytes(pk)), signing_root)
        for pk in state.current_sync_committee.pubkeys
    ]
    aggregate = spec.SyncAggregate(
        sync_committee_bits=bits, sync_committee_signature=bls.Aggregate(sigs)
    )
    for _ in run_sync_aggregate_processing(spec, state, aggregate, valid=False):
        pass


@with_phases(ALTAIR_FORKS)
@always_bls
@spec_state_test
def test_invalid_signature_past_block_root(spec, state):
    next_slot(spec, state)
    next_slot(spec, state)
    size = int(spec.SYNC_COMMITTEE_SIZE)
    bits = [True] * size
    # sign a root two slots back instead of the previous slot
    stale_root = spec.get_block_root_at_slot(state, int(state.slot) - 2)
    fresh_root = spec.get_block_root_at_slot(state, int(state.slot) - 1)
    if bytes(stale_root) == bytes(fresh_root):
        return  # empty-slot chain: roots coincide, nothing to distinguish
    aggregate = make_sync_aggregate(
        spec, state, bits, slot=int(state.slot) - 1, block_root=stale_root
    )
    for _ in run_sync_aggregate_processing(spec, state, aggregate, valid=False):
        pass


@with_phases(ALTAIR_FORKS)
@always_bls
@spec_state_test
def test_invalid_infinite_signature_with_all_participants(spec, state):
    next_slot(spec, state)
    bits = [True] * int(spec.SYNC_COMMITTEE_SIZE)
    aggregate = spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=bls.G2_POINT_AT_INFINITY,
    )
    for _ in run_sync_aggregate_processing(spec, state, aggregate, valid=False):
        pass


@with_phases(ALTAIR_FORKS)
@always_bls
@spec_state_test
def test_invalid_infinite_signature_with_single_participant(spec, state):
    next_slot(spec, state)
    bits = [False] * int(spec.SYNC_COMMITTEE_SIZE)
    bits[0] = True
    aggregate = spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=bls.G2_POINT_AT_INFINITY,
    )
    for _ in run_sync_aggregate_processing(spec, state, aggregate, valid=False):
        pass


@with_phases(ALTAIR_FORKS)
@always_bls
@spec_state_test
def test_invalid_signature_missing_participant(spec, state):
    """All bits set but one member's signature absent from the aggregate."""
    next_slot(spec, state)
    size = int(spec.SYNC_COMMITTEE_SIZE)
    bits = [True] * size
    partial = list(bits)
    partial[0] = False
    aggregate = make_sync_aggregate(spec, state, partial)
    aggregate.sync_committee_bits = bits
    for _ in run_sync_aggregate_processing(spec, state, aggregate, valid=False):
        pass


@with_phases(ALTAIR_FORKS)
@always_bls
@spec_state_test
def test_valid_signature_future_committee(spec, state):
    """After a committee-period rotation the NEW current committee signs —
    membership is read from the post-rotation state (reference:
    valid_signature_future_committee)."""
    period_epochs = int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)
    for _ in range(period_epochs):
        next_epoch(spec, state)
    next_slot(spec, state)
    bits = [True] * int(spec.SYNC_COMMITTEE_SIZE)
    aggregate = make_sync_aggregate(spec, state, bits)
    for _ in run_sync_aggregate_processing(spec, state, aggregate):
        pass
