"""Parity: the fused altair+ columnar epoch kernel (ops/altair_epoch.py)
must be bit-exact with the object-path process_epoch across the
altair->deneb matrix. Equality is asserted on the full post-state
hash_tree_root, so every mutated field (balances, effective balances,
inactivity scores, justification, participation rotation, sync-committee
resampling) is covered."""

import pytest

# device epoch kernel compiles — nightly lane (make test-full)
pytestmark = pytest.mark.slow

from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.attestations import next_epoch_with_attestations
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.state import next_epoch, next_slots

FLAG_FORKS = ["altair", "bellatrix", "capella", "deneb", "electra", "fulu", "gloas"]


def assert_columnar_parity(spec, state):
    boundary = int(state.slot) + (
        spec.SLOTS_PER_EPOCH - int(state.slot) % spec.SLOTS_PER_EPOCH
    )
    if int(state.slot) < boundary - 1:
        spec.process_slots(state, boundary - 1)
    obj_state = state.copy()
    col_state = state.copy()
    spec.process_epoch_object(obj_state)
    spec.process_epoch_columnar(col_state)
    assert hash_tree_root(obj_state) == hash_tree_root(col_state)


@with_phases(FLAG_FORKS)
@spec_state_test
def test_columnar_genesis_epoch(spec, state):
    assert_columnar_parity(spec, state)


@with_phases(FLAG_FORKS)
@spec_state_test
def test_columnar_full_participation(spec, state):
    next_epoch_with_attestations(spec, state, fill_cur_epoch=False, fill_prev_epoch=True)
    next_epoch_with_attestations(spec, state, fill_cur_epoch=True, fill_prev_epoch=True)
    assert_columnar_parity(spec, state)


@with_phases(FLAG_FORKS)
@spec_state_test
def test_columnar_partial_participation(spec, state):
    next_epoch_with_attestations(spec, state, fill_cur_epoch=False, fill_prev_epoch=True)
    # thin out participation: strip flags from every third validator
    for i in range(0, len(state.validators), 3):
        state.previous_epoch_participation[i] = 0
    for i in range(1, len(state.validators), 3):
        state.current_epoch_participation[i] = 0
    assert_columnar_parity(spec, state)


@with_phases(FLAG_FORKS)
@spec_state_test
def test_columnar_inactivity_leak(spec, state):
    # empty epochs beyond the inactivity threshold: leak + score growth
    for _ in range(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY + 3):
        next_epoch(spec, state)
    # some validators have nonzero scores by now; a few keep participating
    for i in range(0, len(state.validators), 4):
        state.previous_epoch_participation[i] = 0b0000_0111
    assert_columnar_parity(spec, state)


@with_phases(FLAG_FORKS)
@spec_state_test
def test_columnar_slashed_validators(spec, state):
    next_epoch_with_attestations(spec, state, fill_cur_epoch=False, fill_prev_epoch=True)
    # slash a handful; some land exactly in the correlated-penalty window
    epoch = spec.get_current_epoch(state)
    half = spec.EPOCHS_PER_SLASHINGS_VECTOR // 2
    for i in range(0, 12, 2):
        v = state.validators[i]
        v.slashed = True
        v.withdrawable_epoch = epoch + 1 + half  # penalty window at next epoch
        state.slashings[0] = int(state.slashings[0]) + int(v.effective_balance)
    for i in range(1, 12, 4):
        state.validators[i].slashed = True
        state.validators[i].withdrawable_epoch = epoch + 100  # outside window
    assert_columnar_parity(spec, state)


@with_phases(["gloas"])
@spec_state_test
def test_columnar_builder_payment_settlement(spec, state):
    """Above-quorum builder payments must settle (exit churn + pending
    withdrawal append) identically in the columnar and object epochs —
    the gloas-specific queue-interleave delta."""
    next_epoch_with_attestations(spec, state, fill_cur_epoch=False, fill_prev_epoch=True)
    quorum = spec.get_builder_payment_quorum_threshold(state)
    payments = list(state.builder_pending_payments)
    for i in (0, 2):
        payments[i] = spec.BuilderPendingPayment(
            weight=quorum + 1 + i,
            withdrawal=spec.BuilderPendingWithdrawal(
                fee_recipient=b"\x42" * 20,
                amount=spec.MIN_ACTIVATION_BALANCE // 4,
                builder_index=i,
                withdrawable_epoch=0,
            ),
        )
    payments[4] = spec.BuilderPendingPayment(  # below quorum: must NOT settle
        weight=max(quorum - 1, 0),
        withdrawal=spec.BuilderPendingWithdrawal(
            fee_recipient=b"\x43" * 20,
            amount=spec.MIN_ACTIVATION_BALANCE // 8,
            builder_index=5,
            withdrawable_epoch=0,
        ),
    )
    state.builder_pending_payments = payments
    pre_withdrawals = len(state.builder_pending_withdrawals)
    assert_columnar_parity(spec, state)
    # settlement actually happened (2 above-quorum payments from the
    # previous-epoch half of the queue; the below-quorum one did not)
    # assert_columnar_parity already advanced state to the boundary slot
    check = state.copy()
    spec.process_epoch_object(check)
    assert len(check.builder_pending_withdrawals) == pre_withdrawals + 2


@with_phases(FLAG_FORKS)
@spec_state_test
def test_columnar_sync_committee_rotation_epoch(spec, state):
    """Run parity across the epoch whose transition resamples the sync
    committee (covers post-writeback effective-balance ordering)."""
    period_slots = spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
    next_slots(spec, state, period_slots - int(state.slot) - 1)
    # unbalance some effective balances so resampling is sensitive to them
    for i in range(0, len(state.validators), 5):
        state.balances[i] = int(state.balances[i]) // 2
    assert_columnar_parity(spec, state)
