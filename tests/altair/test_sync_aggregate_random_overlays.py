"""Sync-aggregate RANDOM participation and lifecycle overlays (reference
analogue: eth2spec/test/altair/block_processing/sync_aggregate/
test_process_sync_aggregate_random.py; spec:
specs/altair/beacon-chain.md process_sync_aggregate — participation is
independent of the members' exit/slash status)."""

import random

from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.state import next_slot
from eth_consensus_specs_tpu.test_infra.sync_committee import (
    committee_indices,
    make_sync_aggregate,
    run_sync_aggregate_processing,
)

ALTAIR_ON = ["altair", "bellatrix", "capella", "deneb", "electra", "fulu"]


def _run_with_bits(spec, state, bits):
    next_slot(spec, state)  # a previous block root must exist
    aggregate = make_sync_aggregate(spec, state, bits)
    for _ in run_sync_aggregate_processing(spec, state, aggregate):
        pass


def _random_bits(spec, rng):
    return [rng.random() < 0.5 for _ in range(int(spec.SYNC_COMMITTEE_SIZE))]


@with_phases(ALTAIR_ON)
@spec_state_test
def test_random_participation_seeds(spec, state):
    for seed in (400, 401, 402):
        rng = random.Random(seed)
        _run_with_bits(spec, state.copy(), _random_bits(spec, rng))


@with_phases(ALTAIR_ON)
@spec_state_test
def test_only_one_participant(spec, state):
    bits = [False] * int(spec.SYNC_COMMITTEE_SIZE)
    bits[3] = True
    _run_with_bits(spec, state, bits)


@with_phases(ALTAIR_ON)
@spec_state_test
def test_all_but_one_participant(spec, state):
    bits = [True] * int(spec.SYNC_COMMITTEE_SIZE)
    bits[3] = False
    _run_with_bits(spec, state, bits)


@with_phases(ALTAIR_ON)
@spec_state_test
def test_slashed_member_still_participates(spec, state):
    """Slashing does not remove a member from the committee for the
    period: its signature stays valid and it still earns the reward."""
    member = int(committee_indices(spec, state)[0])
    state.validators[member].slashed = True
    before = int(state.balances[member])
    bits = [True] * int(spec.SYNC_COMMITTEE_SIZE)
    _run_with_bits(spec, state, bits)
    assert int(state.balances[member]) > before


@with_phases(ALTAIR_ON)
@spec_state_test
def test_random_with_exits_and_slashings(spec, state):
    """Random participation over a committee with scattered exits and
    slashings: participants gain, sole non-participants lose."""
    rng = random.Random(403)
    for member in set(int(i) for i in committee_indices(spec, state)):
        roll = rng.random()
        if roll < 0.15:
            state.validators[member].exit_epoch = spec.get_current_epoch(state)
        elif roll < 0.3:
            state.validators[member].slashed = True
    bits = _random_bits(spec, rng)
    members = [int(i) for i in committee_indices(spec, state)]
    before = [int(b) for b in state.balances]
    _run_with_bits(spec, state, bits)
    proposer = int(spec.get_beacon_proposer_index(state))
    # participants gained, non-participants lost (proposer may offset)
    for pos, member in enumerate(members):
        if member == proposer:
            continue
        if bits[pos]:
            assert int(state.balances[member]) > before[member], member
        elif members.count(member) == 1:
            assert int(state.balances[member]) < before[member], member
