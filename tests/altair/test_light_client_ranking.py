"""Light-client update RANKING and validation tables (reference analogue:
eth2spec/test/altair/light_client/test_update_ranking.py and
test_sync.py invalid tables; spec:
specs/altair/light-client/sync-protocol.md `is_better_update` and
`validate_light_client_update`)."""

from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.context import (
    always_bls,
    expect_assertion_error,
    spec_state_test_with_matching_config,
    with_phases,
)

from .test_light_client import (
    LC_FORKS,
    _advance_with_light_client_update,
    _bootstrap_store,
)


def _update_pair(spec, state):
    store, _ = _bootstrap_store(spec, state)
    update, sig_state = _advance_with_light_client_update(spec, state)
    return store, update, sig_state


def _strip_supermajority(spec, update):
    u = update.copy()
    # leave just over half (>= min participants, < 2/3)
    keep = spec.SYNC_COMMITTEE_SIZE // 2 + 1
    for i in range(keep, spec.SYNC_COMMITTEE_SIZE):
        u.sync_aggregate.sync_committee_bits[i] = False
    return u


def _strip_finality(spec, update):
    u = update.copy()
    u.finalized_header = type(u.finalized_header)()
    u.finality_branch = type(u.finality_branch)(
        [b"\x00" * 32 for _ in range(len(u.finality_branch))]
    )
    return u


# == is_better_update decision table =======================================


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_ranking_supermajority_beats_participation_count(spec, state):
    _, update, _ = _update_pair(spec, state)
    sub = _strip_supermajority(spec, update)
    assert spec.is_better_update(update, sub)
    assert not spec.is_better_update(sub, update)


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_ranking_equal_updates_not_better(spec, state):
    _, update, _ = _update_pair(spec, state)
    assert not spec.is_better_update(update.copy(), update)


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_ranking_among_non_supermajority_more_bits_win(spec, state):
    _, update, _ = _update_pair(spec, state)
    a = _strip_supermajority(spec, update)
    b = a.copy()
    b.sync_aggregate.sync_committee_bits[0] = False  # one fewer bit
    assert spec.is_better_update(a, b)
    assert not spec.is_better_update(b, a)


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_ranking_finality_preferred(spec, state):
    _, update, _ = _update_pair(spec, state)
    if not spec.is_finality_update(update):
        return  # no finality progress at genesis-era updates in this fork
    no_fin = _strip_finality(spec, update)
    assert spec.is_better_update(update, no_fin)
    assert not spec.is_better_update(no_fin, update)


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_ranking_older_attested_slot_tiebreak(spec, state):
    _, update, _ = _update_pair(spec, state)
    older = update.copy()
    newer = update.copy()
    newer.attested_header.beacon.slot = int(update.attested_header.beacon.slot) + 1
    # all else equal: the OLDER attested header wins the final tiebreak
    assert spec.is_better_update(older, newer)


# == validate_light_client_update invalid table ============================


def _process(spec, store, update, sig_state, current_slot=None):
    slot = int(sig_state.slot) + 1 if current_slot is None else current_slot
    spec.process_light_client_update(
        store, update, slot, sig_state.genesis_validators_root
    )


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_invalid_bad_finality_branch(spec, state):
    store, update, sig_state = _update_pair(spec, state)
    bad = update.copy()
    if not spec.is_finality_update(bad):
        return
    bad.finality_branch[0] = b"\x13" * 32
    expect_assertion_error(lambda: _process(spec, store, bad, sig_state))


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_invalid_finalized_header_mismatch(spec, state):
    store, update, sig_state = _update_pair(spec, state)
    bad = update.copy()
    if not spec.is_finality_update(bad):
        return
    bad.finalized_header.beacon.state_root = b"\x55" * 32
    expect_assertion_error(lambda: _process(spec, store, bad, sig_state))


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_invalid_signature_slot_not_after_attested(spec, state):
    store, update, sig_state = _update_pair(spec, state)
    bad = update.copy()
    bad.signature_slot = bad.attested_header.beacon.slot  # must be strictly after
    expect_assertion_error(lambda: _process(spec, store, bad, sig_state))


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_invalid_update_from_the_future(spec, state):
    store, update, sig_state = _update_pair(spec, state)
    # current slot BEFORE the signature slot: not yet processable
    expect_assertion_error(
        lambda: _process(
            spec, store, update, sig_state, current_slot=int(update.signature_slot) - 1
        )
    )


@with_phases(LC_FORKS)
@always_bls
@spec_state_test_with_matching_config
def test_invalid_flipped_participation_signature(spec, state):
    store, update, sig_state = _update_pair(spec, state)
    bad = update.copy()
    # claim LESS participation than was signed: aggregate no longer matches
    bad.sync_aggregate.sync_committee_bits[0] = False
    expect_assertion_error(lambda: _process(spec, store, bad, sig_state))


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_optimistic_update_advances_only_optimistic_head(spec, state):
    store, update, sig_state = _update_pair(spec, state)
    pre_finalized = hash_tree_root(store.finalized_header.beacon)
    optimistic = spec.create_light_client_optimistic_update(update)
    spec.process_light_client_optimistic_update(
        store, optimistic, int(sig_state.slot) + 1, sig_state.genesis_validators_root
    )
    assert hash_tree_root(store.optimistic_header.beacon) == hash_tree_root(
        update.attested_header.beacon
    )
    assert hash_tree_root(store.finalized_header.beacon) == pre_finalized


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_finality_update_shape_roundtrip(spec, state):
    _, update, _ = _update_pair(spec, state)
    fin = spec.create_light_client_finality_update(update)
    assert hash_tree_root(fin.attested_header.beacon) == hash_tree_root(
        update.attested_header.beacon
    )
    assert bytes(fin.sync_aggregate.sync_committee_signature) == bytes(
        update.sync_aggregate.sync_committee_signature
    )
