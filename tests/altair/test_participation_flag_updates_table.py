"""process_participation_flag_updates shape table (reference analogue:
eth2spec/test/altair/epoch_processing/
test_process_participation_flag_updates.py; spec:
specs/altair/beacon-chain.md process_participation_flag_updates — the
epoch rotation current->previous with a zeroed current)."""

import random

from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.epoch_processing import (
    run_epoch_processing_with,
)
from eth_consensus_specs_tpu.test_infra.state import next_epoch

ALTAIR_ON = ["altair", "bellatrix", "capella", "deneb", "electra", "fulu", "gloas"]

FULL_FLAGS = 0b111


def _set_flags(state, previous, current):
    for i in range(len(state.validators)):
        state.previous_epoch_participation[i] = previous(i)
        state.current_epoch_participation[i] = current(i)


def _run_and_check(spec, state):
    """Drive the sub-transition and assert the rotation semantics."""
    expected_previous = [int(v) for v in state.current_epoch_participation]
    for _ in run_epoch_processing_with(
        spec, state, "process_participation_flag_updates"
    ):
        pass
    assert [int(v) for v in state.previous_epoch_participation] == expected_previous
    assert all(int(v) == 0 for v in state.current_epoch_participation)
    assert len(state.current_epoch_participation) == len(state.validators)


@with_phases(ALTAIR_ON)
@spec_state_test
def test_flag_rotation_all_zeroed(spec, state):
    _set_flags(state, lambda i: 0, lambda i: 0)
    _run_and_check(spec, state)


@with_phases(ALTAIR_ON)
@spec_state_test
def test_flag_rotation_filled(spec, state):
    _set_flags(state, lambda i: FULL_FLAGS, lambda i: FULL_FLAGS)
    _run_and_check(spec, state)


@with_phases(ALTAIR_ON)
@spec_state_test
def test_flag_rotation_previous_filled_only(spec, state):
    """The old previous-epoch flags are DISCARDED by the rotation."""
    _set_flags(state, lambda i: FULL_FLAGS, lambda i: 0)
    _run_and_check(spec, state)


@with_phases(ALTAIR_ON)
@spec_state_test
def test_flag_rotation_current_filled_only(spec, state):
    _set_flags(state, lambda i: 0, lambda i: FULL_FLAGS)
    _run_and_check(spec, state)


@with_phases(ALTAIR_ON)
@spec_state_test
def test_flag_rotation_alternating_pattern(spec, state):
    _set_flags(
        state,
        lambda i: FULL_FLAGS if i % 2 == 0 else 0,
        lambda i: 0 if i % 2 == 0 else FULL_FLAGS,
    )
    _run_and_check(spec, state)


@with_phases(ALTAIR_ON)
@spec_state_test
def test_flag_rotation_random_patterns(spec, state):
    for seed in (10, 11, 12):
        rng = random.Random(seed)
        _set_flags(
            state,
            lambda i: rng.getrandbits(3),
            lambda i: rng.getrandbits(3),
        )
        _run_and_check(spec, state)
        next_epoch(spec, state)  # leave the boundary before the next round


@with_phases(ALTAIR_ON)
@spec_state_test
def test_flag_rotation_single_bit_lanes(spec, state):
    """Each individual flag bit survives the rotation positionally."""
    for bit in range(3):
        _set_flags(state, lambda i: 0, lambda i, b=bit: 1 << b)
        _run_and_check(spec, state)
        next_epoch(spec, state)  # leave the boundary before the next round
