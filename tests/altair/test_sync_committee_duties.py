"""Altair sync-committee validator duties (reference analogue:
eth2spec/test/altair/unittests/validator/test_validator.py; spec:
specs/altair/validator.md — messages, selection proofs, aggregator
selection, contributions, contribution-and-proof envelopes)."""

from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.context import (
    always_bls,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.keys import privkeys
from eth_consensus_specs_tpu.test_infra.sync_committee import committee_indices
from eth_consensus_specs_tpu.utils import bls

ALTAIR_ON = ["altair", "bellatrix", "capella", "deneb", "electra", "fulu"]


def _subcommittee_size(spec) -> int:
    return int(spec.SYNC_COMMITTEE_SIZE) // int(spec.SYNC_COMMITTEE_SUBNET_COUNT)


# == sync committee messages ===============================================


@with_phases(ALTAIR_ON)
@always_bls
@spec_state_test
def test_sync_committee_message_verifies(spec, state):
    root = b"\x12" * 32
    msg = spec.get_sync_committee_message(state, root, 0, privkeys[0])
    assert int(msg.slot) == int(state.slot)
    assert bytes(msg.beacon_block_root) == root
    domain = spec.get_domain(
        state, spec.DOMAIN_SYNC_COMMITTEE, spec.get_current_epoch(state)
    )
    signing_root = spec.compute_signing_root(spec.Root(root), domain)
    assert bls.Verify(state.validators[0].pubkey, signing_root, msg.signature)


# == selection proofs and aggregator selection =============================


@with_phases(ALTAIR_ON)
@always_bls
@spec_state_test
def test_selection_proof_binds_slot_and_subcommittee(spec, state):
    proof_a = spec.get_sync_committee_selection_proof(state, 0, 0, privkeys[0])
    proof_b = spec.get_sync_committee_selection_proof(state, 0, 1, privkeys[0])
    proof_c = spec.get_sync_committee_selection_proof(state, 1, 0, privkeys[0])
    assert len({bytes(proof_a), bytes(proof_b), bytes(proof_c)}) == 3
    domain = spec.get_domain(
        state, spec.DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF, spec.compute_epoch_at_slot(0)
    )
    data = spec.SyncAggregatorSelectionData(slot=0, subcommittee_index=0)
    assert bls.Verify(
        state.validators[0].pubkey,
        spec.compute_signing_root(data, domain),
        proof_a,
    )


@with_phases(ALTAIR_ON)
@spec_state_test
def test_sync_aggregator_selection_deterministic(spec, state):
    """Selection is a pure function of the proof bytes with the spec's
    modulo (minimal: subcommittee 8 / target 16 -> modulo 1: everyone)."""
    modulo = max(
        1,
        _subcommittee_size(spec) // int(spec.TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE),
    )
    results = []
    for i in range(8):
        sig = spec.get_sync_committee_selection_proof(state, 0, 0, privkeys[i])
        got = spec.is_sync_committee_aggregator(sig)
        assert got == spec.is_sync_committee_aggregator(sig)
        results.append(got)
    if modulo == 1:
        assert all(results)


# == contributions =========================================================


def _full_contribution(spec, state, subcommittee_index=0, block_root=b"\x34" * 32):
    size = _subcommittee_size(spec)
    members = committee_indices(spec, state)[
        subcommittee_index * size : (subcommittee_index + 1) * size
    ]
    sigs = []
    contribution = spec.SyncCommitteeContribution(
        slot=state.slot,
        beacon_block_root=block_root,
        subcommittee_index=subcommittee_index,
    )
    for pos, validator_index in enumerate(members):
        contribution.aggregation_bits[pos] = True
        msg = spec.get_sync_committee_message(
            state, block_root, validator_index, privkeys[int(validator_index)]
        )
        sigs.append(msg.signature)
    contribution.signature = bls.Aggregate(sigs)
    return contribution


@with_phases(ALTAIR_ON)
@spec_state_test
def test_process_sync_committee_contributions_sets_bits(spec, state):
    """One full contribution per subnet reassembles the FULL aggregate."""
    block = spec.BeaconBlock(slot=state.slot)
    contributions = [
        _full_contribution(spec, state, subcommittee_index=i)
        for i in range(int(spec.SYNC_COMMITTEE_SUBNET_COUNT))
    ]
    spec.process_sync_committee_contributions(block, contributions)
    agg = block.body.sync_aggregate
    assert all(bool(b) for b in agg.sync_committee_bits)


@with_phases(ALTAIR_ON)
@spec_state_test
def test_process_contributions_partial_subnets(spec, state):
    """A single subnet's contribution sets exactly its bit window."""
    block = spec.BeaconBlock(slot=state.slot)
    sub = 1
    spec.process_sync_committee_contributions(
        block, [_full_contribution(spec, state, subcommittee_index=sub)]
    )
    size = _subcommittee_size(spec)
    bits = block.body.sync_aggregate.sync_committee_bits
    for i in range(int(spec.SYNC_COMMITTEE_SIZE)):
        expected = sub * size <= i < (sub + 1) * size
        assert bool(bits[i]) == expected


@with_phases(ALTAIR_ON)
@always_bls
@spec_state_test
def test_contribution_roundtrip_through_sync_aggregate_processing(spec, state):
    """Contributions assembled by the duty pipeline verify as a real
    block-level sync aggregate."""
    from eth_consensus_specs_tpu.test_infra.state import next_slot
    from eth_consensus_specs_tpu.test_infra.sync_committee import (
        build_root_for_current_slot,
    )

    next_slot(spec, state)  # genesis slot has no previous block root
    root = build_root_for_current_slot(spec, state)
    block = spec.BeaconBlock(slot=state.slot)
    contributions = [
        _full_contribution(spec, state, subcommittee_index=i, block_root=root)
        for i in range(int(spec.SYNC_COMMITTEE_SUBNET_COUNT))
    ]
    spec.process_sync_committee_contributions(block, contributions)
    spec.process_sync_aggregate(state, block.body.sync_aggregate)


# == contribution-and-proof envelopes ======================================


@with_phases(ALTAIR_ON)
@spec_state_test
def test_contribution_and_proof_carries_selection(spec, state):
    contribution = _full_contribution(spec, state)
    cap = spec.get_contribution_and_proof(state, 5, contribution, privkeys[5])
    assert int(cap.aggregator_index) == 5
    assert hash_tree_root(cap.contribution) == hash_tree_root(contribution)
    assert bytes(cap.selection_proof) == bytes(
        spec.get_sync_committee_selection_proof(
            state, contribution.slot, contribution.subcommittee_index, privkeys[5]
        )
    )


@with_phases(ALTAIR_ON)
@always_bls
@spec_state_test
def test_contribution_and_proof_signature_verifies(spec, state):
    contribution = _full_contribution(spec, state)
    cap = spec.get_contribution_and_proof(state, 5, contribution, privkeys[5])
    sig = spec.get_contribution_and_proof_signature(state, cap, privkeys[5])
    domain = spec.get_domain(
        state,
        spec.DOMAIN_CONTRIBUTION_AND_PROOF,
        spec.compute_epoch_at_slot(contribution.slot),
    )
    assert bls.Verify(
        state.validators[5].pubkey, spec.compute_signing_root(cap, domain), sig
    )


@with_phases(ALTAIR_ON)
@spec_state_test
def test_compute_subnets_cover_all_members(spec, state):
    """Every sync-committee member maps to at least one subnet, and all
    subnet ids are in range."""
    n_subnets = int(spec.SYNC_COMMITTEE_SUBNET_COUNT)
    seen = set()
    for validator_index in set(int(i) for i in committee_indices(spec, state)):
        subnets = spec.compute_subnets_for_sync_committee(state, validator_index)
        assert subnets
        assert all(0 <= int(s) < n_subnets for s in subnets)
        seen.update(int(s) for s in subnets)
    assert seen == set(range(n_subnets))
