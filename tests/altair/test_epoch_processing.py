"""Altair epoch processing: inactivity scores, participation-flag rotation,
sync-committee rotation (reference analogue: test/altair/epoch_processing/*)."""

from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.attestations import next_epoch_with_attestations
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.manifest import manifest
from eth_consensus_specs_tpu.test_infra.epoch_processing import (
    run_epoch_processing_to,
    run_epoch_processing_with,
)
from eth_consensus_specs_tpu.test_infra.state import next_epoch


@manifest(handler="inactivity_updates")
@with_phases(["altair"])
@spec_state_test
def test_inactivity_scores_increase_when_absent(spec, state):
    # several empty epochs -> leak; eligible validators accrue BIAS per epoch
    for _ in range(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY + 2):
        next_epoch(spec, state)
    assert spec.is_in_inactivity_leak(state)
    yield from run_epoch_processing_with(spec, state, "process_inactivity_updates")
    assert all(int(s) > 0 for s in state.inactivity_scores)


@with_phases(["altair"])
@spec_state_test
def test_inactivity_scores_recover_when_participating(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    run_epoch_processing_to(spec, state, "process_inactivity_updates")
    assert not spec.is_in_inactivity_leak(state)
    for i in range(len(state.validators)):
        state.inactivity_scores[i] = 30
        state.previous_epoch_participation[i] = spec.add_flag(
            0, spec.TIMELY_TARGET_FLAG_INDEX
        )
    spec.process_inactivity_updates(state)
    # participating validators: -1 for participation, -RECOVERY_RATE leak-free
    expected = 30 - 1 - spec.config.INACTIVITY_SCORE_RECOVERY_RATE
    assert all(int(s) == expected for s in state.inactivity_scores)


@with_phases(["altair"])
@spec_state_test
def test_participation_flag_rotation(spec, state):
    # attesting through an epoch leaves flags in PREVIOUS participation
    # (the boundary inside the helper already rotated current -> previous)
    next_epoch(spec, state)
    next_epoch_with_attestations(spec, state, fill_cur_epoch=True, fill_prev_epoch=False)
    assert any(int(f) != 0 for f in state.previous_epoch_participation)
    assert all(int(f) == 0 for f in state.current_epoch_participation)
    # now verify the rotation itself on handcrafted current flags
    for i in range(0, len(state.validators), 2):
        state.current_epoch_participation[i] = spec.add_flag(0, spec.TIMELY_SOURCE_FLAG_INDEX)
    current = [int(f) for f in state.current_epoch_participation]
    spec.process_participation_flag_updates(state)
    assert [int(f) for f in state.previous_epoch_participation] == current
    assert all(int(f) == 0 for f in state.current_epoch_participation)


@manifest(handler="sync_committee_updates")
@with_phases(["altair"])
@spec_state_test
def test_sync_committee_rotation_at_period_boundary(spec, state):
    # advance to one epoch before the period boundary
    period = spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
    target_epoch = period - 1
    while spec.get_current_epoch(state) < target_epoch:
        next_epoch(spec, state)
    old_next = state.next_sync_committee.copy()
    yield from run_epoch_processing_with(spec, state, "process_sync_committee_updates")
    assert hash_tree_root(state.current_sync_committee) == hash_tree_root(old_next)


@manifest(handler="sync_committee_updates")
@with_phases(["altair"])
@spec_state_test
def test_sync_committee_no_rotation_mid_period(spec, state):
    next_epoch(spec, state)
    assert (spec.get_current_epoch(state) + 1) % spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD != 0
    old_current = state.current_sync_committee.copy()
    old_next = state.next_sync_committee.copy()
    yield from run_epoch_processing_with(spec, state, "process_sync_committee_updates")
    assert hash_tree_root(state.current_sync_committee) == hash_tree_root(old_current)
    assert hash_tree_root(state.next_sync_committee) == hash_tree_root(old_next)


@with_phases(["altair"])
@spec_state_test
def test_flag_rewards_full_participation(spec, state):
    next_epoch(spec, state)
    next_epoch_with_attestations(spec, state, fill_cur_epoch=True, fill_prev_epoch=True)
    next_epoch_with_attestations(spec, state, fill_cur_epoch=True, fill_prev_epoch=True)
    run_epoch_processing_to(spec, state, "process_rewards_and_penalties")
    pre_balances = [int(b) for b in state.balances]
    spec.process_rewards_and_penalties(state)
    # full participation: every validator nets positive
    assert all(int(b) > p for b, p in zip(state.balances, pre_balances))


@with_phases(["altair"])
@spec_state_test
def test_flag_penalties_no_participation(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)
    next_epoch(spec, state)
    run_epoch_processing_to(spec, state, "process_rewards_and_penalties")
    pre_balances = [int(b) for b in state.balances]
    spec.process_rewards_and_penalties(state)
    assert all(int(b) < p for b, p in zip(state.balances, pre_balances))
