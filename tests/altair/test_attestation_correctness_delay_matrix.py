"""Attestation correctness x inclusion-delay matrix: which participation
flags each (head/target correctness, delay) combination earns (reference
analogue: eth2spec/test/phase0/block_processing/test_process_attestation.py
`test_{correct,incorrect_head,incorrect_target,...}_included_at_*`; spec:
specs/altair/beacon-chain.md get_attestation_participation_flag_indices,
deneb's removal of the target-flag delay cap)."""

from eth_consensus_specs_tpu.test_infra.attestations import get_valid_attestation
from eth_consensus_specs_tpu.test_infra.context import (
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.forks import is_post_deneb
from eth_consensus_specs_tpu.test_infra.state import next_slots

ALTAIR_ON = ["altair", "bellatrix", "capella", "deneb", "electra", "fulu"]


def _prepared_attestation(spec, state, wrong_head=False, wrong_target=False):
    """Attestation for the current slot, optionally corrupted in the
    LMD/FFG vote (still includable — correctness only affects flags)."""
    attestation = get_valid_attestation(spec, state, signed=False)
    if wrong_head:
        attestation.data.beacon_block_root = b"\x99" * 32
    if wrong_target:
        attestation.data.target.root = b"\x88" * 32
    return attestation


def _include_at_delay(spec, state, attestation, delay: int):
    next_slots(spec, state, delay)
    spec.process_attestation(state, attestation)


def _attester_flags(spec, state, attestation):
    """The flag set of the first attesting validator (all attesters in a
    committee share the same flag outcome)."""
    committee = spec.get_beacon_committee(
        state, attestation.data.slot, attestation.data.index
    )
    epoch_bits = (
        state.current_epoch_participation
        if int(attestation.data.target.epoch) == int(spec.get_current_epoch(state))
        else state.previous_epoch_participation
    )
    return int(epoch_bits[int(committee[0])])


# == correct vote ==========================================================


@with_phases(ALTAIR_ON)
@spec_state_test
def test_correct_at_min_delay_all_flags(spec, state):
    attestation = _prepared_attestation(spec, state)
    _include_at_delay(spec, state, attestation, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))
    flags = _attester_flags(spec, state, attestation)
    assert spec.has_flag(flags, int(spec.TIMELY_SOURCE_FLAG_INDEX))
    assert spec.has_flag(flags, int(spec.TIMELY_TARGET_FLAG_INDEX))
    assert spec.has_flag(flags, int(spec.TIMELY_HEAD_FLAG_INDEX))


@with_phases(ALTAIR_ON)
@spec_state_test
def test_correct_at_sqrt_epoch_delay_drops_head(spec, state):
    delay = int(spec.integer_squareroot(spec.SLOTS_PER_EPOCH))
    attestation = _prepared_attestation(spec, state)
    _include_at_delay(spec, state, attestation, delay)
    flags = _attester_flags(spec, state, attestation)
    assert spec.has_flag(flags, int(spec.TIMELY_SOURCE_FLAG_INDEX))
    assert spec.has_flag(flags, int(spec.TIMELY_TARGET_FLAG_INDEX))
    assert not spec.has_flag(flags, int(spec.TIMELY_HEAD_FLAG_INDEX))


@with_phases(ALTAIR_ON)
@spec_state_test
def test_correct_at_epoch_delay_target_only_plus_deneb_rule(spec, state):
    """At a full-epoch delay the source window has passed; the target flag
    survives (for deneb+ it has NO delay cap at all)."""
    delay = int(spec.SLOTS_PER_EPOCH)
    attestation = _prepared_attestation(spec, state)
    _include_at_delay(spec, state, attestation, delay)
    flags = _attester_flags(spec, state, attestation)
    assert not spec.has_flag(flags, int(spec.TIMELY_SOURCE_FLAG_INDEX))
    assert spec.has_flag(flags, int(spec.TIMELY_TARGET_FLAG_INDEX))
    assert not spec.has_flag(flags, int(spec.TIMELY_HEAD_FLAG_INDEX))


@with_phases(ALTAIR_ON)
@spec_state_test
def test_invalid_after_max_inclusion_window(spec, state):
    """Pre-deneb the inclusion window is one epoch; deneb+ allows any
    delay within the previous-epoch target rule (EIP-7045)."""
    attestation = _prepared_attestation(spec, state)
    delay = int(spec.SLOTS_PER_EPOCH) + 1
    if is_post_deneb(spec):
        # still includable: target is the previous epoch now
        _include_at_delay(spec, state, attestation, delay)
        flags = _attester_flags(spec, state, attestation)
        assert spec.has_flag(flags, int(spec.TIMELY_TARGET_FLAG_INDEX))
    else:
        next_slots(spec, state, delay)
        expect_assertion_error(lambda: spec.process_attestation(state, attestation))


# == incorrect head ========================================================


@with_phases(ALTAIR_ON)
@spec_state_test
def test_incorrect_head_at_min_delay(spec, state):
    attestation = _prepared_attestation(spec, state, wrong_head=True)
    _include_at_delay(spec, state, attestation, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))
    flags = _attester_flags(spec, state, attestation)
    assert spec.has_flag(flags, int(spec.TIMELY_SOURCE_FLAG_INDEX))
    assert spec.has_flag(flags, int(spec.TIMELY_TARGET_FLAG_INDEX))
    assert not spec.has_flag(flags, int(spec.TIMELY_HEAD_FLAG_INDEX))


@with_phases(ALTAIR_ON)
@spec_state_test
def test_incorrect_head_at_sqrt_epoch_delay(spec, state):
    delay = int(spec.integer_squareroot(spec.SLOTS_PER_EPOCH))
    attestation = _prepared_attestation(spec, state, wrong_head=True)
    _include_at_delay(spec, state, attestation, delay)
    flags = _attester_flags(spec, state, attestation)
    assert spec.has_flag(flags, int(spec.TIMELY_SOURCE_FLAG_INDEX))
    assert spec.has_flag(flags, int(spec.TIMELY_TARGET_FLAG_INDEX))
    assert not spec.has_flag(flags, int(spec.TIMELY_HEAD_FLAG_INDEX))


# == incorrect target ======================================================


@with_phases(ALTAIR_ON)
@spec_state_test
def test_incorrect_target_at_min_delay_source_only(spec, state):
    attestation = _prepared_attestation(spec, state, wrong_target=True)
    _include_at_delay(spec, state, attestation, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))
    flags = _attester_flags(spec, state, attestation)
    assert spec.has_flag(flags, int(spec.TIMELY_SOURCE_FLAG_INDEX))
    assert not spec.has_flag(flags, int(spec.TIMELY_TARGET_FLAG_INDEX))
    # head can never match when the target doesn't
    assert not spec.has_flag(flags, int(spec.TIMELY_HEAD_FLAG_INDEX))


@with_phases(ALTAIR_ON)
@spec_state_test
def test_incorrect_target_at_epoch_delay_no_flags(spec, state):
    delay = int(spec.SLOTS_PER_EPOCH)
    attestation = _prepared_attestation(spec, state, wrong_target=True)
    _include_at_delay(spec, state, attestation, delay)
    flags = _attester_flags(spec, state, attestation)
    assert flags == 0


# == incorrect head AND target =============================================


@with_phases(ALTAIR_ON)
@spec_state_test
def test_incorrect_head_and_target_at_min_delay(spec, state):
    attestation = _prepared_attestation(spec, state, wrong_head=True, wrong_target=True)
    _include_at_delay(spec, state, attestation, int(spec.MIN_ATTESTATION_INCLUSION_DELAY))
    flags = _attester_flags(spec, state, attestation)
    assert spec.has_flag(flags, int(spec.TIMELY_SOURCE_FLAG_INDEX))
    assert not spec.has_flag(flags, int(spec.TIMELY_TARGET_FLAG_INDEX))
    assert not spec.has_flag(flags, int(spec.TIMELY_HEAD_FLAG_INDEX))
