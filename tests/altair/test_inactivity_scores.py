"""Inactivity-score update table (spec: specs/altair/beacon-chain.md
process_inactivity_updates; reference analogue:
test/altair/epoch_processing/test_process_inactivity_updates.py)."""

from eth_consensus_specs_tpu.test_infra.attestations import (
    next_epoch_with_attestations,
)
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.state import next_epoch

ALTAIR_PLUS = ["altair", "deneb", "electra"]


@with_phases(ALTAIR_PLUS)
@spec_state_test
def test_scores_zero_at_genesis_epoch_boundary(spec, state):
    next_epoch(spec, state)
    assert all(int(s) == 0 for s in state.inactivity_scores)


@with_phases(ALTAIR_PLUS)
@spec_state_test
def test_nonparticipation_raises_scores(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)  # prev epoch now has zero participation
    next_epoch(spec, state)
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    recovery = int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE)
    expected = max(bias - recovery, 0)  # leak-free recovery applies
    assert all(int(s) == expected for s in state.inactivity_scores)


@with_phases(["altair"])
@spec_state_test
def test_full_participation_keeps_scores_zero(spec, state):
    next_epoch(spec, state)
    _, _, state = next_epoch_with_attestations(spec, state, True, False)
    next_epoch(spec, state)
    assert all(int(s) == 0 for s in state.inactivity_scores)


@with_phases(["altair"])
@spec_state_test
def test_participating_score_decrements(spec, state):
    next_epoch(spec, state)
    for i in range(len(state.inactivity_scores)):
        state.inactivity_scores[i] = 10
    _, _, state = next_epoch_with_attestations(spec, state, True, False)
    next_epoch(spec, state)
    # -1 for participation, then leak-free recovery
    recovery = int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE)
    expected = max(10 - 1 - recovery, 0)
    assert all(int(s) == expected for s in state.inactivity_scores)


@with_phases(["altair"])
@spec_state_test
def test_score_floors_at_zero(spec, state):
    next_epoch(spec, state)
    _, _, state = next_epoch_with_attestations(spec, state, True, False)
    next_epoch(spec, state)
    assert all(int(s) >= 0 for s in state.inactivity_scores)


@with_phases(["altair"])
@spec_state_test
def test_leak_blocks_recovery(spec, state):
    """Once the inactivity leak is on, the recovery-rate decrement is
    withheld: one more non-participating epoch adds exactly +bias."""
    next_epoch(spec, state)
    for _ in range(int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 2):
        next_epoch(spec, state)
    assert spec.is_in_inactivity_leak(state)
    snapshot = [int(s) for s in state.inactivity_scores]
    next_epoch(spec, state)
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    assert [int(s) for s in state.inactivity_scores] == [s + bias for s in snapshot]


@with_phases(["altair"])
@spec_state_test
def test_exited_validators_score_untouched(spec, state):
    """A fully exited, non-slashed validator is not eligible — its score
    freezes once the previous epoch is past its exit."""
    next_epoch(spec, state)
    idx = 3
    state.validators[idx].exit_epoch = spec.get_current_epoch(state)
    state.validators[idx].withdrawable_epoch = spec.get_current_epoch(state)
    # advance until prev_epoch >= exit_epoch (eligibility gone)
    next_epoch(spec, state)
    next_epoch(spec, state)
    frozen = int(state.inactivity_scores[idx])
    next_epoch(spec, state)
    next_epoch(spec, state)
    assert int(state.inactivity_scores[idx]) == frozen
