"""Randomized-property inactivity-score table, altair+ (reference analogue:
test/altair/epoch_processing/test_process_inactivity_updates.py — the
21-variant file crossing {zero, random} scores x {empty, random, full}
participation x {leaking, leak-free}, plus slashed/exited overlays).

Each case drives process_inactivity_updates directly and checks every
index against a pure oracle of the spec rule
(specs/altair/beacon-chain.md process_inactivity_updates)."""

import random

from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.epoch_processing import (
    run_epoch_processing_to,
)
from eth_consensus_specs_tpu.test_infra.state import next_epoch
from eth_consensus_specs_tpu.test_infra.template import instantiate

ALTAIR_PLUS = ["altair", "bellatrix", "capella", "deneb", "electra"]


def _set_participation(spec, state, mode: str, rng):
    """Previous-epoch TIMELY_TARGET participation per `mode`."""
    target = int(spec.TIMELY_TARGET_FLAG_INDEX)
    for i in range(len(state.previous_epoch_participation)):
        if mode == "full":
            bits = 1 << target
        elif mode == "empty":
            bits = 0
        else:
            bits = (1 << target) if rng.random() < 0.5 else 0
        state.previous_epoch_participation[i] = bits


def _set_scores(spec, state, mode: str, rng):
    for i in range(len(state.inactivity_scores)):
        state.inactivity_scores[i] = (
            0 if mode == "zero" else rng.randint(0, 100)
        )


def _force_leak(spec, state):
    """Finality stuck far in the past: is_in_inactivity_leak becomes true."""
    state.finalized_checkpoint.epoch = 0
    # move forward enough epochs that finality_delay > MIN_EPOCHS_TO_INACTIVITY_PENALTY
    target = int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 3
    while int(spec.get_current_epoch(state)) < target:
        next_epoch(spec, state)


def _oracle(spec, state):
    """Expected post-scores per the spec rule, computed index-by-index."""
    participating = spec.get_unslashed_participating_indices(
        state, spec.TIMELY_TARGET_FLAG_INDEX, spec.get_previous_epoch(state)
    )
    eligible = set(spec.get_eligible_validator_indices(state))
    leak_free = not spec.is_in_inactivity_leak(state)
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    recovery = int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE)
    expected = []
    for index in range(len(state.inactivity_scores)):
        score = int(state.inactivity_scores[index])
        if index in eligible:
            if index in participating:
                score -= min(1, score)
            else:
                score += bias
            if leak_free:
                score -= min(recovery, score)
        expected.append(score)
    return expected


def _inactivity_case(scores: str, participation: str, leaking: bool, seed: int):
    @with_phases(ALTAIR_PLUS)
    @spec_state_test
    def case(spec, state):
        rng = random.Random(seed)
        if leaking:
            _force_leak(spec, state)
        else:
            next_epoch(spec, state)
            next_epoch(spec, state)
        run_epoch_processing_to(spec, state, "process_inactivity_updates")
        _set_scores(spec, state, scores, rng)
        _set_participation(spec, state, participation, rng)
        expected = _oracle(spec, state)
        spec.process_inactivity_updates(state)
        got = [int(s) for s in state.inactivity_scores]
        assert got == expected
        if leaking:
            assert spec.is_in_inactivity_leak(state)

    leak_tag = "leaking" if leaking else "leak_free"
    return case, f"test_{scores}_scores_{participation}_participation_{leak_tag}"


for _scores in ("zero", "random"):
    for _participation in ("empty", "random", "full"):
        for _leaking in (False, True):
            instantiate(
                _inactivity_case,
                _scores,
                _participation,
                _leaking,
                seed=hash((_scores, _participation, _leaking)) % 10_000,
            )


def _overlay_case(overlay: str, leaking: bool):
    """Slashed/exited overlays: slashed validators never count as
    participating; exited-but-unwithdrawn stay eligible."""

    @with_phases(ALTAIR_PLUS)
    @spec_state_test
    def case(spec, state):
        rng = random.Random(99)
        if leaking:
            _force_leak(spec, state)
        else:
            next_epoch(spec, state)
            next_epoch(spec, state)
        run_epoch_processing_to(spec, state, "process_inactivity_updates")
        _set_scores(spec, state, "random", rng)
        _set_participation(spec, state, "full", rng)
        n = len(state.validators)
        picks = rng.sample(range(n), max(1, n // 8))
        for i in picks:
            if overlay == "slashed":
                state.validators[i].slashed = True
            else:
                state.validators[i].exit_epoch = int(spec.get_previous_epoch(state))
                state.validators[i].withdrawable_epoch = (
                    int(spec.get_previous_epoch(state)) + 8
                )
        expected = _oracle(spec, state)
        bias = int(spec.config.INACTIVITY_SCORE_BIAS)
        spec.process_inactivity_updates(state)
        got = [int(s) for s in state.inactivity_scores]
        assert got == expected
        if overlay == "slashed" and leaking:
            # slashed validators are eligible non-participants: bias applies
            for i in picks:
                assert got[i] >= bias

    leak_tag = "leaking" if leaking else "leak_free"
    return case, f"test_some_{overlay}_full_participation_{leak_tag}"


for _overlay in ("slashed", "exited"):
    for _leaking in (False, True):
        instantiate(_overlay_case, _overlay, _leaking)


@with_phases(ALTAIR_PLUS)
@spec_state_test
def test_genesis_epoch_noop(spec, state):
    # still inside GENESIS_EPOCH: the function must return untouched
    assert int(spec.get_current_epoch(state)) == int(spec.GENESIS_EPOCH)
    state.inactivity_scores[0] = 55
    spec.process_inactivity_updates(state)
    assert int(state.inactivity_scores[0]) == 55
