"""Multi-period light-client SYNC scenarios: a store following a live
chain across sync-committee periods with and without finality (reference
analogue: eth2spec/test/altair/light_client/test_sync.py driven by
helpers/light_client_sync.py; spec:
specs/altair/light-client/sync-protocol.md `process_light_client_update`,
`process_light_client_store_force_update`).

The period-crossing drives are chain-heavy, so the fork matrix covers the
two gindex eras (altair = pre-execution header, electra = post-6110
gindices) rather than every fork; the per-fork header shape itself is
exercised by tests/altair/test_light_client.py across LC_FORKS.
"""

import pytest

from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test_with_matching_config,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.light_client_sync import LCSyncDriver

SYNC_FORKS = ["altair", "electra"]


def _store_period(spec, store):
    return int(
        spec.compute_sync_committee_period_at_slot(store.finalized_header.beacon.slot)
    )


# == finality advance within one period ====================================


@with_phases(SYNC_FORKS)
@spec_state_test_with_matching_config
def test_sync_finality_advance(spec, state):
    """Three attested epochs finalize; a finality update moves the store's
    finalized header forward and clears best_valid_update."""
    drv = LCSyncDriver(spec, state)
    store = drv.bootstrap_store()
    start_fin_slot = int(store.finalized_header.beacon.slot)

    drv.finalize_epochs(4)
    update, _ = drv.emit_update()
    assert spec.is_finality_update(update)
    drv.process(store, update)

    assert int(store.finalized_header.beacon.slot) > start_fin_slot
    assert store.best_valid_update is None
    assert bytes(hash_tree_root(store.finalized_header.beacon)) == bytes(
        drv.state.finalized_checkpoint.root
    )
    # optimistic head follows the attested header
    assert int(store.optimistic_header.beacon.slot) >= int(
        store.finalized_header.beacon.slot
    )


@with_phases(SYNC_FORKS)
@spec_state_test_with_matching_config
def test_sync_optimistic_only_update_held_as_best_valid(spec, state):
    """A non-finality update advances only the optimistic head; the update
    is retained as best_valid_update for a later force update."""
    drv = LCSyncDriver(spec, state)
    store = drv.bootstrap_store()
    fin_before = int(store.finalized_header.beacon.slot)

    drv.finalize_epochs(1)  # produce blocks but no new finality
    update, _ = drv.emit_update(with_finality=False)
    assert not spec.is_finality_update(update)
    drv.process(store, update)

    assert int(store.finalized_header.beacon.slot) == fin_before
    assert store.best_valid_update is not None
    assert int(store.optimistic_header.beacon.slot) == int(
        update.attested_header.beacon.slot
    )


# == period crossing =======================================================


@pytest.mark.slow
@with_phases(SYNC_FORKS)
@spec_state_test_with_matching_config
def test_sync_across_sync_committee_period(spec, state):
    """Drive the chain into the next sync-committee period with finality;
    the applied update rotates current/next sync committees."""
    drv = LCSyncDriver(spec, state)
    store = drv.bootstrap_store()

    # finalize inside period 0 so the store's next committee becomes known
    drv.finalize_epochs(4)
    upd0, _ = drv.emit_update()
    drv.process(store, upd0)
    assert _store_period(spec, store) == 0
    assert spec.is_next_sync_committee_known(store)
    committee_before = store.next_sync_committee.copy()

    # cross into period 1 and finalize there
    period_epochs = int(spec.EPOCHS_PER_SYNC_COMMITTEE_PERIOD)
    drv.skip_to_epoch_start(period_epochs)
    drv.finalize_epochs(4)
    upd1, _ = drv.emit_update()
    drv.process(store, upd1)

    assert _store_period(spec, store) == 1
    # the old next committee became the current one
    assert bytes(hash_tree_root(store.current_sync_committee)) == bytes(
        hash_tree_root(committee_before)
    )
    assert store.best_valid_update is None


@pytest.mark.slow
@with_phases(SYNC_FORKS)
@spec_state_test_with_matching_config
def test_sync_supply_committee_from_past_update(spec, state):
    """A store bootstrapped WITHOUT next-committee knowledge learns it from
    an update whose attested and finalized periods match the store's."""
    drv = LCSyncDriver(spec, state)
    store = drv.bootstrap_store()
    # forget the next committee (as after a bootstrap from an old snapshot)
    store.next_sync_committee = spec.SyncCommittee()
    assert not spec.is_next_sync_committee_known(store)

    drv.finalize_epochs(4)
    update, _ = drv.emit_update()
    assert spec.is_sync_committee_update(update) and spec.is_finality_update(update)
    drv.process(store, update)

    assert spec.is_next_sync_committee_known(store)
    assert bytes(hash_tree_root(store.next_sync_committee)) == bytes(
        hash_tree_root(drv.state.next_sync_committee)
    )


@pytest.mark.slow
@with_phases(SYNC_FORKS)
@spec_state_test_with_matching_config
def test_sync_force_update_after_timeout(spec, state):
    """With no finality for > UPDATE_TIMEOUT slots, the force-update path
    promotes best_valid_update using its attested header as finalized."""
    drv = LCSyncDriver(spec, state)
    store = drv.bootstrap_store()

    drv.finalize_epochs(1)
    update, _ = drv.emit_update(with_finality=False)
    drv.process(store, update)
    assert store.best_valid_update is not None
    fin_before = int(store.finalized_header.beacon.slot)

    timeout_slot = (
        int(store.finalized_header.beacon.slot) + int(spec.UPDATE_TIMEOUT) + 1
    )
    spec.process_light_client_store_force_update(store, timeout_slot)

    assert store.best_valid_update is None
    assert int(store.finalized_header.beacon.slot) > fin_before
    # the promoted finalized header is the update's attested header
    assert bytes(hash_tree_root(store.finalized_header.beacon)) == bytes(
        hash_tree_root(update.attested_header.beacon)
    )


@with_phases(SYNC_FORKS)
@spec_state_test_with_matching_config
def test_sync_no_force_update_before_timeout(spec, state):
    """Before UPDATE_TIMEOUT elapses the force-update path must not fire."""
    drv = LCSyncDriver(spec, state)
    store = drv.bootstrap_store()

    drv.finalize_epochs(1)
    update, _ = drv.emit_update(with_finality=False)
    drv.process(store, update)
    fin_before = int(store.finalized_header.beacon.slot)

    not_yet = int(store.finalized_header.beacon.slot) + int(spec.UPDATE_TIMEOUT)
    spec.process_light_client_store_force_update(store, not_yet)

    assert store.best_valid_update is not None
    assert int(store.finalized_header.beacon.slot) == fin_before


@with_phases(SYNC_FORKS)
@spec_state_test_with_matching_config
def test_sync_repeated_updates_keep_best(spec, state):
    """Feeding the same non-finality update twice neither regresses the
    optimistic head nor duplicates best_valid_update state."""
    drv = LCSyncDriver(spec, state)
    store = drv.bootstrap_store()

    drv.finalize_epochs(1)
    update, _ = drv.emit_update(with_finality=False)
    drv.process(store, update)
    opt_slot = int(store.optimistic_header.beacon.slot)
    best = store.best_valid_update.copy()

    drv.process(store, update)  # replay
    assert int(store.optimistic_header.beacon.slot) == opt_slot
    assert bytes(hash_tree_root(store.best_valid_update)) == bytes(
        hash_tree_root(best)
    )


@with_phases(SYNC_FORKS)
@spec_state_test_with_matching_config
def test_sync_finality_then_optimistic_ahead(spec, state):
    """After a finality update, later optimistic updates keep moving the
    optimistic head past the finalized one."""
    drv = LCSyncDriver(spec, state)
    store = drv.bootstrap_store()

    drv.finalize_epochs(4)
    upd_fin, _ = drv.emit_update()
    drv.process(store, upd_fin)
    fin_slot = int(store.finalized_header.beacon.slot)

    upd_opt, _ = drv.emit_update(with_finality=False)
    drv.process(store, upd_opt)
    assert int(store.finalized_header.beacon.slot) == fin_slot
    assert int(store.optimistic_header.beacon.slot) > fin_slot


@with_phases(SYNC_FORKS)
@spec_state_test_with_matching_config
def test_sync_participation_tracks_safety_threshold(spec, state):
    """current_max_active_participants follows the strongest seen update;
    the safety threshold is half the max of the two windows."""
    drv = LCSyncDriver(spec, state)
    store = drv.bootstrap_store()

    drv.finalize_epochs(1)
    update, _ = drv.emit_update(with_finality=False)
    drv.process(store, update)

    size = int(spec.SYNC_COMMITTEE_SIZE)
    assert int(store.current_max_active_participants) == size
    assert int(spec.get_safety_threshold(store)) == size // 2
