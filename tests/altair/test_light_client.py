"""Altair light-client sync protocol: bootstrap, update validation,
finality/optimistic processing, force update (reference analogue:
eth2spec/test/altair/light_client/; spec:
specs/altair/light-client/sync-protocol.md, full-node.md)."""

from eth_consensus_specs_tpu.ssz import Bytes32, hash_tree_root
from eth_consensus_specs_tpu.ssz.merkle import compute_merkle_proof
from eth_consensus_specs_tpu.test_infra.attestations import next_epoch_with_attestations
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import (
    always_bls,
    expect_assertion_error,
    spec_state_test_with_matching_config,
    with_phases,
)

# the protocol is altair-born; capella/deneb/electra refine the header and
# (electra) the state gindices — the matrix covers each shape
LC_FORKS = ["altair", "capella", "deneb", "electra"]


def _signed_block_for_state(spec, state):
    """An empty signed block on top of `state` (mutates state)."""
    block = build_empty_block_for_next_slot(spec, state)
    return state_transition_and_sign_block(spec, state, block)


def _bootstrap_store(spec, state):
    """Advance one block, build a bootstrap at the head, initialize."""
    signed = _signed_block_for_state(spec, state)
    bootstrap = spec.create_light_client_bootstrap(state, signed)
    trusted_root = hash_tree_root(signed.message)
    store = spec.initialize_light_client_store(trusted_root, bootstrap)
    return store, signed


# == gindex proofs =========================================================


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_sync_committee_gindex_proofs(spec, state):
    root = hash_tree_root(state)
    for gindex, leaf_obj in (
        (spec.current_sync_committee_gindex_at_slot(state.slot), state.current_sync_committee),
        (spec.next_sync_committee_gindex_at_slot(state.slot), state.next_sync_committee),
    ):
        branch = compute_merkle_proof(state, gindex)
        assert spec.is_valid_normalized_merkle_branch(
            hash_tree_root(leaf_obj), branch, gindex, root
        )


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_finalized_root_gindex_proof(spec, state):
    state.finalized_checkpoint.root = b"\x21" * 32
    gindex = spec.finalized_root_gindex_at_slot(state.slot)
    root = hash_tree_root(state)
    branch = compute_merkle_proof(state, gindex)
    assert spec.is_valid_normalized_merkle_branch(
        Bytes32(state.finalized_checkpoint.root), branch, gindex, root
    )
    # a tampered branch fails
    bad = list(branch)
    bad[0] = b"\x66" * 32
    assert not spec.is_valid_normalized_merkle_branch(
        Bytes32(state.finalized_checkpoint.root), bad, gindex, root
    )


# == bootstrap =============================================================


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_bootstrap_initializes_store(spec, state):
    store, signed = _bootstrap_store(spec, state)
    assert hash_tree_root(store.finalized_header.beacon) == hash_tree_root(signed.message)
    assert store.current_sync_committee == state.current_sync_committee
    assert not spec.is_next_sync_committee_known(store)
    assert store.best_valid_update is None


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_bootstrap_wrong_trusted_root_rejected(spec, state):
    signed = _signed_block_for_state(spec, state)
    bootstrap = spec.create_light_client_bootstrap(state, signed)
    expect_assertion_error(
        lambda: spec.initialize_light_client_store(b"\x13" * 32, bootstrap)
    )


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_bootstrap_bad_committee_branch_rejected(spec, state):
    signed = _signed_block_for_state(spec, state)
    bootstrap = spec.create_light_client_bootstrap(state, signed)
    bootstrap.current_sync_committee_branch[0] = b"\x99" * 32
    expect_assertion_error(
        lambda: spec.initialize_light_client_store(
            hash_tree_root(signed.message), bootstrap
        )
    )


# == updates ===============================================================


def _advance_with_light_client_update(spec, state):
    """Build (attested block, signature block) pair + update on top of the
    current state. Returns (update, signature_block_slot)."""
    attested_block = _signed_block_for_state(spec, state)
    attested_state_post = state.copy()  # state AFTER attested block

    sig_state = state.copy()
    signature_block = build_empty_block_for_next_slot(spec, sig_state)
    # full sync-committee participation signs the attested header
    for i in range(spec.SYNC_COMMITTEE_SIZE):
        signature_block.body.sync_aggregate.sync_committee_bits[i] = True
    from eth_consensus_specs_tpu.test_infra.keys import privkeys
    from eth_consensus_specs_tpu.utils import bls as bls_mod

    # sign the PREVIOUS block root (= attested block) per the sync protocol
    prev_slot = int(signature_block.slot) - 1
    domain = spec.get_domain(
        sig_state, spec.DOMAIN_SYNC_COMMITTEE, spec.compute_epoch_at_slot(prev_slot)
    )
    signing_root = spec.compute_signing_root(
        hash_tree_root(attested_block.message), domain
    )
    committee_pubkeys = list(sig_state.current_sync_committee.pubkeys)
    all_pubkeys = [v.pubkey for v in sig_state.validators]
    sigs = []
    for pk in committee_pubkeys:
        idx = all_pubkeys.index(pk)
        sigs.append(bls_mod.Sign(privkeys[idx], signing_root))
    signature_block.body.sync_aggregate.sync_committee_signature = bls_mod.Aggregate(sigs)
    signed_sig_block = state_transition_and_sign_block(spec, sig_state, signature_block)

    update = spec.create_light_client_update(
        sig_state, signed_sig_block, attested_state_post, attested_block, None
    )
    return update, sig_state


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_process_optimistic_update(spec, state):
    store, _ = _bootstrap_store(spec, state)
    update, sig_state = _advance_with_light_client_update(spec, state)
    optimistic = spec.create_light_client_optimistic_update(update)
    current_slot = int(sig_state.slot) + 1
    spec.process_light_client_optimistic_update(
        store, optimistic, current_slot, sig_state.genesis_validators_root
    )
    assert hash_tree_root(store.optimistic_header.beacon) == hash_tree_root(
        update.attested_header.beacon
    )
    # optimistic update alone does not advance finality
    assert int(store.finalized_header.beacon.slot) < int(
        store.optimistic_header.beacon.slot
    )


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_process_update_tracks_best_valid(spec, state):
    store, _ = _bootstrap_store(spec, state)
    update, sig_state = _advance_with_light_client_update(spec, state)
    current_slot = int(sig_state.slot) + 1
    spec.process_light_client_update(
        store, update, current_slot, sig_state.genesis_validators_root
    )
    assert store.best_valid_update is not None
    assert store.current_max_active_participants == spec.SYNC_COMMITTEE_SIZE


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_validate_update_rejects_future_signature_slot(spec, state):
    store, _ = _bootstrap_store(spec, state)
    update, sig_state = _advance_with_light_client_update(spec, state)
    current_slot = int(update.signature_slot) - 1  # clock behind signature
    expect_assertion_error(
        lambda: spec.validate_light_client_update(
            store, update, current_slot, sig_state.genesis_validators_root
        )
    )


@with_phases(["altair"])
@always_bls
@spec_state_test_with_matching_config
def test_validate_update_rejects_bad_signature(spec, state):
    store, _ = _bootstrap_store(spec, state)
    update, sig_state = _advance_with_light_client_update(spec, state)
    update.sync_aggregate.sync_committee_signature = b"\x11" * 96
    current_slot = int(sig_state.slot) + 1
    expect_assertion_error(
        lambda: spec.validate_light_client_update(
            store, update, current_slot, sig_state.genesis_validators_root
        )
    )


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_validate_update_rejects_empty_participation(spec, state):
    store, _ = _bootstrap_store(spec, state)
    update, sig_state = _advance_with_light_client_update(spec, state)
    bits_type = type(update.sync_aggregate.sync_committee_bits)
    update.sync_aggregate.sync_committee_bits = bits_type()  # all zero
    current_slot = int(sig_state.slot) + 1
    expect_assertion_error(
        lambda: spec.validate_light_client_update(
            store, update, current_slot, sig_state.genesis_validators_root
        )
    )


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_is_better_update_prefers_participation(spec, state):
    store, _ = _bootstrap_store(spec, state)
    update, sig_state = _advance_with_light_client_update(spec, state)
    weaker = update.copy()
    # drop half the participation bits (below supermajority)
    for i in range(spec.SYNC_COMMITTEE_SIZE * 2 // 3):
        weaker.sync_aggregate.sync_committee_bits[i] = False
    assert spec.is_better_update(update, weaker)
    assert not spec.is_better_update(weaker, update)


@with_phases(LC_FORKS)
@spec_state_test_with_matching_config
def test_force_update_applies_best(spec, state):
    store, _ = _bootstrap_store(spec, state)
    update, sig_state = _advance_with_light_client_update(spec, state)
    current_slot = int(sig_state.slot) + 1
    spec.process_light_client_update(
        store, update, current_slot, sig_state.genesis_validators_root
    )
    assert store.best_valid_update is not None
    finalized_before = int(store.finalized_header.beacon.slot)
    # no finality progress for longer than the update timeout
    far_future_slot = current_slot + spec.UPDATE_TIMEOUT + 1
    spec.process_light_client_store_force_update(store, far_future_slot)
    assert store.best_valid_update is None
    assert int(store.finalized_header.beacon.slot) > finalized_before


@with_phases(["deneb"])
@spec_state_test_with_matching_config
def test_capella_era_header_execution_root(spec, state):
    """Deneb's get_lc_execution_root re-projects capella-era headers into
    the capella container shape (deneb LC spec [Modified in Deneb])."""
    from eth_consensus_specs_tpu.forks import get_spec

    capella = get_spec("capella", spec.preset_name)
    # a capella-era execution header lifted into the deneb type with
    # blob-gas fields zero
    deneb_exec = spec.ExecutionPayloadHeader(
        block_number=7, gas_limit=30_000_000, block_hash=b"\x31" * 32
    )
    header = spec.LightClientHeader(beacon=spec.BeaconBlockHeader(slot=0))
    header.execution = deneb_exec
    # pin the header's epoch into the capella era via config: matching
    # config sets DENEB_FORK_EPOCH=0, so craft the comparison directly
    capella_exec = capella.ExecutionPayloadHeader(
        **{name: getattr(deneb_exec, name) for name in capella.ExecutionPayloadHeader.fields()}
    )
    from eth_consensus_specs_tpu.forks import get_spec_with_overrides

    shifted = get_spec_with_overrides(
        "deneb",
        spec.preset_name,
        config_overrides={
            "ALTAIR_FORK_EPOCH": 0,
            "BELLATRIX_FORK_EPOCH": 0,
            "CAPELLA_FORK_EPOCH": 0,
            "DENEB_FORK_EPOCH": 100,  # header slot 0 is capella-era
        },
    )
    header2 = shifted.LightClientHeader(beacon=shifted.BeaconBlockHeader(slot=0))
    header2.execution = shifted.ExecutionPayloadHeader(
        block_number=7, gas_limit=30_000_000, block_hash=b"\x31" * 32
    )
    assert bytes(shifted.get_lc_execution_root(header2)) == bytes(
        hash_tree_root(capella_exec)
    )


@with_phases(["electra"])
@spec_state_test_with_matching_config
def test_upgrade_lc_objects_to_electra(spec, state):
    """Pre-electra LC objects re-home with zero-extended branches."""
    from eth_consensus_specs_tpu.forks import get_spec_with_overrides

    deneb = get_spec_with_overrides(
        "deneb",
        spec.preset_name,
        config_overrides={
            "ALTAIR_FORK_EPOCH": 0,
            "BELLATRIX_FORK_EPOCH": 0,
            "CAPELLA_FORK_EPOCH": 0,
            "DENEB_FORK_EPOCH": 0,
        },
    )
    from eth_consensus_specs_tpu.test_infra.context import (
        default_activation_threshold,
        default_balances,
    )
    from eth_consensus_specs_tpu.test_infra.genesis import create_genesis_state

    dstate = create_genesis_state(
        deneb, default_balances(deneb), default_activation_threshold(deneb)
    )
    signed = _signed_block_for_state(deneb, dstate)
    bootstrap = deneb.create_light_client_bootstrap(dstate, signed)
    upgraded = spec.upgrade_lc_bootstrap_to_electra(bootstrap)
    # branch zero-extends by one level (altair depth 5 -> electra depth 6)
    assert len(upgraded.current_sync_committee_branch) == len(
        bootstrap.current_sync_committee_branch
    ) + 1
    assert bytes(upgraded.current_sync_committee_branch[0]) == b"\x00" * 32
    assert upgraded.current_sync_committee == bootstrap.current_sync_committee
    # store upgrade carries headers + counters over
    store = deneb.initialize_light_client_store(
        hash_tree_root(signed.message), bootstrap
    )
    estore = spec.upgrade_lc_store_to_electra(store)
    assert hash_tree_root(estore.finalized_header.beacon) == hash_tree_root(
        store.finalized_header.beacon
    )
