"""phase0 -> altair state upgrade (reference analogue:
test/altair/fork/test_altair_fork_basic.py; spec: specs/altair/fork.md)."""

from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.attestations import next_epoch_with_attestations
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.state import next_epoch


@with_phases(["phase0"])
@spec_state_test
def test_upgrade_to_altair_basic(spec, state):
    altair = get_spec("altair", spec.preset_name)
    next_epoch(spec, state)
    post = altair.upgrade_from_parent(state)
    assert bytes(post.fork.current_version) == bytes(altair.config.ALTAIR_FORK_VERSION)
    assert bytes(post.fork.previous_version) == bytes(state.fork.current_version)
    assert int(post.slot) == int(state.slot)
    assert len(post.inactivity_scores) == len(state.validators)
    assert all(int(s) == 0 for s in post.inactivity_scores)
    assert hash_tree_root(post.validators) == hash_tree_root(state.validators)
    # both committees seeded and identical at the boundary
    assert hash_tree_root(post.current_sync_committee) == hash_tree_root(
        post.next_sync_committee
    )


@with_phases(["phase0"])
@spec_state_test
def test_upgrade_to_altair_translates_participation(spec, state):
    altair = get_spec("altair", spec.preset_name)
    next_epoch(spec, state)
    next_epoch_with_attestations(spec, state, fill_cur_epoch=False, fill_prev_epoch=True)
    assert len(state.previous_epoch_attestations) > 0
    post = altair.upgrade_from_parent(state)
    flagged = [int(f) for f in post.previous_epoch_participation]
    assert any(f != 0 for f in flagged)
    assert all(int(f) == 0 for f in post.current_epoch_participation)
    # the upgraded state must run under the altair state machine
    next_epoch(altair, post)
