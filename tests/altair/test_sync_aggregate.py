"""Sync-aggregate processing (reference analogue:
test/altair/block_processing/sync_aggregate/*)."""

from eth_consensus_specs_tpu.test_infra.context import (
    always_bls,
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.keys import privkeys, pubkey_to_privkey
from eth_consensus_specs_tpu.test_infra.state import next_slot
from eth_consensus_specs_tpu.utils import bls


def make_sync_aggregate(spec, state, participation_bits):
    """Signed aggregate over the previous block root for the current slot."""
    previous_slot = max(int(state.slot), 1) - 1
    block_root = spec.get_block_root_at_slot(state, previous_slot)
    domain = spec.get_domain(
        state, spec.DOMAIN_SYNC_COMMITTEE, spec.compute_epoch_at_slot(previous_slot)
    )
    signing_root = spec.compute_signing_root(spec.Root(block_root), domain)
    sigs = []
    for pk, bit in zip(state.current_sync_committee.pubkeys, participation_bits):
        if bit:
            sigs.append(bls.Sign(pubkey_to_privkey(bytes(pk)), signing_root))
    signature = bls.Aggregate(sigs) if sigs else bls.G2_POINT_AT_INFINITY
    return spec.SyncAggregate(
        sync_committee_bits=participation_bits, sync_committee_signature=signature
    )


def run_sync_aggregate_processing(spec, state, sync_aggregate, valid=True):
    yield "pre", state
    yield "sync_aggregate", sync_aggregate
    if not valid:
        expect_assertion_error(lambda: spec.process_sync_aggregate(state, sync_aggregate))
        yield "post", None
        return
    spec.process_sync_aggregate(state, sync_aggregate)
    yield "post", state


@with_phases(["altair"])
@spec_state_test
def test_sync_aggregate_full_participation_rewards(spec, state):
    next_slot(spec, state)
    bits = [True] * spec.SYNC_COMMITTEE_SIZE
    aggregate = make_sync_aggregate(spec, state, bits)
    all_pubkeys = [bytes(v.pubkey) for v in state.validators]
    committee = [all_pubkeys.index(bytes(pk)) for pk in state.current_sync_committee.pubkeys]
    pre_balances = [int(state.balances[i]) for i in committee]
    yield from run_sync_aggregate_processing(spec, state, aggregate)
    for i, idx in enumerate(committee):
        assert int(state.balances[idx]) > pre_balances[i]


@with_phases(["altair"])
@spec_state_test
def test_sync_aggregate_empty_participation_penalties(spec, state):
    next_slot(spec, state)
    bits = [False] * spec.SYNC_COMMITTEE_SIZE
    aggregate = spec.SyncAggregate(
        sync_committee_bits=bits, sync_committee_signature=bls.G2_POINT_AT_INFINITY
    )
    all_pubkeys = [bytes(v.pubkey) for v in state.validators]
    committee = [all_pubkeys.index(bytes(pk)) for pk in state.current_sync_committee.pubkeys]
    proposer = spec.get_beacon_proposer_index(state)
    pre_balances = [int(state.balances[i]) for i in committee]
    yield from run_sync_aggregate_processing(spec, state, aggregate)
    for i, idx in enumerate(committee):
        if idx != proposer:
            assert int(state.balances[idx]) < pre_balances[i]


@with_phases(["altair"])
@always_bls
@spec_state_test
def test_sync_aggregate_half_participation_signature(spec, state):
    next_slot(spec, state)
    bits = [i % 2 == 0 for i in range(spec.SYNC_COMMITTEE_SIZE)]
    aggregate = make_sync_aggregate(spec, state, bits)
    yield from run_sync_aggregate_processing(spec, state, aggregate)


@with_phases(["altair"])
@always_bls
@spec_state_test
def test_sync_aggregate_majority_uses_subtraction_path(spec, state):
    # >half participation exercises the aggregate-minus-absentees fast path
    next_slot(spec, state)
    bits = [i != 0 for i in range(spec.SYNC_COMMITTEE_SIZE)]
    aggregate = make_sync_aggregate(spec, state, bits)
    yield from run_sync_aggregate_processing(spec, state, aggregate)


@with_phases(["altair"])
@always_bls
@spec_state_test
def test_sync_aggregate_invalid_signature(spec, state):
    next_slot(spec, state)
    bits = [True] * spec.SYNC_COMMITTEE_SIZE
    aggregate = make_sync_aggregate(spec, state, bits)
    aggregate.sync_committee_signature = bls.Sign(privkeys[0], b"\x13" * 32)
    yield from run_sync_aggregate_processing(spec, state, aggregate, valid=False)


@with_phases(["altair"])
@always_bls
@spec_state_test
def test_sync_aggregate_wrong_bit_invalid(spec, state):
    # flip one participation bit after signing: signature no longer matches
    next_slot(spec, state)
    bits = [i != 0 for i in range(spec.SYNC_COMMITTEE_SIZE)]
    aggregate = make_sync_aggregate(spec, state, bits)
    flipped = list(aggregate.sync_committee_bits)
    flipped[0] = True
    aggregate.sync_committee_bits = flipped
    yield from run_sync_aggregate_processing(spec, state, aggregate, valid=False)
