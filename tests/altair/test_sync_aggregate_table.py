"""Sync-aggregate processing table, altair+ (reference analogue:
test/altair/block_processing/sync_aggregate/ ~40 variants — rewards,
participation shapes, signature validity)."""

from eth_consensus_specs_tpu.test_infra.context import (
    always_bls,
    expect_assertion_error,
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.keys import pubkey_to_privkey
from eth_consensus_specs_tpu.test_infra.state import next_slots
from eth_consensus_specs_tpu.utils import bls

SYNC_FORKS = ["altair", "bellatrix", "capella", "deneb", "electra", "fulu"]


def _signed_aggregate(spec, state, bits):
    prev_slot = int(state.slot) - 1
    root = spec.get_block_root_at_slot(state, prev_slot)
    domain = spec.get_domain(
        state, spec.DOMAIN_SYNC_COMMITTEE, spec.compute_epoch_at_slot(prev_slot)
    )
    signing_root = spec.compute_signing_root(spec.Root(root), domain)
    sigs = [
        bls.Sign(pubkey_to_privkey(bytes(pk)), signing_root)
        for pk, bit in zip(state.current_sync_committee.pubkeys, bits)
        if bit
    ]
    agg = bls.Aggregate(sigs) if sigs else spec.BLSSignature(b"\xc0" + b"\x00" * 95)
    return spec.SyncAggregate(sync_committee_bits=bits, sync_committee_signature=agg)


@with_phases(SYNC_FORKS)
@always_bls
@spec_state_test
def test_sync_full_participation_rewards_everyone(spec, state):
    next_slots(spec, state, 1)
    n = int(spec.SYNC_COMMITTEE_SIZE)
    agg = _signed_aggregate(spec, state, [True] * n)
    proposer = int(spec.get_beacon_proposer_index(state))
    pre_proposer = int(state.balances[proposer])
    spec.process_sync_aggregate(state, agg)
    assert int(state.balances[proposer]) > pre_proposer


@with_phases(SYNC_FORKS)
@always_bls
@spec_state_test
def test_sync_half_participation(spec, state):
    next_slots(spec, state, 1)
    n = int(spec.SYNC_COMMITTEE_SIZE)
    bits = [i % 2 == 0 for i in range(n)]
    agg = _signed_aggregate(spec, state, bits)
    spec.process_sync_aggregate(state, agg)


@with_phases(SYNC_FORKS)
@always_bls
@spec_state_test
def test_sync_nonparticipants_penalized(spec, state):
    next_slots(spec, state, 1)
    n = int(spec.SYNC_COMMITTEE_SIZE)
    bits = [False] * n
    bits[0] = True
    agg = _signed_aggregate(spec, state, bits)
    # a non-participating committee member loses balance
    all_pubkeys = [bytes(v.pubkey) for v in state.validators]
    missing_pk = bytes(state.current_sync_committee.pubkeys[1])
    missing_idx = all_pubkeys.index(missing_pk)
    pre = int(state.balances[missing_idx])
    spec.process_sync_aggregate(state, agg)
    assert int(state.balances[missing_idx]) < pre


@with_phases(SYNC_FORKS)
@always_bls
@spec_state_test
def test_sync_invalid_signature_rejected(spec, state):
    next_slots(spec, state, 1)
    n = int(spec.SYNC_COMMITTEE_SIZE)
    agg = _signed_aggregate(spec, state, [True] * n)
    agg.sync_committee_signature = bls.Sign(123456, b"\x42" * 32)
    expect_assertion_error(lambda: spec.process_sync_aggregate(state, agg))


@with_phases(SYNC_FORKS)
@always_bls
@spec_state_test
def test_sync_invalid_extra_participant_claimed(spec, state):
    next_slots(spec, state, 1)
    n = int(spec.SYNC_COMMITTEE_SIZE)
    bits = [False] * n
    bits[0] = True
    agg = _signed_aggregate(spec, state, bits)
    agg.sync_committee_bits[1] = True  # claims a signer who didn't sign
    expect_assertion_error(lambda: spec.process_sync_aggregate(state, agg))


@with_phases(SYNC_FORKS)
@always_bls
@spec_state_test
def test_sync_empty_participation_infinity_signature_ok(spec, state):
    next_slots(spec, state, 1)
    n = int(spec.SYNC_COMMITTEE_SIZE)
    agg = _signed_aggregate(spec, state, [False] * n)
    spec.process_sync_aggregate(state, agg)  # G2 infinity over empty set


@with_phases(SYNC_FORKS)
@always_bls
@spec_state_test
def test_sync_empty_participation_nonzero_signature_rejected(spec, state):
    next_slots(spec, state, 1)
    n = int(spec.SYNC_COMMITTEE_SIZE)
    agg = _signed_aggregate(spec, state, [False] * n)
    agg.sync_committee_signature = bls.Sign(99, b"\x01" * 32)
    expect_assertion_error(lambda: spec.process_sync_aggregate(state, agg))


@with_phases(SYNC_FORKS)
@always_bls
@spec_state_test
def test_sync_rewards_conserved_modulo_proposer_cut(spec, state):
    """Total balance delta equals proposer reward inflow minus
    non-participant penalties (conservation sanity)."""
    next_slots(spec, state, 1)
    n = int(spec.SYNC_COMMITTEE_SIZE)
    bits = [i % 3 != 0 for i in range(n)]
    agg = _signed_aggregate(spec, state, bits)
    pre_total = sum(int(b) for b in state.balances)
    spec.process_sync_aggregate(state, agg)
    post_total = sum(int(b) for b in state.balances)
    # participant rewards + proposer cut are newly minted; penalties burn
    assert post_total != pre_total or all(bits)
