"""Whole-slot pipeline (ops/slot_pipeline + serve/slot): submit_slot
bit-parity against the sequential host fold, degrade-ladder atomicity at
the slot.verify / slot.reroot fault sites, durable commit + restore with
idempotent replay, the serve-tier threading (phases in the waterfall,
typed Overloaded), and the compile-key discipline (request-derived
capacities, zero cold compiles on a warm shape).

Fast lane: pure host logic — capacities, scatter planning, compile-key
injectivity, result wire codec, site registration. Slow lane (nightly,
like the rest of the device-crypto suite): everything that boots a
world (run_epochs + slot_apply compiles are minutes-scale on CPU)."""

from __future__ import annotations

import shutil
import tempfile
from dataclasses import replace

import numpy as np
import pytest

import __graft_entry__ as graft
import jax
from eth_consensus_specs_tpu import fault
from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.ops import slot_pipeline as sp
from eth_consensus_specs_tpu.ops.state_root import synthetic_static
from eth_consensus_specs_tpu.serve import buckets
from eth_consensus_specs_tpu.utils import bls

N = 64


# ------------------------------------------------------------ test data --


def make_att(subnet, committee, bits, root, bad=False):
    signers = [vi for vi, b in zip(committee, bits) if b]
    sks = [1000 + vi for vi in signers]
    pubkeys = tuple(bytes(bls.SkToPk(sk)) for sk in sks)
    sig = bytes(bls.Aggregate([bls.Sign(sk, root) for sk in sks]))
    if bad:
        sig = bytes(bls.Sign(9999, root))
    return sp.SlotAttestation(
        subnet=subnet, root=root, committee=tuple(committee), bits=tuple(bits),
        pubkeys=pubkeys, sig=sig,
    )


def make_req(slot, boundary=False, bad_att=False, blobs=0, bad_blob=False):
    r1 = b"\x11" * 32
    atts = (
        make_att(3, [1, 2, 3, 4], [1, 1, 0, 1], r1),
        make_att(3, [5, 6], [1, 1], r1),
        make_att(7, [8, 9, 10], [1, 0, 1], b"\x22" * 32, bad=bad_att),
    )
    sync_sks = [2000 + i for i in range(4)]
    sync_msg = b"\x33" * 32
    sync_pk = tuple(bytes(bls.SkToPk(sk)) for sk in sync_sks)
    sync_sig = bytes(bls.Aggregate([bls.Sign(sk, sync_msg) for sk in sync_sks]))
    blob_items = []
    if blobs:
        import hashlib

        from eth_consensus_specs_tpu.crypto import kzg

        for i in range(blobs):
            out = []
            for j in range(kzg.FIELD_ELEMENTS_PER_BLOB):
                h = hashlib.sha256(bytes([i]) + j.to_bytes(4, "big")).digest()
                out.append((int.from_bytes(h, "big") % kzg.BLS_MODULUS).to_bytes(32, "big"))
            blob = b"".join(out)
            c = kzg.blob_to_kzg_commitment(blob)
            p = kzg.compute_blob_kzg_proof(blob, c)
            if bad_blob and i == 0:
                blob = blob[:-1] + bytes([blob[-1] ^ 1])
            blob_items.append((blob, bytes(c), bytes(p)))
    return sp.SlotRequest(
        slot=slot, attestations=atts, sync_pubkeys=sync_pk, sync_message=sync_msg,
        sync_sig=sync_sig, sync_indices=(11, 12, 13, 14), blobs=tuple(blob_items),
        epoch_boundary=boundary,
    )


def dummy_req(slot=0, bits=((1, 1, 0, 1),), sync=4):
    """A shape-only request (garbage signatures): enough for capacity /
    key / planning tests that never verify anything."""
    atts = tuple(
        sp.SlotAttestation(
            subnet=i, root=b"\x00" * 32,
            committee=tuple(range(len(b))), bits=tuple(b),
            pubkeys=tuple(b"\x00" * 48 for bit in b if bit), sig=b"\x00" * 96,
        )
        for i, b in enumerate(bits)
    )
    return sp.SlotRequest(
        slot=slot, attestations=atts, sync_pubkeys=(), sync_message=b"\x00" * 32,
        sync_sig=b"\x00" * 96, sync_indices=tuple(range(sync)), blobs=(),
        epoch_boundary=False,
    )


def host_oracle(reqs, n=N):
    spec = get_spec("altair", "minimal")
    cols, just = graft._example_altair_inputs(n)
    static = synthetic_static(spec, n)
    cols, just = jax.device_put(cols), jax.device_put(just)
    epoch, results = 0, []
    for req in reqs:
        res, cols, just = sp.host_slot_fold(spec, static, cols, just, req, epoch)
        epoch = res.epoch
        results.append(res)
    return results


# ------------------------------------------------------------ fast lane --


def test_request_capacity_is_pre_verdict_shape_only():
    """Capacity counts every SET committee bit and every sync index —
    before any verdict exists — so the front door's routing key and the
    dispatch's compile key derive from the request alone."""
    req = dummy_req(bits=((1, 1, 0, 1), (1, 0)), sync=4)
    assert sp.request_capacity(req) == (4, 4)
    assert sp.request_capacity(dummy_req(bits=(), sync=0)) == (0, 0)


def test_slot_key_buckets_capacities_pow2():
    from eth_consensus_specs_tpu.ops.state_root import forest_plan

    _, meta = synthetic_static(get_spec("altair", "minimal"), N)
    plan = forest_plan(meta)
    k5 = buckets.slot_key(N, 5, 3, plan)
    k8 = buckets.slot_key(N, 8, 4, plan)
    assert k5 == k8  # both capacities bucket up to the same pow2 lanes
    assert k5[0] == "slot_apply" and k5[1] == N
    assert buckets.slot_key(N, 9, 4, plan) != k8  # 9 escapes the 8-bucket
    assert buckets.slot_key(N, 0, 0, plan)[2:4] == (1, 1)  # empty never 0-lane


def test_plan_updates_uses_valid_items_only():
    req = dummy_req(bits=((1, 1, 0, 1), (1, 0)), sync=3)
    flag_idx, reward_idx, reward_amt = sp.plan_updates(req, [True, False], True, N)
    assert sorted(flag_idx.tolist()) == [0, 1, 3]  # second att rejected
    assert reward_idx.tolist() == [0, 1, 2]
    assert np.all(reward_amt == sp.sync_reward_gwei())
    # rejected sync verdict: no rewards at all
    _, r_idx, r_amt = sp.plan_updates(req, [True, True], False, N)
    assert len(r_idx) == 0 and len(r_amt) == 0
    # out-of-registry indices are dropped, never scattered; duplicates
    # survive (the kernel's scatter-ADD hit count is duplicate-safe)
    f2, _, _ = sp.plan_updates(req, [True, True], True, 2)
    assert sorted(f2.tolist()) == [0, 0, 1]


def test_slot_result_wire_codec_roundtrip():
    from eth_consensus_specs_tpu.serve.slot import _result_from_json, _result_json

    res = sp.SlotResult(
        slot=7, att_verdicts=(True, False), sync_verdict=True,
        blob_verdicts=(True,), subnet_aggregates=((3, b"\xaa" * 96),),
        state_root=b"\x42" * 32, epoch=2, replayed=False,
    )
    back = _result_from_json(_result_json(res))
    assert back == res
    # `replayed` is NOT wire state: the dedup window stores the original
    # commit and the flag is stamped at replay time, never persisted
    assert not _result_from_json(_result_json(replace(res, replayed=True))).replayed


def test_slot_world_booting_busy_is_honest(tmp_path):
    """An eager boot in flight answers busy with the measured previous
    boot wall (the ResidentOwner restore-ETA convention) — mid-boot
    submits must never park in the listener backlog. The lazy path
    (no mark_booting) never reports busy."""
    from eth_consensus_specs_tpu.serve.slot import SlotWorld

    w = SlotWorld(n_validators=8, ckpt_dir=str(tmp_path))
    assert not w.busy  # lazy path: nothing eager in flight
    w.mark_booting()
    assert w.busy
    # no measured boot yet: the fallback ETA floors the hint
    assert w.retry_after_s() > 0
    st = w.status()
    assert st["booting"] and st["retry_after_s"] > 0
    # a completed boot persists its wall; the NEXT world's hint is the
    # measured number, not the fallback
    w._persist_eta(7.5)
    w2 = SlotWorld(n_validators=8, ckpt_dir=str(tmp_path))
    assert w2._eta_s == 7.5
    w2.mark_booting()
    assert 0 < w2.retry_after_s() <= 7.5
    # boot completion clears busy (simulated: the flag pair, not a real
    # boot — the slow lane covers the full restore path)
    w2._booted = True
    assert not w2.busy and not w2.status()["booting"]


def test_slot_fault_sites_are_registered():
    from eth_consensus_specs_tpu.fault import sites

    for name in ("slot.verify", "slot.reroot"):
        assert sites.declared(name), name
        assert "raise" in sites.SITES[name].modes


# ------------------------------------------------------------ slow lane --


@pytest.fixture(scope="module")
def slot_reqs():
    return [
        make_req(0, blobs=1),
        make_req(1, bad_att=True),
        make_req(2, blobs=1, bad_blob=True),
        make_req(3, boundary=True),
    ]


@pytest.fixture(scope="module")
def oracle(slot_reqs):
    return host_oracle(slot_reqs)


def _assert_result_parity(d, w):
    assert d.att_verdicts == w.att_verdicts
    assert d.sync_verdict == w.sync_verdict
    assert d.blob_verdicts == w.blob_verdicts
    assert d.subnet_aggregates == w.subnet_aggregates
    assert d.state_root == w.state_root, (d.slot, d.state_root.hex(), w.state_root.hex())
    assert d.epoch == w.epoch


@pytest.mark.slow
def test_submit_slot_bit_parity_vs_sequential_host_fold(slot_reqs, oracle):
    """Valid, invalid-attestation, invalid-blob and epoch-boundary slots
    through the device pipeline — every verdict, aggregate, and post-slot
    state root bit-identical to the sequential host composition; replay
    of a committed slot returns the identical result, flagged."""
    from eth_consensus_specs_tpu.serve.slot import SlotWorld

    world = SlotWorld(n_validators=N)
    for req, want in zip(slot_reqs, oracle):
        got, phases = world.execute(req, sp.prep_request(req))
        _assert_result_parity(got, want)
        assert set(phases) >= {"slot.verify", "slot.aggregate", "slot.reroot"}
    replayed, _ = world.execute(slot_reqs[0])
    assert replayed.replayed and replayed.state_root == oracle[0].state_root


@pytest.mark.slow
def test_device_death_degrades_the_whole_slot_atomically(slot_reqs, oracle):
    """Injected device failure at either site degrades the WHOLE slot to
    the host fold bit-identically — never a half-applied slot; one
    transient reroot failure retries on device and still matches."""
    from eth_consensus_specs_tpu.serve.slot import SlotWorld

    world = SlotWorld(n_validators=N)
    with fault.injected("slot.verify:raise:times=inf"):
        got, _ = world.execute(slot_reqs[0], sp.prep_request(slot_reqs[0]))
    _assert_result_parity(got, oracle[0])
    with fault.injected("slot.reroot:raise"):
        got, _ = world.execute(slot_reqs[1], sp.prep_request(slot_reqs[1]))
    _assert_result_parity(got, oracle[1])
    with fault.injected("slot.reroot:raise:times=inf"):
        for req, want in zip(slot_reqs[2:], oracle[2:]):
            got, _ = world.execute(req, sp.prep_request(req))
            _assert_result_parity(got, want)


@pytest.mark.slow
def test_checkpoint_restore_replays_committed_slots(slot_reqs, oracle):
    """A fresh world restoring from the durable checkpoint resumes at
    the last committed slot: committed slots replay bit-identically from
    the dedup window, uncommitted slots apply with parity."""
    from eth_consensus_specs_tpu.serve.slot import SlotWorld

    d = tempfile.mkdtemp()
    try:
        w1 = SlotWorld(n_validators=N, ckpt_dir=d)
        for req in slot_reqs[:2]:
            w1.execute(req, sp.prep_request(req))
        w2 = SlotWorld(n_validators=N, ckpt_dir=d)
        w2.boot()
        assert w2.root == oracle[1].state_root
        rb, _ = w2.execute(slot_reqs[1])
        assert rb.replayed and rb.state_root == oracle[1].state_root
        got, _ = w2.execute(slot_reqs[2], sp.prep_request(slot_reqs[2]))
        _assert_result_parity(got, oracle[2])
    finally:
        shutil.rmtree(d)


@pytest.mark.slow
def test_service_tier_submit_slot_phases_and_warm_shapes(slot_reqs, oracle):
    """submit_slot through the VerifyService: parity, the three phase
    walls in the stage histograms, and ZERO new compiles when a warm
    shape repeats (the compile key is a pure function of the request)."""
    from eth_consensus_specs_tpu import obs
    from eth_consensus_specs_tpu.serve.config import ServeConfig
    from eth_consensus_specs_tpu.serve.service import VerifyService

    cfg = ServeConfig.from_env(max_batch=8, max_wait_ms=5, slot_validators=N)
    svc = VerifyService(cfg)
    try:
        futs = [svc.submit_slot(r) for r in slot_reqs]
        got = [f.result(timeout=600) for f in futs]
        for d, w in zip(got, oracle):
            _assert_result_parity(d, w)
        for ph in ("slot.verify", "slot.aggregate", "slot.reroot"):
            h = obs.histogram(f"serve.stage_ms.{ph}")
            assert h is not None and h.count >= len(slot_reqs), ph
        assert svc.stats()["slot"]["slots"] >= len(slot_reqs)
        # warm shape: an identical-capacity NEW slot compiles nothing
        compiles = obs.snapshot()["counters"].get("serve.compiles", 0)
        again = make_req(9, boundary=False)
        got2 = svc.submit_slot(again).result(timeout=600)
        assert not got2.replayed
        assert obs.snapshot()["counters"].get("serve.compiles", 0) == compiles
    finally:
        svc.close()


@pytest.mark.slow
def test_mesh_and_single_device_worlds_agree(slot_reqs, oracle):
    """chips=1 vs chips=8 dispatch meshes produce bit-identical slot
    results — the mesh only widens the verify/aggregate legs."""
    from eth_consensus_specs_tpu.parallel.mesh_ops import serve_mesh
    from eth_consensus_specs_tpu.serve.slot import SlotWorld

    mesh = serve_mesh()
    if mesh is None:
        pytest.skip("needs >= 2 devices (conftest forces 8 on CPU)")
    w_single = SlotWorld(n_validators=N)
    w_mesh = SlotWorld(n_validators=N)
    for req, want in zip(slot_reqs[:2], oracle[:2]):
        a, _ = w_single.execute(req, sp.prep_request(req), mesh=None)
        b, _ = w_mesh.execute(req, sp.prep_request(req), mesh=mesh)
        _assert_result_parity(a, want)
        _assert_result_parity(b, want)
