"""Multi-chip sharding correctness in the test suite: the shard_map epoch
kernels and the sharded SSZ tree root must be bit-exact with their
single-device counterparts over the 8-virtual-device CPU mesh that
conftest.py forces (the same mesh shape the driver dry-runs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# 8-virtual-device mesh compiles — nightly lane (make test-full)
pytestmark = pytest.mark.slow
from jax.sharding import NamedSharding, PartitionSpec as P

from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.ops.altair_epoch import (
    AltairEpochParams,
    altair_epoch_accounting,
)
from eth_consensus_specs_tpu.ops.merkle import _tree_root_fused
from eth_consensus_specs_tpu.ops.state_columns import EpochParams, epoch_accounting
from eth_consensus_specs_tpu.parallel import DP_AXIS, SP_AXIS, make_mesh
from eth_consensus_specs_tpu.parallel.epoch import (
    altair_epoch_specs,
    epoch_specs,
    sharded_altair_epoch_fn,
    sharded_epoch_fn,
)
from eth_consensus_specs_tpu.parallel.merkle import tree_root_sharded_fn

N_DEVICES = 8


def _mesh():
    if len(jax.devices()) < N_DEVICES:
        pytest.skip(f"needs {N_DEVICES} devices (conftest forces them on CPU)")
    return make_mesh(N_DEVICES)


def _to_shardings(mesh, tree):
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), tree, is_leaf=lambda x: isinstance(x, P)
    )


def test_make_mesh_shape():
    mesh = _mesh()
    assert mesh.shape[DP_AXIS] * mesh.shape[SP_AXIS] == N_DEVICES
    assert mesh.shape[SP_AXIS] == 2  # even device count -> sp=2


def test_sharded_phase0_epoch_bit_exact():
    import __graft_entry__ as g

    mesh = _mesh()
    spec = get_spec("phase0", "mainnet")
    params = EpochParams.from_spec(spec)
    cols, just = g._example_inputs(64 * N_DEVICES)
    cols_spec, just_spec, res_spec = epoch_specs()
    fn = jax.jit(
        sharded_epoch_fn(mesh, params),
        in_shardings=(_to_shardings(mesh, cols_spec), _to_shardings(mesh, just_spec)),
        out_shardings=_to_shardings(mesh, res_spec),
    )
    res = fn(cols, just)
    ref = epoch_accounting(params, cols, just)
    for name in ("balance", "effective_balance", "rewards", "penalties"):
        np.testing.assert_array_equal(
            np.asarray(getattr(res, name)), np.asarray(getattr(ref, name)), err_msg=name
        )
    assert int(res.finalized_epoch) == int(ref.finalized_epoch)


def test_sharded_altair_epoch_bit_exact():
    import __graft_entry__ as g

    mesh = _mesh()
    spec = get_spec("deneb", "mainnet")
    params = AltairEpochParams.from_spec(spec)
    cols, just = g._example_altair_inputs(64 * N_DEVICES)
    cols_spec, just_spec, res_spec = altair_epoch_specs()
    fn = jax.jit(
        sharded_altair_epoch_fn(mesh, params),
        in_shardings=(_to_shardings(mesh, cols_spec), _to_shardings(mesh, just_spec)),
        out_shardings=_to_shardings(mesh, res_spec),
    )
    res = fn(cols, just)
    ref = altair_epoch_accounting(params, cols, just)
    for name in ("balance", "effective_balance", "inactivity_scores"):
        np.testing.assert_array_equal(
            np.asarray(getattr(res, name)), np.asarray(getattr(ref, name)), err_msg=name
        )
    np.testing.assert_array_equal(
        np.asarray(res.justification_bits), np.asarray(ref.justification_bits)
    )


def test_sharded_tree_root_matches_fused():
    mesh = _mesh()
    depth = 12
    rng = np.random.default_rng(3)
    leaves = jnp.asarray(
        rng.integers(0, 2**32, (1 << depth, 8), dtype=np.uint64).astype(np.uint32)
    )
    fn = jax.jit(
        tree_root_sharded_fn(mesh, depth),
        in_shardings=NamedSharding(mesh, P(SP_AXIS)),
        out_shardings=NamedSharding(mesh, P()),
    )
    root = fn(leaves)
    ref = _tree_root_fused(leaves, depth)
    np.testing.assert_array_equal(np.asarray(root), np.asarray(ref))


def test_sharded_epoch_scatter_add_proposer_rewards_cross_shard():
    """Proposer micro-rewards target global indices that can live on any
    shard — pin a case where every proposer index lands on shard 0."""
    import __graft_entry__ as g

    mesh = _mesh()
    spec = get_spec("phase0", "mainnet")
    params = EpochParams.from_spec(spec)
    n = 64 * N_DEVICES
    cols, just = g._example_inputs(n)
    cols = cols._replace(incl_proposer=np.zeros(n, np.int64))  # all on shard 0
    cols_spec, just_spec, res_spec = epoch_specs()
    fn = jax.jit(
        sharded_epoch_fn(mesh, params),
        in_shardings=(_to_shardings(mesh, cols_spec), _to_shardings(mesh, just_spec)),
        out_shardings=_to_shardings(mesh, res_spec),
    )
    res = fn(cols, just)
    ref = epoch_accounting(params, cols, just)
    np.testing.assert_array_equal(np.asarray(res.balance), np.asarray(ref.balance))


def test_sharded_block_slot_bit_exact():
    """One slot of the block plane (attestation scatters, sync rewards,
    deposits, withdrawal sweep) over the mesh == the unsharded kernel.
    Committee indices span every shard, so this exercises the global
    scatter path the SPMD partitioner must communicate for."""
    import jax.numpy as jnp

    from eth_consensus_specs_tpu.ops import block_epoch as bek
    from eth_consensus_specs_tpu.parallel.block import make_sharded_block_slot_fn

    mesh = _mesh()
    spec = get_spec("deneb", "mainnet")
    n = 64 * N_DEVICES
    cols, st0, static = bek.synthetic_block_columns(spec, n, seed=5, atts_per_slot=4)
    params = bek.BlockEpochParams.from_spec(spec)
    slot_blk = jax.tree_util.tree_map(lambda a: a[0], cols)  # first slot

    fn = make_sharded_block_slot_fn(mesh, params, n)
    out = fn(
        st0,
        slot_blk,
        static.base_reward,
        static.eff_balance,
        static.withdrawable_epoch,
        static.has_eth1_cred,
        static.epoch,
        static.part_reward,
        static.prop_reward,
    )
    ref = bek.process_slot_columnar(
        params,
        n,
        st0,
        slot_blk,
        static.base_reward,
        static.eff_balance,
        static.withdrawable_epoch,
        static.has_eth1_cred,
        static.epoch,
        static.part_reward,
        static.prop_reward,
    )
    np.testing.assert_array_equal(np.asarray(out.balance), np.asarray(ref.balance))
    np.testing.assert_array_equal(np.asarray(out.cur_part), np.asarray(ref.cur_part))
    np.testing.assert_array_equal(np.asarray(out.prev_part), np.asarray(ref.prev_part))
    assert int(out.next_wd_index) == int(ref.next_wd_index)
    assert int(out.next_wd_validator) == int(ref.next_wd_validator)
