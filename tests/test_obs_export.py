"""Telemetry export layer: mergeable histograms, trace propagation,
Prometheus exposition, SLO gates.

The acceptance story: (1) log-bucket histogram quantiles track numpy
percentiles within the layout's error bound and MERGE exactly (shards
== whole); (2) the Prometheus text exposition is well-formed — names,
HELP/TYPE pairs, cumulative ``le`` buckets capped by ``+Inf`` ==
``_count`` — both as a textfile and over the stdlib HTTP endpoint;
(3) a trace context survives serve submit → flush → dispatch (flow
links) and the gen-pool parent → worker process boundary (stitched
JSONL spans, shipped histograms/gauges); (4) SLOs evaluated from a
snapshot take both the pass and the fail path.
"""

import json
import math
import threading
import urllib.request

import numpy as np
import pytest

from eth_consensus_specs_tpu import obs
from eth_consensus_specs_tpu.obs import export, slo, trace
from eth_consensus_specs_tpu.obs.histogram import Histogram

# --------------------------------------------------------------- histogram --


def test_histogram_quantiles_track_numpy_percentiles():
    rng = np.random.default_rng(7)
    xs = rng.lognormal(mean=2.0, sigma=1.2, size=50_000)
    h = Histogram()
    for x in xs:
        h.record(float(x))
    # geometric-midpoint quantiles are bounded by sqrt(growth)-1 (~9 %
    # for the default layout); allow a little sampling slack on top
    bound = math.sqrt(h.growth) - 1 + 0.02
    for q in (0.01, 0.25, 0.5, 0.9, 0.99):
        est = h.quantile(q)
        ref = float(np.percentile(xs, q * 100))
        assert abs(est - ref) / ref < bound, (q, est, ref)
    assert h.quantile(0.0) == pytest.approx(float(xs.min()))
    assert h.quantile(1.0) == pytest.approx(float(xs.max()))
    assert h.mean() == pytest.approx(float(xs.mean()))


def test_histogram_merge_equals_whole():
    rng = np.random.default_rng(11)
    xs = rng.exponential(50.0, size=9_000)
    whole = Histogram()
    shards = [Histogram() for _ in range(3)]
    for i, x in enumerate(xs):
        whole.record(float(x))
        shards[i % 3].record(float(x))
    merged = Histogram()
    merged.merge(shards[0])  # live-instance merge
    for s in shards[1:]:
        merged.merge(s.snapshot())  # snapshot merge (the wire form)
    assert merged.counts == whole.counts
    assert merged.count == whole.count
    assert merged.sum == pytest.approx(whole.sum)
    assert merged.min == whole.min and merged.max == whole.max
    for q in (0.5, 0.99):
        assert merged.quantile(q) == whole.quantile(q)


def test_histogram_merge_rejects_layout_mismatch():
    a, b = Histogram(), Histogram(lo=1e-2)
    b.record(1.0)
    with pytest.raises(ValueError, match="layout mismatch"):
        a.merge(b)


def test_histogram_delta_since_ships_only_new_samples():
    h = Histogram()
    for v in (1.0, 10.0, 100.0):
        h.record(v)
    base = h.snapshot()
    assert h.delta_since(base) is None  # nothing new
    h.record(7.0)
    h.record(0.5)
    delta = h.delta_since(base)
    assert delta["count"] == 2
    assert delta["sum"] == pytest.approx(7.5)
    assert sum(delta["counts"]) == 2
    # folding the delta into a copy of the base reproduces the current state
    rebuilt = Histogram.from_snapshot(base)
    rebuilt.merge(delta)
    assert rebuilt.counts == h.counts and rebuilt.count == h.count
    assert rebuilt.min == h.min and rebuilt.max == h.max


def test_histogram_record_thread_safe():
    h = Histogram()

    def pound():
        for i in range(2_000):
            h.record(0.1 * (i % 37 + 1))

    threads = [threading.Thread(target=pound) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == 16_000
    assert sum(h.counts) == 16_000


def test_histogram_json_roundtrip_answers_quantiles():
    h = Histogram()
    for v in (2.0, 4.0, 8.0, 16.0):
        h.record(v)
    wire = json.loads(json.dumps(h.snapshot()))
    back = Histogram.from_snapshot(wire)
    assert back.quantile(0.5) == h.quantile(0.5)
    assert wire["p50"] is not None and wire["p99"] is not None


def test_registry_observe_and_merge():
    reg = obs.Registry()
    for v in (1.0, 2.0, 3.0):
        reg.observe("t.lat_ms", v)
    snap = reg.snapshot()
    assert snap["histograms"]["t.lat_ms"]["count"] == 3
    # merge a foreign delta (another process's shipped histogram)
    other = Histogram()
    other.record(50.0)
    reg.merge_histogram("t.lat_ms", other.snapshot())
    assert reg.histogram("t.lat_ms").count == 4
    # gauge merge: last is latest-wins, max monotonic
    reg.gauge("t.depth", 9)
    reg.merge_gauge("t.depth", {"last": 2, "max": 5})
    g = reg.snapshot()["gauges"]["t.depth"]
    assert g["last"] == 2 and g["max"] == 9


# -------------------------------------------------------------- exposition --


def _populated_registry() -> obs.Registry:
    reg = obs.Registry()
    reg.count("t.requests", 42)
    reg.count("watchdog.divergences", 0)
    reg.gauge("t.queue_depth", 7)
    for v in (0.5, 3.0, 3.1, 250.0, 9_999.0):
        reg.observe("t.wait_ms", v)
    with reg.span("t.dispatch"):
        pass
    return reg


def test_prometheus_exposition_well_formed():
    text = export.prometheus_text(_populated_registry().snapshot())
    tallies = export.validate_text(text)
    assert tallies["families"] >= 5
    lines = text.splitlines()
    # counter naming + HELP/TYPE discipline
    assert "# TYPE t_requests_total counter" in lines
    assert "t_requests_total 42" in lines
    assert "# TYPE t_queue_depth gauge" in lines
    # histogram: cumulative le buckets, +Inf cap == count
    buckets = [ln for ln in lines if ln.startswith("t_wait_ms_bucket")]
    assert buckets[-1] == 't_wait_ms_bucket{le="+Inf"} 5'
    cums = [float(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert cums == sorted(cums)
    assert "t_wait_ms_count 5" in lines
    # spans export as the calls/seconds counter pair
    assert "t_dispatch_calls_total 1" in lines


def test_prometheus_validator_rejects_malformations():
    good = export.prometheus_text(_populated_registry().snapshot())
    with pytest.raises(ValueError, match="cumulative"):
        export.validate_text(good.replace('le="+Inf"} 5', 'le="+Inf"} 1', 1)
                             .replace("t_wait_ms_count 5", "t_wait_ms_count 1"))
    with pytest.raises(ValueError, match="no declared family"):
        export.validate_text(good + "undeclared_metric 1\n")
    with pytest.raises(ValueError, match="TYPE without HELP"):
        export.validate_text("# TYPE foo counter\nfoo 1\n")


def test_prometheus_textfile_and_http_endpoint(tmp_path, monkeypatch):
    monkeypatch.setenv("ETH_SPECS_OBS_PROM", str(tmp_path / "metrics.prom"))
    path = export.write_textfile(snap=_populated_registry().snapshot())
    assert path == str(tmp_path / "metrics.prom")
    export.validate_text(open(path).read())

    server = export.serve_http(0)  # ephemeral port
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=10) as rsp:
            assert rsp.status == 200
            assert "text/plain" in rsp.headers["Content-Type"]
            export.validate_text(rsp.read().decode())
    finally:
        server.shutdown()


def test_http_endpoint_disabled_without_env(monkeypatch):
    monkeypatch.delenv("ETH_SPECS_OBS_HTTP_PORT", raising=False)
    assert export.serve_http() is None
    # the idempotent entry-point starter is equally env-gated
    assert export.maybe_serve_http() is None


def test_plugin_writes_prom_even_without_report(tmp_path, monkeypatch):
    from eth_consensus_specs_tpu.test_infra.obs_plugin import ObsPlugin

    monkeypatch.setenv("ETH_SPECS_OBS_REPORT", "0")  # JSON report disabled
    prom = tmp_path / "metrics.prom"
    monkeypatch.setenv("ETH_SPECS_OBS_PROM", str(prom))
    obs.count("t.plugin_probe", 1)
    plugin = ObsPlugin(str(tmp_path))
    assert plugin._path is None
    plugin.pytest_sessionfinish(session=None, exitstatus=0)
    export.validate_text(prom.read_text())


# ------------------------------------------------------------------- trace --


def test_trace_wire_roundtrip_and_children():
    root = trace.new_trace()
    wire = trace.to_wire(root)
    back = trace.from_wire(wire)
    assert back.trace_id == root.trace_id and back.span_id == root.span_id
    assert trace.from_wire(None) is None and trace.to_wire(None) is None
    kid = trace.child(root)
    assert kid.trace_id == root.trace_id and kid.parent_id == root.span_id
    # child with no context anywhere = fresh root
    orphan = trace.child()
    assert orphan.parent_id is None and orphan.trace_id != root.trace_id


def test_spans_under_active_context_carry_trace_ids():
    reg = obs.Registry()
    ctx = trace.new_trace()
    with trace.activate(ctx):
        with reg.span("tt.outer"):
            with reg.span("tt.inner"):
                pass
    spans = {e["name"]: e for e in reg.events if e.get("kind") == "span"}
    outer, inner = spans["tt.outer"], spans["tt.inner"]
    assert outer["trace_id"] == inner["trace_id"] == ctx.trace_id
    assert outer["parent_span"] == ctx.span_id
    assert inner["parent_span"] == outer["span_id"]
    # context restored after the block: spans outside record no ids
    with reg.span("tt.free"):
        pass
    assert "trace_id" not in {e["name"]: e for e in reg.events}["tt.free"]


def test_trace_survives_serve_submit_flush_dispatch():
    from eth_consensus_specs_tpu import serve
    from eth_consensus_specs_tpu.serve.config import ServeConfig

    rng = np.random.default_rng(3)
    chunks = rng.integers(0, 256, size=(13, 32)).astype(np.uint8)
    ctx = trace.new_trace()
    svc = serve.VerifyService(ServeConfig.from_env(max_batch=4, max_wait_ms=2))
    try:
        with trace.activate(ctx):
            fut = svc.submit_hash_tree_root(chunks)
        assert fut.result(timeout=60) is not None
    finally:
        svc.close()
    events = list(obs.get_registry().events)
    # the flush event links the request by its wire id (trace_id-span_id)
    flushes = [
        e for e in events
        if e.get("kind") == "serve.flush"
        and any(f.startswith(ctx.trace_id + "-") for f in e.get("flows", ()))
    ]
    assert flushes, "no flush event carried the submitted request's flow link"
    # the dispatch span (another thread) carries the same flow link and
    # its own trace ids
    dispatches = [
        e for e in events
        if e.get("kind") == "span" and e.get("name") == "serve.dispatch"
        and ctx.trace_id in e.get("flows", "")
    ]
    assert dispatches, "dispatch span lost the request's flow link"
    assert all(d.get("trace_id") for d in dispatches)


def test_trace_and_histograms_cross_gen_pool_boundary(tmp_path, monkeypatch):
    """One pool run: worker gen.case spans stitch to the parent's run
    trace through the shared JSONL sink, and the workers' serve wait
    histogram + queue gauges merge into the parent registry (the
    worker→parent delta now ships more than counters)."""
    from eth_consensus_specs_tpu.gen import discover_test_cases, run_generator

    monkeypatch.setenv("ETH_SPECS_SERVE", "1")
    cases = discover_test_cases(
        presets=("minimal",), forks=("phase0",), runners=("operations",)
    )
    cases = [c for c in cases if c.handler == "attestation"][:3]
    assert cases, "need attestation cases for a pool run"
    before_hist = obs.snapshot()["histograms"].get("serve.wait_ms", {}).get("count", 0)
    jsonl = tmp_path / "events.jsonl"
    reg = obs.get_registry()
    reg.configure_jsonl(str(jsonl))
    try:
        stats = run_generator(cases, str(tmp_path / "out"), workers=2)
    finally:
        reg.configure_jsonl(None)
    assert stats["failed"] == 0 and stats["written"] >= 1

    lines = [json.loads(line) for line in open(jsonl)]
    runs = [e for e in lines if e.get("kind") == "gen.run"]
    assert runs and runs[-1].get("trace_id")
    tid = runs[-1]["trace_id"]
    case_spans = [
        e for e in lines if e.get("kind") == "span" and e.get("name") == "gen.case"
    ]
    assert case_spans, "no gen.case spans reached the shared JSONL sink"
    assert all(e.get("trace_id") == tid for e in case_spans), (
        "worker-side case spans did not stitch to the parent run trace"
    )
    snap = obs.snapshot()
    # the workers' wait distribution merged into the parent registry
    assert snap["histograms"].get("serve.wait_ms", {}).get("count", 0) > before_hist
    assert "serve.queue_depth" in snap["gauges"]


# --------------------------------------------------------------------- slo --


def _snapshot_with(p99_ms: float, divergences: int = 0, degraded: int = 0,
                   requests: int = 100) -> dict:
    h = Histogram()
    for _ in range(99):
        h.record(p99_ms / 2)
    for _ in range(2):
        h.record(p99_ms)
    return {
        "counters": {
            "watchdog.divergences": divergences,
            "serve.degraded_items": degraded,
            "serve.requests": requests,
        },
        "histograms": {"serve.wait_ms": h.snapshot()},
    }


def test_slo_pass_path():
    results = slo.evaluate(_snapshot_with(p99_ms=10.0))
    assert slo.passed(results)
    rep = slo.report(results)
    assert rep["ok"] and rep["violations"] == []
    json.dumps(rep)  # CI writes this verbatim


def test_slo_fail_paths():
    bad = slo.evaluate(_snapshot_with(p99_ms=100_000.0, divergences=2, degraded=50))
    rep = slo.report(bad)
    assert not rep["ok"]
    assert {"serve_wait_p99", "watchdog_divergences", "degraded_rate"} <= set(
        rep["violations"]
    )
    # degradations with zero traffic to amortize them violate the ratio SLO
    silent = slo.evaluate(_snapshot_with(p99_ms=1.0, degraded=3, requests=0))
    assert "degraded_rate" in slo.report(silent)["violations"]


def test_slo_vacuous_pass_on_missing_histogram():
    results = slo.evaluate({"counters": {}, "histograms": {}})
    assert slo.passed(results)
    wait = next(r for r in results if r.name == "serve_wait_p99")
    assert wait.observed is None and "vacuous" in wait.detail


def test_slo_env_bound_override(monkeypatch):
    monkeypatch.setenv("ETH_SPECS_SLO_WAIT_P99_MS", "1.5")
    results = slo.evaluate(_snapshot_with(p99_ms=10.0))
    assert "serve_wait_p99" in slo.report(results)["violations"]
