"""BLS12-381 signature-scheme tests: scheme consistency, serialization
round-trips, negative cases, batch verification, and the backend switch."""

import pytest

from eth_consensus_specs_tpu.crypto.curve import (
    g1_from_bytes,
    g1_generator,
    g1_to_bytes,
    g2_from_bytes,
    g2_generator,
    g2_to_bytes,
    in_subgroup,
)
from eth_consensus_specs_tpu.ops.bls_batch import batch_verify_aggregates
from eth_consensus_specs_tpu.utils import bls


def setup_module():
    bls.bls_active = True


MSG_A = b"\x12" * 32
MSG_B = b"\x34" * 32


def test_sign_verify_roundtrip():
    sk = 12345
    pk = bls.SkToPk(sk)
    sig = bls.Sign(sk, MSG_A)
    assert bls.Verify(pk, MSG_A, sig)
    assert not bls.Verify(pk, MSG_B, sig)
    assert not bls.Verify(bls.SkToPk(999), MSG_A, sig)


def test_signature_deterministic():
    assert bls.Sign(7, MSG_A) == bls.Sign(7, MSG_A)
    assert bls.Sign(7, MSG_A) != bls.Sign(8, MSG_A)


def test_aggregate_and_fast_aggregate_verify():
    sks = [1, 2, 3]
    pks = [bls.SkToPk(sk) for sk in sks]
    sigs = [bls.Sign(sk, MSG_A) for sk in sks]
    agg = bls.Aggregate(sigs)
    assert bls.FastAggregateVerify(pks, MSG_A, agg)
    assert not bls.FastAggregateVerify(pks, MSG_B, agg)
    assert not bls.FastAggregateVerify(pks[:2], MSG_A, agg)


def test_aggregate_verify_distinct_messages():
    sks = [5, 6]
    msgs = [MSG_A, MSG_B]
    pks = [bls.SkToPk(sk) for sk in sks]
    agg = bls.Aggregate([bls.Sign(sk, m) for sk, m in zip(sks, msgs)])
    assert bls.AggregateVerify(pks, msgs, agg)
    assert not bls.AggregateVerify(pks, [MSG_A, MSG_A], agg)


def test_key_validate():
    assert bls.KeyValidate(bls.SkToPk(42))
    assert not bls.KeyValidate(bls.G1_POINT_AT_INFINITY)
    assert not bls.KeyValidate(b"\x00" * 48)
    assert not bls.KeyValidate(b"\xff" * 48)


def test_point_serialization_roundtrip():
    p = g1_generator().mul(777)
    assert g1_from_bytes(g1_to_bytes(p)) == p
    q = g2_generator().mul(888)
    assert g2_from_bytes(g2_to_bytes(q)) == q
    assert in_subgroup(q)


def test_invalid_signature_bytes_rejected():
    pk = bls.SkToPk(1)
    assert not bls.Verify(pk, MSG_A, b"\x00" * 96)
    assert not bls.Verify(pk, MSG_A, b"\xff" * 96)


def test_batch_verify_aggregates():
    sks1, sks2 = [1, 2], [3, 4]
    pks1 = [bls.SkToPk(s) for s in sks1]
    pks2 = [bls.SkToPk(s) for s in sks2]
    agg1 = bls.Aggregate([bls.Sign(s, MSG_A) for s in sks1])
    agg2 = bls.Aggregate([bls.Sign(s, MSG_B) for s in sks2])
    assert batch_verify_aggregates([(pks1, MSG_A, agg1), (pks2, MSG_B, agg2)])
    # one bad item poisons the batch
    assert not batch_verify_aggregates([(pks1, MSG_A, agg1), (pks2, MSG_A, agg2)])


def test_stub_mode():
    bls.bls_active = False
    try:
        assert bls.Sign(1, MSG_A) == bls.STUB_SIGNATURE
        assert bls.Verify(b"\x00" * 48, MSG_A, bls.STUB_SIGNATURE)
        assert bls.FastAggregateVerify([], MSG_A, bls.STUB_SIGNATURE)
    finally:
        bls.bls_active = True


def test_h2g2_cache_keys_include_dst():
    """Regression (ADVICE round-4 low): the hash-to-G2 cache must key on
    (dst, message) — a caller priming under one domain-separation tag
    must never serve its points to a reader under another."""
    from eth_consensus_specs_tpu.ops import bls_batch

    msg = b"\xaa" * 32
    dst_a, dst_b = b"DST-A", b"DST-B"
    saved = dict(bls_batch._H2G2_CACHE)
    bls_batch._H2G2_CACHE.clear()
    try:
        bls_batch._prime_h2g2_cache([msg], lambda ms, dst: ["A-point"] * len(ms), dst=dst_a)
        bls_batch._prime_h2g2_cache([msg], lambda ms, dst: ["B-point"] * len(ms), dst=dst_b)
        # both entries coexist — neither aliased the other
        assert bls_batch._h2g2(msg, dst_a) == "A-point"
        assert bls_batch._h2g2(msg, dst_b) == "B-point"
        assert (dst_a, msg) in bls_batch._H2G2_CACHE
        assert (dst_b, msg) in bls_batch._H2G2_CACHE
        # a third DST misses the cache entirely (falls through to a real
        # hash_to_g2 — a point object, never one of the sentinels)
        real = bls_batch._h2g2(msg, b"DST-C" + bls_batch.DST_G2)
        assert real not in ("A-point", "B-point")
    finally:
        bls_batch._H2G2_CACHE.clear()
        bls_batch._H2G2_CACHE.update(saved)


def test_batch_verify_emits_obs_counters(kernel_counters):
    from eth_consensus_specs_tpu import obs

    sks = [5, 6]
    pks = [bls.SkToPk(s) for s in sks]
    agg = bls.Aggregate([bls.Sign(s, MSG_A) for s in sks])
    assert batch_verify_aggregates([(pks, MSG_A, agg)])
    delta = kernel_counters()
    assert delta["bls.batches"] == 1
    assert delta["bls.batch_items"] == 1
    assert delta["bls.pairings"] == 1
    assert "bls.batch_verify" in obs.snapshot()["spans"]
