"""KZG polynomial-commitment tests against the spec semantics
(reference analogue: tests/generators/runners/kzg.py vector families).
Each commit/prove op costs ~1.5 s on the pure-python MSM, so scenarios
share one blob."""

import hashlib

import pytest

# heavy device-compile / pure-python crypto — nightly lane (make test-full)
pytestmark = pytest.mark.slow

from eth_consensus_specs_tpu.crypto import kzg


def make_blob(tag: bytes) -> bytes:
    out = []
    for i in range(kzg.FIELD_ELEMENTS_PER_BLOB):
        h = hashlib.sha256(tag + i.to_bytes(4, "big")).digest()
        out.append((int.from_bytes(h, "big") % kzg.BLS_MODULUS).to_bytes(32, "big"))
    return b"".join(out)


@pytest.fixture(scope="module")
def blob_commit_proof():
    blob = make_blob(b"kzg-test")
    commitment = kzg.blob_to_kzg_commitment(blob)
    proof = kzg.compute_blob_kzg_proof(blob, commitment)
    return blob, commitment, proof


def test_blob_roundtrip(blob_commit_proof):
    blob, commitment, proof = blob_commit_proof
    assert kzg.verify_blob_kzg_proof(blob, commitment, proof)


def test_blob_wrong_proof_rejected(blob_commit_proof):
    blob, commitment, _ = blob_commit_proof
    assert not kzg.verify_blob_kzg_proof(blob, commitment, kzg.G1_POINT_AT_INFINITY)


def test_blob_wrong_blob_rejected(blob_commit_proof):
    blob, commitment, proof = blob_commit_proof
    tampered = b"\x00" * 32 + blob[32:]
    assert not kzg.verify_blob_kzg_proof(tampered, commitment, proof)


def test_point_proof_arbitrary_z(blob_commit_proof):
    blob, commitment, _ = blob_commit_proof
    z = (987654321).to_bytes(32, "big")
    proof, y = kzg.compute_kzg_proof(blob, z)
    assert kzg.verify_kzg_proof(commitment, z, y, proof)
    bad_y = ((int.from_bytes(y, "big") + 1) % kzg.BLS_MODULUS).to_bytes(32, "big")
    assert not kzg.verify_kzg_proof(commitment, z, bad_y, proof)


def test_point_proof_in_domain(blob_commit_proof):
    blob, commitment, _ = blob_commit_proof
    # z a root of unity: y must equal the blob element at that position
    z_int = kzg._roots_brp(kzg.FIELD_ELEMENTS_PER_BLOB)[7]
    z = z_int.to_bytes(32, "big")
    proof, y = kzg.compute_kzg_proof(blob, z)
    assert int.from_bytes(y, "big") == kzg.blob_to_polynomial(blob)[7]
    assert kzg.verify_kzg_proof(commitment, z, y, proof)


def test_batch_verify(blob_commit_proof):
    blob, commitment, proof = blob_commit_proof
    # batch of 2 (same blob twice is a valid batch) plus the empty batch
    assert kzg.verify_blob_kzg_proof_batch([blob, blob], [commitment, commitment], [proof, proof])
    assert kzg.verify_blob_kzg_proof_batch([], [], [])
    assert not kzg.verify_blob_kzg_proof_batch(
        [blob, blob], [commitment, commitment], [proof, kzg.G1_POINT_AT_INFINITY]
    )


def test_scalar_out_of_range_rejected():
    bad = (kzg.BLS_MODULUS).to_bytes(32, "big")
    with pytest.raises(AssertionError):
        kzg.bytes_to_bls_field(bad)


def test_bit_reversal_permutation_involution():
    seq = list(range(16))
    assert kzg.bit_reversal_permutation(kzg.bit_reversal_permutation(seq)) == seq


def test_roots_of_unity():
    roots = kzg.compute_roots_of_unity(4096)
    assert len(set(roots)) == 4096
    assert pow(roots[1], 4096, kzg.BLS_MODULUS) == 1
    assert pow(roots[1], 2048, kzg.BLS_MODULUS) != 1
