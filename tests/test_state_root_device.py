"""Full-state device merkleization (ops/state_root.py via
parallel/resident.py) vs ssz.hash_tree_root on the equivalently-updated
object state — SURVEY hard part 3's bit-exactness gate."""

import pytest

# full-state root compiles are minutes-scale — nightly/full lane (make test-full)
pytestmark = pytest.mark.slow

import numpy as np

from eth_consensus_specs_tpu import ssz
from eth_consensus_specs_tpu.parallel import resident
from eth_consensus_specs_tpu.test_infra.attestations import next_epoch_with_attestations
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases


def _root_bytes(acc) -> bytes:
    return np.asarray(acc).astype(">u4", order="C").view(np.uint8).tobytes()


def _to_boundary(spec, state):
    from eth_consensus_specs_tpu.test_infra.state import next_slots

    boundary = int(state.slot) + (
        spec.SLOTS_PER_EPOCH - int(state.slot) % spec.SLOTS_PER_EPOCH
    )
    if int(state.slot) < boundary - 1:
        next_slots(spec, state, boundary - 1 - int(state.slot))


def _device_vs_object(spec, state, with_root="state"):
    _to_boundary(spec, state)
    cols, just, static = resident.ingest_full(spec, state)
    carry = resident.run_epochs(spec, cols, just, 1, with_root=with_root, static=static)
    device_root = _root_bytes(carry.root_acc)

    expected = state.copy()
    old_current = list(expected.current_epoch_participation)
    resident.writeback(spec, expected, carry)
    # the accounting epoch's participation rotation
    part_t = type(expected.current_epoch_participation)
    expected.previous_epoch_participation = part_t(old_current)
    expected.current_epoch_participation = part_t([0] * len(old_current))
    assert bytes(ssz.hash_tree_root(expected)) == device_root


@with_phases(["altair", "deneb"])
@spec_state_test
def test_state_root_genesis_epoch(spec, state):
    _device_vs_object(spec, state)


@with_phases(["altair", "deneb"])
@spec_state_test
def test_state_root_after_participation(spec, state):
    next_epoch_with_attestations(spec, state, fill_cur_epoch=False, fill_prev_epoch=True)
    # dirty some balances/validators so every dynamic subtree moves
    for i in range(0, len(state.validators), 3):
        state.balances[i] = int(state.balances[i]) - 12345
    state.validators[2].slashed = True
    _device_vs_object(spec, state)


@with_phases(["altair", "deneb"])
@spec_state_test
def test_state_root_incremental_vs_object_tree(spec, state):
    """The merkle_inc forest path against ssz.hash_tree_root on the
    equivalently-updated object state — the incremental root is the
    OBJECT tree's root after writeback, not merely the full device
    path's (which tests/test_resident.py already pins it to)."""
    next_epoch_with_attestations(spec, state, fill_cur_epoch=False, fill_prev_epoch=True)
    for i in range(0, len(state.validators), 3):
        state.balances[i] = int(state.balances[i]) - 12345
    state.validators[2].slashed = True
    _device_vs_object(spec, state, with_root="state_inc")


@with_phases(["altair"])
@spec_state_test
def test_state_root_multi_epoch_chain(spec, state):
    """Three chained epochs: the xor-accumulated roots must equal the
    xor of three independently computed object roots is impractical to
    reconstruct midway, so instead run 1 epoch twice from the same state
    and check determinism + non-triviality."""
    _to_boundary(spec, state)
    cols, just, static = resident.ingest_full(spec, state)
    c1 = resident.run_epochs(spec, cols, just, 1, with_root="state", static=static)
    c2 = resident.run_epochs(spec, cols, just, 1, with_root="state", static=static)
    assert _root_bytes(c1.root_acc) == _root_bytes(c2.root_acc)
    assert _root_bytes(c1.root_acc) != b"\x00" * 32
    c3 = resident.run_epochs(spec, cols, just, 3, with_root="state", static=static)
    assert _root_bytes(c3.root_acc) != _root_bytes(c1.root_acc)
