"""Randomized pre-states through every mainline upgrade (reference
analogue: the per-fork fork/test_*_fork_random.py families — randomized
balances/exits/slashings/participation upgraded and then driven —
generated for every upgrade pair by the template machinery). Each case
randomizes a state, upgrades it, and drives randomized blocks on the
post-fork spec (cheap with the BLS stub: ~0.3 s per case)."""

import random

from eth_consensus_specs_tpu import ssz
from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.test_infra.fork_transition import (
    do_fork,
    transition_until_fork,
)
from eth_consensus_specs_tpu.test_infra.genesis import create_genesis_state
from eth_consensus_specs_tpu.test_infra.template import for_each_upgrade
from eth_consensus_specs_tpu.utils import bls

from ..random.test_random_blocks import _random_chain
from ..random.test_random_scenarios import _check_invariants, randomize_state

FORK_EPOCH = 2


def _bls_off(fn):
    def run():
        with bls.inactive():
            fn()

    return run


def _upgrade_randomized(pre_fork: str, post_fork: str, seed: int, balances: str):
    spec = get_spec(pre_fork, "minimal")
    rng = random.Random(seed)
    cap = int(spec.MAX_EFFECTIVE_BALANCE)
    inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    low = int(spec.config.EJECTION_BALANCE)
    if balances == "low":
        bal = [rng.choice([low, low + inc]) for _ in range(32)]
    elif balances == "misc":
        bal = [rng.choice([low, cap // 2, cap, cap + inc]) for _ in range(32)]
    else:
        bal = [cap] * 32
    state = create_genesis_state(spec, bal, low)
    randomize_state(spec, state, rng)
    post_spec = get_spec(post_fork, "minimal")
    transition_until_fork(spec, state, FORK_EPOCH)
    state, _ = do_fork(spec, post_spec, state, FORK_EPOCH, with_block=False)
    return post_spec, state, rng


def _fork_random_full(pre_fork: str, post_fork: str):
    @_bls_off
    def test_fn():
        post_spec, state, rng = _upgrade_randomized(pre_fork, post_fork, 71, "full")
        _check_invariants(post_spec, state)
        _random_chain(post_spec, state, rng, int(post_spec.SLOTS_PER_EPOCH) + 2)
        _check_invariants(post_spec, state)
        # post state serializes through the post type
        rt = ssz.deserialize(post_spec.BeaconState, ssz.serialize(state))
        assert bytes(ssz.hash_tree_root(rt)) == bytes(ssz.hash_tree_root(state))

    return test_fn, f"test_fork_random_full_{pre_fork}_to_{post_fork}"


def _fork_random_balances(variant: str, seed: int):
    """Factory-of-factories: one body serves every balance profile."""

    def factory(pre_fork: str, post_fork: str):
        @_bls_off
        def test_fn():
            post_spec, state, rng = _upgrade_randomized(
                pre_fork, post_fork, seed, variant
            )
            _check_invariants(post_spec, state)
            _random_chain(post_spec, state, rng, int(post_spec.SLOTS_PER_EPOCH))
            _check_invariants(post_spec, state)

        return test_fn, f"test_fork_random_{variant}_balances_{pre_fork}_to_{post_fork}"

    return factory


for_each_upgrade(_fork_random_full, "altair")
for_each_upgrade(_fork_random_balances("low", 72), "altair")
for_each_upgrade(_fork_random_balances("misc", 73), "altair")
