"""Blocks spanning a fork boundary (reference analogue:
test/altair/transition/test_transition.py and the per-fork
fork/test_*_fork_basic.py families — normal transitions, transitions with
blocks on both sides, and state-shape variations), generated for every
mainline upgrade pair by the template machinery."""

import random

from eth_consensus_specs_tpu import ssz
from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.test_infra.fork_transition import (
    do_fork,
    transition_to_next_epoch_and_append_blocks,
    transition_until_fork,
)
from eth_consensus_specs_tpu.test_infra.genesis import create_genesis_state
from eth_consensus_specs_tpu.test_infra.state import next_epoch
from eth_consensus_specs_tpu.test_infra.template import for_each_upgrade
from eth_consensus_specs_tpu.utils import bls

FORK_EPOCH = 2


def _pre_state(pre_fork: str, balances=None):
    spec = get_spec(pre_fork, "minimal")
    with bls.inactive():
        if balances is None:
            balances = [int(spec.MAX_EFFECTIVE_BALANCE)] * 32
        state = create_genesis_state(spec, balances, int(spec.config.EJECTION_BALANCE))
    return spec, state


def _run_boundary(pre_fork, post_fork, balances=None, blocks_after=2):
    spec, state = _pre_state(pre_fork, balances)
    post_spec = get_spec(post_fork, "minimal")
    with bls.inactive():
        transition_until_fork(spec, state, FORK_EPOCH)
        state, fork_block = do_fork(spec, post_spec, state, FORK_EPOCH)
        assert fork_block is not None
        blocks = [fork_block]
        transition_to_next_epoch_and_append_blocks(
            post_spec, state, blocks, count=blocks_after
        )
    return post_spec, state, blocks


def _normal_transition(pre_fork: str, post_fork: str):
    def test_fn():
        post_spec, state, blocks = _run_boundary(pre_fork, post_fork)
        # chain continuity: every block's parent is the previous block
        for a, b in zip(blocks, blocks[1:]):
            assert bytes(b.message.parent_root) == bytes(
                ssz.hash_tree_root(a.message)
            )
        assert int(state.fork.epoch) == FORK_EPOCH
        # post state round-trips through the post-fork type
        rt = ssz.deserialize(post_spec.BeaconState, ssz.serialize(state))
        assert bytes(ssz.hash_tree_root(rt)) == bytes(ssz.hash_tree_root(state))

    return test_fn, f"test_blocks_across_fork_{pre_fork}_to_{post_fork}"


def _random_balances_transition(pre_fork: str, post_fork: str):
    def test_fn():
        rng = random.Random(40 + len(pre_fork))
        spec = get_spec(pre_fork, "minimal")
        cap = int(spec.MAX_EFFECTIVE_BALANCE)
        inc = int(spec.EFFECTIVE_BALANCE_INCREMENT)
        low = int(spec.config.EJECTION_BALANCE)
        balances = [
            rng.choice([low, low + inc, cap // 2, cap, cap + inc]) for _ in range(32)
        ]
        post_spec, state, blocks = _run_boundary(pre_fork, post_fork, balances)
        assert len(blocks) == 3
        assert int(state.fork.epoch) == FORK_EPOCH

    return test_fn, f"test_fork_random_balances_{pre_fork}_to_{post_fork}"


def _fork_many_epochs_later(pre_fork: str, post_fork: str):
    def test_fn():
        spec, state = _pre_state(pre_fork)
        post_spec = get_spec(post_fork, "minimal")
        with bls.inactive():
            late_epoch = FORK_EPOCH + 3
            for _ in range(late_epoch):
                next_epoch(spec, state)
            # state sits at a late epoch boundary minus nothing: move to
            # last slot before the next epoch, then fork there
            transition_until_fork(spec, state, late_epoch + 1)
            state, fork_block = do_fork(spec, post_spec, state, late_epoch + 1)
            assert int(state.fork.epoch) == late_epoch + 1
            assert fork_block is not None

    return test_fn, f"test_fork_many_epochs_later_{pre_fork}_to_{post_fork}"


for_each_upgrade(_normal_transition, "altair")
for_each_upgrade(_random_balances_transition, "altair")
for_each_upgrade(_fork_many_epochs_later, "altair")
