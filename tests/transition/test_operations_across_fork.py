"""Operations straddling a fork boundary (reference analogue:
test/altair/transition/test_operations.py — each operation included in
the first post-fork block, constructed against the pre-fork state — and
test_leaking.py / test_activations_and_exits.py state-shape variants),
generated for every mainline upgrade pair by the template machinery."""

from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.fork_transition import (
    do_fork,
    transition_until_fork,
)
from eth_consensus_specs_tpu.test_infra.genesis import create_genesis_state
from eth_consensus_specs_tpu.test_infra.slashings import (
    get_valid_attester_slashing,
    get_valid_proposer_slashing,
)
from eth_consensus_specs_tpu.test_infra.template import for_each_upgrade
from eth_consensus_specs_tpu.test_infra.voluntary_exits import prepare_signed_exits
from eth_consensus_specs_tpu.utils import bls

FORK_EPOCH = 2


def _state_at_fork(pre_fork: str, post_fork: str):
    """Pre-state advanced to the last pre-fork slot, then upgraded (no
    boundary block — the op rides the first post-fork block)."""
    spec = get_spec(pre_fork, "minimal")
    state = create_genesis_state(
        spec,
        [int(spec.MAX_EFFECTIVE_BALANCE)] * 32,
        int(spec.config.EJECTION_BALANCE),
    )
    post_spec = get_spec(post_fork, "minimal")
    transition_until_fork(spec, state, FORK_EPOCH)
    state, _ = do_fork(spec, post_spec, state, FORK_EPOCH, with_block=False)
    return spec, post_spec, state


def _apply_post_fork_block_with(post_spec, state, attach):
    block = build_empty_block_for_next_slot(post_spec, state)
    attach(block)
    return state_transition_and_sign_block(post_spec, state, block)


def _with_bls_off(fn):
    def run():
        with bls.inactive():
            fn()

    return run


def _proposer_slashing_after_fork(pre_fork: str, post_fork: str):
    @_with_bls_off
    def test_fn():
        spec, post_spec, state = _state_at_fork(pre_fork, post_fork)
        slashing = get_valid_proposer_slashing(post_spec, state, signed_1=True, signed_2=True)
        idx = int(slashing.signed_header_1.message.proposer_index)
        _apply_post_fork_block_with(
            post_spec, state, lambda b: b.body.proposer_slashings.append(slashing)
        )
        assert state.validators[idx].slashed

    return test_fn, f"test_proposer_slashing_after_fork_{pre_fork}_to_{post_fork}"


def _attester_slashing_after_fork(pre_fork: str, post_fork: str):
    @_with_bls_off
    def test_fn():
        spec, post_spec, state = _state_at_fork(pre_fork, post_fork)
        slashing = get_valid_attester_slashing(
            post_spec, state, signed_1=True, signed_2=True
        )
        targets = set(slashing.attestation_1.attesting_indices) & set(
            slashing.attestation_2.attesting_indices
        )
        assert targets
        _apply_post_fork_block_with(
            post_spec, state, lambda b: b.body.attester_slashings.append(slashing)
        )
        assert all(state.validators[int(i)].slashed for i in targets)

    return test_fn, f"test_attester_slashing_after_fork_{pre_fork}_to_{post_fork}"


def _voluntary_exit_after_fork(pre_fork: str, post_fork: str):
    @_with_bls_off
    def test_fn():
        spec, post_spec, state = _state_at_fork(pre_fork, post_fork)
        # old enough to exit
        state.slot = max(
            int(state.slot),
            int(post_spec.config.SHARD_COMMITTEE_PERIOD) * post_spec.SLOTS_PER_EPOCH,
        )
        signed_exits = prepare_signed_exits(post_spec, state, [1])
        _apply_post_fork_block_with(
            post_spec, state, lambda b: b.body.voluntary_exits.append(signed_exits[0])
        )
        assert state.validators[1].exit_epoch != post_spec.FAR_FUTURE_EPOCH

    return test_fn, f"test_voluntary_exit_after_fork_{pre_fork}_to_{post_fork}"


def _leak_across_fork(pre_fork: str, post_fork: str):
    @_with_bls_off
    def test_fn():
        """A chain leaking before the fork keeps leaking after it: the
        finality-delay signal survives the upgrade."""
        spec, post_spec, state = _state_at_fork(pre_fork, post_fork)
        # no attestations before or after the boundary -> leak sets in
        from eth_consensus_specs_tpu.test_infra.state import next_epoch

        for _ in range(int(post_spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 2):
            next_epoch(post_spec, state)
        assert post_spec.is_in_inactivity_leak(state)
        assert int(state.finalized_checkpoint.epoch) == 0

    return test_fn, f"test_leak_across_fork_{pre_fork}_to_{post_fork}"


def _exits_at_fork(pre_fork: str, post_fork: str):
    @_with_bls_off
    def test_fn():
        """Validators whose exit lands AT the fork epoch leave the active
        set under the post spec."""
        spec = get_spec(pre_fork, "minimal")
        state = create_genesis_state(
            spec,
            [int(spec.MAX_EFFECTIVE_BALANCE)] * 32,
            int(spec.config.EJECTION_BALANCE),
        )
        post_spec = get_spec(post_fork, "minimal")
        quarter = len(state.validators) // 4
        for i in range(quarter):
            state.validators[i].exit_epoch = FORK_EPOCH
        transition_until_fork(spec, state, FORK_EPOCH)
        state, _ = do_fork(spec, post_spec, state, FORK_EPOCH, with_block=False)
        active = post_spec.get_active_validator_indices(
            state, post_spec.get_current_epoch(state)
        )
        assert len(active) == len(state.validators) - quarter
        assert all(int(i) >= quarter for i in active)

    return test_fn, f"test_exits_at_fork_{pre_fork}_to_{post_fork}"


def _historical_roots_preserved(pre_fork: str, post_fork: str):
    @_with_bls_off
    def test_fn():
        """Accumulated history survives the upgrade byte-for-byte."""
        spec, post_spec, state = _state_at_fork(pre_fork, post_fork)
        assert int(state.fork.epoch) == FORK_EPOCH
        # roots written before the fork are still addressable post-fork
        root = post_spec.get_block_root_at_slot(state, int(state.slot) - 1)
        assert bytes(root) != b"\x00" * 32

    return test_fn, f"test_historical_roots_preserved_{pre_fork}_to_{post_fork}"


for_each_upgrade(_proposer_slashing_after_fork, "altair")
for_each_upgrade(_attester_slashing_after_fork, "altair")
for_each_upgrade(_voluntary_exit_after_fork, "altair")
for_each_upgrade(_leak_across_fork, "altair")
for_each_upgrade(_exits_at_fork, "altair")
for_each_upgrade(_historical_roots_preserved, "altair")
