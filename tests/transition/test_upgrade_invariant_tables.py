"""Upgrade invariant tables — properties every fork boundary must
preserve, written out per upgrade edge (reference analogue:
test/<fork>/fork/test_<fork>_fork_basic.py families: one file per
upgrade with basic/randomized/large-validator variants)."""

from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test,
    with_phases,
)
from eth_consensus_specs_tpu.test_infra.state import next_epoch
from eth_consensus_specs_tpu.utils import bls

UPGRADES = [
    ("phase0", "altair"),
    ("altair", "bellatrix"),
    ("bellatrix", "capella"),
    ("capella", "deneb"),
    ("deneb", "electra"),
    ("electra", "fulu"),
    ("fulu", "gloas"),
]


def _upgraded(pre_fork: str, post_fork: str, mutate=None):
    from eth_consensus_specs_tpu.forks import get_spec
    from eth_consensus_specs_tpu.test_infra.genesis import create_genesis_state

    pre_spec = get_spec(pre_fork, "minimal")
    post_spec = get_spec(post_fork, "minimal")
    prev = bls.bls_active
    bls.bls_active = False
    try:
        state = create_genesis_state(
            pre_spec,
            [pre_spec.MAX_EFFECTIVE_BALANCE] * 64,
            pre_spec.MAX_EFFECTIVE_BALANCE,
        )
        next_epoch(pre_spec, state)
        if mutate:
            mutate(pre_spec, state)
        post = post_spec.upgrade_from_parent(state.copy())
    finally:
        bls.bls_active = prev
    return pre_spec, post_spec, state, post


def _check_upgrade_preserves(pre_fork, post_fork):
    pre_spec, post_spec, pre, post = _upgraded(pre_fork, post_fork)
    # registry, balances and randao history survive byte-identically
    assert len(post.validators) == len(pre.validators)
    assert [int(b) for b in post.balances] == [int(b) for b in pre.balances]
    assert bytes(hash_tree_root(post.randao_mixes)) == bytes(
        hash_tree_root(pre.randao_mixes)
    )
    # fork record: previous <- old current, epoch = current epoch
    assert bytes(post.fork.previous_version) == bytes(pre.fork.current_version)
    assert int(post.fork.epoch) == int(pre_spec.get_current_epoch(pre))
    # slot and genesis identity unchanged
    assert int(post.slot) == int(pre.slot)
    assert bytes(post.genesis_validators_root) == bytes(pre.genesis_validators_root)


def _check_upgraded_state_advances(pre_fork, post_fork):
    _, post_spec, _, post = _upgraded(pre_fork, post_fork)
    next_epoch(post_spec, post)
    assert int(post.slot) % int(post_spec.SLOTS_PER_EPOCH) == 0


def _check_upgrade_with_slashed_validators(pre_fork, post_fork):
    def mutate(spec, state):
        for i in (0, 3):
            state.validators[i].slashed = True

    _, post_spec, pre, post = _upgraded(pre_fork, post_fork, mutate)
    assert post.validators[0].slashed and post.validators[3].slashed


def test_upgrade_preserves_phase0_altair():
    _check_upgrade_preserves("phase0", "altair")


def test_upgrade_preserves_altair_bellatrix():
    _check_upgrade_preserves("altair", "bellatrix")


def test_upgrade_preserves_bellatrix_capella():
    _check_upgrade_preserves("bellatrix", "capella")


def test_upgrade_preserves_capella_deneb():
    _check_upgrade_preserves("capella", "deneb")


def test_upgrade_preserves_deneb_electra():
    _check_upgrade_preserves("deneb", "electra")


def test_upgrade_preserves_electra_fulu():
    _check_upgrade_preserves("electra", "fulu")


def test_upgrade_preserves_fulu_gloas():
    _check_upgrade_preserves("fulu", "gloas")


def test_upgrade_advances_phase0_altair():
    _check_upgraded_state_advances("phase0", "altair")


def test_upgrade_advances_capella_deneb():
    _check_upgraded_state_advances("capella", "deneb")


def test_upgrade_advances_deneb_electra():
    _check_upgraded_state_advances("deneb", "electra")


def test_upgrade_advances_electra_fulu():
    _check_upgraded_state_advances("electra", "fulu")


def test_upgrade_advances_fulu_gloas():
    _check_upgraded_state_advances("fulu", "gloas")


def test_upgrade_slashed_phase0_altair():
    _check_upgrade_with_slashed_validators("phase0", "altair")


def test_upgrade_slashed_deneb_electra():
    _check_upgrade_with_slashed_validators("deneb", "electra")


def test_upgrade_slashed_fulu_gloas():
    _check_upgrade_with_slashed_validators("fulu", "gloas")


def test_electra_upgrade_builds_pending_deposit_queue():
    """deneb->electra: unfinalized deposits convert into the new pending
    queue structures and churn fields initialize."""
    _, post_spec, pre, post = _upgraded("deneb", "electra")
    assert int(post.deposit_requests_start_index) == int(
        post_spec.UNSET_DEPOSIT_REQUESTS_START_INDEX
    )
    assert int(post.earliest_exit_epoch) >= 0


def test_fulu_upgrade_initializes_lookahead():
    _, post_spec, pre, post = _upgraded("electra", "fulu")
    n = int(post_spec.SLOTS_PER_EPOCH)
    looked = [int(x) for x in post.proposer_lookahead]
    assert len(looked) == (int(post_spec.MIN_SEED_LOOKAHEAD) + 1) * n
    # entries are valid validator indices
    assert all(0 <= i < len(post.validators) for i in looked)


def test_gloas_upgrade_initializes_builder_fields():
    _, post_spec, pre, post = _upgraded("fulu", "gloas")
    assert len(post.builder_pending_payments) == 2 * int(post_spec.SLOTS_PER_EPOCH)
    assert len(post.builder_pending_withdrawals) == 0
