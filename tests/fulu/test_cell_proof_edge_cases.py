"""Cell-proof batch verification and recovery edge-case tables, fulu
(reference analogue: test/fulu/kzg/test_verify_cell_kzg_proof_batch.py
and test_recover_cells_and_kzg_proofs.py — the corruption-pattern
families; spec: specs/fulu/polynomial-commitments-sampling.md:617-828).

Shares the per-process blob fixture; marked slow (pure-python pairing
per batch check)."""

import pytest

from eth_consensus_specs_tpu.crypto import das

from .das_fixtures import sample_cells_and_proofs, sample_commitment

pytestmark = pytest.mark.slow

CELLS_PER_EXT_BLOB = das.CELLS_PER_EXT_BLOB
HALF = CELLS_PER_EXT_BLOB // 2


def _verify(indices, cells, proofs, commitment=None):
    commitment = commitment or sample_commitment()
    return das.verify_cell_kzg_proof_batch(
        [commitment] * len(indices), list(indices), list(cells), list(proofs)
    )


def test_batch_accepts_empty():
    assert _verify([], [], [])


def test_batch_accepts_single_cell():
    cells, proofs = sample_cells_and_proofs()
    assert _verify([0], [cells[0]], [proofs[0]])


def test_batch_accepts_duplicate_cell_indices():
    """The same (commitment, index, cell, proof) tuple twice is fine — the
    commitment dedup + RLC handle repeats (reference:
    verify_cell_kzg_proof_batch's deduplication, sampling.md:620-667)."""
    cells, proofs = sample_cells_and_proofs()
    assert _verify([3, 3], [cells[3], cells[3]], [proofs[3], proofs[3]])


def test_batch_rejects_cell_index_out_of_range():
    cells, proofs = sample_cells_and_proofs()
    with pytest.raises((AssertionError, IndexError, ValueError)):
        _verify([CELLS_PER_EXT_BLOB], [cells[0]], [proofs[0]])


def test_batch_rejects_mismatched_lengths():
    cells, proofs = sample_cells_and_proofs()
    with pytest.raises((AssertionError, ValueError)):
        das.verify_cell_kzg_proof_batch(
            [sample_commitment()], [0, 1], [cells[0]], [proofs[0]]
        )


def test_batch_rejects_malformed_commitment_length():
    cells, proofs = sample_cells_and_proofs()
    with pytest.raises((AssertionError, ValueError)):
        das.verify_cell_kzg_proof_batch(
            [b"\x01" * 47], [0], [cells[0]], [proofs[0]]
        )


def test_batch_rejects_cross_assigned_proofs():
    cells, proofs = sample_cells_and_proofs()
    assert not _verify([0, 1], [cells[0], cells[1]], [proofs[1], proofs[0]])


def test_batch_rejects_corrupted_cell_byte():
    cells, proofs = sample_cells_and_proofs()
    bad = bytearray(bytes(cells[2]))
    # flip a low-order bit of the first field element, keeping it canonical
    bad[31] ^= 0x01
    assert not _verify([2], [bytes(bad)], [proofs[2]])


def test_recover_from_exactly_half_even_indices():
    cells, proofs = sample_cells_and_proofs()
    indices = list(range(0, CELLS_PER_EXT_BLOB, 2))
    assert len(indices) == HALF
    rec_cells, rec_proofs = das.recover_cells_and_kzg_proofs(
        indices, [cells[i] for i in indices]
    )
    assert [bytes(c) for c in rec_cells] == [bytes(c) for c in cells]
    assert [bytes(p) for p in rec_proofs] == [bytes(p) for p in proofs]


def test_recover_rejects_one_below_half():
    cells, _ = sample_cells_and_proofs()
    indices = list(range(HALF - 1))
    with pytest.raises((AssertionError, ValueError)):
        das.recover_cells_and_kzg_proofs(indices, [cells[i] for i in indices])


def test_recover_from_second_half_only():
    """Recovery from ONLY extension cells reconstructs the systematic half."""
    cells, _ = sample_cells_and_proofs()
    indices = list(range(HALF, CELLS_PER_EXT_BLOB))
    rec_cells, _ = das.recover_cells_and_kzg_proofs(
        indices, [cells[i] for i in indices]
    )
    assert [bytes(c) for c in rec_cells[:HALF]] == [bytes(c) for c in cells[:HALF]]


def test_recover_rejects_non_canonical_cell_bytes():
    cells, _ = sample_cells_and_proofs()
    indices = list(range(HALF))
    donors = [bytes(cells[i]) for i in indices]
    donors[0] = b"\xff" * len(donors[0])  # field elements >= BLS_MODULUS
    with pytest.raises((AssertionError, ValueError)):
        das.recover_cells_and_kzg_proofs(indices, donors)
