"""Sampling KZG: cell computation, batch verification, recovery
(reference: specs/fulu/polynomial-commitments-sampling.md and
eth2spec/test/fulu/unittests/polynomial_commitments/)."""

import random

import pytest

# pure-python cell proofs/verification — nightly lane (make test-full)
pytestmark = pytest.mark.slow

from eth_consensus_specs_tpu.crypto import das, kzg

from .das_fixtures import sample_blob, sample_cells_and_proofs, sample_commitment


def test_fft_field_inverse_roundtrip():
    rng = random.Random(1)
    roots = das.compute_roots_of_unity(128)
    vals = [rng.randrange(das.BLS_MODULUS) for _ in range(128)]
    assert das.fft_field(das.fft_field(vals, roots), roots, inv=True) == vals
    assert das.fft_field(das.fft_field(vals, roots, inv=True), roots) == vals


def test_coset_fft_field_divides_vanishing():
    """coset FFT evaluates away from the subgroup: the subgroup's vanishing
    polynomial X^n - 1 has no zero on the coset."""
    n = 128
    roots = das.compute_roots_of_unity(n)
    vanishing = [(-1) % das.BLS_MODULUS] + [0] * (n - 1)
    # X^n - 1 reduced mod (X^n - const) leaves the constant term only; use
    # full-length coefficient vector [-1, 0, ..., 0] + leading handled via
    # evaluation identity: (x^n - 1) at coset points = shift^n * 1 - 1 != 0
    evals = das.coset_fft_field(vanishing, roots)
    # -1 everywhere plus shift^n * x^n term absent -> just check nonzero of
    # true vanishing evaluation computed directly
    shift = das.PRIMITIVE_ROOT_OF_UNITY
    for r in roots[:4]:
        x = shift * r % das.BLS_MODULUS
        assert pow(x, n, das.BLS_MODULUS) != 1
    assert all(e == (-1) % das.BLS_MODULUS for e in evals)


def test_cells_match_polynomial_evaluations():
    """Cell j's evals equal Horner evaluation over coset_for_cell(j)."""
    blob = sample_blob()
    coeff = das.polynomial_eval_to_coeff(kzg.blob_to_polynomial(blob))
    cells = das.compute_cells(blob)
    for j in (0, 63, 127):
        coset = das.coset_for_cell(j)
        expected = [das.evaluate_polynomialcoeff(coeff, z) for z in coset[:4]]
        got = das.cell_to_coset_evals(cells[j])[:4]
        assert got == expected


def test_first_half_cells_carry_the_blob():
    """The extension is systematic: cells 0..63 in brp order contain the
    original blob's evaluations."""
    blob = sample_blob()
    cells = das.compute_cells(blob)
    poly = kzg.blob_to_polynomial(blob)  # evaluation form, brp-indexed
    # blob evals are over the 4096-domain in brp order; the extended brp
    # order interleaves, so reconstruct directly and compare as sets
    ext_evals = set()
    for c in cells:
        ext_evals.update(das.cell_to_coset_evals(c))
    for y in poly[:64]:
        assert y % das.BLS_MODULUS in ext_evals


def test_verify_cell_kzg_proof_batch():
    cells, proofs = sample_cells_and_proofs()
    commitment = sample_commitment()
    idx = [0, 3, 64, 127]
    assert das.verify_cell_kzg_proof_batch(
        [commitment] * len(idx), idx, [cells[i] for i in idx], [proofs[i] for i in idx]
    )
    # empty batch is vacuously valid (reference behaviour)
    assert das.verify_cell_kzg_proof_batch([], [], [], [])


def test_verify_cell_kzg_proof_batch_rejects_wrong_cell():
    cells, proofs = sample_cells_and_proofs()
    commitment = sample_commitment()
    bad = bytearray(cells[1])
    bad[0:32] = (1).to_bytes(32, "big")
    assert not das.verify_cell_kzg_proof_batch(
        [commitment, commitment], [0, 1], [cells[0], bytes(bad)], [proofs[0], proofs[1]]
    )


def test_verify_cell_kzg_proof_batch_rejects_swapped_proofs():
    cells, proofs = sample_cells_and_proofs()
    commitment = sample_commitment()
    assert not das.verify_cell_kzg_proof_batch(
        [commitment, commitment], [0, 1], [cells[0], cells[1]], [proofs[1], proofs[0]]
    )


def test_verify_cell_kzg_proof_batch_rejects_wrong_index():
    cells, proofs = sample_cells_and_proofs()
    commitment = sample_commitment()
    assert not das.verify_cell_kzg_proof_batch([commitment], [2], [cells[1]], [proofs[1]])


def test_verify_cell_kzg_proof_batch_invalid_inputs():
    cells, proofs = sample_cells_and_proofs()
    commitment = sample_commitment()
    with pytest.raises(AssertionError):
        das.verify_cell_kzg_proof_batch([commitment], [128], [cells[0]], [proofs[0]])
    with pytest.raises(AssertionError):
        das.verify_cell_kzg_proof_batch([commitment[:47]], [0], [cells[0]], [proofs[0]])
    with pytest.raises(AssertionError):
        das.verify_cell_kzg_proof_batch([commitment], [0], [cells[0][:100]], [proofs[0]])


def test_recover_cells_and_kzg_proofs_roundtrip_random_subset():
    cells, proofs = sample_cells_and_proofs()
    rng = random.Random(7)
    keep = sorted(rng.sample(range(das.CELLS_PER_EXT_BLOB), das.CELLS_PER_EXT_BLOB // 2))
    rec_cells, rec_proofs = das.recover_cells_and_kzg_proofs(
        keep, [cells[i] for i in keep]
    )
    assert [bytes(c) for c in rec_cells] == [bytes(c) for c in cells]
    assert [bytes(p) for p in rec_proofs] == [bytes(p) for p in proofs]


def test_recover_with_all_cells_is_identity():
    cells, proofs = sample_cells_and_proofs()
    idx = list(range(das.CELLS_PER_EXT_BLOB))
    rec_cells, rec_proofs = das.recover_cells_and_kzg_proofs(idx, cells)
    assert [bytes(c) for c in rec_cells] == [bytes(c) for c in cells]
    assert [bytes(p) for p in rec_proofs] == [bytes(p) for p in proofs]


def test_fk20_matches_explicit_multiproof():
    """The FK20 lag-MSM + G1-FFT path equals the reference's per-cell
    quotient construction (compute_kzg_proof_multi_impl)."""
    blob = sample_blob()
    coeff = das.polynomial_eval_to_coeff(kzg.blob_to_polynomial(blob))
    cells, proofs = sample_cells_and_proofs()
    for j in (0, 81):
        proof_ref, ys_ref = das.compute_kzg_proof_multi_impl(coeff, das.coset_for_cell(j))
        assert bytes(proofs[j]) == bytes(proof_ref)
        assert das.cell_to_coset_evals(cells[j]) == ys_ref


def test_interpolate_coset_ifft_matches_lagrange():
    rng = random.Random(3)
    ys = [rng.randrange(das.BLS_MODULUS) for _ in range(das.FIELD_ELEMENTS_PER_CELL)]
    for j in (0, 127):
        fast = das._interpolate_coset_ifft(j, ys)
        slow = das.interpolate_polynomialcoeff(das.coset_for_cell(j), ys)
        slow += [0] * (len(fast) - len(slow))
        assert fast == slow
