"""Shared DAS fixtures: one blob's cells/proofs, computed once per process.

compute_cells_and_kzg_proofs is ~1 min of host BLS work (63 lag-MSMs +
a G1 FFT); every DAS test shares this single extended blob, mirroring how
the reference suite reuses one `get_sample_blob` per class of cases."""

from functools import lru_cache

from eth_consensus_specs_tpu.crypto import das, kzg


@lru_cache(maxsize=1)
def sample_blob() -> bytes:
    # deterministic, every field element canonical (< BLS_MODULUS)
    rng_state = 0x07
    out = []
    for i in range(kzg.FIELD_ELEMENTS_PER_BLOB):
        rng_state = (rng_state * 6364136223846793005 + 1442695040888963407) % 2**256
        out.append((rng_state % das.BLS_MODULUS).to_bytes(32, "big"))
    return b"".join(out)


@lru_cache(maxsize=1)
def sample_commitment() -> bytes:
    return kzg.blob_to_kzg_commitment(sample_blob())


@lru_cache(maxsize=1)
def sample_cells_and_proofs():
    return das.compute_cells_and_kzg_proofs(sample_blob())
