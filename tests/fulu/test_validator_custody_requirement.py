"""Validator custody requirement table, fulu (reference analogue:
test/fulu/unittests/test_networking.py get_validators_custody_requirement
family — zero/single/multiple validators, min/max clamps; spec:
specs/fulu/validator.md:124-131)."""

from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases

FULU = ["fulu"]


def _req(spec, state, indices):
    return int(spec.get_validators_custody_requirement(state, indices))


@with_phases(FULU)
@spec_state_test
def test_zero_validators_gets_minimum(spec, state):
    assert _req(spec, state, []) == int(spec.config.VALIDATOR_CUSTODY_REQUIREMENT)


@with_phases(FULU)
@spec_state_test
def test_single_validator_gets_minimum(spec, state):
    # one 32-ETH validator: 1 group worth of balance, clamped up to the min
    assert _req(spec, state, [0]) == int(spec.config.VALIDATOR_CUSTODY_REQUIREMENT)


@with_phases(FULU)
@spec_state_test
def test_below_min_threshold_validators(spec, state):
    min_req = int(spec.config.VALIDATOR_CUSTODY_REQUIREMENT)
    per_group = int(spec.config.BALANCE_PER_ADDITIONAL_CUSTODY_GROUP)
    eff = int(state.validators[0].effective_balance)
    count = max(1, (min_req - 1) * per_group // eff)
    indices = list(range(min(count, len(state.validators))))
    assert _req(spec, state, indices) == min_req


@with_phases(FULU)
@spec_state_test
def test_above_min_scales_with_balance(spec, state):
    min_req = int(spec.config.VALIDATOR_CUSTODY_REQUIREMENT)
    per_group = int(spec.config.BALANCE_PER_ADDITIONAL_CUSTODY_GROUP)
    eff = int(state.validators[0].effective_balance)
    # enough validators for min_req + 4 groups of balance
    needed = ((min_req + 4) * per_group + eff - 1) // eff
    if needed > len(state.validators):
        return  # registry too small under this preset
    indices = list(range(needed))
    expected = sum(
        int(state.validators[i].effective_balance) for i in indices
    ) // per_group
    assert _req(spec, state, indices) == expected
    assert expected >= min_req + 4


@with_phases(FULU)
@spec_state_test
def test_all_validators_clamped_at_total_groups(spec, state):
    # pump every balance so the count clamps at NUMBER_OF_CUSTODY_GROUPS
    per_group = int(spec.config.BALANCE_PER_ADDITIONAL_CUSTODY_GROUP)
    groups = int(spec.config.NUMBER_OF_CUSTODY_GROUPS)
    for i in range(len(state.validators)):
        state.validators[i].effective_balance = 2 * groups * per_group
    indices = list(range(len(state.validators)))
    assert _req(spec, state, indices) == groups


@with_phases(FULU)
@spec_state_test
def test_requirement_counts_effective_not_actual_balance(spec, state):
    per_group = int(spec.config.BALANCE_PER_ADDITIONAL_CUSTODY_GROUP)
    state.balances[0] = 100 * per_group  # actual balance is ignored
    state.validators[0].effective_balance = 32_000_000_000
    assert _req(spec, state, [0]) == int(spec.config.VALIDATOR_CUSTODY_REQUIREMENT)


@with_phases(FULU)
@spec_state_test
def test_requirement_monotone_in_validator_set(spec, state):
    per_group = int(spec.config.BALANCE_PER_ADDITIONAL_CUSTODY_GROUP)
    for i in range(min(len(state.validators), 24)):
        state.validators[i].effective_balance = per_group  # 1 group each
    prev = 0
    for n in (1, 4, 12, 24):
        cur = _req(spec, state, list(range(min(n, len(state.validators)))))
        assert cur >= prev
        prev = cur
