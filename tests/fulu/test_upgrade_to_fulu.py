"""electra -> fulu state upgrade (spec: specs/fulu/fork.md:53-110)."""

from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.state import next_epoch


@with_phases(["electra"])
@spec_state_test
def test_upgrade_to_fulu_basic(spec, state):
    fulu = get_spec("fulu", spec.preset_name)
    next_epoch(spec, state)
    post = fulu.upgrade_from_parent(state)
    assert bytes(post.fork.current_version) == bytes(fulu.config.FULU_FORK_VERSION)
    assert bytes(post.fork.previous_version) == bytes(state.fork.current_version)
    assert int(post.fork.epoch) == fulu.compute_epoch_at_slot(int(state.slot))
    # every pre-fork field carries over
    assert hash_tree_root(post.validators) == hash_tree_root(state.validators)
    assert hash_tree_root(post.balances) == hash_tree_root(state.balances)
    assert int(post.earliest_exit_epoch) == int(state.earliest_exit_epoch)


@with_phases(["electra"])
@spec_state_test
def test_upgrade_to_fulu_initializes_lookahead(spec, state):
    fulu = get_spec("fulu", spec.preset_name)
    post = fulu.upgrade_from_parent(state)
    expected = fulu.initialize_proposer_lookahead(state)
    assert [int(x) for x in post.proposer_lookahead] == [int(x) for x in expected]
    assert len(post.proposer_lookahead) == (fulu.MIN_SEED_LOOKAHEAD + 1) * fulu.SLOTS_PER_EPOCH
    # the post-state remains executable
    next_epoch(fulu, post)
