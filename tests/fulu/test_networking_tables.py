"""Fulu p2p structural-verification tables: data-column sidecar shape
checks, subnet mapping, and custody boundary cases (reference analogue:
eth2spec/test/fulu/unittests/test_networking.py and
fulu/networking/test_get_custody_groups.py; spec:
specs/fulu/p2p-interface.md verify_data_column_sidecar,
specs/fulu/das-core.md get_custody_groups)."""

import pytest

from eth_consensus_specs_tpu.crypto import curve
from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test,
    spec_test,
    with_phases,
)

FULU = ["fulu"]

COMMITMENT = curve.g1_to_bytes(curve.g1_generator())


def _structural_sidecar(spec, n_blobs=1, index=0):
    """A sidecar that satisfies the SHAPE checks (no KZG validity):
    lengths consistent across column/commitments/proofs."""
    cell = b"\x00" * spec.BYTES_PER_CELL
    return spec.DataColumnSidecar(
        index=index,
        column=[cell] * n_blobs,
        kzg_commitments=[COMMITMENT] * n_blobs,
        kzg_proofs=[COMMITMENT] * n_blobs,
        signed_block_header=spec.SignedBeaconBlockHeader(),
    )


# == verify_data_column_sidecar shape table ================================


@with_phases(FULU)
@spec_test
def test_sidecar_shape_valid(spec):
    assert spec.verify_data_column_sidecar(_structural_sidecar(spec, n_blobs=2))


@with_phases(FULU)
@spec_test
def test_sidecar_shape_invalid_zero_blobs(spec):
    assert not spec.verify_data_column_sidecar(_structural_sidecar(spec, n_blobs=0))


@with_phases(FULU)
@spec_test
def test_sidecar_shape_invalid_index(spec):
    sidecar = _structural_sidecar(spec, index=int(spec.NUMBER_OF_COLUMNS))
    assert not spec.verify_data_column_sidecar(sidecar)


@with_phases(FULU)
@spec_test
def test_sidecar_shape_invalid_mismatch_len_column(spec):
    sidecar = _structural_sidecar(spec, n_blobs=2)
    sidecar.column.pop()
    assert not spec.verify_data_column_sidecar(sidecar)


@with_phases(FULU)
@spec_test
def test_sidecar_shape_invalid_mismatch_len_commitments(spec):
    sidecar = _structural_sidecar(spec, n_blobs=2)
    sidecar.kzg_commitments.pop()
    assert not spec.verify_data_column_sidecar(sidecar)


@with_phases(FULU)
@spec_test
def test_sidecar_shape_invalid_mismatch_len_proofs(spec):
    sidecar = _structural_sidecar(spec, n_blobs=2)
    sidecar.kzg_proofs.pop()
    assert not spec.verify_data_column_sidecar(sidecar)


# == subnet mapping ========================================================


@with_phases(FULU)
@spec_test
def test_subnet_for_data_column_sidecar_wraps(spec):
    n_subnets = int(spec.config.DATA_COLUMN_SIDECAR_SUBNET_COUNT)
    seen = set()
    for column in range(int(spec.NUMBER_OF_COLUMNS)):
        subnet = int(spec.compute_subnet_for_data_column_sidecar(column))
        assert 0 <= subnet < n_subnets
        seen.add(subnet)
    assert seen == set(range(n_subnets))


@with_phases(FULU)
@spec_test
def test_subnet_mapping_is_modular(spec):
    n_subnets = int(spec.config.DATA_COLUMN_SIDECAR_SUBNET_COUNT)
    for column in (0, 1, n_subnets, n_subnets + 1):
        assert (
            int(spec.compute_subnet_for_data_column_sidecar(column))
            == column % n_subnets
        )


# == custody boundary table ================================================

U256_MAX = 2**256 - 1


@with_phases(FULU)
@spec_test
def test_custody_groups_min_node_id_min_count(spec):
    groups = spec.get_custody_groups(0, int(spec.config.CUSTODY_REQUIREMENT))
    assert len(groups) == int(spec.config.CUSTODY_REQUIREMENT)
    assert groups == sorted(groups)


@with_phases(FULU)
@spec_test
def test_custody_groups_min_node_id_max_count(spec):
    total = int(spec.config.NUMBER_OF_CUSTODY_GROUPS)
    assert spec.get_custody_groups(0, total) == list(range(total))


@with_phases(FULU)
@spec_test
def test_custody_groups_max_node_id_min_count(spec):
    groups = spec.get_custody_groups(U256_MAX, int(spec.config.CUSTODY_REQUIREMENT))
    assert len(groups) == int(spec.config.CUSTODY_REQUIREMENT)
    assert all(0 <= g < int(spec.config.NUMBER_OF_CUSTODY_GROUPS) for g in groups)


@with_phases(FULU)
@spec_test
def test_custody_groups_max_node_id_max_count(spec):
    total = int(spec.config.NUMBER_OF_CUSTODY_GROUPS)
    assert spec.get_custody_groups(U256_MAX, total) == list(range(total))


@with_phases(FULU)
@spec_test
def test_custody_groups_adjacent_max_node_ids_well_formed(spec):
    """Adjacent max-range ids each derive a deterministic, sorted,
    duplicate-free set (with minimal's small group space the two sets may
    legitimately coincide)."""
    count = max(1, int(spec.config.NUMBER_OF_CUSTODY_GROUPS) // 4)
    for node_id in (U256_MAX, U256_MAX - 1):
        groups = spec.get_custody_groups(node_id, count)
        assert groups == sorted(set(groups))
        assert len(groups) == count
        assert groups == spec.get_custody_groups(node_id, count)


@with_phases(FULU)
@spec_test
def test_custody_groups_short_node_id(spec):
    """Small ids must be padded, not truncated — 0x01 is a distinct seed
    from 0x0100."""
    count = max(1, int(spec.config.NUMBER_OF_CUSTODY_GROUPS) // 4)
    assert spec.get_custody_groups(1, count) != spec.get_custody_groups(256, count)


@with_phases(FULU)
@spec_test
def test_custody_groups_count_over_total_rejected(spec):
    with pytest.raises(AssertionError):
        spec.get_custody_groups(0, int(spec.config.NUMBER_OF_CUSTODY_GROUPS) + 1)


@with_phases(FULU)
@spec_test
def test_sampling_columns_superset_of_custody(spec):
    """Sampling size is max(SAMPLES_PER_SLOT, custody) groups' columns."""
    count = int(spec.config.CUSTODY_REQUIREMENT)
    cols = spec.get_sampling_columns(1234, count)
    groups = spec.get_custody_groups(1234, count)
    custody_cols = set()
    for g in groups:
        custody_cols.update(spec.compute_columns_for_custody_group(g))
    assert custody_cols <= set(cols)
