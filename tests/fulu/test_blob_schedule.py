"""EIP-7892 blob schedule: epoch-dependent blob caps and the fork digest
bitmask (reference: specs/fulu/beacon-chain.md:36-115, :193-235)."""

from eth_consensus_specs_tpu.config import FrozenNamespace
from eth_consensus_specs_tpu.forks import get_spec_with_overrides
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import (
    expect_assertion_error,
    spec_state_test,
    spec_test,
    with_phases,
)


@with_phases(["fulu"])
@spec_test
def test_blob_parameters_default_is_electra(spec):
    bp = spec.get_blob_parameters(0)
    assert bp.max_blobs_per_block == int(spec.config.MAX_BLOBS_PER_BLOCK_ELECTRA)
    assert bp.epoch == int(spec.config.ELECTRA_FORK_EPOCH)


@with_phases(["fulu"])
@spec_test
def test_blob_parameters_follow_schedule(spec):
    sched = (
        FrozenNamespace({"EPOCH": 5, "MAX_BLOBS_PER_BLOCK": 12}),
        FrozenNamespace({"EPOCH": 9, "MAX_BLOBS_PER_BLOCK": 20}),
    )
    s = get_spec_with_overrides(
        "fulu", spec.preset_name, config_overrides={"BLOB_SCHEDULE": sched}
    )
    assert s.get_blob_parameters(4).max_blobs_per_block == int(
        s.config.MAX_BLOBS_PER_BLOCK_ELECTRA
    )
    assert s.get_blob_parameters(5).max_blobs_per_block == 12
    assert s.get_blob_parameters(8).max_blobs_per_block == 12
    assert s.get_blob_parameters(9).max_blobs_per_block == 20
    assert s.get_blob_parameters(10**6).max_blobs_per_block == 20
    assert s.max_blobs_per_block() == 20


@with_phases(["fulu"])
@spec_state_test
def test_execution_payload_respects_scheduled_cap(spec, state):
    """A block carrying more commitments than the scheduled cap is
    invalid; at or below the cap it applies."""
    cap = spec.get_blob_parameters(spec.get_current_epoch(state)).max_blobs_per_block

    block = build_empty_block_for_next_slot(spec, state)
    block.body.blob_kzg_commitments = [b"\xc0" + b"\x00" * 47] * (cap + 1)
    state_transition_and_sign_block(spec, state, block, expect_fail=True)


@with_phases(["fulu"])
@spec_state_test
def test_execution_payload_at_cap_accepted(spec, state):
    cap = spec.get_blob_parameters(spec.get_current_epoch(state)).max_blobs_per_block
    block = build_empty_block_for_next_slot(spec, state)
    block.body.blob_kzg_commitments = [b"\xc0" + b"\x00" * 47] * cap
    state_transition_and_sign_block(spec, state, block)


@with_phases(["fulu"])
@spec_test
def test_fork_digest_masks_blob_parameters(spec):
    """Digest differs when the blob schedule differs, matching the EIP-7892
    bitmask construction."""
    root = b"\x42" * 32
    epoch = int(spec.config.FULU_FORK_EPOCH)
    if epoch == 2**64 - 1:
        epoch = 0  # minimal config never schedules fulu; use genesis epoch
    base = spec.compute_fork_digest(root, epoch)
    assert len(bytes(base)) == 4
    s2 = get_spec_with_overrides(
        "fulu",
        spec.preset_name,
        config_overrides={
            "BLOB_SCHEDULE": (
                FrozenNamespace({"EPOCH": epoch, "MAX_BLOBS_PER_BLOCK": 21}),
            )
        },
    )
    other = s2.compute_fork_digest(root, epoch)
    assert bytes(other) != bytes(base)
    # legacy (version, root) call shape still works
    legacy = spec.compute_fork_digest(spec.config.GENESIS_FORK_VERSION, root)
    assert len(bytes(legacy)) == 4
