"""das-core: custody groups, column mapping, matrix compute/recover
(reference: specs/fulu/das-core.md:101-189 and
eth2spec/test/fulu/unittests/das/test_das.py)."""

import pytest

from eth_consensus_specs_tpu.crypto import das
from eth_consensus_specs_tpu.test_infra.context import spec_test, with_phases

from .das_fixtures import sample_blob, sample_cells_and_proofs


@with_phases(["fulu"])
@spec_test
def test_custody_groups_deterministic_sorted_unique(spec):
    for node_id in (0, 1, 2**64, 2**200 + 7):
        groups = spec.get_custody_groups(node_id, spec.config.CUSTODY_REQUIREMENT)
        assert groups == spec.get_custody_groups(node_id, spec.config.CUSTODY_REQUIREMENT)
        assert groups == sorted(groups)
        assert len(groups) == len(set(groups)) == spec.config.CUSTODY_REQUIREMENT
        for g in groups:
            assert 0 <= g < spec.config.NUMBER_OF_CUSTODY_GROUPS


@with_phases(["fulu"])
@spec_test
def test_custody_groups_extension_property(spec):
    """Increasing custody_group_count extends the set, never reshuffles
    (specs/fulu/das-core.md:209-218)."""
    node_id = 88172645463325252
    small = spec.get_custody_groups(node_id, 4)
    large = spec.get_custody_groups(node_id, 16)
    assert set(small) <= set(large)


@with_phases(["fulu"])
@spec_test
def test_custody_groups_all(spec):
    n = spec.config.NUMBER_OF_CUSTODY_GROUPS
    assert spec.get_custody_groups(1234, n) == list(range(n))


@with_phases(["fulu"])
@spec_test
def test_custody_group_overflow_wraps(spec):
    """current_id wraps at UINT256_MAX rather than overflowing
    (specs/fulu/das-core.md:116-120)."""
    groups = spec.get_custody_groups(spec.UINT256_MAX, 2)
    assert len(groups) == 2


@with_phases(["fulu"])
@spec_test
def test_columns_for_custody_group_partition(spec):
    """Every column appears in exactly one custody group."""
    seen = []
    for g in range(spec.config.NUMBER_OF_CUSTODY_GROUPS):
        seen.extend(spec.compute_columns_for_custody_group(g))
    assert sorted(seen) == list(range(spec.NUMBER_OF_COLUMNS))


@with_phases(["fulu"])
@spec_test
def test_sampling_columns_cover_custody(spec):
    node_id = 42
    sampled = spec.get_sampling_columns(node_id, spec.config.CUSTODY_REQUIREMENT)
    assert len(sampled) == max(
        spec.config.SAMPLES_PER_SLOT, spec.config.CUSTODY_REQUIREMENT
    ) * (spec.NUMBER_OF_COLUMNS // spec.config.NUMBER_OF_CUSTODY_GROUPS)
    for g in spec.get_custody_groups(node_id, spec.config.CUSTODY_REQUIREMENT):
        for col in spec.compute_columns_for_custody_group(g):
            assert col in sampled


@with_phases(["fulu"])
@spec_test
def test_compute_and_recover_matrix_roundtrip(spec):
    """compute_matrix -> drop half the columns -> recover_matrix
    (specs/fulu/das-core.md:140-189)."""
    blob = sample_blob()
    sample_cells_and_proofs()  # warm the FK20 cache once for the module
    matrix = spec.compute_matrix([blob])
    assert len(matrix) == spec.CELLS_PER_EXT_BLOB
    assert {int(e.row_index) for e in matrix} == {0}
    assert [int(e.column_index) for e in matrix] == list(range(spec.CELLS_PER_EXT_BLOB))

    kept = [e for e in matrix if int(e.column_index) % 2 == 0]
    recovered = spec.recover_matrix(kept, 1)
    assert len(recovered) == len(matrix)
    for a, b in zip(recovered, matrix):
        assert bytes(a.cell) == bytes(b.cell)
        assert bytes(a.kzg_proof) == bytes(b.kzg_proof)


@with_phases(["fulu"])
@spec_test
def test_recover_rejects_insufficient_cells(spec):
    cells, _ = sample_cells_and_proofs()
    half = spec.CELLS_PER_EXT_BLOB // 2
    idx = list(range(half - 1))
    with pytest.raises(AssertionError):
        das.recover_cells_and_kzg_proofs(idx, [cells[i] for i in idx])


@with_phases(["fulu"])
@spec_test
def test_recover_rejects_duplicates_and_unsorted(spec):
    cells, _ = sample_cells_and_proofs()
    idx = list(range(64))
    dup = [0, 0] + idx[2:]
    with pytest.raises(AssertionError):
        das.recover_cells_and_kzg_proofs(dup, [cells[i] for i in dup])
    rev = list(reversed(idx))
    with pytest.raises(AssertionError):
        das.recover_cells_and_kzg_proofs(rev, [cells[i] for i in rev])
