"""PeerDAS unit tables — custody group math, column assignment bounds,
matrix indexing, cell bound checks (reference analogue:
test/fulu/unittests/das/test_das.py and networking custody tests; spec:
specs/fulu/das-core.md:101-190)."""

import pytest

from eth_consensus_specs_tpu.test_infra.context import (
    spec_state_test,
    with_phases,
)

FULU = ["fulu", "gloas"]


@with_phases(FULU)
@spec_state_test
def test_custody_groups_deterministic(spec, state):
    node = 123456789
    a = spec.get_custody_groups(node, 4)
    b = spec.get_custody_groups(node, 4)
    assert [int(g) for g in a] == [int(g) for g in b]


@with_phases(FULU)
@spec_state_test
def test_custody_groups_sorted_unique(spec, state):
    groups = [int(g) for g in spec.get_custody_groups(987654321, 6)]
    assert groups == sorted(groups)
    assert len(set(groups)) == len(groups)


@with_phases(FULU)
@spec_state_test
def test_custody_groups_full_count_is_identity(spec, state):
    n = int(spec.config.NUMBER_OF_CUSTODY_GROUPS)
    groups = [int(g) for g in spec.get_custody_groups(42, n)]
    assert groups == list(range(n))


@with_phases(FULU)
@spec_state_test
def test_custody_groups_count_over_limit_rejected(spec, state):
    n = int(spec.config.NUMBER_OF_CUSTODY_GROUPS)
    with pytest.raises(AssertionError):
        spec.get_custody_groups(42, n + 1)


@with_phases(FULU)
@spec_state_test
def test_custody_groups_prefix_property(spec, state):
    """A node's custody set grows monotonically with the count — the
    first k groups of count k+1 contain the count-k set."""
    node = 0xDEADBEEF
    small = {int(g) for g in spec.get_custody_groups(node, 2)}
    large = {int(g) for g in spec.get_custody_groups(node, 5)}
    assert small <= large


@with_phases(FULU)
@spec_state_test
def test_columns_for_custody_group_disjoint_cover(spec, state):
    n = int(spec.config.NUMBER_OF_CUSTODY_GROUPS)
    all_cols: list[int] = []
    for g in range(n):
        all_cols += [int(c) for c in spec.compute_columns_for_custody_group(g)]
    assert sorted(all_cols) == list(range(int(spec.NUMBER_OF_COLUMNS)))


@with_phases(FULU)
@spec_state_test
def test_columns_for_custody_group_out_of_range(spec, state):
    n = int(spec.config.NUMBER_OF_CUSTODY_GROUPS)
    with pytest.raises(AssertionError):
        spec.compute_columns_for_custody_group(n)


@with_phases(["fulu"])
@spec_state_test
def test_cell_coset_roundtrip(spec, state):
    from .das_fixtures import sample_cells_and_proofs

    cells, _ = sample_cells_and_proofs()
    evals = spec.cell_to_coset_evals(cells[3])
    back = spec.coset_evals_to_cell(evals)
    assert bytes(back) == bytes(cells[3])


@with_phases(["fulu"])
@spec_state_test
def test_recovery_needs_at_least_half_the_cells(spec, state):
    from .das_fixtures import sample_cells_and_proofs

    cells, _ = sample_cells_and_proofs()
    half = int(spec.CELLS_PER_EXT_BLOB) // 2
    idxs = list(range(half - 1))  # one short of the recovery threshold
    with pytest.raises(AssertionError):
        spec.recover_cells_and_kzg_proofs(idxs, [cells[i] for i in idxs])
