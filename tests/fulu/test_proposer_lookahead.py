"""EIP-7917 precomputed proposer lookahead
(reference: specs/fulu/beacon-chain.md:238-327 and
eth2spec/test/fulu/unittests/validator/)."""

from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.state import next_epoch, next_slot


@with_phases(["fulu"])
@spec_state_test
def test_genesis_lookahead_matches_direct_computation(spec, state):
    cur = spec.get_current_epoch(state)
    expected = []
    for i in range(spec.MIN_SEED_LOOKAHEAD + 1):
        expected.extend(spec.get_beacon_proposer_indices(state, cur + i))
    assert [int(x) for x in state.proposer_lookahead] == [int(x) for x in expected]


@with_phases(["fulu"])
@spec_state_test
def test_lookahead_shifts_each_epoch(spec, state):
    before = [int(x) for x in state.proposer_lookahead]
    next_epoch(spec, state)
    after = [int(x) for x in state.proposer_lookahead]
    assert after[: -spec.SLOTS_PER_EPOCH] == before[spec.SLOTS_PER_EPOCH :]
    # freshly appended epoch matches direct computation
    new_epoch = spec.get_current_epoch(state) + spec.MIN_SEED_LOOKAHEAD + 1
    # the tail was computed BEFORE the epoch increment, i.e. for
    # (pre_epoch + MIN_SEED_LOOKAHEAD + 1) == current + MIN_SEED_LOOKAHEAD
    tail = after[-spec.SLOTS_PER_EPOCH :]
    assert len(tail) == spec.SLOTS_PER_EPOCH


@with_phases(["fulu"])
@spec_state_test
def test_proposer_index_consistent_with_lookahead(spec, state):
    for _ in range(3):
        next_slot(spec, state)
        slot_in_epoch = int(state.slot) % spec.SLOTS_PER_EPOCH
        assert spec.get_beacon_proposer_index(state) == int(
            state.proposer_lookahead[slot_in_epoch]
        )


@with_phases(["fulu"])
@spec_state_test
def test_block_proposer_from_lookahead_accepted(spec, state):
    """A block signed by the lookahead proposer passes process_block_header."""
    block = build_empty_block_for_next_slot(spec, state)
    assert int(block.proposer_index) == int(
        state.proposer_lookahead[int(block.slot) % spec.SLOTS_PER_EPOCH]
    )
    state_transition_and_sign_block(spec, state, block)
    assert state.latest_block_header.proposer_index == block.proposer_index


@with_phases(["fulu"])
@spec_state_test
def test_lookahead_stable_within_epoch(spec, state):
    """Blocks inside an epoch never change the lookahead (only the epoch
    transition shifts it)."""
    snapshot = [int(x) for x in state.proposer_lookahead]
    for _ in range(min(3, spec.SLOTS_PER_EPOCH - 1)):
        block = build_empty_block_for_next_slot(spec, state)
        state_transition_and_sign_block(spec, state, block)
        assert [int(x) for x in state.proposer_lookahead] == snapshot
