"""DataColumnSidecar construction/verification and the column-sampled
availability gate (reference: specs/fulu/p2p-interface.md:109-175,
specs/fulu/validator.md:207-265, specs/fulu/fork-choice.md:19-34)."""

from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.fork_choice import (
    get_genesis_forkchoice_store,
    tick_and_add_block,
)

from .das_fixtures import sample_blob, sample_cells_and_proofs, sample_commitment


def _signed_blob_block(spec, state):
    """A signed block carrying the sample blob's commitment, applied to
    the state so the header/sidecar plumbing is consistent."""
    from eth_consensus_specs_tpu.test_infra.block import state_transition_and_sign_block

    block = build_empty_block_for_next_slot(spec, state)
    block.body.blob_kzg_commitments = [sample_commitment()]
    signed = state_transition_and_sign_block(spec, state, block)
    return signed


@with_phases(["fulu"])
@spec_state_test
def test_data_column_sidecars_roundtrip(spec, state):
    signed = _signed_blob_block(spec, state)
    sidecars = spec.get_data_column_sidecars_from_block(signed, [sample_cells_and_proofs()])
    assert len(sidecars) == spec.NUMBER_OF_COLUMNS
    for sc in (sidecars[0], sidecars[77]):
        assert spec.verify_data_column_sidecar(sc)
        assert spec.verify_data_column_sidecar_inclusion_proof(sc)
    # KZG batch verification on a couple of columns (one pairing each)
    assert spec.verify_data_column_sidecar_kzg_proofs(sidecars[0])
    assert spec.verify_data_column_sidecar_kzg_proofs(sidecars[127])


@with_phases(["fulu"])
@spec_state_test
def test_data_column_sidecar_rejects_malformed(spec, state):
    signed = _signed_blob_block(spec, state)
    sidecars = spec.get_data_column_sidecars_from_block(signed, [sample_cells_and_proofs()])
    sc = sidecars[3]

    bad = sc.copy()
    bad.index = spec.NUMBER_OF_COLUMNS
    assert not spec.verify_data_column_sidecar(bad)

    bad = sc.copy()
    bad.kzg_commitments = []
    assert not spec.verify_data_column_sidecar(bad)

    bad = sc.copy()
    bad.kzg_proofs = []
    assert not spec.verify_data_column_sidecar(bad)

    bad = sc.copy()
    bad.kzg_commitments_inclusion_proof = [b"\x00" * 32] * len(
        sc.kzg_commitments_inclusion_proof
    )
    assert not spec.verify_data_column_sidecar_inclusion_proof(bad)


@with_phases(["fulu"])
@spec_state_test
def test_data_column_sidecar_kzg_rejects_wrong_cell(spec, state):
    signed = _signed_blob_block(spec, state)
    cells, proofs = sample_cells_and_proofs()
    sidecars = spec.get_data_column_sidecars_from_block(signed, [(cells, proofs)])
    bad = sidecars[5].copy()
    bad.column = [bytes(cells[6])]  # cell from the wrong column
    assert not spec.verify_data_column_sidecar_kzg_proofs(bad)


@with_phases(["fulu"])
@spec_state_test
def test_on_block_checks_column_availability(spec, state):
    """on_block consumes the fulu is_data_available (no commitments arg):
    verified sidecars pass, corrupted ones make the block unavailable."""
    from eth_consensus_specs_tpu.test_infra.context import expect_assertion_error

    store, _anchor = get_genesis_forkchoice_store(spec, state)
    signed = _signed_blob_block(spec, state)
    block_root = hash_tree_root(signed.message)
    sidecars = spec.get_data_column_sidecars_from_block(signed, [sample_cells_and_proofs()])
    sampled = [sidecars[i] for i in (0, 64)]

    spec._column_retriever = lambda root: sampled if root == block_root else []
    try:
        tick_and_add_block(spec, store, signed)
        assert block_root in store.blocks
    finally:
        del spec._column_retriever


@with_phases(["fulu"])
@spec_state_test
def test_on_block_rejects_unavailable_columns(spec, state):
    from eth_consensus_specs_tpu.test_infra.context import expect_assertion_error

    store, _anchor = get_genesis_forkchoice_store(spec, state)
    signed = _signed_blob_block(spec, state)
    block_root = hash_tree_root(signed.message)
    cells, proofs = sample_cells_and_proofs()
    sidecars = spec.get_data_column_sidecars_from_block(signed, [(cells, proofs)])
    corrupted = sidecars[0].copy()
    corrupted.column = [bytes(cells[1])]

    spec._column_retriever = lambda root: [corrupted]
    try:
        tick_and_add_block(spec, store, signed, valid=False)
        assert block_root not in store.blocks
    finally:
        del spec._column_retriever
