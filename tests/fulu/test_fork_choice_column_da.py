"""Fork-choice column-sampled data-availability gate (fulu).

[Modified in Fulu:EIP7594] on_block's availability check consumes DATA
COLUMN sidecars from the sampling seam: every retrieved sidecar must pass
structural and KZG-batch verification or the block is rejected.  An empty
retrieval is vacuously available — how many columns to sample is custody
policy, not the gate's concern (same shape as the upstream handler).
Reference surface: specs/fulu/fork-choice.md is_data_available:19-34 +
eth2spec/test/fulu/fork_choice/test_on_block.py.
"""

from __future__ import annotations

import pytest

from eth_consensus_specs_tpu.ssz import hash_tree_root
from eth_consensus_specs_tpu.test_infra.block import (
    build_empty_block_for_next_slot,
    state_transition_and_sign_block,
)
from eth_consensus_specs_tpu.test_infra.context import spec_state_test, with_phases
from eth_consensus_specs_tpu.test_infra.fork_choice import (
    get_genesis_forkchoice_store,
    tick_and_add_block,
)

from .das_fixtures import sample_cells_and_proofs, sample_commitment

# real KZG pairings per case — nightly lane
pytestmark = pytest.mark.slow

FULU = ["fulu"]


def _signed_blob_block(spec, state):
    block = build_empty_block_for_next_slot(spec, state)
    block.body.blob_kzg_commitments = [sample_commitment()]
    return state_transition_and_sign_block(spec, state, block)


def _run_with_columns(spec, state, columns_fn, valid: bool):
    """Drive a blob block through on_block with `columns_fn(sidecars)`
    selecting/corrupting what the sampling seam serves."""
    store, _ = get_genesis_forkchoice_store(spec, state)
    signed = _signed_blob_block(spec, state)
    sidecars = spec.get_data_column_sidecars_from_block(
        signed, [sample_cells_and_proofs()]
    )
    served = columns_fn(sidecars)
    spec._column_retriever = lambda root: served
    try:
        tick_and_add_block(spec, store, signed, valid=valid)
        if valid:
            assert hash_tree_root(signed.message) in store.blocks
    finally:
        spec._column_retriever = None


@with_phases(FULU)
@spec_state_test
def test_on_block_columns_available(spec, state):
    _run_with_columns(spec, state, lambda scs: [scs[0], scs[64]], valid=True)


@with_phases(FULU)
@spec_state_test
def test_on_block_no_columns_sampled_vacuous(spec, state):
    _run_with_columns(spec, state, lambda scs: [], valid=True)


@with_phases(FULU)
@spec_state_test
def test_on_block_corrupted_cell_rejected(spec, state):
    def corrupt(scs):
        bad = scs[3].copy()
        cell = bytearray(bytes(bad.column[0]))
        cell[7] ^= 0x01
        bad.column[0] = bytes(cell)
        return [bad]

    _run_with_columns(spec, state, corrupt, valid=False)


@with_phases(FULU)
@spec_state_test
def test_on_block_wrong_proof_rejected(spec, state):
    def swap_proof(scs):
        bad = scs[5].copy()
        bad.kzg_proofs[0] = bytes(scs[6].kzg_proofs[0])
        return [bad]

    _run_with_columns(spec, state, swap_proof, valid=False)


@with_phases(FULU)
@spec_state_test
def test_on_block_out_of_range_index_rejected(spec, state):
    def bad_index(scs):
        bad = scs[0].copy()
        bad.index = int(spec.NUMBER_OF_COLUMNS)
        return [bad]

    _run_with_columns(spec, state, bad_index, valid=False)


@with_phases(FULU)
@spec_state_test
def test_on_block_one_bad_column_poisons_batch(spec, state):
    def mixed(scs):
        bad = scs[2].copy()
        cell = bytearray(bytes(bad.column[0]))
        cell[11] ^= 0x80
        bad.column[0] = bytes(cell)
        return [scs[0], bad, scs[9]]

    _run_with_columns(spec, state, mixed, valid=False)
