"""Custody-group assignment tables (spec: specs/fulu/das-core.md
get_custody_groups / compute_columns_for_custody_group; reference
analogue: test/fulu/unittests/das/test_das.py)."""

from eth_consensus_specs_tpu.test_infra.context import spec_test, with_phases

FULU = ["fulu", "gloas"]


@with_phases(FULU)
@spec_test
def test_custody_groups_deterministic_and_sized(spec):
    node_id = 0x1234_5678_9ABC_DEF0 << 180
    count = int(spec.config.CUSTODY_REQUIREMENT)
    groups = spec.get_custody_groups(node_id, count)
    assert len(groups) == count
    assert groups == spec.get_custody_groups(node_id, count)
    assert len(set(int(g) for g in groups)) == count  # no duplicates
    assert all(
        0 <= int(g) < int(spec.config.NUMBER_OF_CUSTODY_GROUPS) for g in groups
    )


@with_phases(FULU)
@spec_test
def test_custody_groups_sorted(spec):
    groups = spec.get_custody_groups(987654321, 6)
    assert [int(g) for g in groups] == sorted(int(g) for g in groups)


@with_phases(FULU)
@spec_test
def test_custody_groups_full_coverage(spec):
    total = int(spec.config.NUMBER_OF_CUSTODY_GROUPS)
    groups = spec.get_custody_groups(42, total)
    assert [int(g) for g in groups] == list(range(total))


@with_phases(FULU)
@spec_test
def test_custody_groups_differ_across_nodes(spec):
    a = spec.get_custody_groups(1, 4)
    b = spec.get_custody_groups(2, 4)
    assert a != b  # overwhelmingly likely by construction


@with_phases(FULU)
@spec_test
def test_columns_for_custody_group_partition(spec):
    """Every column belongs to exactly one custody group."""
    total_groups = int(spec.config.NUMBER_OF_CUSTODY_GROUPS)
    seen: set[int] = set()
    for g in range(total_groups):
        cols = [int(c) for c in spec.compute_columns_for_custody_group(g)]
        assert not (seen & set(cols))
        seen |= set(cols)
    assert len(seen) == int(spec.NUMBER_OF_COLUMNS)


@with_phases(FULU)
@spec_test
def test_custody_group_count_exceeding_total_rejected(spec):
    from eth_consensus_specs_tpu.test_infra.context import expect_assertion_error

    total = int(spec.config.NUMBER_OF_CUSTODY_GROUPS)
    expect_assertion_error(lambda: spec.get_custody_groups(7, total + 1))
