"""Shuffle identity: the whole-permutation kernel must agree with the
per-index spec form everywhere, and be a true permutation."""

import numpy as np
import pytest

from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.ops.shuffle import shuffle_permutation


@pytest.mark.parametrize("n", [1, 2, 7, 64, 257, 1000])
def test_permutation_matches_spec_form(n):
    spec = get_spec("phase0", "minimal")
    seed = bytes(range(32))
    perm = shuffle_permutation(n, seed, spec.SHUFFLE_ROUND_COUNT)
    for i in range(n):
        assert int(perm[i]) == spec.compute_shuffled_index(i, n, seed)


def test_is_permutation():
    seed = b"\xaa" * 32
    perm = shuffle_permutation(5000, seed, 90)
    assert sorted(perm.tolist()) == list(range(5000))


def test_seed_sensitivity():
    a = shuffle_permutation(256, b"\x01" * 32, 90)
    b = shuffle_permutation(256, b"\x02" * 32, 90)
    assert a.tolist() != b.tolist()
