"""Shuffle identity: the whole-permutation kernel must agree with the
per-index spec form everywhere, and be a true permutation."""

import numpy as np
import pytest

from eth_consensus_specs_tpu.forks import get_spec
from eth_consensus_specs_tpu.ops.shuffle import shuffle_permutation


@pytest.mark.parametrize("n", [1, 2, 7, 64, 257, 1000])
def test_permutation_matches_spec_form(n):
    spec = get_spec("phase0", "minimal")
    seed = bytes(range(32))
    perm = shuffle_permutation(n, seed, spec.SHUFFLE_ROUND_COUNT)
    for i in range(n):
        assert int(perm[i]) == spec.compute_shuffled_index(i, n, seed)


def test_is_permutation():
    seed = b"\xaa" * 32
    perm = shuffle_permutation(5000, seed, 90)
    assert sorted(perm.tolist()) == list(range(5000))


def test_seed_sensitivity():
    a = shuffle_permutation(256, b"\x01" * 32, 90)
    b = shuffle_permutation(256, b"\x02" * 32, 90)
    assert a.tolist() != b.tolist()


@pytest.mark.parametrize("n", [1, 255, 256, 257, 1000, 4096])
def test_device_permutation_bit_equal(n):
    """shuffle_permutation_device == host whole-permutation form ==
    compute_shuffled_index (via the host test above), incl. chunk-boundary
    sizes. 90 mainnet rounds."""
    import numpy as np

    from eth_consensus_specs_tpu.ops.shuffle import shuffle_permutation_device

    seed = b"\x5a" * 32
    host = shuffle_permutation(n, seed, 90)
    dev = np.asarray(shuffle_permutation_device(n, seed, 90))
    assert (host == dev).all()
